"""Hybrid ES256 → ML-DSA keyplane migration under load (the headline
post-quantum scenario, ROADMAP open item #2).

A tenant serving ES256 traffic is migrated to ML-DSA-44 through the
keyplane, live, against REAL-ENGINE subprocess workers
(``--keyset jwks:``, no stubs — this is the scenario enterprises will
run this decade):

  epoch 0   workers boot on the tenant's ES256 JWKS
  epoch 2   hybrid push: ES256 + ML-DSA keys (both families verify)
  epoch 3   ML-DSA-only push with a grace window — retired ES kids
            still resolve, so in-flight classical tokens don't flap —
            with ``kill -9`` landing on one worker mid-push

Acceptance (asserted throughout): zero wrong verdicts, zero lost
submissions, fleet convergence on every pushed epoch including after
the SIGKILL respawn, and the rotation-lag SLO green over the run's
telemetry. Everything is dependency-free: ES256 rides the
HostECPublicKey pure-int path, ML-DSA the in-repo FIPS 204 stack.
"""

import hashlib
import json
import signal
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.fleet import FleetClient, WorkerPool
from cap_tpu.fleet.chaos import kill9
from cap_tpu.jwt.jose import b64url_encode
from cap_tpu.jwt.jwk import parse_jwks, serialize_public_key
from cap_tpu.obs import slo as obs_slo
from cap_tpu.tpu import mldsa
from cap_tpu.tpu.ec import HostECPublicKey, curve, host_ecdsa_sign, scalar_mult

HARD_TIMEOUT_S = 300

# Pinned fixture scalars (test-only, never real credentials).
EC_D = 0x2C9F1B3A8D4E6F5C7B8A9D0E1F2A3B4C5D6E7F8091A2B3C4D5E6F708192A3B4C


@pytest.fixture(autouse=True)
def _hard_timeout():
    def on_alarm(signum, frame):
        raise TimeoutError(
            f"hybrid migration test exceeded hard {HARD_TIMEOUT_S}s "
            "timeout")

    old = signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(HARD_TIMEOUT_S)
    try:
        yield
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)


def _jws(alg: str, kid: str, claims: dict, signer) -> str:
    h = b64url_encode(json.dumps({"alg": alg, "kid": kid},
                                 separators=(",", ":")).encode())
    p = b64url_encode(json.dumps(claims,
                                 separators=(",", ":")).encode())
    return h + "." + p + "." + b64url_encode(signer((h + "." + p).encode()))


def _tamper(tok: str) -> str:
    return tok[:-6] + ("AAAAAA" if not tok.endswith("AAAAAA")
                       else "BBBBBB")


@pytest.fixture(scope="module")
def tenant():
    """The tenant's key material + pre-signed token pools."""
    cp = curve("P-256")
    qx, qy = scalar_mult(cp, EC_D, (cp.gx, cp.gy))
    es_key = HostECPublicKey("P-256", qx, qy)

    def es_sign(si: bytes) -> bytes:
        e = int.from_bytes(hashlib.sha256(si).digest(), "big")
        k = (int.from_bytes(hashlib.sha256(b"nonce" + si).digest(),
                            "big") % (cp.n - 2)) + 1
        r, s = host_ecdsa_sign("P-256", EC_D, e, k)
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")

    pq_priv, pq_pub = mldsa.keygen("ML-DSA-44", bytes([42]) * 32)
    from cap_tpu.tpu import slhdsa

    slh_priv, slh_pub = slhdsa.keygen("SLH-DSA-SHAKE-128f",
                                      bytes([43]) * 32)

    es_jwk = serialize_public_key(es_key, kid="tenant-es")
    pq_jwk = serialize_public_key(pq_pub, kid="tenant-pq")
    slh_jwk = serialize_public_key(slh_pub, kid="tenant-slh")

    es_toks = [_jws("ES256", "tenant-es", {"sub": f"es-{i}"}, es_sign)
               for i in range(4)]
    pq_toks = [_jws("ML-DSA-44", "tenant-pq", {"sub": f"pq-{i}"},
                    pq_priv.sign) for i in range(4)]
    slh_toks = [_jws("SLH-DSA-SHAKE-128f", "tenant-slh",
                     {"sub": f"slh-{i}"}, slh_priv.sign)
                for i in range(4)]
    return {
        "es_jwks": {"keys": [es_jwk]},
        "hybrid_jwks": {"keys": [es_jwk, pq_jwk]},
        "pq_jwks": {"keys": [pq_jwk]},
        "pq_slh_jwks": {"keys": [pq_jwk, slh_jwk]},
        "slh_jwks": {"keys": [slh_jwk]},
        "union_jwks": {"keys": [es_jwk, pq_jwk, slh_jwk]},
        "es_toks": es_toks,
        "pq_toks": pq_toks,
        "slh_toks": slh_toks,
        "es_bad": [_tamper(t) for t in es_toks],
        "pq_bad": [_tamper(t) for t in pq_toks],
        "slh_bad": [_tamper(t) for t in slh_toks],
    }


def _wait_epochs(pool, epoch, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if all(e == epoch for e in pool.key_epochs().values()):
            return True
        time.sleep(0.1)
    return False


@pytest.mark.chaos
def test_hybrid_migration_es256_to_mldsa_under_load(tenant, tmp_path):
    """The full migration with kill -9 mid-final-push: zero wrong
    verdicts, zero lost submissions, convergence, rotation SLO green."""
    jwks_path = tmp_path / "tenant_es.json"
    jwks_path.write_text(json.dumps(tenant["es_jwks"]))

    rec = telemetry.enable()
    pool = WorkerPool(2, keyset_spec=f"jwks:{jwks_path}",
                      ping_interval=0.5, max_restarts=20,
                      spawn_timeout=120, max_wait_ms=2.0)
    try:
        assert pool.wait_all_ready(120), "real-engine fleet not ready"
        # The terminal-fallback oracle holds the UNION key set: it can
        # only fire on total fleet failure, where phase-accurate
        # verdicts are unknowable anyway — bad tokens still always
        # reject (parse_jwks is the same code the workers run).
        fallback = _FallbackKeySet(tenant["hybrid_jwks"])
        cl = FleetClient(pool, fallback=fallback, attempt_timeout=5.0,
                         total_deadline=60.0, rr_seed=0)

        ph2_pushed = threading.Event()    # hybrid keys going out
        ph2_converged = threading.Event()
        stop = threading.Event()
        failures = []
        batches = []

        def driver(d):
            i = 0
            while not stop.is_set() and not failures:
                toks = [tenant["es_toks"][i % 4],
                        tenant["es_bad"][i % 4],
                        tenant["pq_toks"][(i + d) % 4],
                        tenant["pq_bad"][(i + d) % 4]]
                submitted_after_conv = ph2_converged.is_set()
                try:
                    res = cl.verify_batch(toks)
                except Exception as e:  # noqa: BLE001
                    failures.append(f"driver {d}: {e!r}")
                    return
                now_pushed = ph2_pushed.is_set()
                if len(res) != len(toks):
                    failures.append(f"driver {d}: lost submissions")
                    return
                es_ok, es_bad, pq_ok, pq_bad = [
                    not isinstance(r, Exception) for r in res]
                if not es_ok:
                    failures.append(
                        f"driver {d}: valid ES256 token rejected")
                if es_bad or pq_bad:
                    failures.append(
                        f"driver {d}: FORGED token accepted")
                if pq_ok and not now_pushed:
                    failures.append(
                        f"driver {d}: ML-DSA accepted before any "
                        "ML-DSA key was pushed")
                if not pq_ok and submitted_after_conv:
                    failures.append(
                        f"driver {d}: valid ML-DSA token rejected "
                        "after fleet convergence")
                if pq_ok and res[2] != {"sub": f"pq-{(i + d) % 4}"}:
                    failures.append(f"driver {d}: wrong ML-DSA claims")
                batches.append(len(toks))
                i += 1

        threads = [threading.Thread(target=driver, args=(d,))
                   for d in range(3)]
        for t in threads:
            t.start()
        time.sleep(1.0)               # ES-only traffic flows first

        # Phase 2: hybrid key set — both families now verify.
        ph2_pushed.set()
        pool.push_keys(tenant["hybrid_jwks"], epoch=2)
        assert _wait_epochs(pool, 2, timeout=60), \
            f"no convergence on hybrid epoch: {pool.key_epochs()}"
        ph2_converged.set()
        time.sleep(1.0)

        # Phase 3: ML-DSA only, with kill -9 landing mid-push. The
        # worker-side grace window keeps retired ES kids resolving, so
        # classical traffic keeps verifying through the cutover.
        victim = pool.pid(0)
        push_started = threading.Event()

        def killer():
            push_started.wait(timeout=10)
            kill9(victim)

        kt = threading.Thread(target=killer)
        kt.start()
        push_started.set()
        acks = pool.push_keys(tenant["pq_jwks"], epoch=3)
        kt.join(timeout=10)
        assert pool.keys_epoch() == 3
        assert 3 in acks.values(), "no worker acked the final push"
        assert _wait_epochs(pool, 3, timeout=120), \
            f"no convergence after kill -9 mid-push: {pool.key_epochs()}"
        assert pool.pid(0) != victim, "victim was not respawned"
        assert pool.epoch_skew() == 0
        time.sleep(1.0)

        stop.set()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "driver wedged"
        assert not failures, failures
        assert sum(batches) > 0
        # Decision counters saw BOTH families flow on the router.
        c = rec.counters()
        assert c.get("decision.router.family.es", 0) > 0
        assert c.get("decision.router.family.mldsa44", 0) > 0
        # Rotation SLO: lag + push-failure budget green over the run.
        results = {r["name"]: r
                   for r in obs_slo.evaluate_once(rec.snapshot())}
        assert results["rotation_lag"]["ok"], results["rotation_lag"]
    finally:
        pool.close()
        telemetry.disable()


class _FallbackKeySet:
    """Terminal-fallback oracle: CPU verify over the union JWKS."""

    def __init__(self, jwks_doc):
        from cap_tpu.jwt.keyset import StaticKeySet

        self._ks = StaticKeySet([j.key for j in parse_jwks(jwks_doc)])

    def verify_batch(self, tokens):
        return self._ks.verify_batch(tokens)


@pytest.mark.chaos
def test_hybrid_migration_mldsa_to_slhdsa_under_load(tenant, tmp_path):
    """The r17 second leg: ES256 → ML-DSA → SLH-DSA, kill -9 landing
    mid-FINAL-push (the SLH-DSA-only cutover). Same invariants as the
    classical→lattice migration above — zero wrong verdicts, zero
    lost submissions, convergence after respawn — now across a second
    family boundary where the replacement engine is the batched
    Keccak hash forest."""
    jwks_path = tmp_path / "tenant_hybrid.json"
    jwks_path.write_text(json.dumps(tenant["hybrid_jwks"]))

    rec = telemetry.enable()
    pool = WorkerPool(2, keyset_spec=f"jwks:{jwks_path}",
                      ping_interval=0.5, max_restarts=20,
                      spawn_timeout=120, max_wait_ms=2.0)
    try:
        assert pool.wait_all_ready(120), "real-engine fleet not ready"
        fallback = _FallbackKeySet(tenant["union_jwks"])
        # Generous per-attempt budget: a worker's FIRST SLH-DSA batch
        # compiles the hash-forest graph (tens of seconds on this
        # 1-core host) — slow is acceptable, wrong is not.
        cl = FleetClient(pool, fallback=fallback, attempt_timeout=60.0,
                         total_deadline=180.0, rr_seed=0)

        slh_pushed = threading.Event()
        slh_converged = threading.Event()
        stop = threading.Event()
        failures = []
        batches = []

        def driver(d):
            i = 0
            while not stop.is_set() and not failures:
                toks = [tenant["pq_toks"][i % 4],
                        tenant["pq_bad"][i % 4],
                        tenant["slh_toks"][(i + d) % 4],
                        tenant["slh_bad"][(i + d) % 4]]
                after_conv = slh_converged.is_set()
                try:
                    res = cl.verify_batch(toks)
                except Exception as e:  # noqa: BLE001
                    failures.append(f"driver {d}: {e!r}")
                    return
                now_pushed = slh_pushed.is_set()
                if len(res) != len(toks):
                    failures.append(f"driver {d}: lost submissions")
                    return
                pq_ok, pq_bad, slh_ok, slh_bad = [
                    not isinstance(r, Exception) for r in res]
                if not pq_ok:
                    failures.append(
                        f"driver {d}: valid ML-DSA token rejected")
                if pq_bad or slh_bad:
                    failures.append(
                        f"driver {d}: FORGED token accepted")
                if slh_ok and not now_pushed:
                    failures.append(
                        f"driver {d}: SLH-DSA accepted before any "
                        "SLH-DSA key was pushed")
                if not slh_ok and after_conv:
                    failures.append(
                        f"driver {d}: valid SLH-DSA token rejected "
                        "after fleet convergence")
                if slh_ok and res[2] != {"sub": f"slh-{(i + d) % 4}"}:
                    failures.append(f"driver {d}: wrong SLH claims")
                batches.append(len(toks))
                i += 1

        threads = [threading.Thread(target=driver, args=(d,))
                   for d in range(2)]
        for t in threads:
            t.start()
        time.sleep(1.0)               # hybrid ES+ML traffic flows

        # Phase 2: ML-DSA + SLH-DSA hybrid (the second hybrid window).
        slh_pushed.set()
        pool.push_keys(tenant["pq_slh_jwks"], epoch=2)
        assert _wait_epochs(pool, 2, timeout=120), \
            f"no convergence on pq+slh epoch: {pool.key_epochs()}"
        # Warm the SLH engines (compile) before declaring convergence
        # to the drivers — slow-compile rejects would be a test
        # artifact, not a correctness signal.
        warm = cl.verify_batch(tenant["slh_toks"])
        assert all(not isinstance(r, Exception) for r in warm), warm
        slh_converged.set()
        time.sleep(1.0)

        # Phase 3: SLH-DSA only, kill -9 mid-push; grace keeps the
        # retired ML-DSA kid resolving through the cutover.
        victim = pool.pid(0)
        push_started = threading.Event()

        def killer():
            push_started.wait(timeout=10)
            kill9(victim)

        kt = threading.Thread(target=killer)
        kt.start()
        push_started.set()
        acks = pool.push_keys(tenant["slh_jwks"], epoch=3)
        kt.join(timeout=10)
        assert pool.keys_epoch() == 3
        assert 3 in acks.values(), "no worker acked the final push"
        assert _wait_epochs(pool, 3, timeout=180), \
            f"no convergence after kill -9 mid-push: {pool.key_epochs()}"
        assert pool.pid(0) != victim, "victim was not respawned"
        assert pool.epoch_skew() == 0
        time.sleep(1.0)

        stop.set()
        for t in threads:
            t.join(timeout=120)
            assert not t.is_alive(), "driver wedged"
        assert not failures, failures
        assert sum(batches) > 0
        c = rec.counters()
        assert c.get("decision.router.family.mldsa44", 0) > 0
        assert c.get("decision.router.family.slhdsa128f", 0) > 0
        results = {r["name"]: r
                   for r in obs_slo.evaluate_once(rec.snapshot())}
        assert results["rotation_lag"]["ok"], results["rotation_lag"]
    finally:
        pool.close()
        telemetry.disable()
