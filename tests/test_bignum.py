"""Bignum device-arithmetic parity vs Python int arithmetic."""

import random

import numpy as np
import pytest

from cap_tpu.tpu import limbs as L
from cap_tpu.tpu import bignum

rng = random.Random(0xCAB)


def rand_ints(n, bits):
    return [rng.getrandbits(bits) for _ in range(n)]


def rand_odd(bits):
    return rng.getrandbits(bits) | (1 << (bits - 1)) | 1


def test_limb_roundtrip():
    vals = rand_ints(17, 200) + [0, 1, (1 << 208) - 1]
    arr = L.ints_to_limbs(vals, 13)
    assert L.limbs_to_ints(arr) == vals


def test_bytes_be_roundtrip():
    chunks = [rng.getrandbits(b * 8).to_bytes(b, "big")
              for b in (1, 5, 16, 31, 32)]
    arr = L.bytes_be_to_limbs(chunks, 16)
    ints = [int.from_bytes(c, "big") for c in chunks]
    assert L.limbs_to_ints(arr) == ints
    back = L.limbs_to_bytes_be(arr, 32)
    assert [b[-len(c):] if len(c) else b"" for b, c in zip(back, chunks)] \
        == list(chunks)


def test_mul_parity():
    import jax.numpy as jnp

    n, k = 64, 16
    a_i = rand_ints(n, k * 16)
    b_i = rand_ints(n, k * 16)
    a = jnp.asarray(L.ints_to_limbs(a_i, k))
    b = jnp.asarray(L.ints_to_limbs(b_i, k))
    out = np.asarray(bignum.mul(a, b))
    got = L.limbs_to_ints(out)
    assert got == [x * y for x, y in zip(a_i, b_i)]


def test_mul_adversarial_carries():
    import jax.numpy as jnp

    k = 8
    top = (1 << (k * 16)) - 1  # all 0xFFFF limbs → worst-case carry ripple
    vals = [top, top, 1, 0]
    a = jnp.asarray(L.ints_to_limbs(vals, k))
    out = np.asarray(bignum.mul(a, a))
    assert L.limbs_to_ints(out) == [v * v for v in vals]


def test_compare_ge_and_sub():
    import jax.numpy as jnp

    k, n = 8, 6
    xs = [5, 10, 10, (1 << 128) - 1, 0, 7]
    ys = [10, 5, 10, (1 << 128) - 2, 0, 7]
    a = jnp.asarray(L.ints_to_limbs(xs, k))
    b = jnp.asarray(L.ints_to_limbs(ys, k))
    ge = np.asarray(bignum.compare_ge(a, b))
    assert ge.tolist() == [x >= y for x, y in zip(xs, ys)]
    d = np.asarray(bignum.sub_where(a, b, jnp.asarray(ge)))
    expect = [x - y if x >= y else x for x, y in zip(xs, ys)]
    assert L.limbs_to_ints(d) == expect


def test_mont_mul_parity():
    import jax.numpy as jnp

    k, n_tok = 16, 32
    mod = rand_odd(k * 16 - 7)
    nprime, r2, _ = bignum.mont_params(mod, k)
    a_i = [rng.randrange(mod) for _ in range(n_tok)]
    b_i = [rng.randrange(mod) for _ in range(n_tok)]
    r_inv = pow(1 << (16 * k), -1, mod)
    a = jnp.asarray(L.ints_to_limbs(a_i, k))
    b = jnp.asarray(L.ints_to_limbs(b_i, k))
    n_arr = jnp.asarray(L.ints_to_limbs([mod] * n_tok, k))
    np_arr = jnp.asarray(L.ints_to_limbs([nprime] * n_tok, k))
    out = np.asarray(bignum.mont_mul(a, b, n_arr, np_arr))
    got = L.limbs_to_ints(out)
    assert got == [(x * y * r_inv) % mod for x, y in zip(a_i, b_i)]


@pytest.mark.parametrize("bits", [256, 2048])
def test_modexp_65537_parity(bits):
    import jax.numpy as jnp

    # One spare limb beyond the modulus width: the lazy-Montgomery chain
    # requires R ≥ 4n (RSAKeyTable allocates this the same way).
    k = L.nlimbs_for_bits(bits) + 1
    n_tok = 8
    mods = [rand_odd(bits) for _ in range(4)]
    idx = [rng.randrange(4) for _ in range(n_tok)]
    s_i = [rng.randrange(mods[i]) for i in idx]
    n_arr = jnp.asarray(L.ints_to_limbs([mods[i] for i in idx], k))
    params = [bignum.mont_params(m, k) for m in mods]
    np_arr = jnp.asarray(L.ints_to_limbs([params[i][0] for i in idx], k))
    r2_arr = jnp.asarray(L.ints_to_limbs([params[i][1] for i in idx], k))
    s = jnp.asarray(L.ints_to_limbs(s_i, k))
    out = np.asarray(bignum.modexp_65537(s, n_arr, np_arr, r2_arr))
    got = L.limbs_to_ints(out)
    assert got == [pow(x, 65537, mods[i]) for x, i in zip(s_i, idx)]


def test_modexp_vare_parity():
    import jax.numpy as jnp

    k, n_tok = 16, 12
    mods = [rand_odd(k * 16) for _ in range(3)]
    exps = [3, 17, 65537]
    idx = [rng.randrange(3) for _ in range(n_tok)]
    s_i = [rng.randrange(mods[i]) for i in idx]
    params = [bignum.mont_params(m, k) for m in mods]
    n_arr = jnp.asarray(L.ints_to_limbs([mods[i] for i in idx], k))
    np_arr = jnp.asarray(L.ints_to_limbs([params[i][0] for i in idx], k))
    r2_arr = jnp.asarray(L.ints_to_limbs([params[i][1] for i in idx], k))
    one_arr = jnp.asarray(L.ints_to_limbs([params[i][2] for i in idx], k))
    e_arr = jnp.asarray(np.asarray([exps[i] for i in idx], np.uint32))
    s = jnp.asarray(L.ints_to_limbs(s_i, k))
    out = np.asarray(bignum.modexp_vare(s, e_arr, n_arr, np_arr, r2_arr,
                                        one_arr, ebits=17))
    got = L.limbs_to_ints(out)
    assert got == [pow(x, exps[i], mods[i]) for x, i in zip(s_i, idx)]


def test_batch_mont_inverse():
    import jax.numpy as jnp

    k = 16
    p = 0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF
    nprime, r2, one_m = bignum.mont_params(p, k)
    r_mod = 1 << (16 * k)
    n_tok = 256
    xs = [rng.randrange(1, p) for _ in range(n_tok)]
    xm = jnp.asarray(L.ints_to_limbs([x * r_mod % p for x in xs], k))

    def c(v):
        return jnp.asarray(L.int_to_limbs(v, k))[:, None]

    inv = np.asarray(bignum.batch_mont_inverse(
        xm, c(p), c(nprime), c(r2), c(one_m), c(p - 2), nbits=256))
    got = L.limbs_to_ints(inv)
    assert got == [pow(x, -1, p) * r_mod % p for x in xs]


def test_modexp_fixed_exponent_parity():
    import jax.numpy as jnp

    k, n_tok = 8, 6
    mod = rand_odd(k * 16)
    nprime, r2, one_m = bignum.mont_params(mod, k)
    # per-token big exponents (e.g. Fermat p-2 style)
    e_i = [rng.getrandbits(k * 16 - 1) | 1 for _ in range(n_tok)]
    s_i = [rng.randrange(mod) for _ in range(n_tok)]
    s = jnp.asarray(L.ints_to_limbs(s_i, k))
    e = jnp.asarray(L.ints_to_limbs(e_i, k))
    n_arr = jnp.asarray(L.ints_to_limbs([mod] * n_tok, k))
    np_arr = jnp.asarray(L.ints_to_limbs([nprime] * n_tok, k))
    r2_arr = jnp.asarray(L.ints_to_limbs([r2] * n_tok, k))
    one_arr = jnp.asarray(L.ints_to_limbs([one_m] * n_tok, k))
    out = np.asarray(bignum.modexp_fixed_exponent(
        s, e, n_arr, np_arr, r2_arr, one_arr, ebits=k * 16))
    assert L.limbs_to_ints(out) == [pow(x, e, mod) for x, e in zip(s_i, e_i)]
