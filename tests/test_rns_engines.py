"""RNS-engine conformance with the accelerator path FORCED on CPU.

The CI suite runs on CPU where use_rns() defaults off (the limb path
compiles much faster there) — these tests pin the RNS engines' parity
against the CPU oracle for every family that has one: ECDSA
(ES256/ES384/ES512 incl. tamper, cross-key, degenerate r/s), Ed25519
(incl. non-canonical S and bad keys), and the PSS modexp-to-limbs
path. Small key counts/batches keep CPU compile time bounded.
"""

import pytest

pytest.importorskip("cryptography", reason=(
    "module-wide fixtures need the cryptography package: "
    "clean skip instead of a collection ERROR on crypto-less hosts"))



@pytest.fixture(autouse=True)
def _force_rns(monkeypatch):
    monkeypatch.setenv("CAP_TPU_RNS", "1")
    yield


from cap_tpu import testing as captest  # noqa: E402
from cap_tpu.errors import InvalidSignatureError  # noqa: E402
from cap_tpu.jwt.jwk import JWK  # noqa: E402
from cap_tpu.jwt.tpu_keyset import TPUBatchKeySet  # noqa: E402


def _parity(jwks, batch):
    """Batch verdicts must equal the keyset's own single-token CPU
    path (which carries the kid-routing semantics), and, for tokens
    with consistent kids, the trial-verify StaticKeySet oracle."""
    ks = TPUBatchKeySet(jwks)
    res = ks.verify_batch(batch)
    for i, (t, r) in enumerate(zip(batch, res)):
        try:
            ks.verify_signature(t)
            want = True
        except Exception:  # noqa: BLE001 - oracle verdict only
            want = False
        assert (not isinstance(r, Exception)) == want, (i, type(r), r)
    return res


@pytest.mark.parametrize("alg", ["ES256", "ES384", "ES512"])
def test_ecdsa_rns_parity(alg):
    jwks, privs = [], []
    for i in range(2):
        priv, pub = captest.generate_keys(alg)
        jwks.append(JWK(pub, kid=f"k{i}"))
        privs.append(priv)
    claims = captest.default_claims()
    toks = [captest.sign_jwt(privs[i % 2], alg, claims, kid=f"k{i % 2}")
            for i in range(6)]
    tam = toks[0][:-8] + ("AAAAAAAA" if not toks[0].endswith("AAAAAAAA")
                          else "BBBBBBBB")
    cross = captest.sign_jwt(privs[0], alg, claims, kid="k1")  # wrong kid
    res = _parity(jwks, toks + [tam, cross])
    assert isinstance(res[-2], InvalidSignatureError)
    assert isinstance(res[-1], InvalidSignatureError)


def test_ecdsa_rns_degenerate_rs():
    """r = 0 / s = 0 / r,s ≥ n style forgeries must reject (range)."""
    import base64

    priv, pub = captest.generate_keys("ES256")
    good = captest.sign_jwt(priv, "ES256", captest.default_claims(),
                            kid="k0")
    head, payload, _ = good.split(".")
    zero_sig = base64.urlsafe_b64encode(b"\x00" * 64).rstrip(b"=").decode()
    ff_sig = base64.urlsafe_b64encode(b"\xff" * 64).rstrip(b"=").decode()
    bad1 = f"{head}.{payload}.{zero_sig}"
    bad2 = f"{head}.{payload}.{ff_sig}"
    res = _parity([JWK(pub, kid="k0")], [good, bad1, bad2])
    assert not isinstance(res[0], Exception)
    assert isinstance(res[1], Exception) and isinstance(res[2], Exception)


def test_ed25519_rns_parity():
    jwks, privs = [], []
    for i in range(2):
        priv, pub = captest.generate_keys("EdDSA")
        jwks.append(JWK(pub, kid=f"e{i}"))
        privs.append(priv)
    claims = captest.default_claims()
    toks = [captest.sign_jwt(privs[i % 2], "EdDSA", claims, kid=f"e{i % 2}")
            for i in range(6)]
    tam = toks[0][:-8] + ("AAAAAAAA" if not toks[0].endswith("AAAAAAAA")
                          else "BBBBBBBB")
    res = _parity(jwks, toks + [tam])
    assert isinstance(res[-1], InvalidSignatureError)


def test_ed25519_rns_noncanonical_s():
    """S + L forgeries (signature malleability) must reject."""
    import base64

    from cap_tpu.tpu.ed25519 import L_ORDER

    priv, pub = captest.generate_keys("EdDSA")
    good = captest.sign_jwt(priv, "EdDSA", captest.default_claims(),
                            kid="e0")
    head, payload, sig_b64 = good.split(".")
    sig = base64.urlsafe_b64decode(sig_b64 + "==")
    s_int = int.from_bytes(sig[32:], "little")
    forged = sig[:32] + ((s_int + L_ORDER) % (1 << 256)).to_bytes(
        32, "little")
    forged_b64 = base64.urlsafe_b64encode(forged).rstrip(b"=").decode()
    res = _parity([JWK(pub, kid="e0")],
                  [good, f"{head}.{payload}.{forged_b64}"])
    assert not isinstance(res[0], Exception)
    assert isinstance(res[1], Exception)


def test_pss_rns_parity():
    jwks, privs = [], []
    for i in range(2):
        priv, pub = captest.generate_keys("PS256", rsa_bits=1024)
        jwks.append(JWK(pub, kid=f"p{i}"))
        privs.append(priv)
    claims = captest.default_claims()
    toks = [captest.sign_jwt(privs[i % 2], "PS256", claims, kid=f"p{i % 2}")
            for i in range(4)]
    tam = toks[0][:-8] + ("AAAAAAAA" if not toks[0].endswith("AAAAAAAA")
                          else "BBBBBBBB")
    res = _parity(jwks, toks + [tam])
    assert isinstance(res[-1], InvalidSignatureError)
