"""Verdict cache + in-flight replay dedup (ROADMAP #3).

Correctness-preserving contract: the cache tier may change how FAST a
verdict is produced, never WHICH verdict — pinned here as unit clamps
(exp/nbf/epoch/grace/TTL/terminal-reject rules), batcher dedup
fan-out, both serve chains end-to-end, the FleetClient tier, and a
randomized mixed parity sweep (expiring tokens crossing ``exp``
mid-run, an epoch swap mid-run) asserting bit-identical verdicts AND
serve-surface decision-reason counters with the cache on vs off.
"""

import base64
import hashlib
import json
import threading
import time

import pytest

from cap_tpu import telemetry
from cap_tpu.errors import (
    InvalidSignatureError,
    MalformedTokenError,
    UnknownKeyIDError,
)
from cap_tpu.serve.protocol import ProtocolError
from cap_tpu.serve import AdaptiveBatcher, VerifyClient, VerifyWorker
from cap_tpu.serve import vcache as V
from cap_tpu.serve.client import RemoteVerifyError


def _payload(claims):
    return base64.urlsafe_b64encode(
        json.dumps(claims).encode()).rstrip(b"=").decode()


def _tok(name, ok=True, **claims):
    """A stub-verifiable token whose middle segment carries real
    claims (the vcache parses exp/nbf out of it)."""
    mid = _payload(claims) if claims else "e30"
    return f"{name}.{mid}.{'ok' if ok else 'bad'}"


class CountingStub:
    """Suffix-determined verdicts; records every engine-visible token
    (the dedup/cache assertions read ``seen``)."""

    def __init__(self):
        self.seen = []
        self.lock = threading.Lock()
        self.key_epoch = 0

    def swap_keys(self, jwks, epoch=None, grace_s=0.0):
        self.key_epoch = (self.key_epoch + 1 if epoch is None
                          else int(epoch))
        return self.key_epoch

    def verify_batch(self, tokens):
        with self.lock:
            self.seen.extend(tokens)
        return [{"sub": t} if t.endswith(".ok")
                else InvalidSignatureError("bad sig") for t in tokens]


# ---------------------------------------------------------------------------
# unit: the cache itself
# ---------------------------------------------------------------------------


def test_digest_definition_is_sha256_16():
    assert V.DIGEST_LEN == 16
    assert V.token_digest("abc") == hashlib.sha256(b"abc").digest()[:16]
    assert V.token_digest(b"abc") == V.token_digest("abc")


def test_roundtrip_and_counter_exactness():
    vc = V.VerdictCache()
    d = V.token_digest("t.ok")
    assert vc.get(d) is V.MISS
    assert vc.insert(d, {"sub": "t"}, token="t.ok", epoch=None)
    assert vc.get(d) == {"sub": "t"}
    st = vc.stats()
    assert st["vcache.lookups"] == 2
    assert st["vcache.hits"] + st["vcache.misses"] == \
        st["vcache.lookups"]
    assert st["vcache.stale_accepts"] == 0


def test_exp_clamp_never_serves_past_exp():
    vc = V.VerdictCache()
    now = time.time()
    tok = _tok("e", exp=now + 0.2)
    d = V.token_digest(tok)
    assert vc.insert(d, {"sub": "e", "exp": now + 0.2}, token=tok,
                     epoch=None)
    assert vc.get(d) != V.MISS
    time.sleep(0.25)
    assert vc.get(d) is V.MISS          # expired → miss, re-verify
    # already-expired claims never insert at all
    assert not vc.insert(d, {"exp": now - 1}, token=tok, epoch=None)


def test_nbf_clamp():
    vc = V.VerdictCache()
    d = V.token_digest("n")
    assert vc.insert(d, {"nbf": time.time() + 30}, token="n",
                     epoch=None)
    assert vc.get(d) is V.MISS          # not yet valid → engine decides


def test_exp_parsed_from_token_payload_for_raw_bytes():
    vc = V.VerdictCache()
    tok = _tok("p", exp=time.time() - 1)
    d = V.token_digest(tok)
    # raw-claims accept whose bytes do not parse as JSON with exp:
    # the clamp falls back to the token's own payload segment
    assert not vc.insert(d, b"not-json", token=tok, epoch=None)


def test_epoch_bump_invalidates_and_grace_retains():
    vc = V.VerdictCache()
    vc.set_epoch(1)
    d = V.token_digest("g.ok")
    vc.insert(d, b'{"sub":"g"}', token="g.ok", epoch=1)
    # bump with grace: previous-epoch entries survive the window
    vc.bump_epoch(2, grace_s=0.3)
    assert vc.get(d) != V.MISS
    time.sleep(0.35)
    assert vc.get(d) is V.MISS
    # two epochs behind is invalid even inside a fresh grace window
    vc.insert(d, b"x", token="g.ok", epoch=2)
    vc.bump_epoch(3, grace_s=5.0)
    assert vc.get(d) != V.MISS          # prev epoch, in grace
    vc.bump_epoch(4, grace_s=5.0)
    assert vc.get(d) is V.MISS          # 2 behind now
    assert vc.stats()["vcache.epoch_bumps"] == 3


def test_bump_same_epoch_is_noop():
    vc = V.VerdictCache()
    vc.set_epoch(5)
    d = V.token_digest("s.ok")
    vc.insert(d, b"v", token="s.ok", epoch=5)
    vc.bump_epoch(5)
    assert vc.get(d) != V.MISS
    assert vc.stats()["vcache.epoch_bumps"] == 0


def test_insert_racing_a_rotation_is_dropped():
    vc = V.VerdictCache()
    vc.set_epoch(1)
    d = V.token_digest("r.ok")
    vc.bump_epoch(2)
    # verified under epoch 1, rotation landed before the fill
    assert not vc.insert(d, b"v", token="r.ok", epoch=1)
    assert vc.get(d) is V.MISS


def test_only_terminal_rejects_cached():
    vc = V.VerdictCache()
    assert vc.cacheable(InvalidSignatureError("x"))
    assert vc.cacheable(MalformedTokenError("x"))
    assert vc.cacheable({"sub": "a"})
    assert not vc.cacheable(UnknownKeyIDError("x"))   # refresh may fix
    assert not vc.cacheable(ProtocolError("x"))       # transport
    assert not vc.cacheable(TimeoutError("x"))
    d = V.token_digest("u")
    assert not vc.insert(d, UnknownKeyIDError("x"), token="u",
                         epoch=None)
    assert vc.stats()["vcache.insert_skips"] == 1


def test_bounded_eviction():
    vc = V.VerdictCache(capacity=32, shards=4)
    for i in range(200):
        vc.insert(V.token_digest(f"t{i}"), b"v", token=f"t{i}",
                  epoch=None)
    assert vc.size() <= 32
    st = vc.stats()
    assert st["vcache.evictions"] >= 200 - 32
    assert st["vcache.inserts"] == 200


def test_ttl_bound_for_expless_tokens():
    vc = V.VerdictCache(max_ttl_s=0.2)
    d = V.token_digest("ttl.ok")
    vc.insert(d, b"v", token="ttl.ok", epoch=None)
    assert vc.get(d) != V.MISS
    time.sleep(0.25)
    assert vc.get(d) is V.MISS


def test_lookup_batch_uses_supplied_digests_and_falls_back():
    vc = V.VerdictCache()
    toks = ["a.ok", "b.ok"]
    d0 = V.token_digest("a.ok")
    vc.insert(d0, b"va", token="a.ok", epoch=None)
    # supplied digest for a, zero/None for b (native zero-row path)
    hits, miss_idx, digs = vc.lookup_batch(toks, digests=[d0, None])
    assert hits[0] == b"va" and miss_idx == [1]
    assert digs[1] == V.token_digest("b.ok")


# ---------------------------------------------------------------------------
# batcher: in-flight replay dedup
# ---------------------------------------------------------------------------


def test_dedup_verifies_once_and_fans_out():
    ks = CountingStub()
    b = AdaptiveBatcher(ks, target_batch=64, max_wait_ms=20.0,
                        dedup=True)
    try:
        p1 = b.submit_nowait(["d.ok", "d.ok", "x.bad"])
        p2 = b.submit_nowait(["d.ok", "y.ok"])
        p1.event.wait(5)
        p2.event.wait(5)
        assert p1.results[0] == {"sub": "d.ok"}
        assert p1.results[1] == {"sub": "d.ok"}
        assert isinstance(p1.results[2], InvalidSignatureError)
        assert p2.results == [{"sub": "d.ok"}, {"sub": "y.ok"}]
        # the engine saw each distinct token ONCE per flush
        assert sorted(ks.seen) == sorted(["d.ok", "x.bad", "y.ok"]) \
            or ks.seen.count("d.ok") < 3   # (split flushes tolerated)
    finally:
        b.close(5)


def test_dedup_off_sends_everything():
    ks = CountingStub()
    b = AdaptiveBatcher(ks, target_batch=64, max_wait_ms=20.0,
                        dedup=False)
    try:
        p = b.submit_nowait(["d.ok", "d.ok", "d.ok"])
        p.event.wait(5)
        assert ks.seen.count("d.ok") == 3
    finally:
        b.close(5)


def test_dedup_async_pipeline_path():
    from cap_tpu.fleet.worker_main import StubKeySet as FleetStub

    ks = FleetStub(pipeline=1)
    b = AdaptiveBatcher(ks, target_batch=64, max_wait_ms=20.0,
                        dedup=True)
    try:
        p = b.submit_nowait(["a.ok"] * 8 + ["b.bad"] * 2)
        p.event.wait(5)
        assert p.results[:8] == [{"sub": "a.ok"}] * 8
        assert all(isinstance(r, InvalidSignatureError)
                   for r in p.results[8:])
    finally:
        b.close(5)


def test_dedup_counts_fanout():
    rec = telemetry.enable()
    rec.reset()
    ks = CountingStub()
    b = AdaptiveBatcher(ks, target_batch=64, max_wait_ms=20.0,
                        dedup=True)
    try:
        p = b.submit_nowait(["z.ok"] * 10)
        p.event.wait(5)
        assert rec.counters().get("batcher.dedup_fanout", 0) == 9
    finally:
        b.close(5)
        telemetry.disable()


# ---------------------------------------------------------------------------
# worker end-to-end (python chain; native chain below, build-gated)
# ---------------------------------------------------------------------------


def _drive(worker, seq):
    host, port = worker.address
    out = []
    with VerifyClient(host, port) as c:
        for batch in seq:
            out.append(c.verify_batch(batch))
    return out


def _norm(results):
    """Comparable verdict form: claims dict or (reject class head)."""
    out = []
    for batch in results:
        out.append([str(r).split(":", 1)[0] if isinstance(r, Exception)
                    else r for r in batch])
    return out


def _serve_decisions(rec):
    return {k: v for k, v in rec.counters().items()
            if k.startswith("decision.serve.")}


def _run_sweep(serve_native, vcache, seq, rotate_at=None):
    """One sweep run → (normalized verdicts, serve decision counters).

    rotate_at: batch index before which an epoch swap is applied —
    the mid-run invalidation leg of the parity pin."""
    rec = telemetry.enable()
    rec.reset()
    ks = CountingStub()
    w = VerifyWorker(ks, target_batch=128, max_wait_ms=2.0,
                     serve_native=serve_native, vcache=vcache)
    try:
        if serve_native and w.serve_chain != "native":
            pytest.skip("native serve chain unavailable")
        host, port = w.address
        out = []
        with VerifyClient(host, port) as c:
            for i, batch in enumerate(seq):
                if rotate_at is not None and i == rotate_at:
                    w.apply_keys({}, 2)
                out.append(c.verify_batch(batch))
        return _norm(out), _serve_decisions(rec)
    finally:
        w.close(10)
        telemetry.disable()


def _mixed_sequence(n_batches=24, seed=7):
    """Randomized repeat-heavy mix: hot tokens, rejects, an expiring
    token whose exp lands mid-run."""
    import random

    rng = random.Random(seed)
    exp_soon = time.time() + 0.8
    pool = ([_tok(f"hot{i}", ok=True, exp=time.time() + 3600)
             for i in range(4)]
            + [_tok(f"bad{i}", ok=False) for i in range(2)]
            + [_tok("expiring", ok=True, exp=exp_soon)])
    seq = []
    for _ in range(n_batches):
        seq.append([rng.choice(pool)
                    for _ in range(rng.randrange(1, 6))])
    return seq


@pytest.mark.parametrize("serve_native", [False, True])
def test_parity_cache_on_vs_off_mixed_sweep(serve_native):
    """The acceptance pin: bit-identical verdicts AND decision-reason
    counters, cache on vs off, incl. exp crossing + epoch swap."""
    seq = _mixed_sequence()
    on_verdicts, on_dec = _run_sweep(serve_native, True, seq,
                                     rotate_at=12)
    off_verdicts, off_dec = _run_sweep(serve_native, False, seq,
                                       rotate_at=12)
    assert on_verdicts == off_verdicts
    assert on_dec == off_dec


def test_worker_cache_hits_and_all_hit_fast_path():
    rec = telemetry.enable()
    rec.reset()
    ks = CountingStub()
    w = VerifyWorker(ks, target_batch=64, max_wait_ms=2.0,
                     vcache=True)
    try:
        out = _drive(w, [["h.x.ok", "r.x.bad"],
                         ["h.x.ok", "r.x.bad"],
                         ["h.x.ok"]])
        assert out[0][0] == {"sub": "h.x.ok"}
        assert isinstance(out[1][1], RemoteVerifyError)
        assert out[2][0] == out[0][0]
        c = rec.counters()
        assert c.get("vcache.hits", 0) >= 3
        assert c["vcache.lookups"] == c["vcache.hits"] \
            + c["vcache.misses"]
        # repeats never reached the engine
        assert ks.seen.count("h.x.ok") == 1
        assert ks.seen.count("r.x.bad") == 1
        # decision records fired for EVERY response, hit or miss
        dec = _serve_decisions(rec)
        assert dec["decision.serve.accept"] == 3
        assert dec["decision.serve.reject.bad_signature"] == 2
    finally:
        w.close(10)
        telemetry.disable()


def test_worker_epoch_swap_invalidates_cache():
    rec = telemetry.enable()
    rec.reset()
    ks = CountingStub()
    w = VerifyWorker(ks, max_wait_ms=2.0, vcache=True)
    try:
        _drive(w, [["e.x.ok"], ["e.x.ok"]])
        assert ks.seen.count("e.x.ok") == 1
        w.apply_keys({}, 9)
        _drive(w, [["e.x.ok"]])
        # rotation dropped the cached verdict → engine re-verified
        assert ks.seen.count("e.x.ok") == 2
        assert rec.counters().get("vcache.epoch_bumps", 0) == 1
        assert rec.counters().get("vcache.stale_accepts", 0) == 0
    finally:
        w.close(10)
        telemetry.disable()


def test_vcache_off_switch(monkeypatch):
    monkeypatch.setenv("CAP_SERVE_VCACHE", "0")
    ks = CountingStub()
    w = VerifyWorker(ks, max_wait_ms=2.0)
    try:
        assert w._vcache is None
        _drive(w, [["o.x.ok"], ["o.x.ok"]])
        assert ks.seen.count("o.x.ok") == 2
    finally:
        w.close(10)


# ---------------------------------------------------------------------------
# native chain: digest cross-parity (C sha256 == Python hashlib)
# ---------------------------------------------------------------------------


def _native_available():
    try:
        from cap_tpu.serve import native_serve

        return bool(getattr(native_serve.load(), "cap_vc_ok", False))
    except Exception:  # noqa: BLE001
        return False


@pytest.mark.skipif(not _native_available(),
                    reason="native serve runtime unavailable")
def test_native_reader_digests_match_python_hashing():
    rec = telemetry.enable()
    rec.reset()
    ks = CountingStub()
    w = VerifyWorker(ks, max_wait_ms=2.0, serve_native=True,
                     vcache=True)
    try:
        assert w.serve_chain == "native"
        assert w._native._native_digests
        tok = "nd.x.ok"
        _drive(w, [[tok]])
        # the cache was filled under the C-computed digest; a lookup
        # by the PYTHON digest must hit — the two definitions agree
        assert w._vcache.get(V.token_digest(tok)) is not V.MISS
        _drive(w, [[tok]])
        assert ks.seen.count(tok) == 1
        assert rec.counters().get("vcache.hits", 0) >= 1
    finally:
        w.close(10)
        telemetry.disable()


# ---------------------------------------------------------------------------
# client tier (FleetClient)
# ---------------------------------------------------------------------------


def test_fleet_client_tier_short_circuits_before_wire():
    from cap_tpu.fleet.worker_main import StubKeySet as FleetStub

    rec = telemetry.enable()
    rec.reset()
    ks = CountingStub()
    w = VerifyWorker(ks, max_wait_ms=2.0, vcache=False)
    try:
        from cap_tpu.fleet import FleetClient

        cl = FleetClient([w.address], fallback=FleetStub(),
                         vcache=True)
        o1 = cl.verify_batch(["fc.x.ok", "fb.x.bad"])
        o2 = cl.verify_batch(["fc.x.ok", "fb.x.bad"])
        assert o1[0] == o2[0] == {"sub": "fc.x.ok"}
        assert type(o1[1]) is type(o2[1])
        # the repeat never crossed the wire
        assert ks.seen.count("fc.x.ok") == 1
        snap = cl.snapshot()
        assert snap["vcache"]["vcache.hits"] == 2
        # router decision counters fired per CALL, hit or miss
        dec = {k: v for k, v in rec.counters().items()
               if k.startswith("decision.router.")}
        assert dec["decision.router.accept"] == 2
    finally:
        w.close(10)
        telemetry.disable()


def test_fleet_client_bare_endpoint_ttl_configurable(monkeypatch):
    """Bare-endpoint clients have NO pool-epoch visibility: the hard
    TTL is their only rotation bound. CAP_CLIENT_VCACHE_TTL makes it
    configurable (default 30 s unchanged); past the TTL the epoch-less
    entry EXPIRES and the next call goes back to the engine."""
    from cap_tpu.fleet import FleetClient
    from cap_tpu.fleet.worker_main import StubKeySet as FleetStub

    ks = CountingStub()
    w = VerifyWorker(ks, max_wait_ms=2.0, vcache=False)
    try:
        # default path: 30 s (unchanged from r14)
        cl = FleetClient([w.address], fallback=FleetStub(),
                         vcache=True)
        assert cl._vcache._max_ttl == 30.0
        # pool-backed clients keep their long TTL (epoch clamp covers
        # them) — the env knob must not touch that path
        monkeypatch.setenv("CAP_CLIENT_VCACHE_TTL", "0.3")
        cl = FleetClient([w.address], fallback=FleetStub(),
                         vcache=True)
        assert cl._vcache._max_ttl == 0.3
        cl.verify_batch(["ttl.x.ok"])
        cl.verify_batch(["ttl.x.ok"])
        assert ks.seen.count("ttl.x.ok") == 1     # hit inside TTL
        time.sleep(0.35)
        cl.verify_batch(["ttl.x.ok"])             # epoch-less expiry
        assert ks.seen.count("ttl.x.ok") == 2
        st = cl.snapshot()["vcache"]
        assert st["vcache.stale_accepts"] == 0
        # a broken value falls back to the default, never to forever
        monkeypatch.setenv("CAP_CLIENT_VCACHE_TTL", "bogus")
        cl = FleetClient([w.address], vcache=True)
        assert cl._vcache._max_ttl == 30.0
        monkeypatch.setenv("CAP_CLIENT_VCACHE_TTL", "0")
        cl = FleetClient([w.address], vcache=True)
        assert cl._vcache._max_ttl > 0
    finally:
        w.close(10)


def test_fleet_client_tier_parity_on_vs_off():
    from cap_tpu.fleet import FleetClient
    from cap_tpu.fleet.worker_main import StubKeySet as FleetStub

    ks = CountingStub()
    w = VerifyWorker(ks, max_wait_ms=2.0, vcache=False)
    try:
        seq = _mixed_sequence(n_batches=10, seed=3)
        outs = {}
        for state in (True, False):
            cl = FleetClient([w.address], fallback=FleetStub(),
                             vcache=state)
            outs[state] = _norm([cl.verify_batch(b) for b in seq])
        assert outs[True] == outs[False]
    finally:
        w.close(10)


# ---------------------------------------------------------------------------
# dedup preserves per-request trace timelines (acceptance: capstat
# --trace reassembles a deduped member's timeline end-to-end)
# ---------------------------------------------------------------------------


def test_deduped_members_keep_their_trace_timelines():
    import sys
    import os

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    from tools import capstat

    rec = telemetry.enable()
    rec.reset()
    ks = CountingStub()
    # big window so both traced submissions coalesce into ONE flush
    w = VerifyWorker(ks, target_batch=4096, max_wait_ms=120.0,
                     vcache=True)
    try:
        host, port = w.address
        tids = []
        results = []

        def one():
            with telemetry.trace() as tid:
                tids.append(tid)
                with VerifyClient(host, port) as c:
                    from cap_tpu.serve import protocol as P

                    P.send_request(c._sock, ["tr.x.ok"], trace=tid)
                    ftype, entries, echo = \
                        c._reader.recv_frame_ex()
                    results.append((ftype, entries, echo))

        th = [threading.Thread(target=one) for _ in range(2)]
        for t in th:
            t.start()
        for t in th:
            t.join(15)
        assert len(results) == 2
        # the engine verified the duplicate ONCE
        assert ks.seen.count("tr.x.ok") == 1
        flight = [{"trace": e.get("trace"), "spans": e.get("spans", [])}
                  for e in rec.flight_slowest()]
        for tid in tids:
            spans = capstat.reassemble_trace(
                tid, [{"flight": [e for e in flight
                                  if e["trace"] == tid]}])
            names = {s["name"] for s in spans}
            # end-to-end: wire dequeue + batcher fill present for BOTH
            # members even though they shared one verify
            assert telemetry.SPAN_WORKER_DEQUEUE in names, \
                (tid, names)
            assert telemetry.SPAN_BATCHER_FILL in names, (tid, names)
    finally:
        w.close(10)
        telemetry.disable()
