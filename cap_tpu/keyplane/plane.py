"""KeyPlaneKeySet: a device keyset fed by the keyplane.

The glue between a :class:`~cap_tpu.keyplane.refresher.Refresher` and
a swap-capable keyset (``TPUBatchKeySet.swap_keys``): boots from the
source's first snapshot, hot-swaps the device tables whenever the
refresher sees a new epoch, and reproduces cap's reference rotation
behavior on the batch path — a verification that fails because its
kid is unknown to the CURRENT epoch triggers (at most) one
refresher-mediated refresh-and-retry, with the refresher's cooldown
and negative-kid cache bounding what hostile kids can cost.

This is what ``worker_main --keyset jwks-url:<url>`` builds: the
worker keeps serving verdicts across rotations without a restart,
and the same object accepts fleet KEYS pushes (``swap_keys``
delegates), so push- and pull-propagation converge on the same
tables.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

from .. import telemetry
from ..errors import InvalidSignatureError
from .refresher import Refresher, Snapshot
from .source import KeySource


class KeyPlaneKeySet:
    """KeySet facade over a keyplane-managed ``TPUBatchKeySet``.

    source: where JWKS documents come from; interval_s/jitter/
    miss_cooldown_s/negative_ttl_s: refresher knobs; grace_s: how long
    a retired epoch's kids keep resolving after a swap;
    keyset_factory: ``callable(jwks, epoch) -> keyset`` override
    (tests); remaining kwargs go to ``TPUBatchKeySet``.
    """

    def __init__(self, source: KeySource, interval_s: float = 300.0,
                 jitter: float = 0.1, miss_cooldown_s: float = 10.0,
                 negative_ttl_s: float = 30.0, grace_s: float = 30.0,
                 start: bool = True, keyset_factory=None,
                 **ks_kwargs: Any):
        self._grace = grace_s
        self._factory = keyset_factory
        self._ks_kwargs = ks_kwargs
        self._ks = None
        self._swap_lock = threading.Lock()
        self._refresher = Refresher(
            source, apply=self._apply_snapshot, interval_s=interval_s,
            jitter=jitter, miss_cooldown_s=miss_cooldown_s,
            negative_ttl_s=negative_ttl_s)
        # First snapshot is mandatory: a worker must not come up READY
        # with no keys (it would reject valid tokens — a wrong verdict).
        self._refresher.refresh()
        if start:
            self._refresher.start()

    # -- keyplane plumbing -------------------------------------------------

    def _make_keyset(self, jwks, epoch: int):
        if self._factory is not None:
            return self._factory(jwks, epoch)
        from ..jwt.tpu_keyset import TPUBatchKeySet

        return TPUBatchKeySet(jwks, epoch=epoch, **self._ks_kwargs)

    def _apply_snapshot(self, snap: Snapshot) -> None:
        from ..jwt.jwk import parse_jwks

        jwks = parse_jwks(snap.doc)
        with self._swap_lock:
            if self._ks is None:
                with telemetry.span(telemetry.SPAN_KEYPLANE_SWAP):
                    self._ks = self._make_keyset(jwks, snap.epoch)
                telemetry.gauge("keyplane.epoch", snap.epoch)
            else:
                self._ks.swap_keys(jwks, epoch=snap.epoch,
                                   grace_s=self._grace)

    @property
    def refresher(self) -> Refresher:
        return self._refresher

    @property
    def key_epoch(self) -> int:
        ks = self._ks
        return getattr(ks, "key_epoch", 0) if ks is not None else 0

    def swap_keys(self, jwks, epoch: Optional[int] = None,
                  grace_s: Optional[float] = None) -> int:
        """Fleet KEYS-push entry point: delegate to the device keyset.

        A pushed epoch overrides the refresher's counter on the TABLE
        side; the refresher keeps its own digest-based counter and
        will only swap again when the SOURCE's content changes.
        """
        with self._swap_lock:
            return self._ks.swap_keys(
                jwks, epoch=epoch,
                grace_s=self._grace if grace_s is None else grace_s)

    def close(self) -> None:
        self._refresher.close()

    # -- verify surface ----------------------------------------------------

    def verify_signature(self, token: str) -> Dict[str, Any]:
        res = self._verify_rotation_aware([token], raw=False)[0]
        if isinstance(res, Exception):
            raise res
        return res

    def verify_batch(self, tokens: Sequence[str]) -> List[Any]:
        return self._verify_rotation_aware(tokens, raw=False)

    def verify_batch_raw(self, tokens: Sequence[str]) -> List[Any]:
        return self._verify_rotation_aware(tokens, raw=True)

    def _verify_rotation_aware(self, tokens: Sequence[str],
                               raw: bool) -> List[Any]:
        from ..jwt.jose import parse_jws

        ks = self._ks
        call = ks.verify_batch_raw if raw else ks.verify_batch
        results = call(tokens)
        snap = self._refresher.snapshot
        known = snap.kids if snap is not None else frozenset()
        missed: Dict[int, str] = {}
        for i, r in enumerate(results):
            if not isinstance(r, InvalidSignatureError):
                continue
            try:
                parsed = parse_jws(tokens[i])
            except Exception:  # noqa: BLE001 - malformed keeps its error
                continue
            if parsed.kid is not None and parsed.kid not in known:
                missed[i] = parsed.kid
        if not missed:
            return results
        # Rotation path: one refresher-mediated refresh for the whole
        # batch (singleflight + cooldown + negative cache inside), then
        # retry ONLY the missed tokens against the swapped tables. A
        # suppressed or failed refresh keeps the original verdicts —
        # never an exception for the whole batch.
        refreshed = None
        for kid in dict.fromkeys(missed.values()):
            refreshed = self._refresher.on_miss(kid) or refreshed
        if refreshed is None:
            return results
        ks = self._ks
        retry_call = ks.verify_batch_raw if raw else ks.verify_batch
        retry = retry_call([tokens[i] for i in missed])
        for i, r in zip(missed, retry):
            results[i] = r
        return results
