"""Keyplane: epoch-versioned JWKS distribution with hot key rotation.

The key-distribution control plane behind BASELINE config 4
("NewJSONWebKeySet with rotating kids") at fleet scale:

- :mod:`cap_tpu.keyplane.source` — where key material comes from
  (static file, remote JWKS URL, OIDC discovery);
- :mod:`cap_tpu.keyplane.refresher` — epoch-versioned snapshots with
  jittered periodic refresh, singleflight on-miss refresh under a
  cooldown, and a TTL'd negative-kid cache;
- :mod:`cap_tpu.keyplane.plane` — :class:`KeyPlaneKeySet`, the
  rotation-aware device keyset a fleet worker serves from;
- fleet propagation rides the CVB1 KEYS frame pair (types 11/12,
  :mod:`cap_tpu.serve.protocol`) pushed by
  :meth:`cap_tpu.fleet.pool.WorkerPool.push_keys`.

See docs/KEYPLANE.md for the epoch model, the grace window, and the
wire format.
"""

from .refresher import Refresher, Snapshot
from .source import (
    KeySource,
    OIDCDiscoverySource,
    RemoteJWKSSource,
    StaticFileSource,
    canonical_digest,
    source_for_spec,
)

__all__ = [
    "KeySource",
    "StaticFileSource",
    "RemoteJWKSSource",
    "OIDCDiscoverySource",
    "canonical_digest",
    "source_for_spec",
    "Refresher",
    "Snapshot",
    "KeyPlaneKeySet",
]


def __getattr__(name):
    # KeyPlaneKeySet pulls in the jwt stack on use, not on package
    # import (same lazy-export discipline as cap_tpu.jwt).
    if name == "KeyPlaneKeySet":
        from .plane import KeyPlaneKeySet

        return KeyPlaneKeySet
    raise AttributeError(name)
