"""Key sources: where a JWKS document comes from.

A :class:`KeySource` produces ``(doc, digest)`` pairs — the parsed
JWKS JSON object plus a content digest over its canonical encoding —
so the refresher can detect "nothing changed" without diffing key
material. Three concrete sources mirror the ways the reference loads
keys (jwt/keyset.go: static keys, remote JWKS URL, OIDC discovery):

- :class:`StaticFileSource` — a JWKS JSON file on disk (the existing
  ``worker_main --keyset jwks:<path>`` input, now re-readable);
- :class:`RemoteJWKSSource` — a JWKS endpoint over
  :mod:`cap_tpu.utils.http`, using conditional ETag fetches so a
  periodic refresh of an unchanged document is a header-only round
  trip;
- :class:`OIDCDiscoverySource` — issuer → discovery document →
  ``jwks_uri`` (reusing :func:`cap_tpu.utils.http.fetch_discovery`,
  including its issuer-equality check), then a remote fetch.

Sources hold PUBLIC key material only (a JWKS by definition); the
digest/doc never contain tokens or claims, so nothing here interacts
with the telemetry redaction rules.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional, Tuple

from ..errors import InvalidJWKSError, InvalidParameterError
from ..utils import http as _http


def canonical_digest(doc: Dict[str, Any]) -> str:
    """Content digest over the canonical (sorted, compact) encoding —
    whitespace or key-order churn at the IdP is not a key rotation."""
    raw = json.dumps(doc, separators=(",", ":"), sort_keys=True).encode()
    return hashlib.sha256(raw).hexdigest()


def _check_jwks(doc: Any, origin: str) -> Dict[str, Any]:
    if not isinstance(doc, dict):
        raise InvalidJWKSError(f"{origin}: jwks is not a JSON object")
    keys = doc.get("keys")
    if not isinstance(keys, list):
        raise InvalidJWKSError(f"{origin}: jwks has no 'keys' array")
    return doc


class KeySource:
    """Produces JWKS snapshots for the refresher."""

    #: short human-readable origin ("file:...", "url:...", "oidc:...")
    description: str = "?"

    def fetch(self) -> Tuple[Dict[str, Any], str]:
        """One fetch → (jwks document, canonical content digest).

        Raises :class:`InvalidJWKSError` (bad payload) or transport
        errors (OSError subclasses) — the refresher counts and keeps
        serving the previous snapshot either way.
        """
        raise NotImplementedError


class StaticFileSource(KeySource):
    """JWKS JSON file on disk, re-read on every fetch (so an operator
    can rotate keys by rewriting the file, atomically via rename)."""

    def __init__(self, path: str):
        if not path:
            raise InvalidParameterError("jwks file path is required")
        self._path = path
        self.description = f"file:{path}"

    def fetch(self) -> Tuple[Dict[str, Any], str]:
        with open(self._path, "rb") as f:
            body = f.read()
        try:
            doc = json.loads(body)
        except ValueError as e:
            raise InvalidJWKSError(
                f"{self.description}: not valid JSON: {e}") from e
        doc = _check_jwks(doc, self.description)
        return doc, canonical_digest(doc)


class RemoteJWKSSource(KeySource):
    """JWKS endpoint over the pooled HTTP helpers, ETag-conditional."""

    def __init__(self, url: str, ca_pem: Optional[str] = None,
                 timeout: float = 10.0):
        if not url:
            raise InvalidParameterError("jwks url is required")
        self._url = url
        self._ctx = _http.ssl_context_for_ca(ca_pem)
        self._timeout = timeout
        self.description = f"url:{url}"

    def fetch(self) -> Tuple[Dict[str, Any], str]:
        status, body, _ = _http.get(self._url, self._ctx,
                                    timeout=self._timeout,
                                    conditional=True)
        if status != 200:
            raise InvalidJWKSError(
                f"{self.description}: fetch failed: status {status}")
        try:
            doc = json.loads(body)
        except ValueError as e:
            raise InvalidJWKSError(
                f"{self.description}: not valid JSON: {e}") from e
        doc = _check_jwks(doc, self.description)
        return doc, canonical_digest(doc)


class OIDCDiscoverySource(RemoteJWKSSource):
    """Issuer → discovery document → jwks_uri → remote JWKS.

    Discovery runs lazily on the first fetch (and again after a fetch
    against a stale ``jwks_uri`` fails), so constructing the source is
    network-free — a worker can build its keyplane before the IdP is
    reachable and converge once it is.
    """

    def __init__(self, issuer: str, ca_pem: Optional[str] = None,
                 timeout: float = 10.0):
        if not issuer:
            raise InvalidParameterError("issuer is required")
        self._issuer = issuer
        self._ca_pem = ca_pem
        self._ctx = _http.ssl_context_for_ca(ca_pem)
        self._timeout = timeout
        self._url: Optional[str] = None
        self.description = f"oidc:{issuer}"

    def _discover(self) -> str:
        doc = _http.fetch_discovery(self._issuer, self._ctx)
        jwks_uri = doc.get("jwks_uri")
        if not isinstance(jwks_uri, str) or not jwks_uri:
            raise InvalidParameterError(
                f"{self.description}: discovery document missing jwks_uri")
        return jwks_uri

    def fetch(self) -> Tuple[Dict[str, Any], str]:
        if self._url is None:
            self._url = self._discover()
        try:
            return super().fetch()
        except (InvalidJWKSError, OSError):
            # jwks_uri may itself have rotated: re-discover once.
            self._url = self._discover()
            return super().fetch()


def source_for_spec(spec: str,
                    ca_pem: Optional[str] = None) -> KeySource:
    """Parse a ``--keyset``-style source spec into a KeySource.

    ``jwks:<path>`` → file, ``jwks-url:<url>`` → remote endpoint,
    ``oidc:<issuer>`` → discovery. Raises ValueError on anything else
    (matching worker_main.make_keyset's contract).
    """
    if spec.startswith("jwks-url:"):
        return RemoteJWKSSource(spec[len("jwks-url:"):], ca_pem=ca_pem)
    if spec.startswith("jwks:"):
        return StaticFileSource(spec[len("jwks:"):])
    if spec.startswith("oidc:"):
        return OIDCDiscoverySource(spec[len("oidc:"):], ca_pem=ca_pem)
    raise ValueError(f"unknown key source spec {spec!r}")
