"""The refresher: epoch-versioned snapshots with stampede protection.

A :class:`Refresher` pulls JWKS documents out of a
:class:`~cap_tpu.keyplane.source.KeySource` and versions them into
:class:`Snapshot` objects — the epoch counter increments ONLY when the
document's canonical digest changes, so jittered periodic polling of a
stable IdP never churns epochs (and never rebuilds device tables).

Stampede protection, in layers:

- **singleflight**: concurrent ``refresh()`` callers coalesce onto one
  in-flight fetch; followers wait for the leader's result instead of
  issuing their own (the thundering-herd guard for a fleet worker
  whose every connection sees the same unknown kid at once);
- **miss cooldown**: ``on_miss(kid)`` refreshes at most once per
  ``miss_cooldown_s`` — attacker tokens with random kids cannot
  amplify into IdP fetches;
- **TTL'd negative-kid cache**: a kid the *freshly fetched* document
  still lacks is remembered for ``negative_ttl_s``; repeat misses on
  it return instantly without even reaching the cooldown check.

The refresher never raises out of its background thread and never
drops a working snapshot on a failed fetch: the previous epoch keeps
serving, and ``keyplane.refresh_errors`` counts the failure.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, FrozenSet, Optional

from .. import telemetry
from .source import KeySource

# Bound on remembered unknown kids (attacker-controlled names).
_MAX_NEGATIVE_KIDS = 1024


class Snapshot:
    """One epoch of key material: the JWKS document, its kid set, and
    the monotonically increasing epoch number."""

    __slots__ = ("epoch", "doc", "digest", "kids", "fetched_at")

    def __init__(self, epoch: int, doc: Dict[str, Any], digest: str,
                 fetched_at: float):
        self.epoch = epoch
        self.doc = doc
        self.digest = digest
        self.fetched_at = fetched_at
        self.kids: FrozenSet[str] = frozenset(
            k.get("kid") for k in doc.get("keys", [])
            if isinstance(k, dict) and k.get("kid"))

    def __repr__(self) -> str:
        return (f"Snapshot(epoch={self.epoch}, kids={len(self.kids)}, "
                f"digest={self.digest[:12]})")


class Refresher:
    """Pull snapshots from a source; push changed ones into ``apply``.

    apply: callable(Snapshot) run OUTSIDE the refresher lock whenever
    the key material changed — the keyplane wires it to
    ``TPUBatchKeySet.swap_keys`` (device-table build happens there, off
    every serving thread but this one). interval_s/jitter: periodic
    cadence of the background thread (``start()``); each sleep is
    ``interval_s`` ± ``jitter`` fraction, so a fleet of workers never
    phase-locks onto the IdP.
    """

    def __init__(self, source: KeySource,
                 apply: Optional[Callable[[Snapshot], None]] = None,
                 interval_s: float = 300.0, jitter: float = 0.1,
                 miss_cooldown_s: float = 10.0,
                 negative_ttl_s: float = 30.0):
        self._source = source
        self._apply = apply
        self._interval = interval_s
        self._jitter = max(0.0, min(jitter, 0.9))
        self._miss_cooldown = miss_cooldown_s
        self._negative_ttl = negative_ttl_s
        self._lock = threading.Lock()
        self._inflight: Optional[threading.Event] = None
        self._snapshot: Optional[Snapshot] = None
        self._neg: Dict[str, float] = {}      # kid → expiry (monotonic)
        self._last_miss = float("-inf")
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- read side ---------------------------------------------------------

    @property
    def snapshot(self) -> Optional[Snapshot]:
        with self._lock:
            return self._snapshot

    @property
    def epoch(self) -> int:
        snap = self.snapshot
        return snap.epoch if snap is not None else 0

    # -- refresh -----------------------------------------------------------

    def refresh(self, wait_s: float = 30.0) -> Optional[Snapshot]:
        """Fetch once (singleflight) and return the current snapshot.

        The LEADER (first caller while nothing is in flight) performs
        the fetch and raises on failure; FOLLOWERS wait up to
        ``wait_s`` for the leader and return whatever snapshot is then
        current (possibly the previous epoch — they never raise for
        the leader's failure).
        """
        with self._lock:
            ev = self._inflight
            if ev is None:
                ev = self._inflight = threading.Event()
                leader = True
            else:
                leader = False
        if not leader:
            telemetry.count("keyplane.refresh_coalesced")
            ev.wait(timeout=wait_s)
            return self.snapshot
        t0 = time.perf_counter()
        try:
            doc, digest = self._source.fetch()
        except Exception:
            telemetry.count("keyplane.refresh_errors")
            raise
        finally:
            with self._lock:
                self._inflight = None
            ev.set()
        now = time.monotonic()
        with self._lock:
            cur = self._snapshot
            if cur is not None and cur.digest == digest:
                cur.fetched_at = now
                telemetry.count("keyplane.refresh_unchanged")
                return cur
            snap = Snapshot((cur.epoch if cur else 0) + 1, doc, digest,
                            now)
            self._snapshot = snap
            # A kid that exists now is no longer negative.
            for kid in list(self._neg):
                if kid in snap.kids:
                    del self._neg[kid]
        telemetry.count("keyplane.refreshes")
        telemetry.observe("keyplane.refresh_s",
                          time.perf_counter() - t0)
        telemetry.gauge("keyplane.epoch", snap.epoch)
        if self._apply is not None:
            # Outside the lock: table builds are slow, readers of
            # .snapshot/.epoch must not block behind them. Serialized
            # anyway — only a refresh leader ever reaches here.
            self._apply(snap)
        return snap

    def on_miss(self, kid: Optional[str]) -> Optional[Snapshot]:
        """Unknown-kid hook: maybe refresh; returns the new snapshot
        when one was fetched, None when suppressed (negative cache or
        cooldown) or when the fetch failed."""
        now = time.monotonic()
        with self._lock:
            if kid:
                exp = self._neg.get(kid)
                if exp is not None and exp > now:
                    telemetry.count("keyplane.miss_negative_hits")
                    return None
            if now - self._last_miss < self._miss_cooldown:
                telemetry.count("keyplane.miss_suppressed")
                return None
            # Stamp BEFORE the fetch so a slow/failing IdP is also
            # rate-limited (same stance as JSONWebKeySet's cooldown).
            self._last_miss = now
        telemetry.count("keyplane.miss_refreshes")
        try:
            snap = self.refresh()
        except Exception:  # noqa: BLE001 - counted in refresh()
            return None
        if kid and snap is not None and kid not in snap.kids:
            with self._lock:
                if len(self._neg) >= _MAX_NEGATIVE_KIDS:
                    # Drop the soonest-to-expire entries first.
                    for k in sorted(self._neg, key=self._neg.get)[
                            :len(self._neg) - _MAX_NEGATIVE_KIDS + 1]:
                        del self._neg[k]
                self._neg[kid] = now + self._negative_ttl
        return snap

    # -- background polling ------------------------------------------------

    def start(self) -> "Refresher":
        """Start the jittered periodic refresh thread (idempotent)."""
        with self._lock:
            if self._thread is not None:
                return self
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="cap-tpu-keyplane")
        self._thread.start()
        return self

    def _loop(self) -> None:
        import random

        while True:
            delay = self._interval * (
                1.0 + self._jitter * (2.0 * random.random() - 1.0))
            if self._closed.wait(max(0.05, delay)):
                return
            try:
                self.refresh()
            except Exception:  # noqa: BLE001 - counted; keep serving
                pass

    def close(self) -> None:
        self._closed.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
