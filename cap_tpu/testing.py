"""Test infrastructure: key generation, JWT signing, CA generation.

Analog of the reference's exported test helpers (oidc/testing.go:29-112:
TestGenerateKeys, TestSignJWT, TestGenerateCA), usable both by this
repo's tests and by users of the framework. Signing exists ONLY to
produce fixtures — the framework's job is verification.
"""

from __future__ import annotations

import datetime
import json
from contextlib import contextmanager
from typing import Any, Dict, Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec, ed25519, padding, rsa
from cryptography.hazmat.primitives.asymmetric.utils import decode_dss_signature
from cryptography.x509.oid import NameOID

from .jwt import algs
from .jwt.jose import b64url_encode

_EC_CURVE = {
    algs.ES256: (ec.SECP256R1, 32),
    algs.ES384: (ec.SECP384R1, 48),
    algs.ES512: (ec.SECP521R1, 66),
}
_HASH = {
    "sha256": hashes.SHA256,
    "sha384": hashes.SHA384,
    "sha512": hashes.SHA512,
}


def generate_keys(alg: str = algs.ES256, rsa_bits: int = 2048):
    """Generate a (private, public) key pair suitable for ``alg``."""
    if alg in (algs.RS256, algs.RS384, algs.RS512,
               algs.PS256, algs.PS384, algs.PS512):
        priv = rsa.generate_private_key(public_exponent=65537, key_size=rsa_bits)
    elif alg in _EC_CURVE:
        priv = ec.generate_private_key(_EC_CURVE[alg][0]())
    elif alg == algs.EdDSA:
        priv = ed25519.Ed25519PrivateKey.generate()
    else:
        raise ValueError(f"unsupported alg {alg!r}")
    return priv, priv.public_key()


def sign_jwt(priv, alg: str, claims: Dict[str, Any],
             kid: Optional[str] = None,
             extra_headers: Optional[Dict[str, Any]] = None) -> str:
    """Sign ``claims`` into a compact JWS with the given private key."""
    header: Dict[str, Any] = {"alg": alg, "typ": "JWT"}
    if kid:
        header["kid"] = kid
    if extra_headers:
        header.update(extra_headers)
    signing_input = (
        b64url_encode(json.dumps(header, separators=(",", ":")).encode())
        + "."
        + b64url_encode(json.dumps(claims, separators=(",", ":")).encode())
    ).encode("ascii")

    hash_cls = _HASH[algs.HASH_FOR_ALG[alg]]
    if alg in (algs.RS256, algs.RS384, algs.RS512):
        sig = priv.sign(signing_input, padding.PKCS1v15(), hash_cls())
    elif alg in (algs.PS256, algs.PS384, algs.PS512):
        sig = priv.sign(
            signing_input,
            padding.PSS(mgf=padding.MGF1(hash_cls()),
                        salt_length=hash_cls.digest_size),
            hash_cls(),
        )
    elif alg in _EC_CURVE:
        coord = _EC_CURVE[alg][1]
        der = priv.sign(signing_input, ec.ECDSA(hash_cls()))
        r, s = decode_dss_signature(der)
        sig = r.to_bytes(coord, "big") + s.to_bytes(coord, "big")
    elif alg == algs.EdDSA:
        sig = priv.sign(signing_input)
    else:
        raise ValueError(f"unsupported alg {alg!r}")
    return signing_input.decode("ascii") + "." + b64url_encode(sig)


def default_claims(issuer: str = "https://example.com/", sub: str = "alice",
                   aud=("client-id",), now: Optional[float] = None,
                   ttl: float = 300.0, **extra) -> Dict[str, Any]:
    """A standard valid claims set for test JWTs."""
    import time

    t = now if now is not None else time.time()
    claims: Dict[str, Any] = {
        "iss": issuer,
        "sub": sub,
        "aud": list(aud),
        "iat": int(t),
        "nbf": int(t),
        "exp": int(t + ttl),
    }
    claims.update(extra)
    return claims


def sign_unique_jwts(signers, n: int, ttl: float = 86400.0):
    """n UNIQUE test JWTs: distinct sub/jti per token → distinct payload
    bytes AND signatures (the honest-bench workload; VERDICT r2 #3).

    signers: [(private_key, alg, kid), ...] cycled round-robin; signing
    runs across threads (OpenSSL releases the GIL).
    """
    import os
    from concurrent.futures import ThreadPoolExecutor

    base = default_claims(ttl=ttl)

    def sign(j: int) -> str:
        priv, alg, kid = signers[j % len(signers)]
        claims = dict(base, sub=f"user-{j:08d}", jti=f"tok-{j:012d}")
        return sign_jwt(priv, alg, claims, kid=kid)

    with ThreadPoolExecutor(min(16, os.cpu_count() or 4)) as ex:
        return list(ex.map(sign, range(n), chunksize=256))


def headline_fixtures(n_unique: int):
    """The BASELINE.json north-star workload: a 16-key JWKS (8×RSA-2048
    + 8×P-256) and n_unique UNIQUE mixed RS256/ES256 tokens.

    Shared by bench.py and tools/bench_serve.py so the offline and
    serving benchmarks can never desynchronize their key mix.
    """
    from .jwt import algs
    from .jwt.jwk import JWK

    jwks, signers = [], []
    for i in range(8):
        priv, pub = generate_keys(algs.RS256, rsa_bits=2048)
        jwks.append(JWK(pub, kid=f"rs-{i}"))
        signers.append((priv, algs.RS256, f"rs-{i}"))
    for i in range(8):
        priv, pub = generate_keys(algs.ES256)
        jwks.append(JWK(pub, kid=f"es-{i}"))
        signers.append((priv, algs.ES256, f"es-{i}"))
    return jwks, sign_unique_jwts(signers, n_unique)


def to_json_form(token: str, flattened: bool = True,
                 unprotected: Optional[Dict[str, Any]] = None) -> str:
    """Re-serialize a compact JWS as its RFC 7515 §7.2 JSON form.

    ``flattened`` picks §7.2.2 (flattened) vs §7.2.1 (general, one
    signature); ``unprotected`` becomes the per-signature unprotected
    header. Fixture helper for the JSON-serialization parity tests.
    """
    h, p, s = token.split(".")
    sig_obj: Dict[str, Any] = {"protected": h, "signature": s}
    if unprotected is not None:
        sig_obj["header"] = unprotected
    if flattened:
        return json.dumps({"payload": p, **sig_obj})
    return json.dumps({"payload": p, "signatures": [sig_obj]})


def x5c_jwk(priv, pub, kid: Optional[str] = None,
            include_params: bool = False) -> Dict[str, Any]:
    """A JWK whose key material rides an ``x5c`` self-signed cert.

    With ``include_params=False`` (the default) the n/e, x/y, or OKP x
    members are stripped so the chain is the ONLY key material — the
    go-jose-accepted shape the x5c parity tests pin. The cert is signed
    with ``priv`` itself (self-signed leaf).
    """
    import base64

    from .jwt.jwk import serialize_public_key

    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "cap-tpu-x5c")])
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(pub)
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
    )
    sign_hash = (None if isinstance(priv, ed25519.Ed25519PrivateKey)
                 else hashes.SHA256())
    cert = builder.sign(priv, sign_hash)
    der = cert.public_bytes(serialization.Encoding.DER)
    jwk = serialize_public_key(pub, kid=kid)
    jwk["x5c"] = [base64.b64encode(der).decode("ascii")]
    if not include_params:
        for field in ("n", "e", "x", "y"):
            jwk.pop(field, None)
    return jwk


def generate_ca(common_name: str = "cap-tpu-test-ca") -> Tuple[str, Any, str]:
    """Generate a self-signed CA; returns (cert_pem, private_key, key_pem)."""
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=1))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .add_extension(
            x509.SubjectAlternativeName([
                x509.DNSName("localhost"),
                x509.IPAddress(__import__("ipaddress").ip_address("127.0.0.1")),
            ]),
            critical=False,
        )
        .sign(key, hashes.SHA256())
    )
    cert_pem = cert.public_bytes(serialization.Encoding.PEM).decode()
    key_pem = key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    ).decode()
    return cert_pem, key, key_pem


@contextmanager
def jwks_test_server(state: Dict[str, Any]):
    """Serve ``{"keys": state["keys"]}`` over loopback HTTP.

    The JWKS analog of :class:`TestProvider` for tests that need ONLY a
    rotating key endpoint (remote/discovery keysets): mutate
    ``state["keys"]`` between requests to rotate; every GET increments
    ``state["fetches"]``. Yields ``(url, server)`` — the server handle
    lets failure tests shut the endpoint down mid-test.
    """
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    state.setdefault("fetches", 0)

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            state["fetches"] += 1
            body = json.dumps({"keys": state["keys"]}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}/jwks", srv
    finally:
        srv.shutdown()
        srv.server_close()  # release the listening fd (idempotent)
