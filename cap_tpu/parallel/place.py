"""Mesh placement helpers for the packed verify programs.

The packed dispatch functions (rsa/ec/ed25519 ``verify_*_packed_pending``)
take every device table as an explicit argument, so multi-chip execution
needs exactly two placements (SURVEY.md §2.6 "sharded bignum kernels"):

- the packed record matrix sharded along the batch axis
  (``PartitionSpec(axis, None)``) — token data parallelism over ICI;
- the key/window tables replicated (``PartitionSpec()``) — the key
  gather then runs locally on every shard.

XLA's GSPMD propagation partitions the whole verify program from those
input shardings; the jit-captured RNS context constants replicate
automatically. Validated on the virtual 8-device CPU mesh by
tests/test_parallel.py and the driver's dryrun_multichip.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

_replicated_cache: Dict[Tuple[int, int], Any] = {}


def batch_axis(mesh) -> str:
    """The mesh axis the batch shards over (its first axis)."""
    return mesh.axis_names[0]


def shard_batch(mesh, arr):
    """Place a host array sharded along axis 0 of the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    spec = PartitionSpec(batch_axis(mesh), *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def replicated(mesh, arr):
    """Mesh-replicated copy of a device array, cached per (mesh, array).

    Cache keys are object ids; both the mesh (owned by the KeySet) and
    the table arrays (owned by the key tables) outlive the cache entry's
    usefulness, so ids are stable.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    key = (id(mesh), id(arr))
    out = _replicated_cache.get(key)
    if out is None:
        out = jax.device_put(arr, NamedSharding(mesh, PartitionSpec()))
        _replicated_cache[key] = out
    return out
