"""Mesh placement helpers for the packed verify programs.

The packed dispatch functions (rsa/ec/ed25519 ``verify_*_packed_pending``)
take every device table as an explicit argument, so multi-chip execution
needs exactly two placements (SURVEY.md §2.6 "sharded bignum kernels"):

- the packed record matrix sharded along the batch axis
  (``PartitionSpec(axis, None)``) — token data parallelism over ICI;
- the key/window tables replicated (``PartitionSpec()``) — the key
  gather then runs locally on every shard.

XLA's GSPMD propagation partitions the whole verify program from those
input shardings; the jit-captured RNS context constants replicate
automatically. Validated on the virtual 8-device CPU mesh by
tests/test_parallel.py and the driver's dryrun_multichip.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry


class PlacementError(ValueError):
    """A fleet placement violates the single-owner-per-device model."""


@dataclass(frozen=True)
class WorkerPlacement:
    """One worker process's exclusive device group.

    The serve-fleet placement model (VERDICT r5: the serve projection
    silently assumed two processes can share one TPU chip — they
    generally cannot): every device belongs to EXACTLY ONE worker
    process, expressed as subprocess environment rather than runtime
    cooperation, so ownership is enforced by process isolation:

    - ``platform="tpu"``: ``TPU_VISIBLE_DEVICES`` restricts the child
      to its chip group (libtpu refuses a chip another process holds —
      the single-owner invariant is also enforced by the hardware
      runtime);
    - ``platform="cpu"`` (this container, tests, dry-runs): the child
      gets its OWN virtual-device world (``JAX_PLATFORMS=cpu`` plus a
      device count); CPU "devices" are process-local threads, so
      disjointness across children holds by construction.
    """

    worker_id: int
    device_ids: Tuple[int, ...]
    platform: str = "cpu"

    def env(self) -> Dict[str, str]:
        """Environment overrides for the worker subprocess."""
        ids = ",".join(str(d) for d in self.device_ids)
        out = {"CAP_FLEET_WORKER_ID": str(self.worker_id),
               "CAP_FLEET_DEVICE_GROUP": ids}
        if self.platform == "tpu":
            out["JAX_PLATFORMS"] = "tpu"
            out["TPU_VISIBLE_DEVICES"] = ids
        else:
            out["JAX_PLATFORMS"] = "cpu"
            out["CAP_FLEET_CPU_DEVICES"] = str(len(self.device_ids))
        return out


def single_owner_placement(n_workers: int, n_devices: int,
                           platform: str = "cpu",
                           devices_per_worker: Optional[int] = None,
                           ) -> List[WorkerPlacement]:
    """Partition ``n_devices`` into disjoint contiguous groups, one per
    worker — no device is ever assigned twice (chip sharing between
    processes is the failure mode this model exists to forbid).

    ``devices_per_worker`` defaults to an even split; the placement is
    rejected (:class:`PlacementError`) if it would overcommit.
    """
    if n_workers < 1:
        raise PlacementError(f"need at least one worker, got {n_workers}")
    if devices_per_worker is None:
        devices_per_worker = n_devices // n_workers
    if devices_per_worker < 1:
        raise PlacementError(
            f"{n_workers} workers over {n_devices} devices leaves some "
            "worker with no device (single-owner placement cannot share)")
    if n_workers * devices_per_worker > n_devices:
        raise PlacementError(
            f"{n_workers} workers x {devices_per_worker} devices = "
            f"{n_workers * devices_per_worker} > {n_devices} available: "
            "refusing to double-book a device")
    placements = [
        WorkerPlacement(
            worker_id=w,
            device_ids=tuple(range(w * devices_per_worker,
                                   (w + 1) * devices_per_worker)),
            platform=platform)
        for w in range(n_workers)
    ]
    assert_single_owner(placements)
    return placements


def assert_single_owner(placements: List[WorkerPlacement]) -> None:
    """Raise :class:`PlacementError` if any device has two owners."""
    owner: Dict[int, int] = {}
    for p in placements:
        for d in p.device_ids:
            if d in owner:
                raise PlacementError(
                    f"device {d} owned by both worker {owner[d]} and "
                    f"worker {p.worker_id}")
            owner[d] = p.worker_id

# (id(mesh), id(arr)) → (mesh, arr, replicated). The STRONG refs to the
# keying objects make id-aliasing impossible while an entry lives (a
# rebuilt key table can never be served another table's replicated
# copy), and the LRU bound keeps dropped keysets from pinning device
# buffers forever.
_replicated_cache: "OrderedDict[Tuple[int, int], Any]" = OrderedDict()
# Bounded by approximate BYTES, not entry count: individual tables
# range from a few KB to ~130 MB (12-bit EC windows), so a count bound
# either evicts a live working set or pins GBs of dropped keysets'
# buffers. The bound must comfortably exceed the combined working set
# of the live keysets or every batch silently re-broadcasts its tables
# across the mesh; 1 GiB covers dozens of keysets at default window
# sizes while capping the HBM a rotation churn can pin. Raise via
# CAP_TPU_REPLICATED_CACHE_MB for many live keysets with large (12-bit)
# windows; the `parallel.replicated_evictions` telemetry counter ticking
# steadily under load is the thrash signal to watch.
_REPLICATED_CACHE_MAX_BYTES = int(os.environ.get(
    "CAP_TPU_REPLICATED_CACHE_MB", str(1 << 10))) << 20
_replicated_cache_bytes = 0
# replicated() is called concurrently (serve dispatcher + user threads
# on the same mesh); the byte counter is read-modify-write state, so
# all cache mutations happen under this lock.
_cache_lock = threading.Lock()


def _entry_nbytes(arr) -> int:
    return int(getattr(arr, "nbytes", 0) or 0)


def batch_axis(mesh) -> str:
    """The mesh axis the batch shards over (its first axis)."""
    return mesh.axis_names[0]


def shard_batch(mesh, arr):
    """Place a host array sharded along axis 0 of the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    spec = PartitionSpec(batch_axis(mesh), *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def replicated(mesh, arr):
    """Mesh-replicated copy of a device array, cached per (mesh, array).

    The cache holds strong references to the mesh and source array, so
    entries can never be aliased by id reuse after garbage collection;
    an LRU bounded by approximate bytes evicts replicated buffers of
    dropped keysets without pinning GBs of HBM under keyset rotation.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    global _replicated_cache_bytes
    key = (id(mesh), id(arr))
    with _cache_lock:
        hit = _replicated_cache.get(key)
        if hit is not None:
            _replicated_cache.move_to_end(key)
            return hit[2]
    out = jax.device_put(arr, NamedSharding(mesh, PartitionSpec()))
    with _cache_lock:
        # A concurrent caller may have inserted the same key while we
        # were broadcasting — keep (and return) the first copy so every
        # shard keeps gathering from one buffer.
        hit = _replicated_cache.get(key)
        if hit is not None:
            _replicated_cache.move_to_end(key)
            return hit[2]
        _replicated_cache[key] = (mesh, arr, out)
        _replicated_cache_bytes += _entry_nbytes(arr)
        while (_replicated_cache_bytes > _REPLICATED_CACHE_MAX_BYTES
               and len(_replicated_cache) > 1):
            _, (_, old_arr, _) = _replicated_cache.popitem(last=False)
            _replicated_cache_bytes -= _entry_nbytes(old_arr)
            telemetry.count("parallel.replicated_evictions")
    return out
