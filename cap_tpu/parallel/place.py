"""Mesh placement helpers for the packed verify programs.

The packed dispatch functions (rsa/ec/ed25519 ``verify_*_packed_pending``)
take every device table as an explicit argument, so multi-chip execution
needs exactly two placements (SURVEY.md §2.6 "sharded bignum kernels"):

- the packed record matrix sharded along the batch axis
  (``PartitionSpec(axis, None)``) — token data parallelism over ICI;
- the key/window tables replicated (``PartitionSpec()``) — the key
  gather then runs locally on every shard.

XLA's GSPMD propagation partitions the whole verify program from those
input shardings; the jit-captured RNS context constants replicate
automatically. Validated on the virtual 8-device CPU mesh by
tests/test_parallel.py and the driver's dryrun_multichip.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Tuple

# (id(mesh), id(arr)) → (mesh, arr, replicated). The STRONG refs to the
# keying objects make id-aliasing impossible while an entry lives (a
# rebuilt key table can never be served another table's replicated
# copy), and the LRU bound keeps dropped keysets from pinning device
# buffers forever.
_replicated_cache: "OrderedDict[Tuple[int, int], Any]" = OrderedDict()
# Sized for several live keysets: one meshed TPUBatchKeySet places
# ~6 arrays per RSA size class + 4-5 per EC curve + Ed tables; the
# bound must comfortably exceed the combined working set or every
# batch silently re-broadcasts its tables across the mesh.
_REPLICATED_CACHE_MAX = 512


def batch_axis(mesh) -> str:
    """The mesh axis the batch shards over (its first axis)."""
    return mesh.axis_names[0]


def shard_batch(mesh, arr):
    """Place a host array sharded along axis 0 of the mesh."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    spec = PartitionSpec(batch_axis(mesh), *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def replicated(mesh, arr):
    """Mesh-replicated copy of a device array, cached per (mesh, array).

    The cache holds strong references to the mesh and source array, so
    entries can never be aliased by id reuse after garbage collection;
    a small LRU bound evicts replicated buffers of dropped keysets.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    key = (id(mesh), id(arr))
    hit = _replicated_cache.get(key)
    if hit is not None:
        _replicated_cache.move_to_end(key)
        return hit[2]
    out = jax.device_put(arr, NamedSharding(mesh, PartitionSpec()))
    _replicated_cache[key] = (mesh, arr, out)
    while len(_replicated_cache) > _REPLICATED_CACHE_MAX:
        _replicated_cache.popitem(last=False)
    return out
