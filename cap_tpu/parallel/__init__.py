"""Multi-chip execution: device meshes + sharded batch verification.

The reference has no distributed execution of any kind (SURVEY.md §2.6);
this package is the TPU-native fill-in. The parallelism axes for a
batched-verify workload:

- ``dp`` — data parallelism over the token batch: each chip verifies a
  shard of the tokens. The analog of DP in an ML framework; tokens are
  independent, so this scales linearly over ICI with zero cross-chip
  traffic in the hot loop.
- key-gather — the EP-analog (SURVEY.md §2.6): per-token kid indices
  gather rows from the key table. Tables are small (a JWKS is ~16
  keys), so they are replicated per chip and the gather stays local;
  the collective cost is one broadcast at table-build time.

Verdict reduction (count of valid tokens) rides a ``psum`` over ``dp``.
"""

from .mesh import (  # noqa: F401
    make_mesh,
    sharded_rs256_verify,
    sharded_verify_step,
)
