"""Device mesh construction and shard_map'd verification steps.

Everything here is shape-static: the sharded batch axis must be a
multiple of the mesh size (callers pad — TPUBatchKeySet already pads
buckets to power-of-two sizes, so any power-of-two mesh divides them).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..tpu import bignum

DP_AXIS = "dp"


def make_mesh(n_devices: Optional[int] = None, axis: str = DP_AXIS) -> Mesh:
    """1-D device mesh over the first ``n_devices`` local devices.

    The batch ("dp") axis is the only sharded axis of this workload;
    the key table is replicated (see package docstring).
    """
    devs = jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.asarray(devs), (axis,))


def _rs256_core(s, n, nprime, r2, expected):
    """Per-shard RS* verify core: modexp + EM compare + range check.

    All inputs are [K, Nl] limb-first arrays for the local shard of the
    batch. Returns ([Nl] bool verdicts, [] global valid count).
    """
    em = bignum.modexp_65537(s, n, nprime, r2)
    eq = jnp.all(em == expected, axis=0)
    in_range = ~bignum.compare_ge(s, n)
    ok = eq & in_range
    total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), DP_AXIS)
    return ok, total


def sharded_rs256_verify(mesh: Mesh):
    """Build the jitted multi-chip RS256 verify step for ``mesh``.

    Returns fn(s, n, nprime, r2, expected) -> (ok[N] bool, total int32)
    with every [K, N] operand sharded over the batch axis. The key
    gather (table row → per-token operand) happens before this step, on
    the host or in a preceding sharded gather; here each chip receives
    its token shard's operands directly.
    """
    spec = P(None, DP_AXIS)
    fn = jax.shard_map(
        _rs256_core,
        mesh=mesh,
        in_specs=(spec, spec, spec, spec, spec),
        out_specs=(P(DP_AXIS), P()),
        # zeros-initialized scan carries inside bignum.mul are unvarying
        # on entry, varying on exit — the vma check rejects that even
        # though the program is correct; disable it.
        check_vma=False,
    )
    return jax.jit(fn)


def _gather_core(tabs, idx):
    """Replicated-table gather: [nk, K] tables + local [Nl] rows → [K, Nl]."""
    return tuple(t[idx].T for t in tabs)


def sharded_verify_step(mesh: Mesh):
    """The FULL multi-chip batch-verify step: key gather + modexp + check.

    fn(n_tab, np_tab, r2_tab, key_idx, s, expected) where the [nk, K]
    tables are replicated across the mesh, and key_idx [N] / s [K, N] /
    expected [K, N] are sharded over ``dp``. This is the step
    ``dryrun_multichip`` compiles: it exercises the key-gather (EP
    analog) and batch-DP shardings together with the psum reduction.
    """
    tab_spec = P(None, None)
    limb_spec = P(None, DP_AXIS)

    def step(n_tab, np_tab, r2_tab, key_idx, s, expected):
        n, nprime, r2 = _gather_core((n_tab, np_tab, r2_tab), key_idx)
        return _rs256_core(s, n, nprime, r2, expected)

    fn = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=(tab_spec, tab_spec, tab_spec, P(DP_AXIS), limb_spec,
                  limb_spec),
        out_specs=(P(DP_AXIS), P()),
        check_vma=False,  # see sharded_rs256_verify
    )
    return jax.jit(fn)


def sharded_rns_verify_step(mesh: Mesh, ctx):
    """Multi-chip RS256 verify on the RNS/MXU engine.

    fn(s_limbs, expected, sig_c, n_B, a2_A, a2_B) → (ok[N], total):
    every operand is [·, N] sharded over the batch axis; the RNS
    context's fixed extension/conversion matrices are compile-time
    constants replicated to every chip. The data-parallel analog of
    the limb step in ``sharded_verify_step``, on the engine the
    benchmark actually uses.
    """
    from ..tpu import rns

    limb_spec = P(None, DP_AXIS)

    def core(s_limbs, expected, sig_c, n_B, a2_A, a2_B):
        ok = rns._rns_verify_core(ctx, s_limbs, expected, sig_c, n_B,
                                  a2_A, a2_B)
        total = jax.lax.psum(jnp.sum(ok.astype(jnp.int32)), DP_AXIS)
        return ok, total

    fn = jax.shard_map(
        core,
        mesh=mesh,
        in_specs=(limb_spec,) * 6,
        out_specs=(P(DP_AXIS), P()),
        check_vma=False,  # see sharded_rs256_verify
    )
    return jax.jit(fn)


def shard_batch_arrays(mesh: Mesh, *arrays):
    """Place [.., N]-batch arrays with their natural sharding on ``mesh``.

    Arrays with ndim == 1 shard over dp on axis 0; ndim == 2 ([K, N])
    shard over dp on axis 1. Returns device arrays.
    """
    out = []
    for a in arrays:
        spec = P(DP_AXIS) if a.ndim == 1 else P(None, DP_AXIS)
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)
