"""Provider: the OIDC relying-party engine.

Parity with oidc/provider.go:33-655. Differences from the reference are
architectural, not behavioral:

- the reference delegates discovery/JWKS/signature work to coreos
  go-oidc; here those are in-tree (cap_tpu.jwt), so there is no
  ``convertError`` substring mapping — the taxonomy errors are raised
  directly by our own stack;
- the Provider accepts an injected KeySet. Passing a
  ``TPUBatchKeySet`` routes ``verify_id_token`` —and the batched
  ``verify_id_token_batch``— through the accelerated device path
  (the north star's shared accelerated verify seam).
"""

from __future__ import annotations

import json
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Tuple
from urllib.parse import urlencode, urlparse, urlunparse

from ..errors import (
    ExpiredAuthTimeError,
    ExpiredTokenError,
    InvalidAudienceError,
    InvalidAuthorizedPartyError,
    InvalidFlowError,
    InvalidIssuedAtError,
    InvalidIssuerError,
    InvalidNonceError,
    InvalidNotBeforeError,
    InvalidParameterError,
    InvalidSignatureError,
    InvalidSubjectError,
    MissingClaimError,
    MissingIDTokenError,
    NilParameterError,
    UnauthorizedRedirectURIError,
    UnsupportedAlgError,
    UserInfoFailedError,
)
from .. import telemetry as _telemetry
from ..jwt.jose import is_json_form, peek_alg
from ..jwt.keyset import JSONWebKeySet, KeySet
from ..utils import http as _http
from ..utils.strutils import remove_duplicates_stable, str_list_contains
from .config import SCOPE_OPENID, Config
from .id_token import IDToken
from .prompt import NONE as PROMPT_NONE
from .request import Request
from .token import Token

_VERIFY_LEEWAY = 60.0  # 1-minute leeway on iat/nbf (provider.go:438)


class Provider:
    """An OIDC relying party bound to one issuer.

    Performs discovery at construction (network). ``done()`` releases
    resources (parity with Provider.Done(); our HTTP layer is
    connectionless so this only drops the keyset cache).
    """

    def __init__(self, config: Config, keyset: Optional[KeySet] = None,
                 discovery_doc: Optional[Dict[str, Any]] = None):
        if config is None:
            raise NilParameterError("provider config is nil")
        config.validate()
        self.config = config
        self._ssl_ctx = _http.ssl_context_for_ca(config.provider_ca or None)
        # alg by compact header segment: tokens from one IdP share the
        # exact header bytes, and peek_alg's per-token re-parse was the
        # binding term of the batched id_token path (docs/PERF.md r5).
        self._alg_cache: Dict[str, str] = {}
        # (allowed?, alg) by header segment — the native claims
        # engine's per-token alg_ok input (bounded like _alg_cache;
        # exact: supported_signing_algs is fixed per Provider)
        self._alg_ok_cache: Dict[str, tuple] = {}

        if discovery_doc is None:
            discovery_doc = _http.fetch_discovery(config.issuer, self._ssl_ctx)
        if discovery_doc.get("issuer") != config.issuer:
            raise InvalidIssuerError(
                f"oidc issuer did not match the issuer returned by provider, "
                f"expected {config.issuer!r} got {discovery_doc.get('issuer')!r}"
            )
        self._discovery = discovery_doc
        self.authorization_endpoint = discovery_doc.get(
            "authorization_endpoint", "")
        self.token_endpoint = discovery_doc.get("token_endpoint", "")
        self.userinfo_endpoint = discovery_doc.get("userinfo_endpoint", "")
        self.jwks_uri = discovery_doc.get("jwks_uri", "")

        if keyset is not None:
            self._keyset = keyset
        else:
            if not self.jwks_uri:
                raise InvalidIssuerError("discovery document missing jwks_uri")
            self._keyset = JSONWebKeySet(
                self.jwks_uri, jwks_ca_pem=config.provider_ca or None)

    def done(self) -> None:
        """Release provider resources (provider.go:96-116 analog)."""
        self._keyset = None  # type: ignore[assignment]

    @property
    def keyset(self) -> KeySet:
        return self._keyset

    # -- AuthURL -----------------------------------------------------------

    def auth_url(self, request: Request) -> str:
        """Build the IdP authorize URL (provider.go:123-208)."""
        if request is None:
            raise NilParameterError("request is nil")
        if not request.state():
            raise InvalidParameterError("request id is empty")
        if not request.nonce():
            raise InvalidParameterError("request nonce is empty")
        if request.state() == request.nonce():
            raise InvalidParameterError(
                "request id and nonce cannot be equal")
        with_implicit, with_implicit_at = request.implicit_flow()
        if request.pkce_verifier() is not None and with_implicit:
            raise InvalidParameterError(
                "request requests both implicit flow and authorization "
                "code with PKCE")
        if not request.redirect_url():
            raise InvalidParameterError("request redirect URL is empty")
        self.valid_redirect(request.redirect_url())

        scopes = request.scopes() or list(self.config.scopes)
        if not str_list_contains(scopes, SCOPE_OPENID):
            scopes = [SCOPE_OPENID] + scopes

        params: List[Tuple[str, str]] = [
            ("response_type", "code"),
            ("client_id", self.config.client_id),
            ("redirect_uri", request.redirect_url()),
            ("scope", " ".join(scopes)),
            ("state", request.state()),
            ("nonce", request.nonce()),
        ]
        if with_implicit:
            req_tokens = ["id_token"] + (["token"] if with_implicit_at else [])
            params = [(k, v) for k, v in params if k != "response_type"]
            params += [
                ("response_type", " ".join(req_tokens)),
                ("response_mode", "form_post"),
            ]
        verifier = request.pkce_verifier()
        if verifier is not None:
            params += [
                ("code_challenge", verifier.challenge()),
                ("code_challenge_method", verifier.method()),
            ]
        max_age, auth_after = request.max_age()
        if auth_after:
            params.append(("max_age", str(int(max_age))))
        if request.prompts():
            prompts = remove_duplicates_stable(
                [str(p) for p in request.prompts()], case_sensitive=False)
            if str_list_contains(prompts, str(PROMPT_NONE)) and len(prompts) > 1:
                raise InvalidParameterError(
                    f'prompts ({prompts}) includes "none" with other values')
            params.append(("prompt", " ".join(prompts)))
        if request.display():
            params.append(("display", str(request.display())))
        if request.ui_locales():
            params.append(("ui_locales", " ".join(request.ui_locales())))
        if request.claims():
            params.append(("claims", request.claims().decode("utf-8")))
        if request.acr_values():
            params.append(("acr_values", " ".join(request.acr_values())))

        sep = "&" if "?" in self.authorization_endpoint else "?"
        return self.authorization_endpoint + sep + urlencode(params)

    # -- Exchange ----------------------------------------------------------

    def exchange(self, request: Request, authorization_state: str,
                 authorization_code: str) -> Token:
        """Auth code → verified Token (provider.go:230-310)."""
        if request is None:
            raise NilParameterError("request is nil")
        with_implicit, _ = request.implicit_flow()
        if with_implicit:
            raise InvalidFlowError(
                f"request ({request.state()}) should not be using the "
                f"implicit flow")
        if request.state() != authorization_state:
            raise InvalidParameterError(
                "authentication request state and authorization state "
                "are not equal")
        if not request.redirect_url():
            raise InvalidParameterError(
                "authentication request redirect URL is empty")
        self.valid_redirect(request.redirect_url())
        if request.is_expired():
            raise InvalidParameterError(
                "authentication request is expired")

        fields = {
            "grant_type": "authorization_code",
            "code": authorization_code,
            "redirect_uri": request.redirect_url(),
            "client_id": self.config.client_id,
        }
        secret = self.config.client_secret.reveal()
        if secret:
            fields["client_secret"] = secret
        verifier = request.pkce_verifier()
        if verifier is not None:
            fields["code_verifier"] = verifier.verifier()
        status, body, _ = _http.post_form(
            self.token_endpoint, fields, self._ssl_ctx)
        if status != 200:
            raise InvalidParameterError(
                f"unable to exchange auth code with provider: "
                f"status {status}: {body[:200]!r}")
        try:
            payload = json.loads(body)
        except ValueError as e:
            raise InvalidParameterError(
                f"token endpoint returned invalid JSON: {e}") from e

        raw_id_token = payload.get("id_token")
        if not isinstance(raw_id_token, str) or not raw_id_token:
            raise MissingIDTokenError(
                "id_token is missing from auth code exchange")
        expires_in = payload.get("expires_in")
        expiry = 0.0
        if isinstance(expires_in, (int, float)) and expires_in:
            expiry = self.config.now() + float(expires_in)
        token = Token(
            IDToken(raw_id_token),
            access_token=payload.get("access_token", "") or "",
            refresh_token=payload.get("refresh_token", "") or "",
            expiry=expiry,
            now_func=self.config.now_func,
        )
        claims = self.verify_id_token(token.id_token(), request)
        if token.access_token().reveal():
            token.id_token().verify_access_token(token.access_token())
        c_hash = claims.get("c_hash")
        if isinstance(c_hash, str) and c_hash:
            token.id_token().verify_authorization_code(authorization_code)
        return token

    # -- VerifyIDToken -----------------------------------------------------

    def verify_id_token(self, id_token: IDToken | str,
                        request: Request) -> Dict[str, Any]:
        """Full id_token verification (provider.go:418-511).

        Signature + iss + exp/nbf via the KeySet/claims engine, then
        nonce, iat (1-minute leeway), audience (request override →
        config default), multi-aud must contain client_id, the three azp
        rules, and auth_time against a requested max_age.
        """
        t = id_token if isinstance(id_token, IDToken) else IDToken(id_token)
        if not t.reveal():
            raise InvalidParameterError("id_token is empty")
        if not request.nonce():
            raise InvalidParameterError("nonce is empty")
        claims = self._verify_signature_and_times(t.reveal())
        return self._validate_id_claims(claims, t.reveal(), request)

    def verify_id_token_batch(self, id_tokens: Sequence[str],
                              request: Request,
                              raw: bool = False) -> List[Any]:
        """Batched verify_id_token: one device dispatch for signatures
        (when the injected keyset is a TPUBatchKeySet), then per-token
        claim validation. Returns claims dict or exception per token.

        ``raw=True`` (the serve-style zero-rematerialization mode):
        accepted tokens yield their signed payload BYTES — already the
        claims JSON — instead of parsed dicts, and validation reads a
        native registered-claims SUBSET (iss/sub/aud/exp/nbf/iat/
        nonce/azp/auth_time) off the phase-1 tape, so no full claims
        dict is ever built. Verdicts are identical to the dict path:
        the validator only reads registered claims, and every parse
        corner falls back to the full json.loads dict. Requires a
        keyset with ``verify_batch_raw`` (the TPU keysets).
        """
        raws = [t.reveal() if isinstance(t, IDToken) else str(t)
                for t in id_tokens]
        if raw:
            if not hasattr(self._keyset, "verify_batch_raw"):
                raise InvalidParameterError(
                    "raw id_token batch mode needs a keyset with "
                    "verify_batch_raw (TPUBatchKeySet/TPURemoteKeySet)")
            results = self._keyset.verify_batch_raw(raws)
            out = [None] * len(raws)
            acc: List[int] = []
            for i, res in enumerate(results):
                if isinstance(res, Exception):
                    # same wrapping as the single-token path so callers
                    # see one taxonomy regardless of which API they used
                    out[i] = res if isinstance(res, InvalidSignatureError) \
                        else InvalidSignatureError(
                            f"failed to verify id token signature: {res}")
                else:
                    acc.append(i)
            self._validate_accepted_raw(acc, raws, results, request, out)
            return out
        results = self._keyset.verify_batch(raws)
        out = []
        for raw_tok, res in zip(raws, results):
            if isinstance(res, Exception):
                if isinstance(res, InvalidSignatureError):
                    out.append(res)
                else:
                    out.append(InvalidSignatureError(
                        f"failed to verify id token signature: {res}"))
                continue
            try:
                self._check_times(res)
                self._validate_id_claims(res, raw_tok, request)
                out.append(res)
            except Exception as e:  # noqa: BLE001 - per-token error channel
                out.append(e)
        return out

    def _validate_accepted_raw(self, acc: List[int], raws: Sequence[str],
                               results: Sequence[Any], request: Request,
                               out: List[Any]) -> None:
        """Claims validation for the raw batch's signature-ACCEPTED
        tokens, filling ``out`` in place (payload bytes or exception).

        One native batched rules call (claims_validate.cpp) replaces
        the per-token Python loop — including ``_check_times`` — when
        the engine is live; per-token ``fallback`` statuses and an
        unavailable/disabled engine (``CAP_OIDC_NATIVE=0``, stale
        ``.so``, layout drift) take the existing Python rule path over
        the registered-claims tape subset, so verdicts cannot diverge
        (``oidc.native_fallbacks`` makes every such token visible).
        """
        from . import claims_native

        with _telemetry.span(_telemetry.SPAN_OIDC_VALIDATE):
            statuses = None
            alg_ok = None
            algs: List[Any] = []
            if acc and claims_native.enabled():
                import numpy as _np

                # Per-token allowed-alg verdicts off the header-
                # segment cache: one (ok, alg) entry per DISTINCT
                # compact header (an IdP has a handful), so the loop
                # is a partition + dict hit per token. JSON-form
                # tokens (no stable prefix) and parse surprises route
                # through _alg_of / the Python arm per token.
                alg_ok = _np.zeros(len(acc), _np.uint8)
                algs = [None] * len(acc)
                forced_fb = []
                supported = self.config.supported_signing_algs
                cache = self._alg_ok_cache
                for j, i in enumerate(acc):
                    t = raws[i]
                    seg = t.partition(".")[0] if t[:1] != "{" else None
                    hit = cache.get(seg) if seg is not None else None
                    if hit is None:
                        try:
                            a = self._alg_of(t)
                        except Exception:  # noqa: BLE001 - Python arm
                            forced_fb.append(j)
                            continue
                        hit = (1 if a in supported else 0, a)
                        if seg is not None:
                            if len(cache) >= 1024:
                                cache.clear()
                            cache[seg] = hit
                    alg_ok[j] = hit[0]
                    algs[j] = hit[1]
                try:
                    statuses = claims_native.validate_payloads(
                        [results[i] for i in acc], alg_ok,
                        self.config.now(), self._policy_blob(request))
                except Exception:  # noqa: BLE001 - degrade, never fail
                    # e.g. a policy the blob can't express (non-string
                    # audiences) — the Python rules remain authoritative
                    statuses = None
                if statuses is not None:
                    for j in forced_fb:
                        statuses[j] = claims_native.STATUS_FALLBACK
            if statuses is None:
                # whole-batch Python path (engine off or refused)
                claims_native.count_fallbacks(len(acc))
                self._python_validate_raw(acc, raws, results, request,
                                          out)
                return
            if not statuses.any():
                # all-accept fast path: the common serve batch — no
                # per-token branching, one count
                for i in acc:
                    out[i] = results[i]
                claims_native.count_validated(len(acc))
                return
            fb: List[int] = []
            now = self.config.now()
            client = self.config.client_id
            for j, i in enumerate(acc):
                st = int(statuses[j])
                if st == claims_native.STATUS_OK:
                    out[i] = results[i]
                elif st == claims_native.STATUS_FALLBACK:
                    fb.append(i)
                else:
                    out[i] = claims_native.status_error(
                        st, alg=algs[j], client_id=client, now=now)
            claims_native.count_validated(len(acc) - len(fb))
            claims_native.count_fallbacks(len(fb))
            if fb:
                self._python_validate_raw(fb, raws, results, request,
                                          out)

    def _python_validate_raw(self, idx: List[int], raws: Sequence[str],
                             results: Sequence[Any], request: Request,
                             out: List[Any]) -> None:
        """The Python rule path for raw-mode tokens: registered-claims
        subset off the native tape (json.loads on its conservative
        fallbacks), then the shared ``_check_times`` +
        ``_validate_id_claims`` rules per token."""
        if not idx:
            return
        from ..runtime.native_binding import (
            registered_claims_from_payloads,
        )

        claims_sub = registered_claims_from_payloads(
            [results[i] for i in idx])
        for i, claims in zip(idx, claims_sub):
            try:
                if isinstance(claims, Exception):
                    raise claims
                self._check_times(claims)
                self._validate_id_claims(claims, raws[i], request)
                out[i] = results[i]
            except Exception as e:  # noqa: BLE001 - per-token channel
                out[i] = e

    def _policy_blob(self, request: Request) -> bytes:
        """The native engine's per-batch policy (compiled once per
        call: issuer/client/nonce/audiences/leeway + the max_age
        rare-flag bit that keeps auth_time on the Python path)."""
        from . import claims_native

        _, auth_after = request.max_age()
        return claims_native.pack_policy(
            self.config.issuer, self.config.client_id, request.nonce(),
            request.audiences() or list(self.config.audiences),
            _VERIFY_LEEWAY, bool(auth_after))

    def _verify_signature_and_times(self, raw: str) -> Dict[str, Any]:
        try:
            claims = self._keyset.verify_signature(raw)
        except InvalidSignatureError:
            raise
        except Exception as e:  # noqa: BLE001
            raise InvalidSignatureError(
                f"failed to verify id token signature: {e}") from e
        self._check_times(claims)
        return claims

    def _alg_of(self, raw: str) -> str:
        """peek_alg with a header-segment cache.

        alg is a pure function of the compact header segment, and the
        token already parsed successfully upstream, so caching by that
        segment is exact; JSON-form tokens (no stable prefix) always
        take the full peek. The cache is bounded — a rotating IdP has
        a handful of distinct headers, an attacker spraying unique
        headers just evicts.
        """
        if is_json_form(raw):           # no stable prefix to key on
            return peek_alg(raw)
        seg, _, rest = raw.partition(".")
        if not rest:
            return peek_alg(raw)
        alg = self._alg_cache.get(seg)
        if alg is None:
            alg = peek_alg(raw)
            if len(self._alg_cache) >= 1024:
                self._alg_cache.clear()
            self._alg_cache[seg] = alg
        return alg

    def _check_times(self, claims: Dict[str, Any]) -> None:
        now = self.config.now()
        exp = claims.get("exp")
        if not isinstance(exp, (int, float)):
            raise MissingClaimError("id_token missing exp claim")
        if now > float(exp):
            raise ExpiredTokenError("token is expired")
        nbf = claims.get("nbf")
        if isinstance(nbf, (int, float)) and now + _VERIFY_LEEWAY < float(nbf):
            raise InvalidNotBeforeError(
                "current time before the nbf (not before) time")

    def _validate_id_claims(self, claims: Dict[str, Any], raw: str,
                            request: Request) -> Dict[str, Any]:
        # issuer (coreos verifier checks this from the discovery doc)
        iss = claims.get("iss")
        if iss != self.config.issuer:
            raise InvalidIssuerError(
                "id token issued by a different provider")
        # signing alg must be in the configured supported list
        alg = self._alg_of(raw)
        if alg not in self.config.supported_signing_algs:
            raise UnsupportedAlgError(
                f"id_token signed with unsupported algorithm {alg!r}")
        if claims.get("nonce") != request.nonce():
            raise InvalidNonceError("invalid id_token nonce")
        now = self.config.now()
        iat = claims.get("iat")
        if isinstance(iat, (int, float)) and now + _VERIFY_LEEWAY < float(iat):
            raise InvalidIssuedAtError(
                f"current time {now} before the iat (issued at) time {iat}")

        aud_claim = claims.get("aud")
        if isinstance(aud_claim, str):
            aud_list = [aud_claim]
        elif isinstance(aud_claim, list):
            # go-jose/go-oidc parity: an aud ARRAY may only hold
            # strings. Non-string entries used to be silently dropped,
            # so ["client", 42] validated as a single-audience token —
            # now they reject (pinned on both rule engines by the
            # differential suite).
            if any(not isinstance(a, str) for a in aud_claim):
                raise InvalidAudienceError(
                    "aud claim contains a non-string value")
            aud_list = list(aud_claim)
        else:
            aud_list = []
        audiences = request.audiences() or list(self.config.audiences)
        if audiences:
            if not any(str_list_contains(aud_list, a) for a in audiences):
                raise InvalidAudienceError("invalid id_token audiences")
        if len(aud_list) > 1 and not str_list_contains(
                aud_list, self.config.client_id):
            raise InvalidAudienceError(
                f"multiple audiences ({aud_list}) and one of them is not "
                f"equal client_id ({self.config.client_id})")

        azp = claims.get("azp")
        client = self.config.client_id
        if azp is not None and azp != client:
            raise InvalidAuthorizedPartyError(
                f"authorized party ({azp}) is not equal client_id ({client})")
        if len(aud_list) > 1 and azp != client:
            raise InvalidAuthorizedPartyError(
                f"multiple audiences and authorized party ({azp}) is not "
                f"equal client_id ({client})")
        if (len(aud_list) == 1 and aud_list[0] != client) and azp != client:
            raise InvalidAuthorizedPartyError(
                f"one audience ({aud_list[0]}) which is not the client_id "
                f"({client}) and authorized party ({azp}) is not equal "
                f"client_id ({client})")

        max_age, auth_after = request.max_age()
        if auth_after:
            at_claim = claims.get("auth_time")
            if not isinstance(at_claim, (int, float)):
                raise MissingClaimError(
                    "missing auth_time claim when max age was requested")
            if not (float(at_claim) + _VERIFY_LEEWAY > auth_after):
                raise ExpiredAuthTimeError(
                    f"auth_time ({at_claim}) is beyond max age ({max_age})")
        return claims

    # -- UserInfo ----------------------------------------------------------

    def userinfo(self, token_source, valid_sub: str,
                 audiences: Optional[Sequence[str]] = None) -> Dict[str, Any]:
        """Fetch and validate userinfo claims (provider.go:324-396)."""
        if token_source is None:
            raise NilParameterError("token source is nil")
        if not self.userinfo_endpoint:
            raise UserInfoFailedError(
                "provider does not advertise a userinfo endpoint")
        access = token_source.token()
        raw = access.reveal() if hasattr(access, "reveal") else str(access)
        status, body, _ = _http.get(
            self.userinfo_endpoint, self._ssl_ctx,
            headers={"Authorization": f"Bearer {raw}"})
        if status != 200:
            raise UserInfoFailedError(
                f"userinfo request failed: status {status}")
        try:
            claims = json.loads(body)
        except ValueError as e:
            raise UserInfoFailedError(
                f"userinfo returned invalid JSON: {e}") from e
        if not isinstance(claims, dict):
            raise UserInfoFailedError("userinfo claims are not an object")
        sub = claims.get("sub")
        if not sub:
            raise MissingClaimError("userinfo response missing sub claim")
        if sub != valid_sub:
            raise InvalidSubjectError(
                "sub from userinfo does not match the expected sub")
        iss = claims.get("iss")
        if iss is not None and iss != self.config.issuer:
            raise InvalidIssuerError(
                "iss from userinfo does not match the provider issuer")
        if audiences:
            aud = claims.get("aud")
            aud_list = [aud] if isinstance(aud, str) else (
                aud if isinstance(aud, list) else [])
            if not any(a in aud_list for a in audiences):
                raise InvalidAudienceError("invalid userinfo audiences")
        return claims

    # -- redirect validation (RFC 8252 §7.3, provider.go:622-655) ----------

    def valid_redirect(self, uri: str) -> None:
        allowed = self.config.allowed_redirect_urls
        if not allowed:
            return
        try:
            parsed = urlparse(uri)
        except ValueError as e:
            raise InvalidParameterError(
                f"redirect URI {uri} is an invalid URI: {e}") from e

        loopbacks = ("localhost", "127.0.0.1", "::1")
        if parsed.hostname not in loopbacks:
            if uri in allowed:
                return
            raise UnauthorizedRedirectURIError(f"redirect URI {uri}")

        # loopback: port-agnostic comparison
        stripped = _strip_port(parsed)
        for a in allowed:
            try:
                allowed_parsed = urlparse(a)
            except ValueError as e:
                raise InvalidParameterError(
                    f"allowed redirect URI {a} is an invalid URI: {e}"
                ) from e
            if stripped == _strip_port(allowed_parsed):
                return
        raise UnauthorizedRedirectURIError(f"redirect URI {uri}")


def _strip_port(parsed) -> str:
    host = parsed.hostname or ""
    if ":" in host:  # IPv6 literal
        host = f"[{host}]"
    return urlunparse(parsed._replace(netloc=host))
