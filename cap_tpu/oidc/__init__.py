"""OIDC relying-party core.

Capability parity with the reference's ``oidc/`` package: Config,
Provider (discovery, AuthURL, Exchange, VerifyIDToken, UserInfo),
Request, Token, IDToken with at_hash/c_hash verification, PKCE S256,
state/nonce generation, prompts/displays, and the redact-by-default
secret types — plus the TPU-era addition: the Provider can be handed an
accelerated KeySet (TPUBatchKeySet) so id_token verification shares the
batched device path (``verify_id_token_batch``).
"""

from .config import ClientSecret, Config
from .display import Display
from .id import DEFAULT_ID_LENGTH, new_id
from .id_token import IDToken
from .pkce import CodeVerifier, S256Verifier, create_code_challenge
from .prompt import Prompt
from .provider import Provider
from .request import REQUEST_EXPIRY_SKEW, Request
from .serve_keyset import OIDCRawKeySet
from .token import TOKEN_EXPIRY_SKEW, AccessToken, RefreshToken, Token

__all__ = [
    "ClientSecret", "Config", "Display", "DEFAULT_ID_LENGTH", "new_id",
    "IDToken", "CodeVerifier", "S256Verifier", "create_code_challenge",
    "Prompt", "Provider", "OIDCRawKeySet", "REQUEST_EXPIRY_SKEW",
    "Request", "TOKEN_EXPIRY_SKEW", "AccessToken", "RefreshToken",
    "Token",
]
