"""Relying-party configuration.

Parity with oidc/config.go:35-239: required client_id + issuer
(http/https scheme), supported signing algs validated against the
registry, optional allowed redirect URLs / scopes ("openid" always
ensured at use sites) / audiences / provider CA / now function.
ClientSecret redacts itself everywhere (config.go:17-31).
"""

from __future__ import annotations

import time as _time
from typing import Callable, List, Optional, Sequence
from urllib.parse import urlparse

from ..errors import (
    InvalidCACertError,
    InvalidIssuerError,
    InvalidParameterError,
)
from ..jwt import algs as _algs
from ..utils.redact import RedactedString

SCOPE_OPENID = "openid"


class ClientSecret(RedactedString):
    redact_label = "ClientSecret"


class Config:
    """Provider (relying party) configuration."""

    def __init__(
        self,
        issuer: str,
        client_id: str,
        client_secret: str | ClientSecret = "",
        supported_signing_algs: Sequence[str] = (),
        allowed_redirect_urls: Sequence[str] = (),
        *,
        scopes: Optional[Sequence[str]] = None,
        audiences: Optional[Sequence[str]] = None,
        provider_ca: Optional[str] = None,
        now_func: Optional[Callable[[], float]] = None,
    ):
        self.issuer = issuer
        self.client_id = client_id
        self.client_secret = (
            client_secret if isinstance(client_secret, ClientSecret)
            else ClientSecret(client_secret)
        )
        self.supported_signing_algs = list(supported_signing_algs)
        self.allowed_redirect_urls = list(allowed_redirect_urls)
        self.scopes = list(scopes) if scopes else []
        self.audiences = list(audiences) if audiences else []
        self.provider_ca = provider_ca or ""
        self.now_func = now_func
        self.validate()

    def now(self) -> float:
        """Current Unix time, honoring now_func (config.go:233-239)."""
        return self.now_func() if self.now_func is not None else _time.time()

    def validate(self) -> None:
        if not self.client_id:
            raise InvalidParameterError("client ID is empty")
        if not self.issuer:
            raise InvalidParameterError("discovery URL is empty")
        for u in self.allowed_redirect_urls:
            try:
                urlparse(u)
            except ValueError as e:
                raise InvalidParameterError(
                    f"invalid AllowedRedirectURLs provided {u}: {e}"
                ) from e
        try:
            parsed = urlparse(self.issuer)
        except ValueError as e:
            raise InvalidIssuerError(f"issuer {self.issuer} is invalid: {e}") from e
        if parsed.scheme not in ("http", "https"):
            raise InvalidIssuerError(
                f"issuer {self.issuer} schema is not http or https"
            )
        if not self.supported_signing_algs:
            raise InvalidParameterError("supported algorithms is empty")
        for a in self.supported_signing_algs:
            if a not in _algs.SUPPORTED_ALGORITHMS:
                raise InvalidParameterError(f"unsupported algorithm {a}")
        if self.provider_ca:
            from ..utils.http import ssl_context_for_ca

            try:
                ssl_context_for_ca(self.provider_ca)
            except InvalidCACertError:
                raise
            except Exception as e:  # noqa: BLE001
                raise InvalidCACertError(str(e)) from e


def encode_certificates(*certs) -> str:
    """PEM-encode x509 certificates (config.go EncodeCertificates analog)."""
    from cryptography.hazmat.primitives.serialization import Encoding

    if not certs or any(c is None for c in certs):
        raise InvalidParameterError("no certificates provided")
    return "".join(
        c.public_bytes(Encoding.PEM).decode("utf-8") for c in certs
    )
