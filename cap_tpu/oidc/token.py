"""Token: id_token plus optional OAuth2 access/refresh tokens.

Parity with oidc/token.go:15-184 (Tk): redacting access/refresh types,
10-second expiry skew on validity checks, a static token source for
UserInfo, and zero-expiry meaning "does not expire".
"""

from __future__ import annotations

import time as _time
from typing import Callable, Optional

from ..errors import InvalidParameterError
from ..utils.redact import RedactedString
from .id_token import IDToken

TOKEN_EXPIRY_SKEW = 10.0  # seconds


class AccessToken(RedactedString):
    redact_label = "access_token"


class RefreshToken(RedactedString):
    redact_label = "refresh_token"


class Token:
    """One authentication's tokens. id_token required; the rest optional."""

    def __init__(self, id_token: IDToken | str,
                 access_token: str = "", refresh_token: str = "",
                 expiry: float = 0.0,
                 now_func: Optional[Callable[[], float]] = None):
        self._id_token = (id_token if isinstance(id_token, IDToken)
                          else IDToken(id_token))
        if not self._id_token.reveal():
            raise InvalidParameterError("id_token is empty")
        self._access_token = AccessToken(access_token or "")
        self._refresh_token = RefreshToken(refresh_token or "")
        self._expiry = float(expiry or 0.0)
        self._now_func = now_func

    # -- accessors ---------------------------------------------------------

    def id_token(self) -> IDToken:
        return self._id_token

    def access_token(self) -> AccessToken:
        return self._access_token

    def refresh_token(self) -> RefreshToken:
        return self._refresh_token

    def expiry(self) -> float:
        """Unix seconds; 0 means no known expiry."""
        return self._expiry

    # -- state -------------------------------------------------------------

    def _now(self) -> float:
        return self._now_func() if self._now_func is not None else _time.time()

    def is_expired(self) -> bool:
        """True if the access token is expired (or absent)."""
        if not self._access_token.reveal():
            return True
        if self._expiry == 0:
            return False
        return self._expiry < self._now() + TOKEN_EXPIRY_SKEW

    def valid(self) -> bool:
        """True if there is an unexpired access token."""
        if not self._access_token.reveal():
            return False
        return not self.is_expired()

    def static_token_source(self):
        """A token source that always returns this token's access token
        (for UserInfo); None when there is no access token."""
        if not self._access_token.reveal():
            return None
        token = self._access_token

        class _Static:
            def token(self) -> AccessToken:
                return token

        return _Static()

    def __repr__(self) -> str:
        return (f"Token(id_token={self._id_token!r}, "
                f"access_token={self._access_token!r}, "
                f"refresh_token={self._refresh_token!r})")
