"""IDToken: a redacting string with unverified claims access and
at_hash / c_hash verification.

Parity with oidc/id_token.go:16-145: ``claims()`` decodes the payload
without verification (signature verification is the Provider's job);
``verify_access_token`` / ``verify_authorization_code`` implement the
OIDC left-half-hash checks, selecting SHA-256/384/512 by the signing
alg's suffix. EdDSA tokens are unverifiable this way → returns False
without error, exactly like the reference (id_token.go:92-145).
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional

from ..errors import (
    InvalidAtHashError,
    InvalidCodeHashError,
    InvalidParameterError,
    MalformedTokenError,
    UnsupportedAlgError,
)
from ..jwt import algs as _algs
from ..jwt.jose import b64url_decode, b64url_encode, parse_jws
from ..utils.redact import RedactedString

_HASH_BY_SUFFIX = {"256": "sha256", "384": "sha384", "512": "sha512"}


class IDToken(RedactedString):
    redact_label = "id_token"

    def claims(self) -> Dict[str, Any]:
        """Unverified claims decode (id_token.go:58-76).

        The token's signature is NOT checked here; use
        Provider.verify_id_token for verified claims.
        """
        if not self.reveal():
            raise InvalidParameterError("id_token is empty")
        parts = self.reveal().split(".")
        if len(parts) != 3:
            raise MalformedTokenError(
                f"id_token must have 3 segments, found {len(parts)}"
            )
        try:
            claims = json.loads(b64url_decode(parts[1]))
        except ValueError as e:
            raise MalformedTokenError(f"claims are not valid JSON: {e}") from e
        if not isinstance(claims, dict):
            raise MalformedTokenError("claims are not a JSON object")
        return claims

    def signing_alg(self) -> str:
        return parse_jws(self.reveal()).alg

    def _verify_hash_claim(self, claim_name: str, value: str,
                           mismatch_exc) -> bool:
        """Left-half-hash verification shared by at_hash/c_hash.

        Returns False (without error) when the token's alg cannot be
        mapped to a hash (EdDSA); raises on absent claim or mismatch.
        """
        if not value:
            raise InvalidParameterError(f"{claim_name} value is empty")
        alg = self.signing_alg()
        if alg not in _algs.SUPPORTED_ALGORITHMS:
            raise UnsupportedAlgError(f"unsupported signing algorithm {alg!r}")
        if alg == _algs.EdDSA:
            return False  # unverifiable: Ed25519 does not pin a hash alg
        hash_name = _HASH_BY_SUFFIX[alg[-3:]]
        claims = self.claims()
        claim = claims.get(claim_name)
        if not isinstance(claim, str) or not claim:
            # The claim is OPTIONAL (OIDC Core 3.1.3.6): absent means
            # "not verifiable", not a failure — exchange must still
            # succeed, mirroring the reference's (false, nil) return.
            return False
        digest = hashlib.new(hash_name, value.encode("utf-8")).digest()
        expected = b64url_encode(digest[: len(digest) // 2])
        if claim != expected:
            raise mismatch_exc()
        return True

    def verify_access_token(self, access_token: str) -> bool:
        """Verify the at_hash claim against an access_token."""
        from .token import AccessToken

        raw = access_token.reveal() if isinstance(access_token, AccessToken) \
            else str(access_token)
        return self._verify_hash_claim("at_hash", raw, InvalidAtHashError)

    def verify_authorization_code(self, code: str) -> bool:
        """Verify the c_hash claim against an authorization code."""
        return self._verify_hash_claim("c_hash", code, InvalidCodeHashError)
