"""PKCE (RFC 7636) code verifier / challenge.

Parity with oidc/pkce_verifier.go:25-99: 43-char base62 verifier, S256
challenge (SHA-256 → base64url, unpadded). Only the S256 method is
supported; "plain" is deliberately absent, as in the reference.
"""

from __future__ import annotations

import hashlib

from ..errors import InvalidParameterError, UnsupportedChallengeMethodError
from ..jwt.jose import b64url_encode
from ..utils.base62 import random_base62

MIN_VERIFIER_LEN = 43
MAX_VERIFIER_LEN = 128


class CodeVerifier:
    """Interface: a PKCE code verifier with its challenge."""

    def verifier(self) -> str:
        raise NotImplementedError

    def challenge(self) -> str:
        raise NotImplementedError

    def method(self) -> str:
        raise NotImplementedError

    def copy(self) -> "CodeVerifier":
        raise NotImplementedError


class S256Verifier(CodeVerifier):
    """SHA-256 PKCE verifier."""

    def __init__(self, verifier: str | None = None):
        v = verifier if verifier is not None else random_base62(MIN_VERIFIER_LEN)
        if not (MIN_VERIFIER_LEN <= len(v) <= MAX_VERIFIER_LEN):
            raise InvalidParameterError(
                f"verifier length must be in [{MIN_VERIFIER_LEN}, "
                f"{MAX_VERIFIER_LEN}], got {len(v)}"
            )
        self._verifier = v
        self._challenge = create_code_challenge(self)

    def verifier(self) -> str:
        return self._verifier

    def challenge(self) -> str:
        return self._challenge

    def method(self) -> str:
        return "S256"

    def copy(self) -> "S256Verifier":
        return S256Verifier(self._verifier)

    def __repr__(self) -> str:
        return "S256Verifier([REDACTED: verifier])"


def create_code_challenge(verifier: CodeVerifier) -> str:
    """Compute the challenge for a verifier (S256 only)."""
    if isinstance(verifier, S256Verifier) or verifier.method() == "S256":
        raw = (verifier._verifier if isinstance(verifier, S256Verifier)
               else verifier.verifier())
        return b64url_encode(hashlib.sha256(raw.encode("ascii")).digest())
    raise UnsupportedChallengeMethodError(
        f"unsupported challenge method {verifier.method()!r}"
    )
