"""Implicit-flow (form_post) callback handler.

Parity with oidc/callback/implicit.go:23-124: reads the form-posted
id_token (+ optional access_token), resolves/guards the Request, runs
``provider.verify_id_token``, verifies at_hash when an access token was
requested and posted, and wraps the result into a Token.
"""

from __future__ import annotations

from typing import Callable

from ...errors import (
    ExpiredRequestError,
    InvalidFlowError,
    MissingIDTokenError,
    NotFoundError,
)
from ..id_token import IDToken
from ..provider import Provider
from ..token import Token
from .authcode import _params, _respond
from .request_reader import RequestReader
from .response_func import AuthenErrorResponse


def implicit(p: Provider, request_reader: RequestReader,
             success_fn: Callable, error_fn: Callable):
    """Build the WSGI callback app for the implicit flow."""
    if p is None:
        raise NotFoundError("provider is nil")
    if request_reader is None:
        raise NotFoundError("request reader is nil")

    def app(environ, start_response):
        params = _params(environ)
        state = params.get("state", "")
        if params.get("error"):
            resp = AuthenErrorResponse(
                error=params["error"],
                description=params.get("error_description", ""),
                uri=params.get("error_uri", ""),
            )
            return _respond(start_response,
                            error_fn(state, resp, None, environ))
        try:
            request = request_reader.read(state)
        except Exception as e:  # noqa: BLE001
            return _respond(start_response,
                            error_fn(state, None, e, environ))
        if request is None:
            return _respond(start_response, error_fn(
                state, None,
                NotFoundError("no request found for state"), environ))
        if request.is_expired():
            return _respond(start_response, error_fn(
                state, None,
                ExpiredRequestError("request is expired"), environ))
        with_implicit, with_access_token = request.implicit_flow()
        if not with_implicit:
            return _respond(start_response, error_fn(
                state, None,
                InvalidFlowError(
                    "request does not use the implicit flow but callback "
                    "is for the implicit flow"), environ))
        raw_id_token = params.get("id_token", "")
        if not raw_id_token:
            return _respond(start_response, error_fn(
                state, None,
                MissingIDTokenError("id_token is missing"), environ))
        id_token = IDToken(raw_id_token)
        try:
            p.verify_id_token(id_token, request)
        except Exception as e:  # noqa: BLE001
            return _respond(start_response,
                            error_fn(state, None, e, environ))
        access_token = params.get("access_token", "")
        if with_access_token and access_token:
            try:
                id_token.verify_access_token(access_token)
            except Exception as e:  # noqa: BLE001
                return _respond(start_response,
                                error_fn(state, None, e, environ))
        try:
            token = Token(id_token, access_token=access_token,
                          now_func=p.config.now_func)
        except Exception as e:  # noqa: BLE001
            return _respond(start_response,
                            error_fn(state, None, e, environ))
        return _respond(start_response,
                        success_fn(state, token, environ))

    return app
