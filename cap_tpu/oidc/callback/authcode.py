"""Authorization-code (+PKCE) callback handler.

Parity with oidc/callback/authcode.go:21-97: a factory returning a WSGI
app that reads state/code/error params, resolves the in-flight Request
via the RequestReader, guards (found / expired / not implicit), runs
``provider.exchange``, and hands off to the success/error callables.
"""

from __future__ import annotations

from typing import Callable
from urllib.parse import parse_qs

from ...errors import ExpiredRequestError, InvalidFlowError, NotFoundError
from ..provider import Provider
from .request_reader import RequestReader
from .response_func import AuthenErrorResponse


def _params(environ) -> dict:
    query = parse_qs(environ.get("QUERY_STRING", ""))
    if environ.get("REQUEST_METHOD") == "POST":
        try:
            length = int(environ.get("CONTENT_LENGTH") or 0)
        except ValueError:
            length = 0
        if length:
            body = environ["wsgi.input"].read(length).decode("utf-8")
            for k, v in parse_qs(body).items():
                query.setdefault(k, v)
    return {k: v[0] for k, v in query.items() if v}


def _respond(start_response, triple):
    status, headers, body = triple
    reason = {200: "OK", 302: "Found", 400: "Bad Request",
              401: "Unauthorized", 403: "Forbidden", 404: "Not Found",
              500: "Internal Server Error"}.get(status, "")
    start_response(f"{status} {reason}".strip(), list(headers))
    return [body if isinstance(body, bytes) else body.encode("utf-8")]


def auth_code(p: Provider, request_reader: RequestReader,
              success_fn: Callable, error_fn: Callable):
    """Build the WSGI callback app for the authorization-code flow."""
    if p is None:
        raise NotFoundError("provider is nil")
    if request_reader is None:
        raise NotFoundError("request reader is nil")

    def app(environ, start_response):
        params = _params(environ)
        state = params.get("state", "")
        if params.get("error"):
            resp = AuthenErrorResponse(
                error=params["error"],
                description=params.get("error_description", ""),
                uri=params.get("error_uri", ""),
            )
            return _respond(start_response,
                            error_fn(state, resp, None, environ))
        code = params.get("code", "")
        try:
            request = request_reader.read(state)
        except Exception as e:  # noqa: BLE001
            return _respond(start_response,
                            error_fn(state, None, e, environ))
        if request is None:
            return _respond(start_response, error_fn(
                state, None,
                NotFoundError("no request found for state"), environ))
        if request.is_expired():
            return _respond(start_response, error_fn(
                state, None,
                ExpiredRequestError("request is expired"), environ))
        implicit, _ = request.implicit_flow()
        if implicit:
            return _respond(start_response, error_fn(
                state, None,
                InvalidFlowError(
                    "request uses implicit flow but callback is for the "
                    "authorization code flow"), environ))
        try:
            token = p.exchange(request, state, code)
        except Exception as e:  # noqa: BLE001
            return _respond(start_response,
                            error_fn(state, None, e, environ))
        return _respond(start_response,
                        success_fn(state, token, environ))

    return app
