"""HTTP callback handlers for OIDC responses.

Parity with oidc/callback/: AuthCode and Implicit handler factories
producing WSGI applications (the Python analog of http.HandlerFunc),
a RequestReader lookup interface keyed by state, and success/error
response callables.
"""

from .authcode import auth_code
from .implicit import implicit
from .request_reader import RequestReader, SingleRequestReader
from .response_func import AuthenErrorResponse

__all__ = [
    "auth_code", "implicit",
    "RequestReader", "SingleRequestReader", "AuthenErrorResponse",
]
