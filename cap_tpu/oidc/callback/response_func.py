"""Success / error response callables for callback handlers.

Parity with oidc/callback/response_func.go:21-43:

- ``success_fn(state, token, environ) -> (status, headers, body)``
- ``error_fn(state, error_response, exception, environ)
  -> (status, headers, body)``

where ``error_response`` is the IdP's OAuth error (when the IdP
redirected with error parameters) and ``exception`` is a local
callback failure; exactly one of the two is set.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class AuthenErrorResponse:
    """OAuth 2.0 error response parameters from the IdP."""

    error: str
    description: str = ""
    uri: str = ""
