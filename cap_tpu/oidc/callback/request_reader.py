"""RequestReader: look up an in-flight oidc.Request by state.

Parity with oidc/callback/request_reader.go:13-34. Implementations must
be safe for concurrent use by multiple callback requests.
"""

from __future__ import annotations

from typing import Optional

from ..request import Request


class RequestReader:
    def read(self, state: str) -> Optional[Request]:
        """Return the Request for ``state``, or None when unknown."""
        raise NotImplementedError


class SingleRequestReader(RequestReader):
    """Trivial reader for apps with one in-flight request."""

    def __init__(self, request: Request):
        self.request = request

    def read(self, state: str) -> Optional[Request]:
        return self.request if self.request.state() == state else None
