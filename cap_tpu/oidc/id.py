"""Opaque ID generation for state and nonce values.

Parity with oidc/id.go:14-71: 20-char base62 (~119 bits of entropy)
with optional prefix joined by "_".
"""

from __future__ import annotations

from ..errors import IDGeneratorFailedError
from ..utils.base62 import random_base62

DEFAULT_ID_LENGTH = 20


def new_id(prefix: str = "", length: int = DEFAULT_ID_LENGTH) -> str:
    """Generate a random base62 ID, optionally prefixed (``prefix_xxxx``)."""
    if length <= 0:
        raise IDGeneratorFailedError("length must be positive")
    try:
        ident = random_base62(length)
    except Exception as e:  # noqa: BLE001 - CSPRNG failure surface
        raise IDGeneratorFailedError(f"unable to generate id: {e}") from e
    return f"{prefix}_{ident}" if prefix else ident
