"""OIDC prompt values (oidc/prompt.go:9-18)."""


class Prompt(str):
    pass


NONE = Prompt("none")
LOGIN = Prompt("login")
CONSENT = Prompt("consent")
SELECT_ACCOUNT = Prompt("select_account")
