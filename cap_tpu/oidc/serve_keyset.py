"""The serve worker's OIDC surface.

``VerifyWorker`` serves whatever exposes ``verify_batch`` /
``verify_batch_raw`` — until now that was raw SIGNATURE verification
only, and full OIDC validation (the thing ``cap`` exists to do) lived
outside the serve tier. :class:`OIDCRawKeySet` closes that gap: it
wraps a :class:`~cap_tpu.oidc.provider.Provider` bound to one
:class:`~cap_tpu.oidc.request.Request` (the RP's expected
nonce/audience policy) and serves the FULL verify-AND-validate path —
``verify_id_token_batch(raw=True)``, whose claims rules run in the
native engine (claims_validate.cpp) when ``CAP_OIDC_NATIVE`` permits,
with per-token Python fallback counted on ``oidc.native_fallbacks``
(visible in worker STATS and obs scrapes, the graceful-degradation
contract).

Keyplane passthrough: KEYS pushes address the provider's underlying
engine, so hot key rotation works unchanged through this wrapper.

``worker_main --keyset "oidc-rp:issuer=...;client=...;nonce=...[;algs=
ES256+RS256][;aud=a+b][;keyset=<inner spec>]"`` builds one of these in
a fleet worker subprocess (discovery is injected, never fetched — the
serve tier must boot without IdP round-trips; the keyplane specs
remain the networked path).
"""

from __future__ import annotations

from typing import Any, List, Sequence

from .provider import Provider
from .request import Request


class OIDCRawKeySet:
    """Serve ``Provider.verify_id_token_batch`` through a VerifyWorker.

    The worker's raw-claims wrapper probes ``verify_batch_raw`` — this
    class exposes it, so accepted tokens stream their signed payload
    bytes straight onto the wire while every registered-claims rule
    (iss/exp/nbf/iat/nonce/aud/azp/auth_time) has been enforced.
    """

    def __init__(self, provider: Provider, request: Request):
        self._provider = provider
        self._request = request

    @property
    def provider(self) -> Provider:
        return self._provider

    @property
    def request(self) -> Request:
        return self._request

    def verify_batch(self, tokens: Sequence[str]) -> List[Any]:
        return self._provider.verify_id_token_batch(
            list(tokens), self._request)

    def verify_batch_raw(self, tokens: Sequence[str]) -> List[Any]:
        return self._provider.verify_id_token_batch(
            list(tokens), self._request, raw=True)

    # -- keyplane passthrough ---------------------------------------------

    @property
    def key_epoch(self):
        return getattr(self._provider.keyset, "key_epoch", None)

    def swap_keys(self, jwks, epoch=None, grace_s: float = 0.0):
        swap = getattr(self._provider.keyset, "swap_keys", None)
        if swap is None:
            raise TypeError(
                f"{type(self._provider.keyset).__name__} does not "
                "support hot key rotation")
        return swap(jwks, epoch=epoch, grace_s=grace_s)


def oidc_rp_keyset_from_spec(opts: dict, inner) -> OIDCRawKeySet:
    """Build the serve surface from parsed ``oidc-rp:`` spec options
    (worker_main's seam; split out so tests can drive it directly)."""
    from .config import Config

    issuer = opts.get("issuer", "")
    client = opts.get("client", "")
    algs = [a for a in (opts.get("algs") or "ES256").split("+") if a]
    auds = [a for a in (opts.get("aud") or "").split("+") if a]
    cfg = Config(issuer=issuer, client_id=client,
                 supported_signing_algs=algs,
                 audiences=auds or None)
    provider = Provider(cfg, keyset=inner,
                        discovery_doc={"issuer": issuer})
    request = Request(3600.0, opts.get("redirect", "http://127.0.0.1:1/cb"),
                      nonce=opts.get("nonce") or None)
    return OIDCRawKeySet(provider, request)
