"""Request: one OIDC authentication attempt.

Parity with oidc/request.go:22-415: auto-generated ``st_``/``n_``
prefixed state and nonce (base62), expiration with a 1-second skew,
redirect URL, per-request scope/audience overrides, implicit-vs-PKCE
mutual exclusion, max_age (with the derived auth_after instant),
prompts, display, ui_locales, claims JSON, and acr_values. Accessors
return defensive copies.
"""

from __future__ import annotations

import json
import time as _time
from typing import Callable, List, Optional, Sequence, Tuple

from ..errors import InvalidParameterError
from .display import Display
from .id import new_id
from .pkce import CodeVerifier
from .prompt import Prompt

REQUEST_EXPIRY_SKEW = 1.0  # seconds


class Request:
    """One authentication attempt's state.

    Construct with ``expires_in`` seconds and the redirect URL; state
    and nonce are generated unless overridden (they must differ).
    """

    def __init__(
        self,
        expires_in: float,
        redirect_url: str,
        *,
        state: Optional[str] = None,
        nonce: Optional[str] = None,
        scopes: Optional[Sequence[str]] = None,
        audiences: Optional[Sequence[str]] = None,
        implicit_flow: bool = False,
        implicit_access_token: bool = False,
        pkce_verifier: Optional[CodeVerifier] = None,
        max_age: Optional[float] = None,
        prompts: Optional[Sequence[Prompt]] = None,
        display: Optional[Display] = None,
        ui_locales: Optional[Sequence[str]] = None,
        claims: Optional[bytes | str | dict] = None,
        acr_values: Optional[Sequence[str]] = None,
        now_func: Optional[Callable[[], float]] = None,
    ):
        if expires_in <= 0:
            raise InvalidParameterError("expires_in must be positive")
        if not redirect_url:
            raise InvalidParameterError("redirect URL is empty")
        self._now_func = now_func
        now = self._now()
        self._expiration = now + float(expires_in)
        self._redirect_url = redirect_url
        self._state = state if state is not None else new_id(prefix="st")
        self._nonce = nonce if nonce is not None else new_id(prefix="n")
        if not self._state:
            raise InvalidParameterError("state is empty")
        if not self._nonce:
            raise InvalidParameterError("nonce is empty")
        if self._state == self._nonce:
            raise InvalidParameterError("state and nonce cannot be equal")

        if (implicit_flow or implicit_access_token) and pkce_verifier:
            raise InvalidParameterError(
                "request cannot use both implicit flow and PKCE"
            )
        self._implicit = bool(implicit_flow or implicit_access_token)
        self._implicit_access_token = bool(implicit_access_token)
        self._pkce_verifier = pkce_verifier

        self._scopes = list(scopes) if scopes else []
        self._audiences = list(audiences) if audiences else []

        self._max_age: Optional[float] = None
        self._auth_after: float = 0.0
        if max_age is not None:
            if max_age < 0:
                raise InvalidParameterError("max_age must be non-negative")
            self._max_age = float(max_age)
            self._auth_after = now - float(max_age)

        if prompts:
            self._prompts = [Prompt(p) for p in prompts]
        else:
            self._prompts = []
        self._display = Display(display) if display else None
        self._ui_locales = list(ui_locales) if ui_locales else []
        self._acr_values = list(acr_values) if acr_values else []

        if claims is None:
            self._claims: Optional[bytes] = None
        else:
            if isinstance(claims, dict):
                claims = json.dumps(claims).encode("utf-8")
            elif isinstance(claims, str):
                claims = claims.encode("utf-8")
            try:
                json.loads(claims)
            except ValueError as e:
                raise InvalidParameterError(
                    f"claims must be valid JSON: {e}"
                ) from e
            self._claims = bytes(claims)

    # -- accessors (defensive copies, request.go:281-415) ------------------

    def state(self) -> str:
        return self._state

    def nonce(self) -> str:
        return self._nonce

    def redirect_url(self) -> str:
        return self._redirect_url

    def scopes(self) -> List[str]:
        return list(self._scopes)

    def audiences(self) -> List[str]:
        return list(self._audiences)

    def implicit_flow(self) -> Tuple[bool, bool]:
        """(using implicit flow, access token also requested)."""
        return self._implicit, self._implicit_access_token

    def pkce_verifier(self) -> Optional[CodeVerifier]:
        return self._pkce_verifier.copy() if self._pkce_verifier else None

    def max_age(self) -> Tuple[Optional[float], float]:
        """(max_age seconds, auth_after instant; 0.0 when unset)."""
        return self._max_age, self._auth_after

    def prompts(self) -> List[Prompt]:
        return list(self._prompts)

    def display(self) -> Optional[Display]:
        return self._display

    def ui_locales(self) -> List[str]:
        return list(self._ui_locales)

    def claims(self) -> Optional[bytes]:
        return bytes(self._claims) if self._claims is not None else None

    def acr_values(self) -> List[str]:
        return list(self._acr_values)

    def expiration(self) -> float:
        return self._expiration

    def _now(self) -> float:
        return self._now_func() if self._now_func is not None else _time.time()

    def is_expired(self) -> bool:
        """True once now is past expiration + skew (request.go:401-407)."""
        return self._now() > self._expiration + REQUEST_EXPIRY_SKEW
