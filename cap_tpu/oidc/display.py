"""OIDC display values (oidc/display.go:9-18)."""


class Display(str):
    pass


PAGE = Display("page")
POPUP = Display("popup")
TOUCH = Display("touch")
WAP = Display("wap")
