"""Binding for the native OIDC claims-rule engine.

``runtime/native/claims_validate.cpp`` (the fourth TU of
libcapruntime.so) evaluates the pure-comparison subset of the
registered-claims rules — iss equality, exp/nbf/iat windows with the
verify leeway, nonce equality, aud membership + multi-aud-contains-
client_id, and the azp simple-equality arm — in one GIL-free batched
call per verify batch, directly off the phase-1 claims tape. This
module is the Python edge of it:

- :data:`STATUS_INDEX` is the FIXED-ORDER status registry (the
  ``REASON_INDEX`` pattern from the r13 telemetry plane): index IS the
  native ABI, append-only, and :func:`_handshake` disables the engine
  when a stale ``.so`` reports a different registry length or version
  — a drifted library can refuse, never misclassify.
- :data:`STATUS_ERROR_NAMES` maps reject statuses **by NAME** onto the
  :mod:`cap_tpu.errors` taxonomy, so a native reject constructs the
  SAME exception class Python's ``_validate_id_claims`` would raise
  (messages match verbatim where the Python message is static;
  dynamic-part messages keep the template without the payload value —
  the differential suite pins verdicts and classes, and the obs
  reason-class mapping rides the class alone).
- status ``fallback`` (and an unavailable/disabled engine) routes the
  token to the existing Python rule path — the conservative-fallback
  contract ``registered_batch`` already uses, counted on
  ``oidc.native_fallbacks``; natively decided tokens count on
  ``oidc.native_validated``.

Switch: ``CAP_OIDC_NATIVE=0`` disables the engine (the graceful kill
switch, same stance as ``CAP_SERVE_VCACHE``); anything else leaves it
on whenever the library loads and the layout handshake passes.
"""

from __future__ import annotations

import ctypes
import os
import struct
from typing import Any, List, Optional, Sequence

import numpy as np

from .. import telemetry
from .. import errors as _errors

# ---------------------------------------------------------------------------
# status registry (native ABI — append-only; claims_validate.cpp's
# VStatus enum and kNumStatus are the C side of this table)
# ---------------------------------------------------------------------------

LAYOUT_VERSION = 1

STATUS_OK = 0
STATUS_FALLBACK = 1

STATUS_INDEX = (
    "ok",                        # 0  accepted natively
    "fallback",                  # 1  Python rules decide this token
    "missing_exp",               # 2
    "expired",                   # 3
    "not_before",                # 4
    "wrong_issuer",              # 5
    "unsupported_alg",           # 6
    "wrong_nonce",               # 7
    "future_iat",                # 8
    "aud_non_string",            # 9
    "aud_mismatch",              # 10
    "multi_aud_missing_client",  # 11
    "azp_mismatch",              # 12
)

# status name → errors.py class NAME (by-name so the mapping is
# wire-roundtrip stable, the decision.REASON_FOR_ERROR stance; the
# differential suite pins every entry against what
# provider._validate_id_claims actually raises)
STATUS_ERROR_NAMES = {
    "missing_exp": "MissingClaimError",
    "expired": "ExpiredTokenError",
    "not_before": "InvalidNotBeforeError",
    "wrong_issuer": "InvalidIssuerError",
    "unsupported_alg": "UnsupportedAlgError",
    "wrong_nonce": "InvalidNonceError",
    "future_iat": "InvalidIssuedAtError",
    "aud_non_string": "InvalidAudienceError",
    "aud_mismatch": "InvalidAudienceError",
    "multi_aud_missing_client": "InvalidAudienceError",
    "azp_mismatch": "InvalidAuthorizedPartyError",
}

# Registered span: the whole claims-validation stage of one raw batch
# (native call or Python rule loop — whichever ran).
SPAN_OIDC_VALIDATE = telemetry.SPAN_OIDC_VALIDATE

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)


def status_error(status: int, alg: Optional[str] = None,
                 client_id: str = "", now: Optional[float] = None
                 ) -> Exception:
    """Construct the taxonomy exception for one native reject status.

    Messages mirror provider.py's wording; static messages are
    byte-identical, dynamic ones keep the template with the parts the
    binding knows (alg from the header-segment cache, client_id from
    the policy) — classes, and therefore obs reason classes, always
    match the Python engine exactly.
    """
    name = STATUS_INDEX[status]
    cls = getattr(_errors, STATUS_ERROR_NAMES[name])
    if name == "missing_exp":
        return cls("id_token missing exp claim")
    if name == "expired":
        return cls("token is expired")
    if name == "not_before":
        return cls("current time before the nbf (not before) time")
    if name == "wrong_issuer":
        return cls("id token issued by a different provider")
    if name == "unsupported_alg":
        return cls(f"id_token signed with unsupported algorithm {alg!r}")
    if name == "wrong_nonce":
        return cls("invalid id_token nonce")
    if name == "future_iat":
        return cls(f"current time {now} before the iat (issued at) time")
    if name == "aud_non_string":
        return cls("aud claim contains a non-string value")
    if name == "aud_mismatch":
        return cls("invalid id_token audiences")
    if name == "multi_aud_missing_client":
        return cls("multiple audiences and one of them is not equal "
                   f"client_id ({client_id})")
    if name == "azp_mismatch":
        return cls(f"authorized party is not equal client_id ({client_id})")
    raise ValueError(f"not a reject status: {status}")


def pack_policy(issuer: str, client_id: str, nonce: str,
                audiences: Sequence[str], leeway: float,
                max_age_requested: bool) -> bytes:
    """Compile one batch's rule policy into the native blob (format
    documented in claims_validate.cpp's parse_policy)."""
    iss = issuer.encode("utf-8")
    cli = client_id.encode("utf-8")
    non = nonce.encode("utf-8")
    auds = [a.encode("utf-8") for a in audiences]
    head = struct.pack("<IIdI", 1, 1 if max_age_requested else 0,
                       float(leeway), len(auds))
    lens = struct.pack("<III", len(iss), len(cli), len(non))
    lens += struct.pack(f"<{len(auds)}I", *[len(a) for a in auds]) \
        if auds else b""
    return head + lens + iss + cli + non + b"".join(auds)


class _Engine:
    """One loaded-and-handshaked native engine (module singleton)."""

    def __init__(self, lib: ctypes.CDLL):
        lib.cap_claims_layout.argtypes = [_i32p]
        layout = np.zeros(2, np.int32)
        lib.cap_claims_layout(layout.ctypes.data_as(_i32p))
        if (int(layout[0]), int(layout[1])) != (LAYOUT_VERSION,
                                                len(STATUS_INDEX)):
            raise RuntimeError(
                f"claims engine layout drift: lib reports "
                f"{layout.tolist()}, binding expects "
                f"[{LAYOUT_VERSION}, {len(STATUS_INDEX)}]")
        lib.cap_claims_validate_batch.restype = ctypes.c_int32
        lib.cap_claims_validate_batch.argtypes = [
            _u8p, ctypes.c_int64, _i64p, _i64p, ctypes.c_int64,
            _u8p, ctypes.c_int64, _u8p, ctypes.c_double, _u8p,
            ctypes.c_int32,
        ]
        self._lib = lib

    def validate(self, payloads: Sequence[bytes], alg_ok: np.ndarray,
                 now: float, policy: bytes) -> Optional[np.ndarray]:
        """[status u8] per payload, or None when the native call
        refuses (unusable policy/spans → whole-batch Python path)."""
        n = len(payloads)
        if n == 0:
            return np.zeros(0, np.uint8)
        scratch = np.frombuffer(b"".join(payloads), np.uint8)
        if len(scratch) == 0:
            scratch = np.zeros(1, np.uint8)
        lens = np.fromiter((len(p) for p in payloads), np.int64, count=n)
        offs = np.zeros(n, np.int64)
        np.cumsum(lens[:-1], out=offs[1:])
        pol = np.frombuffer(policy, np.uint8)
        out = np.zeros(n, np.uint8)
        rc = self._lib.cap_claims_validate_batch(
            scratch.ctypes.data_as(_u8p), len(scratch),
            offs.ctypes.data_as(_i64p), lens.ctypes.data_as(_i64p), n,
            pol.ctypes.data_as(_u8p), len(pol),
            np.ascontiguousarray(alg_ok, np.uint8).ctypes.data_as(_u8p),
            float(now), out.ctypes.data_as(_u8p), 0)
        if rc != 0:
            return None
        return out


_engine: Optional[_Engine] = None
_engine_probed = False


def _load_engine() -> Optional[_Engine]:
    """Load + handshake once per process; None = engine unavailable
    (missing/stale library, layout drift — every caller then takes the
    Python rule path, visibly via oidc.native_fallbacks)."""
    global _engine, _engine_probed
    if _engine_probed:
        return _engine
    _engine_probed = True
    try:
        # native_binding owns the build-on-first-use latch and the one
        # CDLL handle every libcapruntime consumer shares
        from ..runtime import native_binding

        _engine = _Engine(native_binding._lib)
    except Exception:  # noqa: BLE001 - graceful: Python rules serve
        _engine = None
    return _engine


def enabled() -> bool:
    """True when the native rules engine will serve the next batch
    (CAP_OIDC_NATIVE kill switch honored per call, library loaded,
    layout handshake passed)."""
    if os.environ.get("CAP_OIDC_NATIVE", "1") == "0":
        return False
    return _load_engine() is not None


def validate_payloads(payloads: Sequence[bytes], alg_ok: np.ndarray,
                      now: float, policy: bytes) -> Optional[np.ndarray]:
    """One native batched rules call; None → caller takes the Python
    path for the whole batch (engine off/unavailable/refused)."""
    if not enabled():
        return None
    eng = _load_engine()
    assert eng is not None
    return eng.validate(payloads, alg_ok, now, policy)


def count_validated(n: int) -> None:
    if n:
        telemetry.count("oidc.native_validated", n)


def count_fallbacks(n: int) -> None:
    if n:
        telemetry.count("oidc.native_fallbacks", n)


def _reset_for_tests() -> None:
    """Forget the probed engine (stale-.so / drift tests re-probe)."""
    global _engine, _engine_probed
    _engine = None
    _engine_probed = False
