"""TestProvider: a stateful in-process HTTPS OIDC IdP for tests.

Parity with oidc/testing_provider.go:121-910 — the centerpiece of the
reference's test strategy: a real TLS server (self-signed CA exposed via
``ca_cert()``) implementing all five IdP endpoints, with stateful knobs
that double as fault injection:

- ``set_disable_jwks`` (404 JWKS), ``set_invalid_jwks`` (garbage body)
- ``set_disable_token`` (401), ``set_disable_implicit``,
  ``set_disable_userinfo``, ``set_disable_discovery``
- ``set_omit_id_tokens`` / ``set_omit_access_tokens``
- ``set_expected_state`` (send a wrong state back)
- ``set_signing_keys`` (key rotation), ``set_now_func`` (clock control)
- ``set_expected_auth_code``, ``set_expected_auth_nonce``,
  ``set_client_creds``, ``set_expected_code_verifier`` (PKCE),
  ``set_custom_claims``, ``set_custom_audiences``,
  ``set_user_info_reply``, ``set_allowed_redirect_uris``,
  ``set_expected_expiry``, ``set_invalid_jwt_signature``

Tests "do multi-node without a cluster": client and IdP run in one
process over real HTTPS.
"""

from __future__ import annotations

import json
import os
import ssl
import tempfile
import threading
import time as _time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlencode, urlparse

from .. import testing as captest
from ..jwt import algs as _algs
from ..jwt.jwk import serialize_public_key

DEFAULT_EXPECTED_EXPIRY = 300.0


class TestProvider:
    """In-process HTTPS OIDC IdP. Start with ``TestProvider.start()``
    (or use as a context manager); ``stop()`` shuts the server down."""

    __test__ = False  # not a pytest test class despite the name

    def __init__(self, alg: str = _algs.ES256,
                 client_id: str = "test-client-id",
                 client_secret: str = "test-client-secret",
                 expected_auth_code: str = "test-auth-code",
                 with_port: int = 0,
                 no_tls: bool = False):
        self._lock = threading.RLock()
        self._alg = alg
        priv, pub = captest.generate_keys(alg)
        self._signing_key, self._public_key, self._kid = priv, pub, "kid-0"
        self._key_counter = 0
        self.client_id = client_id
        self.client_secret = client_secret
        self.expected_auth_code = expected_auth_code
        self.expected_auth_nonce: Optional[str] = None
        self.expected_code_verifier: Optional[str] = None
        self.expected_state: Optional[str] = None  # override sent-back state
        self.expected_expiry = DEFAULT_EXPECTED_EXPIRY
        self.allowed_redirect_uris: Optional[List[str]] = None
        self.replay_subject = "alice@example.com"
        self.custom_claims: Dict[str, Any] = {}
        self.custom_audiences: Optional[List[str]] = None
        self.user_info_reply: Optional[Dict[str, Any]] = None
        self.now_func: Optional[Callable[[], float]] = None
        self.disable_jwks = False
        self.invalid_jwks = False
        self.disable_token = False
        self.disable_implicit = False
        self.disable_userinfo = False
        self.disable_discovery = False
        self.invalid_jwt_signature = False
        self.omit_id_tokens = False
        self.omit_access_tokens = False
        self.omit_at_hash = False  # issue id_tokens without at_hash

        # nonce bound at /authorize time, replayed by /token per real-IdP
        # semantics (expected_auth_nonce overrides when set)
        self._nonce_for_code: Dict[str, str] = {}
        self._no_tls = no_tls
        self._requested_port = with_port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._ca_pem = ""

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "TestProvider":
        provider = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1 so clients exercise keep-alive connection reuse
            # (the pooled-transport behavior the reference gets from
            # cleanhttp, oidc/provider.go:566-618).
            protocol_version = "HTTP/1.1"

            def do_GET(self):  # noqa: N802
                provider._handle(self)

            def do_POST(self):  # noqa: N802
                provider._handle(self)

            def log_message(self, *args):
                pass

        self._server = ThreadingHTTPServer(
            ("127.0.0.1", self._requested_port), Handler)
        scheme = "http" if self._no_tls else "https"
        if not self._no_tls:
            ca_pem, key, key_pem = captest.generate_ca("cap-tpu-test-idp")
            self._ca_pem = ca_pem
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            with tempfile.NamedTemporaryFile("w", suffix=".pem",
                                             delete=False) as f:
                f.write(ca_pem)
                f.write(key_pem)
                chain = f.name
            try:
                ctx.load_cert_chain(chain)
            finally:
                os.unlink(chain)  # never leave key material on disk
            self._server.socket = ctx.wrap_socket(
                self._server.socket, server_side=True)
        port = self._server.server_address[1]
        self.addr = f"{scheme}://127.0.0.1:{port}"
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def __enter__(self) -> "TestProvider":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- accessors ---------------------------------------------------------

    def issuer(self) -> str:
        return self.addr

    def ca_cert(self) -> str:
        """PEM of the server's self-signed CA (testing_provider.go:498-502)."""
        return self._ca_pem

    def signing_keys(self) -> Tuple[Any, Any, str, str]:
        with self._lock:
            return self._signing_key, self._public_key, self._alg, self._kid

    def now(self) -> float:
        return self.now_func() if self.now_func else _time.time()

    # -- knobs (reference Set* surface) ------------------------------------

    def set_expected_auth_code(self, code: str) -> None:
        with self._lock:
            self.expected_auth_code = code

    def set_expected_auth_nonce(self, nonce: str) -> None:
        with self._lock:
            self.expected_auth_nonce = nonce

    def set_expected_code_verifier(self, verifier: str) -> None:
        with self._lock:
            self.expected_code_verifier = verifier

    def set_expected_state(self, state: str) -> None:
        with self._lock:
            self.expected_state = state

    def set_client_creds(self, client_id: str, client_secret: str) -> None:
        with self._lock:
            self.client_id, self.client_secret = client_id, client_secret

    def set_expected_expiry(self, seconds: float) -> None:
        with self._lock:
            self.expected_expiry = seconds

    def set_allowed_redirect_uris(self, uris: List[str]) -> None:
        with self._lock:
            self.allowed_redirect_uris = list(uris)

    def set_custom_claims(self, claims: Dict[str, Any]) -> None:
        with self._lock:
            self.custom_claims = dict(claims)

    def set_custom_audiences(self, auds: List[str]) -> None:
        with self._lock:
            self.custom_audiences = list(auds)

    def set_user_info_reply(self, reply: Dict[str, Any]) -> None:
        with self._lock:
            self.user_info_reply = dict(reply)

    def set_now_func(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self.now_func = fn

    def set_signing_keys(self, priv, pub, alg: str, kid: str) -> None:
        with self._lock:
            self._signing_key, self._public_key = priv, pub
            self._alg, self._kid = alg, kid

    def rotate_signing_keys(self) -> None:
        """Generate a fresh key pair under a new kid (rotation tests)."""
        with self._lock:
            self._key_counter += 1
            priv, pub = captest.generate_keys(self._alg)
            self._signing_key, self._public_key = priv, pub
            self._kid = f"kid-{self._key_counter}"

    def set_disable_jwks(self, v: bool = True) -> None:
        with self._lock:
            self.disable_jwks = v

    def set_invalid_jwks(self, v: bool = True) -> None:
        with self._lock:
            self.invalid_jwks = v

    def set_disable_token(self, v: bool = True) -> None:
        with self._lock:
            self.disable_token = v

    def set_disable_implicit(self, v: bool = True) -> None:
        with self._lock:
            self.disable_implicit = v

    def set_disable_userinfo(self, v: bool = True) -> None:
        with self._lock:
            self.disable_userinfo = v

    def set_disable_discovery(self, v: bool = True) -> None:
        with self._lock:
            self.disable_discovery = v

    def set_omit_id_tokens(self, v: bool = True) -> None:
        with self._lock:
            self.omit_id_tokens = v

    def set_omit_access_tokens(self, v: bool = True) -> None:
        with self._lock:
            self.omit_access_tokens = v

    def set_omit_at_hash(self, v: bool = True) -> None:
        with self._lock:
            self.omit_at_hash = v

    def set_invalid_jwt_signature(self, v: bool = True) -> None:
        """Issue id_tokens whose signature bytes are corrupted."""
        with self._lock:
            self.invalid_jwt_signature = v

    # -- token issuing (testing_provider.go:582-610) -----------------------

    def issue_signed_jwt(self, nonce: str = "",
                         extra_claims: Optional[Dict[str, Any]] = None) -> str:
        with self._lock:
            now = self.now()
            claims: Dict[str, Any] = {
                "iss": self.issuer(),
                "sub": self.replay_subject,
                "aud": (self.custom_audiences
                        if self.custom_audiences is not None
                        else [self.client_id]),
                "iat": int(now),
                "nbf": int(now),
                "exp": int(now + self.expected_expiry),
                "auth_time": int(now),
            }
            if nonce:
                claims["nonce"] = nonce
            claims.update(self.custom_claims)
            if extra_claims:
                claims.update(extra_claims)
            token = captest.sign_jwt(self._signing_key, self._alg, claims,
                                     kid=self._kid)
            if self.invalid_jwt_signature:
                token = token[:-8] + ("A" * 8 if token[-8:] != "A" * 8
                                      else "B" * 8)
            return token

    # -- HTTP --------------------------------------------------------------

    def _handle(self, h: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(h.path)
        path = parsed.path.rstrip("/") or "/"
        try:
            if path == "/.well-known/openid-configuration":
                self._serve_discovery(h)
            elif path == "/.well-known/jwks.json":
                self._serve_jwks(h)
            elif path == "/authorize":
                self._serve_authorize(h, parsed)
            elif path == "/token":
                self._serve_token(h)
            elif path == "/userinfo":
                self._serve_userinfo(h)
            else:
                self._reply(h, 404, {"error": "not found"})
        except (BrokenPipeError, ConnectionResetError):
            pass

    @staticmethod
    def _reply(h, status: int, payload, content_type="application/json",
               headers=None) -> None:
        body = (json.dumps(payload).encode()
                if content_type == "application/json"
                and not isinstance(payload, (bytes, str)) else
                payload if isinstance(payload, bytes) else
                str(payload).encode())
        h.send_response(status)
        h.send_header("Content-Type", content_type)
        h.send_header("Cache-Control", "no-store")
        h.send_header("Content-Length", str(len(body)))  # keep-alive
        for k, v in (headers or {}).items():
            h.send_header(k, v)
        h.end_headers()
        h.wfile.write(body)

    def _serve_discovery(self, h) -> None:
        if self.disable_discovery:
            self._reply(h, 404, {"error": "discovery disabled"})
            return
        self._reply(h, 200, {
            "issuer": self.issuer(),
            "authorization_endpoint": self.issuer() + "/authorize",
            "token_endpoint": self.issuer() + "/token",
            "userinfo_endpoint": self.issuer() + "/userinfo",
            "jwks_uri": self.issuer() + "/.well-known/jwks.json",
            "response_types_supported": ["code", "id_token",
                                         "id_token token"],
            "subject_types_supported": ["public"],
            "id_token_signing_alg_values_supported": [self._alg],
        })

    def _serve_jwks(self, h) -> None:
        if self.disable_jwks:
            self._reply(h, 404, {"error": "jwks disabled"})
            return
        if self.invalid_jwks:
            self._reply(h, 200, b"{ this is not valid json ]",
                        content_type="application/json")
            return
        with self._lock:
            doc = {"keys": [serialize_public_key(
                self._public_key, kid=self._kid, alg=self._alg)]}
        self._reply(h, 200, doc)

    def _serve_authorize(self, h, parsed) -> None:
        q = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        state = self.expected_state or q.get("state", "")
        redirect = q.get("redirect_uri", "")
        if self.allowed_redirect_uris is not None and \
                redirect not in self.allowed_redirect_uris:
            self._reply(h, 403, {"error": "unauthorized redirect_uri"})
            return
        response_type = q.get("response_type", "code")
        nonce = q.get("nonce", "")
        if "id_token" in response_type:
            if self.disable_implicit:
                self._reply(h, 403, {"error": "implicit disabled"})
                return
            fields: Dict[str, str] = {"state": state}
            if not self.omit_id_tokens:
                fields["id_token"] = self.issue_signed_jwt(nonce=nonce)
            if "token" in response_type.split() and not self.omit_access_tokens:
                fields["access_token"] = "test-access-token"
                # at_hash binding when both tokens are issued
                if "id_token" in fields:
                    fields["id_token"] = self._with_hash_claims(
                        nonce, access_token=fields["access_token"])
            inputs = "".join(
                f'<input type="hidden" name="{k}" value="{v}"/>'
                for k, v in fields.items())
            html = (f'<html><body onload="document.forms[0].submit()">'
                    f'<form method="post" action="{redirect}">{inputs}'
                    f'</form></body></html>')
            self._reply(h, 200, html.encode(), content_type="text/html")
            return
        # code flow: redirect back with code + state
        with self._lock:
            self._nonce_for_code[self.expected_auth_code] = nonce
        sep = "&" if "?" in redirect else "?"
        location = redirect + sep + urlencode(
            {"state": state, "code": self.expected_auth_code})
        h.send_response(302)
        h.send_header("Location", location)
        h.send_header("Content-Length", "0")  # keep-alive framing
        h.end_headers()

    def _with_hash_claims(self, nonce: str, access_token: str = "",
                          code: str = "") -> str:
        import hashlib

        from ..jwt.jose import b64url_encode

        extra: Dict[str, Any] = {}
        hash_name = {"256": "sha256", "384": "sha384",
                     "512": "sha512"}.get(self._alg[-3:], "sha256")

        def half_hash(value: str) -> str:
            d = hashlib.new(hash_name, value.encode()).digest()
            return b64url_encode(d[: len(d) // 2])

        if access_token and not self.omit_at_hash:
            extra["at_hash"] = half_hash(access_token)
        if code:
            extra["c_hash"] = half_hash(code)
        return self.issue_signed_jwt(nonce=nonce, extra_claims=extra)

    def _serve_token(self, h) -> None:
        if self.disable_token:
            self._reply(h, 401, {"error": "token endpoint disabled"})
            return
        length = int(h.headers.get("Content-Length") or 0)
        body = h.rfile.read(length).decode() if length else ""
        fields = {k: v[0] for k, v in parse_qs(body).items()}
        if fields.get("grant_type") != "authorization_code":
            self._reply(h, 400, {"error": "unsupported_grant_type"})
            return
        if fields.get("code") != self.expected_auth_code:
            self._reply(h, 401, {"error": "invalid_grant"})
            return
        # client authentication: accept post body or basic auth
        import base64

        cid, csec = fields.get("client_id"), fields.get("client_secret")
        auth = h.headers.get("Authorization", "")
        if auth.startswith("Basic "):
            try:
                decoded = base64.b64decode(auth[6:]).decode()
                cid, _, csec = decoded.partition(":")
            except Exception:  # noqa: BLE001
                pass
        if self.client_secret and csec != self.client_secret:
            self._reply(h, 401, {"error": "invalid_client"})
            return
        if cid != self.client_id:
            self._reply(h, 401, {"error": "invalid_client"})
            return
        if self.expected_code_verifier is not None and \
                fields.get("code_verifier") != self.expected_code_verifier:
            self._reply(h, 401, {"error": "invalid PKCE verifier"})
            return
        with self._lock:
            nonce = (self.expected_auth_nonce
                     or self._nonce_for_code.get(fields.get("code", ""), ""))
        payload: Dict[str, Any] = {
            "token_type": "Bearer",
            "expires_in": int(self.expected_expiry),
        }
        access_token = None
        if not self.omit_access_tokens:
            access_token = "test-access-token"
            payload["access_token"] = access_token
            payload["refresh_token"] = "test-refresh-token"
        if not self.omit_id_tokens:
            payload["id_token"] = self._with_hash_claims(
                nonce, access_token=access_token or "")
        self._reply(h, 200, payload)

    def _serve_userinfo(self, h) -> None:
        if self.disable_userinfo:
            self._reply(h, 404, {"error": "userinfo disabled"})
            return
        auth = h.headers.get("Authorization", "")
        if not auth.startswith("Bearer "):
            self._reply(h, 401, {"error": "missing bearer token"})
            return
        with self._lock:
            reply = self.user_info_reply or {
                "sub": self.replay_subject,
                "iss": self.issuer(),
                "email": self.replay_subject,
            }
        self._reply(h, 200, reply)
