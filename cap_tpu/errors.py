"""Sentinel error taxonomy.

Mirrors the reference's 32 sentinel errors (oidc/error.go:7-40) as an
exception hierarchy. The reference wraps sentinels with an ``op`` prefix
(e.g. ``oidc.NewProvider: invalid issuer``); here the same convention is
an optional ``op`` argument. ``errors.Is`` becomes ``isinstance``.
"""

from __future__ import annotations


class CapError(Exception):
    """Base class for all cap_tpu errors."""

    default_message = "error"

    def __init__(self, message: str | None = None, *, op: str | None = None):
        msg = message if message is not None else self.default_message
        if op:
            msg = f"{op}: {msg}"
        super().__init__(msg)
        self.op = op


class InvalidParameterError(CapError):
    default_message = "invalid parameter"


class NilParameterError(InvalidParameterError):
    # In Python a "nil parameter" is a missing/None parameter; it is a
    # subclass of InvalidParameterError for ergonomic catching.
    default_message = "missing (None) parameter"


class InvalidCACertError(CapError):
    default_message = "invalid CA certificate"


class InvalidIssuerError(CapError):
    default_message = "invalid issuer"


class ExpiredRequestError(CapError):
    default_message = "request is expired"


class InvalidResponseStateError(CapError):
    default_message = "invalid response state"


class InvalidSignatureError(CapError):
    default_message = "invalid signature"


class UnknownKeyIDError(InvalidSignatureError):
    # Subclass of InvalidSignatureError so existing catch sites are
    # unaffected; raised where a token's kid provably matches NO key in
    # the set (key-rotation misses, stale caches) — a distinct
    # rejection-reason class in telemetry (cap_tpu.obs.decision),
    # because "unknown kid" pages differently than "forged signature".
    default_message = "no key matches the token kid"


class InvalidSubjectError(CapError):
    default_message = "invalid subject"


class InvalidAudienceError(CapError):
    default_message = "invalid audience"


class InvalidNonceError(CapError):
    default_message = "invalid nonce"


class InvalidNotBeforeError(CapError):
    default_message = "invalid not before"


class ExpiredTokenError(CapError):
    default_message = "token is expired"


class InvalidJWKSError(CapError):
    default_message = "invalid jwks"


class InvalidIssuedAtError(CapError):
    default_message = "invalid issued at (iat)"


class InvalidAuthorizedPartyError(CapError):
    default_message = "invalid authorized party (azp)"


class InvalidAtHashError(CapError):
    default_message = "access_token hash does not match value in id_token"


class InvalidCodeHashError(CapError):
    default_message = "authorization code hash does not match value in id_token"


class TokenNotSignedError(CapError):
    default_message = "token is not signed"


class MalformedTokenError(CapError):
    default_message = "token malformed"


class UnsupportedAlgError(CapError):
    default_message = "unsupported signing algorithm"


class IDGeneratorFailedError(CapError):
    default_message = "id generation failed"


class MissingIDTokenError(CapError):
    default_message = "id_token is missing"


class MissingAccessTokenError(CapError):
    default_message = "access_token is missing"


class IDTokenVerificationFailedError(CapError):
    default_message = "id_token verification failed"


class NotFoundError(CapError):
    default_message = "not found"


class LoginFailedError(CapError):
    default_message = "login failed"


class UserInfoFailedError(CapError):
    default_message = "user info failed"


class UnauthorizedRedirectURIError(CapError):
    default_message = "unauthorized redirect_uri"


class InvalidFlowError(CapError):
    default_message = "invalid OIDC flow"


class UnsupportedChallengeMethodError(CapError):
    default_message = "unsupported PKCE challenge method"


class ExpiredAuthTimeError(CapError):
    default_message = "expired auth_time"


class MissingClaimError(CapError):
    default_message = "missing required claim"


class ThrottledError(CapError):
    """Admission control rejected the token BEFORE verification: the
    tenant (issuer) is over its token-bucket budget. A terminal,
    non-verdict rejection — the signature was never checked, so no
    caller may treat it as "invalid", only as "retry later". The wire
    form carries an additive retry-after hint inside the ordinary
    status-1 payload (``retry_after_ms=<int>``), parsed back by
    :func:`cap_tpu.serve.protocol.retry_after_hint`."""

    default_message = "tenant over admission budget"

    def __init__(self, message: str | None = None, *,
                 retry_after_ms: int | None = None,
                 op: str | None = None):
        if message is None and retry_after_ms is not None:
            message = (f"{self.default_message} "
                       f"(retry_after_ms={int(retry_after_ms)})")
        super().__init__(message, op=op)
        self.retry_after_ms = retry_after_ms
