"""Native batch-prep runtime.

The reference has no native components (SURVEY.md §2); this package is
the new framework's native layer: a C++ batch tokenizer (JOSE split,
base64url decode, header scan, SHA-2 over signing inputs) loaded via
ctypes, with a pure-Python fallback so the framework works unbuilt.
"""
