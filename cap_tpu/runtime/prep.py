"""Batch JOSE preparation: C++ fast path with Python fallback.

``prepare_batch(tokens)`` parses every token (strict JWS rules,
identical to cap_tpu.jwt.jose.parse_jws) and returns one entry per
token: a ParsedJWS or the exception that token fails with. The native
implementation (capruntime.so, see cap_tpu/runtime/native/) does the
splitting, base64url decoding, and SHA-2 hashing in multithreaded C++.
"""

from __future__ import annotations

from typing import Any, List, Sequence

from ..jwt.jose import parse_jws


def _prepare_python(tokens: Sequence[str]) -> List[Any]:
    out: List[Any] = []
    for t in tokens:
        try:
            out.append(parse_jws(t))
        except Exception as e:  # noqa: BLE001 - per-token error channel
            out.append(e)
    return out


def prepare_batch(tokens: Sequence[str]) -> List[Any]:
    native = _load_native()
    if native is None:
        return _prepare_python(tokens)
    # The C++ parser is compact-only; JSON-serialization tokens (rare)
    # are re-serialized first — same signing input, same verdict. A
    # valid-but-non-compactable token (alg only in the unprotected
    # header) comes back from normalize_batch as a ready ParsedJWS,
    # which is exactly this function's per-token success type.
    from ..jwt.jose import normalize_batch

    tokens, specials = normalize_batch(tokens)
    out = native.prepare_batch(tokens)
    for i, sp in specials.items():
        out[i] = sp
    return out


_native_mod = None
_native_tried = False


def _load_native():
    global _native_mod, _native_tried
    if not _native_tried:
        _native_tried = True
        try:
            from . import native_binding
            _native_mod = native_binding
        except Exception:  # noqa: BLE001 - unbuilt native is expected
            _native_mod = None
    return _native_mod
