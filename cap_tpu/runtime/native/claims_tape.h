// claims_tape.h — the claims-JSON phase-1 parser, shared verbatim by
// the _capclaims extension (claims_ext.cpp: tape → Python objects) and
// the native claims-rule engine (claims_validate.cpp: tape → rule
// verdicts inside libcapruntime.so). ONE parser feeds both consumers:
// a bounds/validation fix here can never diverge between the path
// that builds dicts and the path that evaluates OIDC rules.
//
// Everything here is Python-free C++17 (claims_validate.cpp compiles
// without the CPython headers); all functions are inline/in-struct so
// the header can sit in several translation units.
//
// Contract (unchanged from the r5 claims_ext.cpp original): for any
// payload the parser accepts (ST_OK), the tape replays into exactly
// what json.loads(payload) would build; anything outside the
// supported envelope (depth > 64, NaN/Infinity, lone surrogates,
// ints > 2000 digits, ...) is flagged ST_FALLBACK and the consumer
// must re-parse with json.loads — never a silent behavioural
// difference. Malformed JSON is ST_MALFORMED.

#ifndef CAP_TPU_CLAIMS_TAPE_H_
#define CAP_TPU_CLAIMS_TAPE_H_

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace capclaims {

// ---------------------------------------------------------------------------
// Tape representation (phase-1 output)
// ---------------------------------------------------------------------------

enum Op : uint32_t {
  OP_OBJ_START = 1,
  OP_OBJ_END = 2,
  OP_ARR_START = 3,
  OP_ARR_END = 4,
  OP_KEY = 5,      // off, len, esc  (string span; esc => needs unescape)
  OP_STR = 6,      // off, len, esc
  OP_INT = 7,      // lo, hi         (int64 in two u32 slots)
  OP_BIGINT = 8,   // off, len       (digits span; PyLong_FromString)
  OP_FLOAT = 9,    // lo, hi         (double bits in two u32 slots)
  OP_TRUE = 10,
  OP_FALSE = 11,
  OP_NULL = 12,
};

enum Status : int32_t {
  ST_OK = 0,
  ST_MALFORMED = 1,   // invalid JSON → MalformedTokenError
  ST_NOT_OBJECT = 2,  // valid JSON, but not an object → MalformedTokenError
  ST_FALLBACK = 3,    // valid-looking but outside the envelope → json.loads
};

constexpr int kMaxDepth = 64;
// CPython refuses int() conversion beyond sys.int_info.default_max_str_digits
// (4300) — route anything close to that through json.loads.
constexpr int kMaxIntDigits = 2000;

struct TokenTape {
  std::vector<uint32_t> ops;  // triplets: op, a, b
  int32_t status = ST_MALFORMED;
};

struct Parser {
  const uint8_t* s;
  size_t n;
  size_t i = 0;
  TokenTape* out;

  explicit Parser(const uint8_t* data, size_t len, TokenTape* tape)
      : s(data), n(len), out(tape) {}

  void emit(uint32_t op, uint32_t a = 0, uint32_t b = 0) {
    out->ops.push_back(op);
    out->ops.push_back(a);
    out->ops.push_back(b);
  }

  void ws() {
    while (i < n && (s[i] == ' ' || s[i] == '\t' || s[i] == '\n' ||
                     s[i] == '\r'))
      ++i;
  }

  // Scan a JSON string starting AFTER the opening quote; returns false on
  // malformed. Sets *esc when escapes are present, validates UTF-8 and
  // escape syntax (so phase 2 can decode without error paths).
  bool scan_string(uint32_t* off, uint32_t* len, uint32_t* esc, bool* fb) {
    size_t start = i;
    *esc = 0;
    while (i < n) {
      uint8_t c = s[i];
      if (c == '"') {
        *off = static_cast<uint32_t>(start);
        *len = static_cast<uint32_t>(i - start);
        ++i;
        return true;
      }
      if (c == '\\') {
        *esc = 1;
        if (i + 1 >= n) return false;
        uint8_t e = s[i + 1];
        if (e == 'u') {
          if (i + 5 >= n) return false;
          for (int k = 2; k <= 5; ++k) {
            uint8_t h = s[i + k];
            if (!((h >= '0' && h <= '9') || (h >= 'a' && h <= 'f') ||
                  (h >= 'A' && h <= 'F')))
              return false;
          }
          // Lone/paired surrogates: json.loads has precise pass-through
          // semantics for lone surrogates — route any surrogate escape
          // to the fallback rather than replicate them bug-for-bug.
          uint32_t v = 0;
          for (int k = 2; k <= 5; ++k) {
            uint8_t h = s[i + k];
            v = v * 16 + (h <= '9' ? h - '0' : (h | 32) - 'a' + 10);
          }
          if (v >= 0xD800 && v <= 0xDFFF) *fb = true;
          i += 6;
          continue;
        }
        if (e != '"' && e != '\\' && e != '/' && e != 'b' && e != 'f' &&
            e != 'n' && e != 'r' && e != 't')
          return false;
        i += 2;
        continue;
      }
      if (c < 0x20) return false;  // unescaped control char
      if (c < 0x80) {
        ++i;
        continue;
      }
      // UTF-8 validation (strict, no overlongs/surrogates) so phase 2's
      // PyUnicode_DecodeUTF8 cannot fail.
      int need;
      uint32_t cp;
      if ((c & 0xE0) == 0xC0) {
        need = 1;
        cp = c & 0x1F;
        if (cp < 2) return false;  // overlong
      } else if ((c & 0xF0) == 0xE0) {
        need = 2;
        cp = c & 0x0F;
      } else if ((c & 0xF8) == 0xF0) {
        need = 3;
        cp = c & 0x07;
      } else {
        return false;
      }
      if (i + need >= n) return false;
      for (int k = 1; k <= need; ++k) {
        uint8_t cc = s[i + k];
        if ((cc & 0xC0) != 0x80) return false;
        cp = (cp << 6) | (cc & 0x3F);
      }
      if (need == 2 && (cp < 0x800 || (cp >= 0xD800 && cp <= 0xDFFF)))
        return false;
      if (need == 3 && (cp < 0x10000 || cp > 0x10FFFF)) return false;
      i += need + 1;
    }
    return false;  // unterminated
  }

  bool parse_number(bool* fb) {
    size_t start = i;
    bool is_float = false;
    if (i < n && s[i] == '-') ++i;
    if (i >= n) return false;
    if (s[i] == '0') {
      ++i;
    } else if (s[i] >= '1' && s[i] <= '9') {
      while (i < n && s[i] >= '0' && s[i] <= '9') ++i;
    } else {
      return false;
    }
    if (i < n && s[i] == '.') {
      is_float = true;
      ++i;
      if (i >= n || s[i] < '0' || s[i] > '9') return false;
      while (i < n && s[i] >= '0' && s[i] <= '9') ++i;
    }
    if (i < n && (s[i] == 'e' || s[i] == 'E')) {
      is_float = true;
      ++i;
      if (i < n && (s[i] == '+' || s[i] == '-')) ++i;
      if (i >= n || s[i] < '0' || s[i] > '9') return false;
      while (i < n && s[i] >= '0' && s[i] <= '9') ++i;
    }
    size_t len = i - start;
    if (is_float) {
      // strtod matches json.loads (float(repr) semantics): both parse
      // the shortest round-trip; overflow → ±inf, same as json.loads.
      char buf[340];
      if (len >= sizeof(buf)) {
        *fb = true;
        return true;
      }
      std::memcpy(buf, s + start, len);
      buf[len] = 0;
      char* end = nullptr;
      double v = std::strtod(buf, &end);
      if (end != buf + len) return false;
      uint64_t bits;
      std::memcpy(&bits, &v, 8);
      emit(OP_FLOAT, static_cast<uint32_t>(bits),
           static_cast<uint32_t>(bits >> 32));
      return true;
    }
    // Integer: int64 fast path, digit-span for big ones.
    size_t digs = len - (s[start] == '-' ? 1 : 0);
    if (digs <= 18) {
      int64_t v = 0;
      size_t k = start + (s[start] == '-' ? 1 : 0);
      for (; k < i; ++k) v = v * 10 + (s[k] - '0');
      if (s[start] == '-') v = -v;
      uint64_t u = static_cast<uint64_t>(v);
      emit(OP_INT, static_cast<uint32_t>(u), static_cast<uint32_t>(u >> 32));
      return true;
    }
    if (digs > kMaxIntDigits) {
      *fb = true;
      return true;
    }
    emit(OP_BIGINT, static_cast<uint32_t>(start), static_cast<uint32_t>(len));
    return true;
  }

  // Full value parser. Returns false on malformed; sets *fb to route the
  // token to json.loads (valid JSON we choose not to replicate).
  bool parse_value(int depth, bool* fb) {
    if (depth > kMaxDepth) {
      *fb = true;
      return true;
    }
    ws();
    if (i >= n) return false;
    uint8_t c = s[i];
    switch (c) {
      case '{': {
        ++i;
        // Operand `a` of OP_OBJ_START is backpatched to the key count
        // so phase 2 can presize the dict (0 = empty or unknown).
        size_t hdr = out->ops.size();
        emit(OP_OBJ_START);
        ws();
        if (i < n && s[i] == '}') {
          ++i;
          emit(OP_OBJ_END);
          return true;
        }
        uint32_t nkeys = 0;
        while (true) {
          ws();
          if (i >= n || s[i] != '"') return false;
          ++i;
          uint32_t off, len, esc;
          if (!scan_string(&off, &len, &esc, fb)) return false;
          emit(OP_KEY, off, (len << 1) | esc);
          ++nkeys;
          ws();
          if (i >= n || s[i] != ':') return false;
          ++i;
          if (!parse_value(depth + 1, fb)) return false;
          if (*fb) return true;  // unwind: token goes to json.loads
          ws();
          if (i >= n) return false;
          if (s[i] == ',') {
            ++i;
            continue;
          }
          if (s[i] == '}') {
            ++i;
            out->ops[hdr + 1] = nkeys;
            emit(OP_OBJ_END);
            return true;
          }
          return false;
        }
      }
      case '[': {
        ++i;
        emit(OP_ARR_START);
        ws();
        if (i < n && s[i] == ']') {
          ++i;
          emit(OP_ARR_END);
          return true;
        }
        while (true) {
          if (!parse_value(depth + 1, fb)) return false;
          if (*fb) return true;  // unwind: token goes to json.loads
          ws();
          if (i >= n) return false;
          if (s[i] == ',') {
            ++i;
            continue;
          }
          if (s[i] == ']') {
            ++i;
            emit(OP_ARR_END);
            return true;
          }
          return false;
        }
      }
      case '"': {
        ++i;
        uint32_t off, len, esc;
        if (!scan_string(&off, &len, &esc, fb)) return false;
        emit(OP_STR, off, (len << 1) | esc);
        return true;
      }
      case 't':
        if (i + 4 <= n && std::memcmp(s + i, "true", 4) == 0) {
          i += 4;
          emit(OP_TRUE);
          return true;
        }
        return false;
      case 'f':
        if (i + 5 <= n && std::memcmp(s + i, "false", 5) == 0) {
          i += 5;
          emit(OP_FALSE);
          return true;
        }
        return false;
      case 'n':
        if (i + 4 <= n && std::memcmp(s + i, "null", 4) == 0) {
          i += 4;
          emit(OP_NULL);
          return true;
        }
        return false;
      case 'N':
      case 'I':
        // NaN / Infinity: json.loads accepts these by default. Rare in
        // real claims — fall back rather than replicate.
        *fb = true;
        return true;
      default:
        if (c == '-' && i + 1 < n && s[i + 1] == 'I') {
          *fb = true;  // -Infinity
          return true;
        }
        return parse_number(fb);
    }
  }

  void run() {
    bool fb = false;
    ws();
    bool is_obj = i < n && s[i] == '{';
    if (!parse_value(0, &fb)) {
      out->status = ST_MALFORMED;
      return;
    }
    if (fb) {
      out->status = ST_FALLBACK;
      return;
    }
    ws();
    if (i != n) {
      out->status = ST_MALFORMED;  // trailing garbage
      return;
    }
    out->status = is_obj ? ST_OK : ST_NOT_OBJECT;
  }
};

}  // namespace capclaims

#endif  // CAP_TPU_CLAIMS_TAPE_H_
