// frontdoor_native.cpp — the native zero-copy relay front door
// (SIXTH translation unit of libcapruntime.so).
//
// The fleet is native-speed everywhere except its own entrance: each
// worker's serve chain moves 1.4M tok/s, but the Python router in
// fleet/frontdoor.py caps every multi-pool number near 15k vps —
// the feeder starves the pipeline (the 2112.02229 shape). This TU
// moves the front door's HOT PATH to the edge:
//
//   per-connection C++ reader ──parse once (cvb1_wire.h, the exact
//   serve-chain parser)──► sha256 digest per token ──consistent-hash
//   lookup against a ring SNAPSHOT (vnode points pushed down from
//   Python on membership change)──► relay the payload bytes to the
//   owning worker's socket WITHOUT re-encoding — a single-owner plain
//   frame is spliced through verbatim; a multi-owner frame is split
//   into per-owner plain sub-frames (memcpy of the original token
//   bytes, never a re-serialize). Responses pair back FIFO per
//   upstream connection (workers answer per-conn in seq order) and
//   merge into one client response, sent in strict client-seq order
//   by the same writer-thread discipline as serve_native.cpp.
//
// Everything that needs POLICY stays in Python on the slow path,
// handed off through cap_frontdoor_drain with a reason code:
//   R_CONTROL       stats / keys push / peer fill / shm attach
//   R_DEAD_POOL     a token's hash owner tripped the breaker
//   R_OVERLOAD      owner's in-flight load exceeds spill_factor×avg
//                   (bounded-load spill decision belongs to Python)
//   R_UPSTREAM_FAIL relay connect/send/recv failed mid-frame — the
//                   WHOLE original frame re-dispatches through the
//                   Python FrontDoor (verification is idempotent;
//                   the failed.CAS guarantees exactly one response
//                   per client seq)
//   R_UNROUTED      no committed ring yet
//
// Parity contract: cap_frontdoor_probe_route exposes the EXACT
// routing decision (owner pid, or -1 when the owner is dead) for a
// batch of digests, and tests/test_frontdoor_native.py pins it
// bit-for-bit against the Python ConsistentHashRing twin — same
// stance as the DRR probe (cap_drr_*) that keeps both serve chains
// scheduling identically.
//
// Counting contract: the native fast path only ever routes a token
// to its PRIMARY live owner, so it contributes equal increments to
// lookups and affinity_hits; every spill / re-route / fallback goes
// through the Python FrontDoor which counts them itself — the exact
// fleet-wide equation lookups == affinity_hits + affinity_misses
// survives the split by construction (obs-smoke gates it).

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include "cvb1_wire.h"

// one sha256 per TU family: jose_native.cpp owns the implementation
namespace sha2 {
void sha256(const uint8_t* data, size_t len, uint8_t out[32]);
}

namespace frontdoor_native {

using namespace cvb1;

static const int DIG_LEN = 16;   // vcache.DIGEST_LEN
static const int MAX_POOLS = 64;
static const int FD_LAYOUT_VERSION = 1;

// counter slots (cap_frontdoor_counter)
enum {
  FDC_CONNS = 0,
  FDC_FRAMES = 1,
  FDC_TOKENS = 2,
  FDC_PROTO_ERR = 3,
  FDC_PONGS = 4,
  FDC_LOOKUPS = 5,
  FDC_HITS = 6,
  FDC_RELAYS = 7,        // per-owner sub-frame sends (re-framed)
  FDC_RELAY_TOKENS = 8,
  FDC_SPLICES = 9,       // whole frames forwarded verbatim
  FDC_SLOW_FRAMES = 10,
  FDC_SLOW_TOKENS = 11,
  FDC_UPSTREAM_FAILS = 12,
  FDC_SEQ_HELD_MAX = 13,  // deepest per-conn reorder queue seen
  FDC_DROPPED_POSTS = 14,
  FDC_CONNS_CLOSED = 15,
  FDC_N = 16,
};

// slow-path handoff reasons (meta[1] of cap_frontdoor_drain)
enum {
  R_CONTROL = 1,
  R_DEAD_POOL = 2,
  R_OVERLOAD = 3,
  R_UPSTREAM_FAIL = 4,
  R_UNROUTED = 5,
};

struct Endpoint {
  std::string host;  // IPv4 dotted quad, or a UDS path when port < 0
  int32_t port = 0;
};

// Immutable routing snapshot, swapped atomically on commit. Readers
// copy the shared_ptr under cfg_mu (one brief lock per frame) and
// then route lock-free against frozen vectors — a membership change
// never mutates a snapshot a reader is walking.
struct FdConfig {
  std::vector<uint64_t> pts;    // sorted ring points
  std::vector<int32_t> owners;  // owner pid per point
  std::vector<int32_t> pool_ids;
  int32_t n_pools = 0;
  double spill = 1.25;
  std::vector<Endpoint> eps[MAX_POOLS];
};

struct FdHandle;
struct FdConn;

// One in-flight client frame being relayed. Parts (per-owner
// sub-frames) resolve from different upstream-reader threads at
// DISJOINT token indices; `remaining` hits zero only when every part
// succeeded, and `failed` CAS-elects exactly one failure handler —
// between them every client seq gets exactly one response, native or
// slow-path, never both and never zero.
struct FdPending {
  std::shared_ptr<FdConn> conn;
  int64_t seq = 0;
  uint8_t ftype = 0;
  uint8_t trace_len = 0;
  char trace[MAX_TRACE_BYTES];
  int32_t n_tokens = 0;
  bool splice = false;      // single-owner plain frame: forward verbatim
  std::string orig;         // original frame bytes (slow re-dispatch)
  std::vector<uint8_t> statuses;
  std::vector<std::string> payloads;
  std::atomic<int32_t> remaining{0};
  std::atomic<int32_t> failed{0};
};

struct Part {
  std::shared_ptr<FdPending> pending;
  std::vector<int32_t> idxs;  // client-frame token indices this part covers
};

// Per-(client conn, pool) upstream connection. Sub-frames go out in
// client-frame order from the one client reader thread; the worker
// answers per-connection in seq order, so responses pair FIFO.
struct UpConn {
  int fd = -1;
  int32_t pool = -1;
  std::mutex mu;  // guards fifo
  std::deque<Part> fifo;
  std::atomic<bool> dead{false};
};

struct FdConn {
  FdHandle* h = nullptr;
  int32_t id = 0;
  int fd = -1;
  std::mutex mu;
  std::condition_variable cv;
  std::map<int64_t, std::string> outq;  // seq → encoded response frame
  int64_t next_send = 0;
  int64_t assigned = 0;  // seqs handed out by the reader (under mu)
  bool reader_done = false;
  bool dead = false;  // send failed: discard, never block
  std::atomic<int> finished{0};  // 2 = reader + writer both exited
  // lazily-created upstream connections; touched ONLY by this conn's
  // reader thread (creation/replacement) — upstream readers hold
  // their own shared_ptr
  std::shared_ptr<UpConn> ups[MAX_POOLS];
};

// Slow-path handoff record (drained by the Python FrontDoor).
struct SlowReq {
  std::shared_ptr<FdConn> conn;
  int64_t seq = 0;
  int32_t reason = 0;
  uint8_t ftype = 0;
  int32_t n_tokens = 0;
  std::string frame;  // original frame bytes, verbatim
};

struct FdHandle {
  std::mutex cfg_mu;
  std::shared_ptr<FdConfig> cfg;
  // staging area (cap_frontdoor_stage_* under cfg_mu; commit swaps)
  std::vector<uint64_t> st_pts;
  std::vector<int32_t> st_owners;
  std::vector<Endpoint> st_eps[MAX_POOLS];
  // breaker state and load: PERSISTENT across commits, so a ring
  // re-push never un-trips a breaker or forgets in-flight work
  std::atomic<int32_t> live[MAX_POOLS];
  std::atomic<int64_t> inflight[MAX_POOLS];
  std::atomic<bool> stop{false};
  std::atomic<int64_t> live_threads{0};
  std::mutex conns_mu;
  std::unordered_map<int32_t, std::shared_ptr<FdConn>> conns;
  int32_t next_id = 1;
  int sweep_tick = 0;
  // slow-path queue (consumer: the Python drain thread)
  std::mutex slow_mu;
  std::condition_variable slow_cv;
  std::deque<SlowReq*> slow;
  SlowReq* carry = nullptr;  // drained but didn't fit the caller's blob
  std::atomic<int64_t> ctr[FDC_N];

  FdHandle() {
    for (auto& c : ctr) c.store(0);
    for (auto& l : live) l.store(1);
    for (auto& f : inflight) f.store(0);
  }
};

static void enqueue_response(const std::shared_ptr<FdConn>& c, int64_t seq,
                             std::string&& data) {
  size_t depth;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    c->outq.emplace(seq, std::move(data));
    depth = c->outq.size();
    c->cv.notify_all();
  }
  // seq-reorder depth high-water mark (capstat --frontdoor)
  int64_t cur = c->h->ctr[FDC_SEQ_HELD_MAX].load(std::memory_order_relaxed);
  while ((int64_t)depth > cur &&
         !c->h->ctr[FDC_SEQ_HELD_MAX].compare_exchange_weak(
             cur, (int64_t)depth, std::memory_order_relaxed)) {
  }
}

static void to_slow(FdHandle* h, const std::shared_ptr<FdConn>& c,
                    int64_t seq, int32_t reason, uint8_t ftype,
                    int32_t n_tokens, const uint8_t* frame, int64_t len) {
  SlowReq* r = new SlowReq();
  r->conn = c;
  r->seq = seq;
  r->reason = reason;
  r->ftype = ftype;
  r->n_tokens = n_tokens;
  r->frame.assign((const char*)frame, (size_t)len);
  h->ctr[FDC_SLOW_FRAMES].fetch_add(1);
  h->ctr[FDC_SLOW_TOKENS].fetch_add(n_tokens);
  {
    std::lock_guard<std::mutex> lk(h->slow_mu);
    h->slow.push_back(r);
  }
  h->slow_cv.notify_one();
}

// Exactly-one-failure-handler: the CAS winner re-dispatches the WHOLE
// original frame through the Python slow path. Verification is
// idempotent, so a part that already verified upstream is merely
// re-verified — never answered twice (completion requires failed==0).
static void fail_part(FdHandle* h, Part& part) {
  FdPending* pd = part.pending.get();
  int32_t exp = 0;
  if (pd->failed.compare_exchange_strong(exp, 1)) {
    h->ctr[FDC_UPSTREAM_FAILS].fetch_add(1);
    to_slow(h, pd->conn, pd->seq, R_UPSTREAM_FAIL, pd->ftype,
            pd->n_tokens, (const uint8_t*)pd->orig.data(),
            (int64_t)pd->orig.size());
  }
}

// Client-shaped response from merged per-part verdicts: mirrors the
// request frame family (plain / CRC / traced, trace id echoed) —
// exactly what protocol.read_response expects from a worker.
static std::string build_resp(FdPending* pd) {
  uint8_t rt = pd->ftype == T_VERIFY_REQ ? T_VERIFY_RESP
               : pd->ftype == T_VERIFY_REQ_CRC ? T_VERIFY_RESP_CRC
                                               : T_VERIFY_RESP_TRACE;
  std::string s;
  size_t est = 9 + (size_t)pd->n_tokens * 5 + 8;
  for (const auto& pl : pd->payloads) est += pl.size();
  s.reserve(est);
  put_u32(s, MAGIC);
  s.push_back((char)rt);
  put_u32(s, (uint32_t)pd->n_tokens);
  if (rt == T_VERIFY_RESP_TRACE) {
    s.push_back((char)pd->trace_len);
    s.append(pd->trace, pd->trace_len);
  }
  for (int32_t i = 0; i < pd->n_tokens; i++) {
    s.push_back((char)pd->statuses[i]);
    put_u32(s, (uint32_t)pd->payloads[i].size());
    s += pd->payloads[i];
  }
  if (rt != T_VERIFY_RESP) append_crc(s);
  return s;
}

// ---------------------------------------------------------------------------
// upstream reader thread: one per live (client conn, pool) pair.
// Pairs worker responses FIFO with the parts this conn relayed to
// that pool, resolves them into the shared pendings, and fails every
// queued part if the upstream breaks — which is what turns a worker
// kill -9 into a slow-path re-dispatch instead of a lost submission.
// ---------------------------------------------------------------------------

static bool resolve_resp(FdHandle* h, UpConn* up, const uint8_t* base,
                         const Parsed& p) {
  Part part;
  {
    std::lock_guard<std::mutex> lk(up->mu);
    if (up->fifo.empty()) return false;  // unsolicited frame: confused peer
    part = std::move(up->fifo.front());
    up->fifo.pop_front();
  }
  h->inflight[up->pool].fetch_sub((int64_t)part.idxs.size());
  FdPending* pd = part.pending.get();
  if (p.ftype != T_VERIFY_RESP ||
      (int32_t)p.entries.size() != (int32_t)part.idxs.size()) {
    fail_part(h, part);
    return false;
  }
  if (pd->splice) {
    // single-owner plain frame: the worker's response IS the client's
    // response — forward the bytes verbatim
    if (pd->failed.load(std::memory_order_relaxed) == 0)
      enqueue_response(pd->conn, pd->seq,
                       std::string((const char*)base, (size_t)p.consumed));
    return true;
  }
  for (size_t k = 0; k < part.idxs.size(); k++) {
    const EntryRef& e = p.entries[k];
    int32_t i = part.idxs[k];
    pd->statuses[i] = e.status;
    pd->payloads[i].assign((const char*)base + e.off, (size_t)e.len);
  }
  if (pd->remaining.fetch_sub(1) == 1 &&
      pd->failed.load(std::memory_order_relaxed) == 0)
    enqueue_response(pd->conn, pd->seq, build_resp(pd));
  return true;
}

static void upstream_main(std::shared_ptr<FdConn> c,
                          std::shared_ptr<UpConn> up) {
  FdHandle* h = c->h;
  std::vector<uint8_t> buf;
  size_t start = 0;
  for (;;) {
    Parsed p;
    int st = PF_INCOMPLETE;
    if (buf.size() > start)
      st = parse_frame(buf.data() + start, (int64_t)(buf.size() - start),
                       p);
    if (st == PF_INCOMPLETE) {
      if (h->stop.load(std::memory_order_relaxed)) break;
      if (start > 0) {
        buf.erase(buf.begin(), buf.begin() + start);
        start = 0;
      }
      size_t old = buf.size();
      buf.resize(old + (1 << 16));
      ssize_t r = ::recv(up->fd, buf.data() + old, 1 << 16, 0);
      if (r <= 0) {
        buf.resize(old);
        break;
      }
      buf.resize(old + (size_t)r);
      continue;
    }
    if (st != PF_OK) break;  // corrupt upstream: sever, fail the queue
    if (!resolve_resp(h, up.get(), buf.data() + start, p)) break;
    start += (size_t)p.consumed;
    if (start == buf.size()) {
      buf.clear();
      start = 0;
    }
  }
  up->dead.store(true);
  ::close(up->fd);
  // every part still queued re-dispatches through the slow path
  for (;;) {
    Part part;
    {
      std::lock_guard<std::mutex> lk(up->mu);
      if (up->fifo.empty()) break;
      part = std::move(up->fifo.front());
      up->fifo.pop_front();
    }
    h->inflight[up->pool].fetch_sub((int64_t)part.idxs.size());
    fail_part(h, part);
  }
  h->live_threads.fetch_sub(1);
}

// Get (or re-establish) this conn's relay socket to a pool. The
// endpoint resolves from the CURRENT snapshot every time — after a
// membership change, a dead upstream reconnects to wherever the pool
// lives now. Returns null on connect failure (caller slow-paths).
static std::shared_ptr<UpConn> get_up(const std::shared_ptr<FdConn>& c,
                                      const FdConfig* cfg, int32_t pool) {
  std::shared_ptr<UpConn> up = c->ups[pool];
  if (up && !up->dead.load(std::memory_order_relaxed)) return up;
  const auto& eps = cfg->eps[pool];
  if (eps.empty()) return nullptr;
  const Endpoint& ep = eps[(size_t)c->id % eps.size()];
  int fd;
  if (ep.port >= 0) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    struct sockaddr_in addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)ep.port);
    if (::inet_pton(AF_INET, ep.host.c_str(), &addr.sin_addr) != 1 ||
        ::connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
      ::close(fd);
      return nullptr;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  } else {
    fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) return nullptr;
    struct sockaddr_un addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    std::strncpy(addr.sun_path, ep.host.c_str(),
                 sizeof(addr.sun_path) - 1);
    if (::connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
      ::close(fd);
      return nullptr;
    }
  }
  up = std::make_shared<UpConn>();
  up->fd = fd;
  up->pool = pool;
  c->ups[pool] = up;
  c->h->live_threads.fetch_add(1);
  std::thread(upstream_main, c, up).detach();
  return up;
}

// ---------------------------------------------------------------------------
// the hot path: route one verify frame
// ---------------------------------------------------------------------------

static void relay_frame(const std::shared_ptr<FdConn>& c,
                        const uint8_t* base, const Parsed& p) {
  FdHandle* h = c->h;
  int32_t n = (int32_t)p.entries.size();
  h->ctr[FDC_TOKENS].fetch_add(n);
  int64_t seq;
  {
    std::lock_guard<std::mutex> lk(c->mu);
    seq = c->assigned++;
  }
  std::shared_ptr<FdConfig> cfg;
  {
    std::lock_guard<std::mutex> lk(h->cfg_mu);
    cfg = h->cfg;
  }
  if (!cfg || cfg->pts.empty() || cfg->n_pools <= 0) {
    to_slow(h, c, seq, R_UNROUTED, p.ftype, n, base, p.consumed);
    return;
  }
  // route every token to its primary ring owner (= Python
  // ConsistentHashRing.primary: bisect_right over the same sha256
  // points — the parity pin's subject)
  std::vector<int32_t> owner_of((size_t)n);
  for (int32_t i = 0; i < n; i++) {
    uint8_t d[32];
    sha2::sha256(base + p.entries[i].off, (size_t)p.entries[i].len, d);
    uint64_t pt = 0;
    for (int k = 0; k < 8; k++) pt = (pt << 8) | d[k];
    size_t j = (size_t)(std::upper_bound(cfg->pts.begin(), cfg->pts.end(),
                                         pt) -
                        cfg->pts.begin());
    int32_t owner = cfg->owners[j % cfg->owners.size()];
    if (!h->live[owner].load(std::memory_order_relaxed)) {
      // breaker re-route is POLICY — Python decides
      to_slow(h, c, seq, R_DEAD_POOL, p.ftype, n, base, p.consumed);
      return;
    }
    owner_of[(size_t)i] = owner;
  }
  // group by owner, preserving token order within each group
  std::vector<int32_t> group_owner;
  std::vector<std::vector<int32_t>> group_idx;
  for (int32_t i = 0; i < n; i++) {
    int32_t o = owner_of[(size_t)i];
    size_t g = 0;
    for (; g < group_owner.size(); g++)
      if (group_owner[g] == o) break;
    if (g == group_owner.size()) {
      group_owner.push_back(o);
      group_idx.emplace_back();
    }
    group_idx[g].push_back(i);
  }
  // bounded-load gate: a hot owner means the SPILL decision is due,
  // and spill arithmetic (and its counters) live in Python
  int64_t sum = 0;
  for (int32_t pid : cfg->pool_ids)
    sum += h->inflight[pid].load(std::memory_order_relaxed);
  double avg = (double)(sum + n) / (double)cfg->n_pools;
  for (int32_t o : group_owner) {
    if ((double)h->inflight[o].load(std::memory_order_relaxed) >
        cfg->spill * avg) {
      to_slow(h, c, seq, R_OVERLOAD, p.ftype, n, base, p.consumed);
      return;
    }
  }
  // fast path committed: primary-owner routing for every token
  h->ctr[FDC_LOOKUPS].fetch_add(n);
  h->ctr[FDC_HITS].fetch_add(n);
  auto pd = std::make_shared<FdPending>();
  pd->conn = c;
  pd->seq = seq;
  pd->ftype = p.ftype;
  pd->n_tokens = n;
  pd->trace_len = (uint8_t)p.trace_len;
  if (p.trace_len)
    std::memcpy(pd->trace, base + p.trace_off, (size_t)p.trace_len);
  pd->orig.assign((const char*)base, (size_t)p.consumed);
  pd->splice = group_owner.size() == 1 && p.ftype == T_VERIFY_REQ;
  if (!pd->splice) {
    pd->statuses.assign((size_t)n, 1);
    pd->payloads.resize((size_t)n);
  }
  pd->remaining.store((int32_t)group_owner.size());
  for (size_t g = 0; g < group_owner.size(); g++) {
    int32_t o = group_owner[g];
    std::shared_ptr<UpConn> up = get_up(c, cfg.get(), o);
    if (!up) {
      h->ctr[FDC_UPSTREAM_FAILS].fetch_add(1);
      int32_t exp = 0;
      if (pd->failed.compare_exchange_strong(exp, 1))
        to_slow(h, c, seq, R_UPSTREAM_FAIL, p.ftype, n, base, p.consumed);
      return;  // unsent groups never resolve; failed gates the response
    }
    std::string sub;
    if (pd->splice) {
      sub.assign((const char*)base, (size_t)p.consumed);
    } else {
      put_u32(sub, MAGIC);
      sub.push_back((char)T_VERIFY_REQ);
      put_u32(sub, (uint32_t)group_idx[g].size());
      for (int32_t i : group_idx[g]) {
        put_u32(sub, (uint32_t)p.entries[i].len);
        sub.append((const char*)base + p.entries[i].off,
                   (size_t)p.entries[i].len);
      }
    }
    h->inflight[o].fetch_add((int64_t)group_idx[g].size());
    {
      std::lock_guard<std::mutex> lk(up->mu);
      up->fifo.push_back(Part{pd, group_idx[g]});
    }
    if (!send_all(up->fd, sub)) {
      // the upstream reader drains the fifo (this part included) and
      // fail_part re-dispatches the frame through the slow path
      up->dead.store(true);
      ::shutdown(up->fd, SHUT_RDWR);
      return;
    }
    if (pd->splice)
      h->ctr[FDC_SPLICES].fetch_add(1);
    else
      h->ctr[FDC_RELAYS].fetch_add(1);
    h->ctr[FDC_RELAY_TOKENS].fetch_add((int64_t)group_idx[g].size());
  }
}

// ---------------------------------------------------------------------------
// client reader / writer threads (serve_native.cpp discipline)
// ---------------------------------------------------------------------------

static void finish_conn(const std::shared_ptr<FdConn>& c) {
  if (c->finished.fetch_add(1) + 1 == 2) ::close(c->fd);
}

// One PF_OK client frame. Returns false when the connection must
// drop (wrong-direction frame).
static bool handle_frame(const std::shared_ptr<FdConn>& c,
                         const uint8_t* base, const Parsed& p) {
  FdHandle* h = c->h;
  if (p.ftype == T_PING) {
    int64_t seq;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      seq = c->assigned++;
    }
    std::string pong(9, '\0');
    uint32_t zero = 0;
    std::memcpy(&pong[0], &MAGIC, 4);
    pong[4] = (char)T_PONG;
    std::memcpy(&pong[5], &zero, 4);
    enqueue_response(c, seq, std::move(pong));
    h->ctr[FDC_PONGS].fetch_add(1);
    return true;
  }
  if (p.ftype == T_VERIFY_REQ || p.ftype == T_VERIFY_REQ_CRC ||
      p.ftype == T_VERIFY_REQ_TRACE) {
    relay_frame(c, base, p);
    return true;
  }
  if (p.ftype == T_STATS_REQ || p.ftype == T_KEYS_PUSH ||
      p.ftype == T_PEER_FILL || p.ftype == T_SHM_ATTACH) {
    // control plane is POLICY: keys fan-out, peer fill, stats merge
    // and the shm refusal all belong to the Python FrontDoor
    int64_t seq;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      seq = c->assigned++;
    }
    to_slow(h, c, seq, R_CONTROL, p.ftype, (int32_t)p.entries.size(),
            base, p.consumed);
    return true;
  }
  // a response frame from a client: confused peer, drop it
  h->ctr[FDC_PROTO_ERR].fetch_add(1);
  return false;
}

static void reader_main(std::shared_ptr<FdConn> c) {
  FdHandle* h = c->h;
  std::vector<uint8_t> buf;
  size_t start = 0;
  for (;;) {
    Parsed p;
    int st = PF_INCOMPLETE;
    if (buf.size() > start)
      st = parse_frame(buf.data() + start, (int64_t)(buf.size() - start),
                       p);
    if (st == PF_INCOMPLETE) {
      if (h->stop.load(std::memory_order_relaxed)) break;
      if (start > 0) {  // compact the consumed prefix
        buf.erase(buf.begin(), buf.begin() + start);
        start = 0;
      }
      size_t old = buf.size();
      buf.resize(old + (1 << 16));
      ssize_t r = ::recv(c->fd, buf.data() + old, 1 << 16, 0);
      if (r <= 0) {
        buf.resize(old);
        break;
      }
      buf.resize(old + (size_t)r);
      continue;
    }
    if (st != PF_OK) {
      h->ctr[FDC_PROTO_ERR].fetch_add(1);
      break;
    }
    h->ctr[FDC_FRAMES].fetch_add(1);
    if (!handle_frame(c, buf.data() + start, p)) break;
    start += (size_t)p.consumed;
    if (start == buf.size()) {
      buf.clear();
      start = 0;
    }
  }
  {
    std::lock_guard<std::mutex> lk(c->mu);
    c->reader_done = true;
    c->cv.notify_all();
  }
  // sever the relay legs so their reader threads unwind (each fails
  // its still-queued parts into the slow path on the way out)
  for (auto& up : c->ups) {
    if (up) {
      up->dead.store(true);
      ::shutdown(up->fd, SHUT_RDWR);
    }
  }
  finish_conn(c);
  h->live_threads.fetch_sub(1);
}

static void writer_main(std::shared_ptr<FdConn> c) {
  FdHandle* h = c->h;
  std::unique_lock<std::mutex> lk(c->mu);
  for (;;) {
    auto it = c->outq.find(c->next_send);
    if (it != c->outq.end()) {
      std::string data = std::move(it->second);
      c->outq.erase(it);
      c->next_send++;
      bool dead = c->dead;
      lk.unlock();
      bool sent = dead ? true : send_all(c->fd, data);
      if (!sent) {
        ::shutdown(c->fd, SHUT_RDWR);
        lk.lock();
        c->dead = true;
      } else {
        lk.lock();
      }
      continue;
    }
    if (h->stop.load(std::memory_order_relaxed)) break;
    if (c->reader_done && c->next_send >= c->assigned)
      break;  // every response this connection will ever owe is sent
    c->cv.wait_for(lk, std::chrono::milliseconds(100));
  }
  lk.unlock();
  finish_conn(c);
  h->live_threads.fetch_sub(1);
}

static void sweep_conns(FdHandle* h) {
  std::lock_guard<std::mutex> lk(h->conns_mu);
  for (auto it = h->conns.begin(); it != h->conns.end();) {
    if (it->second->finished.load() >= 2) {
      h->ctr[FDC_CONNS_CLOSED].fetch_add(1);
      it = h->conns.erase(it);
    } else {
      ++it;
    }
  }
}

}  // namespace frontdoor_native

// ---------------------------------------------------------------------------
// C ABI — probed as one optional symbol group (_FD_SYMBOLS) by
// serve/native_serve.py; a stale .so missing any of them degrades to
// the Python front door with a counted fallback.
// ---------------------------------------------------------------------------

using namespace frontdoor_native;

extern "C" {

void* cap_frontdoor_create(void) { return new FdHandle(); }

// Layout handshake: the binding refuses to arm against a .so whose
// constants drifted from the Python side's expectations.
void cap_frontdoor_layout(int32_t* out) {
  out[0] = MAX_POOLS;
  out[1] = FDC_N;
  out[2] = FD_LAYOUT_VERSION;
  out[3] = DIG_LEN;
}

// Stage a full ring snapshot (sorted points + owner pids). Resets
// the whole staging area — endpoints must be re-staged too.
int32_t cap_frontdoor_stage_ring(void* hv, const uint64_t* pts,
                                 const int32_t* owners, int64_t n) {
  FdHandle* h = (FdHandle*)hv;
  std::lock_guard<std::mutex> lk(h->cfg_mu);
  h->st_pts.assign(pts, pts + n);
  h->st_owners.assign(owners, owners + n);
  for (auto& v : h->st_eps) v.clear();
  for (int64_t i = 0; i < n; i++)
    if (owners[i] < 0 || owners[i] >= MAX_POOLS) return 1;
  return 0;
}

// Append one worker endpoint for a pool (port < 0: host is UDS path).
int32_t cap_frontdoor_stage_pool(void* hv, int32_t pool_id,
                                 const char* host, int32_t port) {
  FdHandle* h = (FdHandle*)hv;
  if (pool_id < 0 || pool_id >= MAX_POOLS) return 1;
  std::lock_guard<std::mutex> lk(h->cfg_mu);
  h->st_eps[pool_id].push_back(Endpoint{std::string(host), port});
  return 0;
}

// Publish the staged snapshot. Readers pick it up on their next
// frame; in-flight relays finish against the old one.
int32_t cap_frontdoor_commit(void* hv, int32_t n_pools, double spill) {
  FdHandle* h = (FdHandle*)hv;
  auto cfg = std::make_shared<FdConfig>();
  std::lock_guard<std::mutex> lk(h->cfg_mu);
  cfg->pts = h->st_pts;
  cfg->owners = h->st_owners;
  cfg->n_pools = n_pools;
  cfg->spill = spill > 0 ? spill : 1.25;
  for (int i = 0; i < MAX_POOLS; i++) cfg->eps[i] = h->st_eps[i];
  for (int32_t o : cfg->owners) {
    bool seen = false;
    for (int32_t pid : cfg->pool_ids) seen = seen || pid == o;
    if (!seen) cfg->pool_ids.push_back(o);
  }
  h->cfg = cfg;
  return 0;
}

// Breaker push-down: Python's _PoolArm.live() projected into the
// native fast path. Persistent across commits.
void cap_frontdoor_set_live(void* hv, int32_t pool_id, int32_t live) {
  FdHandle* h = (FdHandle*)hv;
  if (pool_id < 0 || pool_id >= MAX_POOLS) return;
  h->live[pool_id].store(live ? 1 : 0, std::memory_order_relaxed);
}

int32_t cap_frontdoor_add_conn(void* hv, int32_t fd) {
  FdHandle* h = (FdHandle*)hv;
  if (h->stop.load()) return -1;
  auto c = std::make_shared<FdConn>();
  c->h = h;
  c->fd = fd;
  {
    std::lock_guard<std::mutex> lk(h->conns_mu);
    c->id = h->next_id++;
    h->conns[c->id] = c;
  }
  h->ctr[FDC_CONNS].fetch_add(1);
  h->live_threads.fetch_add(2);
  std::thread(reader_main, c).detach();
  std::thread(writer_main, c).detach();
  if (++h->sweep_tick % 64 == 0) sweep_conns(h);
  return c->id;
}

// Drain slow-path frames for the Python FrontDoor. Returns the frame
// count (0 on timeout, -1 once stopped), or -2 when the FIRST frame
// exceeds blob_cap — out_need[0] then holds the required size and the
// frame carries to the next call (grow-and-retry, like serve drain).
// Layout: blob holds the frames back to back, frame_off[0..n] their
// boundaries, meta stride 4 = (conn_id, reason, ftype, n_tokens),
// seqs the per-conn response slots for cap_frontdoor_post_raw.
int32_t cap_frontdoor_drain(void* hv, double wait_s, uint8_t* blob,
                            int64_t blob_cap, int64_t* frame_off,
                            int32_t* meta, int64_t* seqs,
                            int32_t max_frames, int64_t* out_need) {
  FdHandle* h = (FdHandle*)hv;
  std::unique_lock<std::mutex> lk(h->slow_mu);
  if (!h->carry && h->slow.empty()) {
    if (h->stop.load()) return -1;
    h->slow_cv.wait_for(lk, std::chrono::duration<double>(wait_s));
    if (!h->carry && h->slow.empty()) return h->stop.load() ? -1 : 0;
  }
  int32_t nf = 0;
  int64_t used = 0;
  frame_off[0] = 0;
  while (nf < max_frames) {
    SlowReq* r = h->carry ? h->carry
                 : h->slow.empty() ? nullptr
                                   : h->slow.front();
    if (!r) break;
    if (used + (int64_t)r->frame.size() > blob_cap) {
      if (nf == 0) {
        if (!h->carry) {
          h->carry = r;
          h->slow.pop_front();
        }
        if (out_need) out_need[0] = (int64_t)r->frame.size();
        return -2;
      }
      break;
    }
    if (h->carry)
      h->carry = nullptr;
    else
      h->slow.pop_front();
    std::memcpy(blob + used, r->frame.data(), r->frame.size());
    used += (int64_t)r->frame.size();
    frame_off[nf + 1] = used;
    meta[nf * 4 + 0] = r->conn->id;
    meta[nf * 4 + 1] = r->reason;
    meta[nf * 4 + 2] = (int32_t)r->ftype;
    meta[nf * 4 + 3] = r->n_tokens;
    seqs[nf] = r->seq;
    delete r;
    nf++;
  }
  return nf;
}

// Post one pre-encoded response frame (built by the Python slow path)
// at a drained request's (conn, seq) slot.
int32_t cap_frontdoor_post_raw(void* hv, int32_t conn_id, int64_t seq,
                               const uint8_t* data, int64_t len) {
  FdHandle* h = (FdHandle*)hv;
  std::shared_ptr<FdConn> c;
  {
    std::lock_guard<std::mutex> lk(h->conns_mu);
    auto it = h->conns.find(conn_id);
    if (it != h->conns.end()) c = it->second;
  }
  if (!c) {
    h->ctr[FDC_DROPPED_POSTS].fetch_add(1);
    return 1;
  }
  enqueue_response(c, seq, std::string((const char*)data, (size_t)len));
  return 0;
}

int64_t cap_frontdoor_counter(void* hv, int32_t which) {
  FdHandle* h = (FdHandle*)hv;
  if (which < 0 || which >= FDC_N) return 0;
  return h->ctr[which].load(std::memory_order_relaxed);
}

int64_t cap_frontdoor_inflight(void* hv, int32_t pool_id) {
  FdHandle* h = (FdHandle*)hv;
  if (pool_id < 0 || pool_id >= MAX_POOLS) return 0;
  return h->inflight[pool_id].load(std::memory_order_relaxed);
}

// The parity pin: the exact owner decision the relay fast path makes
// for each 16-byte token digest — owner pid, or -1 when the owner's
// breaker is open (the frame would slow-path to Python). Pinned
// bit-for-bit against the Python ConsistentHashRing twin.
int32_t cap_frontdoor_probe_route(void* hv, const uint8_t* digests,
                                  int32_t n, int32_t* out) {
  FdHandle* h = (FdHandle*)hv;
  std::shared_ptr<FdConfig> cfg;
  {
    std::lock_guard<std::mutex> lk(h->cfg_mu);
    cfg = h->cfg;
  }
  if (!cfg || cfg->pts.empty()) {
    for (int32_t i = 0; i < n; i++) out[i] = -1;
    return 0;
  }
  for (int32_t i = 0; i < n; i++) {
    const uint8_t* d = digests + (int64_t)i * DIG_LEN;
    uint64_t pt = 0;
    for (int k = 0; k < 8; k++) pt = (pt << 8) | d[k];
    size_t j = (size_t)(std::upper_bound(cfg->pts.begin(), cfg->pts.end(),
                                         pt) -
                        cfg->pts.begin());
    int32_t owner = cfg->owners[j % cfg->owners.size()];
    out[i] =
        h->live[owner].load(std::memory_order_relaxed) ? owner : -1;
  }
  return n;
}

// Shutdown: wake everything, sever every client connection (upstream
// legs cascade from their readers), bounded-join, then free — or
// deliberately leak when a wedged thread makes a free unsafe.
void cap_frontdoor_destroy(void* hv) {
  FdHandle* h = (FdHandle*)hv;
  h->stop.store(true);
  {
    std::lock_guard<std::mutex> lk(h->slow_mu);
    h->slow_cv.notify_all();
  }
  std::vector<std::shared_ptr<FdConn>> cs;
  {
    std::lock_guard<std::mutex> lk(h->conns_mu);
    for (auto& kv : h->conns) cs.push_back(kv.second);
  }
  for (auto& c : cs) {
    ::shutdown(c->fd, SHUT_RDWR);
    std::lock_guard<std::mutex> lk(c->mu);
    c->cv.notify_all();
  }
  bool all = false;
  for (int i = 0; i < 500 && !all; i++) {
    all = h->live_threads.load() == 0;
    if (!all) ::usleep(10000);
  }
  {
    std::lock_guard<std::mutex> lk(h->slow_mu);
    for (SlowReq* r : h->slow) delete r;
    h->slow.clear();
    if (h->carry) {
      delete h->carry;
      h->carry = nullptr;
    }
  }
  if (all) delete h;
  // else: leak — a reader thread may still touch the handle
}

}  // extern "C"
