// shm_ring — mmap'd SPSC byte-ring pair for the zero-copy CVB1
// transport (see shm_ring.h for the layout contract).
//
// Safety stance, mirrored from the socket chain's parser hardening:
// every cursor and length is validated BEFORE any byte of the record
// is touched, a producer killed mid-write can never publish a torn
// record (payload first, release-store of head last), and anything a
// hostile or corrupt client CAN make visible — an overrun cursor, an
// impossible length, a foreign generation stamp — maps onto the same
// malformed classes the socket parser raises, so the worker drops the
// transport instead of serving a wrong byte.
//
// The extern "C" surface at the bottom exists for three callers: the
// Python binding's tests (create/open/probe/read/write), the
// native-build symbol gate, and cap_shm_drive — the shm analog of
// cap_bench_drive, a closed-loop load driver that attaches over a
// socket and then drives the rings entirely from C threads so
// tools/bench_stages.py's transport column measures the WORKER, not a
// Python client.

#include "shm_ring.h"

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

namespace cap_shm {

static inline std::atomic<uint64_t>* cursor(Region* r, uint64_t off) {
  return reinterpret_cast<std::atomic<uint64_t>*>(r->base + off);
}

static inline uint64_t head_off(int ring) {
  return ring == RING_REQ ? OFF_REQ_HEAD : OFF_RESP_HEAD;
}

static inline uint64_t tail_off(int ring) {
  return ring == RING_REQ ? OFF_REQ_TAIL : OFF_RESP_TAIL;
}

static bool pow2_in_bounds(uint64_t v) {
  return v >= MIN_RING && v <= MAX_RING && (v & (v - 1)) == 0;
}

static void put_u64(uint8_t* b, uint64_t off, uint64_t v) {
  std::memcpy(b + off, &v, 8);
}

static void put_u32f(uint8_t* b, uint64_t off, uint32_t v) {
  std::memcpy(b + off, &v, 4);
}

static uint64_t get_u64(const uint8_t* b, uint64_t off) {
  uint64_t v;
  std::memcpy(&v, b + off, 8);
  return v;
}

static uint32_t get_u32f(const uint8_t* b, uint64_t off) {
  uint32_t v;
  std::memcpy(&v, b + off, 4);
  return v;
}

Region* create_region(const char* path, uint64_t req_size,
                      uint64_t resp_size, uint32_t gen) {
  if (!pow2_in_bounds(req_size) || !pow2_in_bounds(resp_size) ||
      gen == 0 || std::strlen(path) >= sizeof(Region::path))
    return nullptr;
  int fd = ::open(path, O_RDWR | O_CREAT | O_TRUNC, 0600);
  if (fd < 0) return nullptr;
  uint64_t total = HDR_SIZE + req_size + resp_size;
  if (::ftruncate(fd, (off_t)total) != 0) {
    ::close(fd);
    ::unlink(path);
    return nullptr;
  }
  void* m = ::mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  ::close(fd);
  if (m == MAP_FAILED) {
    ::unlink(path);
    return nullptr;
  }
  Region* r = new Region();
  r->base = (uint8_t*)m;
  r->map_len = total;
  r->ring_off[RING_REQ] = HDR_SIZE;
  r->ring_size[RING_REQ] = req_size;
  r->ring_off[RING_RESP] = HDR_SIZE + req_size;
  r->ring_size[RING_RESP] = resp_size;
  r->gen = gen;
  std::strncpy(r->path, path, sizeof(r->path) - 1);
  uint8_t* b = r->base;
  put_u32f(b, OFF_VERSION, VERSION);
  put_u32f(b, OFF_GEN, gen);
  put_u64(b, OFF_REQ_OFF, HDR_SIZE);
  put_u64(b, OFF_REQ_SIZE, req_size);
  put_u64(b, OFF_RESP_OFF, HDR_SIZE + req_size);
  put_u64(b, OFF_RESP_SIZE, resp_size);
  // magic LAST: a reader that races the create never sees a
  // half-initialized header behind a valid magic
  std::atomic_thread_fence(std::memory_order_release);
  put_u64(b, OFF_MAGIC, MAGIC);
  return r;
}

static int validate_header(const uint8_t* b, uint64_t file_len,
                           char* err, size_t err_len) {
  if (get_u64(b, OFF_MAGIC) != MAGIC) {
    if (err) std::snprintf(err, err_len, "bad shm magic");
    return 1;
  }
  if (get_u32f(b, OFF_VERSION) != VERSION) {
    if (err) std::snprintf(err, err_len, "unsupported shm version");
    return 1;
  }
  if (get_u32f(b, OFF_GEN) == 0) {
    if (err) std::snprintf(err, err_len, "zero generation");
    return 1;
  }
  uint64_t req_off = get_u64(b, OFF_REQ_OFF);
  uint64_t req_size = get_u64(b, OFF_REQ_SIZE);
  uint64_t resp_off = get_u64(b, OFF_RESP_OFF);
  uint64_t resp_size = get_u64(b, OFF_RESP_SIZE);
  if (!pow2_in_bounds(req_size) || !pow2_in_bounds(resp_size)) {
    if (err) std::snprintf(err, err_len, "ring size out of bounds");
    return 2;
  }
  if (req_off != HDR_SIZE || resp_off != HDR_SIZE + req_size ||
      file_len < HDR_SIZE + req_size + resp_size) {
    if (err) std::snprintf(err, err_len, "ring offsets inconsistent");
    return 1;
  }
  return 0;
}

Region* map_region(const char* path, char* err, size_t err_len) {
  if (err && err_len) err[0] = '\0';
  if (std::strlen(path) >= sizeof(Region::path)) {
    if (err) std::snprintf(err, err_len, "path too long");
    return nullptr;
  }
  int fd = ::open(path, O_RDWR);
  if (fd < 0) {
    if (err) std::snprintf(err, err_len, "open failed: %d", errno);
    return nullptr;
  }
  struct stat st;
  if (::fstat(fd, &st) != 0 || (uint64_t)st.st_size < HDR_SIZE ||
      (uint64_t)st.st_size > HDR_SIZE + 2 * MAX_RING) {
    ::close(fd);
    if (err) std::snprintf(err, err_len, "bad region file size");
    return nullptr;
  }
  void* m = ::mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  ::close(fd);
  if (m == MAP_FAILED) {
    if (err) std::snprintf(err, err_len, "mmap failed: %d", errno);
    return nullptr;
  }
  const uint8_t* b = (const uint8_t*)m;
  if (validate_header(b, (uint64_t)st.st_size, err, err_len) != 0) {
    ::munmap(m, (size_t)st.st_size);
    return nullptr;
  }
  Region* r = new Region();
  r->base = (uint8_t*)m;
  r->map_len = (uint64_t)st.st_size;
  r->ring_off[RING_REQ] = get_u64(b, OFF_REQ_OFF);
  r->ring_size[RING_REQ] = get_u64(b, OFF_REQ_SIZE);
  r->ring_off[RING_RESP] = get_u64(b, OFF_RESP_OFF);
  r->ring_size[RING_RESP] = get_u64(b, OFF_RESP_SIZE);
  r->gen = get_u32f(b, OFF_GEN);
  std::strncpy(r->path, path, sizeof(r->path) - 1);
  return r;
}

void close_region(Region* r, bool unlink_file) {
  if (!r) return;
  if (r->base) ::munmap(r->base, (size_t)r->map_len);
  if (unlink_file) ::unlink(r->path);
  delete r;
}

int32_t probe_region(const char* path) {
  char err[128];
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return 1;
  struct stat st;
  if (::fstat(fd, &st) != 0 || (uint64_t)st.st_size < HDR_SIZE) {
    ::close(fd);
    return 1;
  }
  uint8_t hdr[HDR_SIZE];
  ssize_t n = ::read(fd, hdr, sizeof(hdr));
  ::close(fd);
  if (n != (ssize_t)sizeof(hdr)) return 1;
  return validate_header(hdr, (uint64_t)st.st_size, err, sizeof(err));
}

uint64_t max_record(const Region* r, int ring) {
  // a record must fit the ring with headroom for one wrap marker
  return r->ring_size[ring] / 2;
}

int poll_record(Region* r, int ring, const uint8_t** data,
                uint64_t* len) {
  uint64_t size = r->ring_size[ring];
  uint8_t* buf = r->base + r->ring_off[ring];
  for (;;) {
    uint64_t head = cursor(r, head_off(ring))
                        ->load(std::memory_order_acquire);
    uint64_t tail = cursor(r, tail_off(ring))
                        ->load(std::memory_order_relaxed);
    if (head == tail) return SHM_EMPTY;
    if (head - tail > size || (tail & 7) != 0)
      return SHM_MALFORMED;  // cursor overran the ring (or torn state)
    uint64_t off = tail & (size - 1);
    if (head - tail < 8) return SHM_MALFORMED;
    uint32_t rec_len = get_u32f(buf, off);
    uint32_t rec_gen = get_u32f(buf, off + 4);
    if (rec_len == WRAP) {
      if (rec_gen != get_u32f(r->base, OFF_GEN))
        return SHM_STALE_GEN;
      uint64_t skip = size - off;  // jump to the ring start
      if (head - tail < skip) return SHM_MALFORMED;
      cursor(r, tail_off(ring))
          ->store(tail + skip, std::memory_order_release);
      continue;
    }
    if ((uint64_t)rec_len > size / 2) return SHM_TOOLARGE;
    uint64_t adv = 8 + (((uint64_t)rec_len + 7) & ~7ull);
    if (adv > size - off || head - tail < adv)
      return SHM_MALFORMED;  // record claims bytes not published
    if (rec_gen != get_u32f(r->base, OFF_GEN)) return SHM_STALE_GEN;
    *data = buf + off + 8;
    *len = rec_len;
    return SHM_RECORD;
  }
}

void consume_record(Region* r, int ring) {
  uint64_t size = r->ring_size[ring];
  uint8_t* buf = r->base + r->ring_off[ring];
  uint64_t tail = cursor(r, tail_off(ring))
                      ->load(std::memory_order_relaxed);
  uint64_t off = tail & (size - 1);
  uint32_t rec_len = get_u32f(buf, off);
  uint64_t adv = 8 + (((uint64_t)rec_len + 7) & ~7ull);
  cursor(r, tail_off(ring))
      ->store(tail + adv, std::memory_order_release);
}

int write_record(Region* r, int ring, const uint8_t* data,
                 uint64_t len, AbortFn abort, void* ctx) {
  uint64_t size = r->ring_size[ring];
  uint8_t* buf = r->base + r->ring_off[ring];
  if (len > size / 2) return SHM_TOOLARGE;
  uint64_t adv = 8 + ((len + 7) & ~7ull);
  int spins = 0;
  for (;;) {
    uint64_t head = cursor(r, head_off(ring))
                        ->load(std::memory_order_relaxed);
    uint64_t tail = cursor(r, tail_off(ring))
                        ->load(std::memory_order_acquire);
    uint64_t off = head & (size - 1);
    uint64_t wrap_skip = (size - off < adv) ? size - off : 0;
    if (size - (head - tail) >= wrap_skip + adv) {
      if (wrap_skip) {
        put_u32f(buf, off, WRAP);
        put_u32f(buf, off + 4, r->gen);
        head += wrap_skip;
        off = 0;
        // publish the marker so a consumer mid-ring can progress
        cursor(r, head_off(ring))
            ->store(head, std::memory_order_release);
      }
      put_u32f(buf, off, (uint32_t)len);
      put_u32f(buf, off + 4, r->gen);
      if (len) std::memcpy(buf + off + 8, data, (size_t)len);
      cursor(r, head_off(ring))
          ->store(head + adv, std::memory_order_release);
      return 0;
    }
    if (abort && abort(ctx)) return SHM_ABORTED;
    if (++spins < 64)
      std::this_thread::yield();
    else
      ::usleep(spins < 256 ? 50 : 500);
  }
}

// ---------------------------------------------------------------------------
// native closed-loop shm load driver (tools/bench_stages.py transport
// column): attach over the socket, then drive pipelined plain verify
// frames through the rings entirely in C threads.
// ---------------------------------------------------------------------------

static uint32_t drv_crc_table[256];
static bool drv_crc_init = []() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++)
      c = (c & 1) ? (0xEDB88320u ^ (c >> 1)) : (c >> 1);
    drv_crc_table[i] = c;
  }
  return true;
}();

static uint32_t drv_crc32(uint32_t crc, const uint8_t* p, size_t n) {
  crc ^= 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    crc = drv_crc_table[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

static const uint32_t CVB1_MAGIC = 0x31425643;
static const uint8_t T_VERIFY_REQ = 1;
static const uint8_t T_VERIFY_RESP = 2;
static const uint8_t T_SHM_ATTACH = 15;
static const uint8_t T_SHM_ACK = 16;

static void put_u32s(std::string& s, uint32_t v) {
  s.append((const char*)&v, 4);
}

static std::string attach_frame(const std::string& path) {
  // canonical payload: sorted keys + compact separators, exactly what
  // protocol.shm_attach_payload emits
  std::string payload =
      "{\"op\":\"attach\",\"path\":\"" + path + "\",\"version\":1}";
  std::string f;
  put_u32s(f, CVB1_MAGIC);
  f.push_back((char)T_SHM_ATTACH);
  put_u32s(f, 1);
  put_u32s(f, (uint32_t)payload.size());
  f += payload;
  put_u32s(f, drv_crc32(0, (const uint8_t*)f.data(), f.size()));
  return f;
}

static bool send_all_fd(int fd, const std::string& data) {
  const char* p = data.data();
  size_t left = data.size();
  while (left) {
    ssize_t w = ::send(fd, p, left, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return false;
    }
    p += w;
    left -= (size_t)w;
  }
  return true;
}

static bool recv_exact(int fd, uint8_t* out, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::recv(fd, out + got, n - got, 0);
    if (r <= 0) {
      if (r < 0 && errno == EINTR) continue;
      return false;
    }
    got += (size_t)r;
  }
  return true;
}

// read one SHM ack (type 16, one entry) off the socket; returns the
// status byte or -1 on transport/parse failure
static int read_shm_ack(int fd) {
  uint8_t hdr[9];
  if (!recv_exact(fd, hdr, 9)) return -1;
  uint32_t magic, count;
  std::memcpy(&magic, hdr, 4);
  std::memcpy(&count, hdr + 5, 4);
  if (magic != CVB1_MAGIC || hdr[4] != T_SHM_ACK || count != 1)
    return -1;
  uint8_t ehdr[5];
  if (!recv_exact(fd, ehdr, 5)) return -1;
  uint32_t ln;
  std::memcpy(&ln, ehdr + 1, 4);
  if (ln > (1u << 20)) return -1;
  std::vector<uint8_t> payload(ln ? ln : 1);
  if (ln && !recv_exact(fd, payload.data(), ln)) return -1;
  uint8_t crc[4];
  if (!recv_exact(fd, crc, 4)) return -1;
  return ehdr[0];
}

struct ShmDriveShared {
  std::atomic<int64_t> tokens{0};
  std::atomic<int64_t> reqs{0};
  std::atomic<int32_t> errors{0};
  std::atomic<bool> stop{false};
};

struct DriveAbort {
  ShmDriveShared* sh;
  std::chrono::steady_clock::time_point until;  // dead-worker bound
};

static bool drive_abort(void* ctx) {
  DriveAbort* a = (DriveAbort*)ctx;
  return a->sh->stop.load(std::memory_order_relaxed) ||
         std::chrono::steady_clock::now() > a->until;
}

static void shm_drive_one(const char* host, int32_t port,
                          const char* shm_dir, const uint8_t* blob,
                          const int64_t* offs, int32_t n_tokens,
                          int32_t req_tokens, int32_t depth,
                          double seconds, int64_t ring_bytes,
                          uint32_t seed, ShmDriveShared* sh) {
  int fd;
  if (port >= 0) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) { sh->errors.fetch_add(1); return; }
    struct sockaddr_in addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
        ::connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
      ::close(fd);
      sh->errors.fetch_add(1);
      return;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  } else {
    fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) { sh->errors.fetch_add(1); return; }
    struct sockaddr_un addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    std::strncpy(addr.sun_path, host, sizeof(addr.sun_path) - 1);
    if (::connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
      ::close(fd);
      sh->errors.fetch_add(1);
      return;
    }
  }
  // unique per ATTEMPT, not just per connection slot: the worker
  // unlinks a detached region asynchronously, so reusing a path
  // across back-to-back drives (warmup → measured run) would race
  // the janitor and lose the fresh file
  static std::atomic<uint32_t> attempt{0};
  char path[400];
  std::snprintf(path, sizeof(path), "%s/cap-shm-drive-%d-%u-%u",
                shm_dir, (int)::getpid(), seed,
                attempt.fetch_add(1));
  uint64_t rb = ring_bytes > 0 ? (uint64_t)ring_bytes : (1ull << 20);
  uint64_t sz = MIN_RING;
  while (sz < rb && sz < MAX_RING) sz <<= 1;
  Region* r = create_region(path, sz, sz, 0x1000u + seed);
  if (!r) {
    ::close(fd);
    sh->errors.fetch_add(1);
    return;
  }
  if (!send_all_fd(fd, attach_frame(path)) || read_shm_ack(fd) != 0) {
    close_region(r, true);
    ::close(fd);
    sh->errors.fetch_add(1);
    return;
  }
  // pre-encode distinct plain request frames, reused round-robin —
  // exactly cap_bench_drive's shape, so the transport A/B compares
  // rings vs sockets on identical frames
  std::vector<std::string> frames;
  uint32_t rng = seed * 2654435761u + 12345u;
  for (int v = 0; v < 16; v++) {
    rng = rng * 1103515245u + 12345u;
    int32_t lo = (int32_t)(rng % (uint32_t)(n_tokens > req_tokens
                                                ? n_tokens - req_tokens
                                                : 1));
    std::string f;
    put_u32s(f, CVB1_MAGIC);
    f.push_back((char)T_VERIFY_REQ);
    put_u32s(f, (uint32_t)req_tokens);
    for (int32_t j = 0; j < req_tokens; j++) {
      int64_t o = offs[lo + j], e = offs[lo + j + 1];
      put_u32s(f, (uint32_t)(e - o));
      f.append((const char*)(blob + o), (size_t)(e - o));
    }
    frames.push_back(std::move(f));
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration_cast<
                      std::chrono::steady_clock::duration>(
                      std::chrono::duration<double>(seconds));
  DriveAbort ab{sh, deadline + std::chrono::seconds(10)};
  int inflight = 0;
  size_t next = 0;
  bool ok = true;
  for (;;) {
    bool in_window = std::chrono::steady_clock::now() < deadline;
    while (ok && in_window && inflight < depth) {
      const std::string& f = frames[next++ % frames.size()];
      int wr = write_record(r, RING_REQ, (const uint8_t*)f.data(),
                            f.size(), drive_abort, &ab);
      if (wr != 0) { ok = false; break; }
      inflight++;
    }
    if (!inflight || !ok) break;
    // consume one response record
    const uint8_t* rec;
    uint64_t len;
    int spins = 0;
    for (;;) {
      int st = poll_record(r, RING_RESP, &rec, &len);
      if (st == SHM_RECORD) break;
      if (st != SHM_EMPTY || sh->stop.load() ||
          std::chrono::steady_clock::now() > ab.until) {
        if (::getenv("CAP_SHM_DRIVE_DEBUG")) {
          // post-mortem cursor dump (the probe that caught CPython's
          // pack_into zero-fill transit — see shm_ring.py set_cursor)
          std::fprintf(
              stderr,
              "cap_shm_drive[%u]: resp poll st=%d req=%llu/%llu "
              "resp=%llu/%llu\n", seed, st,
              (unsigned long long)cursor(r, OFF_REQ_HEAD)->load(),
              (unsigned long long)cursor(r, OFF_REQ_TAIL)->load(),
              (unsigned long long)cursor(r, OFF_RESP_HEAD)->load(),
              (unsigned long long)cursor(r, OFF_RESP_TAIL)->load());
        }
        ok = false;
        break;
      }
      if (++spins < 64)
        std::this_thread::yield();
      else
        ::usleep(50);
    }
    if (!ok) break;
    if (len >= 9 && rec[4] == T_VERIFY_RESP) {
      uint32_t count;
      std::memcpy(&count, rec + 5, 4);
      if (in_window) {
        sh->tokens.fetch_add((int64_t)count);
        sh->reqs.fetch_add(1);
      }
    } else {
      if (::getenv("CAP_SHM_DRIVE_DEBUG"))
        std::fprintf(stderr, "cap_shm_drive[%u]: bad resp record "
                     "len=%llu type=%d\n", seed,
                     (unsigned long long)len, len ? rec[4] : -1);
      ok = false;
    }
    consume_record(r, RING_RESP);
    inflight--;
    if (!in_window && inflight == 0) break;
  }
  ::close(fd);
  close_region(r, true);
  if (!ok) sh->errors.fetch_add(1);
}

}  // namespace cap_shm

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

using namespace cap_shm;

extern "C" {

void* cap_shm_create(const char* path, int64_t req_size,
                     int64_t resp_size, int32_t gen) {
  return create_region(path, (uint64_t)req_size, (uint64_t)resp_size,
                       (uint32_t)gen);
}

void* cap_shm_open(const char* path) {
  char err[128];
  return map_region(path, err, sizeof(err));
}

void cap_shm_close(void* r, int32_t unlink_file) {
  close_region((Region*)r, unlink_file != 0);
}

int32_t cap_shm_probe(const char* path) { return probe_region(path); }

// Test hook: blocking-with-timeout write of one record.
// 0 ok, SHM_TOOLARGE, SHM_ABORTED (timeout).
struct _Deadline {
  std::chrono::steady_clock::time_point until;
};

static bool _deadline_abort(void* ctx) {
  return std::chrono::steady_clock::now() > ((_Deadline*)ctx)->until;
}

int64_t cap_shm_write(void* rv, int32_t ring, const uint8_t* data,
                      int64_t len, double timeout_s) {
  _Deadline d{std::chrono::steady_clock::now() +
              std::chrono::duration_cast<
                  std::chrono::steady_clock::duration>(
                  std::chrono::duration<double>(timeout_s))};
  return write_record((Region*)rv, ring, data, (uint64_t)len,
                      _deadline_abort, &d);
}

// Test hook: copy the next record of `ring` into out (cap bytes).
// >0 = record length, SHM_EMPTY on timeout, <0 = poisoned ring.
int64_t cap_shm_read(void* rv, int32_t ring, uint8_t* out,
                     int64_t cap, double timeout_s) {
  Region* r = (Region*)rv;
  auto until = std::chrono::steady_clock::now() +
               std::chrono::duration_cast<
                   std::chrono::steady_clock::duration>(
                   std::chrono::duration<double>(timeout_s));
  for (;;) {
    const uint8_t* data;
    uint64_t len;
    int st = poll_record(r, ring, &data, &len);
    if (st == SHM_RECORD) {
      if ((int64_t)len > cap) return SHM_TOOLARGE;
      std::memcpy(out, data, (size_t)len);
      consume_record(r, ring);
      return (int64_t)len;
    }
    if (st != SHM_EMPTY) return st;
    if (std::chrono::steady_clock::now() > until) return SHM_EMPTY;
    ::usleep(100);
  }
}

// Closed-loop shm load driver (the cap_bench_drive analog): each conn
// attaches its own region under shm_dir and pipelines plain verify
// frames through it. port >= 0 → TCP host:port; port < 0 → host is a
// UDS path. Returns 0 when every connection finished cleanly.
int32_t cap_shm_drive(const char* host, int32_t port,
                      const char* shm_dir, const uint8_t* blob,
                      const int64_t* offs, int32_t n_tokens,
                      int32_t req_tokens, int32_t depth,
                      double seconds, int32_t n_conns,
                      int64_t ring_bytes, int64_t* out_tokens,
                      int64_t* out_reqs) {
  ShmDriveShared sh;
  std::vector<std::thread> threads;
  for (int32_t i = 0; i < (n_conns > 0 ? n_conns : 1); i++)
    threads.emplace_back(shm_drive_one, host, port, shm_dir, blob,
                         offs, n_tokens, req_tokens, depth, seconds,
                         ring_bytes, (uint32_t)(i + 1), &sh);
  for (auto& t : threads) t.join();
  if (out_tokens) *out_tokens = sh.tokens.load();
  if (out_reqs) *out_reqs = sh.reqs.load();
  return sh.errors.load() ? -1 : 0;
}

}  // extern "C"
