// shm_ring — shared-memory CVB1 transport region (fifth TU of
// libcapruntime.so). Declarations shared with serve_native.cpp, which
// consumes request frames straight from the mapped region (zero recv,
// zero copy before the Req blob) and posts responses into the paired
// response ring.
//
// REGION LAYOUT (one file per connection, created by the CLIENT; all
// integers little-endian; the header is one page so the rings start
// page-aligned). cap_tpu/serve/shm_ring.py mirrors these constants —
// the Python client/server and the Go client speak the same bytes.
//
//   off 0    magic     u64   "CAPSHMR1" (0x31524D4853504143)
//   off 8    version   u32   1
//   off 12   gen       u32   client generation stamp (nonzero);
//                            every record carries it — a record from
//                            another generation is STALE and rejected
//   off 16   req_off   u64   = HDR_SIZE
//   off 24   req_size  u64   power of two, [MIN_RING, MAX_RING]
//   off 32   resp_off  u64   = HDR_SIZE + req_size
//   off 40   resp_size u64   power of two, [MIN_RING, MAX_RING]
//   off 64   req_head  u64   request-ring producer cursor (client)
//   off 128  req_tail  u64   request-ring consumer cursor (worker)
//   off 192  resp_head u64   response-ring producer cursor (worker)
//   off 256  resp_tail u64   response-ring consumer cursor (client)
//
// Head/tail are monotonically increasing BYTE counters (offset =
// cursor & (size-1)); each lives alone on its own cache line. Records
// are 8-byte aligned: [len u32][gen u32][payload…pad]. len=0xFFFFFFFF
// is a WRAP marker: the producer could not fit the record before the
// ring's end and skipped to offset 0 — the consumer advances its
// cursor by the same amount. The producer writes payload bytes FIRST
// and publishes with a release store of head, so a producer killed
// mid-write (kill -9) leaves the record invisible: the consumer can
// never observe a torn frame. What it CAN observe — a cursor pushed
// past the ring size, an impossible length, a foreign generation — is
// classified exactly like the socket parser's malformed classes.
#ifndef CAP_SHM_RING_H
#define CAP_SHM_RING_H

#include <atomic>
#include <cstddef>
#include <cstdint>

namespace cap_shm {

static const uint64_t MAGIC = 0x31524D4853504143ull;  // "CAPSHMR1"
static const uint32_t VERSION = 1;
static const uint64_t HDR_SIZE = 4096;
static const uint64_t MIN_RING = 4096;
static const uint64_t MAX_RING = 1ull << 30;
static const uint32_t WRAP = 0xFFFFFFFFu;

// header field offsets (bytes)
enum {
  OFF_MAGIC = 0,
  OFF_VERSION = 8,
  OFF_GEN = 12,
  OFF_REQ_OFF = 16,
  OFF_REQ_SIZE = 24,
  OFF_RESP_OFF = 32,
  OFF_RESP_SIZE = 40,
  OFF_REQ_HEAD = 64,
  OFF_REQ_TAIL = 128,
  OFF_RESP_HEAD = 192,
  OFF_RESP_TAIL = 256,
};

enum { RING_REQ = 0, RING_RESP = 1 };

// poll_record outcomes (<0 mirror serve_native's PF_* classes so the
// caller can count/classify without a translation table)
enum {
  SHM_EMPTY = 0,
  SHM_RECORD = 1,
  SHM_MALFORMED = -1,   // overrun cursor / impossible length
  SHM_TOOLARGE = -2,    // record larger than the ring allows
  SHM_STALE_GEN = -3,   // record stamped by another generation
  SHM_ABORTED = -4,     // write gave up (peer gone / shutdown)
};

struct Region {
  uint8_t* base = nullptr;
  uint64_t map_len = 0;
  uint64_t ring_off[2] = {0, 0};
  uint64_t ring_size[2] = {0, 0};
  uint32_t gen = 0;
  char path[512];
};

// Map an existing region file and validate its header; returns null
// with a short reason in err (when given). The worker side.
Region* map_region(const char* path, char* err, size_t err_len);

// Create + initialize a region file (the client side; also what the
// native bench driver and the chaos tests use).
Region* create_region(const char* path, uint64_t req_size,
                      uint64_t resp_size, uint32_t gen);

void close_region(Region* r, bool unlink_file);

// Validate a region file's header without keeping a mapping:
// 0 = ok, else a PF-style status (1 malformed / 2 too large).
int32_t probe_region(const char* path);

// Consumer: peek the next record of `ring`. SHM_RECORD → *data/*len
// point INTO the mapped region (valid until consume_record); SHM_EMPTY
// → nothing published; <0 → the ring is poisoned (see enum above).
// Wrap markers are skipped internally.
int poll_record(Region* r, int ring, const uint8_t** data,
                uint64_t* len);

// Advance the consumer cursor past the record poll_record returned.
void consume_record(Region* r, int ring);

// Producer: append one record (blocking while the ring is full).
// abort(ctx) is polled while waiting; returns 0 on success,
// SHM_TOOLARGE when the record can never fit, SHM_ABORTED when the
// abort callback fired.
typedef bool (*AbortFn)(void* ctx);
int write_record(Region* r, int ring, const uint8_t* data,
                 uint64_t len, AbortFn abort, void* ctx);

// Largest payload write_record accepts for this ring.
uint64_t max_record(const Region* r, int ring);

}  // namespace cap_shm

#endif  // CAP_SHM_RING_H
