// capruntime — native batch JOSE preparation for cap_tpu.
//
// The framework's native runtime component (the reference has none —
// SURVEY.md §2: its hot loops live in Go stdlib crypto; ours live here
// and on the TPU). One call prepares a whole batch of compact JWS
// tokens for device dispatch:
//   - strict structural parse (3 segments, unpadded base64url)
//   - header JSON scan: top-level "alg" and "kid" strings
//     (full minimal JSON parser; duplicate keys: last one wins,
//     matching Python's json.loads)
//   - base64url decode of payload + signature
//   - SHA-256/384/512 of the signing input, chosen by alg family
// Multithreaded over tokens; exposed via a C ABI for ctypes.
//
// Build: make native   (g++ -O3 -shared -fPIC -pthread)

#include <cstdint>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#if defined(__x86_64__) || defined(__i386__)
#include <immintrin.h>  // SHA-NI path (sha2 namespace below)
#include <cpuid.h>      // feature probe fallback for gcc < 11
#endif

// ---------------------------------------------------------------------------
// SHA-2 (FIPS 180-4), implemented from the spec.
// ---------------------------------------------------------------------------

namespace sha2 {

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr32(uint32_t x, int n) {
  return (x >> n) | (x << (32 - n));
}

static void sha256_compress_scalar(uint32_t h[8], const uint8_t* p) {
  uint32_t w[64];
  for (int i = 0; i < 16; i++)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; i++) {
    uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
           g = h[6], hh = h[7];
  for (int i = 0; i < 64; i++) {
    uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = hh + S1 + ch + K256[i] + w[i];
    uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
    uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint32_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

#if defined(__x86_64__) || defined(__i386__)
// SHA-NI block: the canonical x86 SHA extension flow (two rounds per
// sha256rnds2, message schedule via sha256msg1/msg2 with a 4-register
// rotation). Bit-identical to the scalar compress — sha_batch parity
// tests diff it against hashlib on every build.
__attribute__((target("sha,sse4.1,ssse3")))
static void sha256_compress_ni(uint32_t h[8], const uint8_t* p) {
  const __m128i MASK =
      _mm_set_epi64x(0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
  __m128i TMP = _mm_loadu_si128(reinterpret_cast<const __m128i*>(&h[0]));
  __m128i STATE1 =
      _mm_loadu_si128(reinterpret_cast<const __m128i*>(&h[4]));
  TMP = _mm_shuffle_epi32(TMP, 0xB1);           // CDAB
  STATE1 = _mm_shuffle_epi32(STATE1, 0x1B);     // EFGH
  __m128i STATE0 = _mm_alignr_epi8(TMP, STATE1, 8);     // ABEF
  STATE1 = _mm_blend_epi16(STATE1, TMP, 0xF0);          // CDGH
  const __m128i ABEF_SAVE = STATE0;
  const __m128i CDGH_SAVE = STATE1;

  __m128i m[4];
  for (int g = 0; g < 4; ++g)
    m[g] = _mm_shuffle_epi8(
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(p + 16 * g)),
        MASK);
  for (int g = 0; g < 16; ++g) {
    __m128i msg = _mm_add_epi32(
        m[g & 3],
        _mm_loadu_si128(reinterpret_cast<const __m128i*>(&K256[4 * g])));
    STATE1 = _mm_sha256rnds2_epu32(STATE1, STATE0, msg);
    msg = _mm_shuffle_epi32(msg, 0x0E);
    STATE0 = _mm_sha256rnds2_epu32(STATE0, STATE1, msg);
    if (g < 12) {
      // W[g+4] = msg2(msg1(W[g], W[g+1]) + alignr(W[g+3], W[g+2], 4),
      //               W[g+3]) — overwrites the slot just consumed.
      __m128i x = _mm_sha256msg1_epu32(m[g & 3], m[(g + 1) & 3]);
      x = _mm_add_epi32(
          x, _mm_alignr_epi8(m[(g + 3) & 3], m[(g + 2) & 3], 4));
      m[g & 3] = _mm_sha256msg2_epu32(x, m[(g + 3) & 3]);
    }
  }

  STATE0 = _mm_add_epi32(STATE0, ABEF_SAVE);
  STATE1 = _mm_add_epi32(STATE1, CDGH_SAVE);
  TMP = _mm_shuffle_epi32(STATE0, 0x1B);        // FEBA
  STATE1 = _mm_shuffle_epi32(STATE1, 0xB1);     // DCHG
  STATE0 = _mm_blend_epi16(TMP, STATE1, 0xF0);  // DCBA
  STATE1 = _mm_alignr_epi8(STATE1, TMP, 8);     // HGFE
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&h[0]), STATE0);
  _mm_storeu_si128(reinterpret_cast<__m128i*>(&h[4]), STATE1);
}
#endif  // x86

// Runtime dispatch: SHA-NI where the CPU has it (one compress is
// ~10× the scalar rate; the digest is the prep hot loop's biggest
// single term), scalar elsewhere. x86 SHA extensions cover SHA-1/256
// only — SHA-384/512 stays scalar.
static void (*sha256_compress)(uint32_t[8], const uint8_t*) =
    sha256_compress_scalar;

__attribute__((constructor)) static void sha256_pick_impl() {
#if defined(__x86_64__) || defined(__i386__)
#if defined(__clang__) || (defined(__GNUC__) && __GNUC__ >= 11)
  if (__builtin_cpu_supports("sha") &&
      __builtin_cpu_supports("sse4.1") &&
      __builtin_cpu_supports("ssse3"))
    sha256_compress = sha256_compress_ni;
#else
  // gcc < 11 rejects "sha" as a __builtin_cpu_supports feature name
  // (the whole translation unit failed to compile, silently killing
  // the native runtime on those toolchains): probe CPUID leaf 7
  // directly — EBX bit 29 is the SHA-extensions flag.
  unsigned eax = 0, ebx = 0, ecx = 0, edx = 0;
  if (__get_cpuid_count(7, 0, &eax, &ebx, &ecx, &edx) &&
      (ebx & (1u << 29)) &&
      __builtin_cpu_supports("sse4.1") &&
      __builtin_cpu_supports("ssse3"))
    sha256_compress = sha256_compress_ni;
#endif
#endif
}

void sha256(const uint8_t* data, size_t len, uint8_t out[32]) {
  uint32_t h[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                   0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  size_t i = 0;
  for (; i + 64 <= len; i += 64) sha256_compress(h, data + i);
  uint8_t block[128] = {0};
  size_t rem = len - i;
  memcpy(block, data + i, rem);
  block[rem] = 0x80;
  size_t blocks = (rem + 9 <= 64) ? 1 : 2;
  uint64_t bits = uint64_t(len) * 8;
  for (int j = 0; j < 8; j++)
    block[blocks * 64 - 1 - j] = uint8_t(bits >> (8 * j));
  sha256_compress(h, block);
  if (blocks == 2) sha256_compress(h, block + 64);
  for (int j = 0; j < 8; j++) {
    out[4 * j] = uint8_t(h[j] >> 24);
    out[4 * j + 1] = uint8_t(h[j] >> 16);
    out[4 * j + 2] = uint8_t(h[j] >> 8);
    out[4 * j + 3] = uint8_t(h[j]);
  }
}

static const uint64_t K512[80] = {
    0x428a2f98d728ae22ULL, 0x7137449123ef65cdULL, 0xb5c0fbcfec4d3b2fULL,
    0xe9b5dba58189dbbcULL, 0x3956c25bf348b538ULL, 0x59f111f1b605d019ULL,
    0x923f82a4af194f9bULL, 0xab1c5ed5da6d8118ULL, 0xd807aa98a3030242ULL,
    0x12835b0145706fbeULL, 0x243185be4ee4b28cULL, 0x550c7dc3d5ffb4e2ULL,
    0x72be5d74f27b896fULL, 0x80deb1fe3b1696b1ULL, 0x9bdc06a725c71235ULL,
    0xc19bf174cf692694ULL, 0xe49b69c19ef14ad2ULL, 0xefbe4786384f25e3ULL,
    0x0fc19dc68b8cd5b5ULL, 0x240ca1cc77ac9c65ULL, 0x2de92c6f592b0275ULL,
    0x4a7484aa6ea6e483ULL, 0x5cb0a9dcbd41fbd4ULL, 0x76f988da831153b5ULL,
    0x983e5152ee66dfabULL, 0xa831c66d2db43210ULL, 0xb00327c898fb213fULL,
    0xbf597fc7beef0ee4ULL, 0xc6e00bf33da88fc2ULL, 0xd5a79147930aa725ULL,
    0x06ca6351e003826fULL, 0x142929670a0e6e70ULL, 0x27b70a8546d22ffcULL,
    0x2e1b21385c26c926ULL, 0x4d2c6dfc5ac42aedULL, 0x53380d139d95b3dfULL,
    0x650a73548baf63deULL, 0x766a0abb3c77b2a8ULL, 0x81c2c92e47edaee6ULL,
    0x92722c851482353bULL, 0xa2bfe8a14cf10364ULL, 0xa81a664bbc423001ULL,
    0xc24b8b70d0f89791ULL, 0xc76c51a30654be30ULL, 0xd192e819d6ef5218ULL,
    0xd69906245565a910ULL, 0xf40e35855771202aULL, 0x106aa07032bbd1b8ULL,
    0x19a4c116b8d2d0c8ULL, 0x1e376c085141ab53ULL, 0x2748774cdf8eeb99ULL,
    0x34b0bcb5e19b48a8ULL, 0x391c0cb3c5c95a63ULL, 0x4ed8aa4ae3418acbULL,
    0x5b9cca4f7763e373ULL, 0x682e6ff3d6b2b8a3ULL, 0x748f82ee5defb2fcULL,
    0x78a5636f43172f60ULL, 0x84c87814a1f0ab72ULL, 0x8cc702081a6439ecULL,
    0x90befffa23631e28ULL, 0xa4506cebde82bde9ULL, 0xbef9a3f7b2c67915ULL,
    0xc67178f2e372532bULL, 0xca273eceea26619cULL, 0xd186b8c721c0c207ULL,
    0xeada7dd6cde0eb1eULL, 0xf57d4f7fee6ed178ULL, 0x06f067aa72176fbaULL,
    0x0a637dc5a2c898a6ULL, 0x113f9804bef90daeULL, 0x1b710b35131c471bULL,
    0x28db77f523047d84ULL, 0x32caab7b40c72493ULL, 0x3c9ebe0a15c9bebcULL,
    0x431d67c49c100d4cULL, 0x4cc5d4becb3e42b6ULL, 0x597f299cfc657e2aULL,
    0x5fcb6fab3ad6faecULL, 0x6c44198c4a475817ULL};

static inline uint64_t rotr64(uint64_t x, int n) {
  return (x >> n) | (x << (64 - n));
}

static void sha512_compress(uint64_t h[8], const uint8_t* p) {
  uint64_t w[80];
  for (int i = 0; i < 16; i++) {
    uint64_t v = 0;
    for (int j = 0; j < 8; j++) v = (v << 8) | p[8 * i + j];
    w[i] = v;
  }
  for (int i = 16; i < 80; i++) {
    uint64_t s0 = rotr64(w[i - 15], 1) ^ rotr64(w[i - 15], 8) ^ (w[i - 15] >> 7);
    uint64_t s1 = rotr64(w[i - 2], 19) ^ rotr64(w[i - 2], 61) ^ (w[i - 2] >> 6);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint64_t a = h[0], b = h[1], c = h[2], d = h[3], e = h[4], f = h[5],
           g = h[6], hh = h[7];
  for (int i = 0; i < 80; i++) {
    uint64_t S1 = rotr64(e, 14) ^ rotr64(e, 18) ^ rotr64(e, 41);
    uint64_t ch = (e & f) ^ (~e & g);
    uint64_t t1 = hh + S1 + ch + K512[i] + w[i];
    uint64_t S0 = rotr64(a, 28) ^ rotr64(a, 34) ^ rotr64(a, 39);
    uint64_t maj = (a & b) ^ (a & c) ^ (b & c);
    uint64_t t2 = S0 + maj;
    hh = g; g = f; f = e; e = d + t1; d = c; c = b; b = a; a = t1 + t2;
  }
  h[0] += a; h[1] += b; h[2] += c; h[3] += d;
  h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
}

static void sha512_generic(const uint64_t iv[8], const uint8_t* data,
                           size_t len, uint8_t* out, int out_words) {
  uint64_t h[8];
  memcpy(h, iv, sizeof(h));
  size_t i = 0;
  for (; i + 128 <= len; i += 128) sha512_compress(h, data + i);
  uint8_t block[256] = {0};
  size_t rem = len - i;
  memcpy(block, data + i, rem);
  block[rem] = 0x80;
  size_t blocks = (rem + 17 <= 128) ? 1 : 2;
  // message length in bits as 128-bit big-endian (top 64 bits are zero
  // for any realistic input)
  uint64_t bits = uint64_t(len) * 8;
  for (int j = 0; j < 8; j++)
    block[blocks * 128 - 1 - j] = uint8_t(bits >> (8 * j));
  sha512_compress(h, block);
  if (blocks == 2) sha512_compress(h, block + 128);
  for (int j = 0; j < out_words; j++)
    for (int k = 0; k < 8; k++)
      out[8 * j + k] = uint8_t(h[j] >> (56 - 8 * k));
}

void sha512(const uint8_t* data, size_t len, uint8_t out[64]) {
  static const uint64_t iv[8] = {
      0x6a09e667f3bcc908ULL, 0xbb67ae8584caa73bULL, 0x3c6ef372fe94f82bULL,
      0xa54ff53a5f1d36f1ULL, 0x510e527fade682d1ULL, 0x9b05688c2b3e6c1fULL,
      0x1f83d9abfb41bd6bULL, 0x5be0cd19137e2179ULL};
  sha512_generic(iv, data, len, out, 8);
}

void sha384(const uint8_t* data, size_t len, uint8_t out[48]) {
  static const uint64_t iv[8] = {
      0xcbbb9d5dc1059ed8ULL, 0x629a292a367cd507ULL, 0x9159015a3070dd17ULL,
      0x152fecd8f70e5939ULL, 0x67332667ffc00b31ULL, 0x8eb44a8768581511ULL,
      0xdb0c2e0d64f98fa7ULL, 0x47b5481dbefa4fa4ULL};
  sha512_generic(iv, data, len, out, 6);
}

}  // namespace sha2

// ---------------------------------------------------------------------------
// base64url (RFC 7515: unpadded, strict charset)
// ---------------------------------------------------------------------------

static int8_t B64_TABLE[256];
static bool b64_table_init = [] {
  for (int i = 0; i < 256; i++) B64_TABLE[i] = -1;
  const char* cs =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789-_";
  for (int i = 0; i < 64; i++) B64_TABLE[uint8_t(cs[i])] = int8_t(i);
  return true;
}();

// Decode unpadded base64url. Returns decoded length or -1 on error.
static int64_t b64url_decode(const char* in, int64_t n, uint8_t* out) {
  if (n % 4 == 1) return -1;
  int64_t o = 0;
  int64_t i = 0;
  for (; i + 4 <= n; i += 4) {
    int8_t a = B64_TABLE[uint8_t(in[i])], b = B64_TABLE[uint8_t(in[i + 1])],
           c = B64_TABLE[uint8_t(in[i + 2])], d = B64_TABLE[uint8_t(in[i + 3])];
    if ((a | b | c | d) < 0) return -1;
    uint32_t v = (uint32_t(a) << 18) | (uint32_t(b) << 12) |
                 (uint32_t(c) << 6) | uint32_t(d);
    out[o++] = uint8_t(v >> 16);
    out[o++] = uint8_t(v >> 8);
    out[o++] = uint8_t(v);
  }
  int64_t rem = n - i;
  if (rem == 2) {
    int8_t a = B64_TABLE[uint8_t(in[i])], b = B64_TABLE[uint8_t(in[i + 1])];
    if ((a | b) < 0) return -1;
    uint32_t v = (uint32_t(a) << 18) | (uint32_t(b) << 12);
    out[o++] = uint8_t(v >> 16);
    // python's base64 ignores trailing bits in the final quantum; JWS
    // parity: accept (the CPU path accepts as well via urlsafe_b64decode)
  } else if (rem == 3) {
    int8_t a = B64_TABLE[uint8_t(in[i])], b = B64_TABLE[uint8_t(in[i + 1])],
           c = B64_TABLE[uint8_t(in[i + 2])];
    if ((a | b | c) < 0) return -1;
    uint32_t v = (uint32_t(a) << 18) | (uint32_t(b) << 12) | (uint32_t(c) << 6);
    out[o++] = uint8_t(v >> 16);
    out[o++] = uint8_t(v >> 8);
  }
  return o;
}

// Strict UTF-8 validation matching CPython's decoder (rejects overlong
// encodings, surrogates, and > U+10FFFF) — Python's json.loads decodes
// the buffer as UTF-8 before parsing, so the native path must too.
static bool valid_utf8(const uint8_t* p, int64_t n) {
  int64_t i = 0;
  while (i < n) {
    uint8_t c = p[i];
    if (c < 0x80) { i++; continue; }
    if (c < 0xC2) return false;  // continuation byte or overlong C0/C1
    if (c < 0xE0) {              // 2-byte
      if (i + 1 >= n || (p[i + 1] & 0xC0) != 0x80) return false;
      i += 2;
    } else if (c < 0xF0) {       // 3-byte
      if (i + 2 >= n) return false;
      uint8_t c1 = p[i + 1], c2 = p[i + 2];
      if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80) return false;
      if (c == 0xE0 && c1 < 0xA0) return false;         // overlong
      if (c == 0xED && c1 >= 0xA0) return false;        // surrogate
      i += 3;
    } else if (c < 0xF5) {       // 4-byte
      if (i + 3 >= n) return false;
      uint8_t c1 = p[i + 1], c2 = p[i + 2], c3 = p[i + 3];
      if ((c1 & 0xC0) != 0x80 || (c2 & 0xC0) != 0x80 ||
          (c3 & 0xC0) != 0x80) return false;
      if (c == 0xF0 && c1 < 0x90) return false;         // overlong
      if (c == 0xF4 && c1 >= 0x90) return false;        // > U+10FFFF
      i += 4;
    } else {
      return false;
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// Minimal JSON parser — just enough to validate an object and extract
// top-level "alg"/"kid" string values (last duplicate wins, like
// Python's json.loads). Returns false on malformed JSON.
// ---------------------------------------------------------------------------

struct JsonScanner {
  const char* p;
  const char* end;

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r'))
      p++;
  }

  bool parse_string(std::string* out) {
    if (p >= end || *p != '"') return false;
    p++;
    std::string s;
    while (p < end) {
      unsigned char c = *p;
      if (c == '"') {
        p++;
        if (out) *out = s;
        return true;
      }
      if (c == '\\') {
        p++;
        if (p >= end) return false;
        char e = *p++;
        switch (e) {
          case '"': s += '"'; break;
          case '\\': s += '\\'; break;
          case '/': s += '/'; break;
          case 'b': s += '\b'; break;
          case 'f': s += '\f'; break;
          case 'n': s += '\n'; break;
          case 'r': s += '\r'; break;
          case 't': s += '\t'; break;
          case 'u': {
            if (end - p < 4) return false;
            unsigned v = 0;
            for (int i = 0; i < 4; i++) {
              char h = p[i];
              v <<= 4;
              if (h >= '0' && h <= '9') v |= h - '0';
              else if (h >= 'a' && h <= 'f') v |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') v |= h - 'A' + 10;
              else return false;
            }
            p += 4;
            // encode as UTF-8 (surrogate pairs: handle the common case)
            if (v >= 0xD800 && v <= 0xDBFF && end - p >= 6 && p[0] == '\\' &&
                p[1] == 'u') {
              unsigned lo = 0;
              bool ok = true;
              for (int i = 0; i < 4; i++) {
                char h = p[2 + i];
                lo <<= 4;
                if (h >= '0' && h <= '9') lo |= h - '0';
                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                else { ok = false; break; }
              }
              if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                p += 6;
                unsigned cp = 0x10000 + ((v - 0xD800) << 10) + (lo - 0xDC00);
                s += char(0xF0 | (cp >> 18));
                s += char(0x80 | ((cp >> 12) & 0x3F));
                s += char(0x80 | ((cp >> 6) & 0x3F));
                s += char(0x80 | (cp & 0x3F));
                break;
              }
            }
            if (v < 0x80) s += char(v);
            else if (v < 0x800) {
              s += char(0xC0 | (v >> 6));
              s += char(0x80 | (v & 0x3F));
            } else {
              s += char(0xE0 | (v >> 12));
              s += char(0x80 | ((v >> 6) & 0x3F));
              s += char(0x80 | (v & 0x3F));
            }
            break;
          }
          default: return false;
        }
        continue;
      }
      if (c < 0x20) return false;
      s += char(c);
      p++;
    }
    return false;
  }

  bool skip_number() {
    if (p < end && *p == '-') p++;
    if (p >= end) return false;
    if (*p == '0') p++;
    else if (*p >= '1' && *p <= '9') { while (p < end && *p >= '0' && *p <= '9') p++; }
    else return false;
    if (p < end && *p == '.') {
      p++;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    if (p < end && (*p == 'e' || *p == 'E')) {
      p++;
      if (p < end && (*p == '+' || *p == '-')) p++;
      if (p >= end || *p < '0' || *p > '9') return false;
      while (p < end && *p >= '0' && *p <= '9') p++;
    }
    return true;
  }

  bool skip_literal(const char* lit) {
    size_t n = strlen(lit);
    if (size_t(end - p) < n || strncmp(p, lit, n) != 0) return false;
    p += n;
    return true;
  }

  bool skip_value(int depth) {
    if (depth > 64) return false;
    ws();
    if (p >= end) return false;
    switch (*p) {
      case '"': return parse_string(nullptr);
      case '{': return skip_object_with_kidflag(depth + 1, nullptr, nullptr,
                                                nullptr, nullptr);
      case '[': {
        p++;
        ws();
        if (p < end && *p == ']') { p++; return true; }
        while (true) {
          if (!skip_value(depth + 1)) return false;
          ws();
          if (p < end && *p == ',') { p++; continue; }
          if (p < end && *p == ']') { p++; return true; }
          return false;
        }
      }
      case 't': return skip_literal("true");
      case 'f': return skip_literal("false");
      case 'n': return skip_literal("null");
      default: return skip_number();
    }
  }

  // Parses an object. When alg/kid are non-null, captures those
  // top-level string members (top level only when depth == 1);
  // kid_found reports whether a top-level string "kid" member existed
  // (distinguishing an absent kid from an empty-string kid).
  // crit_found flags a top-level "crit" member of ANY value type —
  // go-jose rejects every JWS bearing one, and the Python parser
  // (jwt/jose.py) matches, so the native prep must too.
  bool skip_object_with_kidflag(int depth, std::string* alg,
                                std::string* kid, bool* kid_found,
                                bool* crit_found) {
    if (depth > 64) return false;
    ws();
    if (p >= end || *p != '{') return false;
    p++;
    ws();
    if (p < end && *p == '}') { p++; return true; }
    while (true) {
      ws();
      std::string key;
      if (!parse_string(&key)) return false;
      ws();
      if (p >= end || *p != ':') return false;
      p++;
      ws();
      if (depth == 1 && crit_found && key == "crit") *crit_found = true;
      bool captured = false;
      if (depth == 1 && p < end && *p == '"' && (alg || kid)) {
        if (alg && key == "alg") {
          if (!parse_string(alg)) return false;
          captured = true;
        } else if (kid && key == "kid") {
          if (!parse_string(kid)) return false;
          if (kid_found) *kid_found = true;
          captured = true;
        }
      }
      if (!captured && !skip_value(depth)) return false;
      ws();
      if (p < end && *p == ',') { p++; continue; }
      if (p < end && *p == '}') { p++; return true; }
      return false;
    }
  }
};

// ---------------------------------------------------------------------------
// Batch prepare
// ---------------------------------------------------------------------------

// Status codes (mirrored in cap_tpu/runtime/native_binding.py)
enum Status : int32_t {
  OK = 0,
  ERR_SEGMENTS = 1,     // not exactly 3 dot-separated segments
  ERR_B64 = 2,          // bad base64url in any segment
  ERR_HEADER_JSON = 3,  // header not a JSON object
  ERR_NO_ALG = 4,       // missing/empty alg
  ERR_UNSIGNED = 5,     // empty signature segment
  ERR_CRIT = 6,         // crit protected header present (go-jose parity)
};

// Alg ids (order matches ALG_NAMES in the binding)
static const char* ALG_NAMES[10] = {"RS256", "RS384", "RS512", "ES256",
                                    "ES384", "ES512", "PS256", "PS384",
                                    "PS512", "EdDSA"};

struct TokOut {
  int32_t status;
  int32_t alg_id;          // 0..9, or -1 for unknown alg strings
  int64_t sig_off, sig_len;
  int64_t payload_off, payload_len;
  int64_t signing_input_len;  // prefix length of token (header.payload)
  char kid[160];           // raw kid bytes (may contain NULs)
  char alg_raw[32];        // raw alg bytes for unknown algs
  uint8_t digest[64];      // sha256/384/512 of signing input (by family)
  int32_t digest_len;
  int32_t kid_len;         // -1 = kid absent; -2 = kid longer than 160
  int32_t alg_len;
  int32_t pad;
};

static int alg_id_of(const std::string& a) {
  for (int i = 0; i < 10; i++)
    if (a == ALG_NAMES[i]) return i;
  return -1;
}

static void prepare_one(const char* tok, int64_t len, TokOut* out,
                        uint8_t* scratch, int64_t scratch_cap) {
  memset(out, 0, sizeof(TokOut));
  out->kid_len = -1;
  // split on dots
  int64_t d1 = -1, d2 = -1;
  int dots = 0;
  for (int64_t i = 0; i < len; i++) {
    if (tok[i] == '.') {
      dots++;
      if (dots == 1) d1 = i;
      else if (dots == 2) d2 = i;
    }
  }
  if (dots != 2 || len == 0) {
    out->status = ERR_SEGMENTS;
    return;
  }
  const char* hseg = tok;
  int64_t hlen = d1;
  const char* pseg = tok + d1 + 1;
  int64_t plen = d2 - d1 - 1;
  const char* sseg = tok + d2 + 1;
  int64_t slen = len - d2 - 1;

  // header decode (into scratch)
  std::vector<uint8_t> hbuf((hlen * 3) / 4 + 4);
  int64_t hdec = b64url_decode(hseg, hlen, hbuf.data());
  if (hdec < 0) {
    out->status = ERR_B64;
    return;
  }
  if (!valid_utf8(hbuf.data(), hdec)) {
    out->status = ERR_HEADER_JSON;
    return;
  }
  JsonScanner js{reinterpret_cast<const char*>(hbuf.data()),
                 reinterpret_cast<const char*>(hbuf.data()) + hdec};
  std::string alg;
  std::string kid;
  bool kid_present = false;
  bool crit_present = false;
  if (!js.skip_object_with_kidflag(1, &alg, &kid, &kid_present,
                                   &crit_present)) {
    out->status = ERR_HEADER_JSON;
    return;
  }
  js.ws();
  if (js.p != js.end) {  // trailing garbage after the object
    out->status = ERR_HEADER_JSON;
    return;
  }
  if (alg.empty()) {
    out->status = ERR_NO_ALG;
    return;
  }
  if (crit_present) {  // same check order as jose.py: alg, then crit
    out->status = ERR_CRIT;
    return;
  }
  // payload + signature decode into the caller's scratch region
  if ((plen * 3) / 4 + 4 + (slen * 3) / 4 + 4 > scratch_cap) {
    out->status = ERR_B64;  // scratch sized from token len; cannot happen
    return;
  }
  int64_t pdec = b64url_decode(pseg, plen, scratch);
  if (pdec < 0) {
    out->status = ERR_B64;
    return;
  }
  int64_t sdec = b64url_decode(sseg, slen, scratch + pdec);
  if (sdec < 0) {
    out->status = ERR_B64;
    return;
  }
  if (sdec == 0) {
    out->status = ERR_UNSIGNED;
    return;
  }
  out->payload_off = 0;  // relative; binding adds the token's base offset
  out->payload_len = pdec;
  out->sig_off = pdec;
  out->sig_len = sdec;
  out->signing_input_len = d2;
  // byte-exact kid/alg (embedded NULs preserved; overlong kid flagged so
  // the binding demotes to the exact slow path instead of mismatching)
  if (!kid_present) {
    out->kid_len = -1;
  } else if (kid.size() > sizeof(out->kid)) {
    out->kid_len = -2;
  } else {
    memcpy(out->kid, kid.data(), kid.size());
    out->kid_len = int32_t(kid.size());
  }
  size_t alen = alg.size() < sizeof(out->alg_raw) ? alg.size()
                                                  : sizeof(out->alg_raw);
  memcpy(out->alg_raw, alg.data(), alen);
  out->alg_len = int32_t(alen);
  out->alg_id = (alg.size() <= sizeof(out->alg_raw)) ? alg_id_of(alg) : -1;

  // digest of the signing input, by alg family suffix
  const uint8_t* si = reinterpret_cast<const uint8_t*>(tok);
  if (out->alg_id >= 0) {
    if (alg == "EdDSA") {
      out->digest_len = 0;  // Ed25519 signs the raw message
    } else if (alg.size() == 5 && alg.compare(2, 3, "256") == 0) {
      sha2::sha256(si, size_t(d2), out->digest);
      out->digest_len = 32;
    } else if (alg.compare(2, 3, "384") == 0) {
      sha2::sha384(si, size_t(d2), out->digest);
      out->digest_len = 48;
    } else {
      sha2::sha512(si, size_t(d2), out->digest);
      out->digest_len = 64;
    }
  }
  out->status = OK;
}

extern "C" {

// tokens: concatenated token bytes; offsets: n+1 entries delimiting each
// token; outs: n TokOut records; decode_buf: per-token scratch carved as
// decode_offsets[i] .. decode_offsets[i+1] (binding sizes it from token
// lengths). Multithreaded over tokens.
void cap_prepare_batch(const char* tokens, const int64_t* offsets, int64_t n,
                       TokOut* outs, uint8_t* decode_buf,
                       const int64_t* decode_offsets, int32_t n_threads) {
  if (n_threads <= 0) {
    n_threads = int32_t(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 4;
  }
  if (n_threads > n) n_threads = int32_t(n > 0 ? n : 1);
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      prepare_one(tokens + offsets[i], offsets[i + 1] - offsets[i], &outs[i],
                  decode_buf + decode_offsets[i],
                  decode_offsets[i + 1] - decode_offsets[i]);
    }
  };
  if (n_threads <= 1) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
}

int64_t cap_tokout_size() { return sizeof(TokOut); }

// Standalone batched SHA-2 over byte ranges (used by the PSS host check
// and Ed25519 prehash paths).
void cap_sha_batch(const uint8_t* data, const int64_t* offsets, int64_t n,
                   int32_t bits, uint8_t* out, int32_t n_threads) {
  int32_t out_len = bits / 8;
  if (n_threads <= 0) {
    n_threads = int32_t(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 4;
  }
  if (n_threads > n) n_threads = int32_t(n > 0 ? n : 1);
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      const uint8_t* p = data + offsets[i];
      size_t len = size_t(offsets[i + 1] - offsets[i]);
      if (bits == 256) sha2::sha256(p, len, out + i * out_len);
      else if (bits == 384) sha2::sha384(p, len, out + i * out_len);
      else sha2::sha512(p, len, out + i * out_len);
    }
  };
  if (n_threads <= 1) {
    worker(0, n);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
}


// EMSA-PSS-VERIFY (RFC 8017 §9.1.2) for a batch of device-computed EMs,
// salt auto-recovered (parity with cap_tpu.tpu.rsa.pss_check_em and the
// CPU oracle's PSS.AUTO). em: [n, em_stride] right-aligned big-endian.
void cap_pss_check_batch(const uint8_t* em, int64_t n, int64_t em_stride,
                         const uint8_t* mhash, int64_t mhash_stride,
                         const int64_t* em_bits, int32_t bits,
                         const uint8_t* valid, uint8_t* out_ok,
                         int32_t n_threads) {
  const int64_t h_len = bits / 8;
  void (*hash_fn)(const uint8_t*, size_t, uint8_t*) =
      bits == 256 ? sha2::sha256 : bits == 384 ? sha2::sha384 : sha2::sha512;
  if (n_threads <= 0) {
    n_threads = int32_t(std::thread::hardware_concurrency());
    if (n_threads <= 0) n_threads = 4;
  }
  if (n_threads > n) n_threads = int32_t(n > 0 ? n : 1);
  auto worker = [&](int64_t lo, int64_t hi) {
    std::vector<uint8_t> db((size_t)em_stride);
    std::vector<uint8_t> mgf_in((size_t)h_len + 4);
    std::vector<uint8_t> mgf_out(64);
    std::vector<uint8_t> mprime(8 + 64 + (size_t)em_stride);
    std::vector<uint8_t> hprime(64);
    for (int64_t i = lo; i < hi; i++) {
      out_ok[i] = 0;
      if (!valid[i]) continue;
      const uint8_t* row = em + i * em_stride;
      int64_t elen = (em_bits[i] + 7) / 8;
      if (elen > em_stride) continue;
      // dropped high bytes must be zero (EM < 2^emBits)
      bool lead_zero = true;
      for (int64_t j = 0; j < em_stride - elen; j++)
        if (row[j]) { lead_zero = false; break; }
      if (!lead_zero) continue;
      const uint8_t* e = row + (em_stride - elen);
      if (elen < h_len + 2) continue;
      if (e[elen - 1] != 0xBC) continue;
      int64_t db_len = elen - h_len - 1;
      const uint8_t* masked_db = e;
      const uint8_t* h = e + db_len;
      int unused = int(8 * elen - em_bits[i]);
      if (unused && (masked_db[0] >> (8 - unused))) continue;
      // DB = maskedDB XOR MGF1(H, db_len)
      std::memcpy(mgf_in.data(), h, size_t(h_len));
      for (int64_t off = 0, c = 0; off < db_len; off += h_len, c++) {
        mgf_in[size_t(h_len) + 0] = uint8_t(c >> 24);
        mgf_in[size_t(h_len) + 1] = uint8_t(c >> 16);
        mgf_in[size_t(h_len) + 2] = uint8_t(c >> 8);
        mgf_in[size_t(h_len) + 3] = uint8_t(c);
        hash_fn(mgf_in.data(), size_t(h_len) + 4, mgf_out.data());
        int64_t take = db_len - off < h_len ? db_len - off : h_len;
        for (int64_t j = 0; j < take; j++)
          db[size_t(off + j)] = masked_db[off + j] ^ mgf_out[size_t(j)];
      }
      if (unused) db[0] &= uint8_t(0xFF >> unused);
      // DB = 0x00.. ‖ 0x01 ‖ salt
      int64_t sep = -1;
      for (int64_t j = 0; j < db_len; j++) {
        if (db[size_t(j)] == 0x01) { sep = j; break; }
        if (db[size_t(j)] != 0x00) { sep = -2; break; }
      }
      if (sep < 0) continue;
      const uint8_t* salt = db.data() + sep + 1;
      int64_t salt_len = db_len - sep - 1;
      // H' = Hash(0x00*8 ‖ mHash ‖ salt)
      std::memset(mprime.data(), 0, 8);
      std::memcpy(mprime.data() + 8, mhash + i * mhash_stride,
                  size_t(h_len));
      std::memcpy(mprime.data() + 8 + h_len, salt, size_t(salt_len));
      hash_fn(mprime.data(), size_t(8 + h_len + salt_len), hprime.data());
      out_ok[i] = std::memcmp(hprime.data(), h, size_t(h_len)) == 0;
    }
  };
  if (n_threads <= 1) { worker(0, n); return; }
  std::vector<std::thread> threads;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
}


// Pack one verify chunk's device records in a single multithreaded
// pass: out row r = [right-aligned sig bytes (width) ‖ digest (h_len)
// ‖ valid flag ‖ key row]. Replaces the numpy gather → align → where →
// assemble chain (several full-matrix passes, GIL-held) on the batch
// hot path. Rows whose signature length differs from their key's
// expected size, or whose `extra_valid` is 0, pack as zeros with
// flag 0 (the verdict is decided host-side, matching the CPU oracle).
// Rows in [m, pad) are padding: all-zero. idx selects tokens from the
// batch-wide arrays; sig_off is absolute into scratch.
void cap_pack_sig_records(
    const uint8_t* scratch, int64_t scratch_len,
    const int64_t* sig_off, const int64_t* sig_len,
    const uint8_t* digest, int64_t digest_stride,
    const int64_t* idx, const int64_t* expect_size,
    const uint8_t* extra_valid, const uint8_t* key_rows,
    int64_t m, int64_t pad, int64_t width, int64_t h_len,
    uint8_t* out, int32_t n_threads) {
  const int64_t rec_w = width + h_len + 2;
  auto worker = [&](int64_t lo, int64_t hi) {
    for (int64_t r = lo; r < hi; r++) {
      uint8_t* row = out + r * rec_w;
      if (r >= m) {
        std::memset(row, 0, size_t(rec_w));
        continue;
      }
      int64_t i = idx[r];
      int64_t len = sig_len[i];
      bool valid = extra_valid[r] != 0 && len == expect_size[r] &&
                   len <= width && sig_off[i] >= 0 &&
                   sig_off[i] + len <= scratch_len;
      if (valid) {
        std::memset(row, 0, size_t(width - len));
        std::memcpy(row + width - len, scratch + sig_off[i],
                    size_t(len));
      } else {
        std::memset(row, 0, size_t(width));
      }
      std::memcpy(row + width, digest + i * digest_stride,
                  size_t(h_len));
      row[width + h_len] = valid ? 1 : 0;
      row[width + h_len + 1] = key_rows[r];
    }
  };
  if (n_threads <= 0) {
    unsigned hw = std::thread::hardware_concurrency();
    n_threads = int32_t(hw ? hw : 4);
  }
  if (n_threads <= 1 || pad < 2048) { worker(0, pad); return; }
  std::vector<std::thread> threads;
  int64_t chunk = (pad + n_threads - 1) / n_threads;
  for (int32_t t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = lo + chunk < pad ? lo + chunk : pad;
    if (lo >= hi) break;
    threads.emplace_back(worker, lo, hi);
  }
  for (auto& th : threads) th.join();
}

}  // extern "C"
