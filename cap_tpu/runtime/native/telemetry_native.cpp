// telemetry_native — wire-speed observability: the native telemetry
// plane of the GIL-free serve chain (ISSUE 8 / ROADMAP "native-side
// family counting" lever).
//
// Round 12 measured that with the serve hot path in C++, the Python
// decision/telemetry fold (obs/decision.record_batch) had become the
// dominant per-token serve cost on BOTH chains (~2 of ~2.65 us/token
// full-obs). This TU moves that fold into plain C structs the GIL
// never touches:
//
//   - per-token FAMILY classification happens in the per-connection
//     reader threads at frame-parse time, against a bounded native
//     header-segment cache. The cache is populated exclusively by
//     Python's own classifier (obs/decision._seg_family_kid) on a
//     miss — the native side never parses base64/JSON itself, so
//     family attribution is bit-exact by construction, not by a
//     reimplementation that could drift;
//   - accept / reject-by-reason / per-family COUNTERS fold at
//     response-encode time (cap_serve_post_results_tel) with ONE
//     atomic add per present key per chunk — the same per-batch (not
//     per-item) accounting the Dilithium GPU work (arXiv 2211.12265)
//     uses to keep batched verify at device rate — and the decision
//     ring's sampling positions (first-of-key + every 16th, derived
//     from the post-increment counter value exactly like
//     obs/decision.record_batch's bulk()) are computed here and
//     queued as EXEMPLARS in a bounded ring Python drains on the
//     drain call it already makes;
//   - HISTOGRAMS use the exact bucket edges telemetry.py computes
//     (passed in at create time; std::lower_bound == bisect_left), so
//     bucket counts merge exactly under telemetry.merge_snapshots and
//     fleet quantiles stay exact.
//
// The parity contract — counters, histogram bucket counts, and ring
// sample positions bit-identical to the Python fold — is pinned by
// tests/test_native_obs.py's fuzz sweep.

#include <algorithm>
#include <atomic>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "telemetry_native.h"

namespace cap_tel {

// ---------------------------------------------------------------------------
// header-segment cache: open-addressing, bounded, cleared at cap
// (the same stance as obs/decision._HDR_CACHE). Stores ONLY what the
// Python classifier computed: family index + hashed kid. Segment text
// lives in memory only, like the Python cache — never recorded.
// ---------------------------------------------------------------------------

struct CacheEnt {
  std::string seg;
  int8_t fam = 0;
  uint8_t kid_len = 0;
  int16_t ten = TEN_NONE;
  char kid[KID_LEN];
  bool used = false;
};

static inline uint64_t fnv1a(const uint8_t* p, int64_t n) {
  uint64_t h = 1469598103934665603ull;
  for (int64_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

// ---------------------------------------------------------------------------
// histograms: telemetry.Histogram's exact state (bucket counts via
// bisect_left over the SAME bounds + count/sum/min/max), guarded by a
// small per-series mutex — adds are per request / per chunk, never
// per token, so the lock is nowhere near the hot path.
// ---------------------------------------------------------------------------

struct Hist {
  std::mutex mu;
  std::vector<int64_t> counts;  // n_bounds + 1 (overflow)
  int64_t count = 0;
  double sum = 0.0;
  double vmin = 0.0;
  double vmax = 0.0;
};

struct Exemplar {
  uint8_t rec[EX_STRIDE];
};

struct TelPlane {
  std::vector<double> bounds;
  Hist series[N_SERIES];
  // counter block: single atomics, ONE fetch_add per key per chunk.
  // The post-increment value drives the sampling math, which is why
  // these are global rather than per-shard — the per-key sequence
  // must match the Python fold's count_many return values exactly.
  std::atomic<int64_t> ctr[N_CTR];
  // tenant attribution (r19): 3 globals + per-slot blocks (see
  // telemetry_native.h TEN_* layout) and one latency histogram per
  // slot — the binding maps slots back to issuer-hash labels.
  std::atomic<int64_t> tctr[N_TCTR];
  Hist ten_hist[N_TEN];
  // exemplar ring (FIFO, overwrites oldest — deque(maxlen) semantics)
  std::mutex ex_mu;
  Exemplar ex_ring[EX_RING];
  int64_t ex_head = 0;  // next write slot
  int64_t ex_len = 0;
  // header cache
  std::mutex cache_mu;
  std::vector<CacheEnt> slots;
  int64_t cache_used = 0;

  TelPlane() : slots(2 * CACHE_CAP) {
    for (auto& c : ctr) c.store(0);
    for (auto& c : tctr) c.store(0);
  }
};

TelPlane* create(const double* bounds, int32_t n_bounds) {
  if (!bounds || n_bounds <= 0) return nullptr;
  TelPlane* t = new TelPlane();
  t->bounds.assign(bounds, bounds + n_bounds);
  for (auto& h : t->series) h.counts.assign((size_t)n_bounds + 1, 0);
  for (auto& h : t->ten_hist) h.counts.assign((size_t)n_bounds + 1, 0);
  return t;
}

void destroy(TelPlane* t) { delete t; }

// -- cache ------------------------------------------------------------------

static CacheEnt* find_slot(TelPlane* t, const uint8_t* seg, int64_t len,
                           bool* found) {
  size_t mask = t->slots.size() - 1;
  size_t i = (size_t)fnv1a(seg, len) & mask;
  for (;;) {
    CacheEnt& e = t->slots[i];
    if (!e.used) {
      *found = false;
      return &e;
    }
    if ((int64_t)e.seg.size() == len &&
        std::memcmp(e.seg.data(), seg, (size_t)len) == 0) {
      *found = true;
      return &e;
    }
    i = (i + 1) & mask;
  }
}

int32_t classify(TelPlane* t, const uint8_t* seg, int64_t len,
                 uint8_t* kid_out, int32_t* kid_len_out,
                 int16_t* ten_out) {
  if (kid_len_out) *kid_len_out = 0;
  if (ten_out) *ten_out = TEN_NONE;
  // decision._seg_family_kid: empty or over-long segments are
  // "unknown" without touching the cache (bytes > chars never makes
  // a segment parseable: non-ASCII is invalid base64url anyway).
  // Tenant follows the same bound: "none" without a payload parse,
  // exactly like decision._seg_fkt.
  if (len <= 0 || len > MAX_SEG_BYTES) return FAM_UNKNOWN;
  std::lock_guard<std::mutex> lk(t->cache_mu);
  bool found;
  CacheEnt* e = find_slot(t, seg, len, &found);
  if (!found) {
    t->ctr[CTR_CACHE_MISSES].fetch_add(1, std::memory_order_relaxed);
    return -1;
  }
  t->ctr[CTR_CACHE_HITS].fetch_add(1, std::memory_order_relaxed);
  if (e->kid_len && kid_out) {
    std::memcpy(kid_out, e->kid, e->kid_len);
    if (kid_len_out) *kid_len_out = e->kid_len;
  }
  if (ten_out) *ten_out = e->ten;
  return e->fam;
}

void learn(TelPlane* t, const uint8_t* seg, int64_t len, int32_t fam,
           const uint8_t* kid, int32_t kid_len, int32_t ten) {
  if (len <= 0 || len > MAX_SEG_BYTES) return;
  if (fam < 0 || fam >= N_FAM) fam = FAM_UNKNOWN;
  if (kid_len != KID_LEN || !kid) kid_len = 0;
  if (ten < 0 || ten >= N_TEN) ten = TEN_NONE;
  std::lock_guard<std::mutex> lk(t->cache_mu);
  if (t->cache_used >= CACHE_CAP) {  // clear at cap, like _HDR_CACHE
    for (auto& e : t->slots) {
      e.used = false;
      e.seg.clear();
    }
    t->cache_used = 0;
  }
  bool found;
  CacheEnt* e = find_slot(t, seg, len, &found);
  if (!found) {
    e->seg.assign((const char*)seg, (size_t)len);
    e->used = true;
    t->cache_used++;
  }
  e->fam = (int8_t)fam;
  e->kid_len = (uint8_t)kid_len;
  e->ten = (int16_t)ten;
  if (kid_len) std::memcpy(e->kid, kid, (size_t)kid_len);
}

// -- histograms -------------------------------------------------------------

void observe(TelPlane* t, int32_t series, double value) {
  if (series < 0 || series >= N_SERIES) return;
  Hist& h = t->series[series];
  // bisect_left: first index whose bound is >= value (lower_bound's
  // contract is identical, which the parity test pins over fuzz).
  size_t idx = (size_t)(std::lower_bound(t->bounds.begin(),
                                         t->bounds.end(), value) -
                        t->bounds.begin());
  std::lock_guard<std::mutex> lk(h.mu);
  h.counts[idx]++;
  if (h.count == 0) {
    h.vmin = value;
    h.vmax = value;
  } else {
    if (value < h.vmin) h.vmin = value;
    if (value > h.vmax) h.vmax = value;
  }
  h.count++;
  h.sum += value;
}

// telemetry.Histogram.add_many: k observations of one value in one
// bucket add, sum += value * k — the per-(chunk, tenant) latency
// fold. The arithmetic ORDER matches the Python side exactly, so
// merged states stay bit-identical.
static void hist_add_many(TelPlane* t, Hist& h, double value,
                          int64_t k) {
  if (k <= 0) return;
  size_t idx = (size_t)(std::lower_bound(t->bounds.begin(),
                                         t->bounds.end(), value) -
                        t->bounds.begin());
  std::lock_guard<std::mutex> lk(h.mu);
  h.counts[idx] += k;
  if (h.count == 0) {
    h.vmin = value;
    h.vmax = value;
  } else {
    if (value < h.vmin) h.vmin = value;
    if (value > h.vmax) h.vmax = value;
  }
  h.count += k;
  // volatile: forbid the compiler from contracting the multiply-add
  // into one FMA (-O3 -march=native does) — Python rounds the product
  // BEFORE the add, and the parity pin is bit-exact sums
  volatile double add = value * (double)k;
  h.sum += add;
}

// -- the fold ---------------------------------------------------------------

static void build_exemplar(Exemplar& ex, int32_t key, int8_t fam,
                           int32_t lat_idx, const uint8_t* kid12,
                           const uint8_t* trace, int32_t trace_len) {
  uint8_t* r = ex.rec;
  std::memset(r, 0, EX_STRIDE);
  r[0] = (uint8_t)key;
  r[1] = (uint8_t)fam;
  r[2] = (uint8_t)lat_idx;
  bool has_kid = false;
  for (int i = 0; i < KID_LEN; i++)
    if (kid12[i]) has_kid = true;
  if (has_kid) {
    r[3] = KID_LEN;
    std::memcpy(r + 4, kid12, KID_LEN);
  }
  if (trace && trace_len > 0 && trace_len <= 64) {
    r[16] = (uint8_t)trace_len;
    std::memcpy(r + 17, trace, (size_t)trace_len);
  }
}

void fold(TelPlane* t, int64_t n_tokens, const uint8_t* statuses,
          const uint8_t* reasons, const int8_t* fams,
          const int16_t* tens, const uint8_t* kids, int32_t lat_idx,
          double lat_s, const uint8_t* trace, int32_t trace_len) {
  if (n_tokens <= 0) return;  // record_batch: empty chunk is a no-op
  if (lat_idx < 0 || lat_idx >= N_LAT) lat_idx = LAT_NA;
  // one pass: group token indices by decision key, count families
  // and tenants — the same grouping record_batch builds before its
  // count_many call. Tenant counts accumulate on the stack (~7 KB)
  // and apply as ONE atomic add per touched key per chunk.
  std::vector<int32_t> accept_idx;
  std::vector<int32_t> rej_idx[N_REASON];
  int reason_order[N_REASON];
  int n_reasons = 0;
  bool seen[N_REASON] = {};
  int64_t fam_counts[N_FAM] = {};
  int64_t tloc[N_TEN * TEN_STRIDE];
  std::memset(tloc, 0, sizeof(tloc));
  for (int64_t i = 0; i < n_tokens; i++) {
    int f = fams ? fams[i] : FAM_UNKNOWN;
    if (f < 0 || f >= N_FAM) f = FAM_UNKNOWN;
    fam_counts[f]++;
    int ten = tens ? tens[i] : TEN_NONE;
    if (ten < 0 || ten >= N_TEN) ten = TEN_NONE;
    int64_t* tb = tloc + ten * TEN_STRIDE;
    tb[0]++;  // tokens
    if (!statuses || statuses[i] == 0) {
      accept_idx.push_back((int32_t)i);
      tb[1]++;  // accept
    } else {
      int r = reasons ? reasons[i] : (N_REASON - 1);  // internal
      if (r < 0 || r >= N_REASON) r = N_REASON - 1;
      if (!seen[r]) {
        seen[r] = true;
        reason_order[n_reasons++] = r;  // first-occurrence order
      }
      rej_idx[r].push_back((int32_t)i);
      tb[2]++;        // reject total
      tb[3 + r]++;    // reject by reason
    }
  }
  for (int f = 0; f < N_FAM; f++)
    if (fam_counts[f])
      t->ctr[CTR_FAM0 + f].fetch_add(fam_counts[f],
                                     std::memory_order_relaxed);
  // tenant counters + the exact lookups == attributed + overflow
  // equation (record_batch emits the same three globals)
  int64_t ovf = tloc[TEN_OTHER * TEN_STRIDE + 0];
  t->tctr[TCTR_LOOKUPS].fetch_add(n_tokens, std::memory_order_relaxed);
  if (n_tokens - ovf)
    t->tctr[TCTR_ATTRIBUTED].fetch_add(n_tokens - ovf,
                                       std::memory_order_relaxed);
  if (ovf)
    t->tctr[TCTR_OVERFLOW].fetch_add(ovf, std::memory_order_relaxed);
  for (int s = 0; s < N_TEN; s++) {
    int64_t* tb = tloc + s * TEN_STRIDE;
    if (!tb[0]) continue;
    for (int j = 0; j < TEN_STRIDE; j++)
      if (tb[j])
        t->tctr[TCTR_BASE + s * TEN_STRIDE + j].fetch_add(
            tb[j], std::memory_order_relaxed);
    // per-tenant latency histogram: every token of the chunk
    // observes the chunk latency, as one bucket add of k
    // (record_batch's serve-surface observe_many)
    if (lat_s >= 0.0)
      hist_add_many(t, t->ten_hist[s], lat_s, tb[0]);
  }
  std::vector<Exemplar> exs;
  static const uint8_t no_kid[KID_LEN] = {};
  auto emit = [&](int key, std::atomic<int64_t>& c,
                  const std::vector<int32_t>& idxs) {
    int64_t k = (int64_t)idxs.size();
    if (!k) return;
    int64_t after = c.fetch_add(k, std::memory_order_relaxed) + k;
    int64_t start = after - k;
    // record_batch.bulk(): sampled counts are 1 (first ever) plus
    // every SAMPLE_EVERY-th, attributed to idxs[c - start - 1].
    auto sample = [&](int64_t cval) {
      int32_t i = idxs[(size_t)(cval - start - 1)];
      int f = fams ? fams[i] : FAM_UNKNOWN;
      if (f < 0 || f >= N_FAM) f = FAM_UNKNOWN;
      exs.emplace_back();
      build_exemplar(exs.back(), key, (int8_t)f, lat_idx,
                     kids ? kids + (size_t)i * KID_LEN : no_kid, trace,
                     trace_len);
    };
    if (start == 0) sample(1);
    for (int64_t m = (start / SAMPLE_EVERY + 1) * SAMPLE_EVERY;
         m <= after; m += SAMPLE_EVERY)
      sample(m);
  };
  emit(0, t->ctr[CTR_ACCEPT], accept_idx);  // accepts first, like bulk
  for (int j = 0; j < n_reasons; j++) {
    int r = reason_order[j];
    emit(1 + r, t->ctr[CTR_REJECT0 + r], rej_idx[r]);
  }
  if (!exs.empty()) {
    std::lock_guard<std::mutex> lk(t->ex_mu);
    for (auto& ex : exs) {
      if (t->ex_len == EX_RING)
        t->ctr[CTR_EX_DROPS].fetch_add(1, std::memory_order_relaxed);
      else
        t->ex_len++;
      t->ex_ring[t->ex_head % EX_RING] = ex;
      t->ex_head++;
    }
  }
}

}  // namespace cap_tel

// ---------------------------------------------------------------------------
// C ABI (ctypes binding in serve/native_serve.py; also driven
// standalone by the fuzz parity sweep in tests/test_native_obs.py)
// ---------------------------------------------------------------------------

using namespace cap_tel;

extern "C" {

// Layout handshake: the binding checks these against the Python-side
// registries before enabling the plane (index-vocabulary drift in a
// stale .so must disable the plane, never miscount).
void cap_tel_layout(int32_t* out) {
  out[0] = N_REASON;
  out[1] = N_FAM;
  out[2] = N_LAT;
  out[3] = N_CTR;
  out[4] = EX_STRIDE;
  out[5] = N_SERIES;
  out[6] = SAMPLE_EVERY;
  out[7] = EX_RING;
}

// Tenant-block handshake (r19, a separate symbol so its ABSENCE in a
// stale .so disables the plane cleanly — the binding requires it):
// slot count, per-slot stride, total tenant-counter block, overflow
// slot index. Any drift from obs/decision's registries → plane off.
void cap_tel_layout_ten(int32_t* out) {
  out[0] = N_TEN;
  out[1] = TEN_STRIDE;
  out[2] = N_TCTR;
  out[3] = TEN_OTHER;
}

void* cap_tel_create(const double* bounds, int32_t n_bounds) {
  return create(bounds, n_bounds);
}

void cap_tel_destroy(void* t) { destroy((TelPlane*)t); }

int32_t cap_tel_classify_seg(void* t, const uint8_t* seg, int64_t len,
                             uint8_t* kid_out, int32_t* kid_len_out,
                             int16_t* ten_out) {
  return classify((TelPlane*)t, seg, len, kid_out, kid_len_out,
                  ten_out);
}

void cap_tel_learn(void* t, const uint8_t* seg, int64_t len,
                   int32_t fam, const uint8_t* kid, int32_t kid_len,
                   int32_t ten) {
  learn((TelPlane*)t, seg, len, fam, kid, kid_len, ten);
}

void cap_tel_fold(void* t, int64_t n_tokens, const uint8_t* statuses,
                  const uint8_t* reasons, const int8_t* fams,
                  const int16_t* tens, const uint8_t* kids,
                  int32_t lat_idx, double lat_s, const uint8_t* trace,
                  int32_t trace_len) {
  fold((TelPlane*)t, n_tokens, statuses, reasons, fams, tens, kids,
       lat_idx, lat_s, trace, trace_len);
}

void cap_tel_hist_observe(void* t, int32_t series, double value) {
  observe((TelPlane*)t, series, value);
}

void cap_tel_counters(void* t, int64_t* out) {
  TelPlane* p = (TelPlane*)t;
  for (int i = 0; i < N_CTR; i++)
    out[i] = p->ctr[i].load(std::memory_order_relaxed);
}

// The whole tenant counter block (N_TCTR slots, telemetry_native.h
// layout); the binding maps nonzero slots back to labels.
void cap_tel_tenant_counters(void* t, int64_t* out) {
  TelPlane* p = (TelPlane*)t;
  for (int i = 0; i < N_TCTR; i++)
    out[i] = p->tctr[i].load(std::memory_order_relaxed);
}

// One tenant slot's latency-histogram state (same shape as
// cap_tel_hist_state).
void cap_tel_tenant_hist_state(void* t, int32_t slot,
                               int64_t* bucket_out, int64_t* count_out,
                               double* sum_out, double* min_out,
                               double* max_out) {
  TelPlane* p = (TelPlane*)t;
  if (slot < 0 || slot >= N_TEN) return;
  Hist& h = p->ten_hist[slot];
  std::lock_guard<std::mutex> lk(h.mu);
  std::memcpy(bucket_out, h.counts.data(),
              h.counts.size() * sizeof(int64_t));
  *count_out = h.count;
  *sum_out = h.sum;
  *min_out = h.vmin;
  *max_out = h.vmax;
}

// Histogram state for one series: bucket counts (n_bounds + 1 slots)
// + count/sum/min/max — telemetry.Histogram.state()'s fields, so the
// binding can emit a mergeable snapshot entry.
void cap_tel_hist_state(void* t, int32_t series, int64_t* bucket_out,
                        int64_t* count_out, double* sum_out,
                        double* min_out, double* max_out) {
  TelPlane* p = (TelPlane*)t;
  if (series < 0 || series >= N_SERIES) return;
  Hist& h = p->series[series];
  std::lock_guard<std::mutex> lk(h.mu);
  std::memcpy(bucket_out, h.counts.data(),
              h.counts.size() * sizeof(int64_t));
  *count_out = h.count;
  *sum_out = h.sum;
  *min_out = h.vmin;
  *max_out = h.vmax;
}

// Drain queued exemplars (FIFO, oldest first) into out (EX_STRIDE
// bytes per record); returns how many were written.
int32_t cap_tel_drain_exemplars(void* t, uint8_t* out, int32_t max_n) {
  TelPlane* p = (TelPlane*)t;
  std::lock_guard<std::mutex> lk(p->ex_mu);
  int32_t n = 0;
  while (p->ex_len > 0 && n < max_n) {
    int64_t slot = (p->ex_head - p->ex_len) % EX_RING;
    std::memcpy(out + (size_t)n * EX_STRIDE, p->ex_ring[slot].rec,
                EX_STRIDE);
    p->ex_len--;
    n++;
  }
  return n;
}

void cap_tel_reset(void* t) {
  TelPlane* p = (TelPlane*)t;
  for (auto& c : p->ctr) c.store(0);
  for (auto& c : p->tctr) c.store(0);
  {
    std::lock_guard<std::mutex> lk(p->ex_mu);
    p->ex_head = 0;
    p->ex_len = 0;
  }
  for (auto& h : p->series) {
    std::lock_guard<std::mutex> lk(h.mu);
    std::fill(h.counts.begin(), h.counts.end(), 0);
    h.count = 0;
    h.sum = h.vmin = h.vmax = 0.0;
  }
  for (auto& h : p->ten_hist) {
    std::lock_guard<std::mutex> lk(h.mu);
    std::fill(h.counts.begin(), h.counts.end(), 0);
    h.count = 0;
    h.sum = h.vmin = h.vmax = 0.0;
  }
  std::lock_guard<std::mutex> lk(p->cache_mu);
  for (auto& e : p->slots) {
    e.used = false;
    e.seg.clear();
  }
  p->cache_used = 0;
}

}  // extern "C"
