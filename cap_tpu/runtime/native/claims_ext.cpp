// _capclaims — native batch claims-JSON parsing for cap_tpu.
//
// The reference parses claims with encoding/json per token inside its
// verify path (jwt/validator.go UnmarshalClaims → map[string]interface{});
// the Python analog (json.loads per payload) costs 5-25 µs/token on the
// host and sits on the GIL, capping honest unique-token batch
// throughput. This extension splits the work:
//
//   phase 1 (GIL RELEASED, multithreaded): every payload is scanned by
//     a strict JSON parser into a flat numeric "tape" — string/number
//     spans, structural ops — with all validation done here;
//   phase 2 (GIL held, single pass): the tapes replay into Python
//     objects. Claim KEYS — and short string VALUES (issuer URLs,
//     audiences, scopes) — repeat massively across tokens, so
//     byte-exact hash intern tables reuse one PyUnicode per distinct
//     byte string, and dicts are presized from phase-1 key counts.
//
// Fidelity contract: for any payload this parser accepts, the result
// is indistinguishable from json.loads(payload); anything outside the
// supported envelope (depth > 64, NaN/Infinity literals, lone
// surrogates, ints > 4300 digits, ...) is flagged FALLBACK and the
// Python side re-parses that token with json.loads — never a silent
// behavioural difference. Malformed JSON is flagged with a parse error
// the Python side maps to MalformedTokenError (same taxonomy as the
// jose path).
//
// Build: make native (g++ -O3 -shared -fPIC -pthread, linked against
// the CPython headers; the module ships as source and is compiled on
// first use like the rest of the native runtime).

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <dlfcn.h>

#include "claims_tape.h"

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Phase 1 lives in claims_tape.h (Parser/TokenTape/Op/Status), shared
// with the native claims-rule engine (claims_validate.cpp) so the two
// consumers of the tape can never drift on what they accept.
// ---------------------------------------------------------------------------

using capclaims::kMaxIntDigits;
using capclaims::Parser;
using capclaims::TokenTape;
using capclaims::OP_OBJ_START;
using capclaims::OP_OBJ_END;
using capclaims::OP_ARR_START;
using capclaims::OP_ARR_END;
using capclaims::OP_KEY;
using capclaims::OP_STR;
using capclaims::OP_INT;
using capclaims::OP_BIGINT;
using capclaims::OP_FLOAT;
using capclaims::OP_TRUE;
using capclaims::OP_FALSE;
using capclaims::OP_NULL;
using capclaims::ST_OK;
using capclaims::ST_MALFORMED;
using capclaims::ST_NOT_OBJECT;
using capclaims::ST_FALLBACK;

// ---------------------------------------------------------------------------
// Phase 2: tape → Python objects
// ---------------------------------------------------------------------------

// Byte-exact string intern table (open addressing, FNV-1a). Two uses:
//   keys   — claims keys ("iss", "sub", "exp", ...) repeat across every
//            token in a batch; one interned PyUnicode per distinct key
//            makes dict fills cheap (cached hash, pointer-equal keys);
//   values — short unescaped string VALUES (issuer URLs, audiences,
//            scopes) also repeat per-batch; sharing one PyUnicode turns
//            ~half the per-token decodes into INCREFs. Strings are
//            immutable, so sharing across result dicts is safe.
// Bounded: past max_entries, get() declines and the caller decodes
// directly (degenerate all-unique batches stay O(1) per miss because a
// miss probes an under-half-full table, not a growing list).
struct InternTable {
  struct Slot {
    uint64_t hash = 0;
    uint32_t off = 0;
    uint32_t len = 0;
    PyObject* obj = nullptr;  // owned; nullptr = empty slot
  };
  std::vector<Slot> slots;  // power-of-two size, load factor ≤ 1/2
  std::string arena;        // backing bytes for stored entries
  size_t count = 0;
  size_t max_entries;
  bool intern;  // keys get PyUnicode_InternInPlace; values do not

  InternTable(size_t n_slots_pow2, size_t cap, bool intern_keys)
      : slots(n_slots_pow2), max_entries(cap), intern(intern_keys) {}
  ~InternTable() {
    for (auto& s : slots) Py_XDECREF(s.obj);
  }

  static uint64_t fnv1a(const uint8_t* p, size_t n) {
    uint64_t h = 1469598103934665603ull;
    for (size_t i = 0; i < n; ++i) {
      h ^= p[i];
      h *= 1099511628211ull;
    }
    return h;
  }

  // Borrowed reference, or nullptr when the caller should decode
  // directly (table full, or — impossible for phase-1-validated UTF-8 —
  // decode failure; the caller's own decode then raises properly).
  PyObject* get(const uint8_t* data, size_t len) {
    uint64_t h = fnv1a(data, len);
    size_t mask = slots.size() - 1;
    size_t j = static_cast<size_t>(h) & mask;
    while (slots[j].obj != nullptr) {
      if (slots[j].hash == h && slots[j].len == len &&
          std::memcmp(arena.data() + slots[j].off, data, len) == 0)
        return slots[j].obj;
      j = (j + 1) & mask;
    }
    if (count >= max_entries) return nullptr;
    PyObject* o = PyUnicode_DecodeUTF8(reinterpret_cast<const char*>(data),
                                       static_cast<Py_ssize_t>(len),
                                       nullptr);
    if (o == nullptr) return nullptr;
    if (intern) PyUnicode_InternInPlace(&o);
    slots[j].hash = h;
    slots[j].off = static_cast<uint32_t>(arena.size());
    slots[j].len = static_cast<uint32_t>(len);
    slots[j].obj = o;
    arena.append(reinterpret_cast<const char*>(data), len);
    ++count;
    return o;
  }
};

// Value strings longer than this decode directly: long strings amortize
// their own decode, and the arena stays small.
constexpr size_t kMaxCachedValueLen = 64;

// dlsym-resolved _PyDict_NewPresized (CPython private API, exported and
// stable in practice; pydantic-core relies on it the same way). One
// claims dict has ~8-12 keys — past 5, PyDict_New's initial table
// resizes mid-fill, so presizing saves an alloc + rehash per token.
// nullptr (symbol absent) falls back to PyDict_New.
using DictNewPresizedFn = PyObject* (*)(Py_ssize_t);
DictNewPresizedFn dict_new_presized = nullptr;

PyObject* decode_escaped(const uint8_t* data, size_t len) {
  // Unescape into a scratch, then UTF-8 decode. Validation already
  // happened in phase 1, so escapes are well-formed and non-surrogate.
  std::string buf;
  buf.reserve(len);
  size_t i = 0;
  while (i < len) {
    uint8_t c = data[i];
    if (c != '\\') {
      buf.push_back(static_cast<char>(c));
      ++i;
      continue;
    }
    uint8_t e = data[i + 1];
    switch (e) {
      case '"': buf.push_back('"'); i += 2; break;
      case '\\': buf.push_back('\\'); i += 2; break;
      case '/': buf.push_back('/'); i += 2; break;
      case 'b': buf.push_back('\b'); i += 2; break;
      case 'f': buf.push_back('\f'); i += 2; break;
      case 'n': buf.push_back('\n'); i += 2; break;
      case 'r': buf.push_back('\r'); i += 2; break;
      case 't': buf.push_back('\t'); i += 2; break;
      default: {  // \uXXXX (non-surrogate — surrogates went to fallback)
        uint32_t v = 0;
        for (int k = 2; k <= 5; ++k) {
          uint8_t h = data[i + k];
          v = v * 16 + (h <= '9' ? h - '0' : (h | 32) - 'a' + 10);
        }
        if (v < 0x80) {
          buf.push_back(static_cast<char>(v));
        } else if (v < 0x800) {
          buf.push_back(static_cast<char>(0xC0 | (v >> 6)));
          buf.push_back(static_cast<char>(0x80 | (v & 0x3F)));
        } else {
          buf.push_back(static_cast<char>(0xE0 | (v >> 12)));
          buf.push_back(static_cast<char>(0x80 | ((v >> 6) & 0x3F)));
          buf.push_back(static_cast<char>(0x80 | (v & 0x3F)));
        }
        i += 6;
      }
    }
  }
  return PyUnicode_DecodeUTF8(buf.data(),
                              static_cast<Py_ssize_t>(buf.size()), nullptr);
}

// Replay one token's tape. Returns a new reference, or nullptr with a
// Python exception set.
PyObject* build_from_tape(const TokenTape& tape, const uint8_t* payload,
                          InternTable* keys, InternTable* strs) {
  // Explicit container stack; values attach to the top container (dict
  // via pending key, list via append).
  struct Frame {
    PyObject* container;  // owned here until popped
    PyObject* key;        // owned; pending dict key
  };
  std::vector<Frame> stack;
  PyObject* root = nullptr;

  auto attach = [&](PyObject* v) -> bool {  // steals v
    if (stack.empty()) {
      root = v;
      return true;
    }
    Frame& f = stack.back();
    if (PyDict_CheckExact(f.container)) {
      int rc = PyDict_SetItem(f.container, f.key, v);
      Py_DECREF(v);
      Py_CLEAR(f.key);
      return rc == 0;
    }
    int rc = PyList_Append(f.container, v);
    Py_DECREF(v);
    return rc == 0;
  };
  auto fail = [&]() -> PyObject* {
    for (auto& f : stack) {
      Py_XDECREF(f.container);
      Py_XDECREF(f.key);
    }
    Py_XDECREF(root);
    return nullptr;
  };

  const uint32_t* ops = tape.ops.data();
  size_t nops = tape.ops.size();
  for (size_t t = 0; t < nops; t += 3) {
    uint32_t op = ops[t], a = ops[t + 1], b = ops[t + 2];
    switch (op) {
      case OP_OBJ_START: {
        // `a` = key count (backpatched by phase 1); CPython's fresh
        // dict already holds 5 entries, so presize only beyond that.
        PyObject* d = (a > 5 && dict_new_presized != nullptr)
                          ? dict_new_presized(static_cast<Py_ssize_t>(a))
                          : PyDict_New();
        if (d == nullptr) return fail();
        stack.push_back({d, nullptr});
        break;
      }
      case OP_ARR_START: {
        PyObject* l = PyList_New(0);
        if (l == nullptr) return fail();
        stack.push_back({l, nullptr});
        break;
      }
      case OP_OBJ_END:
      case OP_ARR_END: {
        PyObject* done = stack.back().container;
        Py_XDECREF(stack.back().key);
        stack.pop_back();
        if (!attach(done)) return fail();
        break;
      }
      case OP_KEY: {
        uint32_t len = b >> 1, esc = b & 1;
        const char* data = reinterpret_cast<const char*>(payload + a);
        PyObject* k;
        if (esc) {
          k = decode_escaped(payload + a, len);
        } else {
          PyObject* cached =
              keys->get(reinterpret_cast<const uint8_t*>(data), len);
          if (cached != nullptr) {
            Py_INCREF(cached);
            k = cached;
          } else {
            k = PyUnicode_DecodeUTF8(data, static_cast<Py_ssize_t>(len),
                                     nullptr);
          }
        }
        if (k == nullptr) return fail();
        Py_XDECREF(stack.back().key);
        stack.back().key = k;
        break;
      }
      case OP_STR: {
        uint32_t len = b >> 1, esc = b & 1;
        PyObject* v = nullptr;
        if (!esc && len <= kMaxCachedValueLen) {
          PyObject* cached = strs->get(payload + a, len);
          if (cached != nullptr) {
            Py_INCREF(cached);
            v = cached;
          }
        }
        if (v == nullptr) {
          v = esc ? decode_escaped(payload + a, len)
                  : PyUnicode_DecodeUTF8(
                        reinterpret_cast<const char*>(payload + a),
                        static_cast<Py_ssize_t>(len), nullptr);
        }
        if (v == nullptr || !attach(v)) return fail();
        break;
      }
      case OP_INT: {
        int64_t iv = static_cast<int64_t>(
            (static_cast<uint64_t>(b) << 32) | a);
        PyObject* v = PyLong_FromLongLong(iv);
        if (v == nullptr || !attach(v)) return fail();
        break;
      }
      case OP_BIGINT: {
        char buf[kMaxIntDigits + 2];
        std::memcpy(buf, payload + a, b);
        buf[b] = 0;
        PyObject* v = PyLong_FromString(buf, nullptr, 10);
        if (v == nullptr || !attach(v)) return fail();
        break;
      }
      case OP_FLOAT: {
        uint64_t bits = (static_cast<uint64_t>(b) << 32) | a;
        double d;
        std::memcpy(&d, &bits, 8);
        PyObject* v = PyFloat_FromDouble(d);
        if (v == nullptr || !attach(v)) return fail();
        break;
      }
      case OP_TRUE:
      case OP_FALSE: {
        PyObject* v = op == OP_TRUE ? Py_True : Py_False;
        Py_INCREF(v);
        if (!attach(v)) return fail();
        break;
      }
      case OP_NULL: {
        Py_INCREF(Py_None);
        if (!attach(Py_None)) return fail();
        break;
      }
      default:
        PyErr_SetString(PyExc_SystemError, "corrupt claims tape");
        return fail();
    }
  }
  return root;
}

// ---------------------------------------------------------------------------
// Module entry: parse_batch(scratch, offsets, lengths) → (list, n_bad)
// ---------------------------------------------------------------------------

// Shared phase-1 scaffolding: argument/bounds validation + the GIL-free
// multithreaded scan. per_token(i, tape) runs off the GIL and must not
// touch Python state; both parse_batch and validate_batch ride this so
// a bounds or thread-sizing fix can never diverge between them.
template <typename PerToken>
bool run_phase1(Py_buffer* scratch, Py_buffer* offv, Py_buffer* lenv,
                int n_threads, PerToken per_token) {
  const uint8_t* base = static_cast<const uint8_t*>(scratch->buf);
  const int64_t* offs = static_cast<const int64_t*>(offv->buf);
  const int64_t* lens = static_cast<const int64_t*>(lenv->buf);
  Py_ssize_t n = offv->len / static_cast<Py_ssize_t>(sizeof(int64_t));

  bool bounds_ok = lenv->len == offv->len;
  for (Py_ssize_t i = 0; bounds_ok && i < n; ++i) {
    if (offs[i] < 0 || lens[i] < 0 || offs[i] + lens[i] > scratch->len)
      bounds_ok = false;
  }
  if (!bounds_ok) {
    PyErr_SetString(PyExc_ValueError, "offsets/lengths out of bounds");
    return false;
  }

  Py_BEGIN_ALLOW_THREADS
  unsigned hw = std::thread::hardware_concurrency();
  size_t workers = n_threads > 0 ? static_cast<size_t>(n_threads)
                                 : (hw ? hw : 4);
  if (workers > static_cast<size_t>(n) && n > 0)
    workers = static_cast<size_t>(n);
  if (workers <= 1 || n < 256) {
    for (Py_ssize_t i = 0; i < n; ++i) {
      TokenTape tape;
      Parser p(base + offs[i], static_cast<size_t>(lens[i]), &tape);
      p.run();
      per_token(static_cast<size_t>(i), std::move(tape));
    }
  } else {
    std::vector<std::thread> pool;
    std::atomic<size_t> next{0};
    for (size_t w = 0; w < workers; ++w) {
      pool.emplace_back([&]() {
        constexpr size_t kGrain = 256;
        while (true) {
          size_t lo = next.fetch_add(kGrain);
          if (lo >= static_cast<size_t>(n)) return;
          size_t hi = lo + kGrain;
          if (hi > static_cast<size_t>(n)) hi = static_cast<size_t>(n);
          for (size_t i = lo; i < hi; ++i) {
            TokenTape tape;
            Parser p(base + offs[i], static_cast<size_t>(lens[i]),
                     &tape);
            p.run();
            per_token(i, std::move(tape));
          }
        }
      });
    }
    for (auto& t : pool) t.join();
  }
  Py_END_ALLOW_THREADS
  return true;
}

// Returns (results, n_bad): results is a list with one entry per token:
//   dict  — parsed claims
//   1     — malformed JSON        (int sentinel)
//   2     — valid JSON, not an object
//   3     — fallback: caller must json.loads this payload
// n_bad counts the non-dict entries, so the caller's common case
// (n_bad == 0) can bulk-insert the list without a per-token type scan.
PyObject* parse_batch(PyObject*, PyObject* args) {
  Py_buffer scratch, offv, lenv;
  int n_threads = 0;
  if (!PyArg_ParseTuple(args, "y*y*y*|i", &scratch, &offv, &lenv,
                        &n_threads))
    return nullptr;
  const uint8_t* base = static_cast<const uint8_t*>(scratch.buf);
  const int64_t* offs = static_cast<const int64_t*>(offv.buf);
  Py_ssize_t n = offv.len / static_cast<Py_ssize_t>(sizeof(int64_t));

  std::vector<TokenTape> tapes(static_cast<size_t>(n));
  bool ok = run_phase1(&scratch, &offv, &lenv, n_threads,
                       [&](size_t i, TokenTape&& tape) {
                         tapes[i] = std::move(tape);
                       });
  if (!ok) {
    PyBuffer_Release(&scratch);
    PyBuffer_Release(&offv);
    PyBuffer_Release(&lenv);
    return nullptr;
  }

  InternTable keys(/*n_slots_pow2=*/512, /*cap=*/256,
                   /*intern_keys=*/true);
  // Scale the value table with the batch so small batches (serve
  // batches of ~256, handfuls in tests) don't pay a fixed ~200 KB
  // zero-init before parsing the first token.
  size_t str_slots = 64;
  while (str_slots < static_cast<size_t>(n) * 8 && str_slots < 8192)
    str_slots <<= 1;
  InternTable strs(str_slots, str_slots / 2, /*intern_keys=*/false);
  Py_ssize_t n_bad = 0;
  PyObject* out = PyList_New(n);
  if (out == nullptr) {
    PyBuffer_Release(&scratch);
    PyBuffer_Release(&offv);
    PyBuffer_Release(&lenv);
    return nullptr;
  }
  bool err = false;
  for (Py_ssize_t i = 0; i < n && !err; ++i) {
    PyObject* item;
    if (tapes[i].status == ST_OK) {
      item = build_from_tape(tapes[static_cast<size_t>(i)], base + offs[i],
                             &keys, &strs);
      if (item == nullptr) err = true;
    } else {
      item = PyLong_FromLong(tapes[i].status);
      ++n_bad;
      if (item == nullptr) err = true;
    }
    if (!err) PyList_SET_ITEM(out, i, item);
  }
  PyBuffer_Release(&scratch);
  PyBuffer_Release(&offv);
  PyBuffer_Release(&lenv);
  if (err) {
    Py_DECREF(out);
    return nullptr;
  }
  PyObject* nb = PyLong_FromSsize_t(n_bad);
  if (nb == nullptr) {
    Py_DECREF(out);
    return nullptr;
  }
  PyObject* ret = PyTuple_Pack(2, out, nb);
  Py_DECREF(out);
  Py_DECREF(nb);
  return ret;
}

// Phase 1 ONLY: per-token payload status byte, no Python objects.
// The serve path's raw-claims mode needs "is this a valid JSON object"
// (the signed payload bytes then pass through verbatim) without paying
// for dict construction. Status values are the parser's own:
// 0 = valid object, 1 = malformed, 2 = valid JSON but not an object,
// 3 = outside the strict parser's envelope (caller decides via
// json.loads). Scan runs GIL-free across threads like parse_batch.
PyObject* validate_batch(PyObject*, PyObject* args) {
  Py_buffer scratch, offv, lenv;
  int n_threads = 0;
  if (!PyArg_ParseTuple(args, "y*y*y*|i", &scratch, &offv, &lenv,
                        &n_threads))
    return nullptr;
  Py_ssize_t n = offv.len / static_cast<Py_ssize_t>(sizeof(int64_t));

  PyObject* out = PyBytes_FromStringAndSize(nullptr, n);
  if (out == nullptr) {
    PyBuffer_Release(&scratch);
    PyBuffer_Release(&offv);
    PyBuffer_Release(&lenv);
    return nullptr;
  }
  uint8_t* st = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));

  bool ok = run_phase1(&scratch, &offv, &lenv, n_threads,
                       [&](size_t i, TokenTape&& tape) {
                         st[i] = static_cast<uint8_t>(tape.status);
                       });
  PyBuffer_Release(&scratch);
  PyBuffer_Release(&offv);
  PyBuffer_Release(&lenv);
  if (!ok) {
    Py_DECREF(out);
    return nullptr;
  }
  return out;
}

// ---------------------------------------------------------------------------
// registered_batch: ONLY the registered id_token claims, no full dicts
// ---------------------------------------------------------------------------
//
// The OIDC batch validator reads exactly these top-level claims:
// iss, sub, aud, exp, nbf, iat, nonce, azp, auth_time. Materializing a
// 9-key subset dict from the phase-1 tape skips the full claims dict
// (every key, every value, every nested container) for tokens whose
// payload is only being VALIDATED — the raw-claims OIDC mode, where
// accepted tokens return their signed payload bytes verbatim
// (provider.verify_id_token_batch(raw=True); the serve-path analog).
//
// Conservative fallbacks (status 3 → caller re-parses with json.loads
// and validates from the full dict, so semantics never diverge):
//   - any ESCAPED top-level key (an escape could spell a registered
//     name; the full parser would match it);
//   - a registered claim whose value is an object or a non-flat array
//     (the validator's type checks must see the exact parsed shape).

static const struct {
  const char* name;
  uint32_t len;
} kRegistered[] = {
    {"iss", 3}, {"sub", 3}, {"aud", 3},   {"exp", 3},       {"nbf", 3},
    {"iat", 3}, {"azp", 3}, {"nonce", 5}, {"auth_time", 9},
};
constexpr int kNumRegistered =
    static_cast<int>(sizeof(kRegistered) / sizeof(kRegistered[0]));

// Scalar tape entry → new ref; nullptr with *is_scalar=false for
// container ops (no Python error raised in that case).
PyObject* scalar_of(uint32_t op, uint32_t a, uint32_t b,
                    const uint8_t* payload, bool* is_scalar) {
  *is_scalar = true;
  switch (op) {
    case OP_STR: {
      uint32_t len = b >> 1, esc = b & 1;
      return esc ? decode_escaped(payload + a, len)
                 : PyUnicode_DecodeUTF8(
                       reinterpret_cast<const char*>(payload + a),
                       static_cast<Py_ssize_t>(len), nullptr);
    }
    case OP_INT:
      return PyLong_FromLongLong(static_cast<int64_t>(
          (static_cast<uint64_t>(b) << 32) | a));
    case OP_BIGINT: {
      char buf[kMaxIntDigits + 2];
      std::memcpy(buf, payload + a, b);
      buf[b] = 0;
      return PyLong_FromString(buf, nullptr, 10);
    }
    case OP_FLOAT: {
      uint64_t bits = (static_cast<uint64_t>(b) << 32) | a;
      double d;
      std::memcpy(&d, &bits, 8);
      return PyFloat_FromDouble(d);
    }
    case OP_TRUE:
      Py_RETURN_TRUE;
    case OP_FALSE:
      Py_RETURN_FALSE;
    case OP_NULL:
      Py_RETURN_NONE;
    default:
      *is_scalar = false;
      return nullptr;
  }
}

// Subset dict from one ST_OK tape; nullptr + *fallback for the
// conservative cases above; nullptr without *fallback on real errors.
PyObject* build_registered(const TokenTape& tape, const uint8_t* payload,
                           bool* fallback) {
  *fallback = false;
  const uint32_t* ops = tape.ops.data();
  size_t nops = tape.ops.size();
  PyObject* out = PyDict_New();
  if (out == nullptr) return nullptr;
  int depth = 0;
  int reg = -1;  // pending registered key index at depth 1

  auto bail = [&](bool fb) -> PyObject* {
    *fallback = fb;
    Py_DECREF(out);
    return nullptr;
  };
  auto set_reg = [&](PyObject* v) -> bool {  // steals v
    int rc = PyDict_SetItemString(out, kRegistered[reg].name, v);
    Py_DECREF(v);
    reg = -1;
    return rc == 0;
  };

  for (size_t t = 0; t < nops; t += 3) {
    uint32_t op = ops[t], a = ops[t + 1], b = ops[t + 2];
    switch (op) {
      case OP_OBJ_START:
        if (reg >= 0 && depth == 1) return bail(true);
        ++depth;
        break;
      case OP_ARR_START: {
        if (reg >= 0 && depth == 1) {
          // flat scalar array (the aud shape); anything nested → full
          PyObject* lst = PyList_New(0);
          if (lst == nullptr) return bail(false);
          size_t u = t + 3;
          for (; u < nops; u += 3) {
            if (ops[u] == OP_ARR_END) break;
            bool is_scalar;
            PyObject* v = scalar_of(ops[u], ops[u + 1], ops[u + 2],
                                    payload, &is_scalar);
            if (!is_scalar) {
              Py_DECREF(lst);
              return bail(true);
            }
            if (v == nullptr || PyList_Append(lst, v) != 0) {
              Py_XDECREF(v);
              Py_DECREF(lst);
              return bail(false);
            }
            Py_DECREF(v);
          }
          if (u >= nops) {
            Py_DECREF(lst);
            PyErr_SetString(PyExc_SystemError, "corrupt claims tape");
            return bail(false);
          }
          if (!set_reg(lst)) return bail(false);
          t = u;  // at OP_ARR_END; loop increment skips it
          break;
        }
        ++depth;
        break;
      }
      case OP_OBJ_END:
      case OP_ARR_END:
        --depth;
        break;
      case OP_KEY: {
        if (depth != 1) break;
        uint32_t len = b >> 1, esc = b & 1;
        if (esc) return bail(true);  // could spell a registered name
        reg = -1;
        for (int r = 0; r < kNumRegistered; ++r) {
          if (kRegistered[r].len == len &&
              std::memcmp(payload + a, kRegistered[r].name, len) == 0) {
            reg = r;
            break;
          }
        }
        break;
      }
      default: {
        if (reg >= 0 && depth == 1) {
          bool is_scalar;
          PyObject* v = scalar_of(op, a, b, payload, &is_scalar);
          if (v == nullptr) {
            if (!is_scalar)  // unknown future op: fail LOUDLY, like
                             // build_from_tape's corrupt-tape guard
              PyErr_SetString(PyExc_SystemError, "corrupt claims tape");
            return bail(false);
          }
          if (!set_reg(v)) return bail(false);
        }
        break;
      }
    }
  }
  return out;
}

// Same calling convention and status protocol as parse_batch, but list
// entries are SUBSET dicts (registered claims only). Status 3 also
// covers the conservative fallbacks documented above.
PyObject* registered_batch(PyObject*, PyObject* args) {
  Py_buffer scratch, offv, lenv;
  int n_threads = 0;
  if (!PyArg_ParseTuple(args, "y*y*y*|i", &scratch, &offv, &lenv,
                        &n_threads))
    return nullptr;
  const uint8_t* base = static_cast<const uint8_t*>(scratch.buf);
  const int64_t* offs = static_cast<const int64_t*>(offv.buf);
  Py_ssize_t n = offv.len / static_cast<Py_ssize_t>(sizeof(int64_t));

  std::vector<TokenTape> tapes(static_cast<size_t>(n));
  bool ok = run_phase1(&scratch, &offv, &lenv, n_threads,
                       [&](size_t i, TokenTape&& tape) {
                         tapes[i] = std::move(tape);
                       });
  if (!ok) {
    PyBuffer_Release(&scratch);
    PyBuffer_Release(&offv);
    PyBuffer_Release(&lenv);
    return nullptr;
  }
  Py_ssize_t n_bad = 0;
  PyObject* out = PyList_New(n);
  bool err = out == nullptr;
  for (Py_ssize_t i = 0; i < n && !err; ++i) {
    PyObject* item = nullptr;
    int32_t status = tapes[i].status;
    if (status == ST_OK) {
      bool fb = false;
      item = build_registered(tapes[static_cast<size_t>(i)],
                              base + offs[i], &fb);
      if (item == nullptr) {
        if (!fb) {
          err = true;
        } else {
          status = ST_FALLBACK;
        }
      }
    }
    if (!err && item == nullptr) {
      item = PyLong_FromLong(status);
      ++n_bad;
      if (item == nullptr) err = true;
    }
    if (!err) PyList_SET_ITEM(out, i, item);
  }
  PyBuffer_Release(&scratch);
  PyBuffer_Release(&offv);
  PyBuffer_Release(&lenv);
  if (err) {
    Py_XDECREF(out);
    return nullptr;
  }
  PyObject* nb = PyLong_FromSsize_t(n_bad);
  if (nb == nullptr) {
    Py_DECREF(out);
    return nullptr;
  }
  PyObject* ret = PyTuple_Pack(2, out, nb);
  Py_DECREF(out);
  Py_DECREF(nb);
  return ret;
}

PyMethodDef methods[] = {
    {"parse_batch", parse_batch, METH_VARARGS,
     "parse_batch(scratch, offsets_i64, lengths_i64, n_threads=0) -> "
     "(list[dict | int-status], n_bad)"},
    {"registered_batch", registered_batch, METH_VARARGS,
     "registered_batch(scratch, offsets_i64, lengths_i64, n_threads=0)"
     " -> (list[subset-dict | int-status], n_bad); registered id_token"
     " claims only (iss sub aud exp nbf iat nonce azp auth_time)"},
    {"validate_batch", validate_batch, METH_VARARGS,
     "validate_batch(scratch, offsets_i64, lengths_i64, n_threads=0) "
     "-> bytes (per-token status: 0 ok-object, 1 malformed, 2 "
     "non-object, 3 outside-envelope)"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_capclaims",
    "Batch claims-JSON parsing (native runtime)", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

extern "C" PyMODINIT_FUNC PyInit__capclaims(void) {
#if PY_VERSION_HEX >= 0x03080000 && PY_VERSION_HEX < 0x030E0000
  // _PyDict_NewPresized is private API; its export and semantics are
  // verified against CPython 3.8-3.13 (the signature has been stable
  // since 3.4, and pydantic-core ships the same lookup). On CPython
  // versions outside that tested range the lookup is skipped entirely
  // so a changed symbol can't be trusted blindly: dict_new_presized
  // stays nullptr and every dict build takes the PyDict_New fallback.
  dict_new_presized = reinterpret_cast<DictNewPresizedFn>(
      dlsym(RTLD_DEFAULT, "_PyDict_NewPresized"));
#endif
  return PyModule_Create(&moduledef);
}
