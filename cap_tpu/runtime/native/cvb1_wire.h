// cvb1_wire.h — the CVB1 wire contract, shared across native TUs.
//
// One parser, two chains: serve_native.cpp (the worker serve chain)
// and frontdoor_native.cpp (the relay front door) must reject EXACTLY
// the same malformed / oversize / corrupt frames as
// serve/protocol.py _parse_frame, with the same error classes. The
// parser used to live inside serve_native.cpp; hoisting it here keeps
// the two native readers check-for-check identical by construction —
// the same stance as sha2::sha256 being one implementation in
// jose_native.cpp that every TU links.
//
// Everything here is header-only (inline) and allocation-free; the
// PF_* status codes map 1:1 onto the Python exception classes
// (serve/native_serve.py NATIVE_STATUS_ERRORS).

#ifndef CAP_TPU_CVB1_WIRE_H_
#define CAP_TPU_CVB1_WIRE_H_

#include <array>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include <errno.h>
#include <sys/socket.h>

namespace cvb1 {

static const uint32_t MAGIC = 0x31425643;  // "CVB1"
enum {
  T_VERIFY_REQ = 1,
  T_VERIFY_RESP = 2,
  T_PING = 3,
  T_PONG = 4,
  T_STATS_REQ = 5,
  T_STATS_RESP = 6,
  T_VERIFY_REQ_CRC = 7,
  T_VERIFY_RESP_CRC = 8,
  T_VERIFY_REQ_TRACE = 9,
  T_VERIFY_RESP_TRACE = 10,
  T_KEYS_PUSH = 11,
  T_KEYS_ACK = 12,
  T_PEER_FILL = 13,
  T_PEER_ACK = 14,
  T_SHM_ATTACH = 15,
  T_SHM_ACK = 16,
};
static const int64_t MAX_FRAME_ENTRIES = 1 << 20;
static const int64_t MAX_ENTRY_BYTES = 1 << 20;
static const int64_t MAX_FRAME_BYTES = 1 << 28;
static const int32_t MAX_TRACE_BYTES = 64;

// Parse status codes: the shared error-class contract with
// serve/protocol.py (serve/native_serve.py maps them back to the
// exact Python exception classes).
enum {
  PF_OK = 0,
  PF_MALFORMED = 1,   // MalformedFrameError
  PF_TOOLARGE = 2,    // FrameTooLargeError
  PF_CORRUPT = 3,     // FrameCorruptError
  PF_INCOMPLETE = 4,  // need more bytes (stream: keep reading)
  PF_UTF8 = 5,        // UnicodeDecodeError (token not valid UTF-8)
};

// ---------------------------------------------------------------------------
// zlib-compatible CRC-32 (IEEE reflected, poly 0xEDB88320).
// ---------------------------------------------------------------------------

inline uint32_t crc32_update(uint32_t crc, const uint8_t* p, size_t n) {
  static const std::array<uint32_t, 256> tbl = [] {
    std::array<uint32_t, 256> t{};
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? (0xEDB88320u ^ (c >> 1)) : (c >> 1);
      t[i] = c;
    }
    return t;
  }();
  crc ^= 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++)
    crc = tbl[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// strict UTF-8 validation (CPython's decoder rules: no overlongs, no
// surrogates, max U+10FFFF) — tokens cross into Python as str.
// ---------------------------------------------------------------------------

inline bool utf8_valid(const uint8_t* s, int64_t n) {
  int64_t i = 0;
  while (i < n) {
    uint8_t c = s[i];
    if (c < 0x80) { i++; continue; }
    if (c < 0xC2) return false;
    if (c < 0xE0) {
      if (i + 1 >= n || (s[i + 1] & 0xC0) != 0x80) return false;
      i += 2; continue;
    }
    if (c < 0xF0) {
      if (i + 2 >= n) return false;
      uint8_t lo = (c == 0xE0) ? 0xA0 : 0x80;
      uint8_t hi = (c == 0xED) ? 0x9F : 0xBF;
      if (s[i + 1] < lo || s[i + 1] > hi || (s[i + 2] & 0xC0) != 0x80)
        return false;
      i += 3; continue;
    }
    if (c < 0xF5) {
      if (i + 3 >= n) return false;
      uint8_t lo = (c == 0xF0) ? 0x90 : 0x80;
      uint8_t hi = (c == 0xF4) ? 0x8F : 0xBF;
      if (s[i + 1] < lo || s[i + 1] > hi ||
          (s[i + 2] & 0xC0) != 0x80 || (s[i + 3] & 0xC0) != 0x80)
        return false;
      i += 4; continue;
    }
    return false;
  }
  return true;
}

// ---------------------------------------------------------------------------
// frame parse over a byte buffer — check-for-check identical to
// protocol._parse_frame: every length validated BEFORE the bytes are
// touched, CRC checked before deferred status/trace/UTF-8 validation.
// ---------------------------------------------------------------------------

struct EntryRef {
  int64_t off;
  int64_t len;
  uint8_t status;  // response-shaped entries only
};

struct Parsed {
  uint8_t ftype = 0;
  uint32_t count = 0;
  int64_t trace_off = 0;
  int32_t trace_len = 0;  // 0 = no trace field
  std::vector<EntryRef> entries;
  int64_t consumed = 0;
};

inline int parse_frame(const uint8_t* b, int64_t n, Parsed& out) {
  if (n < 9) return PF_INCOMPLETE;
  uint32_t magic, count;
  std::memcpy(&magic, b, 4);
  uint8_t ftype = b[4];
  std::memcpy(&count, b + 5, 4);
  if (magic != MAGIC) return PF_MALFORMED;
  if ((int64_t)count > MAX_FRAME_ENTRIES) return PF_TOOLARGE;
  bool checksummed =
      ftype == T_VERIFY_REQ_CRC || ftype == T_VERIFY_RESP_CRC ||
      ftype == T_VERIFY_REQ_TRACE || ftype == T_VERIFY_RESP_TRACE ||
      ftype == T_KEYS_PUSH || ftype == T_KEYS_ACK ||
      ftype == T_PEER_FILL || ftype == T_PEER_ACK ||
      ftype == T_SHM_ATTACH || ftype == T_SHM_ACK;
  if ((ftype == T_KEYS_PUSH || ftype == T_KEYS_ACK ||
       ftype == T_PEER_FILL || ftype == T_PEER_ACK ||
       ftype == T_SHM_ATTACH || ftype == T_SHM_ACK) &&
      count != 1)
    return PF_MALFORMED;
  int64_t pos = 9;
  out.trace_off = 0;
  out.trace_len = 0;
  if (ftype == T_VERIFY_REQ_TRACE || ftype == T_VERIFY_RESP_TRACE) {
    if (pos + 1 > n) return PF_INCOMPLETE;
    uint8_t ctx_len = b[pos];
    if (ctx_len == 0 || ctx_len > MAX_TRACE_BYTES) return PF_MALFORMED;
    if (pos + 1 + ctx_len > n) return PF_INCOMPLETE;
    out.trace_off = pos + 1;
    out.trace_len = ctx_len;
    pos += 1 + ctx_len;
  }
  out.ftype = ftype;
  out.count = count;
  out.entries.clear();
  bool req_shape = ftype == T_VERIFY_REQ || ftype == T_VERIFY_REQ_CRC ||
                   ftype == T_VERIFY_REQ_TRACE || ftype == T_KEYS_PUSH ||
                   ftype == T_PEER_FILL || ftype == T_SHM_ATTACH;
  bool resp_shape = ftype == T_VERIFY_RESP || ftype == T_VERIFY_RESP_CRC ||
                    ftype == T_VERIFY_RESP_TRACE || ftype == T_STATS_RESP ||
                    ftype == T_KEYS_ACK || ftype == T_PEER_ACK ||
                    ftype == T_SHM_ACK;
  int64_t total = 0;
  if (req_shape) {
    out.entries.reserve(count < 4096 ? count : 4096);
    for (uint32_t i = 0; i < count; i++) {
      if (pos + 4 > n) return PF_INCOMPLETE;
      uint32_t ln;
      std::memcpy(&ln, b + pos, 4);
      pos += 4;
      total += (int64_t)ln;
      if ((int64_t)ln > MAX_ENTRY_BYTES || total > MAX_FRAME_BYTES)
        return PF_TOOLARGE;
      if (pos + (int64_t)ln > n) return PF_INCOMPLETE;
      out.entries.push_back({pos, (int64_t)ln, 0});
      pos += ln;
    }
  } else if (resp_shape) {
    out.entries.reserve(count < 4096 ? count : 4096);
    for (uint32_t i = 0; i < count; i++) {
      if (pos + 5 > n) return PF_INCOMPLETE;
      uint8_t st = b[pos];
      uint32_t ln;
      std::memcpy(&ln, b + pos + 1, 4);
      pos += 5;
      if (!checksummed && st > 1) return PF_MALFORMED;
      total += (int64_t)ln;
      if ((int64_t)ln > MAX_ENTRY_BYTES || total > MAX_FRAME_BYTES)
        return PF_TOOLARGE;
      if (pos + (int64_t)ln > n) return PF_INCOMPLETE;
      out.entries.push_back({pos, (int64_t)ln, st});
      pos += ln;
    }
  } else if (ftype == T_PING || ftype == T_PONG || ftype == T_STATS_REQ) {
    if (count) return PF_MALFORMED;
  } else {
    return PF_MALFORMED;
  }
  if (checksummed) {
    if (pos + 4 > n) return PF_INCOMPLETE;
    uint32_t want;
    std::memcpy(&want, b + pos, 4);
    uint32_t got = crc32_update(0, b, (size_t)pos);
    pos += 4;
    if (want != got) return PF_CORRUPT;
    // deferred status validation, exactly like the Python parser
    if (resp_shape)
      for (const auto& e : out.entries)
        if (e.status > 1) return PF_MALFORMED;
  }
  if (out.trace_len) {
    for (int32_t i = 0; i < out.trace_len; i++) {
      uint8_t c = b[out.trace_off + i];
      if (!((c >= '0' && c <= '9') || (c >= 'a' && c <= 'f')))
        return PF_MALFORMED;
    }
  }
  if (ftype == T_VERIFY_REQ || ftype == T_VERIFY_REQ_CRC ||
      ftype == T_VERIFY_REQ_TRACE) {
    // token decode AFTER integrity (Python: entries decoded last)
    for (const auto& e : out.entries)
      if (!utf8_valid(b + e.off, e.len)) return PF_UTF8;
  }
  out.consumed = pos;
  return PF_OK;
}

// ---------------------------------------------------------------------------
// frame-encode + socket helpers shared by both chains
// ---------------------------------------------------------------------------

inline void put_u32(std::string& s, uint32_t v) {
  s.append((const char*)&v, 4);
}

inline void append_crc(std::string& s) {
  uint32_t crc = crc32_update(0, (const uint8_t*)s.data(), s.size());
  put_u32(s, crc);
}

inline bool send_all(int fd, const std::string& data) {
  const char* p = data.data();
  size_t left = data.size();
  while (left) {
    ssize_t w = ::send(fd, p, left, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && (errno == EINTR)) continue;
      return false;
    }
    p += w;
    left -= (size_t)w;
  }
  return true;
}

}  // namespace cvb1

#endif  // CAP_TPU_CVB1_WIRE_H_
