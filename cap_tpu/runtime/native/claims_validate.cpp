// claims_validate.cpp — the native OIDC claims-rule engine
// (fourth TU of libcapruntime.so).
//
// PERF.md §Round 5 left ~4 µs/token of per-token Python rule
// evaluation (`oidc/provider.py:_validate_id_claims`) on the batched
// id_token path even after registered-claims extraction went native:
// config ⑤ (full OIDC verify-and-validate) ran at 1.37× the cost of
// config ③ (raw signature verify). The FPGA ECDSA verification-engine
// paper (arXiv:2112.02229) makes the same point in hardware: a verify
// pipeline only hits rated throughput when the ENTIRE per-item
// decision happens inside the pipeline. This TU is the software
// analog: the pure-comparison subset of the registered-claims rules —
// iss equality, exp/nbf/iat windows with the verify leeway, nonce
// equality, aud membership + the multi-aud-contains-client_id rule,
// and the azp simple-equality arm — evaluated straight off the
// phase-1 tape (claims_tape.h, the SAME parser _capclaims uses), one
// GIL-free batched call per verify batch.
//
// Contract (mirrors registered_batch's conservative-fallback stance):
//
// - rule ORDER is exactly provider.py's (`_check_times` then
//   `_validate_id_claims`): exp-missing → expired → nbf → iss → alg →
//   nonce → iat → aud-non-string → aud-membership → multi-aud →
//   azp → (auth_time). The FIRST failing rule's status is returned,
//   so a native reject is always the same class Python would raise.
// - every parse corner falls back per token (VS_FALLBACK → the caller
//   re-validates with the Python rules): escaped top-level keys,
//   container/escaped/bigint-valued claims the rules read, bool-typed
//   time claims (Python's isinstance(True, (int, float)) is True —
//   not replicated here), and any payload outside the strict parser's
//   envelope. Rare-FLAG arms fall back too: the azp 3-rule interplay
//   (azp absent while the aud shape makes rules 2/3 reachable) and
//   any policy with max_age requested (auth_time stays Python).
// - status codes are a FIXED-ORDER registry (kNumStatus below); the
//   Python binding maps them by NAME onto cap_tpu/errors.py and the
//   cap_claims_layout handshake disables the engine on drift — a
//   stale .so can refuse, never misclassify.

#include "claims_tape.h"

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

using capclaims::Parser;
using capclaims::TokenTape;
using namespace capclaims;  // Op/Status enums

// ---------------------------------------------------------------------------
// Status registry (ABI: append-only; mirrored by
// cap_tpu/oidc/claims_native.py STATUS_INDEX and handshaked via
// cap_claims_layout)
// ---------------------------------------------------------------------------

enum VStatus : uint8_t {
  VS_OK = 0,
  VS_FALLBACK = 1,            // Python rules decide this token
  VS_MISSING_EXP = 2,         // MissingClaimError
  VS_EXPIRED = 3,             // ExpiredTokenError
  VS_NOT_BEFORE = 4,          // InvalidNotBeforeError
  VS_WRONG_ISSUER = 5,        // InvalidIssuerError
  VS_UNSUPPORTED_ALG = 6,     // UnsupportedAlgError
  VS_WRONG_NONCE = 7,         // InvalidNonceError
  VS_FUTURE_IAT = 8,          // InvalidIssuedAtError
  VS_AUD_NON_STRING = 9,      // InvalidAudienceError
  VS_AUD_MISMATCH = 10,       // InvalidAudienceError
  VS_AUD_MISSING_CLIENT = 11, // InvalidAudienceError
  VS_AZP_MISMATCH = 12,       // InvalidAuthorizedPartyError
};

constexpr int32_t kLayoutVersion = 1;
constexpr int32_t kNumStatus = 13;

// ---------------------------------------------------------------------------
// Policy (compiled once per batch on the Python side; see
// claims_native.pack_policy). Little-endian blob:
//   u32 version(=1) | u32 flags | f64 leeway | u32 n_aud
//   u32 iss_len | u32 client_len | u32 nonce_len | u32 aud_len[n_aud]
//   bytes: issuer ‖ client_id ‖ nonce ‖ aud[0] ‖ aud[1] ...
// flags bit0: max_age requested (auth_time arm → whole-token fallback
//             AFTER the native rules pass).
// ---------------------------------------------------------------------------

struct Span {
  const uint8_t* p = nullptr;
  uint32_t len = 0;
};

struct Policy {
  Span issuer, client, nonce;
  std::vector<Span> audiences;
  double leeway = 0.0;
  bool max_age_requested = false;
};

inline uint32_t rd_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

bool parse_policy(const uint8_t* blob, int64_t len, Policy* out) {
  if (len < 20) return false;
  const uint8_t* p = blob;
  uint32_t version = rd_u32(p);
  if (version != 1) return false;
  uint32_t flags = rd_u32(p + 4);
  double leeway;
  std::memcpy(&leeway, p + 8, 8);
  uint32_t n_aud = rd_u32(p + 16);
  if (n_aud > 4096) return false;
  int64_t hdr = 20 + 12 + 4 * static_cast<int64_t>(n_aud);
  if (len < hdr) return false;
  uint32_t iss_len = rd_u32(p + 20);
  uint32_t cli_len = rd_u32(p + 24);
  uint32_t non_len = rd_u32(p + 28);
  std::vector<uint32_t> aud_lens(n_aud);
  int64_t total = static_cast<int64_t>(iss_len) + cli_len + non_len;
  for (uint32_t k = 0; k < n_aud; ++k) {
    aud_lens[k] = rd_u32(p + 32 + 4 * k);
    total += aud_lens[k];
  }
  if (len != hdr + total) return false;
  const uint8_t* data = p + hdr;
  out->issuer = {data, iss_len};
  data += iss_len;
  out->client = {data, cli_len};
  data += cli_len;
  out->nonce = {data, non_len};
  data += non_len;
  out->audiences.clear();
  out->audiences.reserve(n_aud);
  for (uint32_t k = 0; k < n_aud; ++k) {
    out->audiences.push_back({data, aud_lens[k]});
    data += aud_lens[k];
  }
  out->leeway = leeway;
  out->max_age_requested = (flags & 1u) != 0;
  return true;
}

// ---------------------------------------------------------------------------
// Registered-claim collection off the tape (LAST occurrence wins, the
// json.loads duplicate-key rule; depth-1 only, exactly like
// claims_ext.cpp's build_registered walk)
// ---------------------------------------------------------------------------

enum CKind : uint8_t {
  K_ABSENT = 0,
  K_STR,       // unescaped string span
  K_ESC,       // escaped string (→ fallback when a rule reads it)
  K_NUM,       // int64/double as double
  K_BOOL,      // → fallback for time claims (isinstance quirk)
  K_NULL,
  K_BIGINT,    // > 18 digits (→ fallback when a rule reads it)
  K_ARR,       // flat-or-not array: tape op range recorded
  K_OBJ,       // object value (→ fallback when a rule reads it)
};

struct CVal {
  uint8_t kind = K_ABSENT;
  uint32_t off = 0, len = 0;    // K_STR span into the payload
  double num = 0.0;             // K_NUM / K_BOOL value
  size_t arr_start = 0, arr_end = 0;  // K_ARR tape op-index range
};

// Claim slots (index into CVal claims[8]).
enum CIdx { C_ISS = 0, C_AUD, C_EXP, C_NBF, C_IAT, C_NONCE, C_AZP,
            C_AUTH_TIME, C_COUNT };

struct RegName {
  const char* name;
  uint32_t len;
};
constexpr RegName kReg[C_COUNT] = {
    {"iss", 3},   {"aud", 3},   {"exp", 3},       {"nbf", 3},
    {"iat", 3},   {"nonce", 5}, {"azp", 3},       {"auth_time", 9},
};

// Walk one ST_OK tape into per-claim values; false → the token must
// fall back (escaped top-level key, or a corrupt tape).
bool collect(const TokenTape& tape, const uint8_t* payload,
             CVal claims[C_COUNT]) {
  const uint32_t* ops = tape.ops.data();
  size_t nops = tape.ops.size();
  int depth = 0;
  int reg = -1;

  auto skip_subtree = [&](size_t t, size_t* closing) -> bool {
    int d = 1;
    size_t u = t + 3;
    for (; u < nops && d > 0; u += 3) {
      if (ops[u] == OP_OBJ_START || ops[u] == OP_ARR_START) ++d;
      else if (ops[u] == OP_OBJ_END || ops[u] == OP_ARR_END) --d;
    }
    if (d != 0) return false;
    *closing = u - 3;
    return true;
  };

  for (size_t t = 0; t < nops; t += 3) {
    uint32_t op = ops[t], a = ops[t + 1], b = ops[t + 2];
    switch (op) {
      case OP_OBJ_START: {
        if (reg >= 0 && depth == 1) {
          claims[reg] = CVal{};
          claims[reg].kind = K_OBJ;
          reg = -1;
          size_t closing;
          if (!skip_subtree(t, &closing)) return false;
          t = closing;
          break;
        }
        ++depth;
        break;
      }
      case OP_ARR_START: {
        if (reg >= 0 && depth == 1) {
          claims[reg] = CVal{};
          claims[reg].kind = K_ARR;
          claims[reg].arr_start = t + 3;
          size_t closing;
          if (!skip_subtree(t, &closing)) return false;
          claims[reg].arr_end = closing;
          reg = -1;
          t = closing;
          break;
        }
        ++depth;
        break;
      }
      case OP_OBJ_END:
      case OP_ARR_END:
        --depth;
        reg = -1;
        break;
      case OP_KEY: {
        if (depth != 1) {
          break;
        }
        uint32_t len = b >> 1, esc = b & 1;
        if (esc) return false;  // escaped key could spell a registered name
        reg = -1;
        for (int r = 0; r < C_COUNT; ++r) {
          if (kReg[r].len == len &&
              std::memcmp(payload + a, kReg[r].name, len) == 0) {
            reg = r;
            break;
          }
        }
        break;
      }
      default: {
        if (reg >= 0 && depth == 1) {
          CVal v;
          switch (op) {
            case OP_STR: {
              uint32_t len = b >> 1, esc = b & 1;
              v.kind = esc ? K_ESC : K_STR;
              v.off = a;
              v.len = len;
              break;
            }
            case OP_INT: {
              v.kind = K_NUM;
              v.num = static_cast<double>(static_cast<int64_t>(
                  (static_cast<uint64_t>(b) << 32) | a));
              break;
            }
            case OP_FLOAT: {
              uint64_t bits = (static_cast<uint64_t>(b) << 32) | a;
              double d;
              std::memcpy(&d, &bits, 8);
              v.kind = K_NUM;
              v.num = d;
              break;
            }
            case OP_BIGINT:
              v.kind = K_BIGINT;
              break;
            case OP_TRUE:
              v.kind = K_BOOL;
              v.num = 1.0;
              break;
            case OP_FALSE:
              v.kind = K_BOOL;
              v.num = 0.0;
              break;
            case OP_NULL:
              v.kind = K_NULL;
              break;
            default:
              return false;  // unknown future op: refuse loudly
          }
          claims[reg] = v;
        }
        reg = -1;  // scalar consumed the pending key either way
        break;
      }
    }
  }
  return true;
}

// ---------------------------------------------------------------------------
// Rule evaluation (one token)
// ---------------------------------------------------------------------------

inline bool span_eq(const uint8_t* payload, const CVal& v, Span s) {
  return v.len == s.len &&
         (v.len == 0 || std::memcmp(payload + v.off, s.p, v.len) == 0);
}

// A parse corner on a claim a rule is about to READ → fallback.
inline bool corner(const CVal& v) {
  return v.kind == K_ESC || v.kind == K_BIGINT || v.kind == K_OBJ;
}

uint8_t evaluate_with_now(const TokenTape& tape, const uint8_t* payload,
                          const Policy& pol, double now, bool alg_ok) {
  if (tape.status != ST_OK) return VS_FALLBACK;
  CVal claims[C_COUNT];
  if (!collect(tape, payload, claims)) return VS_FALLBACK;

  const uint32_t* ops = tape.ops.data();

  // -- _check_times -------------------------------------------------------
  const CVal& exp = claims[C_EXP];
  if (corner(exp) || exp.kind == K_BOOL || exp.kind == K_ARR)
    return VS_FALLBACK;
  if (exp.kind != K_NUM) return VS_MISSING_EXP;
  if (now > exp.num) return VS_EXPIRED;

  const CVal& nbf = claims[C_NBF];
  if (corner(nbf) || nbf.kind == K_BOOL || nbf.kind == K_ARR)
    return VS_FALLBACK;
  if (nbf.kind == K_NUM && now + pol.leeway < nbf.num)
    return VS_NOT_BEFORE;

  // -- _validate_id_claims, in source order -------------------------------
  const CVal& iss = claims[C_ISS];
  if (corner(iss) || iss.kind == K_ARR) return VS_FALLBACK;
  if (!(iss.kind == K_STR && span_eq(payload, iss, pol.issuer)))
    return VS_WRONG_ISSUER;

  if (!alg_ok) return VS_UNSUPPORTED_ALG;

  const CVal& nonce = claims[C_NONCE];
  if (corner(nonce) || nonce.kind == K_ARR) return VS_FALLBACK;
  if (!(nonce.kind == K_STR && span_eq(payload, nonce, pol.nonce)))
    return VS_WRONG_NONCE;

  const CVal& iat = claims[C_IAT];
  if (corner(iat) || iat.kind == K_BOOL || iat.kind == K_ARR)
    return VS_FALLBACK;
  if (iat.kind == K_NUM && now + pol.leeway < iat.num)
    return VS_FUTURE_IAT;

  // aud → aud_list (string → [s]; array → elements; else empty).
  const CVal& aud = claims[C_AUD];
  if (aud.kind == K_ESC || aud.kind == K_OBJ || aud.kind == K_BIGINT)
    return VS_FALLBACK;
  // Element spans for the list form, with the go-jose-parity
  // non-string rule: a non-string SCALAR element rejects; a container
  // or escaped element falls back (Python decides; it rejects too,
  // with the exact message).
  Span single;
  std::vector<Span> aud_list;
  size_t aud_count = 0;
  const Span* auds = nullptr;
  if (aud.kind == K_STR) {
    single = {payload + aud.off, aud.len};
    auds = &single;
    aud_count = 1;
  } else if (aud.kind == K_ARR) {
    for (size_t u = aud.arr_start; u < aud.arr_end; u += 3) {
      uint32_t op = ops[u], a = ops[u + 1], b = ops[u + 2];
      if (op == OP_OBJ_START || op == OP_ARR_START)
        return VS_FALLBACK;  // nested container (build_registered parity)
      if (op != OP_STR) return VS_AUD_NON_STRING;
      if (b & 1) return VS_FALLBACK;  // escaped element: Python decides
      aud_list.push_back({payload + a, b >> 1});
    }
    auds = aud_list.data();
    aud_count = aud_list.size();
  }
  // (other scalar kinds — K_NUM/K_BOOL/K_NULL/K_ABSENT — yield the
  // empty aud_list, exactly like the Python shape normalization)

  auto contains = [&](Span needle) -> bool {
    for (size_t k = 0; k < aud_count; ++k) {
      if (auds[k].len == needle.len &&
          (needle.len == 0 ||
           std::memcmp(auds[k].p, needle.p, needle.len) == 0))
        return true;
    }
    return false;
  };

  if (!pol.audiences.empty()) {
    bool matched = false;
    for (const Span& want : pol.audiences) {
      if (contains(want)) {
        matched = true;
        break;
      }
    }
    if (!matched) return VS_AUD_MISMATCH;
  }
  bool has_client = contains(pol.client);
  if (aud_count > 1 && !has_client) return VS_AUD_MISSING_CLIENT;

  // azp: the simple-equality arm is native; the 3-rule interplay
  // (azp None while rules 2/3 are reachable) is the rare-flag Python
  // fallback — provider.py raises the exact interplay error there.
  const CVal& azp = claims[C_AZP];
  if (corner(azp) || azp.kind == K_ARR) return VS_FALLBACK;
  if (azp.kind != K_ABSENT && azp.kind != K_NULL) {
    // present: equal → all three azp rules pass; unequal (or a
    // non-string scalar, which can never equal a str) → rule 1.
    if (!(azp.kind == K_STR && span_eq(payload, azp, pol.client)))
      return VS_AZP_MISMATCH;
  } else {
    // absent/null: rule 2 fires iff multi-aud; rule 3 iff the single
    // audience is not the client — both Python's call.
    if (aud_count > 1) return VS_FALLBACK;
    if (aud_count == 1 && !has_client) return VS_FALLBACK;
  }

  // auth_time/max_age: rare-flag arm stays Python. Ordering holds:
  // it is the LAST rule, so only fully-passing tokens reach it.
  if (pol.max_age_requested) return VS_FALLBACK;
  return VS_OK;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// Layout handshake: the binding refuses to enable the engine unless
// version and status-registry length match its own STATUS_INDEX (the
// REASON_INDEX pattern from the r13 telemetry plane).
void cap_claims_layout(int32_t* out) {
  out[0] = kLayoutVersion;
  out[1] = kNumStatus;
}

// Batched rule evaluation. scratch/offs/lens describe payload spans
// (the signed claims JSON of signature-ACCEPTED tokens); alg_ok[i] is
// the Python-side allowed-alg verdict from the header-segment cache;
// now/policy are captured once per batch. Writes one status byte per
// token into out_status. Returns 0, or nonzero when the policy blob
// or spans are unusable (caller falls back whole-batch).
int32_t cap_claims_validate_batch(
    const uint8_t* scratch, int64_t scratch_len, const int64_t* offs,
    const int64_t* lens, int64_t n, const uint8_t* policy_blob,
    int64_t policy_len, const uint8_t* alg_ok, double now,
    uint8_t* out_status, int32_t n_threads) {
  Policy pol;
  if (!parse_policy(policy_blob, policy_len, &pol)) return 1;
  for (int64_t i = 0; i < n; ++i) {
    if (offs[i] < 0 || lens[i] < 0 || offs[i] + lens[i] > scratch_len)
      return 2;
  }

  auto one = [&](int64_t i) {
    TokenTape tape;
    Parser p(scratch + offs[i], static_cast<size_t>(lens[i]), &tape);
    p.run();
    out_status[i] = evaluate_with_now(tape, scratch + offs[i], pol, now,
                                      alg_ok[i] != 0);
  };

  unsigned hw = std::thread::hardware_concurrency();
  size_t workers = n_threads > 0 ? static_cast<size_t>(n_threads)
                                 : (hw ? hw : 4);
  if (workers > static_cast<size_t>(n) && n > 0)
    workers = static_cast<size_t>(n);
  if (workers <= 1 || n < 256) {
    for (int64_t i = 0; i < n; ++i) one(i);
  } else {
    std::vector<std::thread> pool;
    std::atomic<size_t> next{0};
    for (size_t w = 0; w < workers; ++w) {
      pool.emplace_back([&]() {
        constexpr size_t kGrain = 256;
        while (true) {
          size_t lo = next.fetch_add(kGrain);
          if (lo >= static_cast<size_t>(n)) return;
          size_t hi = lo + kGrain;
          if (hi > static_cast<size_t>(n)) hi = static_cast<size_t>(n);
          for (size_t i = lo; i < hi; ++i) one(static_cast<int64_t>(i));
        }
      });
    }
    for (auto& t : pool) t.join();
  }
  return 0;
}

}  // extern "C"
