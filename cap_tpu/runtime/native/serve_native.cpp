// serve_native — GIL-free CVB1 serve chain for cap_tpu.
//
// The per-token half of the serve hot path (ROADMAP open item #1):
// frame read/validate/decode and response encode/write run in
// dedicated C++ threads, feeding the Python batcher through a bounded
// lock-free MPSC ring (tokens in, verdicts out). Python touches only
// whole BATCHES — one drain call pulls every queued request's tokens
// into flat buffers, one post call hands a batch of verdicts back —
// so the interpreter's serial cost per token is a couple of memcpy'd
// slices instead of a frame parse, a queue hop, and a struct.pack.
//
// Contract: the frame parser here must reject EXACTLY the same
// malformed / oversize / corrupt frames as serve/protocol.py
// _parse_frame, with the same error classes (status codes below map
// 1:1 onto MalformedFrameError / FrameTooLargeError /
// FrameCorruptError / UnicodeDecodeError — the parity sweep in
// tests/test_serve_native.py pins this over the malformed corpus).
//
// Threading model (one handle per worker):
//   - one reader thread per connection: buffered recv → parse →
//     validate → Req records pushed into the MPSC ring (Vyukov
//     bounded queue; producers lock-free on the fast path, blocking
//     only when the ring or the token watermark is full, which is the
//     backpressure that ends up in the client's TCP window);
//   - one writer thread per connection: sends responses strictly in
//     request order (seq assigned at read time), holding out-of-order
//     completions in a map — CVB1 has no request ids, order IS the
//     correlation;
//   - pings are answered natively (pong enqueued at the ping's seq);
//     stats/keys frames ride the ring as control records so Python
//     handles them IN ORDER with the verifies around them.
//
// Built into libcapruntime.so alongside jose_native.cpp (one TU each,
// same .so — see Makefile `native` / cap_tpu/_build.py).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <arpa/inet.h>
#include <errno.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <sys/types.h>
#include <sys/un.h>
#include <unistd.h>

#include "cvb1_wire.h"
#include "shm_ring.h"
#include "telemetry_native.h"

// SHA-256 from jose_native.cpp (same .so, SHA-NI dispatched): the
// verdict-cache token digest is sha256(token)[:16], computed here in
// the reader threads so the Python drain does zero hashing.
namespace sha2 {
void sha256(const uint8_t* data, size_t len, uint8_t out[32]);
}

namespace serve_native {

// The wire contract (frame types, limits, PF_* codes, parse_frame,
// crc32, UTF-8 validation, encode/send helpers) lives in
// cvb1_wire.h, shared with frontdoor_native.cpp — one parser, every
// native reader.
using namespace cvb1;

static const int DIG_LEN = 16;  // vcache.DIGEST_LEN

// ---------------------------------------------------------------------------
// bounded MPSC ring (Vyukov bounded queue; single consumer = the
// Python drain thread, producers = per-connection reader threads).
// ---------------------------------------------------------------------------

class MpscRing {
 public:
  explicit MpscRing(size_t cap_pow2) : mask_(cap_pow2 - 1),
                                       cells_(cap_pow2) {
    for (size_t i = 0; i < cap_pow2; i++)
      cells_[i].seq.store(i, std::memory_order_relaxed);
  }

  bool try_push(void* p) {
    size_t pos = head_.load(std::memory_order_relaxed);
    for (;;) {
      Cell& c = cells_[pos & mask_];
      size_t seq = c.seq.load(std::memory_order_acquire);
      intptr_t diff = (intptr_t)seq - (intptr_t)pos;
      if (diff == 0) {
        if (head_.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed)) {
          c.data = p;
          c.seq.store(pos + 1, std::memory_order_release);
          return true;
        }
      } else if (diff < 0) {
        return false;  // full
      } else {
        pos = head_.load(std::memory_order_relaxed);
      }
    }
  }

  // single-consumer pop: tail_ is plain, only the drain thread moves it
  void* try_pop() {
    Cell& c = cells_[tail_ & mask_];
    size_t seq = c.seq.load(std::memory_order_acquire);
    if ((intptr_t)seq - (intptr_t)(tail_ + 1) < 0) return nullptr;
    void* p = c.data;
    c.seq.store(tail_ + mask_ + 1, std::memory_order_release);
    tail_++;
    return p;
  }

 private:
  struct Cell {
    std::atomic<size_t> seq;
    void* data;
  };
  size_t mask_;
  std::vector<Cell> cells_;
  std::atomic<size_t> head_{0};
  size_t tail_ = 0;
};

// ---------------------------------------------------------------------------
// deficit-round-robin scheduler (r20 tenant fairness). One slot per
// real tenant (telemetry_native.h TEN_SLOTS) plus ONE shared
// best-effort slot for none/other/unclassified traffic. Costs are
// TOKENS (the unit the pipeline fills with); a queue whose head costs
// more than its deficit yields the cursor and earns another quantum
// on its next visit — the classic DRR result behind token-bucket-
// policed ingest (the FPGA ECDSA engine paper's scheduling frame).
//
// Single-consumer by construction: only the drain thread touches it.
// The algorithm is mirrored LINE FOR LINE by cap_tpu/serve/drr.py
// (the python chain's AdaptiveBatcher fair mode) and the dispatch-
// order parity is pinned by tests/test_admission.py through the
// cap_drr_* test ABI below — both chains must schedule identically.
// ---------------------------------------------------------------------------

static const int SCHED_SLOTS = 65;       // TEN_SLOTS real + 1 best-effort
static const int SCHED_BE = 64;          // the shared best-effort slot
static const int64_t SCHED_QUANTUM = 512;

struct DrrSched {
  std::deque<std::pair<void*, int64_t>> q[SCHED_SLOTS];
  int64_t deficit[SCHED_SLOTS] = {};
  int32_t weight[SCHED_SLOTS];
  int64_t quantum = SCHED_QUANTUM;
  int32_t cursor = 0;
  bool fresh = true;   // cursor just arrived at its slot (one charge)
  int64_t n = 0;

  DrrSched() {
    for (auto& w : weight) w = 1;
  }

  void push(int slot, void* item, int64_t cost) {
    if (slot < 0 || slot >= SCHED_SLOTS) slot = SCHED_BE;
    q[slot].emplace_back(item, cost < 1 ? 1 : cost);
    n++;
  }

  // Next item in DRR order (nullptr when empty). Deterministic given
  // the arrival sequence — the parity contract with serve/drr.py.
  void* pop() {
    if (n == 0) return nullptr;
    int empties = 0;
    for (;;) {
      int s = cursor;
      if (q[s].empty()) {
        deficit[s] = 0;              // leaving the active set resets
        cursor = (s + 1) % SCHED_SLOTS;
        fresh = true;
        if (++empties >= SCHED_SLOTS) return nullptr;  // defensive
        continue;
      }
      empties = 0;
      if (fresh) {
        deficit[s] += quantum * (int64_t)weight[s];
        fresh = false;
      }
      auto& head = q[s].front();
      if (head.second <= deficit[s]) {
        deficit[s] -= head.second;
        void* item = head.first;
        q[s].pop_front();
        n--;
        return item;
      }
      cursor = (s + 1) % SCHED_SLOTS;  // out of deficit: yield turn
      fresh = true;
    }
  }
};

// ---------------------------------------------------------------------------
// per-tenant token-bucket admission (r20). Checked by the READER
// threads at enqueue, per token: over-budget tokens are marked
// throttled and never reach the verify pipeline — the drain path
// answers them with a status-1 ThrottledError carrying a retry-after
// hint, and the reader's blocking push is what turns a sustained
// flood into TCP backpressure (wire pushback). One bucket per tenant
// slot INCLUDING none/other (N_TEN buckets), refilled lazily from a
// monotonic clock under one small mutex (one lock round per frame).
// ---------------------------------------------------------------------------

struct AdmBucket {
  double level = 0.0;
  double t_last = 0.0;
  double scale = 1.0;   // shed lever: effective rate = rate * scale
  bool init = false;
};

// ---------------------------------------------------------------------------
// handle / connection / request records
// ---------------------------------------------------------------------------

struct Handle;

struct Conn {
  Handle* h = nullptr;
  int32_t id = 0;
  int fd = -1;
  std::mutex mu;
  std::condition_variable cv;
  std::map<int64_t, std::string> outq;  // seq → encoded response frame
  int64_t next_send = 0;
  int64_t assigned = 0;      // seqs handed out by the reader (under mu)
  bool reader_done = false;
  bool dead = false;         // send failed: discard, never block
  std::atomic<int> finished{0};  // 2 = both threads exited
  // shm transport (negotiated per connection via T_SHM_ATTACH): once
  // attached, requests arrive through the region's request ring and
  // responses with seq >= shm_from_seq leave through its response
  // ring; the SOCKET stays open purely as the liveness channel (EOF =
  // client gone → detach + reclaim). The attach ack itself rides the
  // socket (seq < shm_from_seq), so the client can confirm the switch
  // before it starts producing.
  cap_shm::Region* shm_region = nullptr;
  int64_t shm_from_seq = INT64_MAX;  // under mu
  std::atomic<bool> peer_gone{false};
};

// Request kinds surfaced to the Python drain loop.
enum { K_VERIFY = 0, K_STATS = 2, K_KEYS = 3, K_PEER = 4 };

struct Req {
  std::shared_ptr<Conn> conn;
  int64_t seq = 0;
  uint8_t ftype = 0;
  uint8_t kind = K_VERIFY;
  uint8_t trace_len = 0;
  char trace[MAX_TRACE_BYTES];
  double t_recv = 0.0;
  // reader-side enqueue stamp (steady clock == CLOCK_MONOTONIC ==
  // Python time.monotonic() on Linux): the occupancy plane (r22)
  // measures queue.ring_wait_s as drain-side monotonic() - t_enq.
  double t_enq = 0.0;
  std::vector<int64_t> offs;  // entry boundaries into blob (n+1)
  std::string blob;           // concatenated entry bytes
  // telemetry plane (when attached): per-token family index (-1 =
  // header-cache miss, resolved by Python on the drain path), hashed
  // kid, and tenant slot (issuer hash → bounded table; misses resolve
  // with the family), classified by THIS reader thread at parse time.
  std::vector<int8_t> fams;
  std::string kids;  // 12 bytes per token, zero = none
  std::vector<int16_t> tens;
  // verdict cache (when enabled): sha256(token)[:16] per token,
  // computed by THIS reader thread at parse time
  std::string digests;
  // tenant-fair scheduling (r20): the DRR slot this request queues
  // under (first token's tenant; -1 / out-of-range → best-effort) and
  // the per-token admission verdicts — thr[i] != 0 means token i was
  // rejected by the token bucket and must NOT be verified; retry_ms
  // is the frame's retry-after hint (max over its throttled tokens).
  int16_t sched_slot = -1;
  std::vector<uint8_t> thr;
  int32_t retry_ms = 0;
};

// counter slots (cap_serve_counter)
enum {
  CTR_CONNS = 0,
  CTR_FRAMES = 1,
  CTR_TOKENS = 2,
  CTR_PROTO_ERR = 3,
  CTR_PONGS = 4,
  CTR_DROPPED_POSTS = 5,
  CTR_CONNS_CLOSED = 6,
  // shm transport (slots additive — a stale binding reading only 0-6
  // keeps its exact meanings)
  CTR_SHM_ATTACHES = 7,
  CTR_SHM_FALLBACKS = 8,
  CTR_SHM_FRAMES = 9,
  CTR_SHM_STALE_GEN = 10,
  CTR_SHM_DETACHES = 11,
  // admission control (r20; slots additive like the shm block — a
  // stale binding reading only 0-11 keeps its exact meanings). The
  // exact equation ADM_CHECKED == ADM_ADMITTED + ADM_THROTTLED is an
  // obs-smoke gate.
  CTR_ADM_CHECKED = 12,
  CTR_ADM_ADMITTED = 13,
  CTR_ADM_THROTTLED = 14,
  CTR_N = 15,
};

struct Handle {
  MpscRing ring;
  std::atomic<int64_t> queued_tokens{0};
  // burst visibility: the highest queued_tokens seen between scrapes
  // (drain-time sampling misses bursts; cap_serve_ring_hwm resets it)
  std::atomic<int64_t> ring_hwm{0};
  int64_t max_queued_tokens;
  // native telemetry plane (nullable; cap_serve_set_telemetry). Owned
  // by this handle once attached — freed together in destroy.
  cap_tel::TelPlane* tel = nullptr;
  // per-token (fam, kid, tenant) of the LAST drain call, in drain
  // order — cap_serve_drain_aux / cap_serve_drain_tens copy them out;
  // single-consumer like carry.
  std::vector<int8_t> last_fams;
  std::vector<uint8_t> last_kids;
  std::vector<int16_t> last_tens;
  // shm transport armed (cap_serve_set_shm): attach requests are
  // honored; off → acked status 1 + CTR_SHM_FALLBACKS (the socket
  // chain keeps serving, the r12 graceful-fallback contract)
  std::atomic<int32_t> shm_on{0};
  // verdict-cache digests (cap_serve_set_digests arms the readers;
  // cap_serve_drain_digests copies the last drain's out)
  std::atomic<int32_t> digests_on{0};
  std::vector<uint8_t> last_digests;
  // tenant-fair DRR scheduling (r20, cap_serve_set_fair). The sched
  // struct and barrier are CONSUMER-OWNED (only the drain thread
  // touches them); fair_on is sampled per pop so arming/disarming is
  // safe at any time. A control record becomes a BARRIER: everything
  // queued before it drains first (DRR only reorders verifies BETWEEN
  // control records — the keys-push ordering contract is unchanged),
  // and nothing behind it leaves the MPSC ring until it is delivered.
  std::atomic<int32_t> fair_on{0};
  DrrSched sched;
  Req* barrier = nullptr;
  // per-tenant token-bucket admission (r20, cap_serve_set_admission):
  // shared by every reader thread under adm_mu. rate is tokens/sec
  // PER TENANT; burst is the bucket depth in tokens.
  std::atomic<int32_t> adm_on{0};
  std::mutex adm_mu;
  double adm_rate = 0.0;
  double adm_burst = 0.0;
  AdmBucket adm[cap_tel::N_TEN];
  // per-token throttle mask of the LAST drain (cap_serve_drain_thr),
  // token-aligned like last_fams; single-consumer.
  std::vector<uint8_t> last_thr;
  // per-REQUEST ring-enqueue stamps of the LAST drain (r22 occupancy
  // plane, cap_serve_drain_enq): one double per drained request, in
  // drain order; single-consumer like last_thr.
  std::vector<double> last_enq;
  std::mutex mu;  // guards the two cvs' sleep/wake protocol
  std::condition_variable cv_data;   // drain thread sleeps here
  std::condition_variable cv_space;  // producers sleep here when full
  std::atomic<bool> stop{false};
  std::mutex conns_mu;
  std::unordered_map<int32_t, std::shared_ptr<Conn>> conns;
  int32_t next_id = 1;
  Req* carry = nullptr;  // drained but didn't fit the caller's buffers
  std::atomic<int64_t> ctr[CTR_N];
  int sweep_tick = 0;

  Handle(size_t cap, int64_t maxq) : ring(cap), max_queued_tokens(maxq) {
    for (auto& c : ctr) c.store(0);
  }
};

static double wall_now() {
  struct timeval tv;
  gettimeofday(&tv, nullptr);
  return (double)tv.tv_sec + (double)tv.tv_usec * 1e-6;
}

static double mono_now() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

static void enqueue_response(const std::shared_ptr<Conn>& c, int64_t seq,
                             std::string&& data) {
  std::lock_guard<std::mutex> lk(c->mu);
  c->outq.emplace(seq, std::move(data));
  c->cv.notify_all();
}

// blockingly push one request into the ring (token watermark +
// ring-capacity backpressure; false only on shutdown)
static bool push_req(Handle* h, Req* r, int64_t ntok) {
  for (;;) {
    if (h->stop.load(std::memory_order_relaxed)) return false;
    if (h->queued_tokens.load(std::memory_order_relaxed) <=
            h->max_queued_tokens &&
        h->ring.try_push(r)) {
      int64_t now =
          h->queued_tokens.fetch_add(ntok, std::memory_order_relaxed) +
          ntok;
      int64_t hwm = h->ring_hwm.load(std::memory_order_relaxed);
      while (now > hwm &&
             !h->ring_hwm.compare_exchange_weak(
                 hwm, now, std::memory_order_relaxed)) {
      }
      std::lock_guard<std::mutex> lk(h->mu);
      h->cv_data.notify_one();
      return true;
    }
    std::unique_lock<std::mutex> lk(h->mu);
    h->cv_space.wait_for(lk, std::chrono::milliseconds(20));
  }
}

// ---------------------------------------------------------------------------
// reader thread: buffered recv → parse → ring (or native pong); an
// attached shm region swaps the byte source from recv to the mapped
// request ring (zero syscalls, zero copy before the Req blob).
// ---------------------------------------------------------------------------

// Both-threads-done teardown: the LAST thread out closes the fd and
// reclaims the shm region (unmap + unlink — a client killed by -9
// left the file behind; the worker is the reliable janitor). Nothing
// here touches the Handle: cap_serve_destroy may free it as soon as
// every conn shows finished == 2.
static void finish_conn(const std::shared_ptr<Conn>& c) {
  if (c->finished.fetch_add(1) + 1 == 2) {
    if (c->shm_region) cap_shm::close_region(c->shm_region, true);
    ::close(c->fd);
  }
}

// Handle one PF_OK frame exactly as the socket reader always has:
// native pong, or a Req pushed into the MPSC ring (verify tokens and
// in-order control records alike). Returns false when the connection
// must drop (wrong-direction frame, shutdown during push).
static bool handle_frame(const std::shared_ptr<Conn>& c,
                         const uint8_t* base, const Parsed& p) {
  Handle* h = c->h;
  if (p.ftype == T_PING) {
    int64_t seq;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      seq = c->assigned++;
    }
    std::string pong(9, '\0');
    uint32_t zero = 0;
    std::memcpy(&pong[0], &MAGIC, 4);
    pong[4] = (char)T_PONG;
    std::memcpy(&pong[5], &zero, 4);
    enqueue_response(c, seq, std::move(pong));
    h->ctr[CTR_PONGS].fetch_add(1);
    return true;
  }
  if (p.ftype == T_VERIFY_REQ || p.ftype == T_VERIFY_REQ_CRC ||
      p.ftype == T_VERIFY_REQ_TRACE || p.ftype == T_STATS_REQ ||
      p.ftype == T_KEYS_PUSH || p.ftype == T_PEER_FILL) {
    Req* r = new Req();
    r->conn = c;
    r->ftype = p.ftype;
    r->kind = p.ftype == T_STATS_REQ ? K_STATS
              : p.ftype == T_KEYS_PUSH ? K_KEYS
              : p.ftype == T_PEER_FILL ? K_PEER
                                       : K_VERIFY;
    {
      std::lock_guard<std::mutex> lk(c->mu);
      r->seq = c->assigned++;
    }
    r->t_recv = wall_now();
    r->t_enq = mono_now();
    r->trace_len = (uint8_t)p.trace_len;
    if (p.trace_len)
      std::memcpy(r->trace, base + p.trace_off, (size_t)p.trace_len);
    size_t nent = p.entries.size();
    r->offs.resize(nent + 1);
    r->offs[0] = 0;
    int64_t tot = 0;
    for (size_t i = 0; i < nent; i++) {
      tot += p.entries[i].len;
      r->offs[i + 1] = tot;
    }
    r->blob.resize((size_t)tot);
    for (size_t i = 0; i < nent; i++)
      std::memcpy(&r->blob[(size_t)r->offs[i]], base + p.entries[i].off,
                  (size_t)p.entries[i].len);
    if (r->kind == K_VERIFY &&
        h->digests_on.load(std::memory_order_relaxed)) {
      // verdict-cache digest per token, while the bytes are hot
      // (SHA-NI where the CPU has it — ~0.1 µs for a typical token)
      r->digests.resize(nent * DIG_LEN);
      uint8_t d32[32];
      for (size_t i = 0; i < nent; i++) {
        sha2::sha256(base + p.entries[i].off,
                     (size_t)p.entries[i].len, d32);
        std::memcpy(&r->digests[i * DIG_LEN], d32, DIG_LEN);
      }
    }
    if (h->tel && r->kind == K_VERIFY) {
      // classify each token's family AND tenant here, GIL-free, while
      // the frame bytes are cache-hot: header segment = bytes before
      // the first '.' (token.split(".", 1)[0], byte-for-byte); the
      // tenant slot rides the same cache entry (issuer parsing only
      // ever happens in Python, on a miss)
      r->fams.resize(nent);
      r->kids.assign(nent * cap_tel::KID_LEN, '\0');
      r->tens.assign(nent, (int16_t)-1);
      for (size_t i = 0; i < nent; i++) {
        const uint8_t* tok = base + p.entries[i].off;
        int64_t tlen = p.entries[i].len;
        const uint8_t* dot =
            (const uint8_t*)std::memchr(tok, '.', (size_t)tlen);
        int64_t slen = dot ? (int64_t)(dot - tok) : tlen;
        int32_t kid_len = 0;
        r->fams[i] = (int8_t)cap_tel::classify(
            h->tel, tok, slen,
            (uint8_t*)&r->kids[i * cap_tel::KID_LEN], &kid_len,
            &r->tens[i]);
        if (r->fams[i] < 0) r->tens[i] = -1;  // miss: Python resolves
      }
    }
    if (r->kind == K_VERIFY) {
      // DRR slot: the FIRST token's reader-classified tenant decides
      // (frames are per-connection and issuers per-client, so mixed-
      // tenant frames are rare; the python twin picks the same way).
      // Unclassified / none / other / header-cache miss → the shared
      // best-effort slot (sched_slot stays -1).
      if (!r->tens.empty() && r->tens[0] >= 0 &&
          r->tens[0] < cap_tel::TEN_SLOTS)
        r->sched_slot = r->tens[0];
      if (h->adm_on.load(std::memory_order_relaxed) && nent) {
        // token-bucket admission, per token, while the frame is hot:
        // a throttled token is marked (never verified) and answered
        // from the drain path with the retry-after pushback — the
        // whole point is that a flood costs the pipeline ~nothing.
        r->thr.assign(nent, 0);
        double now = mono_now();
        int64_t throttled = 0, judged = 0;
        double worst = 0.0;
        {
          std::lock_guard<std::mutex> lk(h->adm_mu);
          for (size_t i = 0; i < nent; i++) {
            if (i >= r->tens.size() || r->tens[i] < 0 ||
                r->tens[i] >= cap_tel::N_TEN) {
              // header-cache miss (or no telemetry plane): the tenant
              // is unknown HERE — judging it against a shared bucket
              // would let one tenant's cold frames starve another's.
              // Mark PENDING; the drain path judges it through
              // cap_serve_adm_take once Python resolved the issuer.
              r->thr[i] = 2;
              continue;
            }
            judged++;
            AdmBucket& b = h->adm[r->tens[i]];
            double rate = h->adm_rate * b.scale;
            if (!b.init) {
              b.init = true;
              b.level = h->adm_burst;   // buckets start full
              b.t_last = now;
            } else if (now > b.t_last) {
              b.level += (now - b.t_last) * rate;
              if (b.level > h->adm_burst) b.level = h->adm_burst;
              b.t_last = now;
            }
            if (b.level >= 1.0) {
              b.level -= 1.0;
            } else {
              r->thr[i] = 1;
              throttled++;
              double wait = rate > 1e-9 ? (1.0 - b.level) / rate
                                        : 60.0;
              if (wait > worst) worst = wait;
            }
          }
        }
        if (judged) h->ctr[CTR_ADM_CHECKED].fetch_add(judged);
        if (throttled) {
          h->ctr[CTR_ADM_THROTTLED].fetch_add(throttled);
          int64_t ms = (int64_t)(worst * 1000.0) + 1;
          if (ms < 1) ms = 1;
          if (ms > 60000) ms = 60000;
          r->retry_ms = (int32_t)ms;
        }
        if (judged - throttled)
          h->ctr[CTR_ADM_ADMITTED].fetch_add(judged - throttled);
      }
    }
    int64_t ntok = r->kind == K_VERIFY ? (int64_t)nent : 1;
    if (r->kind == K_VERIFY) h->ctr[CTR_TOKENS].fetch_add(nent);
    if (!push_req(h, r, ntok)) {
      delete r;
      return false;
    }
    return true;
  }
  // valid frame, wrong direction (a response type at the server — or
  // a second SHM attach): protocol violation → drop the connection.
  return false;
}

// extract the "path" string out of the attach payload JSON — the one
// field the native side needs; escaped paths are rejected (the
// clients never emit them, and un-escaping here would invite drift)
static std::string attach_path(const uint8_t* payload, int64_t len) {
  static const char key[] = "\"path\":\"";
  std::string s((const char*)payload, (size_t)len);
  size_t at = s.find(key);
  if (at == std::string::npos) return "";
  size_t start = at + sizeof(key) - 1;
  size_t end = s.find('"', start);
  if (end == std::string::npos) return "";
  std::string path = s.substr(start, end - start);
  if (path.find('\\') != std::string::npos) return "";
  return path;
}

// checksummed SHM ack (type 16, one entry) — byte-identical to
// protocol.encode_shm_ack
static std::string shm_ack_frame(const std::string& error) {
  std::string payload =
      error.empty() ? std::string("{\"transport\":\"shm\"}") : error;
  std::string f;
  put_u32(f, MAGIC);
  f.push_back((char)T_SHM_ACK);
  put_u32(f, 1);
  f.push_back(error.empty() ? '\0' : '\x01');
  put_u32(f, (uint32_t)payload.size());
  f += payload;
  append_crc(f);
  return f;
}

// Serve one attached connection from its mapped request ring. The
// socket is polled (non-blocking) as the liveness channel: EOF means
// the client is gone — including kill -9 mid-write, whose partial
// record was never published and is simply reclaimed with the ring.
static void shm_reader_loop(const std::shared_ptr<Conn>& c) {
  Handle* h = c->h;
  cap_shm::Region* r = c->shm_region;
  int idle = 0;
  for (;;) {
    if (h->stop.load(std::memory_order_relaxed)) break;
    const uint8_t* rec;
    uint64_t len;
    int st = cap_shm::poll_record(r, cap_shm::RING_REQ, &rec, &len);
    if (st == cap_shm::SHM_EMPTY) {
      if (++idle >= 32) {
        idle = 0;
        char probe[64];
        ssize_t n = ::recv(c->fd, probe, sizeof(probe), MSG_DONTWAIT);
        if (n == 0) break;  // EOF: client gone → detach + reclaim
        if (n > 0) {
          // bytes on the socket after the attach: protocol violation
          h->ctr[CTR_PROTO_ERR].fetch_add(1);
          break;
        }
        if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) break;
      }
      ::usleep(100);
      continue;
    }
    if (st != cap_shm::SHM_RECORD) {
      // poisoned ring: overrun cursor / impossible length / foreign
      // generation — the shm analog of a malformed socket frame
      if (st == cap_shm::SHM_STALE_GEN)
        h->ctr[CTR_SHM_STALE_GEN].fetch_add(1);
      h->ctr[CTR_PROTO_ERR].fetch_add(1);
      break;
    }
    idle = 0;
    Parsed p;
    int pst = parse_frame(rec, (int64_t)len, p);
    if (pst != PF_OK || (uint64_t)p.consumed != len ||
        p.ftype == T_SHM_ATTACH) {
      h->ctr[CTR_PROTO_ERR].fetch_add(1);
      break;
    }
    h->ctr[CTR_FRAMES].fetch_add(1);
    h->ctr[CTR_SHM_FRAMES].fetch_add(1);
    bool ok = handle_frame(c, rec, p);
    // consume AFTER handle_frame copied the entry bytes out — the
    // producer may reuse the space the moment the tail moves
    cap_shm::consume_record(r, cap_shm::RING_REQ);
    if (!ok) break;
  }
  c->peer_gone.store(true);
  h->ctr[CTR_SHM_DETACHES].fetch_add(1);
}

static void reader_main(std::shared_ptr<Conn> c) {
  Handle* h = c->h;
  std::vector<uint8_t> buf;
  size_t start = 0;
  for (;;) {
    Parsed p;
    int st = PF_INCOMPLETE;
    if (buf.size() > start)
      st = parse_frame(buf.data() + start, (int64_t)(buf.size() - start),
                       p);
    if (st == PF_INCOMPLETE) {
      if (h->stop.load(std::memory_order_relaxed)) break;
      if (start > 0) {  // compact the consumed prefix
        buf.erase(buf.begin(), buf.begin() + start);
        start = 0;
      }
      size_t old = buf.size();
      buf.resize(old + (1 << 16));
      ssize_t r = ::recv(c->fd, buf.data() + old, 1 << 16, 0);
      if (r <= 0) {  // EOF / error / shutdown
        buf.resize(old);
        break;
      }
      buf.resize(old + (size_t)r);
      continue;
    }
    if (st != PF_OK) {
      // Malformed / oversize / corrupt / bad-UTF-8: same stance as
      // the Python worker — count it, drop the connection quietly.
      h->ctr[CTR_PROTO_ERR].fetch_add(1);
      break;
    }
    h->ctr[CTR_FRAMES].fetch_add(1);
    const uint8_t* base = buf.data() + start;
    if (p.ftype == T_SHM_ATTACH) {
      // transport negotiation: map the client's region and switch
      // this connection's frame source to its request ring; anything
      // unsupported acks status 1 and the socket chain keeps serving
      // (serve.shm_fallbacks — the graceful-fallback contract)
      int64_t seq;
      {
        std::lock_guard<std::mutex> lk(c->mu);
        seq = c->assigned++;
      }
      std::string path = attach_path(base + p.entries[0].off,
                                     p.entries[0].len);
      if (!h->shm_on.load(std::memory_order_relaxed) || path.empty() ||
          c->shm_region) {
        h->ctr[CTR_SHM_FALLBACKS].fetch_add(1);
        enqueue_response(
            c, seq,
            shm_ack_frame("TypeError: worker has no shm transport "
                          "(transport=socket)"));
      } else {
        char err[128];
        cap_shm::Region* region =
            cap_shm::map_region(path.c_str(), err, sizeof(err));
        if (!region) {
          h->ctr[CTR_SHM_FALLBACKS].fetch_add(1);
          enqueue_response(
              c, seq,
              shm_ack_frame(std::string("ValueError: shm region "
                                        "unusable: ") + err));
        } else {
          {
            std::lock_guard<std::mutex> lk(c->mu);
            c->shm_region = region;
            c->shm_from_seq = seq + 1;  // the ack rides the socket
          }
          h->ctr[CTR_SHM_ATTACHES].fetch_add(1);
          enqueue_response(c, seq, shm_ack_frame(""));
          start += (size_t)p.consumed;
          shm_reader_loop(c);
          break;
        }
      }
    } else if (!handle_frame(c, base, p)) {
      break;
    }
    start += (size_t)p.consumed;
    if (start == buf.size()) {
      buf.clear();
      start = 0;
    }
  }
  {
    std::lock_guard<std::mutex> lk(c->mu);
    c->reader_done = true;
    c->cv.notify_all();
  }
  finish_conn(c);
}

// ---------------------------------------------------------------------------
// writer thread: strict seq-order sends, discards once the peer broke
// ---------------------------------------------------------------------------

// write_record abort hook: give up when the worker is shutting down
// or the client is gone (a dead client stops consuming the response
// ring — blocking forever would wedge the writer thread).
static bool shm_write_abort(void* ctx) {
  Conn* c = (Conn*)ctx;
  return c->h->stop.load(std::memory_order_relaxed) ||
         c->peer_gone.load(std::memory_order_relaxed);
}

static void writer_main(std::shared_ptr<Conn> c) {
  Handle* h = c->h;
  std::unique_lock<std::mutex> lk(c->mu);
  for (;;) {
    auto it = c->outq.find(c->next_send);
    if (it != c->outq.end()) {
      int64_t seq = c->next_send;
      std::string data = std::move(it->second);
      c->outq.erase(it);
      c->next_send++;
      bool dead = c->dead;
      bool to_shm = c->shm_region != nullptr && seq >= c->shm_from_seq;
      lk.unlock();
      bool sent;
      if (dead) {
        sent = true;  // discarding
      } else if (to_shm) {
        sent = cap_shm::write_record(
                   c->shm_region, cap_shm::RING_RESP,
                   (const uint8_t*)data.data(), data.size(),
                   shm_write_abort, c.get()) == 0;
      } else {
        sent = send_all(c->fd, data);
      }
      if (!sent) {
        // Broken mid-response: wake the reader out of recv, then keep
        // DRAINING queued entries so in-flight posts never pile up.
        ::shutdown(c->fd, SHUT_RDWR);
        lk.lock();
        c->dead = true;
      } else {
        lk.lock();
      }
      continue;
    }
    if (h->stop.load(std::memory_order_relaxed)) break;
    if (c->reader_done && c->next_send >= c->assigned)
      break;  // every response this connection will ever owe is sent
    c->cv.wait_for(lk, std::chrono::milliseconds(100));
  }
  lk.unlock();
  (void)h;
  finish_conn(c);
}

// Single-consumer pop honoring fair mode (drain thread only). FIFO
// mode with an empty scheduler is the plain ring pop — zero added
// work on the classic path. In fair mode everything currently queued
// in the MPSC ring first transfers into the per-tenant subqueues,
// stopping at the first CONTROL record, which becomes a barrier:
// every request read before it drains first (over however many drain
// calls that takes), and nothing read after it leaves the ring until
// it is delivered — DRR reorders verifies only BETWEEN controls, so
// the keys-push / stats ordering contract is exactly the FIFO one.
static Req* sched_pop(Handle* h) {
  bool fair = h->fair_on.load(std::memory_order_relaxed) != 0;
  if (!fair && h->sched.n == 0 && !h->barrier)
    return (Req*)h->ring.try_pop();
  if (fair && !h->barrier) {
    for (;;) {
      Req* r = (Req*)h->ring.try_pop();
      if (!r) break;
      if (r->kind != K_VERIFY) {
        h->barrier = r;
        break;
      }
      h->sched.push(r->sched_slot >= 0 ? r->sched_slot : SCHED_BE, r,
                    (int64_t)r->offs.size() - 1);
    }
  }
  if (h->sched.n) {
    Req* r = (Req*)h->sched.pop();
    if (r) return r;
  }
  if (h->barrier) {
    Req* c = h->barrier;
    h->barrier = nullptr;
    return c;
  }
  return (Req*)h->ring.try_pop();
}

// remove fully-finished connections (both threads exited → every
// owed response was sent or discarded; any later post is dropped)
static void sweep_conns(Handle* h) {
  std::lock_guard<std::mutex> lk(h->conns_mu);
  for (auto it = h->conns.begin(); it != h->conns.end();) {
    if (it->second->finished.load() >= 2) {
      h->ctr[CTR_CONNS_CLOSED].fetch_add(1);
      it = h->conns.erase(it);
    } else {
      ++it;
    }
  }
}

}  // namespace serve_native

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

using namespace serve_native;

extern "C" {

void* cap_serve_create(int32_t ring_capacity, int64_t max_queued_tokens) {
  size_t cap = 1;
  while ((int32_t)cap < (ring_capacity > 0 ? ring_capacity : 4096))
    cap <<= 1;
  return new Handle(cap, max_queued_tokens > 0 ? max_queued_tokens
                                               : (int64_t)4 * 32768);
}

int32_t cap_serve_add_conn(void* hv, int32_t fd) {
  Handle* h = (Handle*)hv;
  if (h->stop.load()) return -1;
  auto c = std::make_shared<Conn>();
  c->h = h;
  c->fd = fd;
  {
    std::lock_guard<std::mutex> lk(h->conns_mu);
    c->id = h->next_id++;
    h->conns[c->id] = c;
  }
  h->ctr[CTR_CONNS].fetch_add(1);
  std::thread(reader_main, c).detach();
  std::thread(writer_main, c).detach();
  if (++h->sweep_tick % 64 == 0) sweep_conns(h);
  return c->id;
}

int64_t cap_serve_ring_depth(void* hv) {
  if (!hv) return 0;
  return ((Handle*)hv)->queued_tokens.load(std::memory_order_relaxed);
}

int64_t cap_serve_counter(void* hv, int32_t which) {
  if (!hv || which < 0 || which >= CTR_N) return -1;
  return ((Handle*)hv)->ctr[which].load(std::memory_order_relaxed);
}

// Drain queued requests into flat caller-owned buffers. Returns the
// number of requests drained (0 on timeout), or -2 when the FIRST
// request alone exceeds the caller's buffers — out_counts then holds
// the required sizes and the request is carried for the retry.
//
// req_meta stride is 6 int32s per request:
//   [kind, conn_id, ftype, n_entries, trace_len, reserved]
// tok_off holds n_tokens+1 cumulative byte offsets into tok_blob.
// Returns early (before min_tokens / max_wait) when a control record
// (stats / keys push) is drained — Python must handle it in order.
int64_t cap_serve_drain(void* hv, int64_t min_tokens, int64_t max_tokens,
                        double max_wait_s, double idle_wait_s,
                        uint8_t* tok_blob, int64_t blob_cap,
                        int64_t* tok_off, int32_t* req_meta,
                        int64_t* req_seq, double* req_t0,
                        uint8_t* trace_buf, int32_t max_reqs,
                        int64_t* out_counts) {
  Handle* h = (Handle*)hv;
  using clock = std::chrono::steady_clock;
  auto t_start = clock::now();
  auto t_first = t_start;
  bool have = false;
  int64_t n_reqs = 0, n_toks = 0, blob_used = 0;
  tok_off[0] = 0;
  if (h->tel) {
    h->last_fams.clear();
    h->last_kids.clear();
    h->last_tens.clear();
  }
  bool want_digests = h->digests_on.load(std::memory_order_relaxed);
  if (want_digests) h->last_digests.clear();
  h->last_thr.clear();
  h->last_enq.clear();
  bool stop_drain = false;
  while (!stop_drain) {
    Req* r = h->carry;
    h->carry = nullptr;
    if (!r) r = sched_pop(h);
    if (!r) {
      std::unique_lock<std::mutex> lk(h->mu);
      r = sched_pop(h);
      if (!r) {
        if (h->stop.load(std::memory_order_relaxed)) break;
        auto now = clock::now();
        auto until =
            have ? t_first + std::chrono::duration_cast<clock::duration>(
                                 std::chrono::duration<double>(max_wait_s))
                 : t_start + std::chrono::duration_cast<clock::duration>(
                                 std::chrono::duration<double>(idle_wait_s));
        if (now >= until) break;
        h->cv_data.wait_until(lk, until);
        continue;
      }
    }
    int64_t nent = (int64_t)r->offs.size() - 1;
    int64_t bl = (int64_t)r->blob.size();
    if (n_reqs + 1 > (int64_t)max_reqs || n_toks + nent > max_tokens ||
        blob_used + bl > blob_cap) {
      h->carry = r;  // keep for the next drain call
      if (n_reqs == 0) {
        out_counts[0] = 1;
        out_counts[1] = nent;
        out_counts[2] = bl;
        return -2;  // caller must grow its buffers and retry
      }
      break;
    }
    if (!have) {
      have = true;
      t_first = clock::now();
    }
    std::memcpy(tok_blob + blob_used, r->blob.data(), (size_t)bl);
    for (int64_t j = 0; j < nent; j++)
      tok_off[n_toks + 1 + j] = blob_used + r->offs[j + 1];
    int32_t* m = req_meta + n_reqs * 6;
    m[0] = r->kind;
    m[1] = r->conn->id;
    m[2] = r->ftype;
    m[3] = (int32_t)nent;
    m[4] = r->trace_len;
    m[5] = r->retry_ms;  // admission retry-after hint (0 = none)
    req_seq[n_reqs] = r->seq;
    req_t0[n_reqs] = r->t_recv;
    h->last_enq.push_back(r->t_enq);
    if (r->trace_len)
      std::memcpy(trace_buf + (size_t)n_reqs * MAX_TRACE_BYTES, r->trace,
                  r->trace_len);
    if (h->tel) {
      // keep token-aligned (fam, kid, tenant) for cap_serve_drain_aux
      // / cap_serve_drain_tens — control entries get filler slots so
      // offsets line up
      if (r->kind == K_VERIFY && (int64_t)r->fams.size() == nent) {
        h->last_fams.insert(h->last_fams.end(), r->fams.begin(),
                            r->fams.end());
        h->last_kids.insert(h->last_kids.end(), r->kids.begin(),
                            r->kids.end());
        h->last_tens.insert(h->last_tens.end(), r->tens.begin(),
                            r->tens.end());
      } else {
        h->last_fams.insert(h->last_fams.end(), (size_t)nent, -1);
        h->last_kids.insert(h->last_kids.end(),
                            (size_t)nent * cap_tel::KID_LEN, 0);
        h->last_tens.insert(h->last_tens.end(), (size_t)nent,
                            (int16_t)-1);
      }
    }
    if (want_digests) {
      // token-aligned digests; zero filler (= "rehash in Python")
      // for control records and requests read before arming
      if (r->kind == K_VERIFY &&
          (int64_t)r->digests.size() == nent * DIG_LEN) {
        h->last_digests.insert(h->last_digests.end(),
                               r->digests.begin(), r->digests.end());
      } else {
        h->last_digests.insert(h->last_digests.end(),
                               (size_t)nent * DIG_LEN, 0);
      }
    }
    // token-aligned admission verdicts (cap_serve_drain_thr): zero
    // filler for control records / pre-arming requests
    if (r->kind == K_VERIFY && (int64_t)r->thr.size() == nent) {
      h->last_thr.insert(h->last_thr.end(), r->thr.begin(),
                         r->thr.end());
    } else {
      h->last_thr.insert(h->last_thr.end(), (size_t)nent, 0);
    }
    int64_t consumed = r->kind == K_VERIFY ? nent : 1;
    h->queued_tokens.fetch_sub(consumed, std::memory_order_relaxed);
    n_reqs++;
    n_toks += nent;
    blob_used += bl;
    bool control = r->kind != K_VERIFY;
    delete r;
    {
      std::lock_guard<std::mutex> lk(h->mu);
      h->cv_space.notify_all();
    }
    if (control) break;  // flush now: Python handles it in order
    if (n_toks >= min_tokens) stop_drain = true;
  }
  out_counts[0] = n_reqs;
  out_counts[1] = n_toks;
  out_counts[2] = blob_used;
  return n_reqs;
}

// Post one drained span's verdicts: per request, encode the response
// frame (plain / checksummed / traced mirrors the request type) and
// hand it to the connection's writer at the request's seq. When the
// telemetry plane is attached and fold args are provided, the SAME
// walk folds the chunk's decisions (cap_tel::fold) and observes
// per-request latency — accounting rides the encode, not a Python
// side trip.
static int32_t post_results_impl(Handle* h, const int32_t* req_meta,
                                 const int64_t* req_seq,
                                 const uint8_t* trace_buf,
                                 const double* req_t0, int32_t n_reqs,
                                 const uint8_t* statuses,
                                 const uint8_t* payload_blob,
                                 const int64_t* payload_off,
                                 const uint8_t* reasons,
                                 const int8_t* fams,
                                 const int16_t* tens,
                                 const uint8_t* kids,
                                 int32_t lat_idx, double lat_s,
                                 bool do_fold) {
  int64_t t = 0;
  int32_t dropped = 0;
  double now = (do_fold && req_t0) ? wall_now() : 0.0;
  // the chunk's trace id: the first traced request's, exactly like
  // the drain loop's traces[0] on the Python side
  const uint8_t* fold_trace = nullptr;
  int32_t fold_trace_len = 0;
  for (int32_t i = 0; i < n_reqs; i++) {
    const int32_t* m = req_meta + i * 6;
    int32_t conn_id = m[1];
    uint8_t ftype = (uint8_t)m[2];
    int64_t ntok = m[3];
    uint8_t rtype = ftype == T_VERIFY_REQ_CRC ? T_VERIFY_RESP_CRC
                    : ftype == T_VERIFY_REQ_TRACE ? T_VERIFY_RESP_TRACE
                                                  : T_VERIFY_RESP;
    bool crc = rtype != T_VERIFY_RESP;
    std::string frame;
    int64_t body = payload_off[t + ntok] - payload_off[t];
    frame.reserve((size_t)(9 + 70 + ntok * 5 + body + 4));
    put_u32(frame, MAGIC);
    frame.push_back((char)rtype);
    put_u32(frame, (uint32_t)ntok);
    if (rtype == T_VERIFY_RESP_TRACE) {
      uint8_t tl = (uint8_t)m[4];
      frame.push_back((char)tl);
      frame.append((const char*)(trace_buf + (size_t)i * MAX_TRACE_BYTES),
                   tl);
    }
    for (int64_t j = 0; j < ntok; j++) {
      int64_t off = payload_off[t + j];
      int64_t len = payload_off[t + j + 1] - off;
      frame.push_back((char)statuses[t + j]);
      put_u32(frame, (uint32_t)len);
      frame.append((const char*)(payload_blob + off), (size_t)len);
    }
    if (crc) append_crc(frame);
    t += ntok;
    if (do_fold) {
      if (!fold_trace && m[4] > 0) {
        fold_trace = trace_buf + (size_t)i * MAX_TRACE_BYTES;
        fold_trace_len = m[4];
      }
      if (req_t0 && h->tel)
        cap_tel::observe(h->tel, cap_tel::SERIES_REQUEST_S,
                         now - req_t0[i]);
    }
    std::shared_ptr<Conn> c;
    {
      std::lock_guard<std::mutex> lk(h->conns_mu);
      auto it = h->conns.find(conn_id);
      if (it != h->conns.end()) c = it->second;
    }
    if (c) {
      enqueue_response(c, req_seq[i], std::move(frame));
    } else {
      dropped++;
      h->ctr[CTR_DROPPED_POSTS].fetch_add(1);
    }
  }
  if (do_fold && h->tel && t > 0) {
    cap_tel::observe(h->tel, cap_tel::SERIES_CHUNK_TOKENS, (double)t);
    cap_tel::fold(h->tel, t, statuses, reasons, fams, tens, kids,
                  lat_idx, lat_s, fold_trace, fold_trace_len);
  }
  return dropped;
}

int32_t cap_serve_post_results(void* hv, const int32_t* req_meta,
                               const int64_t* req_seq,
                               const uint8_t* trace_buf, int32_t n_reqs,
                               const uint8_t* statuses,
                               const uint8_t* payload_blob,
                               const int64_t* payload_off) {
  return post_results_impl((Handle*)hv, req_meta, req_seq, trace_buf,
                           nullptr, n_reqs, statuses, payload_blob,
                           payload_off, nullptr, nullptr, nullptr,
                           nullptr, 0, -1.0, false);
}

// The telemetry-folding variant (a separate symbol so a stale .so
// degrades the plane gracefully — the binding probes for it and falls
// back to the Python fold when absent; the r19 tenant extension rides
// the cap_tel_layout_ten handshake, which also gates this signature).
// reasons may be NULL when every status is 0 (the all-accept fast
// path); tens NULL folds every token as tenant "none"; lat_s < 0
// skips the per-tenant latency observation (latency_s=None).
int32_t cap_serve_post_results_tel(
    void* hv, const int32_t* req_meta, const int64_t* req_seq,
    const uint8_t* trace_buf, const double* req_t0, int32_t n_reqs,
    const uint8_t* statuses, const uint8_t* payload_blob,
    const int64_t* payload_off, const uint8_t* reasons,
    const int8_t* fams, const int16_t* tens, const uint8_t* kids,
    int32_t lat_idx, double lat_s) {
  return post_results_impl((Handle*)hv, req_meta, req_seq, trace_buf,
                           req_t0, n_reqs, statuses, payload_blob,
                           payload_off, reasons, fams, tens, kids,
                           lat_idx, lat_s, true);
}

// Attach a telemetry plane (before any connection is added). The
// handle takes ownership: the plane is freed with the handle in
// cap_serve_destroy (or deliberately leaked with it when a wedged
// thread prevents a safe free).
void cap_serve_set_telemetry(void* hv, void* tel) {
  ((Handle*)hv)->tel = (cap_tel::TelPlane*)tel;
}

// Per-token (fam, kid-hash) of the LAST cap_serve_drain call, token-
// aligned with its tok_off ordering. Single-consumer: must be called
// from the drain thread, between drains. Returns tokens copied.
int64_t cap_serve_drain_aux(void* hv, int8_t* fams_out,
                            uint8_t* kids_out, int64_t max_tokens) {
  Handle* h = (Handle*)hv;
  int64_t n = (int64_t)h->last_fams.size();
  if (n > max_tokens) n = max_tokens;
  if (n > 0) {
    std::memcpy(fams_out, h->last_fams.data(), (size_t)n);
    std::memcpy(kids_out, h->last_kids.data(),
                (size_t)n * cap_tel::KID_LEN);
  }
  return n;
}

// Per-token tenant slots of the LAST cap_serve_drain call (-1 = the
// header-cache miss Python's fix_misses resolves), token-aligned with
// cap_serve_drain_aux. Single-consumer, like the others.
int64_t cap_serve_drain_tens(void* hv, int16_t* tens_out,
                             int64_t max_tokens) {
  Handle* h = (Handle*)hv;
  int64_t n = (int64_t)h->last_tens.size();
  if (n > max_tokens) n = max_tokens;
  if (n > 0)
    std::memcpy(tens_out, h->last_tens.data(),
                (size_t)n * sizeof(int16_t));
  return n;
}

// Arm (or disarm) reader-side verdict-cache digests. Call before the
// first connection is added — readers sample the flag per frame.
void cap_serve_set_digests(void* hv, int32_t on) {
  ((Handle*)hv)->digests_on.store(on, std::memory_order_relaxed);
}

// Arm (or disarm) the shm transport: attach requests (CVB1 type 15)
// are honored when on; off acks them status 1 (socket keeps serving)
// and counts CTR_SHM_FALLBACKS.
void cap_serve_set_shm(void* hv, int32_t on) {
  ((Handle*)hv)->shm_on.store(on, std::memory_order_relaxed);
}

// ---------------------------------------------------------------------------
// r20 tenant-fair scheduling + admission ABI. ALL of these symbols
// are probed as one group by the binding (_SCHED_SYMBOLS): a stale
// .so missing any of them degrades to FIFO + python-side admission
// with a counted fallback — never wrong scheduling, just slower.
// ---------------------------------------------------------------------------

// Layout handshake: slot counts and the counter-block length the
// binding must agree on before arming fair/admission natively.
void cap_serve_layout_sched(int32_t* out) {
  out[0] = SCHED_SLOTS;
  out[1] = SCHED_BE;
  out[2] = cap_tel::N_TEN;
  out[3] = CTR_N;
}

// Arm (or disarm) DRR fair scheduling on the drain path. quantum is
// the per-visit token credit (<= 0 keeps the current value). Safe at
// any time; a disarm flushes the parked subqueues in DRR order first.
void cap_serve_set_fair(void* hv, int32_t on, int64_t quantum) {
  Handle* h = (Handle*)hv;
  if (quantum > 0) h->sched.quantum = quantum;
  h->fair_on.store(on, std::memory_order_relaxed);
}

// Per-slot DRR weight (slot = tenant slot 0..63, or SCHED_BE for the
// shared best-effort slot). Weights < 1 are ignored.
void cap_serve_set_weight(void* hv, int32_t slot, int32_t w) {
  Handle* h = (Handle*)hv;
  if (slot < 0 || slot >= SCHED_SLOTS || w < 1) return;
  h->sched.weight[slot] = w;
}

// Arm (or disarm) per-tenant token-bucket admission in the readers:
// rate tokens/sec per tenant, burst tokens of depth. Reconfiguring
// resets every bucket (full) and every shed scale (1.0).
void cap_serve_set_admission(void* hv, int32_t on, double rate,
                             double burst) {
  Handle* h = (Handle*)hv;
  std::lock_guard<std::mutex> lk(h->adm_mu);
  h->adm_rate = rate < 0 ? 0 : rate;
  h->adm_burst = burst < 0 ? 0 : burst;
  for (auto& b : h->adm) b = AdmBucket();
  h->adm_on.store(on, std::memory_order_relaxed);
}

// Shed lever: scale one tenant slot's effective rate (slot indexes
// the FULL tenant table, none/other included). 1.0 restores.
void cap_serve_set_tenant_scale(void* hv, int32_t slot, double scale) {
  Handle* h = (Handle*)hv;
  if (slot < 0 || slot >= cap_tel::N_TEN) return;
  std::lock_guard<std::mutex> lk(h->adm_mu);
  h->adm[slot].scale = scale < 0 ? 0 : scale;
}

// Late admission: one bucket take for a token whose tenant was a
// header-cache MISS at read time (the drain path calls this after
// Python resolved the issuer — same arithmetic, same counters, so
// the exact checked == admitted + throttled equation still holds).
// Returns 1 = throttled (*retry_ms_out set), 0 = admitted.
int32_t cap_serve_adm_take(void* hv, int32_t slot,
                           int32_t* retry_ms_out) {
  Handle* h = (Handle*)hv;
  if (slot < 0 || slot >= cap_tel::N_TEN) slot = cap_tel::TEN_NONE;
  double now = mono_now();
  bool throttled = false;
  double wait = 0.0;
  {
    std::lock_guard<std::mutex> lk(h->adm_mu);
    AdmBucket& b = h->adm[slot];
    double rate = h->adm_rate * b.scale;
    if (!b.init) {
      b.init = true;
      b.level = h->adm_burst;
      b.t_last = now;
    } else if (now > b.t_last) {
      b.level += (now - b.t_last) * rate;
      if (b.level > h->adm_burst) b.level = h->adm_burst;
      b.t_last = now;
    }
    if (b.level >= 1.0) {
      b.level -= 1.0;
    } else {
      throttled = true;
      wait = rate > 1e-9 ? (1.0 - b.level) / rate : 60.0;
    }
  }
  h->ctr[CTR_ADM_CHECKED].fetch_add(1);
  if (throttled) {
    h->ctr[CTR_ADM_THROTTLED].fetch_add(1);
    if (retry_ms_out) {
      int64_t ms = (int64_t)(wait * 1000.0) + 1;
      if (ms < 1) ms = 1;
      if (ms > 60000) ms = 60000;
      *retry_ms_out = (int32_t)ms;
    }
    return 1;
  }
  h->ctr[CTR_ADM_ADMITTED].fetch_add(1);
  return 0;
}

// One tenant bucket's current fill level in tokens (no refill — the
// capstat admission column's point-in-time view).
double cap_serve_bucket_fill(void* hv, int32_t slot) {
  Handle* h = (Handle*)hv;
  if (slot < 0 || slot >= cap_tel::N_TEN) return 0.0;
  std::lock_guard<std::mutex> lk(h->adm_mu);
  return h->adm[slot].init ? h->adm[slot].level : h->adm_burst;
}

// Per-token admission verdicts of the LAST cap_serve_drain call
// (1 = throttled: answer with pushback, never verify), token-aligned
// with cap_serve_drain_aux. Single-consumer, like the others.
int64_t cap_serve_drain_thr(void* hv, uint8_t* out,
                            int64_t max_tokens) {
  Handle* h = (Handle*)hv;
  int64_t n = (int64_t)h->last_thr.size();
  if (n > max_tokens) n = max_tokens;
  if (n > 0) std::memcpy(out, h->last_thr.data(), (size_t)n);
  return n;
}

// ---------------------------------------------------------------------------
// r22 occupancy-plane ABI. Probed as one group by the binding
// (_OCC_SYMBOLS); a stale .so missing either symbol degrades to
// inferred ring-wait with a counted fallback
// (serve.native.occ_fallbacks) — never wrong numbers, just coarser.
// ---------------------------------------------------------------------------

// Layout handshake: [abi version, doubles per drained request]. The
// binding disarms the plane on any mismatch.
void cap_serve_layout_occ(int32_t* out) {
  out[0] = 1;  // version
  out[1] = 1;  // one t_enq double per request
}

// Per-REQUEST reader-side enqueue stamps (steady-clock seconds) of the
// LAST cap_serve_drain call, in drain order — request-aligned with
// req_seq/req_t0. Single-consumer, like the others.
int64_t cap_serve_drain_enq(void* hv, double* out, int64_t max_reqs) {
  Handle* h = (Handle*)hv;
  int64_t n = (int64_t)h->last_enq.size();
  if (n > max_reqs) n = max_reqs;
  if (n > 0)
    std::memcpy(out, h->last_enq.data(), (size_t)n * sizeof(double));
  return n;
}

// ---------------------------------------------------------------------------
// DRR test probe: drives the EXACT scheduler struct the drain path
// uses, item identity = arrival order — tests/test_admission.py pins
// the dispatch order bit-for-bit against the python twin
// (cap_tpu/serve/drr.py), which is what makes both chains schedule
// identically by construction.
// ---------------------------------------------------------------------------

namespace serve_native {
struct DrrProbe {
  DrrSched s;
  int64_t next_id = 0;
};
}  // namespace serve_native

void* cap_drr_create(int64_t quantum) {
  DrrProbe* p = new DrrProbe();
  if (quantum > 0) p->s.quantum = quantum;
  return p;
}

void cap_drr_set_weight(void* pv, int32_t slot, int32_t w) {
  DrrProbe* p = (DrrProbe*)pv;
  if (slot >= 0 && slot < SCHED_SLOTS && w >= 1)
    p->s.weight[slot] = w;
}

void cap_drr_push(void* pv, int32_t slot, int64_t cost) {
  DrrProbe* p = (DrrProbe*)pv;
  p->next_id++;
  p->s.push(slot, (void*)(uintptr_t)p->next_id, cost);
}

int64_t cap_drr_pop(void* pv) {
  DrrProbe* p = (DrrProbe*)pv;
  void* item = p->s.pop();
  return item ? (int64_t)(uintptr_t)item - 1 : -1;
}

void cap_drr_destroy(void* pv) { delete (DrrProbe*)pv; }

// Per-token sha256[:16] digests of the LAST cap_serve_drain call,
// token-aligned with its tok_off ordering (zero rows = compute in
// Python). Single-consumer, like cap_serve_drain_aux.
int64_t cap_serve_drain_digests(void* hv, uint8_t* digests_out,
                                int64_t max_tokens) {
  Handle* h = (Handle*)hv;
  int64_t n = (int64_t)(h->last_digests.size() / DIG_LEN);
  if (n > max_tokens) n = max_tokens;
  if (n > 0)
    std::memcpy(digests_out, h->last_digests.data(),
                (size_t)n * DIG_LEN);
  return n;
}

// Ring high-water mark since the last reset (gauge-reset-on-scrape:
// pass reset=1 to rearm at the CURRENT depth, so the next interval's
// mark starts from live occupancy, not zero).
int64_t cap_serve_ring_hwm(void* hv, int32_t reset) {
  if (!hv) return 0;
  Handle* h = (Handle*)hv;
  int64_t hwm = h->ring_hwm.load(std::memory_order_relaxed);
  if (reset)
    h->ring_hwm.store(h->queued_tokens.load(std::memory_order_relaxed),
                      std::memory_order_relaxed);
  return hwm;
}

// Post one pre-encoded frame (stats response / keys ack built in
// Python) at the given request's seq slot.
int32_t cap_serve_post_raw(void* hv, int32_t conn_id, int64_t seq,
                           const uint8_t* data, int64_t len) {
  Handle* h = (Handle*)hv;
  std::shared_ptr<Conn> c;
  {
    std::lock_guard<std::mutex> lk(h->conns_mu);
    auto it = h->conns.find(conn_id);
    if (it != h->conns.end()) c = it->second;
  }
  if (!c) {
    h->ctr[CTR_DROPPED_POSTS].fetch_add(1);
    return 1;
  }
  enqueue_response(c, seq, std::string((const char*)data, (size_t)len));
  return 0;
}

// Shutdown: wake everything, sever every connection, join (bounded).
// The handle is freed only when every thread confirmed exit —
// otherwise it is deliberately leaked (a wedged kernel call must not
// become a use-after-free).
void cap_serve_destroy(void* hv) {
  Handle* h = (Handle*)hv;
  h->stop.store(true);
  {
    std::lock_guard<std::mutex> lk(h->mu);
    h->cv_data.notify_all();
    h->cv_space.notify_all();
  }
  std::vector<std::shared_ptr<Conn>> cs;
  {
    std::lock_guard<std::mutex> lk(h->conns_mu);
    for (auto& kv : h->conns) cs.push_back(kv.second);
  }
  for (auto& c : cs) {
    ::shutdown(c->fd, SHUT_RDWR);
    std::lock_guard<std::mutex> lk(c->mu);
    c->cv.notify_all();
  }
  bool all = false;
  for (int i = 0; i < 500 && !all; i++) {
    all = true;
    for (auto& c : cs)
      if (c->finished.load() < 2) all = false;
    if (!all) ::usleep(10000);
  }
  for (;;) {
    Req* r = (Req*)h->ring.try_pop();
    if (!r) break;
    delete r;
  }
  for (int s = 0; s < SCHED_SLOTS; s++) {
    for (auto& it : h->sched.q[s]) delete (Req*)it.first;
    h->sched.q[s].clear();
  }
  h->sched.n = 0;
  if (h->barrier) {
    delete h->barrier;
    h->barrier = nullptr;
  }
  if (h->carry) {
    delete h->carry;
    h->carry = nullptr;
  }
  if (all) {
    if (h->tel) cap_tel::destroy(h->tel);
    delete h;
  }  // else: leak handle AND plane — reader threads may still touch both
}

// Test/parity hook: classify one frame held fully in a byte buffer,
// with the exact reader semantics (PF_* status codes above).
int32_t cap_serve_probe_frame(const uint8_t* data, int64_t len,
                              int64_t* consumed) {
  Parsed p;
  int st = parse_frame(data, len, p);
  if (consumed) *consumed = (st == PF_OK) ? p.consumed : 0;
  return st;
}

// ---------------------------------------------------------------------------
// native closed-loop load driver (tools/bench_stages.py): streams
// pipelined plain verify requests and parses responses entirely in C,
// so a bench against a stub engine isolates the WORKER's Python-side
// serial cost per token — no Python client chain in the measurement.
// ---------------------------------------------------------------------------

namespace serve_native {

struct DriveShared {
  std::atomic<int64_t> tokens{0};
  std::atomic<int64_t> reqs{0};
  std::atomic<int32_t> errors{0};
};

// port >= 0 → TCP host:port; port < 0 → host is a UDS path (the
// bench_stages transport column's uds arm).
static void drive_one(const char* host, int32_t port, const uint8_t* blob,
                      const int64_t* offs, int32_t n_tokens,
                      int32_t req_tokens, int32_t depth, double seconds,
                      uint32_t seed, DriveShared* sh) {
  int fd;
  if (port >= 0) {
    fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) { sh->errors.fetch_add(1); return; }
    struct sockaddr_in addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
        ::connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
      ::close(fd);
      sh->errors.fetch_add(1);
      return;
    }
    int one = 1;
    ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  } else {
    fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
    if (fd < 0) { sh->errors.fetch_add(1); return; }
    struct sockaddr_un addr;
    std::memset(&addr, 0, sizeof(addr));
    addr.sun_family = AF_UNIX;
    std::strncpy(addr.sun_path, host, sizeof(addr.sun_path) - 1);
    if (::connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
      ::close(fd);
      sh->errors.fetch_add(1);
      return;
    }
  }
  // pre-encode a handful of distinct request frames, reused round-robin
  std::vector<std::string> frames;
  uint32_t rng = seed * 2654435761u + 12345u;
  for (int v = 0; v < 16; v++) {
    rng = rng * 1103515245u + 12345u;
    int32_t lo = (int32_t)(rng % (uint32_t)(n_tokens > req_tokens
                                                ? n_tokens - req_tokens
                                                : 1));
    std::string f;
    put_u32(f, MAGIC);
    f.push_back((char)T_VERIFY_REQ);
    put_u32(f, (uint32_t)req_tokens);
    for (int32_t j = 0; j < req_tokens; j++) {
      int64_t o = offs[lo + j], e = offs[lo + j + 1];
      put_u32(f, (uint32_t)(e - o));
      f.append((const char*)(blob + o), (size_t)(e - o));
    }
    frames.push_back(std::move(f));
  }
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration_cast<
                      std::chrono::steady_clock::duration>(
                      std::chrono::duration<double>(seconds));
  std::vector<uint8_t> buf;
  size_t start = 0;
  int inflight = 0;
  size_t next = 0;
  bool ok = true;
  for (;;) {
    bool in_window = std::chrono::steady_clock::now() < deadline;
    while (ok && in_window && inflight < depth) {
      ok = send_all(fd, frames[next++ % frames.size()]);
      if (ok) inflight++;
    }
    if (!inflight || !ok) break;
    // read one response frame
    for (;;) {
      Parsed p;
      int st = PF_INCOMPLETE;
      if (buf.size() > start)
        st = parse_frame(buf.data() + start,
                         (int64_t)(buf.size() - start), p);
      if (st == PF_OK) {
        start += (size_t)p.consumed;
        if (start == buf.size()) { buf.clear(); start = 0; }
        inflight--;
        if (in_window) {
          sh->tokens.fetch_add((int64_t)p.entries.size());
          sh->reqs.fetch_add(1);
        }
        break;
      }
      if (st != PF_INCOMPLETE) { ok = false; break; }
      if (start > 0) {
        buf.erase(buf.begin(), buf.begin() + start);
        start = 0;
      }
      size_t old = buf.size();
      buf.resize(old + (1 << 16));
      ssize_t r = ::recv(fd, buf.data() + old, 1 << 16, 0);
      if (r <= 0) { buf.resize(old); ok = false; break; }
      buf.resize(old + (size_t)r);
    }
    if (!in_window && inflight == 0) break;
  }
  ::close(fd);
  if (!ok) sh->errors.fetch_add(1);
}

}  // namespace serve_native

int32_t cap_bench_drive(const char* host, int32_t port,
                        const uint8_t* blob, const int64_t* offs,
                        int32_t n_tokens, int32_t req_tokens,
                        int32_t depth, double seconds, int32_t n_conns,
                        int64_t* out_tokens, int64_t* out_reqs) {
  DriveShared sh;
  std::vector<std::thread> threads;
  for (int32_t i = 0; i < (n_conns > 0 ? n_conns : 1); i++)
    threads.emplace_back(drive_one, host, port, blob, offs, n_tokens,
                         req_tokens, depth, seconds, (uint32_t)(i + 1),
                         &sh);
  for (auto& t : threads) t.join();
  if (out_tokens) *out_tokens = sh.tokens.load();
  if (out_reqs) *out_reqs = sh.reqs.load();
  return sh.errors.load() ? -1 : 0;
}

}  // extern "C"
