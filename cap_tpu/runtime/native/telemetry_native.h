// telemetry_native — the native telemetry plane's internal interface,
// shared between telemetry_native.cpp (the plane itself) and
// serve_native.cpp (the serve chain that feeds it).
//
// The plane mirrors cap_tpu.obs.decision's registered vocabularies by
// INDEX: reason classes, families, and latency buckets are fixed-order
// tuples on the Python side (REASON_INDEX / FAMILIES /
// LAT_BUCKET_INDEX) and plain enums here; cap_tel_layout() lets the
// binding verify both sides agree before enabling the plane, so a
// stale .so degrades to the Python fold instead of miscounting.

#ifndef CAP_TPU_TELEMETRY_NATIVE_H
#define CAP_TPU_TELEMETRY_NATIVE_H

#include <cstdint>

namespace cap_tel {

// obs/decision.py REASON_INDEX order (12 registered reason classes;
// r20 inserted "throttled" — admission pushback — before "internal",
// which stays LAST: the fold uses the final index as its
// out-of-range bucket).
enum {
  N_REASON = 12,
  // obs/decision.py FAMILIES order; index 10 is "unknown" (r17 added
  // slhdsa128s/slhdsa128f before "other" — layout handshake bumped).
  N_FAM = 11,
  FAM_UNKNOWN = 10,
  // obs/decision.py LAT_BUCKET_INDEX order; index 5 is "na".
  N_LAT = 6,
  LAT_NA = 5,
  // counter block layout: accept, reject[11], family[9], then the
  // plane's own native counters (header-cache hits/misses, exemplar
  // ring drops).
  CTR_ACCEPT = 0,
  CTR_REJECT0 = 1,
  CTR_FAM0 = CTR_REJECT0 + N_REASON,
  CTR_CACHE_HITS = CTR_FAM0 + N_FAM,
  CTR_CACHE_MISSES = CTR_CACHE_HITS + 1,
  CTR_EX_DROPS = CTR_CACHE_MISSES + 1,
  N_CTR = CTR_EX_DROPS + 1,
  // native histogram series (telemetry.py bucket layout, bounds
  // passed in at create time so the edges are bit-identical).
  SERIES_REQUEST_S = 0,
  SERIES_CHUNK_TOKENS = 1,
  N_SERIES = 2,
  // obs/decision.py RING_SAMPLE_EVERY.
  SAMPLE_EVERY = 16,
  // bounded exemplar ring (matches telemetry.MAX_DECISION_ENTRIES).
  EX_RING = 256,
  // fixed exemplar record stride handed across the ctypes boundary:
  // key(1) fam(1) lat(1) kid_len(1) kid(12) trace_len(1) trace(64),
  // padded to 88.
  EX_STRIDE = 88,
  KID_LEN = 12,
  MAX_SEG_BYTES = 1024,  // decision._seg_family_kid's parse bound
  CACHE_CAP = 4096,      // decision._HDR_CACHE_CAP (clear at cap)
  // tenant attribution (r19): obs/decision.py's bounded tenant table
  // — TENANT_CAP real slots + "none" + "other". Like families, the
  // native side never derives a tenant itself: slots arrive from the
  // Python classifier through learn(), counters are per SLOT here and
  // mapped back to labels (issuer hashes) by the binding at scrape.
  TEN_SLOTS = 64,        // decision.TENANT_CAP
  TEN_NONE = 64,         // decision.TENANT_NONE_IDX
  TEN_OTHER = 65,        // decision.TENANT_OTHER_IDX
  N_TEN = 66,            // decision.N_TENANT
  // per-slot tenant counter stride: tokens, accept, reject_total,
  // reject[N_REASON] — then the whole block is prefixed by three
  // globals (lookups, attributed, overflow) so the exact equation
  // lookups == attributed + overflow folds natively too.
  TEN_STRIDE = 3 + N_REASON,
  TCTR_LOOKUPS = 0,
  TCTR_ATTRIBUTED = 1,
  TCTR_OVERFLOW = 2,
  TCTR_BASE = 3,
  N_TCTR = TCTR_BASE + N_TEN * TEN_STRIDE,
};

struct TelPlane;

TelPlane* create(const double* bounds, int32_t n_bounds);
void destroy(TelPlane* t);

// Classify one header SEGMENT against the native cache. Returns the
// family index on a hit (kid copied into kid_out, kid_len_out set,
// tenant slot into ten_out), -1 on a miss — the caller (Python, on
// the drain path) resolves the miss with obs/decision._seg_fkt and
// learn()s it back, which is what makes family AND tenant
// classification structurally bit-exact: the cache only ever holds
// values the Python classifier produced.
int32_t classify(TelPlane* t, const uint8_t* seg, int64_t len,
                 uint8_t* kid_out, int32_t* kid_len_out,
                 int16_t* ten_out);
void learn(TelPlane* t, const uint8_t* seg, int64_t len, int32_t fam,
           const uint8_t* kid, int32_t kid_len, int32_t ten);

// Fold one chunk of verdicts: the exact obs/decision.record_batch
// aggregation (one counter add per present key, sampling positions
// c == 1 or c % 16 == 0 over the post-increment sequence, exemplars
// attributed to the same token the Python fold would sample).
// tens: per-token tenant slot (nullptr / out-of-range → TEN_NONE);
// lat_s: the chunk latency in seconds (< 0 → no per-tenant latency
// observation, mirroring record_batch's latency_s=None).
void fold(TelPlane* t, int64_t n_tokens, const uint8_t* statuses,
          const uint8_t* reasons, const int8_t* fams,
          const int16_t* tens, const uint8_t* kids, int32_t lat_idx,
          double lat_s, const uint8_t* trace, int32_t trace_len);

void observe(TelPlane* t, int32_t series, double value);

}  // namespace cap_tel

#endif  // CAP_TPU_TELEMETRY_NATIVE_H
