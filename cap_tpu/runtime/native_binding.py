"""ctypes binding for the native capruntime (jose_native.cpp).

Loads cap_tpu/runtime/native/libcapruntime.so (built via ``make native``)
and exposes ``prepare_batch(tokens)`` returning, per token, either a
:class:`NativeParsed` (duck-compatible with jose.ParsedJWS for the batch
path: alg / kid / signature / signing_input / payload / claims() /
digest()) or the same taxonomy exception the Python parser raises.

Raises OSError at import when the library is missing — runtime.prep
catches that and falls back to pure Python.
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..errors import MalformedTokenError, TokenNotSignedError

_LIB_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "native", "libcapruntime.so")
# Build artifacts are not committed (ADVICE r1): build on first use.
# Unconditional — build_native() is a cheap no-op when everything is
# fresh (per-target mtime checks) and rebuilds STALE libraries too; a
# missing-only gate would leave an old libcapruntime.so without the
# record packer and never compile _capclaims.so at all.
from .._build import build_native

build_native()
_lib = ctypes.CDLL(_LIB_PATH)

ALG_NAMES = ["RS256", "RS384", "RS512", "ES256", "ES384", "ES512",
             "PS256", "PS384", "PS512", "EdDSA"]

(_OK, _ERR_SEGMENTS, _ERR_B64, _ERR_HEADER_JSON, _ERR_NO_ALG, _ERR_UNSIGNED,
 _ERR_CRIT) = range(7)


class _TokOut(ctypes.Structure):
    _fields_ = [
        ("status", ctypes.c_int32),
        ("alg_id", ctypes.c_int32),
        ("sig_off", ctypes.c_int64),
        ("sig_len", ctypes.c_int64),
        ("payload_off", ctypes.c_int64),
        ("payload_len", ctypes.c_int64),
        ("signing_input_len", ctypes.c_int64),
        ("kid", ctypes.c_uint8 * 160),
        ("alg_raw", ctypes.c_uint8 * 32),
        ("digest", ctypes.c_uint8 * 64),
        ("digest_len", ctypes.c_int32),
        ("kid_len", ctypes.c_int32),
        ("alg_len", ctypes.c_int32),
        ("pad", ctypes.c_int32),
    ]


assert ctypes.sizeof(_TokOut) == _lib.cap_tokout_size(), \
    "TokOut ABI mismatch between binding and libcapruntime"

_lib.cap_prepare_batch.argtypes = [
    ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
    ctypes.POINTER(_TokOut), ctypes.POINTER(ctypes.c_uint8),
    ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
]
_lib.cap_sha_batch.argtypes = [
    ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
    ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_int32,
]
def _load_claims_ext():
    """Import the _capclaims extension module (None when unbuilt)."""
    import importlib.machinery
    import importlib.util

    from .._build import EXT_NAME

    path = os.path.join(os.path.dirname(_LIB_PATH), EXT_NAME)
    if not os.path.exists(path):
        return None
    try:
        loader = importlib.machinery.ExtensionFileLoader("_capclaims", path)
        spec = importlib.util.spec_from_loader("_capclaims", loader)
        mod = importlib.util.module_from_spec(spec)
        loader.exec_module(mod)
        return mod
    except Exception:  # noqa: BLE001 - stale/foreign .so → Python parse
        return None


_claims_ext = _load_claims_ext()

try:
    _lib.cap_pack_sig_records.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
    ]
    _HAS_PACK_RECORDS = True
except AttributeError:       # stale .so from before the packer
    _HAS_PACK_RECORDS = False

try:
    _lib.cap_pss_check_batch.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_int32,
    ]
    _HAS_PSS_CHECK = True
except AttributeError:           # stale .so from before the PSS check
    _HAS_PSS_CHECK = False


class NativeParsed:
    """Parsed JWS view over native-decoded buffers (unverified)."""

    __slots__ = ("alg", "kid", "signature", "payload", "signing_input",
                 "_digest", "_digest_len", "header")

    def __init__(self, alg: str, kid: Optional[str], signature: bytes,
                 payload: bytes, signing_input: bytes,
                 digest: bytes):
        self.alg = alg
        self.kid = kid
        self.signature = signature
        self.payload = payload
        self.signing_input = signing_input
        self._digest = digest
        # only alg/kid are extracted natively; enough for the batch path
        self.header: Dict[str, Any] = (
            {"alg": alg, "kid": kid} if kid is not None else {"alg": alg})

    def claims(self) -> Dict[str, Any]:
        try:
            claims = json.loads(self.payload)
        except (ValueError, UnicodeDecodeError) as e:
            raise MalformedTokenError(f"payload is not valid JSON: {e}") from e
        if not isinstance(claims, dict):
            raise MalformedTokenError("payload is not a JSON object")
        return claims

    def digest(self) -> bytes:
        """Precomputed SHA-2 of the signing input (empty for EdDSA)."""
        return self._digest


def prepare_batch(tokens: Sequence[str],
                  n_threads: int = 0) -> List[Any]:
    n = len(tokens)
    if n == 0:
        return []
    try:
        encoded = [t.encode("ascii") for t in tokens]
    except UnicodeEncodeError:
        # non-ascii tokens: delegate entirely to the Python parser
        from .prep import _prepare_python

        return _prepare_python(tokens)
    blob = b"".join(encoded)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    # per-token scratch: decoded payload+sig always fit in token length+8
    scratch_sizes = np.asarray([len(e) + 8 for e in encoded], np.int64)
    scratch_offsets = np.zeros(n + 1, np.int64)
    np.cumsum(scratch_sizes, out=scratch_offsets[1:])
    scratch = np.empty(int(scratch_offsets[-1]), np.uint8)
    outs = (_TokOut * n)()

    _lib.cap_prepare_batch(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, outs,
        scratch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        scratch_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n_threads,
    )

    scratch_bytes = scratch.tobytes()
    results: List[Any] = []
    for i in range(n):
        o = outs[i]
        if o.status == _OK:
            base = int(scratch_offsets[i])
            tok_base = int(offsets[i])
            payload = scratch_bytes[base: base + o.payload_len]
            sig = scratch_bytes[base + o.sig_off:
                                base + o.sig_off + o.sig_len]
            signing_input = blob[tok_base: tok_base + o.signing_input_len]
            if o.kid_len == -1:
                kid = None
            elif o.kid_len == -2:
                from ..jwt.jose import parse_compact as _pc

                try:
                    kid = _pc(tokens[i]).kid
                except Exception:  # noqa: BLE001
                    kid = None
            else:
                kid = bytes(bytearray(o.kid[: o.kid_len])).decode(
                    "utf-8", "surrogateescape")
            alg = (ALG_NAMES[o.alg_id] if o.alg_id >= 0
                   else bytes(bytearray(o.alg_raw[: o.alg_len])).decode(
                       "utf-8", "surrogateescape"))
            results.append(NativeParsed(
                alg, kid, sig, payload, signing_input,
                bytes(o.digest[: o.digest_len])))
        elif o.status == _ERR_UNSIGNED:
            results.append(TokenNotSignedError("token must be signed"))
        elif o.status == _ERR_SEGMENTS:
            results.append(MalformedTokenError(
                "compact JWS must have 3 segments"))
        elif o.status == _ERR_NO_ALG:
            results.append(MalformedTokenError(
                "protected header missing alg parameter"))
        elif o.status == _ERR_HEADER_JSON:
            results.append(MalformedTokenError(
                "protected header is not a JSON object"))
        elif o.status == _ERR_CRIT:
            results.append(MalformedTokenError("unsupported crit header"))
        else:
            results.append(MalformedTokenError(
                "invalid base64url segment"))
    return results


def _loads_claims(raw: bytes):
    """ONE json.loads-payload-to-claims helper: dict or the
    MalformedTokenError whose class/wording every parse path shares
    (prefetch fallbacks, the raw OIDC mode, _parse_one)."""
    try:
        c = json.loads(raw)
        return c if isinstance(c, dict) else \
            MalformedTokenError("payload is not a JSON object")
    except (ValueError, UnicodeDecodeError) as e:
        return MalformedTokenError(f"payload is not valid JSON: {e}")


def registered_claims_from_payloads(payloads: Sequence[bytes]):
    """[payload bytes] → per-payload claims for VALIDATION only.

    Each entry is a dict (the native extension's registered-claims
    SUBSET — iss/sub/aud/exp/nbf/iat/nonce/azp/auth_time — or the
    json.loads full dict on its conservative fallbacks) or a
    MalformedTokenError. The OIDC raw mode reads only registered
    claims, so the subset is indistinguishable from the full parse
    there while skipping the full dict build per token.
    """
    full = _loads_claims
    if _claims_ext is None or not hasattr(_claims_ext,
                                          "registered_batch"):
        return [full(p) for p in payloads]
    scratch = b"".join(payloads)
    lens = np.asarray([len(p) for p in payloads], np.int64)
    offs = np.concatenate([[0], np.cumsum(lens)[:-1]]).astype(np.int64) \
        if len(payloads) else np.zeros(0, np.int64)
    parsed, n_bad = _claims_ext.registered_batch(
        scratch, np.ascontiguousarray(offs), np.ascontiguousarray(lens))
    if n_bad == 0:
        return parsed
    return [v if type(v) is dict else full(payloads[i])
            for i, v in enumerate(parsed)]


def _copy_claims(v):
    """Independent copy of a parsed-JSON value (containers only)."""
    if isinstance(v, dict):
        return {k: _copy_claims(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_copy_claims(x) for x in v]
    return v


class PreparedBatch:
    """Structure-of-arrays view of a prepared token batch.

    The zero-copy fast path for ``TPUBatchKeySet``: statuses, alg ids,
    kid bytes, signatures, and digests stay as numpy arrays so
    bucketing, key-row lookup, and limb packing are vectorized;
    per-token Python objects are only created lazily (claims of
    verified tokens, error objects for failures).
    """

    __slots__ = ("n", "status", "alg_id", "kid_mat", "kid_len", "sig_off",
                 "sig_len", "payload_off", "payload_len", "si_len", "digest",
                 "digest_len", "scratch", "blob", "tok_off", "alg_raw",
                 "alg_len", "_claims_cache", "_raw_ok")

    def __init__(self, n, status, alg_id, kid_mat, kid_len, sig_off, sig_len,
                 payload_off, payload_len, si_len, digest, digest_len,
                 scratch, blob, tok_off, alg_raw, alg_len):
        self.n = n
        self.status = status
        self.alg_id = alg_id
        self.kid_mat = kid_mat
        self.kid_len = kid_len          # -1 absent, -2 overlong
        self.sig_off = sig_off          # absolute into scratch
        self.sig_len = sig_len
        self.payload_off = payload_off  # absolute into scratch
        self.payload_len = payload_len
        self.si_len = si_len
        self.digest = digest            # [n, 64] uint8
        self.digest_len = digest_len
        self.scratch = scratch          # uint8 array (decoded payload+sig)
        self.blob = blob                # bytes (raw concatenated tokens)
        self.tok_off = tok_off
        self.alg_raw = alg_raw          # [n, 32] uint8 (for unknown algs)
        self.alg_len = alg_len

    # -- vectorized helpers -----------------------------------------------

    def sig_matrix(self, idx: np.ndarray, width: int) -> np.ndarray:
        """[len(idx), width] uint8: left-aligned raw signature bytes,
        zero-padded at the tail (pair with sig_len)."""
        cols = np.arange(width)[None, :]
        offs = self.sig_off[idx][:, None] + cols
        lens = self.sig_len[idx][:, None]
        safe = np.minimum(offs, len(self.scratch) - 1)
        mat = self.scratch[safe]
        return np.where(cols < lens, mat, 0).astype(np.uint8)

    def kid_rows(self, idx: np.ndarray, kid_to_row: dict) -> np.ndarray:
        """Vectorized kid → key-row resolution. Returns row per token;
        -1 = no kid; -2 = unknown/unresolvable kid.

        One np.unique over (kid bytes ‖ kid length) views, then a dict
        lookup per *unique* kid — O(m log m + uniques), independent of
        JWKS size (byte-exact: embedded NULs fine; overlong kids were
        flagged by the native layer and resolve to -2 → exact slow path).
        """
        m = len(idx)
        lens = self.kid_len[idx]
        rows = np.full(m, -2, np.int32)
        rows[lens == -1] = -1
        present = lens >= 0
        if not present.any():
            return rows
        keyed = np.zeros((m, 164), np.uint8)
        keyed[present, :160] = self.kid_mat[idx[present]]
        keyed[present, 160:] = lens[present, None].astype(np.int32).view(
            np.uint8).reshape(-1, 4)
        view = np.ascontiguousarray(keyed).view(
            np.dtype((np.void, 164))).ravel()
        uniq, inverse = np.unique(view, return_inverse=True)
        uniq_rows = np.full(len(uniq), -2, np.int32)
        for u in range(len(uniq)):
            raw = uniq[u].tobytes()
            klen = int(np.frombuffer(raw[160:], np.int32)[0])
            if klen < 0:
                continue
            kid = raw[:klen].decode("utf-8", "surrogateescape")
            uniq_rows[u] = kid_to_row.get(kid, -2)
        resolved = uniq_rows[inverse]
        rows[present] = resolved[present]
        return rows

    def pack_sig_records(self, idx: np.ndarray, expect_size: np.ndarray,
                         extra_valid: np.ndarray, key_rows: np.ndarray,
                         width: int, h_len: int,
                         pad: int) -> Optional[np.ndarray]:
        """One-pass native build of a packed [pad, width+h_len+2] u8
        record chunk: right-aligned signature ‖ digest ‖ flag ‖ key row.

        Row flags are 1 iff extra_valid[r] and sig_len == expect_size
        (the CPU oracle's length rejections). Returns None when the
        loaded library predates the packer (caller uses the numpy
        path). GIL-free and multithreaded — this replaces several
        full-matrix numpy passes on the batch hot path.
        """
        if not _HAS_PACK_RECORDS:
            return None
        m = len(idx)
        idx = np.ascontiguousarray(idx, np.int64)
        expect = np.ascontiguousarray(expect_size, np.int64)
        valid = np.ascontiguousarray(extra_valid, np.uint8)
        rows = np.ascontiguousarray(key_rows, np.uint8)
        out = np.empty((pad, width + h_len + 2), np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        i64p = ctypes.POINTER(ctypes.c_int64)
        _lib.cap_pack_sig_records(
            self.scratch.ctypes.data_as(u8p), len(self.scratch),
            self.sig_off.ctypes.data_as(i64p),
            self.sig_len.ctypes.data_as(i64p),
            self.digest.ctypes.data_as(u8p), self.digest.shape[1],
            idx.ctypes.data_as(i64p), expect.ctypes.data_as(i64p),
            valid.ctypes.data_as(u8p), rows.ctypes.data_as(u8p),
            m, pad, width, h_len, out.ctypes.data_as(u8p), 0)
        return out

    # -- lazy per-token materialization -----------------------------------

    def payload_bytes(self, i: int) -> bytes:
        o, l = int(self.payload_off[i]), int(self.payload_len[i])
        return self.scratch[o: o + l].tobytes()

    def payload_object_ok(self, indices) -> np.ndarray:
        """[len(indices)] bool: the payload parses as a JSON OBJECT.

        Phase-1 only (no dicts built): the raw-claims serve path passes
        the signed payload bytes through verbatim, but a verified
        signature over a non-object payload must still reject exactly
        like the claims() path. Status 3 (outside the strict native
        parser's envelope) falls back to json.loads so the decision is
        byte-identical to the Python path; without the extension every
        token takes that fallback.
        """
        idx = np.ascontiguousarray(indices, np.int64)
        out = np.zeros(len(idx), bool)
        if _claims_ext is not None and hasattr(_claims_ext,
                                               "validate_batch"):
            offs = np.ascontiguousarray(self.payload_off[idx], np.int64)
            lens = np.ascontiguousarray(self.payload_len[idx], np.int64)
            st = np.frombuffer(
                _claims_ext.validate_batch(self.scratch, offs, lens),
                np.uint8)
            out[:] = st == 0
            for k in np.nonzero(st == 3)[0]:
                out[k] = self._payload_is_object(int(idx[k]))
            return out
        for k, i in enumerate(idx):
            out[k] = self._payload_is_object(int(i))
        return out

    def _payload_is_object(self, i: int) -> bool:
        try:
            return isinstance(json.loads(self.payload_bytes(i)), dict)
        except (ValueError, UnicodeDecodeError):
            return False

    def claims(self, i: int) -> Dict[str, Any]:
        cache = getattr(self, "_claims_cache", None)
        if cache is not None:
            hit = cache.get(i)
            if hit is not None:
                if isinstance(hit, MalformedTokenError):
                    raise hit
                return hit
        try:
            claims = json.loads(self.payload_bytes(i))
        except (ValueError, UnicodeDecodeError) as e:
            raise MalformedTokenError(f"payload is not valid JSON: {e}") from e
        if not isinstance(claims, dict):
            raise MalformedTokenError("payload is not a JSON object")
        return claims

    def prefetch_claims(self, indices) -> None:
        """Pre-parse claim payloads into a per-index cache.

        Called between device dispatch and the materializing sync so
        the host-side JSON parsing overlaps the device wait instead of
        serializing after it. The _capclaims extension does the heavy
        scan GIL-free across threads (~2 µs/token); payloads outside
        its envelope re-parse with json.loads — byte-for-byte identical
        results either way (tests/test_native_runtime.py fuzz parity).
        Without the extension, identical payload bytes (replay-heavy
        workloads) parse once and fan out as independent copies.
        """
        try:
            cache = self._claims_cache
        except AttributeError:
            cache = {}
            self._claims_cache = cache
        scratch = self.scratch
        off, ln = self.payload_off, self.payload_len
        if not cache and isinstance(indices, np.ndarray):
            idx = indices.astype(np.int64, copy=False)
        else:
            idx = np.asarray([i for i in indices
                              if int(i) not in cache], np.int64)
        if len(idx) == 0:
            return
        if _claims_ext is not None:
            offs = np.ascontiguousarray(off[idx], np.int64)
            lens = np.ascontiguousarray(ln[idx], np.int64)
            res = _claims_ext.parse_batch(scratch, offs, lens)
            if isinstance(res, tuple):
                parsed, n_bad = res
            else:
                # pre-(list, n_bad) extension build still loaded (a
                # failed rebuild keeps the old .so): no fast-path count,
                # take the per-token branch below.
                parsed, n_bad = res, -1
            idx_list = idx.tolist()
            if n_bad == 0:
                # All dicts: one C-level bulk insert, no per-token
                # Python iteration (measurable at 64k tokens on a
                # one-core host).
                cache.update(zip(idx_list, parsed))
                return
            for j, v in zip(idx_list, parsed):
                if type(v) is dict:
                    cache[j] = v
                else:
                    # malformed / not-an-object / outside-envelope:
                    # re-parse with json.loads so messages and edge
                    # semantics are byte-identical to the Python path
                    # (the int status is only a fast-path filter).
                    cache[j] = self._parse_one(int(off[j]), int(ln[j]))
            return
        protos: Dict[bytes, Any] = {}
        for i in idx:
            i = int(i)
            raw = scratch[off[i]: off[i] + ln[i]].tobytes()
            proto = protos.get(raw)
            if proto is None:
                proto = self._parse_one(int(off[i]), int(ln[i]))
                protos[raw] = proto
            cache[i] = _copy_claims(proto) \
                if isinstance(proto, dict) else proto

    def _parse_one(self, off: int, ln: int) -> Any:
        """json.loads one payload → dict or MalformedTokenError."""
        return _loads_claims(self.scratch[off: off + ln].tobytes())

    def signature(self, i: int) -> bytes:
        o, l = int(self.sig_off[i]), int(self.sig_len[i])
        return self.scratch[o: o + l].tobytes()

    def signing_input(self, i: int) -> bytes:
        o = int(self.tok_off[i])
        return self.blob[o: o + int(self.si_len[i])]

    def token(self, i: int) -> str:
        o, e = int(self.tok_off[i]), int(self.tok_off[i + 1])
        return self.blob[o:e].decode("ascii")

    def alg(self, i: int) -> str:
        aid = int(self.alg_id[i])
        if aid >= 0:
            return ALG_NAMES[aid]
        n = int(self.alg_len[i])
        return self.alg_raw[i, :n].tobytes().decode("utf-8", "surrogateescape")

    def kid(self, i: int) -> Optional[str]:
        n = int(self.kid_len[i])
        if n == -1:
            return None
        if n == -2:
            # overlong kid (>160B): not captured natively; re-parse the
            # original token in Python for the exact value
            from ..jwt.jose import parse_compact

            try:
                return parse_compact(self.token(i)).kid
            except Exception:  # noqa: BLE001
                return None
        return self.kid_mat[i, :n].tobytes().decode("utf-8", "surrogateescape")

    def error(self, i: int) -> Exception:
        s = int(self.status[i])
        if s == _ERR_UNSIGNED:
            return TokenNotSignedError("token must be signed")
        if s == _ERR_SEGMENTS:
            return MalformedTokenError("compact JWS must have 3 segments")
        if s == _ERR_NO_ALG:
            return MalformedTokenError(
                "protected header missing alg parameter")
        if s == _ERR_HEADER_JSON:
            return MalformedTokenError(
                "protected header is not a JSON object")
        if s == _ERR_CRIT:
            return MalformedTokenError("unsupported crit header")
        return MalformedTokenError("invalid base64url segment")

    def parsed(self, i: int) -> "NativeParsed":
        """Materialize one token as a NativeParsed (slow-path interop)."""
        return NativeParsed(
            self.alg(i), self.kid(i), self.signature(i),
            self.payload_bytes(i), self.signing_input(i),
            bytes(self.digest[i, : self.digest_len[i]]))


_TOKOUT_DTYPE = np.dtype([
    ("status", np.int32), ("alg_id", np.int32),
    ("sig_off", np.int64), ("sig_len", np.int64),
    ("payload_off", np.int64), ("payload_len", np.int64),
    ("signing_input_len", np.int64),
    ("kid", np.uint8, 160), ("alg_raw", np.uint8, 32),
    ("digest", np.uint8, 64), ("digest_len", np.int32),
    ("kid_len", np.int32), ("alg_len", np.int32), ("pad", np.int32),
])
assert _TOKOUT_DTYPE.itemsize == ctypes.sizeof(_TokOut)


def prepare_batch_arrays(tokens: Sequence[str],
                         n_threads: int = 0) -> PreparedBatch:
    """Prepare a batch into structure-of-arrays form (the fast path)."""
    n = len(tokens)
    blob_str = "".join(tokens)
    blob = blob_str.encode("ascii", "replace")  # non-ascii → malformed anyway
    lengths = np.fromiter((len(t) for t in tokens), np.int64, count=n)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(lengths, out=offsets[1:])
    scratch_offsets = np.zeros(n + 1, np.int64)
    np.cumsum(lengths + 8, out=scratch_offsets[1:])
    scratch = np.empty(int(scratch_offsets[-1]) + 1, np.uint8)
    outs = np.zeros(n, dtype=_TOKOUT_DTYPE)

    _lib.cap_prepare_batch(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n,
        outs.ctypes.data_as(ctypes.POINTER(_TokOut)),
        scratch.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        scratch_offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n_threads,
    )
    base = scratch_offsets[:n]
    return PreparedBatch(
        n=n,
        status=outs["status"],
        alg_id=outs["alg_id"],
        kid_mat=outs["kid"],
        kid_len=outs["kid_len"],
        sig_off=base + outs["sig_off"],
        # contiguous copies: the native record packer reads these
        # through raw pointers (structured-array field views stride by
        # the full record and would be misread)
        sig_len=np.ascontiguousarray(outs["sig_len"]),
        payload_off=base + outs["payload_off"],
        payload_len=outs["payload_len"],
        si_len=outs["signing_input_len"],
        digest=np.ascontiguousarray(outs["digest"]),
        digest_len=outs["digest_len"],
        scratch=scratch,
        blob=blob,
        tok_off=offsets,
        alg_raw=outs["alg_raw"],
        alg_len=outs["alg_len"],
    )


def sha_batch(chunks: Sequence[bytes], bits: int,
              n_threads: int = 0) -> List[bytes]:
    """Batched SHA-256/384/512 over byte chunks via the native library."""
    n = len(chunks)
    if n == 0:
        return []
    blob = b"".join(chunks)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum([len(c) for c in chunks], out=offsets[1:])
    out_len = bits // 8
    out = np.empty(n * out_len, np.uint8)
    data = np.frombuffer(blob, np.uint8)
    _lib.cap_sha_batch(
        data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        n, bits,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_threads,
    )
    raw = out.tobytes()
    return [raw[i * out_len:(i + 1) * out_len] for i in range(n)]


def pss_check_batch(em_mat: np.ndarray, mhash_mat: np.ndarray,
                    em_bits: np.ndarray, bits: int, valid: np.ndarray,
                    n_threads: int = 0) -> Optional[np.ndarray]:
    """Batched EMSA-PSS-VERIFY (salt auto-recovered) in native C++.

    em_mat: [n, stride] right-aligned big-endian EM bytes;
    mhash_mat: [n, ≥bits/8] digests; em_bits: [n] modBits-1;
    valid: [n] precondition mask. Returns [n] bool, or None when the
    loaded library predates cap_pss_check_batch (caller falls back to
    the Python check).
    """
    if not _HAS_PSS_CHECK:
        return None
    em_mat = np.ascontiguousarray(em_mat, np.uint8)
    mhash_mat = np.ascontiguousarray(mhash_mat, np.uint8)
    em_bits = np.ascontiguousarray(em_bits, np.int64)
    valid_u8 = np.ascontiguousarray(valid, np.uint8)
    n = em_mat.shape[0]
    out = np.zeros(n, np.uint8)
    _lib.cap_pss_check_batch(
        em_mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n, em_mat.shape[1],
        mhash_mat.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        mhash_mat.shape[1],
        em_bits.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        bits,
        valid_u8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_threads,
    )
    return out.astype(bool)
