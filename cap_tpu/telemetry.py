"""Instrumentation: bounded metrics, structured tracing, flight record.

The reference has no tracing or metrics of any kind — its only
"observability" is the ``const op`` error-prefix convention
(/root/reference/oidc/provider.go:58) and redaction of secrets
(SURVEY.md §5). For a batched TPU verify engine that trades latency
for throughput — and a fleet of worker processes routing around
faults — real instrumentation is required. This module provides:

- a process-local :class:`Recorder` with named counters, gauges, and
  **bounded** log-scale histograms (``observe`` into a long-running
  worker stays O(buckets) forever — raw samples are retained only up
  to a small reservoir cap, quantiles come from the buckets beyond
  it);
- **mergeable snapshots** (:meth:`Recorder.snapshot`,
  :func:`merge_snapshots`): bucket counts add exactly, so a fleet
  aggregate of per-worker snapshots yields the same quantiles as one
  recorder observing everything — no lossy averaging of p99s;
- **structured tracing**: a 16-hex trace id carried in a
  ``contextvars`` context (:func:`trace` / :func:`current_trace`),
  per-stage span records (:func:`span` attaches automatically when a
  trace is active, :func:`trace_span` records explicitly from worker
  threads), and the CVB1 trace-context frame field
  (:mod:`cap_tpu.serve.protocol` types 9/10) to cross process
  boundaries;
- a **flight recorder**: a bounded ring of completed request
  timelines, from which the slowest recent requests can be replayed
  span by span (the worker's ``/flight`` endpoint, ``tools/capstat.py
  --trace``).

Redaction discipline carries over from the reference
(/root/reference/oidc/config.go:20-31): recorders store ONLY metric
names and numbers — never tokens, keys, claims, or any request
payload. Metric names are *checked* on first use (:func:`check_name`
rejects anything token-shaped), and span notes pass through
:func:`scrub_note`.

Telemetry is off by default (zero overhead beyond one attribute check
on the hot path); enable with ``telemetry.enable()`` or scoped via
``telemetry.recording()``.
"""

from __future__ import annotations

import contextvars
import os
import threading
import time
from bisect import bisect_left
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple, Union

# ---------------------------------------------------------------------------
# registered span names (docs/OBSERVABILITY.md keeps the same table —
# tests pin the two against each other so names cannot drift)
# ---------------------------------------------------------------------------

SPAN_FRONTDOOR_ROUTE = "frontdoor.route"    # FrontDoor partition+dispatch
SPAN_FRONTDOOR_RELAY = "frontdoor.relay"    # native gate slow-path handoff
SPAN_CLIENT_SUBMIT = "client.submit"        # FleetClient.verify_batch, whole
SPAN_ROUTER_ATTEMPT = "router.attempt"      # one wire attempt on one worker
SPAN_ROUTER_HEDGE = "router.hedge"          # duplicate attempt on a peer
SPAN_ROUTER_BACKOFF = "router.backoff"      # sleep between retry rounds
SPAN_ROUTER_FALLBACK = "router.fallback"    # terminal CPU-oracle verify
SPAN_WORKER_DEQUEUE = "worker.dequeue"      # frame read -> batcher admit
SPAN_BATCHER_FILL = "batcher.fill"          # batcher admit -> flush start
SPAN_BATCHER_FLUSH = "batcher.flush"        # sync verify_batch call
SPAN_BATCHER_DISPATCH = "batcher.dispatch"  # async dispatch (prep+H2D)
SPAN_BATCHER_COLLECT = "batcher.collect"    # async device drain
SPAN_KEYPLANE_SWAP = "keyplane.swap"        # key-table build + hot swap
SPAN_NATIVE_DRAIN = "serve.native.drain"    # ring drain -> batcher submit
SPAN_NATIVE_POST = "serve.native.post"      # verdicts -> native writers
SPAN_SHM_ATTACH = "serve.shm.attach"        # shm region map + negotiate
SPAN_OIDC_VALIDATE = "oidc.claims_validate"  # raw-batch claims rules
SPAN_ENGINE_PREFIX = "dispatch."            # dispatch.<family>.<detail>

SPAN_NAMES = frozenset({
    SPAN_CLIENT_SUBMIT, SPAN_ROUTER_ATTEMPT, SPAN_ROUTER_HEDGE,
    SPAN_ROUTER_BACKOFF, SPAN_ROUTER_FALLBACK, SPAN_WORKER_DEQUEUE,
    SPAN_BATCHER_FILL, SPAN_BATCHER_FLUSH, SPAN_BATCHER_DISPATCH,
    SPAN_BATCHER_COLLECT, SPAN_KEYPLANE_SWAP, SPAN_NATIVE_DRAIN,
    SPAN_NATIVE_POST, SPAN_SHM_ATTACH, SPAN_OIDC_VALIDATE,
    SPAN_FRONTDOOR_ROUTE, SPAN_FRONTDOOR_RELAY,
})

# ---------------------------------------------------------------------------
# histogram buckets: log-scale, fixed at import time
# ---------------------------------------------------------------------------

# Geometric bucket edges covering 100 ns .. 1e7 (seconds for spans,
# dimensionless for batch sizes / ratios), 4 buckets per octave →
# ≤ ~9% quantile error at the geometric midpoint. ~190 edges, shared
# (module-level) by every histogram — per-series memory is one int
# array plus the reservoir.
_HIST_LO = 1e-7
_HIST_HI = 1e7
_PER_OCTAVE = 4


def _make_bounds() -> Tuple[float, ...]:
    bounds: List[float] = []
    step = 2.0 ** (1.0 / _PER_OCTAVE)
    v = _HIST_LO
    while v < _HIST_HI:
        bounds.append(v)
        v *= step
    bounds.append(_HIST_HI)
    return tuple(bounds)


BUCKET_BOUNDS: Tuple[float, ...] = _make_bounds()
_N_BUCKETS = len(BUCKET_BOUNDS) + 1          # +1 overflow bucket

# Raw samples kept per series before going bucket-only. Small counts
# (most tests, cold workers) get EXACT quantiles; past the cap the
# series stays O(buckets) no matter how many observations arrive.
RESERVOIR_CAP = 256

# Bounded trace storage: span records and completed-request timelines.
MAX_TRACE_SPANS = 4096
MAX_FLIGHT_ENTRIES = 256
# Bounded decision ring: sampled verdict records (cap_tpu.obs.decision).
MAX_DECISION_ENTRIES = 256


class Histogram:
    """Fixed-bucket log-scale histogram + exact count/sum/min/max.

    NOT thread-safe on its own — the owning Recorder's lock guards it.
    """

    __slots__ = ("counts", "count", "total", "vmin", "vmax", "raw")

    def __init__(self) -> None:
        self.counts = [0] * _N_BUCKETS
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.raw: Optional[List[float]] = []   # None once bucket-only

    def add(self, value: float) -> None:
        self.counts[bisect_left(BUCKET_BOUNDS, value)] += 1
        self.count += 1
        self.total += value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        if self.raw is not None:
            if len(self.raw) < RESERVOIR_CAP:
                self.raw.append(value)
            else:
                self.raw = None                # bucket-only from now on

    def add_many(self, value: float, k: int) -> None:
        """``k`` observations of the same value in one bucket add
        (``sum += value * k`` — the exact arithmetic the native
        telemetry plane replicates, so merged states stay
        bit-identical). The per-tenant latency fold uses this: one add
        per (chunk, tenant), never per token."""
        if k <= 0:
            return
        self.counts[bisect_left(BUCKET_BOUNDS, value)] += k
        self.count += k
        self.total += value * k
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value
        if self.raw is not None:
            if len(self.raw) + k <= RESERVOIR_CAP:
                self.raw.extend([value] * k)
            else:
                self.raw = None                # bucket-only from now on

    def quantile(self, q: float) -> float:
        """Exact while the reservoir holds every sample; bucket
        geometric-midpoint interpolation beyond it."""
        if self.count == 0:
            return 0.0
        if self.raw is not None and len(self.raw) == self.count:
            vals = sorted(self.raw)
            idx = min(len(vals) - 1,
                      max(0, int(round(q * (len(vals) - 1)))))
            return vals[idx]
        rank = q * (self.count - 1)
        seen = 0
        for i, c in enumerate(self.counts):
            if c == 0:
                continue
            seen += c
            if seen > rank:
                lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                hi = (BUCKET_BOUNDS[i] if i < len(BUCKET_BOUNDS)
                      else self.vmax)
                mid = ((lo * hi) ** 0.5 if lo > 0 else hi / 2.0)
                return min(max(mid, self.vmin), self.vmax)
        return self.vmax

    def state(self) -> Dict[str, Any]:
        """Mergeable snapshot: sparse bucket counts + exact moments."""
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin if self.count else 0.0,
            "max": self.vmax if self.count else 0.0,
            "buckets": {str(i): c for i, c in enumerate(self.counts)
                        if c},
        }

    @classmethod
    def from_state(cls, state: Dict[str, Any]) -> "Histogram":
        h = cls()
        h.raw = None                           # snapshots are bucket-only
        h.count = int(state.get("count", 0))
        h.total = float(state.get("sum", 0.0))
        if h.count:
            h.vmin = float(state.get("min", 0.0))
            h.vmax = float(state.get("max", 0.0))
        for i, c in (state.get("buckets") or {}).items():
            h.counts[int(i)] += int(c)
        return h


# ---------------------------------------------------------------------------
# name hygiene (redaction enforcement at the write boundary)
# ---------------------------------------------------------------------------

MAX_NAME_LEN = 120


def check_name(name: str) -> str:
    """Reject metric/span names that could smuggle payload material:
    over-long names, embedded whitespace/newlines, anything starting
    like a JWS segment (``eyJ`` = base64url('{"')), or a raw ISSUER
    string (URL-shaped — ``://``; tenants are recorded ONLY as
    sha256(iss)[:12] hashes, docs/OBSERVABILITY.md §Tenant
    attribution). Applied on FIRST use of a name (dict miss), so the
    hot path stays one dict hit."""
    if (len(name) > MAX_NAME_LEN or "eyJ" in name or "://" in name
            or any(ch.isspace() for ch in name)):
        raise ValueError(
            f"metric name rejected by redaction rules (len="
            f"{len(name)}): names must be short registered "
            f"identifiers, never payload material")
    return name


def scrub_note(note: Optional[str]) -> Optional[str]:
    """Span notes are free-text-ish (endpoints, family names) — bound
    the length and drop anything token-shaped or issuer-shaped (raw
    issuer URLs are tenant PII — only their hashes may be recorded)
    rather than record it."""
    if note is None:
        return None
    if "eyJ" in note or "://" in note or len(note) > MAX_NAME_LEN:
        return "[redacted]"
    return note


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------


class Recorder:
    """Thread-safe counters + gauges + bounded histograms + traces."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._series: Dict[str, Histogram] = {}
        self._trace_spans: deque = deque(maxlen=MAX_TRACE_SPANS)
        self._flight: deque = deque(maxlen=MAX_FLIGHT_ENTRIES)
        self._decisions: deque = deque(maxlen=MAX_DECISION_ENTRIES)

    # -- write side -------------------------------------------------------

    def count(self, name: str, n: int = 1) -> int:
        """Increment and return the new value (the return value lets
        deterministic samplers key off the count without re-reading
        the whole counter map)."""
        with self._lock:
            if name in self._counters:
                self._counters[name] += n
            else:
                self._counters[check_name(name)] = n
            return self._counters[name]

    def count_many(self, increments: Dict[str, int]) -> Dict[str, int]:
        """Apply several counter increments under ONE lock acquisition;
        returns the post-increment value per name (same contract as
        :meth:`count`, batched — the decision hot path uses this so a
        drained chunk costs one lock round, not one per counter)."""
        out: Dict[str, int] = {}
        with self._lock:
            counters = self._counters
            for name, n in increments.items():
                if name in counters:
                    counters[name] += n
                else:
                    counters[check_name(name)] = n
                out[name] = counters[name]
        return out

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            if name not in self._gauges:
                check_name(name)
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._series.get(name)
            if h is None:
                h = self._series[check_name(name)] = Histogram()
            h.add(float(value))

    def observe_many(self, name: str, value: float, k: int) -> None:
        """``k`` observations of one value under one lock round (see
        :meth:`Histogram.add_many`)."""
        if k <= 0:
            return
        with self._lock:
            h = self._series.get(name)
            if h is None:
                h = self._series[check_name(name)] = Histogram()
            h.add_many(float(value), k)

    @contextmanager
    def span(self, name: str, note: Optional[str] = None) -> Iterator[None]:
        """Time a block; the duration lands in the ``name`` series (s).
        When a trace context is active, a span record is attached to
        the trace(s) as well."""
        traces = _trace_ctx.get()
        t0_wall = time.time()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - t0
            self.observe(name, dur)
            if traces is not None:
                self.trace_span(traces, name, t0_wall, dur, note=note)

    def trace_span(self, trace: Union[str, Sequence[str]], name: str,
                   t0: float, dur: float,
                   note: Optional[str] = None) -> None:
        """Record a span explicitly (worker threads where the context
        var does not flow). ``trace`` may be one id or several (a
        coalesced batch fans its device spans out to every member)."""
        if not trace:
            return
        ids = (trace,) if isinstance(trace, str) else tuple(trace)
        note = scrub_note(note)
        with self._lock:
            if name not in self._series and name not in SPAN_NAMES:
                check_name(name)
            for tid in ids:
                rec = {"trace": tid, "name": name, "t0": t0, "dur": dur}
                if note:
                    rec["note"] = note
                self._trace_spans.append(rec)

    def flight(self, trace: str, total_s: float,
               note: Optional[str] = None) -> None:
        """Close out a traced request: snapshot its span records into
        the flight ring (bounded; ``flight_slowest`` reads it back)."""
        note = scrub_note(note)
        with self._lock:
            spans = [dict(s) for s in self._trace_spans
                     if s["trace"] == trace]
            entry: Dict[str, Any] = {"trace": trace, "t_done": time.time(),
                                     "total_s": total_s, "spans": spans}
            if note:
                entry["note"] = note
            self._flight.append(entry)

    def decision(self, entry: Dict[str, Any]) -> None:
        """Append one sampled decision record (bounded ring; entries
        are built and redaction-checked by cap_tpu.obs.decision)."""
        with self._lock:
            self._decisions.append(dict(entry))

    def decision_many(self, entries: Sequence[Dict[str, Any]]) -> None:
        """Append several decision records under ONE lock round (the
        native plane's exemplar pump hands over a drained batch)."""
        with self._lock:
            self._decisions.extend(dict(e) for e in entries)

    # -- read side --------------------------------------------------------

    def decisions(self) -> List[dict]:
        with self._lock:
            return [dict(d) for d in self._decisions]

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def series(self, name: str) -> List[float]:
        """Raw reservoir samples (complete below RESERVOIR_CAP
        observations; empty once a hot series goes bucket-only)."""
        with self._lock:
            h = self._series.get(name)
            return list(h.raw) if h is not None and h.raw else []

    def trace_spans(self, trace: Optional[str] = None) -> List[dict]:
        with self._lock:
            return [dict(s) for s in self._trace_spans
                    if trace is None or s["trace"] == trace]

    def flight_entries(self) -> List[dict]:
        with self._lock:
            return [dict(e) for e in self._flight]

    def flight_slowest(self, n: int = 32) -> List[dict]:
        """The n slowest request timelines still in the ring."""
        return sorted(self.flight_entries(),
                      key=lambda e: e["total_s"], reverse=True)[:n]

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-series {count, total, mean, p50, p95, p99, max}."""
        with self._lock:
            items = list(self._series.items())
            stats = [(k, h.count, h.total, h.quantile(0.50),
                      h.quantile(0.95), h.quantile(0.99), h.vmax)
                     for k, h in items if h.count]
        return {name: {"count": float(n), "total": total,
                       "mean": total / n, "p50": p50, "p95": p95,
                       "p99": p99, "max": vmax}
                for name, n, total, p50, p95, p99, vmax in stats}

    def snapshot(self) -> Dict[str, Any]:
        """Mergeable JSON-able state: counters, gauges, histogram
        bucket counts. ``merge_snapshots`` adds these exactly."""
        with self._lock:
            return {
                "v": 1,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "series": {k: h.state()
                           for k, h in self._series.items() if h.count},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._series.clear()
            self._trace_spans.clear()
            self._flight.clear()
            self._decisions.clear()


# ---------------------------------------------------------------------------
# snapshot merge + summary (the fleet aggregation path)
# ---------------------------------------------------------------------------


def merge_snapshots(snaps: Sequence[Optional[Dict[str, Any]]]
                    ) -> Dict[str, Any]:
    """Exact aggregate of recorder snapshots: counters and histogram
    buckets ADD; gauges add too (fleet gauges are occupancy-like —
    queued tokens, open breakers — where the fleet total is the sum).
    Quantiles of the merged histograms equal those of one recorder
    that had observed every sample (within bucket resolution)."""
    counters: Dict[str, int] = {}
    gauges: Dict[str, float] = {}
    series: Dict[str, Histogram] = {}
    for snap in snaps:
        if not snap:
            continue
        for k, v in (snap.get("counters") or {}).items():
            counters[k] = counters.get(k, 0) + int(v)
        for k, v in (snap.get("gauges") or {}).items():
            gauges[k] = gauges.get(k, 0.0) + float(v)
        for k, st in (snap.get("series") or {}).items():
            h = Histogram.from_state(st)
            if k in series:
                prev = series[k]
                for i, c in enumerate(h.counts):
                    prev.counts[i] += c
                prev.count += h.count
                prev.total += h.total
                prev.vmin = min(prev.vmin, h.vmin)
                prev.vmax = max(prev.vmax, h.vmax)
            else:
                series[k] = h
    return {"v": 1, "counters": counters, "gauges": gauges,
            "series": {k: h.state() for k, h in series.items()}}


def summarize_snapshot(snap: Dict[str, Any]
                       ) -> Dict[str, Dict[str, float]]:
    """summary()-shaped quantiles computed from a (merged) snapshot."""
    out: Dict[str, Dict[str, float]] = {}
    for name, st in (snap.get("series") or {}).items():
        h = Histogram.from_state(st)
        if not h.count:
            continue
        out[name] = {"count": float(h.count), "total": h.total,
                     "mean": h.total / h.count,
                     "p50": h.quantile(0.50), "p95": h.quantile(0.95),
                     "p99": h.quantile(0.99), "max": h.vmax}
    return out


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------

_trace_ctx: "contextvars.ContextVar[Optional[Union[str, Tuple[str, ...]]]]" \
    = contextvars.ContextVar("cap_tpu_trace", default=None)

TRACE_HEX = "0123456789abcdef"


def new_trace_id() -> str:
    """16 lowercase hex chars (64 random bits)."""
    return os.urandom(8).hex()


def valid_trace_id(tid: str) -> bool:
    return (0 < len(tid) <= 64 and len(tid) % 2 == 0
            and all(c in TRACE_HEX for c in tid))


def current_trace() -> Optional[str]:
    """The active trace id (first of the set, if a batch scope)."""
    t = _trace_ctx.get()
    if t is None or isinstance(t, str):
        return t
    return t[0] if t else None


def current_traces() -> Tuple[str, ...]:
    t = _trace_ctx.get()
    if t is None:
        return ()
    return (t,) if isinstance(t, str) else tuple(t)


@contextmanager
def trace(trace_id: Optional[str] = None) -> Iterator[str]:
    """Scoped trace context: spans inside attach to this id."""
    tid = trace_id if trace_id is not None else new_trace_id()
    token = _trace_ctx.set(tid)
    try:
        yield tid
    finally:
        _trace_ctx.reset(token)


@contextmanager
def trace_scope(trace_ids: Sequence[str]) -> Iterator[None]:
    """Batch scope: spans inside fan out to EVERY id (a coalesced
    device batch serves many traced requests at once)."""
    token = _trace_ctx.set(tuple(trace_ids) if trace_ids else None)
    try:
        yield
    finally:
        _trace_ctx.reset(token)


# -- module-level switchboard ---------------------------------------------

_recorder: Optional[Recorder] = None


def enable(recorder: Optional[Recorder] = None) -> Recorder:
    """Turn telemetry on (idempotent); returns the active recorder."""
    global _recorder
    if recorder is not None:
        _recorder = recorder
    elif _recorder is None:
        _recorder = Recorder()
    return _recorder


def disable() -> None:
    global _recorder
    _recorder = None


def active() -> Optional[Recorder]:
    """The live recorder, or None when telemetry is off."""
    return _recorder


@contextmanager
def recording(recorder: Optional[Recorder] = None) -> Iterator[Recorder]:
    """Scoped telemetry: enable for the block, restore the prior state."""
    global _recorder
    prev = _recorder
    rec = recorder if recorder is not None else Recorder()
    _recorder = rec
    try:
        yield rec
    finally:
        _recorder = prev


def count(name: str, n: int = 1) -> Optional[int]:
    rec = _recorder
    if rec is not None:
        return rec.count(name, n)
    return None


def gauge(name: str, value: float) -> None:
    rec = _recorder
    if rec is not None:
        rec.gauge(name, value)


def observe(name: str, value: float) -> None:
    rec = _recorder
    if rec is not None:
        rec.observe(name, value)


def trace_span(trace_ids: Union[str, Sequence[str]], name: str,
               t0: float, dur: float, note: Optional[str] = None) -> None:
    rec = _recorder
    if rec is not None:
        rec.trace_span(trace_ids, name, t0, dur, note=note)


def flight(trace_id: str, total_s: float,
           note: Optional[str] = None) -> None:
    rec = _recorder
    if rec is not None:
        rec.flight(trace_id, total_s, note=note)


@contextmanager
def span(name: str, note: Optional[str] = None) -> Iterator[None]:
    rec = _recorder
    if rec is None:
        yield
        return
    with rec.span(name, note=note):
        yield
