"""Instrumentation: per-batch timings, counters, latency quantiles.

The reference has no tracing or metrics of any kind — its only
"observability" is the ``const op`` error-prefix convention
(/root/reference/oidc/provider.go:58) and redaction of secrets
(SURVEY.md §5). For a batched TPU verify engine that trades latency for
throughput, real instrumentation is required: this module provides a
process-local :class:`Recorder` with named counters and duration
histograms, ``span()`` context managers around pipeline stages (host
prep, kid gather, per-family device dispatch), and p50/p95/p99
summaries.

Redaction discipline carries over from the reference
(/root/reference/oidc/config.go:20-31): recorders store ONLY metric
names and numbers — never tokens, keys, claims, or any request payload.

Telemetry is off by default (zero overhead beyond one attribute check
on the hot path); enable with ``telemetry.enable()`` or scoped via
``telemetry.recording()``.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, Iterator, List, Optional


class Recorder:
    """Thread-safe counters + duration/value histograms."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._series: Dict[str, List[float]] = {}

    # -- write side -------------------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._series.setdefault(name, []).append(float(value))

    @contextmanager
    def span(self, name: str) -> Iterator[None]:
        """Time a block; the duration lands in the ``name`` series (s)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # -- read side --------------------------------------------------------

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    def series(self, name: str) -> List[float]:
        with self._lock:
            return list(self._series.get(name, []))

    def summary(self) -> Dict[str, Dict[str, float]]:
        """Per-series {count, total, mean, p50, p95, p99, max}."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            items = [(k, list(v)) for k, v in self._series.items()]
        for name, vals in items:
            vals.sort()
            n = len(vals)
            if n == 0:
                continue
            total = sum(vals)
            out[name] = {
                "count": float(n),
                "total": total,
                "mean": total / n,
                "p50": _quantile(vals, 0.50),
                "p95": _quantile(vals, 0.95),
                "p99": _quantile(vals, 0.99),
                "max": vals[-1],
            }
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._series.clear()


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank quantile on an already-sorted list."""
    n = len(sorted_vals)
    idx = min(n - 1, max(0, int(round(q * (n - 1)))))
    return sorted_vals[idx]


# -- module-level switchboard ---------------------------------------------

_recorder: Optional[Recorder] = None


def enable(recorder: Optional[Recorder] = None) -> Recorder:
    """Turn telemetry on (idempotent); returns the active recorder."""
    global _recorder
    if recorder is not None:
        _recorder = recorder
    elif _recorder is None:
        _recorder = Recorder()
    return _recorder


def disable() -> None:
    global _recorder
    _recorder = None


def active() -> Optional[Recorder]:
    """The live recorder, or None when telemetry is off."""
    return _recorder


@contextmanager
def recording(recorder: Optional[Recorder] = None) -> Iterator[Recorder]:
    """Scoped telemetry: enable for the block, restore the prior state."""
    global _recorder
    prev = _recorder
    rec = recorder if recorder is not None else Recorder()
    _recorder = rec
    try:
        yield rec
    finally:
        _recorder = prev


def count(name: str, n: int = 1) -> None:
    rec = _recorder
    if rec is not None:
        rec.count(name, n)


def observe(name: str, value: float) -> None:
    rec = _recorder
    if rec is not None:
        rec.observe(name, value)


@contextmanager
def span(name: str) -> Iterator[None]:
    rec = _recorder
    if rec is None:
        yield
        return
    with rec.span(name):
        yield
