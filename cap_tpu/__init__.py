"""cap_tpu — a TPU-native authentication framework.

cap_tpu re-creates the capability surface of the ``cap`` auth library
(JWT signature verification + claims validation, and OIDC relying-party
flows — see /root/reference, a pure-Go client library) as a TPU-first
framework:

- ``cap_tpu.jwt``  — JWT verification: ``KeySet`` implementations,
  ``Validator`` claims engine, and the batched TPU execution backend
  (``TPUBatchKeySet.verify_batch``) whose RSA modular exponentiation and
  elliptic-curve scalar multiplication run as JAX/Pallas kernels.
- ``cap_tpu.oidc`` — OIDC relying-party: discovery, auth-URL generation,
  code/PKCE/implicit flows, token exchange, id_token verification,
  UserInfo, HTTP callback handlers, and an in-process fake IdP for tests.
- ``cap_tpu.tpu``  — the verify engine: limb-vector bignum, Montgomery
  modexp, EC kernels, batching/bucketing runtime, mesh sharding.
- ``cap_tpu.runtime`` — native C++ batch tokenizer (JOSE split, base64url,
  SHA-2) with a pure-Python fallback.

The pure-CPU path (backed by the ``cryptography`` package) is the default
and the correctness oracle; the TPU path is gated behind the same KeySet
interface, mirroring the reference's seam at jwt/keyset.go:27-32.
"""

__version__ = "0.1.0"
