"""String-slice helpers (reference: oidc/internal/strutils/strutils.go:6-35)."""

from __future__ import annotations

from typing import Iterable, List


def str_list_contains(haystack: Iterable[str], needle: str) -> bool:
    return needle in haystack


def remove_duplicates_stable(items: Iterable[str], case_sensitive: bool) -> List[str]:
    """De-duplicate, trim whitespace, and drop empties, preserving order."""
    seen = set()
    out: List[str] = []
    for item in items:
        key = item.strip()
        if not case_sensitive:
            key = key.lower()
        if not key or key in seen:
            continue
        seen.add(key)
        out.append(item.strip())
    return out
