"""Pooled HTTP helpers with optional CA pinning.

The reference reuses pooled cleanhttp transports with custom RootCAs
for discovery/token/JWKS/UserInfo traffic (jwt/keyset.go:204-225,
oidc/provider.go:566-618). The Python analog here: a process-wide
keep-alive connection pool keyed by (scheme, host, port, SSL context),
so one TLS handshake serves a Provider's whole flow — discovery, token
exchange, JWKS fetches, and UserInfo ride the same socket when the
server allows keep-alive.

Connection reuse is observable via telemetry counters
(``http.conn_new`` / ``http.conn_reused``).
"""

from __future__ import annotations

import http.client
import json
import socket
import ssl
import threading
from typing import Any, Dict, Optional, Tuple
from urllib.parse import urlparse, urljoin

from .. import telemetry
from ..errors import InvalidCACertError


def ssl_context_for_ca(ca_pem: Optional[str]) -> Optional[ssl.SSLContext]:
    """Build an SSLContext trusting only ``ca_pem`` (None → system default)."""
    if not ca_pem:
        return None
    ctx = ssl.create_default_context()
    try:
        ctx.load_verify_locations(cadata=ca_pem)
    except ssl.SSLError as e:
        raise InvalidCACertError(f"could not load CA PEM: {e}") from e
    return ctx


class ConnectionPool:
    """Keep-alive HTTP(S) connection cache.

    Mirrors the pooled-transport role of the reference's cleanhttp
    clients: idle connections are parked per (scheme, host, port, SSL
    context) and reused for subsequent requests. A request on a reused
    connection that fails mid-flight (stale keep-alive the server
    already closed) is retried ONCE on a fresh connection — idempotent
    methods only; failures on fresh connections propagate as
    ConnectionError/OSError.
    """

    def __init__(self, max_idle_per_key: int = 4,
                 idle_ttl: float = 60.0):
        self._idle: Dict[tuple, list] = {}   # key -> [(conn, parked_at)]
        self._lock = threading.Lock()
        self._max_idle = max_idle_per_key
        self._idle_ttl = idle_ttl

    def _checkout(self, key):
        import time

        now = time.monotonic()
        stale = []
        try:
            with self._lock:
                conns = self._idle.get(key)
                while conns:
                    conn, parked = conns.pop()
                    if now - parked <= self._idle_ttl:
                        return conn, True
                    stale.append(conn)
            return None, False
        finally:
            for c in stale:
                c.close()

    def _checkin(self, key, conn) -> None:
        import time

        now = time.monotonic()
        evict = []
        with self._lock:
            # lazy sweep: expire idle sockets everywhere so dead
            # Providers' contexts don't pin fds for the process life
            for k in list(self._idle):
                kept = [(c, t) for (c, t) in self._idle[k]
                        if now - t <= self._idle_ttl]
                evict.extend(c for (c, t) in self._idle[k]
                             if now - t > self._idle_ttl)
                if kept:
                    self._idle[k] = kept
                else:
                    del self._idle[k]
            conns = self._idle.setdefault(key, [])
            if len(conns) < self._max_idle:
                conns.append((conn, now))
                conn = None
        for c in evict:
            c.close()
        if conn is not None:
            conn.close()

    def close(self) -> None:
        with self._lock:
            for conns in self._idle.values():
                for c, _ in conns:
                    c.close()
            self._idle.clear()

    def request(self, method: str, url: str,
                body: Optional[bytes] = None,
                headers: Optional[Dict[str, str]] = None,
                ctx: Optional[ssl.SSLContext] = None,
                timeout: float = 30.0,
                max_redirects: int = 5) -> Tuple[int, bytes,
                                                 Dict[str, str]]:
        """One HTTP exchange → (status, body, lowercased headers).

        4xx/5xx are returned, not raised (callers branch on status);
        transport failures raise OSError subclasses. GET redirects are
        followed up to ``max_redirects`` (the reference's http.Client
        default behavior).
        """
        origin = urlparse(url)
        for _ in range(max_redirects + 1):
            status, data, hdrs = self._one(method, url, body, headers,
                                           ctx, timeout)
            loc = hdrs.get("location")
            if loc and status in (301, 302, 303, 307, 308):
                url = urljoin(url, loc)
                target = urlparse(url)
                downgrade = origin.scheme == "https" and \
                    target.scheme != "https"
                if headers and (target.hostname != origin.hostname
                                or downgrade):
                    # Credentials must not follow a redirect off the
                    # original host OR onto cleartext http (Go's
                    # http.Client strips them the same way): a
                    # compromised IdP response would otherwise
                    # exfiltrate Bearer/Basic credentials.
                    headers = {k: v for k, v in headers.items()
                               if k.lower() not in ("authorization",
                                                    "cookie")}
                if status in (301, 302, 303) and method != "GET":
                    # urllib/browser semantics: re-issue as GET
                    method, body = "GET", None
                continue  # 307/308 keep method + body
            return status, data, hdrs
        raise ConnectionError(f"{method} {url}: too many redirects")

    def _one(self, method, url, body, headers, ctx, timeout):
        u = urlparse(url)
        if u.scheme not in ("http", "https"):
            raise ConnectionError(f"unsupported URL scheme {u.scheme!r}")
        port = u.port or (443 if u.scheme == "https" else 80)
        # Key on the SSLContext OBJECT (hashable; the pool entry keeps
        # it alive): an id()-based key could alias a dead Provider's
        # context with a newly-allocated one at the same address and
        # hand out a socket validated under the wrong CA.
        key = (u.scheme, u.hostname, port, ctx)
        path = u.path or "/"
        if u.query:
            path += "?" + u.query

        last_exc: Optional[Exception] = None
        for attempt in (0, 1):
            if attempt == 0:
                conn, reused = self._checkout(key)
            else:
                conn, reused = None, False  # retry always on a fresh conn
            if conn is None:
                try:
                    if u.scheme == "https":
                        conn = http.client.HTTPSConnection(
                            u.hostname, port, timeout=timeout,
                            context=ctx)
                    else:
                        conn = http.client.HTTPConnection(
                            u.hostname, port, timeout=timeout)
                except Exception as e:  # noqa: BLE001
                    raise ConnectionError(str(e)) from e
                reused = False
            else:
                # reused sockets keep their creator's timeout: apply
                # THIS caller's
                conn.timeout = timeout
                if getattr(conn, "sock", None) is not None:
                    conn.sock.settimeout(timeout)
            sent = False
            try:
                conn.request(method, path, body=body,
                             headers=headers or {})
                sent = True
                resp = conn.getresponse()
                data = resp.read()
            except (http.client.HTTPException, ConnectionError,
                    BrokenPipeError, socket.timeout, ssl.SSLError,
                    OSError) as e:
                conn.close()
                last_exc = e
                if reused and (not sent or method in ("GET", "HEAD")):
                    # Stale keep-alive → one fresh retry. Send-phase
                    # failures retry for ANY method (the server closed
                    # the parked socket before reading, so nothing was
                    # processed); after the request went out, only
                    # idempotent methods retry — replaying a completed
                    # POST (token exchange) could consume the one-shot
                    # auth code twice.
                    continue
                if isinstance(e, OSError):
                    raise
                raise ConnectionError(str(e)) from e
            telemetry.count("http.conn_reused" if reused
                            else "http.conn_new")
            if resp.will_close:
                conn.close()
            else:
                self._checkin(key, conn)
            return (resp.status, data,
                    {k.lower(): v for k, v in resp.getheaders()})
        raise ConnectionError(str(last_exc)) from last_exc


_POOL = ConnectionPool()


def default_pool() -> ConnectionPool:
    return _POOL


# Conditional-GET cache: url+context → (etag, body, headers) of the
# last 200 that carried an ETag. ``get(conditional=True)`` sends
# If-None-Match and transparently answers a 304 with the cached body,
# so a periodic keyplane refresh of an unchanged JWKS costs one
# header-only round trip instead of the document. Bounded (FIFO) —
# this is a freshness cache for a handful of polled endpoints, not a
# general HTTP cache (no Vary/Cache-Control semantics).
_COND_LOCK = threading.Lock()
_COND_CACHE: Dict[tuple, Tuple[str, bytes, Dict[str, str]]] = {}
_COND_CACHE_MAX = 64


def get(url: str, ctx: Optional[ssl.SSLContext] = None,
        headers: Optional[Dict[str, str]] = None,
        timeout: float = 30.0,
        conditional: bool = False) -> Tuple[int, bytes, Dict[str, str]]:
    """GET a URL; returns (status, body, lowercased headers).

    ``conditional=True``: honor ETag validators — a cached ETag for
    this (url, ctx) is sent as If-None-Match, and a 304 answer is
    returned as status 200 with the CACHED body (plus header
    ``x-cap-conditional: revalidated``), so callers branch on status
    exactly as for a plain fetch.
    """
    key = (url, ctx)
    cached = None
    if conditional:
        with _COND_LOCK:
            cached = _COND_CACHE.get(key)
        if cached is not None:
            headers = dict(headers or {})
            headers["If-None-Match"] = cached[0]
    status, body, hdrs = _POOL.request("GET", url, headers=headers,
                                       ctx=ctx, timeout=timeout)
    if not conditional:
        return status, body, hdrs
    if status == 304 and cached is not None:
        telemetry.count("http.etag_hits")
        out = dict(cached[2])
        out.update(hdrs)
        out["x-cap-conditional"] = "revalidated"
        return 200, cached[1], out
    if status == 200:
        etag = hdrs.get("etag")
        if etag:
            with _COND_LOCK:
                if key not in _COND_CACHE and \
                        len(_COND_CACHE) >= _COND_CACHE_MAX:
                    _COND_CACHE.pop(next(iter(_COND_CACHE)))
                _COND_CACHE[key] = (etag, body, hdrs)
    return status, body, hdrs


def get_json(url: str, ctx: Optional[ssl.SSLContext] = None,
             timeout: float = 30.0) -> Any:
    status, body, headers = get(url, ctx, timeout=timeout)
    if status != 200:
        raise RuntimeError(f"GET {url}: unexpected status {status}: {body[:200]!r}")
    content_type = headers.get("content-type", "")
    try:
        return json.loads(body)
    except ValueError as e:
        raise RuntimeError(
            f"GET {url}: expected JSON (content-type {content_type!r}): {e}"
        ) from e


def fetch_discovery(issuer: str,
                    ctx: Optional[ssl.SSLContext] = None) -> Dict[str, Any]:
    """Fetch {issuer}/.well-known/openid-configuration and enforce the
    issuer-equality check (single source of the discovery protocol for
    both the jwt discovery keyset and the oidc Provider)."""
    from ..errors import InvalidIssuerError

    well_known = issuer.rstrip("/") + "/.well-known/openid-configuration"
    status, body, _ = get(well_known, ctx)
    if status != 200:
        raise InvalidIssuerError(f"discovery request failed: status {status}")
    try:
        doc = json.loads(body)
    except ValueError as e:
        raise InvalidIssuerError(f"discovery document is not JSON: {e}") from e
    if not isinstance(doc, dict):
        raise InvalidIssuerError("discovery document is not a JSON object")
    got = doc.get("issuer")
    if got != issuer:
        raise InvalidIssuerError(
            f"oidc issuer did not match the issuer returned by provider, "
            f"expected {issuer!r} got {got!r}")
    return doc


def post_form(url: str, fields: Dict[str, str],
              ctx: Optional[ssl.SSLContext] = None,
              headers: Optional[Dict[str, str]] = None,
              timeout: float = 30.0) -> Tuple[int, bytes, Dict[str, str]]:
    """POST application/x-www-form-urlencoded fields."""
    from urllib.parse import urlencode

    data = urlencode(fields).encode("ascii")
    hdrs = {"Content-Type": "application/x-www-form-urlencoded"}
    hdrs.update(headers or {})
    return _POOL.request("POST", url, body=data, headers=hdrs, ctx=ctx,
                         timeout=timeout)
