"""Small HTTP helpers with optional CA pinning.

The reference builds pooled cleanhttp transports with custom RootCAs
(jwt/keyset.go:204-225, oidc/provider.go:566-618); the Python analog is a
shared ssl.SSLContext built from the provided CA PEM, used for every
request a keyset/provider makes.
"""

from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request
from typing import Any, Dict, Optional, Tuple

from ..errors import InvalidCACertError


def ssl_context_for_ca(ca_pem: Optional[str]) -> Optional[ssl.SSLContext]:
    """Build an SSLContext trusting only ``ca_pem`` (None → system default)."""
    if not ca_pem:
        return None
    ctx = ssl.create_default_context()
    try:
        ctx.load_verify_locations(cadata=ca_pem)
    except ssl.SSLError as e:
        raise InvalidCACertError(f"could not load CA PEM: {e}") from e
    return ctx


def get(url: str, ctx: Optional[ssl.SSLContext] = None,
        headers: Optional[Dict[str, str]] = None,
        timeout: float = 30.0) -> Tuple[int, bytes, Dict[str, str]]:
    """GET a URL; returns (status, body, lowercased headers)."""
    req = urllib.request.Request(url, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as resp:
            return (
                resp.status,
                resp.read(),
                {k.lower(): v for k, v in resp.headers.items()},
            )
    except urllib.error.HTTPError as e:
        return e.code, e.read(), {k.lower(): v for k, v in e.headers.items()}


def get_json(url: str, ctx: Optional[ssl.SSLContext] = None,
             timeout: float = 30.0) -> Any:
    status, body, headers = get(url, ctx, timeout=timeout)
    if status != 200:
        raise RuntimeError(f"GET {url}: unexpected status {status}: {body[:200]!r}")
    content_type = headers.get("content-type", "")
    try:
        return json.loads(body)
    except ValueError as e:
        raise RuntimeError(
            f"GET {url}: expected JSON (content-type {content_type!r}): {e}"
        ) from e


def fetch_discovery(issuer: str,
                    ctx: Optional[ssl.SSLContext] = None) -> Dict[str, Any]:
    """Fetch {issuer}/.well-known/openid-configuration and enforce the
    issuer-equality check (single source of the discovery protocol for
    both the jwt discovery keyset and the oidc Provider)."""
    from ..errors import InvalidIssuerError

    well_known = issuer.rstrip("/") + "/.well-known/openid-configuration"
    status, body, _ = get(well_known, ctx)
    if status != 200:
        raise InvalidIssuerError(f"discovery request failed: status {status}")
    try:
        doc = json.loads(body)
    except ValueError as e:
        raise InvalidIssuerError(f"discovery document is not JSON: {e}") from e
    if not isinstance(doc, dict):
        raise InvalidIssuerError("discovery document is not a JSON object")
    got = doc.get("issuer")
    if got != issuer:
        raise InvalidIssuerError(
            f"oidc issuer did not match the issuer returned by provider, "
            f"expected {issuer!r} got {got!r}")
    return doc


def post_form(url: str, fields: Dict[str, str],
              ctx: Optional[ssl.SSLContext] = None,
              headers: Optional[Dict[str, str]] = None,
              timeout: float = 30.0) -> Tuple[int, bytes, Dict[str, str]]:
    """POST application/x-www-form-urlencoded fields."""
    from urllib.parse import urlencode

    data = urlencode(fields).encode("ascii")
    hdrs = {"Content-Type": "application/x-www-form-urlencoded"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=data, headers=hdrs, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout, context=ctx) as resp:
            return (
                resp.status,
                resp.read(),
                {k.lower(): v for k, v in resp.headers.items()},
            )
    except urllib.error.HTTPError as e:
        return e.code, e.read(), {k.lower(): v for k, v in e.headers.items()}
