from .base62 import random_base62
from .strutils import str_list_contains, remove_duplicates_stable

__all__ = ["random_base62", "str_list_contains", "remove_duplicates_stable"]
