"""Redact-by-default secret string types.

The reference's observability stance is redaction (SURVEY.md §5):
ClientSecret, IDToken, AccessToken, RefreshToken all render as
``[REDACTED: …]`` from String()/MarshalJSON. The Python analog: a str
subclass whose repr/str/format/JSON renderings are redacted; the raw
value is reachable only via ``.reveal()``. Operations that would leak
through str-ness (concatenation, equality) operate on the real value —
matching the reference, where the underlying string type is usable.
"""

from __future__ import annotations


class RedactedString(str):
    """A string that redacts itself in every rendering channel."""

    redact_label = "secret"

    def reveal(self) -> str:
        """The actual secret value (deliberate unwrap, like the
        reference's explicit string conversions in examples)."""
        return str.__str__(self)

    def _redacted(self) -> str:
        return f"[REDACTED: {self.redact_label}]"

    def __repr__(self) -> str:  # noqa: D105
        return self._redacted()

    def __str__(self) -> str:  # noqa: D105
        return self._redacted()

    def __format__(self, spec: str) -> str:  # noqa: D105
        return self._redacted().__format__(spec)

    # json.dumps(default=...) can't intercept str subclasses, so redact
    # via a .__json__-style helper used by our own serializers; for
    # stdlib json the caller must reveal() deliberately.
    def to_json(self) -> str:
        return self._redacted()

    def __eq__(self, other) -> bool:  # noqa: D105
        if isinstance(other, RedactedString):
            return self.reveal() == other.reveal()
        if isinstance(other, str):
            return self.reveal() == other
        return NotImplemented

    def __hash__(self) -> int:  # noqa: D105
        return str.__hash__(self)

    def __bool__(self) -> bool:  # noqa: D105
        return len(self.reveal()) > 0
