"""Unbiased random base62 strings.

Equivalent of the reference's oidc/internal/base62 (base62.go:12-50):
rejection-sampled uniform characters (~5.95 bits/char) from a CSPRNG.
Python's ``secrets.choice`` already rejection-samples internally, so the
implementation is a straight comprehension over the charset.
"""

from __future__ import annotations

import secrets

CHARSET = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"


def random_base62(length: int) -> str:
    """Return a cryptographically random base62 string of ``length`` chars."""
    if length < 0:
        raise ValueError("length must be non-negative")
    return "".join(secrets.choice(CHARSET) for _ in range(length))
