"""Observability exposition surface: /metrics, /snapshot, /flight.

Every fleet worker (and anything else that wants one) can serve a tiny
HTTP endpoint exposing the process's telemetry:

- ``/metrics`` — Prometheus text format: counters (``_total``),
  gauges, and histogram series as summaries (p50/p95/p99 quantile
  labels + ``_sum``/``_count``), so any off-the-shelf scraper can
  consume the fleet;
- ``/snapshot`` — the MERGEABLE JSON snapshot
  (:meth:`cap_tpu.telemetry.Recorder.snapshot` plus live extras such
  as batcher depth): ``tools/capstat.py`` scrapes these and merges
  them exactly (bucket counts add) rather than averaging quantiles;
- ``/flight`` — the flight recorder: the N slowest recent TRACED
  request timelines, each a list of span records, from which a
  cross-process trace can be reassembled by joining on the 16-hex
  trace id (``capstat.py --trace``);
- ``/decisions`` — the sampled decision ring
  (:mod:`cap_tpu.obs.decision`): full verdict records with reason
  class, family, latency bucket, hashed kid;
- ``/tenants`` — this worker's per-tenant rollup (issuer HASH →
  tokens / accept / reject mix / vcache splits) plus the exact
  ``lookups == attributed + overflow`` accounting triple, over the
  same merged snapshot ``/snapshot`` serves (docs/OBSERVABILITY.md
  §Tenant attribution — raw issuers never appear here);
- ``/healthz`` — liveness.

Stalled-scraper hardening: every connection runs on its own daemon
handler thread with a SHORT socket timeout (``handler_timeout_s``,
default 5 s) — a scraper that connects and never sends a request, or
stops reading the response, times out and its thread exits instead of
accumulating forever. The worker's serve loop never shares a thread
with scrapes in the first place; the timeout bounds the obs server's
own resource growth under a misbehaving collector (chaos-tested).

Redaction discipline: everything served here comes from the telemetry
recorder, whose write boundary already rejects token-shaped names and
scrubs notes (:func:`cap_tpu.telemetry.check_name`); the server adds
no request-derived content of its own.

The server is stdlib-only (``http.server`` on a daemon thread), binds
127.0.0.1 by default, and costs nothing until scraped.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from .. import telemetry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "cap_" + _NAME_RE.sub("_", name)


def render_prometheus(snapshot: Dict[str, Any],
                      extra_gauges: Optional[Dict[str, float]] = None
                      ) -> str:
    """Prometheus text exposition of a telemetry snapshot.

    Counters → ``cap_<name>_total``; gauges (snapshot + extras) →
    ``cap_<name>``; histogram series → summary: quantile-labelled
    samples (computed from the log-scale buckets) plus _sum/_count.
    """
    lines = ["# TYPE cap_up gauge", "cap_up 1"]
    for name, v in sorted((snapshot.get("counters") or {}).items()):
        pn = _prom_name(name) + "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {v}")
    gauges = dict(snapshot.get("gauges") or {})
    gauges.update(extra_gauges or {})
    for name, v in sorted(gauges.items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {float(v):g}")
    summaries = telemetry.summarize_snapshot(snapshot)
    for name, s in sorted(summaries.items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} summary")
        for q in ("0.5", "0.95", "0.99"):
            key = "p" + str(int(float(q) * 100))
            lines.append(f'{pn}{{quantile="{q}"}} {s[key]:.9g}')
        lines.append(f"{pn}_sum {s['total']:.9g}")
        lines.append(f"{pn}_count {int(s['count'])}")
    return "\n".join(lines) + "\n"


class ObsServer:
    """Serve the process's telemetry over HTTP (daemon thread).

    extra: callable returning live numeric gauges to fold into every
    scrape (the worker passes batcher depth/inflight); flight_n: how
    many slowest timelines ``/flight`` returns.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 extra: Optional[Callable[[], Dict[str, float]]] = None,
                 snapshot_extra: Optional[Callable[[], Optional[
                     Dict[str, Any]]]] = None,
                 flight_n: int = 32, handler_timeout_s: float = 5.0):
        self._extra = extra
        # snapshot_extra: callable returning an ADDITIONAL mergeable
        # snapshot (or None) folded into /metrics and /snapshot via
        # merge_snapshots — the worker passes its native telemetry
        # plane, so natively-counted decisions and histograms scrape
        # exactly like recorder-side ones.
        self._snapshot_extra = snapshot_extra
        self._flight_n = flight_n
        obs = self

        class _Handler(BaseHTTPRequestHandler):
            # Socket timeout for the whole request/response exchange
            # (stdlib applies it in setup()): a scraper that stalls —
            # never sends the request line, or never drains the
            # response — raises in ITS handler thread and the thread
            # exits; other scrapes and the worker loop are unaffected.
            timeout = handler_timeout_s

            def log_message(self, *args):   # no stderr chatter
                pass

            def handle_timeout(self):       # noqa: N802 (stdlib API)
                self.close_connection = True

            def do_GET(self):               # noqa: N802 (stdlib API)
                try:
                    obs._respond(self)
                except (BrokenPipeError, ConnectionResetError,
                        TimeoutError, OSError):
                    self.close_connection = True

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="cap-tpu-obs")
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass

    # -- handlers ---------------------------------------------------------

    def _extras(self) -> Dict[str, float]:
        try:
            return dict(self._extra()) if self._extra is not None else {}
        except Exception:  # noqa: BLE001 - a scrape must never 500 on it
            return {}

    def _snapshot(self, rec) -> Dict[str, Any]:
        if rec is not None:
            # flush the occupancy plane's counter deltas + window
            # gauges BEFORE the snapshot is taken, so every scrape is
            # self-contained (r22; no-op until an engine dispatches)
            try:
                from ..obs import occupancy as _occupancy

                _occupancy.publish(rec)
            except Exception:  # noqa: BLE001 - never 500 a scrape
                pass
        snap = rec.snapshot() if rec is not None else {}
        if self._snapshot_extra is not None:
            try:
                extra_snap = self._snapshot_extra()
            except Exception:  # noqa: BLE001 - never 500 a scrape
                extra_snap = None
            if extra_snap:
                snap = telemetry.merge_snapshots([snap, extra_snap])
        return snap

    def _respond(self, h: BaseHTTPRequestHandler) -> None:
        rec = telemetry.active()
        path = h.path.split("?", 1)[0]
        if path == "/metrics":
            body = render_prometheus(self._snapshot(rec),
                                     self._extras()).encode()
            ctype = "text/plain; version=0.0.4"
        elif path == "/snapshot":
            body = json.dumps({
                "snapshot": self._snapshot(rec),
                "extra": self._extras(),
            }).encode()
            ctype = "application/json"
        elif path == "/flight":
            entries = (rec.flight_slowest(self._flight_n)
                       if rec is not None else [])
            body = json.dumps({"slowest": entries}).encode()
            ctype = "application/json"
        elif path == "/decisions":
            body = json.dumps({
                "decisions": rec.decisions() if rec is not None else [],
            }).encode()
            ctype = "application/json"
        elif path == "/tenants":
            from ..obs import decision as _decision

            counters = self._snapshot(rec).get("counters") or {}
            body = json.dumps({
                "tenants": _decision.tenant_totals(counters),
                "lookups": counters.get("tenant.lookups", 0),
                "attributed": counters.get("tenant.attributed", 0),
                "overflow": counters.get("tenant.overflow", 0),
            }).encode()
            ctype = "application/json"
        elif path == "/healthz":
            body = b'{"ok": true}'
            ctype = "application/json"
        else:
            h.send_response(404)
            h.end_headers()
            return
        h.send_response(200)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(body)))
        h.end_headers()
        h.wfile.write(body)
