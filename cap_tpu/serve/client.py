"""Python client for the verify worker (CVB1 protocol).

Mirrors the KeySet surface so a host app can swap a local
TPUBatchKeySet for a remote worker without code changes:
``verify_batch`` returns the same per-token claims-dict-or-Exception
list, with rejected tokens surfaced as RemoteVerifyError (the worker
sends only the error class + message — never token material).
"""

from __future__ import annotations

import json
import socket
from typing import Any, List, Optional, Sequence

from ..errors import CapError
from . import protocol


class RemoteVerifyError(CapError):
    """A token the worker rejected; message is the worker's error."""


class VerifyClient:
    """Blocking client; one socket, pipelined request/response frames."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 uds_path: Optional[str] = None, timeout: float = 30.0,
                 crc: bool = False):
        # crc=True: speak the checksummed frame pair (REQ_CRC/RESP_CRC)
        # so byte corruption anywhere on the path raises
        # FrameCorruptError instead of returning a wrong verdict — the
        # fleet router always sets this.
        self._crc = crc
        if uds_path is not None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            self._sock.settimeout(timeout)
            self._sock.connect(uds_path)
        else:
            self._sock = socket.create_connection((host, port),
                                                  timeout=timeout)
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # The client owns this socket's read side: buffered reader.
        self._reader = protocol.FrameReader(self._sock)

    def ping(self) -> bool:
        protocol.send_ping(self._sock)
        ftype, _ = self._reader.recv_frame()
        return ftype == protocol.T_PONG

    def stats(self) -> dict:
        """The worker's STATS snapshot (queue depth, inflight,
        counters, per-series p50/p95/p99)."""
        protocol.send_stats_request(self._sock)
        ftype, entries = self._reader.recv_frame()
        if ftype != protocol.T_STATS_RESP or len(entries) != 1:
            raise protocol.ProtocolError(
                f"expected stats response, got type {ftype}")
        return json.loads(entries[0][1].decode())

    def verify_batch(self, tokens: Sequence[str]) -> List[Any]:
        """Claims dict per verified token; RemoteVerifyError per reject."""
        if not tokens:
            return []
        protocol.send_request(self._sock, tokens, crc=self._crc)
        return self._read_response(len(tokens))

    def verify_stream(self, batches, depth: int = 4):
        """Pipelined requests: up to ``depth`` frames in flight.

        Yields each batch's results in request order (CVB1 correlates
        by order). The worker reads eagerly, so while batch k verifies
        on the device, batches k+1.. are already crossing the wire and
        queueing in its batcher — the serve-path analog of
        ``TPUBatchKeySet.verify_stream`` (VERDICT r3 #7). A sender
        thread writes frames so a full send buffer can never deadlock
        against the unread responses.

        Leaving the stream early (break / exception) POISONS the
        client: in-flight responses would otherwise be misattributed
        to later requests (order is the only correlation), so the
        socket is closed and any further call raises.
        """
        import queue
        import threading

        sent: "queue.Queue" = queue.Queue()
        slots = threading.Semaphore(depth)
        stop = threading.Event()
        send_err: List[BaseException] = []

        def sender() -> None:
            try:
                for toks in batches:
                    toks = list(toks)
                    while not slots.acquire(timeout=0.25):
                        if stop.is_set():
                            return
                    if stop.is_set():
                        return
                    if toks:
                        protocol.send_request(self._sock, toks,
                                              crc=self._crc)
                    sent.put(len(toks))
            except BaseException as e:  # noqa: BLE001 - re-raised below
                send_err.append(e)
            finally:
                sent.put(None)

        t = threading.Thread(target=sender, daemon=True,
                             name="cap-tpu-client-send")
        t.start()
        clean = False
        try:
            while True:
                n = sent.get()
                if n is None:
                    if send_err:
                        raise send_err[0]
                    clean = True
                    return
                out = self._read_response(n) if n else []
                slots.release()
                yield out
        finally:
            stop.set()
            if not clean:
                # abandoned or failed mid-stream: unread responses are
                # on the wire — the connection cannot be reused
                self.close()

    def _read_response(self, n_tokens: int) -> List[Any]:
        ftype, entries = self._reader.recv_frame()
        # In crc mode a plain (unchecksummed) response is a protocol
        # violation — integrity must not be silently downgradable.
        want = (protocol.T_VERIFY_RESP_CRC if self._crc
                else protocol.T_VERIFY_RESP)
        if ftype != want:
            raise protocol.ProtocolError(f"expected response type "
                                         f"{want}, got {ftype}")
        if len(entries) != n_tokens:
            raise protocol.ProtocolError(
                f"response count {len(entries)} != request {n_tokens}")
        out: List[Any] = []
        for status, payload in entries:
            if status == 0:
                out.append(json.loads(payload.decode()))
            else:
                out.append(RemoteVerifyError(payload.decode()))
        return out

    def verify_signature(self, token: str) -> Any:
        """Single-token convenience; raises on rejection (KeySet shape)."""
        res = self.verify_batch([token])[0]
        if isinstance(res, Exception):
            raise res
        return res

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
