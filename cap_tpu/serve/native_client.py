"""ctypes wrapper over the native (C++) verify-worker client.

``NativeVerifyClient`` mirrors ``VerifyClient`` but rides
libcapclient.so — the same shim a C/C++/cgo host application links.
Build with ``make native``; falls back with ImportError if unbuilt.
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import Any, List, Optional, Sequence

from .client import RemoteVerifyError

_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "native",
                   "libcapclient.so")


def _load():
    if not os.path.exists(_SO):
        from .._build import build_native
        build_native()
    if not os.path.exists(_SO):
        raise ImportError(f"{_SO} not built (run: make native)")
    lib = ctypes.CDLL(_SO)
    lib.cap_client_connect.restype = ctypes.c_void_p
    lib.cap_client_connect.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.cap_client_connect_uds.restype = ctypes.c_void_p
    lib.cap_client_connect_uds.argtypes = [ctypes.c_char_p]
    lib.cap_client_ping.restype = ctypes.c_int
    lib.cap_client_ping.argtypes = [ctypes.c_void_p]
    lib.cap_client_verify.restype = ctypes.c_int
    lib.cap_client_verify.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.POINTER(ctypes.c_uint32),
        ctypes.c_uint32,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.cap_client_close.restype = None
    lib.cap_client_close.argtypes = [ctypes.c_void_p]
    return lib


class NativeVerifyClient:
    """KeySet-shaped client backed by the C ABI shim."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 uds_path: Optional[str] = None):
        self._lib = _load()
        if uds_path is not None:
            self._h = self._lib.cap_client_connect_uds(uds_path.encode())
        else:
            self._h = self._lib.cap_client_connect(host.encode(), port)
        if not self._h:
            raise ConnectionError("native client failed to connect")

    def ping(self) -> bool:
        return bool(self._lib.cap_client_ping(self._h))

    def verify_batch(self, tokens: Sequence[str]) -> List[Any]:
        if not tokens:
            return []
        n = len(tokens)
        raw = [t.encode() for t in tokens]
        arr = (ctypes.c_char_p * n)(*raw)
        lens = (ctypes.c_uint32 * n)(*[len(r) for r in raw])
        statuses = (ctypes.c_uint8 * n)()
        offs = (ctypes.c_uint64 * (n + 1))()
        cap = max(4096, 1024 * n)
        buf = ctypes.create_string_buffer(cap)
        rc = self._lib.cap_client_verify(
            self._h, arr, lens, n, statuses, buf, cap, offs)
        if rc == -2:  # grow and retry once with the reported size
            cap = int(offs[n])
            buf = ctypes.create_string_buffer(cap)
            rc = self._lib.cap_client_verify(
                self._h, arr, lens, n, statuses, buf, cap, offs)
        if rc != 0:
            raise ConnectionError(f"native verify failed (rc={rc})")
        out: List[Any] = []
        for i in range(n):
            payload = buf.raw[offs[i]: offs[i + 1]]
            if statuses[i] == 0:
                out.append(json.loads(payload.decode()))
            else:
                out.append(RemoteVerifyError(payload.decode()))
        return out

    def close(self) -> None:
        if self._h:
            self._lib.cap_client_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
