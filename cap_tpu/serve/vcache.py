"""Verdict cache: serve repeated tokens at memory speed.

Real ingress traffic for an auth verifier is massively repetitive —
the same bearer token arrives hundreds of times within its lifetime
(ROADMAP item #3; the Zipf harness measured repeat_rate ≈ 0.996 on
realistic mixes). This module is the correctness-preserving caching
tier in front of the verify engines: a sharded, bounded map from
**token digest** (sha256 of the token bytes, truncated to 16 bytes —
collision-resistant, so digest equality IS token equality) to the
token's verdict, clamped so a cached entry can never outlive:

- the token's own ``exp`` (and never activate before ``nbf``) — both
  parsed once, at insert time, from the claims the accept carries;
- the key-table **epoch**: every entry is tagged with the epoch it was
  verified under; a keyplane rotation bumps the cache epoch atomically
  (:meth:`VerdictCache.bump_epoch`) and entries from the previous
  epoch survive only inside the rotation's grace window (default 0 —
  cached verdicts die IMMEDIATELY on rotation; the engines' own grace
  handling serves the re-verify);
- a hard TTL (``max_ttl_s``) as belt-and-braces bound for entries
  whose claims carry no ``exp``.

What is cached: **accepts** (the claims payload — for raw-claims
engines these are exactly the token's own payload bytes, so a cache
hit is byte-identical to a fresh verify by construction) and **only
terminal rejects** — reason classes where the verdict is a pure
function of the token bytes and the key material
(:data:`CACHEABLE_REJECTS`: bad_signature / malformed / not_signed).
Transient or environment-dependent classes (unknown_kid before a
refresh, jwks_error, transport, expired, internal) are NEVER cached:
the next arrival must reach an engine.

Any clamp uncertainty resolves to a MISS: the token goes to the
engine and the verdict is whatever the engine says — the cache can
change how fast a verdict is produced, never which verdict. A final
re-validation at serve time backs this with a tripwire counter
(``vcache.stale_accepts``, SLO-pinned to 0).

Counters (one ``count_many`` lock round per batched lookup):
``vcache.lookups == vcache.hits + vcache.misses`` exactly
(obs-smoke gates this), plus inserts / evictions / epoch_bumps /
clamp_drops, and a ``vcache.size`` gauge on the worker scrape.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import telemetry
from ..obs import decision as _decision

DIGEST_LEN = 16

# Reject reason classes whose verdict depends only on the token bytes
# and the installed key tables — safe to replay until the epoch moves.
CACHEABLE_REJECTS = frozenset({
    _decision.REASON_BAD_SIGNATURE,
    _decision.REASON_MALFORMED,
    _decision.REASON_NOT_SIGNED,
})

_MISS = object()


def token_digest(token: Any) -> bytes:
    """The cache key: sha256 of the token's UTF-8 bytes, truncated to
    :data:`DIGEST_LEN`. The native serve chain computes the identical
    digest in its reader threads (serve_native.cpp) so the Python
    drain does zero hashing on that chain."""
    if isinstance(token, str):
        token = token.encode("utf-8", "surrogatepass")
    return hashlib.sha256(token).digest()[:DIGEST_LEN]


def _claims_exp_nbf(verdict: Any, token: Any) -> Tuple[Optional[float],
                                                       Optional[float]]:
    """(exp, nbf) for the clamp, best-effort: from the accept's claims
    (dict or raw payload bytes), else from the token's payload
    segment. Unparseable → (None, None): the TTL bound still applies."""
    claims = None
    if isinstance(verdict, dict):
        claims = verdict
    elif isinstance(verdict, (bytes, bytearray, memoryview)):
        try:
            claims = json.loads(bytes(verdict))
        except (ValueError, UnicodeDecodeError):
            claims = None
    if claims is None and isinstance(token, str):
        parts = token.split(".")
        if len(parts) >= 2:
            seg = parts[1]
            try:
                pad = "=" * (-len(seg) % 4)
                claims = json.loads(base64.urlsafe_b64decode(seg + pad))
            except (ValueError, binascii.Error, UnicodeDecodeError):
                claims = None
    if not isinstance(claims, dict):
        return (None, None)

    def _num(v):
        return float(v) if isinstance(v, (int, float)) \
            and not isinstance(v, bool) else None

    return (_num(claims.get("exp")), _num(claims.get("nbf")))


# Cache entries are plain tuples — the lookup hot loop indexes them
# without attribute-load overhead:
#   (verdict, valid_from, valid_until, epoch, exp)
# valid_from = nbf (0.0 when absent); valid_until = min(insert-time +
# max_ttl, exp) — the exp and TTL clamps collapse into ONE compare.
_E_VERDICT, _E_FROM, _E_UNTIL, _E_EPOCH, _E_EXP = range(5)


class VerdictCache:
    """Sharded bounded token-digest → verdict map with epoch/exp/nbf
    clamps. Thread-safe; every public entry point may be called from
    any serve/drain/client thread."""

    def __init__(self, capacity: int = 65536, shards: int = 16,
                 max_ttl_s: float = 300.0):
        # power-of-two shard count so digest[0] masks cleanly
        n = 1
        while n < max(1, shards):
            n <<= 1
        self._n_shards = n
        self._cap_per_shard = max(1, capacity // n)
        self._shards: List[Dict[bytes, _Entry]] = [{} for _ in range(n)]
        self._locks = [threading.Lock() for _ in range(n)]
        self._max_ttl = float(max_ttl_s)
        # epoch state: entries tagged `epoch` serve while it is the
        # current epoch, or while it is the PREVIOUS epoch inside the
        # grace window of the last bump. Anything older is invalid.
        self._epoch_lock = threading.Lock()
        self._epoch: Optional[int] = None
        self._prev_epoch: Optional[int] = None
        self._grace_until = 0.0
        # counter staging: folded into the active telemetry recorder
        # in one count_many round per batched operation
        self._ctr_lock = threading.Lock()
        self._ctr = {"vcache.lookups": 0, "vcache.hits": 0,
                     "vcache.misses": 0, "vcache.inserts": 0,
                     "vcache.insert_skips": 0, "vcache.evictions": 0,
                     "vcache.epoch_bumps": 0, "vcache.clamp_drops": 0,
                     "vcache.stale_accepts": 0,
                     "vcache.peer_fills": 0,
                     "vcache.peer_fill_skips": 0,
                     "vcache.peer_exports": 0}

    # -- epoch / invalidation ---------------------------------------------

    @property
    def epoch(self) -> Optional[int]:
        return self._epoch

    def set_epoch(self, epoch: Optional[int]) -> None:
        """Initial epoch install (construction time): no bump
        accounting, no grace — the cache is empty anyway."""
        with self._epoch_lock:
            self._epoch = epoch
            self._prev_epoch = None
            self._grace_until = 0.0

    def bump_epoch(self, epoch: Optional[int],
                   grace_s: float = 0.0) -> None:
        """Atomic invalidation on key rotation: entries verified under
        the (now previous) epoch stay valid for ``grace_s`` seconds,
        then die; entries from any older epoch are invalid at once.
        A no-op when the epoch is unchanged (re-pushes must not churn
        the cache)."""
        with self._epoch_lock:
            if epoch == self._epoch:
                return
            self._prev_epoch = self._epoch
            self._epoch = epoch
            self._grace_until = time.time() + max(0.0, grace_s)
        self._count({"vcache.epoch_bumps": 1})

    def _epoch_valid(self, entry_epoch: Optional[int],
                     now: float) -> bool:
        # unlocked read of the trio: a racing bump makes the check
        # CONSERVATIVE at worst (a just-valid entry misses)
        if entry_epoch == self._epoch:
            return True
        return (entry_epoch == self._prev_epoch
                and entry_epoch is not None
                and now < self._grace_until)

    # -- lookup -----------------------------------------------------------

    def _valid(self, e: tuple, now: float) -> bool:
        return (e[_E_FROM] <= now < e[_E_UNTIL]
                and self._epoch_valid(e[_E_EPOCH], now))

    def get(self, digest: bytes, now: Optional[float] = None) -> Any:
        """The verdict for one digest, or the module's miss sentinel
        (compare with ``vcache.MISS``). Single-key form of
        :meth:`lookup_batch` — counts exactly the same way."""
        hit = self._get_nocount(digest, now)
        self._count({"vcache.lookups": 1,
                     "vcache.hits": 0 if hit is _MISS else 1,
                     "vcache.misses": 1 if hit is _MISS else 0})
        return hit

    def _get_nocount(self, digest: bytes,
                     now: Optional[float] = None) -> Any:
        if now is None:
            now = time.time()
        s = digest[0] & (self._n_shards - 1)
        e = self._shards[s].get(digest)
        if e is None:
            return _MISS
        if not self._valid(e, now):
            with self._locks[s]:
                self._shards[s].pop(digest, None)
            self._stage("vcache.clamp_drops", 1)
            return _MISS
        verdict = e[_E_VERDICT]
        # serve-time tripwire: re-validate against a FRESH clock read
        # before the verdict leaves the cache — an accept that expired
        # between check and serve is dropped and counted, never served
        # (vcache.stale_accepts is SLO-pinned to 0).
        if not isinstance(verdict, BaseException) \
                and not self._valid(e, time.time()):
            self._stage("vcache.stale_accepts", 1)
            return _MISS
        return verdict

    def lookup_batch(self, tokens: Sequence[Any],
                     digests: Optional[Sequence[Optional[bytes]]] = None
                     ) -> Tuple[List[Any], List[int], List[bytes]]:
        """Consult the cache for a whole batch in one pass.

        Returns ``(results, miss_idx, digests)``: ``results`` has the
        cached verdict at hit positions and ``None`` at misses,
        ``miss_idx`` lists the miss positions (submit exactly these to
        the engine), ``digests`` the per-token digest (computed here
        unless the caller supplies them, e.g. from the native reader
        threads). One counter fold for the whole batch."""
        now = time.time()
        n = len(tokens)
        out: List[Any] = [None] * n
        miss_idx: List[int] = []
        digs: List[bytes] = [b""] * n
        # inlined hot loop (no per-token function calls): validity =
        # TTL deadline ∧ epoch/grace ∧ exp ∧ nbf, all against one
        # clock read; epoch trio snapshotted unlocked (a racing bump
        # makes the check conservative at worst)
        shards = self._shards
        locks = self._locks
        mask = self._n_shards - 1
        cur, prev, guntil = self._epoch, self._prev_epoch, \
            self._grace_until
        hits = 0
        drops = 0
        hit_entries: List[tuple] = []
        for i in range(n):
            d = digests[i] if digests is not None else None
            if not d:
                d = token_digest(tokens[i])
            digs[i] = d
            # unlocked read (GIL-atomic, same stance as the decision
            # header cache); the lock is taken only to delete
            e = shards[d[0] & mask].get(d)
            if e is not None:
                ep = e[3]
                if e[1] <= now < e[2] and (
                        ep == cur or (ep == prev and ep is not None
                                      and now < guntil)):
                    out[i] = e[0]
                    hits += 1
                    hit_entries.append((i, e))
                    continue
                s = d[0] & mask
                with locks[s]:
                    shards[s].pop(d, None)
                drops += 1
            miss_idx.append(i)
        # serve-time tripwire: ONE fresh clock read for the batch; an
        # accept whose exp crossed between check and serve is demoted
        # to a miss and counted (vcache.stale_accepts, SLO-pinned 0).
        stale = 0
        if hit_entries:
            now2 = time.time()
            for i, e in hit_entries:
                exp = e[4]
                if exp is not None and now2 >= exp:
                    out[i] = None
                    miss_idx.append(i)
                    hits -= 1
                    stale += 1
            if stale:
                miss_idx.sort()
        self._count({"vcache.lookups": n, "vcache.hits": hits,
                     "vcache.misses": n - hits,
                     "vcache.clamp_drops": drops,
                     "vcache.stale_accepts": stale})
        return out, miss_idx, digs

    # -- insert -----------------------------------------------------------

    def cacheable(self, verdict: Any) -> bool:
        """Whether a verdict may be cached at all: accepts always,
        rejects only for :data:`CACHEABLE_REJECTS` reason classes."""
        if isinstance(verdict, BaseException):
            return _decision.classify(verdict) in CACHEABLE_REJECTS
        return True

    def insert(self, digest: bytes, verdict: Any, token: Any = None,
               epoch: Optional[int] = None,
               now: Optional[float] = None) -> bool:
        """Insert one verdict; returns False (counted as a skip) when
        the verdict class is uncacheable, the entry is already expired,
        or ``epoch`` no longer matches the cache epoch (the verify
        raced a rotation — conservative drop)."""
        if now is None:
            now = time.time()
        if not self.cacheable(verdict) or epoch != self._epoch:
            self._count({"vcache.insert_skips": 1})
            return False
        exp, nbf = _claims_exp_nbf(verdict, token) \
            if not isinstance(verdict, BaseException) else (None, None)
        if exp is not None and now >= exp:
            self._count({"vcache.insert_skips": 1})
            return False
        until = now + self._max_ttl
        if exp is not None and exp < until:
            until = exp
        e = (verdict, nbf if nbf is not None else 0.0, until, epoch,
             exp)
        s = digest[0] & (self._n_shards - 1)
        evicted = 0
        with self._locks[s]:
            shard = self._shards[s]
            if digest not in shard and len(shard) >= self._cap_per_shard:
                # bounded: evict the oldest inserted (dict order)
                shard.pop(next(iter(shard)))
                evicted = 1
            shard[digest] = e
        self._count({"vcache.inserts": 1, "vcache.evictions": evicted})
        return True

    def insert_batch(self, digests: Sequence[bytes],
                     verdicts: Sequence[Any],
                     tokens: Optional[Sequence[Any]] = None,
                     epoch: Optional[int] = None) -> int:
        now = time.time()
        n_in = 0
        for i, d in enumerate(digests):
            if self.insert(d, verdicts[i],
                           token=tokens[i] if tokens is not None
                           else None,
                           epoch=epoch, now=now):
                n_in += 1
        return n_in

    # -- peer fill (fleet cache warming, CVB1 frame pair 13/14) -----------

    def export_entries(self, max_entries: int = 2048,
                       max_bytes: int = 768 * 1024
                       ) -> Tuple[List[list], Optional[int]]:
        """Dump currently-valid ACCEPT entries for a peer-fill
        transfer → (entries, epoch).

        Each entry is ``[digest_hex, payload_b64, valid_from,
        valid_until, exp_or_null]``. Accepts only: rejects are cheap
        to re-verify and their exception classes don't round-trip
        bit-exactly. Entries from a previous epoch (grace residue)
        are skipped — an export carries exactly ONE epoch, the
        current one, so the importer's clamp is a single equality.
        ``max_bytes`` approximates the wire bound so the frame can
        never exceed ``protocol.MAX_ENTRY_BYTES``."""
        now = time.time()
        epoch = self._epoch
        out: List[list] = []
        size = 0
        for shard in self._shards:
            if len(out) >= max_entries or size >= max_bytes:
                break
            # snapshot the dict (GIL-atomic list()) — exports race
            # inserts harmlessly; we only need a consistent-ish slice
            for digest, e in list(shard.items()):
                if len(out) >= max_entries or size >= max_bytes:
                    break
                verdict = e[_E_VERDICT]
                if isinstance(verdict, BaseException):
                    continue
                if e[_E_EPOCH] != epoch or not (e[_E_FROM] <= now
                                                < e[_E_UNTIL]):
                    continue
                if isinstance(verdict, (bytes, bytearray, memoryview)):
                    payload = bytes(verdict)
                else:
                    # exactly protocol._response_parts' encoding, so
                    # an imported hit is byte-identical on the wire
                    payload = json.dumps(
                        verdict, separators=(",", ":")).encode()
                row = [digest.hex(),
                       base64.b64encode(payload).decode("ascii"),
                       e[_E_FROM], e[_E_UNTIL], e[_E_EXP]]
                size += len(payload) + len(row[0]) + 48
                out.append(row)
        self._count({"vcache.peer_exports": len(out)})
        return out, epoch

    def import_entries(self, entries: Sequence[Sequence[Any]],
                       epoch: Any) -> int:
        """Install a peer's export, under the SAME clamps a local
        insert gets — warming can never extend a verdict's validity:

        - ``epoch`` must equal the cache's CURRENT epoch (a transfer
          racing a rotation is dropped whole — conservative);
        - per entry, ``valid_until`` is re-bounded by this cache's own
          ``now + max_ttl`` (min, never max) and already-expired or
          not-yet-valid windows are skipped;
        - the serve-time stale-accept tripwire applies to imported
          entries exactly as to local ones (they are ordinary entries).

        Returns how many entries were installed
        (``vcache.peer_fills``); clamped drops count
        ``vcache.peer_fill_skips``."""
        now = time.time()
        if epoch != self._epoch:
            self._count({"vcache.peer_fill_skips": len(entries)})
            return 0
        filled = 0
        skipped = 0
        evicted = 0
        for row in entries:
            try:
                digest = bytes.fromhex(row[0])
                payload = base64.b64decode(row[1])
                valid_from = float(row[2])
                valid_until = float(row[3])
                exp = float(row[4]) if row[4] is not None else None
            except (ValueError, TypeError, IndexError,
                    binascii.Error):
                skipped += 1
                continue
            until = min(valid_until, now + self._max_ttl)
            if exp is not None:
                until = min(until, exp)
            if len(digest) != DIGEST_LEN or now >= until:
                skipped += 1
                continue
            # re-check the epoch per entry: a rotation landing mid-
            # import invalidates the REST of the transfer, not just
            # the next lookup
            if epoch != self._epoch:
                skipped += len(entries) - filled - skipped
                break
            e = (payload, valid_from, until, epoch, exp)
            s = digest[0] & (self._n_shards - 1)
            with self._locks[s]:
                shard = self._shards[s]
                if digest not in shard \
                        and len(shard) >= self._cap_per_shard:
                    shard.pop(next(iter(shard)))
                    evicted += 1
                shard[digest] = e
            filled += 1
        self._count({"vcache.peer_fills": filled,
                     "vcache.peer_fill_skips": skipped,
                     "vcache.evictions": evicted})
        return filled

    # -- stats ------------------------------------------------------------

    def size(self) -> int:
        return sum(len(s) for s in self._shards)

    def stats(self) -> Dict[str, int]:
        """Lifetime counters (also folded into the telemetry recorder
        under the same names) plus the live size."""
        with self._ctr_lock:
            out = dict(self._ctr)
        out["vcache.size"] = self.size()
        return out

    def clear(self) -> None:
        for lock, shard in zip(self._locks, self._shards):
            with lock:
                shard.clear()

    # -- counter plumbing -------------------------------------------------

    def _stage(self, name: str, n: int) -> None:
        if not n:
            return
        with self._ctr_lock:
            self._ctr[name] += n
        telemetry.count(name, n)

    def _count(self, increments: Dict[str, int]) -> None:
        inc = {k: v for k, v in increments.items() if v}
        if not inc:
            return
        with self._ctr_lock:
            for k, v in inc.items():
                self._ctr[k] += v
        rec = telemetry.active()
        if rec is not None:
            rec.count_many(inc)


MISS = _MISS


def enabled_from_env(default: bool = True) -> bool:
    """The documented graceful-off switch: ``CAP_SERVE_VCACHE=0``
    disables the whole tier (worker caches, native digest handoff,
    batcher in-flight dedup stays separately controllable)."""
    import os

    v = os.environ.get("CAP_SERVE_VCACHE")
    if v is None:
        return default
    return v != "0"
