"""Python client for the zero-copy shared-memory transport.

Mirrors :class:`~cap_tpu.serve.client.VerifyClient`'s surface
(``verify_batch`` / ``ping`` / ``stats`` / ``close``) but moves every
frame through the mmap'd ring pair once the worker acks the attach —
the socket stays open purely as the liveness channel. The frames
themselves are byte-identical to the socket transport's (the SAME
``protocol.send_*`` encoders write into the ring), so everything
above the transport — checksums, traced requests, verdict parsing —
is untouched.

Fallback contract (the r12 graceful-fallback stance, now at the
transport layer): a worker that refuses the attach (transport off,
region unusable) acks status 1 and this client silently keeps the
SOCKET transport on the same connection; a worker whose library
predates frame type 15 drops the connection instead, and this client
redials socket-only. Either way the caller gets a working client —
``transport`` says which one — and the fallback is counted
(``shm.client_fallbacks``).
"""

from __future__ import annotations

import json
import os
import socket
from typing import Any, List, Optional, Sequence

from .. import telemetry
from . import protocol
from .client import RemoteVerifyError
from .shm_ring import RingConsumer, RingProducer, ShmRegion, default_dir


class ShmVerifyClient:
    """Blocking client over the shm ring transport (socket fallback).

    host/port or uds_path address the worker's serve socket exactly
    like VerifyClient; ``ring_bytes`` sizes each ring (one request +
    one response ring per connection); ``shm_dir`` overrides where the
    region file lives (default: CAP_SHM_DIR → /dev/shm → tmp).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 uds_path: Optional[str] = None, timeout: float = 30.0,
                 crc: bool = False, ring_bytes: int = 1 << 20,
                 shm_dir: Optional[str] = None):
        self._crc = crc
        self._timeout = timeout
        self._addr = (host, port, uds_path)
        self._sock = self._connect()
        self._reader = protocol.FrameReader(self._sock)
        self._region: Optional[ShmRegion] = None
        self._producer: Optional[RingProducer] = None
        self._consumer: Optional[RingConsumer] = None
        self._closed = False
        self.transport = "socket"
        self.attach_error: Optional[str] = None
        self._attach(ring_bytes, shm_dir)

    def _connect(self) -> socket.socket:
        host, port, uds_path = self._addr
        if uds_path is not None:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            s.settimeout(self._timeout)
            s.connect(uds_path)
            return s
        s = socket.create_connection((host, port),
                                     timeout=self._timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _attach(self, ring_bytes: int, shm_dir: Optional[str]) -> None:
        size = 1 << max(12, (ring_bytes - 1).bit_length())
        path = os.path.join(
            shm_dir or default_dir(),
            f"cap-shm-{os.getpid()}-{os.urandom(4).hex()}")
        region = None
        try:
            region = ShmRegion.create(path, req_size=size,
                                      resp_size=size)
            protocol.send_shm_attach(self._sock, path)
            ftype, entries, _ = self._reader.recv_frame_ex()
            if ftype != protocol.T_SHM_ACK:
                raise protocol.MalformedFrameError(
                    f"expected shm ack, got type {ftype}")
            status, payload = entries[0]
            if status != 0:
                # negotiated refusal: the worker serves this very
                # connection over the socket — keep it
                self.attach_error = payload.decode(errors="replace")
                telemetry.count("shm.client_fallbacks")
                region.close(unlink=True)
                return
            self._region = region
            self._producer = RingProducer(region, "req")
            self._consumer = RingConsumer(region, "resp")
            self.transport = "shm"
        except (ConnectionError, OSError, protocol.ProtocolError) as e:
            # stale worker dropped the unknown frame type (or died):
            # redial socket-only — attach must never cost the caller
            # a working client
            self.attach_error = f"{type(e).__name__}: {e}"
            telemetry.count("shm.client_fallbacks")
            if region is not None:
                region.close(unlink=True)
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = self._connect()
            self._reader = protocol.FrameReader(self._sock)

    # -- frame transport ---------------------------------------------------

    def _send(self, send_fn, *args, **kw) -> None:
        """Run one protocol.send_* encoder against the active
        transport (the ring producer duck-types sendall)."""
        if self.transport == "shm":
            send_fn(self._producer, *args, **kw)
        else:
            send_fn(self._sock, *args, **kw)

    def _recv_frame(self):
        if self.transport != "shm":
            return self._reader.recv_frame_ex()
        deadline = (None if self._timeout is None
                    else self._timeout)
        import time as _time

        t0 = _time.monotonic()
        while True:
            rec = self._consumer.read(timeout=0.05)
            if rec is not None:
                ftype, entries, trace, used = \
                    protocol.parse_frame_bytes(rec)
                if used != len(rec):
                    raise protocol.MalformedFrameError(
                        "shm record carries trailing bytes")
                return ftype, entries, trace
            # liveness: a dead worker means the response never comes
            if self._worker_gone():
                raise ConnectionError("worker closed the shm "
                                      "liveness socket")
            if deadline is not None \
                    and _time.monotonic() - t0 > deadline:
                raise TimeoutError("no shm response within timeout")

    def _worker_gone(self) -> bool:
        import select

        try:
            r, _, _ = select.select([self._sock], [], [], 0)
            if not r:
                return False
            return self._sock.recv(4096) == b""
        except OSError:
            return True

    # -- VerifyClient surface ----------------------------------------------

    def ping(self) -> bool:
        self._send(protocol.send_ping)
        ftype, _, _ = self._recv_frame()
        return ftype == protocol.T_PONG

    def stats(self) -> dict:
        self._send(protocol.send_stats_request)
        ftype, entries, _ = self._recv_frame()
        if ftype != protocol.T_STATS_RESP or len(entries) != 1:
            raise protocol.ProtocolError(
                f"expected stats response, got type {ftype}")
        return json.loads(entries[0][1].decode())

    def verify_batch(self, tokens: Sequence[str],
                     trace: Optional[str] = None) -> List[Any]:
        """Claims dict per verified token; RemoteVerifyError per
        reject — byte-identical verdicts to the socket transport."""
        if not tokens:
            return []
        self._send(protocol.send_request, tokens, crc=self._crc,
                   trace=trace)
        want = (protocol.T_VERIFY_RESP_TRACE if trace is not None
                else protocol.T_VERIFY_RESP_CRC if self._crc
                else protocol.T_VERIFY_RESP)
        ftype, entries, _ = self._recv_frame()
        if ftype != want:
            raise protocol.ProtocolError(
                f"expected response type {want}, got {ftype}")
        if len(entries) != len(tokens):
            raise protocol.ProtocolError(
                f"response count {len(entries)} != request "
                f"{len(tokens)}")
        out: List[Any] = []
        for status, payload in entries:
            if status == 0:
                out.append(json.loads(payload.decode()))
            else:
                out.append(RemoteVerifyError(payload.decode()))
        return out

    def verify_signature(self, token: str) -> Any:
        res = self.verify_batch([token])[0]
        if isinstance(res, Exception):
            raise res
        return res

    def push_keys(self, jwks_doc: dict, epoch: int) -> int:
        """KEYS push over the active transport; returns the acked
        epoch (raises RemoteVerifyError on a status-1 ack)."""
        self._send(protocol.send_keys_push, jwks_doc, epoch)
        ftype, entries, _ = self._recv_frame()
        if ftype != protocol.T_KEYS_ACK or not entries:
            raise protocol.ProtocolError(
                f"expected keys ack, got type {ftype}")
        status, payload = entries[0]
        if status != 0:
            raise RemoteVerifyError(payload.decode())
        return int(json.loads(payload).get("epoch"))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        if self._region is not None:
            self._region.close(unlink=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
