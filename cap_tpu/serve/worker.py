"""The verify worker: owns the device engine, serves the wire protocol.

The TPU-native analog of "the process that owns the accelerator": host
applications connect over TCP (or a Unix socket) and stream verify
requests; all connections share ONE AdaptiveBatcher → ONE
TPUBatchKeySet → one device, so concurrent small callers coalesce into
full device batches (SURVEY.md §2.6, §7 step 7).
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Optional, Tuple

from .. import telemetry
from ..obs import decision as _decision
from ..obs import occupancy as _occupancy
from . import protocol
from . import shm_ring as _shm
from . import vcache as _vcache
from .batcher import AdaptiveBatcher

# shared pre-set event for all-cache-hit submissions (nothing to wait on)
_DONE_EVENT = threading.Event()
_DONE_EVENT.set()


class _CachePending:
    """A pending-shaped handle over a cache-consulted submission.

    Mirrors the ``_Pending`` surface the responder loop reads
    (``tokens`` / ``ts`` / ``event`` / ``results``): all-hit requests
    carry their verdicts immediately (event pre-set, no batcher
    round-trip); partial hits wait on the underlying miss submission
    and merge lazily at respond time, filling the cache with the fresh
    verdicts as a side effect."""

    __slots__ = ("tokens", "ts", "event", "_hits", "_miss_idx",
                 "_inner", "_fill", "_results")

    def __init__(self, tokens, hits, miss_idx, inner, fill):
        self.tokens = tokens
        self.ts = time.monotonic()
        self._hits = hits
        self._miss_idx = miss_idx
        self._inner = inner
        self._fill = fill
        self._results = None
        self.event = inner.event if inner is not None else _DONE_EVENT

    @property
    def results(self):
        if self._results is None:
            out = self._hits
            if self._inner is not None:
                fresh = self._inner.results
                for j, i in enumerate(self._miss_idx):
                    out[i] = fresh[j]
                if self._fill is not None:
                    self._fill(self._miss_idx, fresh)
            self._results = out
        return self._results


class _RawClaimsSync:
    """Route the batcher at a keyset's SYNC raw-claims entry point
    (rotation-aware keysets like TPURemoteKeySet: no async dispatch,
    the batcher falls back to its sync path)."""

    def __init__(self, keyset):
        self._keyset = keyset

    def verify_batch(self, tokens):
        return self._keyset.verify_batch_raw(tokens)


class _RawClaims(_RawClaimsSync):
    """Raw entry points including async dispatch (TPUBatchKeySet)."""

    def verify_batch_async(self, tokens):
        return self._keyset.verify_batch_async_raw(tokens)


class VerifyWorker:
    """Serve ``keyset.verify_batch`` over the CVB1 protocol.

    keyset: typically a TPUBatchKeySet; anything with verify_batch.
    host/port: TCP bind (port 0 → ephemeral, see ``address``);
    uds_path: serve on a Unix socket instead of TCP.
    """

    def __init__(self, keyset, host: str = "127.0.0.1", port: int = 0,
                 uds_path: Optional[str] = None,
                 target_batch: int = 4096, max_wait_ms: float = 2.0,
                 max_batch: int = 32768, raw_claims: bool = True,
                 obs_port: Optional[int] = None,
                 serve_native: Optional[bool] = None,
                 vcache: Optional[bool] = None,
                 vcache_capacity: int = 0,
                 transport: Optional[str] = None,
                 fair: Optional[bool] = None,
                 admit_rate: Optional[float] = None,
                 admit_burst: Optional[float] = None):
        # Transport capability (docs/SERVE.md §Transports): "shm"
        # accepts per-connection shared-memory attach negotiations
        # (CVB1 type 15) on BOTH serve chains; "socket" (default) acks
        # them status 1 — the connection keeps serving over the socket
        # and serve.shm_fallbacks counts the refusal. The worker
        # always serves the socket either way; shm is negotiated per
        # connection, never assumed.
        if transport is None:
            transport = os.environ.get("CAP_SERVE_TRANSPORT", "socket")
        if transport not in ("socket", "shm"):
            raise ValueError(f"unknown transport {transport!r}")
        self._shm_enabled = transport == "shm"
        # The unwrapped engine: keyplane operations (KEYS pushes,
        # epoch reporting) address it directly, whatever raw-claims
        # wrapper the batcher ends up routed through.
        self._engine = keyset
        # Raw-claims passthrough: the response payload for a verified
        # token IS its claims JSON, and the signed payload bytes are
        # exactly that — building dicts only to re-serialize them
        # wastes the host core the worker shares with prep/packing.
        # Keysets without the raw entry (stubs, plain KeySets) keep
        # the dict path; the wire format is identical either way.
        if raw_claims and hasattr(keyset, "verify_batch_async_raw"):
            keyset = _RawClaims(keyset)
        elif raw_claims and hasattr(keyset, "verify_batch_raw"):
            keyset = _RawClaimsSync(keyset)
        # Verdict cache (ROADMAP #3): consulted in both serve chains'
        # drain paths BEFORE the batcher; epoch-invalidated by KEYS
        # pushes (apply_keys below), exp/nbf-clamped per entry. Off via
        # vcache=False or CAP_SERVE_VCACHE=0 (the graceful-off switch),
        # which also turns the batcher's in-flight dedup off (one tier,
        # one switch) unless CAP_SERVE_DEDUP overrides explicitly.
        if vcache is None:
            vcache = _vcache.enabled_from_env(True)
        # Tenant-fair scheduling + admission (r20, docs/SERVE.md
        # §Admission & fairness): DRR over per-tenant queues in both
        # chains (native ring subqueues / the batcher's fair mode)
        # plus per-tenant token-bucket admission with wire pushback.
        # Knobs: args here win, else CAP_SERVE_FAIR /
        # CAP_SERVE_ADMIT_RATE / CAP_SERVE_ADMIT_BURST /
        # CAP_SERVE_DRR_QUANTUM / CAP_SERVE_DRR_WEIGHTS.
        from . import admission as _admission

        self._adm_cfg = _admission.AdmissionConfig(
            fair=fair, rate=admit_rate, burst=admit_burst)
        self._admission: Optional[_admission.AdmissionController] = None
        self._batcher = AdaptiveBatcher(
            keyset, target_batch=target_batch, max_wait_ms=max_wait_ms,
            max_batch=max_batch,
            dedup=(None if os.environ.get("CAP_SERVE_DEDUP") is not None
                   else bool(vcache)),
            fair=self._adm_cfg.fair,
            drr_quantum=self._adm_cfg.quantum)
        if self._adm_cfg.fair and self._adm_cfg.weights:
            from . import drr as _drr

            for label, w in self._adm_cfg.weights.items():
                slot = (_drr.SCHED_BE if label == "be"
                        else _drr.sched_slot_for_label(label))
                self._batcher.set_weight(slot, w)
        self._vcache: Optional[_vcache.VerdictCache] = None
        if vcache:
            self._vcache = _vcache.VerdictCache(
                capacity=vcache_capacity
                or int(os.environ.get("CAP_SERVE_VCACHE_CAP", "65536")))
            self._vcache.set_epoch(getattr(self._engine, "key_epoch",
                                           None))
        # Serve-chain selection: the NATIVE chain (C++ frame I/O +
        # lock-free ring, serve/native_serve.py) when requested via
        # serve_native=True or CAP_SERVE_NATIVE=1, with a graceful
        # fallback to the pure-Python reader/responder chain when the
        # library is absent/stale or the transport is UDS (the native
        # readers own TCP fds). Both chains speak byte-identical CVB1
        # and reject the same malformed frames with the same classes.
        if serve_native is None:
            serve_native = os.environ.get("CAP_SERVE_NATIVE", "0") == "1"
        self._native = None
        if serve_native and uds_path is None:
            try:
                from .native_serve import NativeServeChain

                self._native = NativeServeChain(
                    self._batcher, stats_fn=self.stats,
                    keys_fn=self.apply_keys,
                    peer_fill_fn=self.peer_fill,
                    target_batch=target_batch,
                    max_wait_ms=max_wait_ms, max_batch=max_batch,
                    vcache=self._vcache,
                    shm=self._shm_enabled,
                    admission=self._adm_cfg)
            except Exception:  # noqa: BLE001 - fall back, visibly
                telemetry.count("serve.native_fallbacks")
                self._native = None
        if self._native is None and self._adm_cfg.admission_on:
            # python-chain admission (also the native-request-fell-
            # back arm): the reader thread polices at dispatch time
            self._admission = _admission.AdmissionController(
                self._adm_cfg.rate, self._adm_cfg.burst)
        self._uds_path = uds_path
        if uds_path is not None:
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                os.unlink(uds_path)        # stale socket from a restart
            except FileNotFoundError:
                pass
            self._sock.bind(uds_path)
            self._addr: Tuple[str, int] = (uds_path, 0)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            self._sock.bind((host, port))
            self._addr = self._sock.getsockname()
        self._sock.listen(128)
        self._closed = False
        # Observability surface (obs_port=None → off, 0 → ephemeral):
        # Prometheus /metrics + mergeable /snapshot + /flight recorder
        # (serve.obs). Extras are live batcher depth — present in every
        # scrape even when the telemetry recorder is off.
        self._obs = None
        if obs_port is not None:
            from .obs import ObsServer

            self._obs = ObsServer(
                host=host if uds_path is None else "127.0.0.1",
                port=obs_port, extra=self._obs_gauges,
                snapshot_extra=self._native_obs_snapshot)
        # connection plane (r22): live python-chain connections (the
        # native chain's live count derives from its own counters)
        self._conns_live = 0
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="cap-tpu-accept")
        self._accept_thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        """(host, port) for TCP, (path, 0) for UDS."""
        return self._addr

    @property
    def obs_address(self) -> Optional[Tuple[str, int]]:
        """(host, port) of the HTTP observability server, if enabled."""
        return self._obs.address if self._obs is not None else None

    @property
    def key_epoch(self):
        """The engine's key-table epoch (None: not epoch-versioned)."""
        return getattr(self._engine, "key_epoch", None)

    @property
    def serve_chain(self) -> str:
        """Which serve chain this worker runs: "native" (C++ frame I/O
        + lock-free ring) or "python" (reader/responder threads)."""
        return "native" if self._native is not None else "python"

    @property
    def transport(self) -> str:
        """Transport capability actually live: "shm" when this worker
        honors shared-memory attach negotiations, "socket" otherwise
        (including the stale-library fallback on the native chain —
        the ready line reports what RUNS, not what was asked)."""
        if not self._shm_enabled:
            return "socket"
        if self._native is not None and not self._native.shm_armed:
            return "socket"
        return "shm"

    def apply_keys(self, jwks_doc: dict, epoch) -> int:
        """Apply one keyplane KEYS push; returns the installed epoch.

        Raises when the engine is not swap-capable or the document is
        unusable — the caller acks with the error, never half-applies.
        """
        swap = getattr(self._engine, "swap_keys", None)
        if swap is None:
            raise TypeError(
                f"{type(self._engine).__name__} does not support hot "
                "key rotation")
        got = swap(jwks_doc, epoch=epoch)
        if self._vcache is not None:
            # Atomic cache invalidation rides the SAME push that swaps
            # the tables: cached verdicts from the previous epoch die
            # immediately (grace 0 — the ENGINE's grace window covers
            # retired-kid re-verifies; the cache never extends it), so
            # a cached accept cannot outlive a rotated key.
            self._vcache.bump_epoch(got)
        telemetry.count("worker.keys_pushes")
        telemetry.gauge("keyplane.epoch", got)
        return got

    def peer_fill(self, doc: dict) -> dict:
        """Handle one peer-fill op (CVB1 type 13; see
        :mod:`cap_tpu.serve.vcache` for the clamp contract).

        ``op=export`` dumps a bounded slice of this worker's verdict
        cache; ``op=import`` installs a sibling's dump into it;
        ``op=admission`` (r20 — rides the same control pair, no new
        frame type) retunes the admission plane: per-tenant shed
        scales and/or a new rate/burst, pushed by the pool's
        SLO-burn autoscaler. Raises when this worker cannot serve the
        op or the document is unusable — the caller acks with the
        error, nothing is half-applied."""
        op = doc.get("op")
        if op == "admission":
            return self.apply_admission(doc)
        if self._vcache is None:
            raise TypeError("worker has no verdict-cache tier "
                            "(vcache off)")
        if op == "export":
            max_n = int(doc.get("max") or 2048)
            entries, epoch = self._vcache.export_entries(
                max_entries=max_n)
            telemetry.count("worker.peer_exports")
            return {"entries": entries, "epoch": epoch}
        if op == "import":
            n = self._vcache.import_entries(
                doc.get("entries") or [], epoch=doc.get("epoch"))
            telemetry.count("worker.peer_imports")
            return {"imported": n}
        raise ValueError(f"unknown peer-fill op {op!r}")

    def apply_admission(self, doc: dict) -> dict:
        """Apply one admission-control op (``op=admission`` on the
        CVB1 type-13/14 pair): ``scale`` maps tenant hashes to rate
        scales (< 1.0 sheds, 1.0 restores); ``rate``/``burst`` retune
        the buckets wholesale. Raises when this worker has no
        admission plane armed — the pool's autoscaler treats that as
        "nothing to tighten" and moves on."""
        native = self._native
        if self._admission is None and (
                native is None or not (native.adm_native
                                       or native._py_admission)):
            raise TypeError("worker has no admission plane armed "
                            "(CAP_SERVE_ADMIT_RATE unset)")
        applied = 0
        rate = doc.get("rate")
        burst = doc.get("burst")
        if rate is not None:
            rate = float(rate)
            burst = float(burst) if burst is not None \
                else max(1.0, 2.0 * rate)
            if native is not None and native.adm_native:
                native._lib.cap_serve_set_admission(
                    native._h, 1, rate, burst)
            if self._admission is not None:
                self._admission.rate = max(0.0, rate)
                self._admission.burst = burst
            self._adm_cfg.rate = max(0.0, rate)
            self._adm_cfg.burst = burst
            applied += 1
        for label, s in (doc.get("scale") or {}).items():
            label = str(label)
            s = float(s)
            if native is not None:
                native.set_tenant_scale(label, s)
            if self._admission is not None:
                self._admission.set_scale(label, s)
            telemetry.count("admission.sheds" if s < 1.0
                            else "admission.unsheds")
            applied += 1
        telemetry.count("worker.admission_ops")
        return {"applied": applied, "shed": self.shed_state()}

    def shed_state(self) -> dict:
        """Currently shed tenants (label → rate scale), whichever
        enforcement point holds them."""
        if self._native is not None:
            return self._native.shed_state
        if self._admission is not None:
            return dict(self._admission.shed)
        return {}

    def _obs_gauges(self) -> dict:
        # flush the occupancy plane's counter deltas + window gauges
        # into the recorder so this scrape sees device.occupancy fresh
        _occupancy.publish()
        d = self._batcher.depth()
        out = {"batcher.queued_tokens": d["queued_tokens"],
               "batcher.inflight_batches": d["inflight_batches"],
               "worker.pid": os.getpid(),
               # 1.0 when the native chain serves this worker — the
               # numeric form capstat renders as chain=native
               "serve.native.active": 1.0 if self._native else 0.0,
               # 1.0 when shm attach negotiation is live — capstat
               # renders it as tr=shm
               "serve.shm.active": 1.0 if self.transport == "shm"
               else 0.0}
        if self._native is not None:
            out["serve.native.ring_depth"] = float(
                self._native.ring_depth())
            # burst-visible peak depth since the LAST scrape (the
            # native side tracks the max at push time; reading it here
            # rearms the mark — gauge-reset-on-scrape)
            out["serve.native.ring_hwm"] = float(
                self._native.ring_hwm(reset=True))
            out["serve.native.obs_plane"] = (
                1.0 if self._native.obs_plane is not None else 0.0)
        # connection plane (r22): live conns, whichever chain accepts
        if self._native is not None:
            nc = self._native.counters()
            out["serve.conns_live"] = float(
                nc.get("serve.native.connections", 0)
                - nc.get("serve.native.connections_closed", 0))
        else:
            out["serve.conns_live"] = float(self._conns_live)
        epoch = self.key_epoch
        if epoch is not None:
            out["keyplane.epoch"] = float(epoch)
        if self._vcache is not None:
            out["vcache.size"] = float(self._vcache.size())
        # admission & fairness state (capstat's tenant-ledger columns)
        out["serve.fair.active"] = 1.0 if self._adm_cfg.fair else 0.0
        adm_on = (self._admission is not None
                  or (self._native is not None
                      and (self._native.adm_native
                           or self._native._py_admission is not None)))
        out["admission.active"] = 1.0 if adm_on else 0.0
        if adm_on:
            out["admission.rate"] = float(self._adm_cfg.rate)
            out["admission.burst"] = float(self._adm_cfg.burst)
            for label, s in self.shed_state().items():
                out[f"admission.tenant.{label}.shed_scale"] = float(s)
            # per-tenant bucket fill + DRR weight for the capstat
            # ledger's admission columns (bounded: the tenant table
            # caps at 64 slots + none/other)
            weights = self._adm_cfg.weights
            for slot, label in sorted(
                    _decision.TENANTS.labels().items()):
                fill = None
                if self._native is not None:
                    fill = self._native.admission_fill(label)
                elif self._admission is not None:
                    fill = self._admission.fill(label)
                if fill is not None:
                    out[f"admission.tenant.{label}.fill"] = \
                        round(float(fill), 3)
                w = weights.get(label)
                if w is not None:
                    out[f"admission.tenant.{label}.weight"] = float(w)
        return out

    def _native_obs_snapshot(self):
        """The native side's mergeable snapshot (None on the python
        chain): the serve chain's own counters plus — when the
        telemetry plane is on — its decision counters and histogram
        series. Scrape paths, STATS and postmortems fold it into the
        recorder's snapshot with ``merge_snapshots``; the exemplar
        pump runs first so the decision ring is scrape-fresh."""
        native = self._native
        if native is None:
            return None
        snap = {"v": 1, "counters": dict(native.counters()),
                "gauges": {}, "series": {}}
        plane = native.obs_plane
        if plane is not None:
            plane.pump()
            snap = telemetry.merge_snapshots([snap, plane.snapshot()])
        return snap

    def stats(self) -> dict:
        """Process-local load/health snapshot (the STATS op payload).

        Counts and timings only — never tokens, keys, or claims. The
        telemetry recorder may be off (empty dicts then); queue depth
        and inflight come straight from the batcher either way.
        """
        rec = telemetry.active()
        # occupancy counters flush into the recorder BEFORE the
        # snapshot below, so STATS / pool merges carry them
        _occupancy.publish(rec)
        obs = self.obs_address
        native_counters = (self._native.counters()
                           if self._native is not None else {})
        # Native telemetry plane: its counters (decision.serve.*) and
        # histogram series live in the C region, not the recorder —
        # merge them here so STATS, postmortems and pool.stats_merged
        # see one coherent worker, whichever side counted.
        plane_snap = self._native_obs_snapshot()
        snap = rec.snapshot() if rec is not None else {}
        series = rec.summary() if rec is not None else {}
        if plane_snap is not None:
            snap = telemetry.merge_snapshots([snap, plane_snap])
            series = {**series, **telemetry.summarize_snapshot(
                {"series": plane_snap.get("series") or {}})}
        return {
            "pid": os.getpid(),
            # depth plus — additively, only once flushes happened —
            # the r22 flush-reason mix and last-flush lifecycle
            **self._batcher.stats(),
            "key_epoch": self.key_epoch,
            "serve_chain": self.serve_chain,
            "transport": self.transport,
            **({"ring_depth": self._native.ring_depth()}
               if self._native is not None else {}),
            "obs_port": obs[1] if obs is not None else None,
            "counters": {**(rec.counters() if rec is not None else {}),
                         **native_counters,
                         **((plane_snap.get("counters") or {})
                            if plane_snap is not None else {})},
            "series": series,
            # Mergeable form: pool.stats_merged() adds bucket counts
            # across workers for EXACT fleet-wide quantiles.
            "snapshot": snap,
        }

    def close(self, deadline_s: float = 120.0) -> None:
        self._closed = True
        if self._obs is not None:
            self._obs.close()
        try:
            self._sock.close()
        except OSError:
            pass
        if self._uds_path is not None:
            try:
                os.unlink(self._uds_path)
            except OSError:
                pass
        if self._native is not None:
            # Graceful-drain order: flush the ring into the batcher,
            # let the batcher finish (its close waits for in-flight
            # dispatches, whose on_done posts write the responses),
            # give the native writers a beat, then sever connections.
            self._native.stop_drain(deadline_s=min(10.0, deadline_s))
        self._batcher.close(deadline_s=deadline_s)
        if self._native is not None:
            time.sleep(0.2)
            self._native.destroy()

    # -- internals --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # socket closed
            telemetry.count("worker.connections")
            if self._native is not None:
                # Native chain: the fd moves to C++ reader/writer
                # threads; Python never sees this connection's frames.
                try:
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                except OSError:
                    pass
                self._native.add_conn(conn)
                continue
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="cap-tpu-conn").start()

    # Outstanding frames per connection before the reader stops reading
    # (backpressure then propagates to the client through TCP). Bounds
    # the memory a frame-spamming client can pin.
    _MAX_INFLIGHT = 64

    def _serve_conn(self, conn: socket.socket) -> None:
        import queue as q

        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # UDS
        # Reader/responder split: this thread KEEPS READING frames while
        # earlier submissions verify, so a client may pipeline several
        # requests on one connection; responses return strictly in
        # request order (CVB1 has no request ids — order IS the
        # correlation).
        respq: "q.Queue" = q.Queue(maxsize=self._MAX_INFLIGHT)
        responder = threading.Thread(
            target=self._respond_loop, args=(conn, respq),
            daemon=True, name="cap-tpu-respond")
        responder.start()
        # This thread owns the connection's read side exclusively, so
        # the buffered FrameReader is safe (and ~3x the throughput of
        # per-entry exact reads — the reader was the one serve stage
        # under 500k tok/s/core, docs/PERF.md r5).
        reader = protocol.FrameReader(conn)
        with self._conns_lock:
            self._conns_live += 1
            live = self._conns_live
        telemetry.gauge("serve.conns_live", float(live))
        tenant_counted = False
        try:
            while True:
                try:
                    t_recv = time.time()
                    ftype, entries, trace = reader.recv_frame_ex()
                except (ConnectionError, OSError):
                    return
                except (protocol.ProtocolError, UnicodeDecodeError):
                    # Malformed frame (attacker-spammable): drop the
                    # connection quietly instead of letting the
                    # exception escape the thread as stderr noise.
                    telemetry.count("worker.protocol_errors")
                    return
                if ftype == protocol.T_SHM_ATTACH:
                    # Transport negotiation: map the client's region
                    # and swap this connection's frame source to its
                    # request ring (responses follow through the
                    # responder's sink switch). Anything unsupported
                    # acks status 1 and the SOCKET keeps serving —
                    # the attach can never cost the client its
                    # connection.
                    shm_state = self._shm_attach(entries, respq)
                    if shm_state is None:
                        continue
                    region, consumer = shm_state
                    self._serve_shm_conn(conn, respq, region, consumer)
                    return
                if (not tenant_counted and entries
                        and ftype in (protocol.T_VERIFY_REQ,
                                      protocol.T_VERIFY_REQ_CRC,
                                      protocol.T_VERIFY_REQ_TRACE)
                        and telemetry.active() is not None):
                    # attribute the connection to its first verify
                    # frame's tenant, once (r22 connection plane)
                    tenant_counted = True
                    label = _decision.tenant_labels(entries[:1])[0]
                    telemetry.count(f"serve.tenant.{label}.conns")
                if not self._dispatch_frame(ftype, entries, trace,
                                            respq, t_recv):
                    return  # protocol violation → drop the connection
        finally:
            with self._conns_lock:
                self._conns_live -= 1
                live = self._conns_live
            telemetry.gauge("serve.conns_live", float(live))
            if reader.hwm:
                # how deep this connection's read buffering ran —
                # the per-conn memory item #3's C1M ingest must bound
                telemetry.observe("serve.conn_buffered_hwm_b",
                                  float(reader.hwm))
            respq.put(None)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch_frame(self, ftype, entries, trace, respq,
                        t_recv) -> bool:
        """Handle one parsed frame (both transports feed this): queue
        the response kind in order; False = protocol violation."""
        if ftype == protocol.T_PING:
            respq.put(("pong", None, None))
            return True
        if ftype == protocol.T_STATS_REQ:
            respq.put(("stats", None, None))
            return True
        if ftype == protocol.T_KEYS_PUSH:
            # Applied HERE, in the reader thread (the pool pushes on a
            # dedicated connection): the table build blocks only this
            # connection, and by frame order every verify request read
            # AFTER the push dispatches on the new epoch. The ack
            # rides the responder queue so in-order delivery holds.
            import json as _json

            try:
                doc = _json.loads(entries[0])
                got = self.apply_keys(doc.get("jwks") or {},
                                      doc.get("epoch"))
                respq.put(("keys_ack", got, None))
            except Exception as e:  # noqa: BLE001 - acked
                telemetry.count("worker.keys_push_errors")
                respq.put(("keys_err",
                           f"{type(e).__name__}: {e}", None))
            return True
        if ftype == protocol.T_PEER_FILL:
            # Same in-order stance as KEYS pushes: applied in the
            # reader thread, acked through the responder queue — a
            # verify read after an import sees the warmed cache.
            import json as _json

            try:
                doc = self.peer_fill(_json.loads(entries[0]))
                respq.put(("peer_ack", doc, None))
            except Exception as e:  # noqa: BLE001 - acked
                telemetry.count("worker.peer_fill_errors")
                respq.put(("peer_err",
                           f"{type(e).__name__}: {e}", None))
            return True
        if ftype not in (protocol.T_VERIFY_REQ,
                         protocol.T_VERIFY_REQ_CRC,
                         protocol.T_VERIFY_REQ_TRACE):
            return False
        telemetry.count("worker.requests")
        telemetry.count("worker.tokens", len(entries))
        # A checksummed request gets a checksummed response, a traced
        # one a traced response echoing its trace id — the fleet
        # router's end-to-end integrity envelope.
        if ftype == protocol.T_VERIFY_REQ_TRACE:
            pending = self._admitted_submit(entries, trace=trace)
            telemetry.trace_span(
                trace, telemetry.SPAN_WORKER_DEQUEUE, t_recv,
                time.time() - t_recv)
            respq.put(("batch_trace", pending, trace))
            return True
        crc = ftype == protocol.T_VERIFY_REQ_CRC
        respq.put(("batch_crc" if crc else "batch",
                   self._admitted_submit(entries), None))
        return True

    def _shm_attach(self, entries, respq):
        """Negotiate one shm attach: returns (region, consumer) on
        success (ack queued), None on a status-1 refusal (socket keeps
        serving)."""
        import json as _json

        with telemetry.span(telemetry.SPAN_SHM_ATTACH):
            try:
                if not self._shm_enabled:
                    raise TypeError("worker has no shm transport "
                                    "(transport=socket)")
                doc = _json.loads(entries[0])
                if doc.get("op") != "attach" \
                        or doc.get("version") != 1:
                    raise ValueError(
                        f"unsupported attach op/version: "
                        f"{doc.get('op')!r}/{doc.get('version')!r}")
                region = _shm.ShmRegion.open(str(doc.get("path")))
            except Exception as e:  # noqa: BLE001 - acked, never fatal
                telemetry.count("serve.shm_fallbacks")
                respq.put(("shm_err",
                           f"{type(e).__name__}: {e}", None))
                return None
            telemetry.count("serve.shm.attaches")
            # short write timeout: a client killed mid-read stops
            # consuming the response ring; the responder must give up
            # and discard, not wedge for the default 30s per frame
            producer = _shm.RingProducer(region, "resp", timeout=5.0)
            consumer = _shm.RingConsumer(region, "req")
            # the ack rides the SOCKET; every later response rides the
            # ring (the responder switches sinks on this marker)
            respq.put(("shm_ack", producer, None))
            return region, consumer

    def _serve_shm_conn(self, conn, respq, region, consumer) -> None:
        """Serve one attached connection from its request ring; the
        socket is polled as the liveness channel only. A poisoned ring
        (overrun / stale generation / malformed frame) detaches, the
        worker survives — the shm analog of dropping a bad socket."""
        import select

        try:
            while True:
                try:
                    rec = consumer.read(timeout=0.05)
                except _shm.StaleGenerationError:
                    telemetry.count("serve.shm.stale_gen")
                    telemetry.count("worker.protocol_errors")
                    return
                except (protocol.ProtocolError, ValueError):
                    telemetry.count("worker.protocol_errors")
                    return
                if rec is None:
                    if self._closed:
                        return
                    try:
                        readable, _, _ = select.select([conn], [], [], 0)
                        if readable:
                            if conn.recv(4096) == b"":
                                return       # EOF: client gone
                            # bytes on the socket after the attach:
                            # protocol violation
                            telemetry.count("worker.protocol_errors")
                            return
                    except (OSError, ValueError):
                        return
                    continue
                t_recv = time.time()
                try:
                    ftype, entries, trace, used = \
                        protocol.parse_frame_bytes(rec)
                    if used != len(rec):
                        raise protocol.MalformedFrameError(
                            "shm record carries trailing bytes")
                except (protocol.ProtocolError, UnicodeDecodeError,
                        ConnectionError):
                    telemetry.count("worker.protocol_errors")
                    return
                telemetry.count("serve.shm.frames")
                if not self._dispatch_frame(ftype, entries, trace,
                                            respq, t_recv):
                    return
        finally:
            telemetry.count("serve.shm.detaches")
            # the worker is the reliable janitor: unlink reclaims the
            # file even after the client died to kill -9 (its own
            # mapping dies with it); the responder may still hold the
            # mmap through its producer — close(unlink) only unlinks
            # the name, the mapping stays valid until close
            try:
                os.unlink(region.path)
            except OSError:
                pass

    def _admitted_submit(self, entries, trace: Optional[str] = None):
        """Token-bucket admission in front of the cache/batcher (the
        python chain's enforcement point — the native chain polices in
        its C++ readers instead). Throttled tokens get a ThrottledError
        with the retry-after pushback hint and are NEVER verified; the
        responder's decision fold counts them under reason
        ``throttled`` per tenant like any other reject."""
        adm = self._admission
        if adm is None:
            return self._cached_submit(entries, trace=trace)
        mask, retry_ms = adm.check_tokens(entries)
        if mask is None:
            return self._cached_submit(entries, trace=trace)
        from . import admission as _admission

        hits = [(_admission.throttled_error(retry_ms) if m else None)
                for m in mask]
        admit_idx = [i for i, m in enumerate(mask) if not m]
        if not admit_idx:
            return _CachePending(list(entries), hits, (), None, None)
        inner = self._cached_submit([entries[i] for i in admit_idx],
                                    trace=trace)
        return _CachePending(list(entries), hits, admit_idx, inner,
                             None)

    def _cached_submit(self, entries, trace: Optional[str] = None):
        """Consult the verdict cache, then submit only the misses.

        All-hit requests never touch the batcher (answered at memory
        speed); partial hits submit the miss subset and merge at
        respond time. Returns a pending-shaped handle either way."""
        vc = self._vcache
        if vc is None:
            return self._batcher.submit_nowait(entries, trace=trace)
        hits, miss_idx, digests = vc.lookup_batch(entries)
        if telemetry.active() is not None:
            # per-tenant cache accounting (header-segment cached —
            # one dict hit per token); the native chain counts the
            # same names from its reader-classified slots
            _decision.count_tenant_cache(
                _decision.tenant_labels(entries), miss_idx)
        if not miss_idx:
            return _CachePending(list(entries), hits, (), None, None)
        epoch0 = vc.epoch

        def fill(idxs, fresh):
            vc.insert_batch([digests[i] for i in idxs], fresh,
                            tokens=[entries[i] for i in idxs],
                            epoch=epoch0)

        inner = self._batcher.submit_nowait(
            [entries[i] for i in miss_idx], trace=trace,
            digests=[digests[i] for i in miss_idx])
        return _CachePending(list(entries), hits, miss_idx, inner, fill)

    def _respond_loop(self, conn: socket.socket, respq) -> None:
        broken = False
        # Responses go to `sink`: the socket, until an shm attach
        # swaps in the region's response-ring producer (which
        # duck-types sendall — every protocol.send_* call emits one
        # complete frame in one sendall). The attach ACK itself still
        # rides the socket, so the client confirms the switch before
        # it starts reading the ring.
        sink = conn
        while True:
            item = respq.get()
            if item is None:
                return
            if broken:
                continue              # discard; reader is winding down
            kind, pending, trace = item
            try:
                if kind == "pong":
                    protocol.send_pong(sink)
                elif kind == "shm_ack":
                    protocol.send_shm_ack(conn)
                    sink = pending    # the RingProducer
                elif kind == "shm_err":
                    protocol.send_shm_ack(conn, error=pending)
                elif kind == "keys_ack":
                    protocol.send_keys_ack(sink, epoch=pending)
                elif kind == "keys_err":
                    protocol.send_keys_ack(sink, error=pending)
                elif kind == "peer_ack":
                    protocol.send_peer_ack(sink, doc=pending)
                elif kind == "peer_err":
                    protocol.send_peer_ack(sink, error=pending)
                elif kind == "stats":
                    # Snapshot at RESPOND time (in-order with verifies
                    # on this connection, so a stats probe sent after a
                    # batch reflects that batch's accounting).
                    protocol.send_stats_response(sink, self.stats())
                else:
                    pending.event.wait()
                    # Serve-surface decision records: every verdict that
                    # leaves this worker is accounted by reason class,
                    # with the request's submit→respond latency bucket.
                    latency_s = time.monotonic() - pending.ts
                    # the stage-waterfall denominator: the occupancy
                    # plane's queue.* + device.exec_s histograms must
                    # sum to this within tolerance (docs/OBSERVABILITY
                    # §Occupancy plane, pinned by test)
                    telemetry.observe("serve.request_s", latency_s)
                    _decision.record_batch(
                        "serve", pending.results, tokens=pending.tokens,
                        latency_s=latency_s,
                        trace=trace)
                    protocol.send_response(sink, pending.results,
                                           crc=kind == "batch_crc",
                                           trace=trace)
            except (ConnectionError, OSError, TimeoutError,
                    protocol.ProtocolError):
                # Connection broke mid-response (socket) or the peer
                # stopped consuming the response ring (shm): close the
                # socket so the reader unblocks, then keep DRAINING
                # until the reader's final None — exiting early would
                # leave the reader wedged in a full-queue put().
                broken = True
                try:
                    conn.close()
                except OSError:
                    pass
