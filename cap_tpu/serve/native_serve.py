"""ctypes binding + drain loop for the native (C++) serve chain.

``serve_native.cpp`` (built into ``libcapruntime.so``) owns the
per-token serve hot path: per-connection reader threads parse and
validate CVB1 frames GIL-free and feed a bounded lock-free MPSC ring;
per-connection writer threads encode and send responses in strict
request order. Python's only per-token work is slicing the drained
token blob into strings and joining verdict payloads back into one
buffer — everything else crosses the boundary as whole batches:

    drain()  → one flat buffer of tokens + request descriptors
    batcher  → one submission per drained chunk (no per-token or
               per-request callbacks; ``AdaptiveBatcher.submit_handoff``)
    post()   → one call with every verdict of the chunk

Control frames (stats requests, keyplane KEYS pushes) ride the SAME
ring in frame order, so a keys push still applies before any verify
read after it, exactly like the Python chain's reader-thread apply.
Pings are answered natively without waking Python at all.

Raises ImportError when the library is missing or predates the serve
chain — ``VerifyWorker`` catches that and falls back to the pure
Python chain (``serve_chain == "python"``).
"""

from __future__ import annotations

import ctypes
import json
import os
import threading
import time
from typing import Any, Callable, List, Optional

import numpy as np

from .. import telemetry
from ..obs import decision as _decision
from . import protocol

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "runtime", "native", "libcapruntime.so")

_SYMBOLS = ("cap_serve_create", "cap_serve_destroy", "cap_serve_add_conn",
            "cap_serve_drain", "cap_serve_post_results",
            "cap_serve_post_raw", "cap_serve_ring_depth",
            "cap_serve_counter", "cap_serve_probe_frame",
            "cap_bench_drive")

# Telemetry-plane symbols are OPTIONAL: a stale .so that predates the
# plane still serves (the serve chain falls back to the Python
# decision fold), it just can't count natively. load() probes these
# and records the verdict on the library object.
_TEL_SYMBOLS = ("cap_tel_layout", "cap_tel_create", "cap_tel_destroy",
                "cap_tel_classify_seg", "cap_tel_learn", "cap_tel_fold",
                "cap_tel_hist_observe", "cap_tel_counters",
                "cap_tel_hist_state", "cap_tel_drain_exemplars",
                "cap_tel_reset", "cap_serve_set_telemetry",
                "cap_serve_drain_aux", "cap_serve_post_results_tel",
                "cap_serve_ring_hwm",
                # r19 tenant-attribution block: REQUIRED — a .so
                # missing these predates tenant counting and the
                # extended classify/fold signatures, so the plane
                # must disable as a whole (Python fold, counted).
                "cap_tel_layout_ten", "cap_tel_tenant_counters",
                "cap_tel_tenant_hist_state", "cap_serve_drain_tens")

# Verdict-cache digest symbols are OPTIONAL too: a stale .so without
# them still serves — the drain loop hashes in Python instead of
# riding the reader threads' sha256 (serve.native.digest_fallbacks).
_VC_SYMBOLS = ("cap_serve_set_digests", "cap_serve_drain_digests")

# Shared-memory transport symbols (shm_ring.cpp) are OPTIONAL the
# same way: a stale .so still serves sockets; an shm-transport request
# then degrades with a serve.shm_fallbacks count and attach frames
# get refused (or, truly stale, dropped — the clients redial).
_SHM_SYMBOLS = ("cap_serve_set_shm", "cap_shm_create", "cap_shm_open",
                "cap_shm_close", "cap_shm_probe", "cap_shm_write",
                "cap_shm_read", "cap_shm_drive")

# Tenant-fair scheduling + admission symbols (r20) are OPTIONAL as a
# group: a stale .so degrades to FIFO scheduling and PYTHON-side
# admission with a counted fallback (serve.native.sched_fallbacks) —
# never wrong scheduling, only slower pushback.
_SCHED_SYMBOLS = ("cap_serve_layout_sched", "cap_serve_set_fair",
                  "cap_serve_set_weight", "cap_serve_set_admission",
                  "cap_serve_set_tenant_scale", "cap_serve_adm_take",
                  "cap_serve_bucket_fill", "cap_serve_drain_thr",
                  "cap_drr_create", "cap_drr_set_weight",
                  "cap_drr_push", "cap_drr_pop", "cap_drr_destroy")

# Occupancy-plane symbols (r22) are OPTIONAL as a group: a stale .so
# still serves — queue.ring_wait_s just can't be measured from the
# reader-side enqueue stamps, and every drain that wanted them counts
# serve.native.occ_fallbacks (loud, never wrong).
_OCC_SYMBOLS = ("cap_serve_layout_occ", "cap_serve_drain_enq")

# Native relay front-door symbols (frontdoor_native.cpp, r21) are
# OPTIONAL as a group: a stale .so degrades the front-door gate to
# the pure-Python router with a counted fallback
# (frontdoor.native_fallbacks) — same routing decisions, just slower.
_FD_SYMBOLS = ("cap_frontdoor_create", "cap_frontdoor_destroy",
               "cap_frontdoor_layout", "cap_frontdoor_stage_ring",
               "cap_frontdoor_stage_pool", "cap_frontdoor_commit",
               "cap_frontdoor_set_live", "cap_frontdoor_add_conn",
               "cap_frontdoor_drain", "cap_frontdoor_post_raw",
               "cap_frontdoor_counter", "cap_frontdoor_inflight",
               "cap_frontdoor_probe_route")

# exemplar record stride (telemetry_native.h EX_STRIDE)
_EX_STRIDE = 88
_KID_LEN = 12
_DIG_LEN = 16
_ZERO_DIG = b"\x00" * _DIG_LEN

# counter slots, mirroring serve_native.cpp
CTR_CONNS = 0
CTR_FRAMES = 1
CTR_TOKENS = 2
CTR_PROTO_ERR = 3
CTR_PONGS = 4
CTR_DROPPED_POSTS = 5
CTR_CONNS_CLOSED = 6
CTR_SHM_ATTACHES = 7
CTR_SHM_FALLBACKS = 8
CTR_SHM_FRAMES = 9
CTR_SHM_STALE_GEN = 10
CTR_SHM_DETACHES = 11
CTR_ADM_CHECKED = 12
CTR_ADM_ADMITTED = 13
CTR_ADM_THROTTLED = 14

# front-door relay counter slots, mirroring frontdoor_native.cpp
FDC_CONNS = 0
FDC_FRAMES = 1
FDC_TOKENS = 2
FDC_PROTO_ERR = 3
FDC_PONGS = 4
FDC_LOOKUPS = 5
FDC_HITS = 6
FDC_RELAYS = 7
FDC_RELAY_TOKENS = 8
FDC_SPLICES = 9
FDC_SLOW_FRAMES = 10
FDC_SLOW_TOKENS = 11
FDC_UPSTREAM_FAILS = 12
FDC_SEQ_HELD_MAX = 13
FDC_DROPPED_POSTS = 14
FDC_CONNS_CLOSED = 15
FDC_N = 16
FD_MAX_POOLS = 64

# front-door slow-path handoff reasons (drain meta[1])
FD_R_CONTROL = 1
FD_R_DEAD_POOL = 2
FD_R_OVERLOAD = 3
FD_R_UPSTREAM_FAIL = 4
FD_R_UNROUTED = 5

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i8p = ctypes.POINTER(ctypes.c_int8)
_i16p = ctypes.POINTER(ctypes.c_int16)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_f64p = ctypes.POINTER(ctypes.c_double)

_lib = None
_lib_lock = threading.Lock()


def load() -> ctypes.CDLL:
    """Load (building on first use) and type-check the library; raises
    ImportError when unbuildable or stale (missing serve symbols)."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from .._build import build_native

        build_native()
        if not os.path.exists(_LIB_PATH):
            raise ImportError(f"{_LIB_PATH} not built (run: make native)")
        lib = ctypes.CDLL(_LIB_PATH)
        for sym in _SYMBOLS:
            if not hasattr(lib, sym):
                raise ImportError(
                    f"stale libcapruntime.so: missing {sym} "
                    "(run: make native-build)")
        lib.cap_serve_create.restype = ctypes.c_void_p
        lib.cap_serve_create.argtypes = [ctypes.c_int32, ctypes.c_int64]
        lib.cap_serve_destroy.argtypes = [ctypes.c_void_p]
        lib.cap_serve_add_conn.restype = ctypes.c_int32
        lib.cap_serve_add_conn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.cap_serve_ring_depth.restype = ctypes.c_int64
        lib.cap_serve_ring_depth.argtypes = [ctypes.c_void_p]
        lib.cap_serve_counter.restype = ctypes.c_int64
        lib.cap_serve_counter.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.cap_serve_drain.restype = ctypes.c_int64
        lib.cap_serve_drain.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, _u8p, ctypes.c_int64,
            _i64p, _i32p, _i64p, _f64p, _u8p, ctypes.c_int32, _i64p]
        lib.cap_serve_post_results.restype = ctypes.c_int32
        lib.cap_serve_post_results.argtypes = [
            ctypes.c_void_p, _i32p, _i64p, _u8p, ctypes.c_int32,
            _u8p, _u8p, _i64p]
        lib.cap_serve_post_raw.restype = ctypes.c_int32
        lib.cap_serve_post_raw.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64, _u8p,
            ctypes.c_int64]
        lib.cap_serve_probe_frame.restype = ctypes.c_int32
        lib.cap_serve_probe_frame.argtypes = [_u8p, ctypes.c_int64, _i64p]
        lib.cap_bench_drive.restype = ctypes.c_int32
        lib.cap_bench_drive.argtypes = [
            ctypes.c_char_p, ctypes.c_int32, _u8p, _i64p, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_double,
            ctypes.c_int32, _i64p, _i64p]
        lib.cap_tel_ok = _setup_tel(lib)
        lib.cap_vc_ok = _setup_vc(lib)
        lib.cap_shm_ok = _setup_shm(lib)
        lib.cap_sched_ok = _setup_sched(lib)
        lib.cap_fd_ok = _setup_fd(lib)
        lib.cap_occ_ok = _setup_occ(lib)
        _lib = lib
        return lib


def _setup_sched(lib: ctypes.CDLL) -> bool:
    """Type the fair-scheduling/admission symbols and verify the slot
    layout; False (FIFO + python admission, counted fallback) on a
    stale .so or any layout drift."""
    from ..obs import decision as _dec

    if not all(hasattr(lib, s) for s in _SCHED_SYMBOLS):
        return False
    lib.cap_serve_layout_sched.argtypes = [_i32p]
    lib.cap_serve_set_fair.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                       ctypes.c_int64]
    lib.cap_serve_set_weight.argtypes = [ctypes.c_void_p,
                                         ctypes.c_int32, ctypes.c_int32]
    lib.cap_serve_set_admission.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_double,
        ctypes.c_double]
    lib.cap_serve_set_tenant_scale.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_double]
    lib.cap_serve_adm_take.restype = ctypes.c_int32
    lib.cap_serve_adm_take.argtypes = [ctypes.c_void_p,
                                       ctypes.c_int32, _i32p]
    lib.cap_serve_bucket_fill.restype = ctypes.c_double
    lib.cap_serve_bucket_fill.argtypes = [ctypes.c_void_p,
                                          ctypes.c_int32]
    lib.cap_serve_drain_thr.restype = ctypes.c_int64
    lib.cap_serve_drain_thr.argtypes = [ctypes.c_void_p, _u8p,
                                        ctypes.c_int64]
    lib.cap_drr_create.restype = ctypes.c_void_p
    lib.cap_drr_create.argtypes = [ctypes.c_int64]
    lib.cap_drr_set_weight.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                       ctypes.c_int32]
    lib.cap_drr_push.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                 ctypes.c_int64]
    lib.cap_drr_pop.restype = ctypes.c_int64
    lib.cap_drr_pop.argtypes = [ctypes.c_void_p]
    lib.cap_drr_destroy.argtypes = [ctypes.c_void_p]
    layout = np.zeros(4, np.int32)
    lib.cap_serve_layout_sched(layout.ctypes.data_as(_i32p))
    want = (_dec.TENANT_CAP + 1, _dec.TENANT_CAP, _dec.N_TENANT, 15)
    return tuple(int(v) for v in layout) == want


def _setup_occ(lib: ctypes.CDLL) -> bool:
    """Type the occupancy-plane symbols and verify the per-request
    stamp layout; False (inferred ring-wait, counted fallback) on a
    stale .so or any layout drift."""
    if not all(hasattr(lib, s) for s in _OCC_SYMBOLS):
        return False
    lib.cap_serve_layout_occ.argtypes = [_i32p]
    lib.cap_serve_drain_enq.restype = ctypes.c_int64
    lib.cap_serve_drain_enq.argtypes = [ctypes.c_void_p, _f64p,
                                        ctypes.c_int64]
    layout = np.zeros(2, np.int32)
    lib.cap_serve_layout_occ(layout.ctypes.data_as(_i32p))
    return tuple(int(v) for v in layout) == (1, 1)


def _setup_fd(lib: ctypes.CDLL) -> bool:
    """Type the relay front-door symbols and verify the layout
    handshake; False (pure-Python front door, counted fallback) on a
    stale .so or any constant drift."""
    if not all(hasattr(lib, s) for s in _FD_SYMBOLS):
        return False
    lib.cap_frontdoor_create.restype = ctypes.c_void_p
    lib.cap_frontdoor_create.argtypes = []
    lib.cap_frontdoor_destroy.argtypes = [ctypes.c_void_p]
    lib.cap_frontdoor_layout.argtypes = [_i32p]
    lib.cap_frontdoor_stage_ring.restype = ctypes.c_int32
    lib.cap_frontdoor_stage_ring.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64), _i32p,
        ctypes.c_int64]
    lib.cap_frontdoor_stage_pool.restype = ctypes.c_int32
    lib.cap_frontdoor_stage_pool.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
        ctypes.c_int32]
    lib.cap_frontdoor_commit.restype = ctypes.c_int32
    lib.cap_frontdoor_commit.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_double]
    lib.cap_frontdoor_set_live.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
    lib.cap_frontdoor_add_conn.restype = ctypes.c_int32
    lib.cap_frontdoor_add_conn.argtypes = [ctypes.c_void_p,
                                           ctypes.c_int32]
    lib.cap_frontdoor_drain.restype = ctypes.c_int32
    lib.cap_frontdoor_drain.argtypes = [
        ctypes.c_void_p, ctypes.c_double, _u8p, ctypes.c_int64, _i64p,
        _i32p, _i64p, ctypes.c_int32, _i64p]
    lib.cap_frontdoor_post_raw.restype = ctypes.c_int32
    lib.cap_frontdoor_post_raw.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64, _u8p,
        ctypes.c_int64]
    lib.cap_frontdoor_counter.restype = ctypes.c_int64
    lib.cap_frontdoor_counter.argtypes = [ctypes.c_void_p,
                                          ctypes.c_int32]
    lib.cap_frontdoor_inflight.restype = ctypes.c_int64
    lib.cap_frontdoor_inflight.argtypes = [ctypes.c_void_p,
                                           ctypes.c_int32]
    lib.cap_frontdoor_probe_route.restype = ctypes.c_int32
    lib.cap_frontdoor_probe_route.argtypes = [
        ctypes.c_void_p, _u8p, ctypes.c_int32, _i32p]
    layout = np.zeros(4, np.int32)
    lib.cap_frontdoor_layout(layout.ctypes.data_as(_i32p))
    want = (FD_MAX_POOLS, FDC_N, 1, _DIG_LEN)
    return tuple(int(v) for v in layout) == want


def _setup_shm(lib: ctypes.CDLL) -> bool:
    """Type the shm-transport symbols; False (socket-only serving,
    attach requests refused) on a stale .so."""
    if not all(hasattr(lib, s) for s in _SHM_SYMBOLS):
        return False
    lib.cap_serve_set_shm.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.cap_shm_create.restype = ctypes.c_void_p
    lib.cap_shm_create.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                   ctypes.c_int64, ctypes.c_int32]
    lib.cap_shm_open.restype = ctypes.c_void_p
    lib.cap_shm_open.argtypes = [ctypes.c_char_p]
    lib.cap_shm_close.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.cap_shm_probe.restype = ctypes.c_int32
    lib.cap_shm_probe.argtypes = [ctypes.c_char_p]
    lib.cap_shm_write.restype = ctypes.c_int64
    lib.cap_shm_write.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                  _u8p, ctypes.c_int64,
                                  ctypes.c_double]
    lib.cap_shm_read.restype = ctypes.c_int64
    lib.cap_shm_read.argtypes = [ctypes.c_void_p, ctypes.c_int32,
                                 _u8p, ctypes.c_int64, ctypes.c_double]
    lib.cap_shm_drive.restype = ctypes.c_int32
    lib.cap_shm_drive.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_char_p, _u8p, _i64p,
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_double, ctypes.c_int32, ctypes.c_int64, _i64p, _i64p]
    return True


def _setup_vc(lib: ctypes.CDLL) -> bool:
    """Type the verdict-cache digest symbols; False (Python-side
    hashing fallback, serve chain unaffected) on a stale .so."""
    if not all(hasattr(lib, s) for s in _VC_SYMBOLS):
        return False
    lib.cap_serve_set_digests.argtypes = [ctypes.c_void_p,
                                          ctypes.c_int32]
    lib.cap_serve_drain_digests.restype = ctypes.c_int64
    lib.cap_serve_drain_digests.argtypes = [ctypes.c_void_p, _u8p,
                                            ctypes.c_int64]
    return True


def _setup_tel(lib: ctypes.CDLL) -> bool:
    """Type the telemetry-plane symbols; False (plane disabled, serve
    chain unaffected) when the .so predates the plane or its index
    vocabularies no longer match the Python registries."""
    from ..obs import decision as _dec

    if not all(hasattr(lib, s) for s in _TEL_SYMBOLS):
        return False
    lib.cap_tel_layout.argtypes = [_i32p]
    lib.cap_tel_layout_ten.argtypes = [_i32p]
    lib.cap_tel_create.restype = ctypes.c_void_p
    lib.cap_tel_create.argtypes = [_f64p, ctypes.c_int32]
    lib.cap_tel_destroy.argtypes = [ctypes.c_void_p]
    lib.cap_tel_classify_seg.restype = ctypes.c_int32
    lib.cap_tel_classify_seg.argtypes = [
        ctypes.c_void_p, _u8p, ctypes.c_int64, _u8p, _i32p, _i16p]
    lib.cap_tel_learn.argtypes = [
        ctypes.c_void_p, _u8p, ctypes.c_int64, ctypes.c_int32, _u8p,
        ctypes.c_int32, ctypes.c_int32]
    lib.cap_tel_fold.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, _u8p, _u8p, _i8p, _i16p,
        _u8p, ctypes.c_int32, ctypes.c_double, _u8p, ctypes.c_int32]
    lib.cap_tel_hist_observe.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_double]
    lib.cap_tel_counters.argtypes = [ctypes.c_void_p, _i64p]
    lib.cap_tel_tenant_counters.argtypes = [ctypes.c_void_p, _i64p]
    lib.cap_tel_hist_state.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _i64p, _i64p, _f64p, _f64p,
        _f64p]
    lib.cap_tel_tenant_hist_state.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _i64p, _i64p, _f64p, _f64p,
        _f64p]
    lib.cap_tel_drain_exemplars.restype = ctypes.c_int32
    lib.cap_tel_drain_exemplars.argtypes = [
        ctypes.c_void_p, _u8p, ctypes.c_int32]
    lib.cap_tel_reset.argtypes = [ctypes.c_void_p]
    lib.cap_serve_set_telemetry.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p]
    lib.cap_serve_drain_aux.restype = ctypes.c_int64
    lib.cap_serve_drain_aux.argtypes = [
        ctypes.c_void_p, _i8p, _u8p, ctypes.c_int64]
    lib.cap_serve_drain_tens.restype = ctypes.c_int64
    lib.cap_serve_drain_tens.argtypes = [
        ctypes.c_void_p, _i16p, ctypes.c_int64]
    lib.cap_serve_post_results_tel.restype = ctypes.c_int32
    lib.cap_serve_post_results_tel.argtypes = [
        ctypes.c_void_p, _i32p, _i64p, _u8p, _f64p, ctypes.c_int32,
        _u8p, _u8p, _i64p, _u8p, _i8p, _i16p, _u8p, ctypes.c_int32,
        ctypes.c_double]
    lib.cap_serve_ring_hwm.restype = ctypes.c_int64
    lib.cap_serve_ring_hwm.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    # layout handshake: reason/family/latency vocabularies are indexed
    # in the C structs; any drift must disable the plane, not miscount
    layout = np.zeros(8, np.int32)
    lib.cap_tel_layout(layout.ctypes.data_as(_i32p))
    want = (len(_dec.REASON_INDEX), len(_dec.FAMILIES),
            len(_dec.LAT_BUCKET_INDEX),
            1 + len(_dec.REASON_INDEX) + len(_dec.FAMILIES) + 3,
            _EX_STRIDE, 2, _dec.RING_SAMPLE_EVERY,
            telemetry.MAX_DECISION_ENTRIES)
    if tuple(int(v) for v in layout) != want:
        return False
    # tenant-block handshake (r19): the bounded tenant table's slot
    # layout is ABI too — drift disables the plane the same way
    ten_stride = 3 + len(_dec.REASON_INDEX)
    layout_ten = np.zeros(4, np.int32)
    lib.cap_tel_layout_ten(layout_ten.ctypes.data_as(_i32p))
    want_ten = (_dec.N_TENANT, ten_stride,
                3 + _dec.N_TENANT * ten_stride, _dec.TENANT_OTHER_IDX)
    return tuple(int(v) for v in layout_ten) == want_ten


def probe_frame(data: bytes) -> int:
    """Classify one complete frame with the NATIVE parser → PF status
    (0 ok; see protocol.NATIVE_STATUS_ERRORS for the class map). The
    malformed-frame parity sweep drives this against
    ``protocol.parse_frame_bytes``."""
    lib = load()
    buf = np.frombuffer(bytearray(data), np.uint8) if data else \
        np.zeros(1, np.uint8)
    return int(lib.cap_serve_probe_frame(
        buf.ctypes.data_as(_u8p), len(data), None))


class NativeTelemetryPlane:
    """Binding for the native telemetry plane (telemetry_native.cpp).

    The plane holds the serve surface's decision counters, log-bucket
    histograms, and sampled-exemplar ring in a plain C struct region
    the GIL never touches. This class is the Python edge of it:

    - ``fix_misses`` resolves header-cache misses with the REAL
      classifier (``obs/decision._seg_family_kid``) and teaches the
      native cache — family attribution is therefore bit-exact by
      construction, the cache only ever holds Python-computed values;
    - ``pump`` drains sampled exemplars into the active recorder's
      decision ring (same entries ``record_batch`` would have built);
    - ``snapshot`` emits the plane's state as a MERGEABLE telemetry
      snapshot — scrape paths fold it in with ``merge_snapshots``, so
      fleet quantiles and counter totals stay exact.

    Standalone use (``fold_batch``) exists for the fuzz parity sweep:
    it drives the same classify → learn → fold path the serve chain
    uses, without sockets.
    """

    SERIES_NAMES = ("serve.native.request_s", "serve.native.chunk_tokens")
    _FAM_UNKNOWN = len(_decision.FAMILIES) - 1

    def __init__(self, lib: Optional[ctypes.CDLL] = None):
        self._lib = lib if lib is not None else load()
        if not getattr(self._lib, "cap_tel_ok", False):
            raise ImportError(
                "libcapruntime.so lacks the telemetry plane "
                "(stale build — run: make native-build)")
        bounds = np.asarray(telemetry.BUCKET_BOUNDS, np.float64)
        self._n_buckets = len(bounds) + 1
        self._h: Optional[ctypes.c_void_p] = ctypes.c_void_p(
            self._lib.cap_tel_create(bounds.ctypes.data_as(_f64p),
                                     len(bounds)))
        if not self._h:
            raise ImportError("cap_tel_create failed")
        # True until attached to a serve handle (which then owns the
        # free); standalone planes free themselves in destroy().
        self._owned = True
        self._fam_to_idx = {f: i for i, f
                            in enumerate(_decision.FAMILIES)}
        n_reason = len(_decision.REASON_INDEX)
        self._ctr_names = (
            ["decision.serve.accept"]
            + [f"decision.serve.reject.{r}"
               for r in _decision.REASON_INDEX]
            + [f"decision.serve.family.{f}" for f in _decision.FAMILIES]
            + ["serve.native.hdr_cache_hits",
               "serve.native.hdr_cache_misses",
               "serve.native.exemplar_drops"])
        self._n_ctr = len(self._ctr_names)
        self._n_reason = n_reason
        self._ctr_buf = np.zeros(self._n_ctr, np.int64)
        # tenant counter block (telemetry_native.h TEN_* layout): 3
        # globals + per-slot [tokens, accept, reject, reject.<r>…];
        # slots map back to issuer-hash labels via decision.TENANTS
        # at scrape time, so names match the Python fold exactly
        self._ten_stride = 3 + n_reason
        self._n_tctr = 3 + _decision.N_TENANT * self._ten_stride
        self._tctr_buf = np.zeros(self._n_tctr, np.int64)
        self._ex_buf = np.zeros(
            telemetry.MAX_DECISION_ENTRIES * _EX_STRIDE, np.uint8)
        self._bucket_buf = np.zeros(self._n_buckets, np.int64)
        self._pump_lock = threading.Lock()
        # captured at teardown so the sigterm-drain postmortem (which
        # checkpoints AFTER the native side is destroyed) still
        # carries everything the plane ever counted
        self._final_snapshot: Optional[dict] = None

    # -- classification ---------------------------------------------------

    def classify_seg(self, seg_bytes: bytes):
        """(fam_idx, kid, tenant_slot) via the NATIVE cache; fam_idx
        -1 = miss (tenant then unresolved too)."""
        if not self._h:
            return (-1, None, -1)
        if not seg_bytes:
            return (self._FAM_UNKNOWN, None, _decision.TENANT_NONE_IDX)
        buf = np.frombuffer(seg_bytes, np.uint8)
        kid_out = np.zeros(_KID_LEN, np.uint8)
        kid_len = ctypes.c_int32(0)
        ten = ctypes.c_int16(-1)
        fam = int(self._lib.cap_tel_classify_seg(
            self._h, buf.ctypes.data_as(_u8p), len(seg_bytes),
            kid_out.ctypes.data_as(_u8p), ctypes.byref(kid_len),
            ctypes.byref(ten)))
        kid = (kid_out[: kid_len.value].tobytes().decode("ascii")
               if kid_len.value else None)
        return (fam, kid, int(ten.value) if fam >= 0 else -1)

    def learn(self, seg_bytes: bytes, fam_idx: int,
              kid: Optional[str], ten_idx: int) -> None:
        if not self._h or not seg_bytes:
            return
        buf = np.frombuffer(seg_bytes, np.uint8)
        kb = np.frombuffer(kid.encode(), np.uint8) if kid else None
        self._lib.cap_tel_learn(
            self._h, buf.ctypes.data_as(_u8p), len(seg_bytes), fam_idx,
            kb.ctypes.data_as(_u8p) if kb is not None else None,
            _KID_LEN if kid else 0, int(ten_idx))

    def fix_misses(self, tokens, fams: np.ndarray, kids: np.ndarray,
                   tens: Optional[np.ndarray] = None) -> None:
        """Resolve header-cache misses (fam < 0) with the Python
        classifier and teach the native cache — cold headers cost one
        Python parse (header AND, for the tenant, the first such
        token's payload — decision._seg_fkt) per DISTINCT header, then
        hit natively forever. Per-chunk the first miss of a segment
        resolves it; later same-segment tokens reuse that resolution,
        exactly like record_batch's per-distinct-segment pass."""
        seen: dict = {}
        for i in np.nonzero(fams < 0)[0]:
            tok = tokens[i]
            seg = tok.split(".", 1)[0] if isinstance(tok, str) else None
            hit = seen.get(seg) if isinstance(seg, str) else None
            if hit is None:
                fam_name, kid, ten_label = _decision._seg_fkt(seg, tok)
                hit = (self._fam_to_idx[fam_name], kid,
                       _decision.tenant_index(ten_label))
                if isinstance(seg, str) and 0 < len(seg) <= 1024:
                    seen[seg] = hit
                    self.learn(seg.encode("utf-8"), hit[0], kid,
                               hit[2])
            fams[i] = hit[0]
            if hit[1]:
                kids[i * _KID_LEN:(i + 1) * _KID_LEN] = \
                    np.frombuffer(hit[1].encode(), np.uint8)
            if tens is not None:
                tens[i] = hit[2]

    # -- standalone fold (the parity sweep's entry point) -----------------

    def fold_batch(self, results, tokens=None, latency_s=None,
                   trace=None) -> None:
        """Drive one batch through the native fold exactly as the
        serve chain would: classify (native cache → Python on miss),
        statuses from the verify contract, reasons via the indexed
        classifier, one cap_tel_fold call."""
        n = len(results)
        if n == 0 or not self._h:
            return
        fams = np.full(n, -1, np.int8)
        kids = np.zeros(n * _KID_LEN, np.uint8)
        tens = np.full(n, -1, np.int16)
        if tokens is not None:
            for i, t in enumerate(tokens):
                if not isinstance(t, str):
                    fams[i] = self._FAM_UNKNOWN
                    tens[i] = _decision.TENANT_NONE_IDX
                    continue
                fam, kid, ten = self.classify_seg(
                    t.split(".", 1)[0].encode("utf-8"))
                if fam >= 0:
                    fams[i] = fam
                    tens[i] = ten
                    if kid:
                        kids[i * _KID_LEN:(i + 1) * _KID_LEN] = \
                            np.frombuffer(kid.encode(), np.uint8)
            if (fams < 0).any():
                self.fix_misses(tokens, fams, kids, tens)
        else:
            fams[:] = self._FAM_UNKNOWN
            tens[:] = _decision.TENANT_NONE_IDX
        statuses = np.zeros(n, np.uint8)
        reasons = None
        for i, r in enumerate(results):
            if isinstance(r, BaseException):
                if reasons is None:
                    reasons = np.zeros(n, np.uint8)
                statuses[i] = 1
                reasons[i] = _decision.reason_index(r)
        lat_idx = _decision.latency_bucket_index(latency_s)
        tb = np.frombuffer(trace.encode(), np.uint8) \
            if trace else None
        self._lib.cap_tel_fold(
            self._h, n, statuses.ctypes.data_as(_u8p),
            reasons.ctypes.data_as(_u8p) if reasons is not None
            else None,
            fams.ctypes.data_as(_i8p), tens.ctypes.data_as(_i16p),
            kids.ctypes.data_as(_u8p), lat_idx,
            -1.0 if latency_s is None else float(latency_s),
            tb.ctypes.data_as(_u8p) if tb is not None else None,
            len(tb) if tb is not None else 0)

    # -- scrape side ------------------------------------------------------

    def pump(self, rec: Optional[telemetry.Recorder] = None) -> int:
        """Drain queued exemplars into the recorder's decision ring;
        returns how many entries crossed."""
        if rec is None:
            rec = telemetry.active()
        h = self._h
        if rec is None or not h:
            return 0
        with self._pump_lock:
            n = int(self._lib.cap_tel_drain_exemplars(
                h, self._ex_buf.ctypes.data_as(_u8p),
                telemetry.MAX_DECISION_ENTRIES))
            if not n:
                return 0
            entries = []
            buf = self._ex_buf
            for i in range(n):
                r = buf[i * _EX_STRIDE:(i + 1) * _EX_STRIDE]
                kid_len = int(r[3])
                kid = (r[4:4 + kid_len].tobytes().decode("ascii")
                       if kid_len else None)
                trace_len = int(r[16])
                trace = (r[17:17 + trace_len].tobytes().decode("ascii")
                         if trace_len else None)
                entries.append(_decision.entry_from_exemplar(
                    int(r[0]), int(r[1]), int(r[2]), kid, trace))
        rec.decision_many(entries)
        return n

    def counters(self):
        """Nonzero plane counters under their registered names (the
        final pre-teardown values once destroyed) — including the
        per-tenant block, with native slots mapped back to issuer-hash
        labels so the names match the Python fold exactly."""
        h = self._h
        if not h:
            return dict((self._final_snapshot or {}).get("counters")
                        or {})
        self._lib.cap_tel_counters(h,
                                   self._ctr_buf.ctypes.data_as(_i64p))
        out = {name: int(v) for name, v
               in zip(self._ctr_names, self._ctr_buf) if v}
        self._lib.cap_tel_tenant_counters(
            h, self._tctr_buf.ctypes.data_as(_i64p))
        tb = self._tctr_buf
        for name, v in zip(("tenant.lookups", "tenant.attributed",
                            "tenant.overflow"), tb[:3]):
            if v:
                out[name] = int(v)
        if tb[3:].any():
            labels = _decision.TENANTS.labels()
            stride = self._ten_stride
            for slot in range(_decision.N_TENANT):
                base = 3 + slot * stride
                if not tb[base]:
                    continue
                t = labels.get(slot, _decision.TENANT_OTHER)
                prefix = f"decision.serve.tenant.{t}"
                out[f"{prefix}.tokens"] = int(tb[base])
                if tb[base + 1]:
                    out[f"{prefix}.accept"] = int(tb[base + 1])
                if tb[base + 2]:
                    out[f"{prefix}.reject"] = int(tb[base + 2])
                for j, reason in enumerate(_decision.REASON_INDEX):
                    if tb[base + 3 + j]:
                        out[f"{prefix}.reject.{reason}"] = \
                            int(tb[base + 3 + j])
        return out

    def _hist_state(self, series: int, tenant_slot: bool = False):
        count = np.zeros(1, np.int64)
        smm = np.zeros(3, np.float64)
        fn = (self._lib.cap_tel_tenant_hist_state if tenant_slot
              else self._lib.cap_tel_hist_state)
        fn(self._h, series, self._bucket_buf.ctypes.data_as(_i64p),
           count.ctypes.data_as(_i64p),
           smm[0:].ctypes.data_as(_f64p),
           smm[1:].ctypes.data_as(_f64p),
           smm[2:].ctypes.data_as(_f64p))
        return {"count": int(count[0]), "sum": float(smm[0]),
                "min": float(smm[1]), "max": float(smm[2]),
                "buckets": {str(i): int(c) for i, c
                            in enumerate(self._bucket_buf) if c}}

    def snapshot(self):
        """telemetry.Recorder.snapshot()-shaped state: scrape paths
        merge it with the Python recorder's via merge_snapshots.
        After teardown, the final pre-destroy snapshot is served."""
        if not self._h:
            return dict(self._final_snapshot
                        or {"v": 1, "counters": {}, "gauges": {},
                            "series": {}})
        series = {}
        for idx, name in enumerate(self.SERIES_NAMES):
            st = self._hist_state(idx)
            if st["count"]:
                series[name] = st
        # per-tenant latency series under the fold's exact names
        # (tenant.<label>.request_s), slot → label like counters()
        labels = None
        for slot in range(_decision.N_TENANT):
            st = self._hist_state(slot, tenant_slot=True)
            if not st["count"]:
                continue
            if labels is None:
                labels = _decision.TENANTS.labels()
            t = labels.get(slot, _decision.TENANT_OTHER)
            series[f"tenant.{t}.request_s"] = st
        return {"v": 1, "counters": self.counters(), "gauges": {},
                "series": series}

    def observe(self, series: int, value: float) -> None:
        if self._h:
            self._lib.cap_tel_hist_observe(self._h, series,
                                           float(value))

    def reset(self) -> None:
        if self._h:
            self._lib.cap_tel_reset(self._h)

    def destroy(self) -> None:
        h, self._h = self._h, None
        if h and self._owned:
            self._lib.cap_tel_destroy(h)


class NativeServeChain:
    """One worker's native frame-I/O front end.

    batcher: the worker's AdaptiveBatcher (must expose
    ``submit_handoff``). stats_fn / keys_fn: the worker's control-op
    handlers (``VerifyWorker.stats`` / ``VerifyWorker.apply_keys``).
    """

    _META_STRIDE = 6

    def __init__(self, batcher, stats_fn: Callable[[], dict],
                 keys_fn: Callable[[dict, Any], int],
                 peer_fill_fn: Optional[Callable[[dict], dict]] = None,
                 target_batch: int = 4096, max_wait_ms: float = 2.0,
                 max_batch: int = 32768, vcache=None,
                 shm: bool = False, admission=None):
        self._lib = load()
        self._batcher = batcher
        self._stats_fn = stats_fn
        self._keys_fn = keys_fn
        self._peer_fill_fn = peer_fill_fn
        self._target = max(1, target_batch)
        self._h = ctypes.c_void_p(self._lib.cap_serve_create(
            4096, 4 * max_batch))
        if not self._h:
            raise ImportError("cap_serve_create failed")
        # Shared-memory transport: arm attach negotiation in the C++
        # readers when requested AND the library carries the shm TU; a
        # stale .so degrades to socket-only serving with a counted
        # fallback (the clients negotiate the same degradation).
        self.shm_armed = False
        if shm and getattr(self._lib, "cap_shm_ok", False):
            self._lib.cap_serve_set_shm(self._h, 1)
            self.shm_armed = True
        elif shm:
            telemetry.count("serve.shm_fallbacks")
        # Verdict cache (the worker's instance — one cache serves both
        # chains, so the worker's apply_keys invalidation hook covers
        # this chain too). When the library carries the digest symbols
        # the C readers sha256 each token at frame-parse time and the
        # drain picks the digests up next to fams/kids — zero Python
        # hashing on the hot path; otherwise lookup_batch hashes in
        # Python (counted, visible).
        self._vcache = vcache
        # A digest-routed engine underneath (the front-door router)
        # consumes reader digests through the batcher even when this
        # worker's own cache tier is off.
        wants_digests = (vcache is not None
                         or getattr(batcher, "_wants_digests", False))
        self._native_digests = False
        if wants_digests and getattr(self._lib, "cap_vc_ok", False):
            self._lib.cap_serve_set_digests(self._h, 1)
            self._native_digests = True
        elif wants_digests:
            telemetry.count("serve.native.digest_fallbacks")
        # Native telemetry plane: on when telemetry is enabled, the
        # library carries the plane symbols, and CAP_SERVE_NATIVE_OBS
        # isn't 0. Any failure degrades to the Python decision fold
        # (visible via serve.native.obs_fallbacks) — never to silence.
        self._plane = None
        if (telemetry.active() is not None
                and os.environ.get("CAP_SERVE_NATIVE_OBS", "1") != "0"):
            try:
                plane = NativeTelemetryPlane(self._lib)
                self._lib.cap_serve_set_telemetry(self._h, plane._h)
                plane._owned = False   # freed with the serve handle
                self._plane = plane
            except Exception:  # noqa: BLE001 - fall back, visibly
                telemetry.count("serve.native.obs_fallbacks")
                self._plane = None
        # Occupancy plane (r22): when the library carries the occ
        # group the drain copies the reader-side enqueue stamps out
        # next to req_t0 and queue.ring_wait_s is MEASURED (steady
        # clock both sides). A stale .so degrades to no ring-wait
        # histogram with a per-drain serve.native.occ_fallbacks count.
        self._occ_native = bool(getattr(self._lib, "cap_occ_ok", False))
        self._occ_n = 0
        # conn ids already attributed to a tenant (r22 connection
        # plane); bounded — a clear on overflow re-attributes at most
        # one extra count per long-lived conn
        self._conn_tenants_seen: set = set()
        # Tenant-fair DRR scheduling + token-bucket admission (r20):
        # armed NATIVELY (the C++ readers police, the drain pops DRR)
        # when the library carries the sched group, else the counted
        # degradation — FIFO pop order + PYTHON-side admission in
        # _submit_segment. Either way the wire behavior (throttled
        # rejects with retry-after pushback) is identical; only the
        # enforcement point moves.
        self.fair_native = False
        self.adm_native = False
        self._py_admission = None
        self._shed: dict = {}               # tenant label → scale
        if admission is not None and (admission.fair
                                      or admission.admission_on):
            if getattr(self._lib, "cap_sched_ok", False):
                if admission.fair:
                    self._lib.cap_serve_set_fair(
                        self._h, 1, int(admission.quantum or 0))
                    for label, w in admission.weights.items():
                        self.set_weight(label, w)
                    self.fair_native = True
                if admission.admission_on:
                    self._lib.cap_serve_set_admission(
                        self._h, 1, float(admission.rate),
                        float(admission.burst))
                    self.adm_native = True
            else:
                telemetry.count("serve.native.sched_fallbacks")
                if admission.admission_on:
                    from . import admission as _adm

                    self._py_admission = _adm.AdmissionController(
                        admission.rate, admission.burst)
        self._final_counters: dict = {}     # captured at destroy
        self._stop = threading.Event()
        self._drained = threading.Event()   # ring empty after stop
        # drain buffers (grown on demand when a giant frame arrives)
        self._alloc(max_tokens=max_batch, blob_cap=8 << 20,
                    max_reqs=4096)
        self._thread = threading.Thread(
            target=self._drain_loop, daemon=True,
            name="cap-tpu-native-drain")
        self._thread.start()

    def _alloc(self, max_tokens: int, blob_cap: int,
               max_reqs: int) -> None:
        self._max_tokens = max_tokens
        self._blob_cap = blob_cap
        self._max_reqs = max_reqs
        self._tok_blob = np.empty(blob_cap, np.uint8)
        self._tok_off = np.zeros(max_tokens + 1, np.int64)
        self._req_meta = np.zeros(max_reqs * self._META_STRIDE, np.int32)
        self._req_seq = np.zeros(max_reqs, np.int64)
        self._req_t0 = np.zeros(max_reqs, np.float64)
        self._trace_buf = np.zeros(max_reqs * 64, np.uint8)
        self._out_counts = np.zeros(3, np.int64)
        # telemetry plane: per-token (family idx, kid hash, tenant
        # slot) of the last drain, classified by the native readers
        self._fam_buf = np.full(max_tokens, -1, np.int8)
        self._kid_buf = np.zeros(max_tokens * _KID_LEN, np.uint8)
        self._ten_buf = np.full(max_tokens, -1, np.int16)
        # verdict cache: per-token digest of the last drain (sha256
        # truncated, computed by the native readers; all-zero rows
        # fall back to Python hashing)
        self._dig_buf = np.zeros(max_tokens * _DIG_LEN, np.uint8)
        # admission: per-token throttle verdicts of the last drain
        # (1 = over budget — answer with pushback, never verify)
        self._thr_buf = np.zeros(max_tokens, np.uint8)
        # occupancy: per-REQUEST reader-side enqueue stamps (steady-
        # clock seconds) of the last drain
        self._enq_buf = np.zeros(max_reqs, np.float64)

    # -- connection handoff ------------------------------------------------

    def add_conn(self, conn) -> int:
        """Take ownership of an accepted socket: its fd moves to the
        native reader/writer threads (the Python socket object is
        detached and must not be used again)."""
        fd = conn.detach()
        cid = int(self._lib.cap_serve_add_conn(self._h, fd))
        if cid < 0:
            os.close(fd)
        return cid

    # -- stats surface -----------------------------------------------------

    def ring_depth(self) -> int:
        h = self._h
        if not h:               # destroyed (post-drain stats snapshot)
            return 0
        return int(self._lib.cap_serve_ring_depth(h))

    def ring_hwm(self, reset: bool = True) -> int:
        """Ring high-water mark since the last scrape (native-side
        max of queued tokens — drain-time sampling misses bursts);
        reset=True rearms the mark at the current depth."""
        h = self._h
        if not h or not getattr(self._lib, "cap_tel_ok", False):
            return 0
        return int(self._lib.cap_serve_ring_hwm(h, 1 if reset else 0))

    @property
    def obs_plane(self) -> Optional[NativeTelemetryPlane]:
        """The attached native telemetry plane (None: Python fold)."""
        return self._plane

    # -- fair scheduling / admission (r20) ---------------------------------

    @staticmethod
    def _sched_slot(label: str) -> int:
        """Tenant label → DRR slot (best-effort for none/other/"be")."""
        if label == "be":
            return _decision.TENANT_CAP
        idx = _decision.tenant_index(label)
        return idx if 0 <= idx < _decision.TENANT_CAP \
            else _decision.TENANT_CAP

    def set_weight(self, label: str, w: int) -> None:
        """Per-tenant DRR weight (label = issuer hash, or "be" for the
        shared best-effort slot). No-op without the sched group."""
        if getattr(self._lib, "cap_sched_ok", False) and self._h:
            self._lib.cap_serve_set_weight(self._h,
                                           self._sched_slot(label),
                                           int(w))

    def set_tenant_scale(self, label: str, scale: float) -> None:
        """Shed lever: scale one tenant's admission rate (1.0
        restores). Reaches whichever enforcement point runs — the
        native buckets or the python fallback controller."""
        scale = max(0.0, float(scale))
        if self.adm_native and self._h:
            self._lib.cap_serve_set_tenant_scale(
                self._h, _decision.tenant_index(label), scale)
        if self._py_admission is not None:
            self._py_admission.set_scale(label, scale)
        if scale < 1.0:
            self._shed[label] = scale
        else:
            self._shed.pop(label, None)

    @property
    def shed_state(self) -> dict:
        """Currently shed tenants (label → rate scale)."""
        return dict(self._shed)

    def admission_fill(self, label: str) -> Optional[float]:
        """One tenant bucket's current level in tokens (None when
        admission is not natively armed)."""
        if not self.adm_native or not self._h:
            if self._py_admission is not None:
                return self._py_admission.fill(label)
            return None
        return float(self._lib.cap_serve_bucket_fill(
            self._h, _decision.tenant_index(label)))

    def counters(self) -> dict:
        h = self._h
        if not h:               # destroyed: serve the final values
            return dict(self._final_counters)  # (postmortem freshness)
        return self._read_counters(h)

    def _read_counters(self, h) -> dict:
        c = self._lib.cap_serve_counter
        out = {
            "serve.native.connections": int(c(h, CTR_CONNS)),
            "serve.native.frames": int(c(h, CTR_FRAMES)),
            "serve.native.tokens": int(c(h, CTR_TOKENS)),
            "serve.native.protocol_errors": int(c(h, CTR_PROTO_ERR)),
            "serve.native.pongs": int(c(h, CTR_PONGS)),
            "serve.native.dropped_posts": int(c(h, CTR_DROPPED_POSTS)),
            "serve.native.connections_closed":
                int(c(h, CTR_CONNS_CLOSED)),
        }
        if getattr(self._lib, "cap_shm_ok", False):
            # shm-transport slots exist in this .so (additive; a stale
            # library would return -1 for them)
            out["serve.shm.attaches"] = int(c(h, CTR_SHM_ATTACHES))
            out["serve.shm_fallbacks"] = int(c(h, CTR_SHM_FALLBACKS))
            out["serve.shm.frames"] = int(c(h, CTR_SHM_FRAMES))
            out["serve.shm.stale_gen"] = int(c(h, CTR_SHM_STALE_GEN))
            out["serve.shm.detaches"] = int(c(h, CTR_SHM_DETACHES))
        if getattr(self._lib, "cap_sched_ok", False):
            # admission slots (r20, additive like shm): exposed under
            # the EXACT names the python AdmissionController counts,
            # so fleet merges and the obs-smoke equality gate are
            # chain-agnostic. Zeros stay out (a python-chain worker
            # with admission off has no such counters either).
            for name, slot in (("admission.checked", CTR_ADM_CHECKED),
                               ("admission.admitted",
                                CTR_ADM_ADMITTED),
                               ("admission.throttled",
                                CTR_ADM_THROTTLED)):
                v = int(c(h, slot))
                if v:
                    out[name] = v
        return out

    # -- drain loop --------------------------------------------------------

    def _drain_loop(self) -> None:
        lib = self._lib
        h = self._h
        while True:
            stopping = self._stop.is_set()
            # GREEDY drain: block until at least one request is queued
            # (idle wait), then take everything available and return —
            # the drain layer adds NO batching window of its own; the
            # AdaptiveBatcher below owns the latency/throughput
            # tradeoff, exactly as on the Python chain. Under load the
            # ring refills while Python processes the previous chunk,
            # so chunks grow toward max_tokens by themselves.
            rc = int(lib.cap_serve_drain(
                h, self._max_tokens, self._max_tokens,
                0.0,
                # short idle wait while serving (cheap wakeups keep
                # close() responsive); near-zero when draining out
                0.0 if stopping else 0.05,
                self._tok_blob.ctypes.data_as(_u8p), self._blob_cap,
                self._tok_off.ctypes.data_as(_i64p),
                self._req_meta.ctypes.data_as(_i32p),
                self._req_seq.ctypes.data_as(_i64p),
                self._req_t0.ctypes.data_as(_f64p),
                self._trace_buf.ctypes.data_as(_u8p),
                self._max_reqs,
                self._out_counts.ctypes.data_as(_i64p)))
            if rc == -2:
                # one request alone exceeds the buffers: grow to fit
                # (bounded by the protocol's own frame caps)
                need_toks, need_blob = int(self._out_counts[1]), \
                    int(self._out_counts[2])
                self._alloc(
                    max_tokens=max(self._max_tokens, need_toks),
                    blob_cap=max(self._blob_cap * 2, need_blob),
                    max_reqs=self._max_reqs)
                continue
            if self._plane is not None:
                # exemplar handoff rides the drain cadence: one call
                # moves everything the fold sampled since last time
                # into the recorder's decision ring
                self._plane.pump()
            if rc <= 0:
                if stopping:
                    self._drained.set()
                    return
                continue
            if self._plane is not None:
                lib.cap_serve_drain_aux(
                    h, self._fam_buf.ctypes.data_as(_i8p),
                    self._kid_buf.ctypes.data_as(_u8p),
                    self._max_tokens)
                lib.cap_serve_drain_tens(
                    h, self._ten_buf.ctypes.data_as(_i16p),
                    self._max_tokens)
            if self._native_digests:
                lib.cap_serve_drain_digests(
                    h, self._dig_buf.ctypes.data_as(_u8p),
                    self._max_tokens)
            if self.adm_native:
                self._thr_buf[:] = 0
                lib.cap_serve_drain_thr(
                    h, self._thr_buf.ctypes.data_as(_u8p),
                    self._max_tokens)
            self._occ_n = 0
            if self._occ_native:
                self._occ_n = int(lib.cap_serve_drain_enq(
                    h, self._enq_buf.ctypes.data_as(_f64p),
                    self._max_reqs))
            elif telemetry.active() is not None:
                telemetry.count("serve.native.occ_fallbacks")
            telemetry.gauge("serve.native.ring_depth",
                            float(self.ring_depth()))
            try:
                self._process(int(rc))
            except Exception:  # noqa: BLE001 - the loop must survive
                telemetry.count("serve.native.drain_errors")

    def _process(self, n_reqs: int) -> None:
        t_drain = time.time()
        n_toks = int(self._out_counts[1])
        rec = telemetry.active()
        if rec is not None and self._occ_n:
            # measured ring wait: drain-side monotonic minus the
            # reader-side enqueue stamp (same CLOCK_MONOTONIC both
            # sides — see serve_native.cpp Req.t_enq)
            waits = time.monotonic() \
                - self._enq_buf[: min(self._occ_n, n_reqs)]
            for w in waits:
                rec.observe("queue.ring_wait_s", max(0.0, float(w)))
        # same accounting names the Python chain counts per frame, so
        # pool.stats_merged / bench per-worker attribution are
        # chain-agnostic (control records ride in n_reqs but carry no
        # tokens; close enough for request accounting).
        telemetry.count("worker.requests", n_reqs)
        telemetry.count("worker.tokens", n_toks)
        blob = self._tok_blob[: int(self._out_counts[2])].tobytes()
        # ASCII fast path: one whole-blob decode, then str slicing per
        # token (byte offsets == char offsets). Compact JWS is ASCII
        # by construction; non-ASCII tokens take the per-slice decode.
        try:
            text: Optional[str] = blob.decode("ascii")
        except UnicodeDecodeError:
            text = None
        offs = self._tok_off[: n_toks + 1].tolist()
        meta = self._req_meta[: n_reqs * self._META_STRIDE]
        tok_i = 0
        i = 0
        while i < n_reqs:
            kind = int(meta[i * 6 + 0])
            if kind == 0:
                # contiguous run of verify requests → ONE submission
                j = i
                seg_toks = 0
                while j < n_reqs and int(meta[j * 6 + 0]) == 0:
                    seg_toks += int(meta[j * 6 + 3])
                    j += 1
                self._submit_segment(i, j, tok_i, seg_toks, blob, text,
                                     offs, t_drain)
                tok_i += seg_toks
                i = j
            else:
                self._handle_control(i, kind, blob, offs, tok_i)
                tok_i += int(meta[i * 6 + 3])
                i += 1

    def _submit_segment(self, i0: int, i1: int, tok0: int, seg_toks: int,
                        blob: bytes, text: Optional[str],
                        offs: List[int], t_drain: float) -> None:
        with telemetry.span(telemetry.SPAN_NATIVE_DRAIN):
            if text is not None:
                tokens = [text[offs[k]: offs[k + 1]]
                          for k in range(tok0, tok0 + seg_toks)]
            else:
                tokens = [blob[offs[k]: offs[k + 1]].decode("utf-8")
                          for k in range(tok0, tok0 + seg_toks)]
            n = i1 - i0
            meta = self._req_meta[i0 * 6: i1 * 6].copy()
            seqs = self._req_seq[i0:i1].copy()
            t0s = self._req_t0[i0:i1].copy()
            traces_raw = self._trace_buf[i0 * 64: i1 * 64].copy()
            plane = self._plane
            if plane is not None:
                # reader-classified (family, kid, tenant) per token;
                # the rare header-cache misses resolve through the
                # Python classifier ONCE per distinct header (issuer
                # parse included), then hit native
                fams = self._fam_buf[tok0: tok0 + seg_toks].copy()
                kids = self._kid_buf[tok0 * _KID_LEN:
                                     (tok0 + seg_toks) * _KID_LEN].copy()
                tens = self._ten_buf[tok0: tok0 + seg_toks].copy()
                if (fams < 0).any():
                    plane.fix_misses(tokens, fams, kids, tens)
            else:
                fams = kids = tens = None
            if tens is not None:
                # connection plane (r22): attribute each conn to its
                # FIRST verify frame's tenant, once — same counter the
                # python chain's reader thread writes
                labels = None
                tb = 0
                for k in range(n):
                    nent = int(meta[k * 6 + 3])
                    cid = int(meta[k * 6 + 1])
                    if nent and cid not in self._conn_tenants_seen:
                        if len(self._conn_tenants_seen) >= 1 << 20:
                            self._conn_tenants_seen.clear()
                        self._conn_tenants_seen.add(cid)
                        if labels is None:
                            labels = _decision.TENANTS.labels()
                        label = labels.get(int(tens[tb]),
                                           _decision.TENANT_NONE)
                        telemetry.count(f"serve.tenant.{label}.conns")
                    tb += nent
            traces: List[tuple] = []
            for k in range(n):
                tl = int(meta[k * 6 + 4])
                if tl:
                    tid = traces_raw[k * 64: k * 64 + tl].tobytes() \
                        .decode("ascii")
                    t_recv = float(t0s[k])
                    telemetry.trace_span(
                        tid, telemetry.SPAN_WORKER_DEQUEUE, t_recv,
                        max(0.0, t_drain - t_recv))
                    traces.append((tid, t_recv))

        def on_done(results: List[Any]) -> None:
            # Serve-surface decision records (the r9 contract). With
            # the native plane attached, the fold happens INSIDE the
            # response-encode call (cap_serve_post_results_tel) — same
            # counters, same ring sample positions, no Python pass
            # over the tokens. Without it, the Python fold runs, same
            # as the Python chain's responder. Cache hits flow through
            # the SAME fold — the decision counters cannot tell a
            # cached verdict from a fresh one (that is the parity pin).
            if plane is not None:
                lat_s = time.time() - t_drain
                self._post(results, meta, seqs, traces_raw, n, traces,
                           t0s=t0s, fams=fams, kids=kids, tens=tens,
                           lat_idx=_decision.latency_bucket_index(
                               lat_s),
                           lat_s=lat_s)
            else:
                _decision.record_batch(
                    "serve", results, tokens=tokens,
                    latency_s=time.time() - t_drain,
                    trace=traces[0][0] if traces else None)
                self._post(results, meta, seqs, traces_raw, n, traces)

        # Admission (r20): throttled tokens are answered with the
        # retry-after pushback and NEVER verified — they skip the
        # cache and the batcher entirely. The decision fold still
        # counts them (reason "throttled", per tenant) because
        # on_done always receives the FULL-length results.
        verify_idx: Optional[List[int]] = None
        thr = None
        retry_pend: dict = {}
        if self.adm_native:
            tb = self._thr_buf[tok0: tok0 + seg_toks]
            if (tb == 2).any():
                # header-cache-miss tokens the reader could not judge:
                # their tenants are resolved NOW (fix_misses above /
                # the python classifier), so take from the native
                # buckets late — same arithmetic, same counters, and
                # no cross-tenant bleed through a shared miss bucket
                if tens is not None:
                    slots = [int(s) for s in tens]
                else:
                    slots = [_decision.tenant_index(label) for label
                             in _decision.tenant_labels(tokens)]
                rb = ctypes.c_int32(0)
                for i in np.nonzero(tb == 2)[0]:
                    i = int(i)
                    if self._lib.cap_serve_adm_take(
                            self._h, slots[i], ctypes.byref(rb)):
                        tb[i] = 1
                        retry_pend[i] = int(rb.value)
                    else:
                        tb[i] = 0
            if tb.any():
                thr = tb != 0
        elif self._py_admission is not None:
            labels = (_decision.tenant_labels_from_slots(tens)
                      if tens is not None
                      else _decision.tenant_labels(tokens))
            mask, retry_ms0 = self._py_admission.check(labels)
            if mask is not None:
                thr = np.asarray(mask, bool)
        if thr is not None and thr.any():
            from . import admission as _adm

            # per-token retry hint: the owning request's drained
            # meta[5] (native readers) or the controller's chunk hint
            retry_of = np.zeros(seg_toks, np.int32)
            if self.adm_native:
                at = 0
                for k in range(n):
                    cnt = int(meta[k * 6 + 3])
                    retry_of[at: at + cnt] = int(meta[k * 6 + 5])
                    at += cnt
                for i, ms in retry_pend.items():
                    retry_of[i] = ms    # late-judged miss tokens
            else:
                retry_of[:] = retry_ms0
            full: List[Any] = [None] * seg_toks
            verify_idx = []
            for i in range(seg_toks):
                if thr[i]:
                    full[i] = _adm.throttled_error(int(retry_of[i]))
                else:
                    verify_idx.append(i)
            base_done = on_done
            if not verify_idx:
                base_done(full)     # all-throttled: zero verify work
                return

            def on_done(fresh: List[Any], _full=full, _vi=verify_idx,
                        _bd=base_done) -> None:
                for j, i in enumerate(_vi):
                    _full[i] = fresh[j]
                _bd(_full)

            tokens_v = [tokens[i] for i in verify_idx]
        else:
            tokens_v = tokens
        # reader-computed digests when the .so carries them (all-zero
        # rows — stale carry, control filler — rehash in Python)
        dig_full = None
        if self._native_digests:
            db = self._dig_buf[tok0 * _DIG_LEN:
                               (tok0 + seg_toks) * _DIG_LEN].tobytes()
            dig_full = [None if (d := db[k * _DIG_LEN:
                                         (k + 1) * _DIG_LEN])
                        == _ZERO_DIG else d for k in range(seg_toks)]
        if verify_idx is None or dig_full is None:
            dig_list = dig_full
        else:
            dig_list = [dig_full[i] for i in verify_idx]
        vc = self._vcache
        if vc is None:
            self._batcher.submit_handoff(
                tokens_v, traces=[t for t, _ in traces],
                on_done=on_done, digests=dig_list)
            return
        # Verdict-cache consult BEFORE the batcher (admitted tokens
        # only — throttled traffic must not warm or read the cache).
        hits, miss_idx, digs = vc.lookup_batch(tokens_v,
                                               digests=dig_list)
        # per-tenant cache accounting (the capstat ledger's hit%
        # column): reader-classified slots when the plane runs, the
        # Python classifier on the plane-less fallback arm
        if telemetry.active() is not None:
            if tens is not None:
                tens_v = (tens if verify_idx is None
                          else tens[np.asarray(verify_idx,
                                               np.intp)])
                cache_labels = _decision.tenant_labels_from_slots(
                    tens_v)
            else:
                cache_labels = _decision.tenant_labels(tokens_v)
            _decision.count_tenant_cache(cache_labels, miss_idx)
        if not miss_idx:
            # every token answered from cache: encode + fold directly,
            # no batcher round-trip (memory-speed path)
            on_done(hits)
            return
        if len(miss_idx) == len(tokens_v):
            epoch0 = vc.epoch

            def on_done_fill(fresh: List[Any]) -> None:
                vc.insert_batch(digs, fresh, tokens=tokens_v,
                                epoch=epoch0)
                on_done(fresh)

            self._batcher.submit_handoff(
                tokens_v, traces=[t for t, _ in traces],
                on_done=on_done_fill, digests=digs)
            return
        epoch0 = vc.epoch
        miss_tokens = [tokens_v[i] for i in miss_idx]

        def on_done_merge(fresh: List[Any]) -> None:
            vc.insert_batch([digs[i] for i in miss_idx], fresh,
                            tokens=miss_tokens, epoch=epoch0)
            full = hits
            for j, i in enumerate(miss_idx):
                full[i] = fresh[j]
            on_done(full)

        self._batcher.submit_handoff(
            miss_tokens, traces=[t for t, _ in traces],
            on_done=on_done_merge,
            digests=[digs[i] for i in miss_idx])

    def _post(self, results: List[Any], meta: np.ndarray,
              seqs: np.ndarray, traces_raw: np.ndarray, n_reqs: int,
              traces: List[tuple],
              t0s: Optional[np.ndarray] = None,
              fams: Optional[np.ndarray] = None,
              kids: Optional[np.ndarray] = None,
              tens: Optional[np.ndarray] = None,
              lat_idx: int = 0, lat_s: float = -1.0) -> None:
        tel = fams is not None and self._plane is not None
        with telemetry.span(telemetry.SPAN_NATIVE_POST):
            n_tok = len(results)
            poff = np.zeros(n_tok + 1, np.int64)
            reasons: Optional[np.ndarray] = None
            try:
                # fast path: every verdict is raw payload bytes (the
                # raw-claims engines) — one join, all statuses 0
                pblob = b"".join(results)
                if n_tok:
                    np.cumsum(np.fromiter(map(len, results), np.int64,
                                          count=n_tok), out=poff[1:])
                st = np.zeros(max(1, n_tok), np.uint8)
            except TypeError:
                statuses = bytearray(n_tok)
                rbuf = bytearray(n_tok) if tel else None
                payloads: List[bytes] = []
                for i, r in enumerate(results):
                    if isinstance(r, Exception):
                        statuses[i] = 1
                        if rbuf is not None:
                            # exact reason class, resolved per
                            # exception TYPE (one dict hit) — the
                            # native fold consumes the index
                            rbuf[i] = _decision.reason_index(r)
                        payloads.append(
                            f"{type(r).__name__}: {r}".encode())
                    elif isinstance(r, (bytes, bytearray, memoryview)):
                        payloads.append(bytes(r))
                    else:
                        payloads.append(
                            json.dumps(r, separators=(",", ":")).encode())
                pblob = b"".join(payloads)
                if payloads:
                    np.cumsum([len(p) for p in payloads], out=poff[1:])
                st = np.frombuffer(bytes(statuses), np.uint8) \
                    if statuses else np.zeros(1, np.uint8)
                if rbuf is not None:
                    reasons = np.frombuffer(bytes(rbuf), np.uint8)
            pb = np.frombuffer(pblob, np.uint8) if pblob else \
                np.zeros(1, np.uint8)
            if tel:
                # encode + decision fold + latency observe in ONE
                # GIL-released native call
                self._lib.cap_serve_post_results_tel(
                    self._h, meta.ctypes.data_as(_i32p),
                    seqs.ctypes.data_as(_i64p),
                    traces_raw.ctypes.data_as(_u8p),
                    t0s.ctypes.data_as(_f64p), n_reqs,
                    st.ctypes.data_as(_u8p), pb.ctypes.data_as(_u8p),
                    poff.ctypes.data_as(_i64p),
                    reasons.ctypes.data_as(_u8p)
                    if reasons is not None else None,
                    fams.ctypes.data_as(_i8p),
                    tens.ctypes.data_as(_i16p)
                    if tens is not None else None,
                    kids.ctypes.data_as(_u8p), lat_idx, lat_s)
            else:
                self._lib.cap_serve_post_results(
                    self._h, meta.ctypes.data_as(_i32p),
                    seqs.ctypes.data_as(_i64p),
                    traces_raw.ctypes.data_as(_u8p), n_reqs,
                    st.ctypes.data_as(_u8p), pb.ctypes.data_as(_u8p),
                    poff.ctypes.data_as(_i64p))
        now = time.time()
        for tid, t_recv in traces:
            telemetry.flight(tid, now - t_recv)

    def _handle_control(self, i: int, kind: int, blob: bytes,
                        offs: List[int], tok0: int) -> None:
        meta = self._req_meta
        conn_id = int(meta[i * 6 + 1])
        seq = int(self._req_seq[i])
        if kind == 2:  # stats request
            try:
                frame = protocol.encode_stats_response(self._stats_fn())
            except Exception as e:  # noqa: BLE001 - never wedge the loop
                frame = protocol.encode_stats_response(
                    {"error": f"{type(e).__name__}"})
        elif kind == 4:  # peer fill (exactly one entry: the op JSON)
            try:
                doc = json.loads(blob[offs[tok0]: offs[tok0 + 1]])
                if self._peer_fill_fn is None:
                    raise TypeError("worker has no peer-fill handler")
                frame = protocol.encode_peer_ack(
                    doc=self._peer_fill_fn(doc))
            except Exception as e:  # noqa: BLE001 - acked, like Python
                telemetry.count("worker.peer_fill_errors")
                frame = protocol.encode_peer_ack(
                    error=f"{type(e).__name__}: {e}")
        else:          # keys push (exactly one entry: the payload)
            try:
                doc = json.loads(blob[offs[tok0]: offs[tok0 + 1]])
                got = self._keys_fn(doc.get("jwks") or {},
                                    doc.get("epoch"))
                frame = protocol.encode_keys_ack(epoch=got)
            except Exception as e:  # noqa: BLE001 - acked, like Python
                telemetry.count("worker.keys_push_errors")
                frame = protocol.encode_keys_ack(
                    error=f"{type(e).__name__}: {e}")
        buf = np.frombuffer(frame, np.uint8)
        self._lib.cap_serve_post_raw(
            self._h, conn_id, seq, buf.ctypes.data_as(_u8p), len(frame))

    # -- shutdown ----------------------------------------------------------

    def stop_drain(self, deadline_s: float = 10.0) -> None:
        """Stop the drain loop AFTER it has emptied the ring into the
        batcher — queued requests are flushed, not dropped."""
        self._stop.set()
        self._drained.wait(timeout=deadline_s)
        self._thread.join(timeout=deadline_s)

    def destroy(self) -> None:
        """Tear down the native side (sever connections, join its
        threads). Call after the batcher has finished so in-flight
        verdict posts have been written out."""
        h, self._h = self._h, None
        if self._plane is not None:
            # last exemplar handoff and a final snapshot capture, then
            # invalidate under the pump lock (a concurrent scrape's
            # pump either finished or sees None): the plane's C region
            # is freed with the handle, but the sigterm-drain
            # postmortem still reads the captured state
            self._plane.pump()
            self._plane._final_snapshot = self._plane.snapshot()
            with self._plane._pump_lock:
                self._plane._h = None
        if h:
            self._final_counters = self._read_counters(h)
            self._lib.cap_serve_destroy(h)
