"""ctypes binding + drain loop for the native (C++) serve chain.

``serve_native.cpp`` (built into ``libcapruntime.so``) owns the
per-token serve hot path: per-connection reader threads parse and
validate CVB1 frames GIL-free and feed a bounded lock-free MPSC ring;
per-connection writer threads encode and send responses in strict
request order. Python's only per-token work is slicing the drained
token blob into strings and joining verdict payloads back into one
buffer — everything else crosses the boundary as whole batches:

    drain()  → one flat buffer of tokens + request descriptors
    batcher  → one submission per drained chunk (no per-token or
               per-request callbacks; ``AdaptiveBatcher.submit_handoff``)
    post()   → one call with every verdict of the chunk

Control frames (stats requests, keyplane KEYS pushes) ride the SAME
ring in frame order, so a keys push still applies before any verify
read after it, exactly like the Python chain's reader-thread apply.
Pings are answered natively without waking Python at all.

Raises ImportError when the library is missing or predates the serve
chain — ``VerifyWorker`` catches that and falls back to the pure
Python chain (``serve_chain == "python"``).
"""

from __future__ import annotations

import ctypes
import json
import os
import threading
import time
from typing import Any, Callable, List, Optional

import numpy as np

from .. import telemetry
from ..obs import decision as _decision
from . import protocol

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "runtime", "native", "libcapruntime.so")

_SYMBOLS = ("cap_serve_create", "cap_serve_destroy", "cap_serve_add_conn",
            "cap_serve_drain", "cap_serve_post_results",
            "cap_serve_post_raw", "cap_serve_ring_depth",
            "cap_serve_counter", "cap_serve_probe_frame",
            "cap_bench_drive")

# counter slots, mirroring serve_native.cpp
CTR_CONNS = 0
CTR_FRAMES = 1
CTR_TOKENS = 2
CTR_PROTO_ERR = 3
CTR_PONGS = 4
CTR_DROPPED_POSTS = 5
CTR_CONNS_CLOSED = 6

_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_f64p = ctypes.POINTER(ctypes.c_double)

_lib = None
_lib_lock = threading.Lock()


def load() -> ctypes.CDLL:
    """Load (building on first use) and type-check the library; raises
    ImportError when unbuildable or stale (missing serve symbols)."""
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from .._build import build_native

        build_native()
        if not os.path.exists(_LIB_PATH):
            raise ImportError(f"{_LIB_PATH} not built (run: make native)")
        lib = ctypes.CDLL(_LIB_PATH)
        for sym in _SYMBOLS:
            if not hasattr(lib, sym):
                raise ImportError(
                    f"stale libcapruntime.so: missing {sym} "
                    "(run: make native-build)")
        lib.cap_serve_create.restype = ctypes.c_void_p
        lib.cap_serve_create.argtypes = [ctypes.c_int32, ctypes.c_int64]
        lib.cap_serve_destroy.argtypes = [ctypes.c_void_p]
        lib.cap_serve_add_conn.restype = ctypes.c_int32
        lib.cap_serve_add_conn.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.cap_serve_ring_depth.restype = ctypes.c_int64
        lib.cap_serve_ring_depth.argtypes = [ctypes.c_void_p]
        lib.cap_serve_counter.restype = ctypes.c_int64
        lib.cap_serve_counter.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.cap_serve_drain.restype = ctypes.c_int64
        lib.cap_serve_drain.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_double, ctypes.c_double, _u8p, ctypes.c_int64,
            _i64p, _i32p, _i64p, _f64p, _u8p, ctypes.c_int32, _i64p]
        lib.cap_serve_post_results.restype = ctypes.c_int32
        lib.cap_serve_post_results.argtypes = [
            ctypes.c_void_p, _i32p, _i64p, _u8p, ctypes.c_int32,
            _u8p, _u8p, _i64p]
        lib.cap_serve_post_raw.restype = ctypes.c_int32
        lib.cap_serve_post_raw.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int64, _u8p,
            ctypes.c_int64]
        lib.cap_serve_probe_frame.restype = ctypes.c_int32
        lib.cap_serve_probe_frame.argtypes = [_u8p, ctypes.c_int64, _i64p]
        lib.cap_bench_drive.restype = ctypes.c_int32
        lib.cap_bench_drive.argtypes = [
            ctypes.c_char_p, ctypes.c_int32, _u8p, _i64p, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_double,
            ctypes.c_int32, _i64p, _i64p]
        _lib = lib
        return lib


def probe_frame(data: bytes) -> int:
    """Classify one complete frame with the NATIVE parser → PF status
    (0 ok; see protocol.NATIVE_STATUS_ERRORS for the class map). The
    malformed-frame parity sweep drives this against
    ``protocol.parse_frame_bytes``."""
    lib = load()
    buf = np.frombuffer(bytearray(data), np.uint8) if data else \
        np.zeros(1, np.uint8)
    return int(lib.cap_serve_probe_frame(
        buf.ctypes.data_as(_u8p), len(data), None))


class NativeServeChain:
    """One worker's native frame-I/O front end.

    batcher: the worker's AdaptiveBatcher (must expose
    ``submit_handoff``). stats_fn / keys_fn: the worker's control-op
    handlers (``VerifyWorker.stats`` / ``VerifyWorker.apply_keys``).
    """

    _META_STRIDE = 6

    def __init__(self, batcher, stats_fn: Callable[[], dict],
                 keys_fn: Callable[[dict, Any], int],
                 target_batch: int = 4096, max_wait_ms: float = 2.0,
                 max_batch: int = 32768):
        self._lib = load()
        self._batcher = batcher
        self._stats_fn = stats_fn
        self._keys_fn = keys_fn
        self._target = max(1, target_batch)
        self._h = ctypes.c_void_p(self._lib.cap_serve_create(
            4096, 4 * max_batch))
        if not self._h:
            raise ImportError("cap_serve_create failed")
        self._stop = threading.Event()
        self._drained = threading.Event()   # ring empty after stop
        # drain buffers (grown on demand when a giant frame arrives)
        self._alloc(max_tokens=max_batch, blob_cap=8 << 20,
                    max_reqs=4096)
        self._thread = threading.Thread(
            target=self._drain_loop, daemon=True,
            name="cap-tpu-native-drain")
        self._thread.start()

    def _alloc(self, max_tokens: int, blob_cap: int,
               max_reqs: int) -> None:
        self._max_tokens = max_tokens
        self._blob_cap = blob_cap
        self._max_reqs = max_reqs
        self._tok_blob = np.empty(blob_cap, np.uint8)
        self._tok_off = np.zeros(max_tokens + 1, np.int64)
        self._req_meta = np.zeros(max_reqs * self._META_STRIDE, np.int32)
        self._req_seq = np.zeros(max_reqs, np.int64)
        self._req_t0 = np.zeros(max_reqs, np.float64)
        self._trace_buf = np.zeros(max_reqs * 64, np.uint8)
        self._out_counts = np.zeros(3, np.int64)

    # -- connection handoff ------------------------------------------------

    def add_conn(self, conn) -> int:
        """Take ownership of an accepted socket: its fd moves to the
        native reader/writer threads (the Python socket object is
        detached and must not be used again)."""
        fd = conn.detach()
        cid = int(self._lib.cap_serve_add_conn(self._h, fd))
        if cid < 0:
            os.close(fd)
        return cid

    # -- stats surface -----------------------------------------------------

    def ring_depth(self) -> int:
        h = self._h
        if not h:               # destroyed (post-drain stats snapshot)
            return 0
        return int(self._lib.cap_serve_ring_depth(h))

    def counters(self) -> dict:
        c = self._lib.cap_serve_counter
        h = self._h
        if not h:               # destroyed: final counters are gone —
            return {}           # the postmortem keeps its last doc
        return {
            "serve.native.connections": int(c(h, CTR_CONNS)),
            "serve.native.frames": int(c(h, CTR_FRAMES)),
            "serve.native.tokens": int(c(h, CTR_TOKENS)),
            "serve.native.protocol_errors": int(c(h, CTR_PROTO_ERR)),
            "serve.native.pongs": int(c(h, CTR_PONGS)),
            "serve.native.dropped_posts": int(c(h, CTR_DROPPED_POSTS)),
        }

    # -- drain loop --------------------------------------------------------

    def _drain_loop(self) -> None:
        lib = self._lib
        h = self._h
        while True:
            stopping = self._stop.is_set()
            # GREEDY drain: block until at least one request is queued
            # (idle wait), then take everything available and return —
            # the drain layer adds NO batching window of its own; the
            # AdaptiveBatcher below owns the latency/throughput
            # tradeoff, exactly as on the Python chain. Under load the
            # ring refills while Python processes the previous chunk,
            # so chunks grow toward max_tokens by themselves.
            rc = int(lib.cap_serve_drain(
                h, self._max_tokens, self._max_tokens,
                0.0,
                # short idle wait while serving (cheap wakeups keep
                # close() responsive); near-zero when draining out
                0.0 if stopping else 0.05,
                self._tok_blob.ctypes.data_as(_u8p), self._blob_cap,
                self._tok_off.ctypes.data_as(_i64p),
                self._req_meta.ctypes.data_as(_i32p),
                self._req_seq.ctypes.data_as(_i64p),
                self._req_t0.ctypes.data_as(_f64p),
                self._trace_buf.ctypes.data_as(_u8p),
                self._max_reqs,
                self._out_counts.ctypes.data_as(_i64p)))
            if rc == -2:
                # one request alone exceeds the buffers: grow to fit
                # (bounded by the protocol's own frame caps)
                need_toks, need_blob = int(self._out_counts[1]), \
                    int(self._out_counts[2])
                self._alloc(
                    max_tokens=max(self._max_tokens, need_toks),
                    blob_cap=max(self._blob_cap * 2, need_blob),
                    max_reqs=self._max_reqs)
                continue
            if rc <= 0:
                if stopping:
                    self._drained.set()
                    return
                continue
            telemetry.gauge("serve.native.ring_depth",
                            float(self.ring_depth()))
            try:
                self._process(int(rc))
            except Exception:  # noqa: BLE001 - the loop must survive
                telemetry.count("serve.native.drain_errors")

    def _process(self, n_reqs: int) -> None:
        t_drain = time.time()
        n_toks = int(self._out_counts[1])
        # same accounting names the Python chain counts per frame, so
        # pool.stats_merged / bench per-worker attribution are
        # chain-agnostic (control records ride in n_reqs but carry no
        # tokens; close enough for request accounting).
        telemetry.count("worker.requests", n_reqs)
        telemetry.count("worker.tokens", n_toks)
        blob = self._tok_blob[: int(self._out_counts[2])].tobytes()
        # ASCII fast path: one whole-blob decode, then str slicing per
        # token (byte offsets == char offsets). Compact JWS is ASCII
        # by construction; non-ASCII tokens take the per-slice decode.
        try:
            text: Optional[str] = blob.decode("ascii")
        except UnicodeDecodeError:
            text = None
        offs = self._tok_off[: n_toks + 1].tolist()
        meta = self._req_meta[: n_reqs * self._META_STRIDE]
        tok_i = 0
        i = 0
        while i < n_reqs:
            kind = int(meta[i * 6 + 0])
            if kind == 0:
                # contiguous run of verify requests → ONE submission
                j = i
                seg_toks = 0
                while j < n_reqs and int(meta[j * 6 + 0]) == 0:
                    seg_toks += int(meta[j * 6 + 3])
                    j += 1
                self._submit_segment(i, j, tok_i, seg_toks, blob, text,
                                     offs, t_drain)
                tok_i += seg_toks
                i = j
            else:
                self._handle_control(i, kind, blob, offs, tok_i)
                tok_i += int(meta[i * 6 + 3])
                i += 1

    def _submit_segment(self, i0: int, i1: int, tok0: int, seg_toks: int,
                        blob: bytes, text: Optional[str],
                        offs: List[int], t_drain: float) -> None:
        with telemetry.span(telemetry.SPAN_NATIVE_DRAIN):
            if text is not None:
                tokens = [text[offs[k]: offs[k + 1]]
                          for k in range(tok0, tok0 + seg_toks)]
            else:
                tokens = [blob[offs[k]: offs[k + 1]].decode("utf-8")
                          for k in range(tok0, tok0 + seg_toks)]
            n = i1 - i0
            meta = self._req_meta[i0 * 6: i1 * 6].copy()
            seqs = self._req_seq[i0:i1].copy()
            t0s = self._req_t0[i0:i1].copy()
            traces_raw = self._trace_buf[i0 * 64: i1 * 64].copy()
            traces: List[tuple] = []
            for k in range(n):
                tl = int(meta[k * 6 + 4])
                if tl:
                    tid = traces_raw[k * 64: k * 64 + tl].tobytes() \
                        .decode("ascii")
                    t_recv = float(t0s[k])
                    telemetry.trace_span(
                        tid, telemetry.SPAN_WORKER_DEQUEUE, t_recv,
                        max(0.0, t_drain - t_recv))
                    traces.append((tid, t_recv))

        def on_done(results: List[Any]) -> None:
            # Serve-surface decision records (the r9 contract, same
            # call the Python chain's responder makes per request —
            # here once per drained chunk, exact counters either way).
            _decision.record_batch(
                "serve", results, tokens=tokens,
                latency_s=time.time() - t_drain,
                trace=traces[0][0] if traces else None)
            self._post(results, meta, seqs, traces_raw, n, traces)

        self._batcher.submit_handoff(
            tokens, traces=[t for t, _ in traces], on_done=on_done)

    def _post(self, results: List[Any], meta: np.ndarray,
              seqs: np.ndarray, traces_raw: np.ndarray, n_reqs: int,
              traces: List[tuple]) -> None:
        with telemetry.span(telemetry.SPAN_NATIVE_POST):
            n_tok = len(results)
            poff = np.zeros(n_tok + 1, np.int64)
            try:
                # fast path: every verdict is raw payload bytes (the
                # raw-claims engines) — one join, all statuses 0
                pblob = b"".join(results)
                if n_tok:
                    np.cumsum(np.fromiter(map(len, results), np.int64,
                                          count=n_tok), out=poff[1:])
                st = np.zeros(max(1, n_tok), np.uint8)
            except TypeError:
                statuses = bytearray(n_tok)
                payloads: List[bytes] = []
                for i, r in enumerate(results):
                    if isinstance(r, Exception):
                        statuses[i] = 1
                        payloads.append(
                            f"{type(r).__name__}: {r}".encode())
                    elif isinstance(r, (bytes, bytearray, memoryview)):
                        payloads.append(bytes(r))
                    else:
                        payloads.append(
                            json.dumps(r, separators=(",", ":")).encode())
                pblob = b"".join(payloads)
                if payloads:
                    np.cumsum([len(p) for p in payloads], out=poff[1:])
                st = np.frombuffer(bytes(statuses), np.uint8) \
                    if statuses else np.zeros(1, np.uint8)
            pb = np.frombuffer(pblob, np.uint8) if pblob else \
                np.zeros(1, np.uint8)
            self._lib.cap_serve_post_results(
                self._h, meta.ctypes.data_as(_i32p),
                seqs.ctypes.data_as(_i64p),
                traces_raw.ctypes.data_as(_u8p), n_reqs,
                st.ctypes.data_as(_u8p), pb.ctypes.data_as(_u8p),
                poff.ctypes.data_as(_i64p))
        now = time.time()
        for tid, t_recv in traces:
            telemetry.flight(tid, now - t_recv)

    def _handle_control(self, i: int, kind: int, blob: bytes,
                        offs: List[int], tok0: int) -> None:
        meta = self._req_meta
        conn_id = int(meta[i * 6 + 1])
        seq = int(self._req_seq[i])
        if kind == 2:  # stats request
            try:
                frame = protocol.encode_stats_response(self._stats_fn())
            except Exception as e:  # noqa: BLE001 - never wedge the loop
                frame = protocol.encode_stats_response(
                    {"error": f"{type(e).__name__}"})
        else:          # keys push (exactly one entry: the payload)
            try:
                doc = json.loads(blob[offs[tok0]: offs[tok0 + 1]])
                got = self._keys_fn(doc.get("jwks") or {},
                                    doc.get("epoch"))
                frame = protocol.encode_keys_ack(epoch=got)
            except Exception as e:  # noqa: BLE001 - acked, like Python
                telemetry.count("worker.keys_push_errors")
                frame = protocol.encode_keys_ack(
                    error=f"{type(e).__name__}: {e}")
        buf = np.frombuffer(frame, np.uint8)
        self._lib.cap_serve_post_raw(
            self._h, conn_id, seq, buf.ctypes.data_as(_u8p), len(frame))

    # -- shutdown ----------------------------------------------------------

    def stop_drain(self, deadline_s: float = 10.0) -> None:
        """Stop the drain loop AFTER it has emptied the ring into the
        batcher — queued requests are flushed, not dropped."""
        self._stop.set()
        self._drained.wait(timeout=deadline_s)
        self._thread.join(timeout=deadline_s)

    def destroy(self) -> None:
        """Tear down the native side (sever connections, join its
        threads). Call after the batcher has finished so in-flight
        verdict posts have been written out."""
        h, self._h = self._h, None
        if h:
            self._lib.cap_serve_destroy(h)
