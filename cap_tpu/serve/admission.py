"""Per-tenant token-bucket admission control (the python half).

The native serve chain checks admission in its C++ readers
(``serve_native.cpp`` ``cap_serve_set_admission``); this module is the
python chain's implementation AND the stale-``.so`` fallback for the
native chain — same bucket arithmetic (start full, lazy refill from a
monotonic clock, one token per token), same counters, so the obs-smoke
gate can pin ``admission.checked == admission.admitted +
admission.throttled`` and cross-chain equality over a deterministic
(rate≈0) configuration.

A throttled token is rejected BEFORE verification with
:class:`cap_tpu.errors.ThrottledError` whose message carries the
additive ``retry_after_ms=<int>`` pushback hint
(``serve/protocol.retry_after_hint`` parses it back). The decision
fold then counts it under the registered ``throttled`` reason —
per tenant — like any other reject, which is what the SLO shed rules
and the capstat admission columns read.

Config (the worker reads these; the pool forwards via ``env_extra``):

- ``CAP_SERVE_ADMIT_RATE``  — tokens/sec per tenant (unset/0 = off)
- ``CAP_SERVE_ADMIT_BURST`` — bucket depth in tokens (default 2×rate,
  min 1)
- ``CAP_SERVE_FAIR``        — 1 = DRR fair scheduling on
- ``CAP_SERVE_DRR_QUANTUM`` — DRR per-visit token credit (default 512)
- ``CAP_SERVE_DRR_WEIGHTS`` — ``<tenant-hash>:<w>[,...]`` (``be:<w>``
  addresses the shared best-effort slot)
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .. import telemetry
from ..errors import ThrottledError
from ..obs import decision as _decision


class AdmissionConfig:
    """Parsed admission/fairness knobs (worker args override env)."""

    __slots__ = ("fair", "rate", "burst", "quantum", "weights")

    def __init__(self, fair: Optional[bool] = None,
                 rate: Optional[float] = None,
                 burst: Optional[float] = None,
                 quantum: Optional[int] = None,
                 weights: Optional[Dict[str, int]] = None):
        env = os.environ
        if fair is None:
            fair = env.get("CAP_SERVE_FAIR", "0") == "1"
        if rate is None:
            try:
                rate = float(env.get("CAP_SERVE_ADMIT_RATE", "0") or 0)
            except ValueError:
                rate = 0.0
        if burst is None:
            try:
                burst = float(env.get("CAP_SERVE_ADMIT_BURST", "0") or 0)
            except ValueError:
                burst = 0.0
        if burst <= 0:
            burst = max(1.0, 2.0 * rate)
        if quantum is None:
            try:
                quantum = int(env.get("CAP_SERVE_DRR_QUANTUM", "0") or 0)
            except ValueError:
                quantum = 0
        if weights is None:
            weights = {}
            for part in env.get("CAP_SERVE_DRR_WEIGHTS", "").split(","):
                if not part:
                    continue
                key, _, w = part.partition(":")
                try:
                    weights[key.strip()] = max(1, int(w))
                except ValueError:
                    continue
        self.fair = bool(fair)
        self.rate = float(rate)
        self.burst = float(burst)
        self.quantum = int(quantum) if quantum and quantum > 0 else 0
        self.weights = dict(weights)

    @property
    def admission_on(self) -> bool:
        """Admission is armed iff a positive per-tenant rate is set
        (a deterministic hard-cap config uses a tiny rate, e.g.
        1e-4 tok/s, so refill is negligible inside a test window)."""
        return self.rate > 0


class _Bucket:
    __slots__ = ("level", "t_last", "scale", "init")

    def __init__(self):
        self.level = 0.0
        self.t_last = 0.0
        self.scale = 1.0
        self.init = False


class AdmissionController:
    """Token buckets keyed by tenant LABEL (hash / none / other).

    ``check(labels)`` refills + takes one token per entry and returns
    ``(mask, retry_after_ms)`` — mask[i] True means token i is over
    budget (reject with pushback, never verify). Counters ride the
    active recorder under the exact names the native chain exposes
    from its counter slots (``admission.checked`` / ``.admitted`` /
    ``.throttled``), so fleet merges are chain-agnostic.
    """

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock=time.monotonic):
        self.rate = max(0.0, float(rate))
        self.burst = float(burst) if burst and burst > 0 \
            else max(1.0, 2.0 * self.rate)
        self._clock = clock
        self._lock = threading.Lock()
        self._buckets: Dict[str, _Bucket] = {}
        # shed state: tenant label → rate scale (the pool's admission
        # op writes it; capstat's ledger renders it)
        self.shed: Dict[str, float] = {}

    # -- hot path ---------------------------------------------------------

    def check(self, labels: Sequence[str]
              ) -> Tuple[Optional[List[bool]], int]:
        """One bucket take per token; (None, 0) when all admitted."""
        n = len(labels)
        if n == 0:
            return (None, 0)
        now = self._clock()
        throttled = 0
        worst = 0.0
        mask: Optional[List[bool]] = None
        with self._lock:
            for i, label in enumerate(labels):
                b = self._buckets.get(label)
                if b is None:
                    if len(self._buckets) >= 4 * _decision.N_TENANT:
                        self._buckets.clear()   # bounded, like caches
                    b = self._buckets[label] = _Bucket()
                rate = self.rate * b.scale
                if not b.init:
                    b.init = True
                    b.level = self.burst     # buckets start full
                    b.t_last = now
                elif now > b.t_last:
                    b.level = min(self.burst,
                                  b.level + (now - b.t_last) * rate)
                    b.t_last = now
                if b.level >= 1.0:
                    b.level -= 1.0
                else:
                    if mask is None:
                        mask = [False] * n
                    mask[i] = True
                    throttled += 1
                    wait = (1.0 - b.level) / rate if rate > 1e-9 \
                        else 60.0
                    if wait > worst:
                        worst = wait
        rec = telemetry.active()
        if rec is not None:
            inc = {"admission.checked": n}
            if n - throttled:
                inc["admission.admitted"] = n - throttled
            if throttled:
                inc["admission.throttled"] = throttled
            rec.count_many(inc)
        retry_ms = 0
        if throttled:
            retry_ms = min(60000, max(1, int(worst * 1000.0) + 1))
        return (mask, retry_ms)

    def check_tokens(self, tokens: Sequence[str]
                     ) -> Tuple[Optional[List[bool]], int]:
        """check() over per-token tenant labels (header-segment
        cached — the python chain's entry point)."""
        return self.check(_decision.tenant_labels(tokens))

    # -- shed lever (the pool's admission op) -----------------------------

    def set_scale(self, label: str, scale: float) -> None:
        scale = max(0.0, float(scale))
        with self._lock:
            b = self._buckets.get(label)
            if b is None:
                b = self._buckets[label] = _Bucket()
            b.scale = scale
        if scale < 1.0:
            self.shed[label] = scale
        else:
            self.shed.pop(label, None)

    def fill(self, label: str) -> float:
        """Current bucket level in tokens (point-in-time, no refill —
        the capstat admission column)."""
        with self._lock:
            b = self._buckets.get(label)
            return b.level if b is not None and b.init else self.burst


def throttled_error(retry_ms: int) -> ThrottledError:
    """The canonical pushback exception both chains encode: class head
    ``ThrottledError`` + the additive ``retry_after_ms`` hint."""
    return ThrottledError(retry_after_ms=max(1, int(retry_ms or 1)))
