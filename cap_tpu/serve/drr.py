"""Deficit-round-robin scheduler: the python twin of the native DRR.

``serve_native.cpp``'s ``DrrSched`` drains the MPSC ring's per-tenant
subqueues in deficit-round-robin order; this module is its LINE-FOR-
LINE python mirror, used by :class:`~cap_tpu.serve.batcher.
AdaptiveBatcher`'s ``fair=True`` mode so BOTH serve chains schedule
identically. The dispatch-order parity is pinned by
``tests/test_admission.py``: a randomized multi-tenant interleave is
driven through this class and through the native ``cap_drr_*`` probe
ABI and the two pop orders must match element for element.

Shape (the classic DRR result — Shreedhar & Varghese — behind
token-bucket-policed ingest): one subqueue per real tenant slot
(``TENANT_CAP``) plus ONE shared best-effort slot for none / other /
unclassified traffic; costs are TOKENS; a queue whose head costs more
than its accumulated deficit yields the cursor and earns another
``quantum × weight`` on its next visit; a queue that empties resets
its deficit (leaving the active set forfeits credit).
"""

from __future__ import annotations

from collections import deque
from typing import Any, List, Optional, Tuple

from ..obs import decision as _decision

# One slot per real tenant + the shared best-effort slot. Mirrors
# serve_native.cpp SCHED_SLOTS / SCHED_BE — the parity test drives
# both against the same slot universe.
SCHED_SLOTS = _decision.TENANT_CAP + 1
SCHED_BE = _decision.TENANT_CAP
DEFAULT_QUANTUM = 512


def sched_slot_for_label(label: str) -> int:
    """DRR slot for a resolved tenant label: its own slot while the
    tenant table has room, the shared best-effort slot for none /
    other (the native readers make the same call on the tenant slot
    they classified at frame-parse time)."""
    idx = _decision.tenant_index(label)
    if 0 <= idx < _decision.TENANT_CAP:
        return idx
    return SCHED_BE


def sched_slot_for_tokens(tokens) -> int:
    """Slot of a submission: the FIRST token's tenant (frames are
    per-connection and issuers per-client, so mixed-tenant
    submissions are rare — the native reader picks the same way)."""
    if not tokens:
        return SCHED_BE
    tok = tokens[0]
    seg = tok.split(".", 1)[0] if isinstance(tok, str) else None
    return sched_slot_for_label(_decision._seg_fkt(seg, tok)[2])


class DRRScheduler:
    """Deficit round robin over ``SCHED_SLOTS`` subqueues.

    ``push(slot, item, cost)`` enqueues; ``pop()`` returns the next
    item in DRR order (None when empty). Deterministic given the
    arrival sequence — the cross-chain parity contract.
    """

    __slots__ = ("_q", "_deficit", "weight", "quantum", "_cursor",
                 "_fresh", "n")

    def __init__(self, quantum: int = DEFAULT_QUANTUM,
                 slots: int = SCHED_SLOTS):
        self._q: List[deque] = [deque() for _ in range(slots)]
        self._deficit = [0] * slots
        self.weight = [1] * slots
        self.quantum = int(quantum) if quantum > 0 else DEFAULT_QUANTUM
        self._cursor = 0
        self._fresh = True
        self.n = 0

    def set_weight(self, slot: int, w: int) -> None:
        if 0 <= slot < len(self._q) and w >= 1:
            self.weight[slot] = int(w)

    def push(self, slot: int, item: Any, cost: int) -> None:
        if not 0 <= slot < len(self._q):
            slot = SCHED_BE
        self._q[slot].append((item, max(1, int(cost))))
        self.n += 1

    def peek_oldest_ts(self, ts_of) -> Optional[float]:
        """min(ts) over every queue head (the batcher's flush-window
        clock needs the OLDEST pending submission, whichever slot it
        parked in)."""
        oldest = None
        for q in self._q:
            if q:
                ts = ts_of(q[0][0])
                if oldest is None or ts < oldest:
                    oldest = ts
        return oldest

    def pop(self) -> Optional[Any]:
        if self.n == 0:
            return None
        nslot = len(self._q)
        empties = 0
        while True:
            s = self._cursor
            q = self._q[s]
            if not q:
                self._deficit[s] = 0     # leaving the active set
                self._cursor = (s + 1) % nslot
                self._fresh = True
                empties += 1
                if empties >= nslot:     # defensive; n > 0 excludes it
                    return None
                continue
            empties = 0
            if self._fresh:
                self._deficit[s] += self.quantum * self.weight[s]
                self._fresh = False
            item, cost = q[0]
            if cost <= self._deficit[s]:
                self._deficit[s] -= cost
                q.popleft()
                self.n -= 1
                return item
            self._cursor = (s + 1) % nslot   # out of deficit: yield
            self._fresh = True

    def drain_fifo(self) -> List[Any]:
        """Flush everything in plain slot-scan order (shutdown path:
        nothing may be stranded when fair mode winds down)."""
        out = []
        for q in self._q:
            while q:
                out.append(q.popleft()[0])
        self.n = 0
        return out
