"""Adaptive batching in front of the device engine.

The throughput/latency tension from SURVEY.md §7: >500k verifies/sec
wants huge device batches, p99 latency wants small ones. The batcher
resolves it adaptively — submissions from any number of threads or
connections accumulate in one queue; a dispatcher flushes to
``KeySet.verify_batch`` as soon as EITHER the batch-size target is
reached OR the oldest queued token has waited ``max_wait_ms``. Under
load, flushes are back-to-back full batches (max throughput); when
idle, a lone token waits at most one wait window (bounded p99).

When the keyset exposes ``verify_batch_async`` (TPUBatchKeySet), the
dispatcher runs TWO-DEEP: flush k+1's host prep and H2D overlap flush
k's device drain (a collector thread owns the materializing syncs), so
sustained load keeps the wire busy — the same pipelining bench.py
measures, available to every serve client.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from .. import telemetry

# Dispatcher-side shutdown sentinel: put into the inflight queue by the
# dispatcher thread itself just before it exits, so by FIFO order it
# arrives AFTER every batch the dispatcher ever handed over.
_DISPATCHER_DONE = object()


class _Pending:
    __slots__ = ("tokens", "results", "event", "ts", "trace", "t0_wall",
                 "traces", "on_done", "digests", "handoff")

    def __init__(self, tokens: Sequence[str],
                 trace: Optional[str] = None,
                 traces: Optional[Sequence[str]] = None,
                 on_done=None,
                 digests: Optional[Sequence[Optional[bytes]]] = None,
                 handoff: bool = False):
        self.tokens = tokens
        # Per-token sha256[:16] digests, when the submitter already
        # has them (the serve cache-consult path; the native chain's
        # C readers compute them at frame-parse time). Routed engines
        # (``verify_batch_digests``) consume them instead of
        # re-hashing; everyone else ignores them.
        self.digests = digests
        self.results: Optional[List[Any]] = None
        self.event = threading.Event()
        self.ts = time.monotonic()
        # Trace context: captured at submit (explicitly from the wire,
        # or from the caller's telemetry.trace() scope) so the flush /
        # dispatch stages can attribute their spans per request even
        # though many submissions coalesce into one device batch.
        self.trace = trace if trace is not None \
            else telemetry.current_trace()
        self.t0_wall = time.time() if (self.trace or traces) else 0.0
        # Batch handoff extras (submit_handoff): the native serve
        # chain submits one _Pending per DRAINED RING CHUNK, carrying
        # the union of its requests' trace ids and one completion
        # callback — no per-token (or per-request) callbacks anywhere.
        self.traces: Sequence[str] = traces or ()
        self.on_done = on_done
        # Ring-chunk handoff marker: a size-triggered flush whose sole
        # member is one handed-off chunk classifies as flush reason
        # "handoff" (the native chain's drained-chunk shape) rather
        # than "size".
        self.handoff = handoff


class AdaptiveBatcher:
    """Aggregates verify submissions into device-sized batches.

    keyset: anything with ``verify_batch(tokens) -> list`` (claims dict
    or per-token Exception). target_batch: flush threshold;
    max_wait_ms: max time the OLDEST submission waits before a flush;
    max_batch: hard cap per device dispatch.
    """

    def __init__(self, keyset, target_batch: int = 4096,
                 max_wait_ms: float = 2.0, max_batch: int = 32768,
                 max_queued_tokens: int = 0,
                 dedup: Optional[bool] = None,
                 fair: Optional[bool] = None,
                 drr_quantum: int = 0):
        self._keyset = keyset
        # Tenant-fair mode (r20): pending submissions park in
        # per-tenant DRR subqueues (cap_tpu.serve.drr — the EXACT
        # python twin of the native ring's scheduler) and flushes pop
        # them in deficit-round-robin order, so a flooding issuer
        # cannot starve quiet tenants of batch slots on the python
        # chain either. fair=None → CAP_SERVE_FAIR=1.
        if fair is None:
            fair = os.environ.get("CAP_SERVE_FAIR", "0") == "1"
        self._sched = None
        self._carry: Optional["_Pending"] = None
        if fair:
            from . import drr as _drr

            self._sched = _drr.DRRScheduler(
                quantum=drr_quantum or _drr.DEFAULT_QUANTUM)
        self.fair = self._sched is not None
        # In-flight replay dedup (ROADMAP #3): identical tokens queued
        # together verify ONCE per flush and the single verdict fans
        # out to every waiter (verify is deterministic, so duplicate
        # suppression cannot change any verdict; per-submission trace
        # ids and decision records are untouched — they attach to the
        # _Pending, not to the deduped dispatch list). dedup=None →
        # CAP_SERVE_DEDUP if set, else the vcache tier's master switch
        # (CAP_SERVE_VCACHE=0 turns the whole tier off).
        if dedup is None:
            env = os.environ.get("CAP_SERVE_DEDUP")
            if env is not None:
                dedup = env != "0"
            else:
                from .vcache import enabled_from_env

                dedup = enabled_from_env(True)
        self._dedup = bool(dedup)
        # Digest-routed engines (the front-door router): the sync
        # flush path calls ``verify_batch_digests(tokens, digests)``
        # so reader/cache-computed digests survive the batcher instead
        # of being re-hashed per hop. Async dispatch wins when a
        # keyset exposes both.
        self._wants_digests = hasattr(keyset, "verify_batch_digests")
        self._target = target_batch
        self._max_wait = max_wait_ms / 1000.0
        self._max_batch = max_batch
        # Admission watermark: submit_nowait blocks once this many
        # tokens are queued (pipelined connections then push the
        # backpressure into TCP instead of growing the queue without
        # bound). 0 → 4 device batches of headroom.
        self._max_queued = max_queued_tokens or 4 * max_batch
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[_Pending] = []
        self._queued_tokens = 0
        self._closed = False
        # Flush-reason attribution + last-flush lifecycle (occupancy
        # plane, docs/OBSERVABILITY.md §Occupancy plane). Written only
        # from the dispatcher/collector threads; reads take racy-but-
        # consistent dict copies (stats()).
        self._flush_reasons: Dict[str, int] = {}
        self._last_flush: Dict[str, Any] = {}
        self._gauges_decayed = False
        # 2-deep pipeline: one batch draining in the collector while
        # the dispatcher preps/dispatches the next. TWO slots, each
        # acquired BEFORE dispatching and released when the collector
        # finishes draining that batch: batch k+1's host prep/H2D runs
        # while batch k drains (the point of the pipeline), and batch
        # k+2's dispatch blocks until k is collected — a bounded queue
        # alone would admit a third batch's device work first.
        self._inflight: "queue.Queue" = queue.Queue()
        self._slot = threading.Semaphore(2)
        self._collector = threading.Thread(
            target=self._collect_loop, daemon=True,
            name="cap-tpu-collector")
        self._collector.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cap-tpu-batcher")
        self._thread.start()

    # -- submission side --------------------------------------------------

    def submit(self, tokens: Sequence[str]) -> List[Any]:
        """Block until the batch containing ``tokens`` is verified."""
        p = self.submit_nowait(tokens)
        p.event.wait()
        assert p.results is not None
        return p.results

    def submit_nowait(self, tokens: Sequence[str],
                      trace: Optional[str] = None,
                      digests: Optional[Sequence[Optional[bytes]]]
                      = None) -> "_Pending":
        """Enqueue and return the pending handle WITHOUT waiting.

        The caller waits on ``pending.event`` and reads
        ``pending.results``. This is what lets a serve connection keep
        READING frames while earlier submissions verify — request
        pipelining (VERDICT r3 #7). ``trace``: telemetry trace id for
        this submission (the worker passes the wire's trace-context).
        ``digests``: optional per-token sha256[:16] for digest-routed
        engines.
        """
        return self._admit(_Pending(list(tokens), trace=trace,
                                    digests=digests))

    def submit_handoff(self, tokens: Sequence[str],
                       traces: Sequence[str] = (),
                       on_done=None,
                       digests: Optional[Sequence[Optional[bytes]]]
                       = None) -> "_Pending":
        """Batch handoff for ring-draining front ends (the native
        serve chain): enqueue one whole drained chunk, with ``traces``
        (the union of its requests' trace ids, for fill/dispatch span
        attribution) and ONE ``on_done(results)`` callback invoked
        from the dispatcher/collector thread when the chunk's verdicts
        are ready — the caller never parks a thread per submission and
        never registers per-token callbacks."""
        return self._admit(_Pending(list(tokens), traces=traces,
                                    on_done=on_done, digests=digests,
                                    handoff=True))

    def _admit(self, p: "_Pending") -> "_Pending":
        if not p.tokens:
            p.results = []
            p.event.set()
            if p.on_done is not None:
                p.on_done(p.results)
            return p
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher is closed")
            # Admission control: hold the caller (a serve reader
            # thread) while the queue is saturated — an empty queue
            # always admits, so one oversized submission can't wedge.
            while (self._queued_tokens > 0
                   and self._queued_tokens + len(p.tokens)
                   > self._max_queued and not self._closed):
                self._cv.wait()
            if self._closed:
                raise RuntimeError("batcher is closed")
            if self._sched is not None:
                from . import drr as _drr

                self._sched.push(_drr.sched_slot_for_tokens(p.tokens),
                                 p, len(p.tokens))
            else:
                self._queue.append(p)
            self._queued_tokens += len(p.tokens)
            self._cv.notify_all()
        return p

    def set_weight(self, slot: int, w: int) -> None:
        """Per-tenant DRR weight (fair mode only; slot = tenant slot,
        ``drr.SCHED_BE`` for the best-effort slot)."""
        if self._sched is not None:
            with self._lock:
                self._sched.set_weight(slot, w)

    # -- fair-mode pending accessors (called under self._lock) ------------

    def _have_pending(self) -> bool:
        if self._sched is not None:
            return self._carry is not None or self._sched.n > 0
        return bool(self._queue)

    def _oldest_ts(self) -> float:
        if self._sched is None:
            return self._queue[0].ts
        oldest = self._carry.ts if self._carry is not None else None
        ts = self._sched.peek_oldest_ts(lambda p: p.ts)
        if ts is not None and (oldest is None or ts < oldest):
            oldest = ts
        return oldest if oldest is not None else time.monotonic()

    def _take_batch(self):
        """Next flush's members: FIFO order, or DRR order in fair mode
        (a popped submission that would overflow max_batch carries to
        the next flush — same carry semantics as the native drain)."""
        batch: List[_Pending] = []
        n = 0
        if self._sched is None:
            while self._queue and n < self._max_batch:
                nxt = self._queue[0]
                if batch and n + len(nxt.tokens) > self._max_batch:
                    break
                batch.append(self._queue.pop(0))
                n += len(nxt.tokens)
            return batch, n
        while n < self._max_batch:
            p = self._carry
            self._carry = None
            if p is None:
                p = self._sched.pop()
            if p is None:
                break
            if batch and n + len(p.tokens) > self._max_batch:
                self._carry = p
                break
            batch.append(p)
            n += len(p.tokens)
        return batch, n

    def depth(self) -> Dict[str, int]:
        """Queue-depth snapshot: tokens awaiting dispatch + batches in
        flight on the device (the fleet STATS op reads this)."""
        with self._lock:
            queued = self._queued_tokens
        return {"queued_tokens": queued,
                "inflight_batches": self._inflight.qsize()}

    def stats(self) -> Dict[str, Any]:
        """Depth plus occupancy-plane extras: cumulative flush-reason
        counts and the last flush's lifecycle durations. ADDITIVE —
        the keys are absent until the first flush, so STATS frames of
        a batcher that never flushed are byte-identical to before this
        surface existed."""
        out: Dict[str, Any] = self.depth()
        reasons = dict(self._flush_reasons)
        if reasons:
            out["flush_reasons"] = reasons
        last = dict(self._last_flush)
        if last:
            out["last_flush"] = last
        return out

    def close(self, deadline_s: float = 120.0) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify()
        # The dispatcher may be blocked in _slot.acquire() for its LAST
        # batch while the collector sits in a multi-second device sync —
        # wait it out, but bound the whole shutdown: if a device sync
        # wedges past the deadline, give up and return; both threads
        # are daemons, and the collector keeps draining whatever the
        # dispatcher hands it until the dispatcher-side DONE sentinel.
        limit = time.monotonic() + deadline_s
        while self._thread.is_alive() and time.monotonic() < limit:
            self._thread.join(timeout=2.0)
        self._collector.join(timeout=max(1.0, limit - time.monotonic()))

    # -- dispatcher -------------------------------------------------------

    def _run(self) -> None:
        try:
            self._run_loop()
        finally:
            self._inflight.put(_DISPATCHER_DONE)

    def _run_loop(self) -> None:
        while True:
            with self._cv:
                if not self._have_pending() and not self._gauges_decayed:
                    # Staleness fix: an emptied queue decays its depth
                    # gauges to 0 instead of freezing the last flush's
                    # values on the scrape surface forever.
                    telemetry.gauge("batcher.queued_tokens", 0)
                    telemetry.gauge("batcher.fill_ratio", 0.0)
                    self._gauges_decayed = True
                while not self._have_pending() and not self._closed:
                    self._cv.wait()
                if self._closed and not self._have_pending():
                    return
                # Wait for more work up to the flush condition: the
                # OLDEST queued submission waits at most max_wait.
                while (self._queued_tokens < self._target
                       and not self._closed):
                    remaining = (self._oldest_ts() + self._max_wait
                                 - time.monotonic())
                    if remaining <= 0:
                        break
                    self._cv.wait(timeout=remaining)
                # Flush-reason attribution, decided while the queue
                # state that caused the flush is still visible.
                if self._queued_tokens >= self._target:
                    reason = "size"
                elif self._closed:
                    reason = "close"
                else:
                    reason = "timeout"
                batch, n = self._take_batch()
                self._queued_tokens -= n
                if n:
                    self._cv.notify_all()   # wake admission waiters
            if not batch:
                continue
            if reason == "size" and len(batch) == 1 and batch[0].handoff:
                # One drained ring chunk alone met the size target —
                # the native chain's characteristic flush shape.
                reason = "handoff"
            elif reason == "close" and n >= self._target:
                reason = "drain"       # full batch while closing
            self._flush(batch, n, reason)

    def _flush(self, batch: List[_Pending], n: int,
               reason: str = "size") -> None:
        t_flush = time.monotonic()
        tokens: List[str] = []
        for p in batch:
            tokens.extend(p.tokens)
        telemetry.count("batcher.flushes")
        telemetry.count(f"batcher.flush.{reason}")
        self._flush_reasons[reason] = \
            self._flush_reasons.get(reason, 0) + 1
        telemetry.observe("batcher.batch_size", float(n))
        # Depth/fill gauges at flush time: what the exposition surface
        # shows as the batcher's current operating point.
        telemetry.gauge("batcher.queued_tokens", self.depth()["queued_tokens"])
        telemetry.gauge("batcher.fill_ratio", n / self._target)
        self._gauges_decayed = False
        telemetry.observe("batcher.fill_ratio", n / self._target)
        now_wall = time.time()
        telemetry.observe("batcher.fill_wait_s", t_flush - batch[0].ts)
        # Stage waterfall (docs/OBSERVABILITY.md §Occupancy plane):
        # per-member queueing delay submit → flush start.
        for p in batch:
            telemetry.observe("queue.batcher_wait_s", t_flush - p.ts)
        lf: Dict[str, Any] = {"t_wall": now_wall, "reason": reason,
                              "batch_size": n,
                              "batcher_wait_s": t_flush - batch[0].ts}
        self._last_flush = lf
        # Per-request FILL span (submit -> flush start), then run the
        # flush/dispatch under the union of member traces so engine
        # spans (dispatch.<family>.*) attach to every traced request
        # in the coalesced batch.
        traces = []
        for p in batch:
            for tid in (p.traces or ((p.trace,) if p.trace else ())):
                traces.append(tid)
                telemetry.trace_span(tid, telemetry.SPAN_BATCHER_FILL,
                                     p.t0_wall, now_wall - p.t0_wall)
        # Per-token digests for digest-routed engines: token-aligned,
        # None where a submitter had none (the engine hashes those
        # itself — digest is a pure function of the token, so a mixed
        # list is still exact).
        digests: Optional[List[Optional[bytes]]] = None
        if self._wants_digests:
            digests = []
            for p in batch:
                if p.digests is not None and len(p.digests) \
                        == len(p.tokens):
                    digests.extend(p.digests)
                else:
                    digests.extend([None] * len(p.tokens))
        # In-flight dedup: collapse identical tokens queued in this
        # flush to ONE dispatch slot each; the verdict fans back out
        # in _expand. Digest equality == token equality (the vcache's
        # sha256 contract), so string identity is the same key.
        send_tokens = tokens
        send_digests = digests
        expand: Optional[List[int]] = None
        # len(set()) probe first: all-unique flushes (the common case
        # once the vcache absorbs repeats upstream) pay one C-speed
        # pass, not a per-token Python dict loop.
        if self._dedup and n > 1 and len(set(tokens)) < n:
            first: Dict[Any, int] = {}
            idx_map: List[int] = []
            uniq: List[Any] = []
            uniq_dig: List[Optional[bytes]] = []
            for i, t in enumerate(tokens):
                j = first.get(t)
                if j is None:
                    j = first[t] = len(uniq)
                    uniq.append(t)
                    if digests is not None:
                        uniq_dig.append(digests[i])
                idx_map.append(j)
            telemetry.count("batcher.dedup_fanout", n - len(uniq))
            send_tokens = uniq
            if digests is not None:
                send_digests = uniq_dig
            expand = idx_map
        dispatch = getattr(self._keyset, "verify_batch_async", None)
        if dispatch is not None:
            self._slot.acquire()          # backpressure BEFORE dispatch
            # flush → dispatch gap: dominated by _slot.acquire, i.e.
            # the 2-deep pipeline's backpressure on the device.
            t_dispatch = time.monotonic()
            telemetry.observe("queue.dispatch_gap_s", t_dispatch - t_flush)
            lf["dispatch_gap_s"] = t_dispatch - t_flush
            try:
                with telemetry.trace_scope(traces), \
                        telemetry.span(telemetry.SPAN_BATCHER_DISPATCH):
                    collect = dispatch(send_tokens)
            except Exception as e:  # noqa: BLE001 - fan the failure out
                self._slot.release()
                self._distribute(batch, [e] * n)
                return
            self._inflight.put((batch, n, collect, expand, t_dispatch, lf))
            return
        t_dispatch = time.monotonic()
        telemetry.observe("queue.dispatch_gap_s", t_dispatch - t_flush)
        lf["dispatch_gap_s"] = t_dispatch - t_flush
        try:
            with telemetry.trace_scope(traces), \
                    telemetry.span(telemetry.SPAN_BATCHER_FLUSH):
                if self._wants_digests:
                    raw = self._keyset.verify_batch_digests(
                        send_tokens, send_digests)
                else:
                    raw = self._keyset.verify_batch(send_tokens)
                results = self._expand(raw, expand)
        except Exception as e:  # noqa: BLE001 - fan the failure out
            results = [e] * n
        exec_s = time.monotonic() - t_dispatch
        telemetry.observe("device.exec_s", exec_s)
        lf["exec_s"] = exec_s
        self._distribute(batch, results)

    @staticmethod
    def _expand(results: List[Any],
                expand: Optional[List[int]]) -> List[Any]:
        """Fan a deduped dispatch's verdicts back out to every queued
        position (shared verdict objects — verify is deterministic and
        downstream only reads them)."""
        if expand is None:
            return results
        return [results[j] for j in expand]

    def _collect_loop(self) -> None:
        # The dispatcher enqueues _DISPATCHER_DONE on exit, so by FIFO
        # order every batch it ever dispatched is collected before this
        # loop returns — even when close()'s deadline expired while
        # batches were still dispatching, submitters are never stranded
        # in event.wait(). (A dispatcher that dies without the sentinel
        # is impossible short of interpreter teardown; both threads are
        # daemons regardless.)
        while True:
            item = self._inflight.get()
            if item is _DISPATCHER_DONE:
                return
            batch, n_tokens, collect, expand, t_dispatch, lf = item
            traces = [tid for p in batch
                      for tid in (p.traces
                                  or ((p.trace,) if p.trace else ()))]
            try:
                with telemetry.trace_scope(traces), \
                        telemetry.span(telemetry.SPAN_BATCHER_COLLECT):
                    results = self._expand(collect(), expand)
            except Exception as e:  # noqa: BLE001 - fan the failure out
                results = [e] * n_tokens
            finally:
                self._slot.release()
            # dispatch → collect-done: the device-execution stage of
            # the waterfall (includes the in-flight overlap window).
            exec_s = time.monotonic() - t_dispatch
            telemetry.observe("device.exec_s", exec_s)
            lf["exec_s"] = exec_s
            self._distribute(batch, results)

    @staticmethod
    def _distribute(batch: List[_Pending], results: List[Any]) -> None:
        off = 0
        now = time.time()
        for p in batch:
            p.results = list(results[off: off + len(p.tokens)])
            off += len(p.tokens)
            if p.trace:
                # Close the traced request's worker-side timeline into
                # the flight ring (spans: fill, flush/dispatch/collect,
                # any engine dispatch.* recorded under the batch scope)
                # BEFORE waking the submitter, so a scrape racing the
                # response already sees the completed timeline.
                telemetry.flight(p.trace, now - p.t0_wall)
            p.event.set()
            if p.on_done is not None:
                # Batch handoff: the whole chunk's verdicts in one
                # call, from this (dispatcher/collector) thread. The
                # native chain records its traced requests' flight
                # entries itself (it knows each request's t0).
                try:
                    p.on_done(p.results)
                except Exception:  # noqa: BLE001 - never kill the loop
                    telemetry.count("batcher.handoff_errors")
