"""The verify service layer: the framework's communication backend.

The reference is a pure in-process library — its only "communication
backend" is HTTPS to the IdP (SURVEY.md §5). The TPU-native framework
adds a real one: host applications (any language) talk to a colocated
verify worker that owns the device and the batched KeySet, over a
length-prefixed binary protocol on TCP/UDS (``protocol``), through an
adaptive batcher (``batcher``) that trades p99 latency against batch
throughput. ``worker`` is the server; ``client`` the Python client;
the C runtime ships a matching native client shim.

Redaction discipline (reference: oidc/config.go:20-31 etc.) carries
across the wire: the service never logs tokens, keys, or claims —
telemetry records only counts and timings.
"""

from .batcher import AdaptiveBatcher
from .client import VerifyClient
from .vcache import VerdictCache
from .worker import VerifyWorker

__all__ = ["AdaptiveBatcher", "VerdictCache", "VerifyClient",
           "VerifyWorker"]
