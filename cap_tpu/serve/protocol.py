"""Wire protocol for the verify service: length-prefixed binary frames.

Deliberately trivial to implement from any language (the C runtime has
a native client): fixed little-endian framing, no schema compiler.

Frame layout (all integers little-endian):

    magic   u32   0x31425643 ("CVB1")
    type    u8    1 = verify request, 2 = verify response, 3 = ping,
                  4 = pong
    count   u32   number of entries
    entries:
      request entry:   len u32, token bytes (UTF-8 compact JWS)
      response entry:  status u8 (0 = verified, 1 = rejected),
                       len u32, payload bytes
                       (claims JSON when verified; error string when
                       rejected — the error CLASS name plus message,
                       never the token itself)

Secrets stance: tokens cross this boundary by necessity (the worker
must verify them); nothing here logs, copies, or echoes them beyond
the response payload, and error strings never embed token material.
"""

from __future__ import annotations

import json
import socket
import struct
from typing import Any, List, Sequence, Tuple

MAGIC = 0x31425643
T_VERIFY_REQ = 1
T_VERIFY_RESP = 2
T_PING = 3
T_PONG = 4

_HDR = struct.Struct("<IBI")

MAX_FRAME_ENTRIES = 1 << 20
MAX_ENTRY_BYTES = 1 << 20
MAX_FRAME_BYTES = 1 << 28        # aggregate cap: one frame ≤ 256 MiB


class ProtocolError(Exception):
    pass


_LEN_U32 = struct.Struct("<I")
_LEN_BU32 = struct.Struct("<BI")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return bytes(buf)


def send_request(sock: socket.socket, tokens: Sequence[str]) -> None:
    parts = [_HDR.pack(MAGIC, T_VERIFY_REQ, len(tokens))]
    for t in tokens:
        raw = t.encode()
        parts.append(struct.pack("<I", len(raw)))
        parts.append(raw)
    sock.sendall(b"".join(parts))


def send_response(sock: socket.socket, results: Sequence[Any]) -> None:
    """results: claims (dict, or the raw payload-JSON bytes the worker
    verified — sent verbatim, zero re-serialization) or Exception."""
    parts = [_HDR.pack(MAGIC, T_VERIFY_RESP, len(results))]
    for r in results:
        if isinstance(r, Exception):
            payload = f"{type(r).__name__}: {r}".encode()
            parts.append(struct.pack("<BI", 1, len(payload)))
        elif isinstance(r, (bytes, bytearray, memoryview)):
            payload = bytes(r)
            parts.append(struct.pack("<BI", 0, len(payload)))
        else:
            payload = json.dumps(r, separators=(",", ":")).encode()
            parts.append(struct.pack("<BI", 0, len(payload)))
        parts.append(payload)
    sock.sendall(b"".join(parts))


def send_ping(sock: socket.socket) -> None:
    sock.sendall(_HDR.pack(MAGIC, T_PING, 0))


def send_pong(sock: socket.socket) -> None:
    sock.sendall(_HDR.pack(MAGIC, T_PONG, 0))


def recv_frame(sock: socket.socket) -> Tuple[int, List[Any]]:
    """Read one frame → (type, entries), exact reads (no buffering).

    Request entries are token strings; response entries are
    (status, payload-bytes) pairs. Hot loops should use
    :class:`FrameReader` instead — this per-entry exact-read form
    costs two syscalls and two allocations per entry and measured
    374k tokens/s on one core vs FrameReader's buffered parse
    (docs/PERF.md r5 serve projection); it stays for one-shot uses
    and as the simplest reference of the wire format.
    """
    return _parse_frame(lambda n: _recv_exact(sock, n))


def _parse_frame(take) -> Tuple[int, List[Any]]:
    """Shared CVB1 frame parse over a ``take(n) -> bytes`` source."""
    magic, ftype, count = _HDR.unpack(take(_HDR.size))
    if magic != MAGIC:
        raise ProtocolError(f"bad magic 0x{magic:08x}")
    if count > MAX_FRAME_ENTRIES:
        raise ProtocolError(f"frame too large: {count} entries")
    entries: List[Any] = []
    total = 0
    u32 = _LEN_U32.unpack
    bu32 = _LEN_BU32.unpack
    if ftype == T_VERIFY_REQ:
        for _ in range(count):
            (ln,) = u32(take(4))
            total += ln
            if ln > MAX_ENTRY_BYTES or total > MAX_FRAME_BYTES:
                raise ProtocolError(f"frame too large ({total} bytes)")
            entries.append(take(ln).decode())
    elif ftype == T_VERIFY_RESP:
        for _ in range(count):
            status, ln = bu32(take(5))
            total += ln
            if ln > MAX_ENTRY_BYTES or total > MAX_FRAME_BYTES:
                raise ProtocolError(f"frame too large ({total} bytes)")
            entries.append((status, take(ln)))
    elif ftype in (T_PING, T_PONG):
        pass
    else:
        raise ProtocolError(f"unknown frame type {ftype}")
    return ftype, entries


class FrameReader:
    """Buffered CVB1 frame reader: one ~64 KiB recv instead of two
    syscalls per entry.

    The wire has no frame-length prefix, so buffered reads can consume
    the start of the NEXT frame — leftover bytes are retained across
    calls, which means a socket must be read EXCLUSIVELY through one
    FrameReader once attached (the worker's reader thread and the
    client already own their sockets' read sides exclusively).
    """

    __slots__ = ("_sock", "_buf", "_off")

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""
        self._off = 0

    def _take(self, n: int) -> bytes:
        buf, off = self._buf, self._off
        if len(buf) - off < n:
            parts = [buf[off:]] if off < len(buf) else []
            got = len(buf) - off
            while got < n:
                chunk = self._sock.recv(max(n - got, 1 << 16))
                if not chunk:
                    raise ConnectionError("peer closed mid-frame")
                parts.append(chunk)
                got += len(chunk)
            buf = b"".join(parts)
            off = 0
            self._buf = buf
        self._off = off + n
        return buf[off:off + n]

    def recv_frame(self) -> Tuple[int, List[Any]]:
        out = _parse_frame(self._take)
        # Drop the consumed prefix so an idle connection never pins a
        # whole parsed frame (frames may be up to MAX_FRAME_BYTES).
        if self._off:
            self._buf = self._buf[self._off:]
            self._off = 0
        return out
