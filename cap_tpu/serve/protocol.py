"""Wire protocol for the verify service: length-prefixed binary frames.

Deliberately trivial to implement from any language (the C runtime has
a native client): fixed little-endian framing, no schema compiler.

Frame layout (all integers little-endian):

    magic   u32   0x31425643 ("CVB1")
    type    u8    1 = verify request, 2 = verify response, 3 = ping,
                  4 = pong, 5 = stats request, 6 = stats response,
                  7 = checksummed verify request,
                  8 = checksummed verify response,
                  9 = traced verify request,
                  10 = traced verify response,
                  11 = keys push (keyplane),
                  12 = keys ack (keyplane),
                  13 = peer fill (verdict-cache warming),
                  14 = peer fill ack,
                  15 = shm attach (shared-memory transport),
                  16 = shm attach ack
    count   u32   number of entries
    trace-context (types 9/10 only, between header and entries):
      ctx_len u8   length of the trace-context field (1..64)
      ctx     …    ctx_len bytes: the trace id, lowercase hex ASCII
                   (16 chars as emitted by telemetry.new_trace_id)
    entries:
      request entry:   len u32, token bytes (UTF-8 compact JWS)
      response entry:  status u8 (0 = verified, 1 = rejected),
                       len u32, payload bytes
                       (claims JSON when verified; error string when
                       rejected — the error CLASS name plus message,
                       never the token itself)
      stats response:  exactly one response-shaped entry whose payload
                       is the worker's stats JSON (counts and timings
                       only — redaction discipline applies)
    trailer (types 7/8 only):
      crc32   u32   zlib.crc32 over every frame byte from the magic
                    through the last entry byte

Types 7/8 are the fleet router's integrity envelope: a worker answers
a checksummed request with a checksummed response, so a flipped byte
anywhere in either direction (status, lengths, payload) surfaces as
:class:`FrameCorruptError` instead of a silently wrong verdict. Plain
clients (Go, native, VerifyClient default) keep the exact CVB1 bytes
of types 1-4 — the golden vectors are unchanged.

Types 11/12 are the keyplane's distribution pair, ADDITIVE like 9/10
(types 1-10 keep their exact bytes — the golden vectors pin them):

- **KEYS push (11)**: checksummed, exactly ONE request-shaped entry
  whose payload is the key-distribution JSON
  ``{"epoch": <int>, "jwks": {"keys": [...]}}`` — canonical form
  (sorted keys, compact separators) so identical snapshots serialize
  identically. Public key material only (a JWKS by definition);
  redaction discipline for tokens/claims is untouched.
- **KEYS ack (12)**: checksummed, exactly ONE response-shaped entry:
  status 0 + ``{"epoch": <int>}`` when the worker swapped its tables
  onto the pushed epoch, status 1 + an error string (class name +
  message, never key material) when it could not.

A corrupt push must never install half a key set — the CRC check runs
before the payload is even decoded, same stance as types 7-10.

Types 13/14 are the verdict-cache PEER-FILL pair, ADDITIVE exactly
like the KEYS pair (types 1-12 keep their bytes — the golden vectors
pin them):

- **peer fill (13)**: checksummed, exactly ONE request-shaped entry
  whose payload is the peer-fill JSON in canonical form. Two ops:
  ``{"max": <int>, "op": "export"}`` asks a worker to dump (a bounded
  slice of) its verdict cache; ``{"entries": [...], "epoch": <int>,
  "op": "import"}`` hands a dump to a freshly (re)spawned worker.
  Each entry is ``[digest_hex, payload_b64, valid_from, valid_until,
  exp_or_null]`` — ACCEPTS only, and the receiver re-clamps every
  entry (epoch equality, exp/nbf, its own TTL) so an import can only
  ever SHORTEN a verdict's validity, never extend it
  (:meth:`cap_tpu.serve.vcache.VerdictCache.import_entries`).
- **peer fill ack (14)**: checksummed, exactly ONE response-shaped
  entry: status 0 + the op's result JSON (``{"entries": ..,
  "epoch": ..}`` for export, ``{"imported": N}`` for import), status
  1 + an error string when the worker has no cache tier or the
  payload is unusable.

Secrets stance for 13/14: digests are one-way hashes and payloads are
the claims JSON a verify response would carry anyway — no token ever
crosses in either direction, and error strings stay class+message.

Types 15/16 negotiate the ZERO-COPY shared-memory transport (docs/
SERVE.md §Transports), ADDITIVE exactly like the KEYS pair (types
1-14 keep their bytes — the golden vectors pin them):

- **shm attach (15)**: checksummed, exactly ONE request-shaped entry
  whose payload is the canonical JSON ``{"op": "attach", "path":
  <region file>, "version": 1}``. The CLIENT creates and maps the
  region file (header + request ring + response ring — layout in
  cap_tpu/serve/shm_ring.py, mirrored by runtime/native/shm_ring.h);
  the worker maps the same file and, from the next frame on, consumes
  requests from the request ring and posts responses into the
  response ring. The socket stays open as the LIVENESS channel only.
- **shm attach ack (16)**: checksummed, exactly ONE response-shaped
  entry, sent over the SOCKET (the client confirms the switch before
  producing): status 0 + ``{"transport":"shm"}`` when the worker
  mapped the region, status 1 + an error string when the transport is
  off or the region is unusable — the connection then keeps serving
  over the socket unchanged (``serve.shm_fallbacks``), which is the
  whole fallback contract: a client NEVER loses a connection to a
  refused attach. Workers whose library predates the pair drop the
  connection on the unknown type instead; clients treat that exactly
  like a refusal and redial socket-only.

Types 9/10 are the TRACED variant of 7/8: same checksummed envelope
plus one additive trace-context field between the header and the
entries, so a request's 16-hex trace id crosses the process boundary
and the worker's span records (batcher fill, device dispatch — see
:mod:`cap_tpu.telemetry`) can be joined with the router's client-side
spans into one cross-process timeline. A worker answers a traced
request with a traced response echoing the same trace id. The field
is validated AFTER the CRC matches (like status bytes) and must be
lowercase-hex ASCII — it can never carry payload material. Frame
types 1-8 are byte-identical to before this field existed
(tests/test_conformance.py pins all of them against the committed
golden vectors).

Hardening stance: every length prefix is bound-checked BEFORE any
allocation or read of entry bytes (a hostile or corrupt frame cannot
make the parser allocate unbounded memory), and malformed values
(unknown type, bad magic, nonzero ping/pong count, status byte
outside {0, 1}) raise typed subclasses of :class:`ProtocolError`.
Liveness against a peer that claims N entries and then stalls is the
CALLER's job (socket timeouts / fleet router deadlines) — a blocking
read cannot be both exact and self-timing.

Secrets stance: tokens cross this boundary by necessity (the worker
must verify them); nothing here logs, copies, or echoes them beyond
the response payload, and error strings never embed token material.
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

MAGIC = 0x31425643
T_VERIFY_REQ = 1
T_VERIFY_RESP = 2
T_PING = 3
T_PONG = 4
T_STATS_REQ = 5
T_STATS_RESP = 6
T_VERIFY_REQ_CRC = 7
T_VERIFY_RESP_CRC = 8
T_VERIFY_REQ_TRACE = 9
T_VERIFY_RESP_TRACE = 10
T_KEYS_PUSH = 11
T_KEYS_ACK = 12
T_PEER_FILL = 13
T_PEER_ACK = 14
T_SHM_ATTACH = 15
T_SHM_ACK = 16

_HDR = struct.Struct("<IBI")

MAX_FRAME_ENTRIES = 1 << 20
MAX_ENTRY_BYTES = 1 << 20
MAX_FRAME_BYTES = 1 << 28        # aggregate cap: one frame ≤ 256 MiB
MAX_TRACE_BYTES = 64             # trace-context field length bound
_TRACE_HEX = frozenset(b"0123456789abcdef")


class ProtocolError(Exception):
    """Base class for CVB1 wire-format violations."""


# ---------------------------------------------------------------------------
# admission pushback (r20): the wire encoding is ADDITIVE on the
# existing status-1 response entry — a throttled token's payload is
# the ordinary "<ErrorClass>: <message>" error string whose class head
# is ``ThrottledError`` and whose message carries a machine-parseable
# ``retry_after_ms=<int>`` hint. Frames stay byte-identical when
# admission is off (no throttled entries then), so every committed
# golden vector is untouched and stale clients simply see one more
# rejected-token error class.
# ---------------------------------------------------------------------------

_RETRY_AFTER_RE = None


def is_throttled_payload(payload: str) -> bool:
    """Whether a status-1 entry's error string is an admission
    pushback (class head ``ThrottledError``) rather than a verify
    verdict."""
    return payload.startswith("ThrottledError")


def retry_after_hint(payload: str) -> Optional[float]:
    """Parse the additive ``retry_after_ms=<int>`` hint out of a
    pushback payload → seconds, or None when absent/unparseable.
    Never raises: a garbled hint degrades to "no hint", the same
    stance as every other additive field."""
    global _RETRY_AFTER_RE
    if _RETRY_AFTER_RE is None:
        import re

        _RETRY_AFTER_RE = re.compile(r"retry_after_ms=(\d{1,9})")
    m = _RETRY_AFTER_RE.search(payload)
    if not m:
        return None
    return int(m.group(1)) / 1000.0


class MalformedFrameError(ProtocolError):
    """Structurally invalid frame: bad magic, unknown type, nonzero
    ping/pong count, or a response status byte outside {0, 1}."""


class FrameTooLargeError(ProtocolError):
    """A length prefix or entry count exceeds the protocol bounds.

    Raised BEFORE any allocation for the oversized region — a hostile
    length (e.g. 0xFFFFFFFF, a "negative" i32) costs nothing."""


class FrameCorruptError(ProtocolError):
    """A checksummed frame's CRC32 trailer does not match its bytes."""


_LEN_U32 = struct.Struct("<I")
_LEN_BU32 = struct.Struct("<BI")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed mid-frame")
        got += r
    return bytes(buf)


def _with_crc(parts: List[bytes]) -> List[bytes]:
    crc = 0
    for p in parts:
        crc = zlib.crc32(p, crc)
    parts.append(_LEN_U32.pack(crc & 0xFFFFFFFF))
    return parts


def _trace_field(trace: str) -> bytes:
    raw = trace.encode("ascii")
    if not (0 < len(raw) <= MAX_TRACE_BYTES
            and all(b in _TRACE_HEX for b in raw)):
        raise MalformedFrameError(
            f"invalid trace id ({len(raw)} bytes; must be 1..:"
            f"{MAX_TRACE_BYTES} lowercase-hex chars)")
    return bytes([len(raw)]) + raw


def send_request(sock: socket.socket, tokens: Sequence[str],
                 crc: bool = False, trace: Optional[str] = None) -> None:
    """trace: a telemetry trace id; selects the traced checksummed
    frame (type 9) carrying the trace-context field."""
    ftype = (T_VERIFY_REQ_TRACE if trace is not None
             else T_VERIFY_REQ_CRC if crc else T_VERIFY_REQ)
    parts = [_HDR.pack(MAGIC, ftype, len(tokens))]
    if trace is not None:
        parts.append(_trace_field(trace))
    for t in tokens:
        raw = t.encode()
        parts.append(struct.pack("<I", len(raw)))
        parts.append(raw)
    if trace is not None or crc:
        _with_crc(parts)
    sock.sendall(b"".join(parts))


def _response_parts(ftype: int, results: Sequence[Any]) -> List[bytes]:
    parts = [_HDR.pack(MAGIC, ftype, len(results))]
    for r in results:
        if isinstance(r, Exception):
            payload = f"{type(r).__name__}: {r}".encode()
            parts.append(struct.pack("<BI", 1, len(payload)))
        elif isinstance(r, (bytes, bytearray, memoryview)):
            payload = bytes(r)
            parts.append(struct.pack("<BI", 0, len(payload)))
        else:
            payload = json.dumps(r, separators=(",", ":")).encode()
            parts.append(struct.pack("<BI", 0, len(payload)))
        parts.append(payload)
    return parts


def send_response(sock: socket.socket, results: Sequence[Any],
                  crc: bool = False, trace: Optional[str] = None) -> None:
    """results: claims (dict, or the raw payload-JSON bytes the worker
    verified — sent verbatim, zero re-serialization) or Exception.
    trace: echo of the request's trace id (traced frame, type 10)."""
    if trace is not None:
        parts = _response_parts(T_VERIFY_RESP_TRACE, results)
        parts.insert(1, _trace_field(trace))
        _with_crc(parts)
    elif crc:
        parts = _with_crc(_response_parts(T_VERIFY_RESP_CRC, results))
    else:
        parts = _response_parts(T_VERIFY_RESP, results)
    sock.sendall(b"".join(parts))


def encode_response(results: Sequence[Any], crc: bool = False,
                    trace: Optional[str] = None) -> bytes:
    """Encoded verify-response frame bytes (same family selection as
    :func:`send_response`). The native front-door gate posts these
    verbatim through ``cap_frontdoor_post_raw`` for slow-path frames,
    so the socket writer and the relay writer share one encoder."""
    if trace is not None:
        parts = _response_parts(T_VERIFY_RESP_TRACE, results)
        parts.insert(1, _trace_field(trace))
        _with_crc(parts)
    elif crc:
        parts = _with_crc(_response_parts(T_VERIFY_RESP_CRC, results))
    else:
        parts = _response_parts(T_VERIFY_RESP, results)
    return b"".join(parts)


def send_ping(sock: socket.socket) -> None:
    sock.sendall(_HDR.pack(MAGIC, T_PING, 0))


def send_pong(sock: socket.socket) -> None:
    sock.sendall(_HDR.pack(MAGIC, T_PONG, 0))


def send_stats_request(sock: socket.socket) -> None:
    sock.sendall(_HDR.pack(MAGIC, T_STATS_REQ, 0))


def encode_stats_response(stats: Any) -> bytes:
    """Encoded stats-response frame bytes (one response-shaped entry
    carrying the stats JSON object). The native serve chain posts
    these verbatim, so both chains share one encoder."""
    payload = json.dumps(stats, separators=(",", ":")).encode()
    return (_HDR.pack(MAGIC, T_STATS_RESP, 1)
            + struct.pack("<BI", 0, len(payload)) + payload)


def send_stats_response(sock: socket.socket, stats: Any) -> None:
    """One response-shaped entry carrying the stats JSON object."""
    sock.sendall(encode_stats_response(stats))


def keys_payload(jwks_doc: Dict[str, Any], epoch: int) -> bytes:
    """Canonical KEYS-push payload bytes: sorted keys + compact
    separators, so one snapshot has one wire encoding (golden vectors
    and dedup both rely on it)."""
    return json.dumps({"epoch": int(epoch), "jwks": jwks_doc},
                      separators=(",", ":"), sort_keys=True).encode()


def send_keys_push(sock: socket.socket, jwks_doc: Dict[str, Any],
                   epoch: int) -> None:
    """Checksummed KEYS push (type 11): one entry, the epoch+JWKS JSON."""
    payload = keys_payload(jwks_doc, epoch)
    if len(payload) > MAX_ENTRY_BYTES:
        raise FrameTooLargeError(
            f"keys payload {len(payload)} bytes exceeds entry bound")
    parts = [_HDR.pack(MAGIC, T_KEYS_PUSH, 1),
             _LEN_U32.pack(len(payload)), payload]
    sock.sendall(b"".join(_with_crc(parts)))


def encode_keys_ack(epoch: Optional[int] = None,
                    error: Optional[str] = None) -> bytes:
    """Encoded checksummed KEYS-ack frame bytes (type 12): status 0 +
    {"epoch": N} on a successful swap, status 1 + error string
    otherwise. Shared by the socket sender and the native chain."""
    if error is None:
        status, payload = 0, json.dumps(
            {"epoch": int(epoch or 0)}, separators=(",", ":")).encode()
    else:
        status, payload = 1, error.encode()
    parts = [_HDR.pack(MAGIC, T_KEYS_ACK, 1),
             _LEN_BU32.pack(status, len(payload)), payload]
    return b"".join(_with_crc(parts))


def send_keys_ack(sock: socket.socket, epoch: Optional[int] = None,
                  error: Optional[str] = None) -> None:
    """Checksummed KEYS ack (type 12): status 0 + {"epoch": N} on a
    successful swap, status 1 + error string otherwise."""
    sock.sendall(encode_keys_ack(epoch=epoch, error=error))


def peer_fill_payload(doc: Dict[str, Any]) -> bytes:
    """Canonical peer-fill payload bytes (sorted keys + compact
    separators — one document, one wire encoding, exactly like
    :func:`keys_payload`)."""
    return json.dumps(doc, separators=(",", ":"),
                      sort_keys=True).encode()


def send_peer_fill(sock: socket.socket, doc: Dict[str, Any]) -> None:
    """Checksummed peer-fill frame (type 13): one entry, the op JSON
    (``op=export`` request or ``op=import`` push)."""
    payload = peer_fill_payload(doc)
    if len(payload) > MAX_ENTRY_BYTES:
        raise FrameTooLargeError(
            f"peer-fill payload {len(payload)} bytes exceeds entry "
            "bound")
    parts = [_HDR.pack(MAGIC, T_PEER_FILL, 1),
             _LEN_U32.pack(len(payload)), payload]
    sock.sendall(b"".join(_with_crc(parts)))


def encode_peer_ack(doc: Optional[Dict[str, Any]] = None,
                    error: Optional[str] = None) -> bytes:
    """Encoded checksummed peer-fill ack (type 14): status 0 + the
    op's result JSON, status 1 + error string. Shared by the socket
    sender and the native chain's control path."""
    if error is None:
        status = 0
        payload = json.dumps(doc if doc is not None else {},
                             separators=(",", ":"),
                             sort_keys=True).encode()
    else:
        status, payload = 1, error.encode()
    if len(payload) > MAX_ENTRY_BYTES:
        raise FrameTooLargeError(
            f"peer-fill ack payload {len(payload)} bytes exceeds "
            "entry bound")
    parts = [_HDR.pack(MAGIC, T_PEER_ACK, 1),
             _LEN_BU32.pack(status, len(payload)), payload]
    return b"".join(_with_crc(parts))


def send_peer_ack(sock: socket.socket,
                  doc: Optional[Dict[str, Any]] = None,
                  error: Optional[str] = None) -> None:
    sock.sendall(encode_peer_ack(doc=doc, error=error))


def shm_attach_payload(path: str) -> bytes:
    """Canonical shm-attach payload bytes (sorted keys + compact
    separators — one request, one wire encoding, exactly like
    :func:`keys_payload`). The native driver and the Go client build
    the same string by hand; this function is the reference."""
    return json.dumps({"op": "attach", "path": path, "version": 1},
                      separators=(",", ":"), sort_keys=True).encode()


def send_shm_attach(sock: socket.socket, path: str) -> None:
    """Checksummed shm-attach frame (type 15): one entry, the region
    path JSON. The region file must already exist and carry a valid
    header — the worker maps it before acking."""
    payload = shm_attach_payload(path)
    if len(payload) > MAX_ENTRY_BYTES:
        raise FrameTooLargeError(
            f"shm-attach payload {len(payload)} bytes exceeds entry "
            "bound")
    parts = [_HDR.pack(MAGIC, T_SHM_ATTACH, 1),
             _LEN_U32.pack(len(payload)), payload]
    sock.sendall(b"".join(_with_crc(parts)))


def encode_shm_ack(error: Optional[str] = None) -> bytes:
    """Encoded checksummed shm ack (type 16): status 0 +
    {"transport":"shm"} when the worker mapped the region, status 1 +
    error string otherwise. Shared by the socket sender and the native
    chain (serve_native.cpp shm_ack_frame mirrors it byte-for-byte)."""
    if error is None:
        status, payload = 0, b'{"transport":"shm"}'
    else:
        status, payload = 1, error.encode()
    parts = [_HDR.pack(MAGIC, T_SHM_ACK, 1),
             _LEN_BU32.pack(status, len(payload)), payload]
    return b"".join(_with_crc(parts))


def send_shm_ack(sock: socket.socket,
                 error: Optional[str] = None) -> None:
    sock.sendall(encode_shm_ack(error=error))


def recv_frame(sock: socket.socket) -> Tuple[int, List[Any]]:
    """Read one frame → (type, entries), exact reads (no buffering).

    Request entries are token strings; response entries are
    (status, payload-bytes) pairs. Hot loops should use
    :class:`FrameReader` instead — this per-entry exact-read form
    costs two syscalls and two allocations per entry and measured
    374k tokens/s on one core vs FrameReader's buffered parse
    (docs/PERF.md r5 serve projection); it stays for one-shot uses
    and as the simplest reference of the wire format.
    """
    ftype, entries, _ = _parse_frame(lambda n: _recv_exact(sock, n))
    return ftype, entries


def recv_frame_ex(sock: socket.socket) -> Tuple[int, List[Any],
                                                Optional[str]]:
    """Like :func:`recv_frame`, also returning the trace id carried by
    a traced frame (types 9/10; None for every other type)."""
    return _parse_frame(lambda n: _recv_exact(sock, n))


def _parse_frame(take) -> Tuple[int, List[Any], Optional[str]]:
    """Shared CVB1 frame parse over a ``take(n) -> bytes`` source →
    (type, entries, trace-id-or-None).

    Every length is validated BEFORE the corresponding ``take`` — the
    parser never allocates for an out-of-bounds prefix. Checksummed
    frame types defer UTF-8 decoding, status validation, and
    trace-context validation until the CRC trailer has matched, so a
    flipped byte anywhere in the frame surfaces as
    :class:`FrameCorruptError`.
    """
    raw_take = take
    hdr = raw_take(_HDR.size)
    magic, ftype, count = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise MalformedFrameError(f"bad magic 0x{magic:08x}")
    if count > MAX_FRAME_ENTRIES:
        raise FrameTooLargeError(f"frame too large: {count} entries")
    checksummed = ftype in (T_VERIFY_REQ_CRC, T_VERIFY_RESP_CRC,
                            T_VERIFY_REQ_TRACE, T_VERIFY_RESP_TRACE,
                            T_KEYS_PUSH, T_KEYS_ACK, T_PEER_FILL,
                            T_PEER_ACK, T_SHM_ATTACH, T_SHM_ACK)
    if ftype in (T_KEYS_PUSH, T_KEYS_ACK, T_PEER_FILL, T_PEER_ACK,
                 T_SHM_ATTACH, T_SHM_ACK) and count != 1:
        raise MalformedFrameError(
            f"type-{ftype} control frame must carry exactly one "
            f"entry, got {count}")
    if checksummed:
        crc_state = [zlib.crc32(hdr)]

        def take(n: int, _t: Callable[[int], bytes] = raw_take) -> bytes:
            b = _t(n)
            crc_state[0] = zlib.crc32(b, crc_state[0])
            return b

    trace_raw: Optional[bytes] = None
    if ftype in (T_VERIFY_REQ_TRACE, T_VERIFY_RESP_TRACE):
        (ctx_len,) = take(1)
        if not 0 < ctx_len <= MAX_TRACE_BYTES:
            raise MalformedFrameError(
                f"trace-context length {ctx_len} outside 1..:"
                f"{MAX_TRACE_BYTES}")
        trace_raw = take(ctx_len)

    entries: List[Any] = []
    total = 0
    u32 = _LEN_U32.unpack
    bu32 = _LEN_BU32.unpack
    if ftype in (T_VERIFY_REQ, T_VERIFY_REQ_CRC, T_VERIFY_REQ_TRACE,
                 T_KEYS_PUSH, T_PEER_FILL, T_SHM_ATTACH):
        for _ in range(count):
            (ln,) = u32(take(4))
            total += ln
            if ln > MAX_ENTRY_BYTES or total > MAX_FRAME_BYTES:
                raise FrameTooLargeError(f"frame too large ({total} bytes)")
            entries.append(take(ln))
    elif ftype in (T_VERIFY_RESP, T_VERIFY_RESP_CRC,
                   T_VERIFY_RESP_TRACE, T_STATS_RESP, T_KEYS_ACK,
                   T_PEER_ACK, T_SHM_ACK):
        for _ in range(count):
            status, ln = bu32(take(5))
            if not checksummed and status not in (0, 1):
                raise MalformedFrameError(f"bad status byte {status}")
            total += ln
            if ln > MAX_ENTRY_BYTES or total > MAX_FRAME_BYTES:
                raise FrameTooLargeError(f"frame too large ({total} bytes)")
            entries.append((status, take(ln)))
    elif ftype in (T_PING, T_PONG, T_STATS_REQ):
        if count:
            raise MalformedFrameError(
                f"type-{ftype} frame with nonzero count {count}")
    else:
        raise MalformedFrameError(f"unknown frame type {ftype}")

    if checksummed:
        (want,) = u32(raw_take(4))          # trailer: outside the CRC
        if want != (crc_state[0] & 0xFFFFFFFF):
            raise FrameCorruptError(
                f"crc mismatch (frame type {ftype}): wire says "
                f"0x{want:08x}")
        for e in entries:                   # deferred status validation
            if isinstance(e, tuple) and e[0] not in (0, 1):
                raise MalformedFrameError(f"bad status byte {e[0]}")
    trace: Optional[str] = None
    if trace_raw is not None:
        # Validated AFTER integrity, like status bytes: the field is a
        # registered-charset identifier, never payload material.
        if not all(b in _TRACE_HEX for b in trace_raw):
            raise MalformedFrameError("trace-context not lowercase hex")
        trace = trace_raw.decode("ascii")
    if ftype in (T_VERIFY_REQ, T_VERIFY_REQ_CRC, T_VERIFY_REQ_TRACE):
        # Token decode AFTER integrity: corruption inside a checksummed
        # frame can never masquerade as a different (valid) token.
        entries = [e.decode() for e in entries]
    return ftype, entries, trace


def parse_frame_bytes(data: bytes) -> Tuple[int, List[Any],
                                            Optional[str], int]:
    """Parse ONE complete frame held in a byte buffer →
    (type, entries, trace-id-or-None, bytes consumed).

    Same validation (and the same typed error classes) as the socket
    readers — this is the REFERENCE the native reader's frame parser
    is pinned against: the malformed-frame parity sweep feeds the
    corpus through this function and through
    ``cap_serve_probe_frame`` and asserts identical error classes.
    A buffer that ends mid-frame raises :class:`ConnectionError`,
    matching a peer that closed mid-frame on the stream paths.
    """
    pos = 0

    def take(n: int) -> bytes:
        nonlocal pos
        if pos + n > len(data):
            raise ConnectionError("peer closed mid-frame")
        b = data[pos: pos + n]
        pos += n
        return b

    ftype, entries, trace = _parse_frame(take)
    return ftype, entries, trace, pos


# Native parse-status → Python error class: the shared frame-rejection
# contract (serve_native.cpp PF_* codes). Status 0 is success, 4 means
# "incomplete frame" (the stream readers just keep reading; the probe
# maps it onto the same ConnectionError parse_frame_bytes raises).
NATIVE_STATUS_ERRORS = {
    1: MalformedFrameError,
    2: FrameTooLargeError,
    3: FrameCorruptError,
    4: ConnectionError,
    5: UnicodeDecodeError,
}


class FrameReader:
    """Buffered CVB1 frame reader: one ~64 KiB recv instead of two
    syscalls per entry.

    The wire has no frame-length prefix, so buffered reads can consume
    the start of the NEXT frame — leftover bytes are retained across
    calls, which means a socket must be read EXCLUSIVELY through one
    FrameReader once attached (the worker's reader thread and the
    client already own their sockets' read sides exclusively).
    """

    __slots__ = ("_sock", "_buf", "_off", "hwm")

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._buf = b""
        self._off = 0
        # buffered-bytes high-water mark (r22 connection plane): the
        # most bytes this reader ever held at once — the per-conn
        # memory figure ROADMAP #3's C1M ingest must bound
        self.hwm = 0

    def _take(self, n: int) -> bytes:
        buf, off = self._buf, self._off
        if len(buf) - off < n:
            parts = [buf[off:]] if off < len(buf) else []
            got = len(buf) - off
            while got < n:
                chunk = self._sock.recv(max(n - got, 1 << 16))
                if not chunk:
                    raise ConnectionError("peer closed mid-frame")
                parts.append(chunk)
                got += len(chunk)
            buf = b"".join(parts)
            off = 0
            self._buf = buf
            if len(buf) > self.hwm:
                self.hwm = len(buf)
        self._off = off + n
        return buf[off:off + n]

    def recv_frame(self) -> Tuple[int, List[Any]]:
        ftype, entries, _ = self.recv_frame_ex()
        return ftype, entries

    def recv_frame_ex(self) -> Tuple[int, List[Any], Optional[str]]:
        """(type, entries, trace-id-or-None) — the trace id is non-None
        only for traced frames (types 9/10)."""
        out = _parse_frame(self._take)
        # Drop the consumed prefix so an idle connection never pins a
        # whole parsed frame (frames may be up to MAX_FRAME_BYTES).
        if self._off:
            self._buf = self._buf[self._off:]
            self._off = 0
        return out
