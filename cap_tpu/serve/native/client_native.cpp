// capclient — native client for the cap_tpu verify worker (CVB1).
//
// The reference is a pure in-process Go library; this framework runs
// its verify engine in a worker process that owns the accelerator, so
// host applications in ANY language need a client. This is the C ABI
// one (usable from C/C++/Go-cgo/ctypes): blocking connect + batched
// verify over the length-prefixed CVB1 protocol (see
// cap_tpu/serve/protocol.py for the frame layout).
//
// Redaction stance: no logging; error strings from the worker never
// contain token material.
//
// Build: make native   (g++ -O3 -shared -fPIC)

#include <arpa/inet.h>
#include <cstdint>
#include <cstring>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x31425643;  // "CVB1"
constexpr uint8_t kVerifyReq = 1;
constexpr uint8_t kVerifyResp = 2;
constexpr uint8_t kPing = 3;
constexpr uint8_t kPong = 4;
// Mirror protocol.py's limits: reject hostile/corrupt lengths instead
// of allocating them (a bad_alloc escaping extern "C" would terminate
// the embedding process).
constexpr uint32_t kMaxEntryBytes = 1u << 20;
constexpr uint64_t kMaxFrameBytes = 1ull << 28;

struct Client {
  int fd = -1;
  // Set on any transport/parse error: the socket may hold unread
  // response bytes, so a retry on the same handle would misparse
  // subsequent frames. Poisoned handles fail fast instead.
  bool dead = false;
};

int poison(Client* c) {
  c->dead = true;
  if (c->fd >= 0) {
    ::close(c->fd);
    c->fd = -1;
  }
  return -1;
}

bool send_all(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t w = ::send(fd, p, n, 0);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void put_u32(std::string& out, uint32_t v) {
  char b[4];
  std::memcpy(b, &v, 4);  // little-endian hosts only (x86/ARM LE)
  out.append(b, 4);
}

}  // namespace

extern "C" {

// Connect over TCP. Returns an opaque handle or null.
void* cap_client_connect(const char* host, int port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host, &addr.sin_addr) != 1 ||
      ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  auto* c = new Client;
  c->fd = fd;
  return c;
}

// Connect over a Unix socket. Returns an opaque handle or null.
void* cap_client_connect_uds(const char* path) {
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  std::strncpy(addr.sun_path, path, sizeof(addr.sun_path) - 1);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    ::close(fd);
    return nullptr;
  }
  auto* c = new Client;
  c->fd = fd;
  return c;
}

// Liveness probe. 1 on pong, 0 on failure.
int cap_client_ping(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (c->dead) return 0;
  std::string frame;
  put_u32(frame, kMagic);
  frame.push_back(static_cast<char>(kPing));
  put_u32(frame, 0);
  if (!send_all(c->fd, frame.data(), frame.size())) return poison(c), 0;
  uint8_t hdr[9];
  if (!recv_all(c->fd, hdr, 9)) return poison(c), 0;
  if (hdr[4] != kPong) return poison(c), 0;
  return 1;
}

// Verify a batch.
//   tokens/token_lens/count: the batch (UTF-8 compact JWS each).
//   statuses[count]: out, 0 = verified, 1 = rejected.
//   payload_buf/payload_cap: out, concatenated payloads
//     (claims JSON / error string per token).
//   payload_off[count + 1]: out, token i's payload is
//     payload_buf[payload_off[i] .. payload_off[i+1]).
// Returns 0 ok; -1 transport error; -2 payload_buf too small
// (payload_off[count] then holds the required size).
int cap_client_verify(void* handle, const char** tokens,
                      const uint32_t* token_lens, uint32_t count,
                      uint8_t* statuses, char* payload_buf,
                      uint64_t payload_cap, uint64_t* payload_off) {
  auto* c = static_cast<Client*>(handle);
  if (c->dead) return -1;
  std::string frame;
  frame.reserve(9 + 512 * count);
  put_u32(frame, kMagic);
  frame.push_back(static_cast<char>(kVerifyReq));
  put_u32(frame, count);
  for (uint32_t i = 0; i < count; i++) {
    put_u32(frame, token_lens[i]);
    frame.append(tokens[i], token_lens[i]);
  }
  if (!send_all(c->fd, frame.data(), frame.size())) return poison(c);

  uint8_t hdr[9];
  if (!recv_all(c->fd, hdr, 9)) return poison(c);
  uint32_t magic, n;
  std::memcpy(&magic, hdr, 4);
  std::memcpy(&n, hdr + 5, 4);
  if (magic != kMagic || hdr[4] != kVerifyResp || n != count) return poison(c);

  uint64_t off = 0;
  char sink[65536];
  for (uint32_t i = 0; i < count; i++) {
    uint8_t entry[5];
    if (!recv_all(c->fd, entry, 5)) return poison(c);
    uint32_t ln;
    std::memcpy(&ln, entry + 1, 4);
    if (ln > kMaxEntryBytes || off + ln > kMaxFrameBytes) return poison(c);
    statuses[i] = entry[0];
    payload_off[i] = off;
    if (off + ln <= payload_cap) {
      if (!recv_all(c->fd, payload_buf + off, ln)) return poison(c);
    } else {
      // drain in bounded chunks so the connection stays usable, then
      // report the required size via payload_off[count]
      for (uint32_t left = ln; left;) {
        uint32_t take = left < sizeof(sink) ? left : sizeof(sink);
        if (!recv_all(c->fd, sink, take)) return poison(c);
        left -= take;
      }
    }
    off += ln;
  }
  payload_off[count] = off;
  return off <= payload_cap ? 0 : -2;
}

void cap_client_close(void* handle) {
  auto* c = static_cast<Client*>(handle);
  if (c->fd >= 0) ::close(c->fd);
  delete c;
}

}  // extern "C"
