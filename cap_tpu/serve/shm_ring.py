"""Pure-Python side of the shared-memory CVB1 transport region.

One region file per connection, created by the CLIENT, mapped by the
worker. Layout (all little-endian; mirrored by
``runtime/native/shm_ring.h`` — the native readers and the Go client
speak the same bytes):

.. code-block:: text

    off 0     magic      u64   "CAPSHMR1"
    off 8     version    u32   1
    off 12    gen        u32   client generation stamp (nonzero)
    off 16    req_off    u64   = 4096 (one page of header)
    off 24    req_size   u64   power of two
    off 32    resp_off   u64   = 4096 + req_size
    off 40    resp_size  u64   power of two
    off 64    req_head   u64   request-ring producer cursor (client)
    off 128   req_tail   u64   request-ring consumer cursor (worker)
    off 192   resp_head  u64   response-ring producer cursor (worker)
    off 256   resp_tail  u64   response-ring consumer cursor (client)

Head/tail are monotonically increasing byte counters; ``offset =
cursor & (size - 1)``. Records are 8-byte aligned: ``[len u32]
[gen u32][payload … pad]``; ``len == 0xFFFFFFFF`` is a WRAP marker
(the producer skipped the ring's tail end). The producer writes the
payload FIRST and publishes by storing head LAST, so a producer
killed mid-write never publishes a torn record. What a consumer CAN
observe — an overrun cursor, an impossible length, a record stamped
by a foreign generation — raises the SAME typed classes as the socket
parser's malformed frames, so both transports share one rejection
taxonomy (:class:`StaleGenerationError` is a
:class:`~cap_tpu.serve.protocol.MalformedFrameError`).

This module is deliberately dependency-free (mmap + struct): it is
the reference implementation the Python shm client and the
python-serve-chain worker share, and the seam the chaos tests use to
inject stale-generation and overrun faults. The HOT path lives in
``shm_ring.cpp`` — CPython's 8-byte aligned writes into an mmap are a
single memcpy on x86-64, which is atomic enough for the cursor
protocol at Python speeds, but the native side uses real atomics.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Optional

from . import protocol

MAGIC = 0x31524D4853504143          # "CAPSHMR1"
VERSION = 1
HDR_SIZE = 4096
MIN_RING = 4096
MAX_RING = 1 << 30
WRAP = 0xFFFFFFFF

OFF_MAGIC = 0
OFF_VERSION = 8
OFF_GEN = 12
OFF_REQ_OFF = 16
OFF_REQ_SIZE = 24
OFF_RESP_OFF = 32
OFF_RESP_SIZE = 40
_CURSORS = {
    ("req", "head"): 64,
    ("req", "tail"): 128,
    ("resp", "head"): 192,
    ("resp", "tail"): 256,
}

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")
_REC = struct.Struct("<II")


class ShmFormatError(protocol.MalformedFrameError):
    """The region file's header is not a valid CAPSHMR1 layout."""


class StaleGenerationError(protocol.MalformedFrameError):
    """A ring record stamped by a foreign generation — a recycled or
    corrupted region. Counted (``serve.shm.stale_gen``) and fatal for
    the transport, exactly like a malformed socket frame."""


def _pow2_ok(v: int) -> bool:
    return MIN_RING <= v <= MAX_RING and (v & (v - 1)) == 0


def default_dir() -> str:
    """Where region files live: ``CAP_SHM_DIR``, else ``/dev/shm``
    when present (a real shared-memory tmpfs), else the tmp dir."""
    d = os.environ.get("CAP_SHM_DIR")
    if d:
        return d
    if os.path.isdir("/dev/shm"):
        return "/dev/shm"
    import tempfile

    return tempfile.gettempdir()


class ShmRegion:
    """One mapped region (create = client side, open = worker side)."""

    def __init__(self, path: str, mm: mmap.mmap, created: bool):
        self.path = path
        self._mm = mm
        self.created = created
        self.gen = _U32.unpack_from(mm, OFF_GEN)[0]
        self.ring_off = {
            "req": _U64.unpack_from(mm, OFF_REQ_OFF)[0],
            "resp": _U64.unpack_from(mm, OFF_RESP_OFF)[0],
        }
        self.ring_size = {
            "req": _U64.unpack_from(mm, OFF_REQ_SIZE)[0],
            "resp": _U64.unpack_from(mm, OFF_RESP_SIZE)[0],
        }
        self._closed = False

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, path: str, req_size: int = 1 << 20,
               resp_size: int = 1 << 20,
               gen: Optional[int] = None) -> "ShmRegion":
        if not _pow2_ok(req_size) or not _pow2_ok(resp_size):
            raise ValueError("ring sizes must be powers of two in "
                             f"[{MIN_RING}, {MAX_RING}]")
        if gen is None:
            gen = (int.from_bytes(os.urandom(4), "little") | 1) \
                & 0xFFFFFFFF
        if not 0 < gen <= 0xFFFFFFFF:
            raise ValueError("generation must be a nonzero u32")
        total = HDR_SIZE + req_size + resp_size
        fd = os.open(path, os.O_RDWR | os.O_CREAT | os.O_TRUNC, 0o600)
        try:
            os.ftruncate(fd, total)
            mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        _U32.pack_into(mm, OFF_VERSION, VERSION)
        _U32.pack_into(mm, OFF_GEN, gen)
        _U64.pack_into(mm, OFF_REQ_OFF, HDR_SIZE)
        _U64.pack_into(mm, OFF_REQ_SIZE, req_size)
        _U64.pack_into(mm, OFF_RESP_OFF, HDR_SIZE + req_size)
        _U64.pack_into(mm, OFF_RESP_SIZE, resp_size)
        # magic LAST: a racing reader never sees a half-written header
        _U64.pack_into(mm, OFF_MAGIC, MAGIC)
        return cls(path, mm, created=True)

    @classmethod
    def open(cls, path: str) -> "ShmRegion":
        fd = os.open(path, os.O_RDWR)
        try:
            size = os.fstat(fd).st_size
            if size < HDR_SIZE or size > HDR_SIZE + 2 * MAX_RING:
                raise ShmFormatError(
                    f"bad region file size {size}")
            mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        try:
            if _U64.unpack_from(mm, OFF_MAGIC)[0] != MAGIC:
                raise ShmFormatError("bad shm magic")
            if _U32.unpack_from(mm, OFF_VERSION)[0] != VERSION:
                raise ShmFormatError("unsupported shm version")
            if _U32.unpack_from(mm, OFF_GEN)[0] == 0:
                raise ShmFormatError("zero generation")
            req_off = _U64.unpack_from(mm, OFF_REQ_OFF)[0]
            req_size = _U64.unpack_from(mm, OFF_REQ_SIZE)[0]
            resp_off = _U64.unpack_from(mm, OFF_RESP_OFF)[0]
            resp_size = _U64.unpack_from(mm, OFF_RESP_SIZE)[0]
            if not _pow2_ok(req_size) or not _pow2_ok(resp_size):
                raise ShmFormatError("ring size out of bounds")
            if (req_off != HDR_SIZE
                    or resp_off != HDR_SIZE + req_size
                    or size < HDR_SIZE + req_size + resp_size):
                raise ShmFormatError("ring offsets inconsistent")
        except ShmFormatError:
            mm.close()
            raise
        return cls(path, mm, created=False)

    # -- cursors -----------------------------------------------------------

    def cursor(self, ring: str, side: str) -> int:
        return _U64.unpack_from(self._mm, _CURSORS[(ring, side)])[0]

    def set_cursor(self, ring: str, side: str, value: int) -> None:
        # NEVER struct.pack_into here: it ZERO-FILLS the destination
        # before writing the bytes, so a concurrent reader in the
        # OTHER process can observe the cursor transit through 0 — a
        # torn publish the native consumer rightly classifies as an
        # overrun (measured: ~16 zero-sightings per 2×10⁹ reads).
        # Slice assignment is one 8-byte memcpy: no intermediate state
        # was ever observed under the same probe.
        off = _CURSORS[(ring, side)]
        self._mm[off:off + 8] = (value & 0xFFFFFFFFFFFFFFFF).to_bytes(
            8, "little")

    def close(self, unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mm.close()
        except (BufferError, ValueError):
            pass  # exported views die with the process
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def max_record(self, ring: str) -> int:
        return self.ring_size[ring] // 2


class RingProducer:
    """SPSC producer over one of a region's rings.

    ``sendall`` aliases ``write`` so a producer duck-types as the
    ``sock`` argument of every ``protocol.send_*`` helper (each sends
    exactly one complete frame in one ``sendall`` call) — the worker's
    responder loop and the shm client swap a socket for a ring without
    touching the encoders.
    """

    def __init__(self, region: ShmRegion, ring: str,
                 timeout: float = 30.0):
        self._r = region
        self._ring = ring
        self._size = region.ring_size[ring]
        self._off = region.ring_off[ring]
        self.timeout = timeout

    def write(self, data: bytes, timeout: Optional[float] = None,
              abort=None) -> None:
        r, size = self._r, self._size
        n = len(data)
        if n > size // 2:
            raise protocol.FrameTooLargeError(
                f"frame of {n} bytes exceeds shm ring capacity "
                f"({size // 2})")
        adv = 8 + ((n + 7) & ~7)
        mm = r._mm
        if timeout is None:
            timeout = self.timeout
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while True:
            head = r.cursor(self._ring, "head")
            tail = r.cursor(self._ring, "tail")
            off = head & (size - 1)
            wrap_skip = size - off if size - off < adv else 0
            if size - (head - tail) >= wrap_skip + adv:
                break
            if abort is not None and abort():
                raise ConnectionError("shm peer gone (write aborted)")
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("shm ring full (peer not reading)")
            time.sleep(0.0002)
        base = self._off
        if wrap_skip:
            _REC.pack_into(mm, base + off, WRAP, r.gen)
            head += wrap_skip
            off = 0
            r.set_cursor(self._ring, "head", head)
        _REC.pack_into(mm, base + off, n, r.gen)
        mm[base + off + 8: base + off + 8 + n] = data
        r.set_cursor(self._ring, "head", head + adv)

    # protocol.send_* compatibility
    sendall = write


class RingConsumer:
    """SPSC consumer over one of a region's rings; raises the socket
    parser's typed classes on anything a hostile producer can make
    visible."""

    def __init__(self, region: ShmRegion, ring: str):
        self._r = region
        self._ring = ring
        self._size = region.ring_size[ring]
        self._off = region.ring_off[ring]

    def read(self, timeout: float = 0.05) -> Optional[bytes]:
        """Next record's payload bytes (a complete CVB1 frame), or
        None when nothing was published within ``timeout``."""
        r, size = self._r, self._size
        mm = r._mm
        deadline = time.monotonic() + timeout
        while True:
            head = r.cursor(self._ring, "head")
            tail = r.cursor(self._ring, "tail")
            if head != tail:
                if head - tail > size or tail & 7 or head - tail < 8:
                    raise protocol.MalformedFrameError(
                        "shm ring cursor overran the ring")
                off = tail & (size - 1)
                base = self._off
                rec_len, rec_gen = _REC.unpack_from(mm, base + off)
                if rec_len == WRAP:
                    if rec_gen != r.gen:
                        raise StaleGenerationError(
                            f"wrap marker from generation {rec_gen}")
                    skip = size - off
                    if head - tail < skip:
                        raise protocol.MalformedFrameError(
                            "shm wrap marker overruns published bytes")
                    r.set_cursor(self._ring, "tail", tail + skip)
                    continue
                if rec_len > size // 2:
                    raise protocol.FrameTooLargeError(
                        f"shm record of {rec_len} bytes exceeds ring "
                        "bound")
                adv = 8 + ((rec_len + 7) & ~7)
                if adv > size - off or head - tail < adv:
                    raise protocol.MalformedFrameError(
                        "shm record claims unpublished bytes")
                if rec_gen != r.gen:
                    raise StaleGenerationError(
                        f"record from generation {rec_gen}")
                data = bytes(mm[base + off + 8:
                                base + off + 8 + rec_len])
                r.set_cursor(self._ring, "tail", tail + adv)
                return data
            if time.monotonic() > deadline:
                return None
            time.sleep(0.0002)
