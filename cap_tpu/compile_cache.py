"""Persistent XLA compilation cache for cap_tpu programs.

The engines are shape-static by design (pow-2 bucket padding, fixed
chunk shapes), so across processes the same programs recompile from
scratch — on TPU a cold compile of the full mixed pipeline costs tens
of seconds (the round-1 config-⑤ timeout). Enabling JAX's persistent
compilation cache makes every compile after the first process-lifetime
one a disk hit.

Call :func:`enable` before the first jit execution (bench.py, the
tools, and tests/conftest.py do). Opt out with CAP_TPU_COMPILE_CACHE=0
or redirect with CAP_TPU_COMPILE_CACHE=/path.
"""

from __future__ import annotations

import os

_DEFAULT_DIR = os.path.join(os.path.expanduser("~"), ".cache",
                            "cap_tpu", "xla")
_enabled = False


def enable(cache_dir: str | None = None) -> str | None:
    """Idempotently enable the persistent compilation cache.

    Returns the cache directory, or None when disabled via env.
    """
    global _enabled
    env = os.environ.get("CAP_TPU_COMPILE_CACHE")
    if env in ("0", "false", "no"):
        return None
    if cache_dir is None:
        cache_dir = env if env else _DEFAULT_DIR
    if _enabled:
        return cache_dir
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _enabled = True
        return cache_dir
    except Exception:  # noqa: BLE001 - cache is best-effort
        return None
