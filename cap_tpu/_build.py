"""Build the native runtime libraries on demand.

The compiled ``.so`` artifacts are not committed (they are unreviewable
and go stale silently). The bindings call :func:`build_native` on first
use; it compiles the ``.cpp`` sources that ship INSIDE the package with
g++ directly — no Makefile needed, so non-editable pip installs build
too — using the RUNNING interpreter's headers for the extension module
(a PATH ``python3`` of a different version must not pick the headers).
Failures are non-fatal: every native component has a pure Python
fallback. ``make native`` remains the developer-facing entry point.
"""

from __future__ import annotations

import os
import subprocess
import sysconfig
import threading

_PKG = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_done = False

_FLAGS = ["-O3", "-fPIC", "-shared", "-pthread", "-std=c++17"]

# The extension module's filename carries the running interpreter's ABI
# tag (e.g. _capclaims.cpython-311-x86_64-linux-gnu.so): it is built
# against THIS interpreter's headers, and an untagged name would let a
# checkout shared across CPython minor versions load a mismatched ABI.
EXT_NAME = "_capclaims" + (sysconfig.get_config_var("EXT_SUFFIX") or ".so")

# (sources, output, needs_python_headers) — paths relative to
# cap_tpu/. libcapruntime.so is built from SIX translation units:
# jose_native.cpp (batch JOSE prep), serve_native.cpp (the GIL-free
# serve chain), telemetry_native.cpp (the native telemetry plane),
# claims_validate.cpp (the OIDC claims-rule engine), shm_ring.cpp
# (the zero-copy shared-memory transport), and frontdoor_native.cpp
# (the zero-copy relay front door) — one .so, so every binding loads
# the same library.
_TARGETS = [
    ((os.path.join("runtime", "native", "jose_native.cpp"),
      os.path.join("runtime", "native", "serve_native.cpp"),
      os.path.join("runtime", "native", "telemetry_native.cpp"),
      os.path.join("runtime", "native", "claims_validate.cpp"),
      os.path.join("runtime", "native", "shm_ring.cpp"),
      os.path.join("runtime", "native", "frontdoor_native.cpp")),
     os.path.join("runtime", "native", "libcapruntime.so"), False),
    ((os.path.join("serve", "native", "client_native.cpp"),),
     os.path.join("serve", "native", "libcapclient.so"), False),
    ((os.path.join("runtime", "native", "claims_ext.cpp"),),
     os.path.join("runtime", "native", EXT_NAME), True),
]


def _build_one(sources, out: str, py_headers: bool,
               timeout: float, force: bool = False) -> None:
    srcs = [os.path.join(_PKG, s) for s in sources]
    srcs = [s for s in srcs if os.path.exists(s)]
    out = os.path.join(_PKG, out)
    if not srcs:
        return
    # headers shared between the TUs count toward staleness too: the
    # same-basename .h of each source plus the cross-TU tape header
    # (claims_tape.h is included by BOTH claims_ext.cpp and
    # claims_validate.cpp — an edit there must rebuild both .so's)
    src_dirs = {os.path.dirname(s) for s in srcs}
    deps = srcs + [h for s in srcs
                   for h in [os.path.splitext(s)[0] + ".h"]
                   if os.path.exists(h)]
    # telemetry_native.h, shm_ring.h and cvb1_wire.h are likewise
    # cross-TU (serve_native.cpp feeds the plane and consumes the shm
    # rings; frontdoor_native.cpp shares the CVB1 parser — an
    # ABI/layout bump must rebuild every consumer)
    deps += [h for d in src_dirs
             for name in ("claims_tape.h", "telemetry_native.h",
                          "shm_ring.h", "cvb1_wire.h")
             for h in [os.path.join(d, name)]
             if os.path.exists(h) and h not in deps]
    if not force and os.path.exists(out) and \
            os.path.getmtime(out) >= max(os.path.getmtime(s)
                                         for s in deps):
        return
    cmd = ["g++", *_FLAGS]
    # -march=native when the compiler supports it (portable fallback
    # without), matching the Makefile's default flags.
    cmd.append("-march=native")
    if py_headers:
        cmd.append("-I" + sysconfig.get_paths()["include"])
    cmd += ["-o", out, *srcs]
    res = subprocess.run(cmd, capture_output=True, timeout=timeout,
                         check=False)
    if res.returncode != 0 and "-march=native" in cmd:
        cmd.remove("-march=native")
        subprocess.run(cmd, capture_output=True, timeout=timeout,
                       check=False)


def build_native(timeout: float = 180.0, force: bool = False) -> None:
    """Compile any missing/stale native library once, best-effort.

    ``force=True`` rebuilds every target unconditionally (``make
    native-build`` / the build-health tier-1 test) and bypasses the
    once-per-process latch so a later call still works.
    """
    global _done
    with _lock:
        if _done and not force:
            return
        _done = True
        for srcs, out, py_headers in _TARGETS:
            try:
                _build_one(srcs, out, py_headers, timeout, force=force)
            except Exception:  # noqa: BLE001 - fallbacks handle absence
                pass
