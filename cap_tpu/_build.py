"""Build the native runtime libraries on demand.

The compiled ``.so`` artifacts are not committed (they are unreviewable
and go stale silently); ``make native`` produces them, and the ctypes
bindings call :func:`build_native` on first use when the library is
missing. Failures are non-fatal — every native component has a pure
Python fallback.
"""

from __future__ import annotations

import os
import subprocess
import threading

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_lock = threading.Lock()
_done = False


def build_native(timeout: float = 180.0) -> None:
    """Run ``make -C <repo> native`` once, quietly, best-effort."""
    global _done
    with _lock:
        if _done:
            return
        _done = True
        try:
            subprocess.run(["make", "-C", _REPO, "native"],
                           capture_output=True, timeout=timeout,
                           check=False)
        except Exception:  # noqa: BLE001 - fallbacks handle absence
            pass
