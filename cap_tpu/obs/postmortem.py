"""Crash postmortems: what a worker looked like just before it died.

A ``kill -9`` leaves no chance to flush logs — so the flight recorder,
counters, and decision ring a worker accumulated die with it, exactly
when they are most needed. The fix is checkpoint-shaped, not
signal-shaped:

- every worker runs a :class:`PostmortemWriter`: an immediate
  checkpoint at startup, then one every ``interval_s`` seconds, each
  an ATOMIC write (tmp + ``os.replace``) of the process's telemetry
  state to a well-known path (``CAP_FLEET_PM_PATH``, set by the pool);
- on SIGTERM drain the worker writes one final fresh checkpoint
  (reason ``sigterm-drain``);
- the :class:`~cap_tpu.fleet.pool.WorkerPool` COLLECTS the file once a
  worker's death is confirmed — so even the hardest crash leaves a
  postmortem at most one checkpoint interval stale;
- ``capstat --postmortem FILE`` renders it (final flight ring, stage
  quantiles, decision/reason counters, queue depth at death).

Redaction: everything checkpointed comes from the telemetry recorder
(whose write boundary already rejects token-shaped content), and the
writer re-scrubs the serialized document anyway — any string that
looks like a JWS segment or is implausibly long is replaced with
``[redacted]`` before it reaches disk. Defense in depth: a postmortem
file must be shareable in an incident channel.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import telemetry

PM_VERSION = 1
DEFAULT_INTERVAL_S = 2.0
_MAX_STR = 512
_FLIGHT_KEEP = 16


def _scrub(obj: Any) -> Any:
    """Recursive write-boundary scrub (strings only; keys included).
    Token-shaped, over-long, and raw-issuer-shaped (URL — tenants are
    recorded only as hashes) strings are all replaced."""
    if isinstance(obj, str):
        if "eyJ" in obj or "://" in obj or len(obj) > _MAX_STR:
            return "[redacted]"
        return obj
    if isinstance(obj, dict):
        return {_scrub(k): _scrub(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_scrub(v) for v in obj]
    return obj


def build_postmortem(reason: str,
                     stats_fn: Optional[Callable[[], Dict[str, Any]]]
                     = None,
                     t_start: Optional[float] = None) -> Dict[str, Any]:
    """Assemble (and scrub) one postmortem document from the live
    process state. Never raises — a failing stats callback degrades to
    an error note, because the checkpoint path must survive exactly
    the situations that break everything else."""
    rec = telemetry.active()
    doc: Dict[str, Any] = {
        "v": PM_VERSION,
        "pid": os.getpid(),
        "reason": reason,
        "t_write": time.time(),
    }
    if t_start is not None:
        doc["uptime_s"] = round(time.time() - t_start, 3)
    snap = None
    if stats_fn is not None:
        try:
            stats = dict(stats_fn())
            # the stats snapshot is the worker's MERGED view (recorder
            # + native telemetry plane, serve.worker.stats) — prefer
            # it over the bare recorder so natively-counted decisions
            # survive into the document (carried once, below)
            snap = stats.pop("snapshot", None)
            doc["stats"] = stats
        except Exception as e:  # noqa: BLE001 - keep checkpointing
            doc["stats_error"] = repr(e)[:_MAX_STR]
    if rec is not None:
        doc["snapshot"] = snap if snap else rec.snapshot()
        doc["flight"] = rec.flight_slowest(_FLIGHT_KEEP)
        doc["decisions"] = rec.decisions()
    elif snap:
        doc["snapshot"] = snap
    return _scrub(doc)


def write_postmortem(path: str, doc: Dict[str, Any]) -> None:
    """Atomic single-file write: readers (the pool, capstat) never see
    a torn document, even when SIGKILL lands mid-checkpoint."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f, separators=(",", ":"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_postmortem(path: str) -> Optional[Dict[str, Any]]:
    """Parse a postmortem file; None when absent/unreadable (a worker
    that died before its first checkpoint, or an empty slot)."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class PostmortemWriter:
    """Periodic checkpointer (daemon thread) + final-write hook.

    Writes IMMEDIATELY on construction (so a worker killed in its
    first milliseconds still leaves a document), then every
    ``interval_s``. ``close(reason)`` writes one final fresh
    checkpoint and stops the timer — the SIGTERM drain path.
    """

    def __init__(self, path: str, interval_s: float = DEFAULT_INTERVAL_S,
                 stats_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self.path = path
        self._interval = max(0.05, float(interval_s))
        self._stats_fn = stats_fn
        self._t_start = time.time()
        self._stop = threading.Event()
        self.write_now("startup")
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="cap-tpu-postmortem")
        self._thread.start()

    def write_now(self, reason: str) -> None:
        try:
            write_postmortem(self.path, build_postmortem(
                reason, self._stats_fn, self._t_start))
        except OSError:
            pass                       # a full disk must not kill serving

    def close(self, reason: str = "shutdown") -> None:
        self._stop.set()
        self.write_now(reason)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.write_now("checkpoint")


# ---------------------------------------------------------------------------
# rendering (capstat --postmortem)
# ---------------------------------------------------------------------------


def render_postmortem(doc: Dict[str, Any]) -> str:
    """One-screen incident view of a collected postmortem."""
    lines: List[str] = []
    age = time.time() - float(doc.get("t_write", 0.0))
    lines.append(
        f"postmortem pid={doc.get('pid')} reason={doc.get('reason')} "
        f"written {age:.1f}s ago"
        + (f" uptime={doc.get('uptime_s')}s" if "uptime_s" in doc
           else ""))
    stats = doc.get("stats") or {}
    if stats:
        lines.append(
            f"  queue at death: queued_tokens="
            f"{stats.get('queued_tokens', 0)} inflight_batches="
            f"{stats.get('inflight_batches', 0)}")
    snap = doc.get("snapshot") or {}
    counters = snap.get("counters") or {}
    worker_counts = {k: v for k, v in sorted(counters.items())
                     if k.startswith(("worker.", "batcher.flushes"))}
    if worker_counts:
        lines.append("  counters: " + "  ".join(
            f"{k}={v}" for k, v in worker_counts.items()))
    from . import decision as _decision

    rollup = _decision.surface_totals(counters)
    for surf, row in sorted(rollup.items()):
        reasons = "  ".join(f"{k.split('.', 1)[1]}={v}"
                            for k, v in sorted(row.items())
                            if k.startswith("reject."))
        lines.append(f"  decisions[{surf}]: accept={row['accept']} "
                     f"reject={row['reject']}"
                     + (f"  ({reasons})" if reasons else ""))
    tenants = _decision.tenant_totals(counters)
    if tenants:
        lines.append(f"  tenants ({len(tenants)} attributed):")
        ordered = sorted(tenants.items(),
                         key=lambda kv: kv[1].get("tokens", 0),
                         reverse=True)
        for t, r in ordered[:8]:
            mix = "  ".join(f"{k.split('.', 1)[1]}={v}"
                            for k, v in sorted(r.items())
                            if k.startswith("reject."))
            lines.append(
                f"    tenant={t:<12} tokens={r.get('tokens', 0)} "
                f"accept={r.get('accept', 0)} "
                f"reject={r.get('reject', 0)}"
                + (f"  wrong_verdicts={r['wrong_verdicts']}"
                   if r.get("wrong_verdicts") else "")
                + (f"  ({mix})" if mix else ""))
    summary = telemetry.summarize_snapshot(snap)
    for name in sorted(summary):
        s = summary[name]
        lines.append(f"  {name:<28} n={int(s['count']):>7}  "
                     f"p50={s['p50'] * 1e3:9.3f}ms  "
                     f"p99={s['p99'] * 1e3:9.3f}ms")
    flights = doc.get("flight") or []
    if flights:
        lines.append(f"  final flight ring ({len(flights)} traced):")
        for e in flights[:8]:
            lines.append(f"    trace={e.get('trace')} "
                         f"total={float(e.get('total_s', 0)) * 1e3:.3f}ms "
                         f"spans={len(e.get('spans') or [])}")
    decisions = doc.get("decisions") or []
    if decisions:
        lines.append(f"  decision ring ({len(decisions)} sampled):")
        for d in decisions[-8:]:
            lines.append(
                "    " + " ".join(f"{k}={v}" for k, v in d.items()))
    return "\n".join(lines)
