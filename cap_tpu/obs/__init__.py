"""Decision-grade observability: verdict accounting, SLOs, postmortems.

PR 3's telemetry layer made the fleet *mechanically* observable (stage
spans, mergeable histograms, the /metrics scrape surface). This package
answers the questions an auth service actually gets paged on:

- :mod:`cap_tpu.obs.decision` — WHY tokens are rejected: every verify
  on every surface (CPU oracle, TPU batch engine, serve worker, fleet
  router) emits a bounded, redaction-enforced decision record into
  reason-keyed mergeable counters plus a sampled ring;
- :mod:`cap_tpu.obs.slo` — is the availability contract ("never
  wrong, at worst slow") actually holding: declarative objectives
  evaluated with multi-window burn rates (``capstat --slo``);
- :mod:`cap_tpu.obs.postmortem` — what a worker looked like in the
  seconds before it died: periodic crash-consistent checkpoints of the
  telemetry state, collected by the pool on confirmed death and
  rendered by ``capstat --postmortem``.

Everything here is stdlib-only and rides the existing telemetry
recorder — counters merge exactly through ``pool.stats_merged()`` and
the CVB1 STATS/snapshot wire, with redaction enforced at the write
boundary exactly like metric names (:func:`cap_tpu.telemetry.check_name`).
"""

from . import decision, postmortem, slo

__all__ = ["decision", "postmortem", "slo"]
