"""Device-occupancy plane: interval accounting for pipeline busy time.

2112.02229's thesis is that verification throughput is won by keeping a
fixed-latency pipeline FULL, not by making one batch faster — so the
number that matters for ROADMAP #5 (continuous batching) is the
fraction of wall time with device work in flight. This module is that
measurement: every engine dispatch site records a busy interval into
one process-wide accumulator, and the scrape surface turns the
accumulated counters into `device.occupancy` gauges.

Accounting model
----------------
``record(family, t0, t1)`` folds one busy interval (monotonic-clock
endpoints, seconds):

- **global busy** is the UNION of all intervals — each interval is
  clipped against the running high-water end, so two overlapping
  in-flight batches (the 2-deep pipeline) never double-count a
  microsecond. ``device.occupancy = Δbusy / Δwall`` is therefore a
  true "work in flight" fraction, ≤ 1 by construction.
- **per-family busy** is the RAW duration — overlap double-counts
  deliberately, because ``device.<fam>.busy_us`` answers "how much
  device time did family X consume", the lane-share question
  2211.12265's per-scheme GPU batching motivates.
- **idle gaps**: a positive gap between the previous dispatch-level
  interval's end and this one's start is the host-prep bubble #5's
  double-buffering must close; it lands in the ``device.idle_gap_s``
  histogram (observed through the active recorder, so it is a no-op
  while telemetry is off).

All totals are integer MICROSECONDS held locally and flushed to the
active recorder as plain counters at :func:`publish` time — counters
merge exactly across snapshot/STATS/`pool.stats_merged()`, and
consumers apply the r13 counter-reset clamp (never a negative rate
after a worker restart). The wall-clock anchor is itself a counter
(``device.wall_us``: µs elapsed since the first interval), so a fleet
merge yields sum-busy / sum-wall — the worker-weighted mean occupancy.

Published keys (see docs/OBSERVABILITY.md §Occupancy plane):

==============================  =============================================
counter                         meaning
==============================  =============================================
``device.busy_us``              union busy time, µs (occupancy numerator)
``device.wall_us``              wall anchor, µs since first interval
``device.dispatches``           dispatch-level intervals recorded
``device.<fam>.busy_us``        per-family raw busy time, µs
``device.<fam>.intervals``      per-family interval count
==============================  =============================================

Gauges (scrape-window delta ratios, set at publish):
``device.occupancy``, ``device.<fam>.occupancy``.

Clock note: interval endpoints are ``time.monotonic()`` seconds. On
Linux that is CLOCK_MONOTONIC — the same clock the native serve
chain's ``std::chrono::steady_clock`` enqueue stamps use, so ring-wait
math mixes the two freely (cap_tpu/serve/native_serve.py).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, Iterator, Optional

from .. import telemetry

__all__ = [
    "OccAccumulator", "accumulator", "reset", "interval", "begin",
    "end", "publish", "occupancy_from_counters",
]


def _us(seconds: float) -> int:
    return int(seconds * 1e6)


class OccAccumulator:
    """Mergeable busy-interval accumulator (thread-safe).

    Holds its own integer-µs totals independent of any recorder;
    :meth:`publish` flushes the delta since the previous publish into
    the active telemetry recorder. A fake ``clock`` makes every number
    deterministic under test.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._origin: Optional[float] = None   # first interval start
        self._last_end: float = 0.0            # union high-water mark
        self._busy_us = 0                      # global union, µs
        self._dispatches = 0
        self._fam_us: Dict[str, int] = {}
        self._fam_n: Dict[str, int] = {}
        # per-name totals already flushed to the recorder
        self._published: Dict[str, int] = {}
        # previous publish's totals, for the gauge window
        self._win_busy = 0
        self._win_wall = 0
        self._win_fam: Dict[str, int] = {}

    # -- write side -------------------------------------------------------

    def record(self, family: Optional[str], t0: float, t1: float,
               dispatch: bool = False) -> None:
        """Fold one busy interval [t0, t1] (monotonic seconds).

        ``family`` feeds the per-family raw counters (None: global
        union only). ``dispatch`` marks a batch-level interval: it
        increments ``device.dispatches`` and participates in idle-gap
        accounting (per-family enqueue slices inside one batch do
        not — their gaps are host packing, not pipeline bubbles).
        """
        if t1 < t0:
            t1 = t0
        with self._lock:
            if self._origin is None:
                self._origin = t0
                self._last_end = t0
            elif dispatch and t0 > self._last_end:
                gap = t0 - self._last_end
                telemetry.observe("device.idle_gap_s", gap)
            self._busy_us += _us(max(0.0, t1 - max(t0, self._last_end)))
            if t1 > self._last_end:
                self._last_end = t1
            if dispatch:
                self._dispatches += 1
            if family is not None:
                self._fam_us[family] = (self._fam_us.get(family, 0)
                                        + _us(t1 - t0))
                self._fam_n[family] = self._fam_n.get(family, 0) + 1

    @contextmanager
    def interval(self, family: Optional[str],
                 dispatch: bool = True) -> Iterator[None]:
        """Time a block as one busy interval. No-op (one attribute
        check) while telemetry is off — the obs-off bench arms must
        not even read the clock."""
        if telemetry.active() is None:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            self.record(family, t0, self._clock(), dispatch=dispatch)

    def begin(self) -> Optional[float]:
        """Start stamp for a split begin/end interval (the async
        dispatch→collect path); None while telemetry is off."""
        if telemetry.active() is None:
            return None
        return self._clock()

    def end(self, family: Optional[str], t0: Optional[float],
            dispatch: bool = True) -> None:
        """Close a :meth:`begin` interval (no-op when t0 is None)."""
        if t0 is None or telemetry.active() is None:
            return
        self.record(family, t0, self._clock(), dispatch=dispatch)

    # -- publish side -----------------------------------------------------

    def totals(self) -> Dict[str, int]:
        """Cumulative counter values (µs / counts) as of now."""
        with self._lock:
            return self._totals_locked()

    def _totals_locked(self) -> Dict[str, int]:
        if self._origin is None:
            return {}
        out = {
            "device.busy_us": self._busy_us,
            "device.wall_us": max(0, _us(self._clock() - self._origin)),
            "device.dispatches": self._dispatches,
        }
        for fam, us in self._fam_us.items():
            out[f"device.{fam}.busy_us"] = us
            out[f"device.{fam}.intervals"] = self._fam_n[fam]
        return out

    def publish(self, rec: Optional[telemetry.Recorder] = None) -> None:
        """Flush counter deltas since the previous publish into the
        recorder and set the scrape-window occupancy gauges. Called
        from every scrape surface (worker stats/gauges, bench embeds);
        publishes nothing until the first interval lands, so an engine
        that never dispatched contributes no occupancy keys."""
        rec = rec if rec is not None else telemetry.active()
        if rec is None:
            return
        with self._lock:
            totals = self._totals_locked()
            if not totals:
                return
            increments = {}
            for k, v in totals.items():
                d = v - self._published.get(k, 0)
                if d > 0 or k not in self._published:
                    increments[k] = max(0, d)
                self._published[k] = v
            busy, wall = totals["device.busy_us"], totals["device.wall_us"]
            d_busy = max(0, busy - self._win_busy)
            d_wall = max(0, wall - self._win_wall)
            gauges = {"device.occupancy":
                      min(1.0, d_busy / d_wall) if d_wall else 0.0}
            for fam, us in self._fam_us.items():
                d_fam = max(0, us - self._win_fam.get(fam, 0))
                gauges[f"device.{fam}.occupancy"] = (
                    d_fam / d_wall if d_wall else 0.0)
                self._win_fam[fam] = us
            self._win_busy, self._win_wall = busy, wall
        if increments:
            rec.count_many(increments)
        for k, v in gauges.items():
            rec.gauge(k, v)


# ---------------------------------------------------------------------------
# module-level accumulator: one per process (workers are processes)
# ---------------------------------------------------------------------------

_acc = OccAccumulator()


def accumulator() -> OccAccumulator:
    return _acc


def reset(clock: Callable[[], float] = time.monotonic) -> OccAccumulator:
    """Replace the process accumulator (tests / chain swaps)."""
    global _acc
    _acc = OccAccumulator(clock)
    return _acc


def interval(family: Optional[str], dispatch: bool = True):
    return _acc.interval(family, dispatch=dispatch)


def begin() -> Optional[float]:
    return _acc.begin()


def end(family: Optional[str], t0: Optional[float],
        dispatch: bool = True) -> None:
    _acc.end(family, t0, dispatch=dispatch)


def publish(rec: Optional[telemetry.Recorder] = None) -> None:
    _acc.publish(rec)


# ---------------------------------------------------------------------------
# counter-space rollup (capstat / SLO / pool aggregate views)
# ---------------------------------------------------------------------------


def occupancy_from_counters(cur: Dict[str, Any],
                            prev: Optional[Dict[str, Any]] = None
                            ) -> Optional[Dict[str, Any]]:
    """Occupancy rollup from (merged) counter maps.

    With ``prev`` (an earlier scrape of the same surface) the ratios
    are window deltas with the r13 counter-reset clamp (a restarted
    worker's lower counters clamp to zero contribution, never a
    negative rate); without it they are lifetime ratios. Returns None
    when the occupancy section is absent (plane never recorded).
    """
    prev = prev or {}

    def delta(key: str) -> int:
        return max(0, int(cur.get(key, 0)) - int(prev.get(key, 0)))

    if "device.wall_us" not in cur:
        return None
    d_wall = delta("device.wall_us")
    d_busy = delta("device.busy_us")
    fams = sorted({k.split(".")[1] for k in cur
                   if k.startswith("device.") and k.endswith(".busy_us")
                   and k.count(".") == 2})
    out = {
        "occupancy": min(1.0, d_busy / d_wall) if d_wall else 0.0,
        "busy_us": d_busy,
        "wall_us": d_wall,
        "dispatches": delta("device.dispatches"),
        "families": {},
    }
    for fam in fams:
        d_fam = delta(f"device.{fam}.busy_us")
        out["families"][fam] = {
            "occupancy": d_fam / d_wall if d_wall else 0.0,
            "busy_us": d_fam,
            "intervals": delta(f"device.{fam}.intervals"),
        }
    return out
