"""Decision records: WHY every token was accepted or rejected.

The error taxonomy in :mod:`cap_tpu.errors` (34 sentinel classes) is
precise at the raise site and invisible in telemetry — an operator
watching a rejection spike cannot tell expired from bad-signature from
malformed. This module maps every exception a verify surface can
produce onto a small, REGISTERED set of rejection-reason classes and
folds each verdict into:

- **reason-keyed mergeable counters** on the active telemetry
  recorder (``decision.<surface>.accept``,
  ``decision.<surface>.reject.<reason>``,
  ``decision.<surface>.family.<family>``) — these ride the existing
  STATS/snapshot wire and add exactly under
  ``pool.stats_merged()`` / ``capstat``;
- a **sampled decision ring** (bounded, 256 entries per recorder):
  full records ``{surface, family, verdict, reason, lat, trace,
  kid}`` for the first occurrence of every (surface, reason) pair and
  a deterministic 1-in-16 sample after that. The worker obs server
  exposes it at ``/decisions``.

Four surfaces record: the CPU oracle (``KeySet.verify_batch``), the
TPU batch engine (``TPUBatchKeySet``), the serve worker (per response
batch), and the fleet router (``FleetClient.verify_batch``). A
rejection increments the SAME reason class on every surface — the
router sees worker rejections as ``RemoteVerifyError`` whose payload
is ``"<ErrorClass>: <message>"`` (serve/protocol.py), and the
classifier parses that head back to the class's reason, so
cross-process parity is structural, not incidental.

Redaction: reasons, families, and verdicts are registered enum
strings; kids are HASHED (sha256, 12 hex chars) before they touch the
recorder; trace ids are lowercase hex; latency is a bucket label.
``_checked_entry`` enforces this at the write boundary (anything
token-shaped raises), same stance as ``telemetry.check_name``.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
import threading
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence

from .. import telemetry

# ---------------------------------------------------------------------------
# rejection-reason classes (registered; docs/OBSERVABILITY.md pins this
# table and tests pin the mapping's coverage of cap_tpu/errors.py)
# ---------------------------------------------------------------------------

REASON_MALFORMED = "malformed"            # unparseable / invalid structure
REASON_NOT_SIGNED = "not_signed"          # empty/absent signature
REASON_BAD_SIGNATURE = "bad_signature"    # signature check failed
REASON_UNKNOWN_KID = "unknown_kid"        # kid matches no known key
REASON_UNSUPPORTED_ALG = "unsupported_alg"
REASON_EXPIRED = "expired"                # exp / auth_time / request age
REASON_INVALID_CLAIMS = "invalid_claims"  # iss/aud/sub/nonce/azp/hashes
REASON_JWKS_ERROR = "jwks_error"          # key material unavailable/bad
REASON_OIDC_FLOW = "oidc_flow"            # RP flow violations
REASON_TRANSPORT = "transport"            # wire/socket/protocol failure
REASON_THROTTLED = "throttled"            # admission pushback (not a verdict)
REASON_INTERNAL = "internal"              # anything else (bug bucket)

REASON_CLASSES = frozenset({
    REASON_MALFORMED, REASON_NOT_SIGNED, REASON_BAD_SIGNATURE,
    REASON_UNKNOWN_KID, REASON_UNSUPPORTED_ALG, REASON_EXPIRED,
    REASON_INVALID_CLAIMS, REASON_JWKS_ERROR, REASON_OIDC_FLOW,
    REASON_TRANSPORT, REASON_THROTTLED, REASON_INTERNAL,
})

# FIXED-ORDER index form of the registry: the native telemetry plane
# (runtime/native/telemetry_native.cpp) counts by INDEX in a plain C
# struct region and the binding maps indices back to these names at
# scrape time. Order is part of the native ABI — append-only; the
# layout handshake in native_serve disables the plane on length drift.
# Like families, new reasons insert BEFORE "internal" (the native fold
# uses the LAST index as its out-of-range bucket): r20 added
# "throttled" for admission pushback, bumping N_REASON 11 → 12 with a
# matching telemetry_native.h edit + rebuild.
REASON_INDEX = (
    REASON_MALFORMED, REASON_NOT_SIGNED, REASON_BAD_SIGNATURE,
    REASON_UNKNOWN_KID, REASON_UNSUPPORTED_ALG, REASON_EXPIRED,
    REASON_INVALID_CLAIMS, REASON_JWKS_ERROR, REASON_OIDC_FLOW,
    REASON_TRANSPORT, REASON_THROTTLED, REASON_INTERNAL,
)
_REASON_TO_INDEX = {r: i for i, r in enumerate(REASON_INDEX)}

# classify() resolved per exception TYPE (one dict hit on the reject
# path instead of an MRO walk per token). RemoteVerifyError is never
# cached: its reason depends on the MESSAGE head, not the type.
_REASON_IDX_BY_TYPE: Dict[type, int] = {}


def reason_index(err: BaseException) -> int:
    """Index of ``classify(err)`` in :data:`REASON_INDEX` (cached by
    exception type; the native fold consumes the index directly)."""
    t = type(err)
    if t.__name__ == "RemoteVerifyError":
        return _REASON_TO_INDEX[classify(err)]
    idx = _REASON_IDX_BY_TYPE.get(t)
    if idx is None:
        idx = _REASON_TO_INDEX[classify(err)]
        if len(_REASON_IDX_BY_TYPE) < 1024:
            _REASON_IDX_BY_TYPE[t] = idx
    return idx

# Exception CLASS NAME -> reason. Keyed by name (not type) so the
# classifier needs no imports from the crypto-dependent modules and so
# a wire-roundtripped error ("InvalidSignatureError: ...") classifies
# identically to the in-process instance — the four-surface parity
# contract. tests/test_obs_decision.py pins completeness over every
# CapError subclass in cap_tpu/errors.py.
REASON_FOR_ERROR: Dict[str, str] = {
    # base (fallback for unmapped future subclasses via MRO walk)
    "CapError": REASON_INTERNAL,
    # structure / parameters
    "InvalidParameterError": REASON_MALFORMED,
    "NilParameterError": REASON_MALFORMED,
    "MalformedTokenError": REASON_MALFORMED,
    "TokenNotSignedError": REASON_NOT_SIGNED,
    "UnsupportedAlgError": REASON_UNSUPPORTED_ALG,
    # signature layer
    "InvalidSignatureError": REASON_BAD_SIGNATURE,
    "UnknownKeyIDError": REASON_UNKNOWN_KID,
    "IDTokenVerificationFailedError": REASON_BAD_SIGNATURE,
    # freshness
    "ExpiredTokenError": REASON_EXPIRED,
    "ExpiredRequestError": REASON_EXPIRED,
    "ExpiredAuthTimeError": REASON_EXPIRED,
    # claims validation
    "InvalidIssuerError": REASON_INVALID_CLAIMS,
    "InvalidSubjectError": REASON_INVALID_CLAIMS,
    "InvalidAudienceError": REASON_INVALID_CLAIMS,
    "InvalidNonceError": REASON_INVALID_CLAIMS,
    "InvalidNotBeforeError": REASON_INVALID_CLAIMS,
    "InvalidIssuedAtError": REASON_INVALID_CLAIMS,
    "InvalidAuthorizedPartyError": REASON_INVALID_CLAIMS,
    "InvalidAtHashError": REASON_INVALID_CLAIMS,
    "InvalidCodeHashError": REASON_INVALID_CLAIMS,
    "MissingClaimError": REASON_INVALID_CLAIMS,
    # key material
    "InvalidJWKSError": REASON_JWKS_ERROR,
    "InvalidCACertError": REASON_JWKS_ERROR,
    # OIDC relying-party flow
    "InvalidResponseStateError": REASON_OIDC_FLOW,
    "InvalidFlowError": REASON_OIDC_FLOW,
    "UnsupportedChallengeMethodError": REASON_OIDC_FLOW,
    "UnauthorizedRedirectURIError": REASON_OIDC_FLOW,
    "LoginFailedError": REASON_OIDC_FLOW,
    "UserInfoFailedError": REASON_OIDC_FLOW,
    "MissingIDTokenError": REASON_OIDC_FLOW,
    "MissingAccessTokenError": REASON_OIDC_FLOW,
    "IDGeneratorFailedError": REASON_INTERNAL,
    "NotFoundError": REASON_INTERNAL,
    # admission control (serve-time pushback; never a verify verdict)
    "ThrottledError": REASON_THROTTLED,
    # serve/fleet transport layer
    "ProtocolError": REASON_TRANSPORT,
    "MalformedFrameError": REASON_TRANSPORT,
    "FrameTooLargeError": REASON_TRANSPORT,
    "FrameCorruptError": REASON_TRANSPORT,
    "FleetExhaustedError": REASON_TRANSPORT,
    "ConnectionError": REASON_TRANSPORT,
    "TimeoutError": REASON_TRANSPORT,
    "OSError": REASON_TRANSPORT,
}


def classify(err: BaseException) -> str:
    """Map one rejection to its registered reason class.

    ``RemoteVerifyError`` (a worker rejection crossing the CVB1 wire)
    carries ``"<ErrorClass>: <message>"`` — the head is parsed back so
    the router increments the SAME reason the worker's engine did.
    Everything else walks the MRO by class name; unknown classes land
    in ``internal`` (never raises — classification must not be able to
    break a verify path).
    """
    if type(err).__name__ == "RemoteVerifyError":
        head = str(err).split(":", 1)[0].strip()
        return REASON_FOR_ERROR.get(head, REASON_INTERNAL)
    for klass in type(err).__mro__:
        reason = REASON_FOR_ERROR.get(klass.__name__)
        if reason is not None:
            return reason
    return REASON_INTERNAL


# ---------------------------------------------------------------------------
# tenant (issuer) attribution — the per-stream accounting ROADMAP #1's
# admission control needs (arXiv 2112.02229 frames multi-tenant verify
# as filling a fixed-latency pipeline from competing request streams;
# the streams must be *countable* before they can be arbitrated)
# ---------------------------------------------------------------------------

# Tenant ids are sha256(iss)[:12] HASHES — the same redaction stance
# as hash_kid: records correlate per issuer without the issuer string
# (a URL, i.e. payload material) ever touching a recorder.
TENANT_HASH_LEN = 12

# Fixed-size tenant table: at most TENANT_CAP distinct issuer hashes
# get their own label; every later tenant routes to the "other"
# overflow bucket, so a hostile unique-issuer flood cannot blow up
# label cardinality. The cap is part of the native-plane ABI
# (telemetry_native.h N_TEN = TENANT_CAP + 2; layout handshake).
TENANT_CAP = 64
TENANT_NONE = "none"      # no/unparseable issuer claim
TENANT_OTHER = "other"    # table full — overflow bucket
TENANT_NONE_IDX = TENANT_CAP
TENANT_OTHER_IDX = TENANT_CAP + 1
N_TENANT = TENANT_CAP + 2

_MAX_PAYLOAD_SEG = 4096   # issuer parse bound (payloads > headers)
_MAX_ISS_LEN = 1024


class TenantTable:
    """Bounded issuer-hash → slot map (slots 0..TENANT_CAP-1).

    ``admit`` allocates first-come-first-served and routes everything
    past the cap to the overflow slot; the mapping is shared by the
    Python fold and the native plane (the plane counts by SLOT, the
    binding maps slots back to labels here at scrape time), so both
    folds attribute identically by construction. ``reset`` drops every
    mapping and counts the evictions (``tenant.table_evictions``) —
    the only way an admitted tenant ever leaves the table.
    """

    def __init__(self, cap: int = TENANT_CAP):
        self.cap = cap
        self._slots: Dict[str, int] = {}
        self._lock = threading.Lock()
        self.evictions = 0

    def admit(self, tenant_hash: str) -> tuple:
        """(slot, label) for one raw issuer hash: its own slot + hash
        label while the table has room, the overflow slot + "other"
        after."""
        slot = self._slots.get(tenant_hash)
        if slot is not None:
            return (slot, tenant_hash)
        with self._lock:
            slot = self._slots.get(tenant_hash)
            if slot is not None:
                return (slot, tenant_hash)
            if len(self._slots) >= self.cap:
                return (TENANT_OTHER_IDX, TENANT_OTHER)
            slot = len(self._slots)
            self._slots[tenant_hash] = slot
            return (slot, tenant_hash)

    def label(self, slot: int) -> str:
        if slot == TENANT_NONE_IDX:
            return TENANT_NONE
        if slot == TENANT_OTHER_IDX:
            return TENANT_OTHER
        with self._lock:
            for h, s in self._slots.items():
                if s == slot:
                    return h
        return TENANT_OTHER

    def labels(self) -> Dict[int, str]:
        """slot → label for every allocated slot (plus none/other)."""
        with self._lock:
            out = {s: h for h, s in self._slots.items()}
        out[TENANT_NONE_IDX] = TENANT_NONE
        out[TENANT_OTHER_IDX] = TENANT_OTHER
        return out

    def size(self) -> int:
        return len(self._slots)

    def reset(self) -> int:
        """Drop every mapping; returns (and accumulates) the eviction
        count, mirrored onto the active recorder as
        ``tenant.table_evictions``."""
        with self._lock:
            n = len(self._slots)
            self._slots.clear()
            self.evictions += n
        if n:
            telemetry.count("tenant.table_evictions", n)
        return n


# The process-wide table (one per process = one per worker; the fleet
# view merges by LABEL, so slot numbering never crosses processes).
TENANTS = TenantTable()


def issuer_hash(iss: Any) -> str:
    """sha256(iss)[:12 hex] — or "none" for anything that is not a
    plausible issuer string (non-str, empty, over-long)."""
    if not isinstance(iss, str) or not iss or len(iss) > _MAX_ISS_LEN:
        return TENANT_NONE
    return hashlib.sha256(iss.encode("utf-8", "surrogatepass")) \
        .hexdigest()[:TENANT_HASH_LEN]


def token_tenant(token: Any) -> str:
    """Raw tenant hash for one token: the ``iss`` claim of its payload
    segment, hashed — "none" when the token has no parseable issuer.
    Bounded like the header parse (over-long segments are "none"
    without decoding). This is the ONE place issuer extraction
    happens: the native plane never parses payloads, it memoizes what
    this classifier produced (the r13 fix_misses seam)."""
    if not isinstance(token, str):
        return TENANT_NONE
    parts = token.split(".")
    if len(parts) < 2:
        return TENANT_NONE
    seg = parts[1]
    if not seg or len(seg) > _MAX_PAYLOAD_SEG:
        return TENANT_NONE
    try:
        pad = "=" * (-len(seg) % 4)
        claims = json.loads(base64.urlsafe_b64decode(seg + pad))
    except (ValueError, binascii.Error, UnicodeDecodeError):
        return TENANT_NONE
    if not isinstance(claims, dict):
        return TENANT_NONE
    return issuer_hash(claims.get("iss"))


# ---------------------------------------------------------------------------
# family + kid extraction (bounded, cached — hot-path safe)
# ---------------------------------------------------------------------------

# Fixed-order family registry — like REASON_INDEX, the ORDER is part
# of the native telemetry plane's ABI (telemetry_native.h N_FAM /
# FAM_UNKNOWN): new families insert BEFORE "other"/"unknown" with a
# matching header bump + rebuild, and the cap_tel_layout handshake
# disables the plane on any drift.
FAMILIES = ("rs", "ps", "es", "ed", "mldsa44", "mldsa65", "mldsa87",
            "slhdsa128s", "slhdsa128f", "other", "unknown")

_FAMILY_FOR_ALG_PREFIX = {"RS": "rs", "PS": "ps", "ES": "es"}

# Post-quantum families: one registered family per parameter set so a
# hybrid-migration rollout can watch ES256 traffic drain and ML-DSA /
# SLH-DSA traffic ramp as separate counter series (docs/KEYPLANE.md).
_MLDSA_FAMILY = {"ML-DSA-44": "mldsa44", "ML-DSA-65": "mldsa65",
                 "ML-DSA-87": "mldsa87",
                 "SLH-DSA-SHAKE-128s": "slhdsa128s",
                 "SLH-DSA-SHAKE-128f": "slhdsa128f"}

# JOSE headers repeat massively across a token stream (one IdP = a
# handful of distinct headers), so (family, kid-hash, tenant-label)
# is cached by the raw header segment. The cache holds header TEXT as
# keys in memory only — nothing from it is ever recorded. Bounded:
# cleared at cap. The tenant slot is resolved LAZILY (None until a
# tenant-aware caller supplies a token whose payload carries the
# issuer) — attribution granularity is therefore per distinct header,
# which is what lets the native readers classify tenants at frame-
# parse time without ever parsing a payload in C.
_HDR_CACHE: Dict[str, tuple] = {}
_HDR_CACHE_CAP = 4096
_HDR_LOCK = threading.Lock()


def family_for_alg(alg: Optional[str]) -> str:
    # non-string alg values (e.g. a crafted header {"alg": 5}) must
    # classify, not raise — a TypeError here used to escape through
    # record_batch into the serve responder (found by the native-plane
    # parity sweep's adversarial corpus)
    if not alg or not isinstance(alg, str):
        return "unknown"
    if alg == "EdDSA":
        return "ed"
    fam = _MLDSA_FAMILY.get(alg)
    if fam is not None:
        return fam
    return _FAMILY_FOR_ALG_PREFIX.get(alg[:2], "other")


def hash_kid(kid: Optional[str]) -> Optional[str]:
    """12-hex one-way digest: correlates records without carrying the
    kid itself (kids can embed tenant/issuer hints)."""
    if not kid:
        return None
    return hashlib.sha256(str(kid).encode()).hexdigest()[:12]


def _parse_header_segment(seg: str) -> tuple:
    try:
        pad = "=" * (-len(seg) % 4)
        hdr = json.loads(base64.urlsafe_b64decode(seg + pad))
        if not isinstance(hdr, dict):
            return ("unknown", None)
        return (family_for_alg(hdr.get("alg")), hash_kid(hdr.get("kid")))
    except (ValueError, binascii.Error, UnicodeDecodeError):
        return ("unknown", None)


def _seg_family_kid(seg: Any) -> tuple:
    """(family, kid-hash-or-None) for one header SEGMENT (cached)."""
    if not isinstance(seg, str) or not seg or len(seg) > 1024:
        return ("unknown", None)
    hit = _HDR_CACHE.get(seg)
    if hit is not None:
        return hit[:2]
    out = _parse_header_segment(seg) + (None,)
    with _HDR_LOCK:
        if len(_HDR_CACHE) >= _HDR_CACHE_CAP:
            _HDR_CACHE.clear()
        _HDR_CACHE[seg] = out
    return out[:2]


def _seg_fkt(seg: Any, token: Any) -> tuple:
    """(family, kid-hash-or-None, tenant-label) for one header segment,
    resolving the tenant lazily from ``token``'s payload on the first
    tenant-aware sighting of the segment. The label is the table's
    DISPLAY label (hash while the tenant table has room, "other" once
    it overflowed, "none" without an issuer) captured at resolve time
    — stable for the cached lifetime of the segment, which is exactly
    what keeps the Python fold and the native plane bit-identical
    (fix_misses resolves through THIS function)."""
    if not isinstance(seg, str) or not seg or len(seg) > 1024:
        return ("unknown", None, TENANT_NONE)
    hit = _HDR_CACHE.get(seg)
    if hit is not None and hit[2] is not None:
        return hit
    fam, kid = hit[:2] if hit is not None else _parse_header_segment(seg)
    raw = token_tenant(token)
    label = raw if raw == TENANT_NONE else TENANTS.admit(raw)[1]
    out = (fam, kid, label)
    with _HDR_LOCK:
        if len(_HDR_CACHE) >= _HDR_CACHE_CAP:
            _HDR_CACHE.clear()
        _HDR_CACHE[seg] = out
    return out


def tenant_index(label: str) -> int:
    """The native-plane slot for a resolved tenant label (the inverse
    lives in ``TENANTS.labels()``)."""
    if label == TENANT_NONE:
        return TENANT_NONE_IDX
    if label == TENANT_OTHER:
        return TENANT_OTHER_IDX
    return TENANTS.admit(label)[0]


def tenant_labels_from_slots(slots: Sequence[int]) -> List[str]:
    """Native-plane slot array → labels (the native serve chain's
    per-tenant vcache accounting; unresolved slots map to "none")."""
    labels = TENANTS.labels()
    return [labels.get(int(s), TENANT_NONE) for s in slots]


def tenant_labels(tokens: Sequence[Any]) -> List[str]:
    """Per-token tenant labels (header-segment cached — O(1) per
    repeated header). The python serve chain's cache tier uses this
    for its per-tenant vcache accounting."""
    out = []
    for t in tokens:
        seg = t.split(".", 1)[0] if isinstance(t, str) else None
        out.append(_seg_fkt(seg, t)[2])
    return out


def token_family_kid(token: Any) -> tuple:
    """(family, kid-hash-or-None) from a token's header segment.

    O(1) per repeated header (cache hit); the parse itself is bounded
    (header segment > 1024 chars -> "unknown" without decoding).
    """
    if not isinstance(token, str):
        return ("unknown", None)
    return _seg_family_kid(token.split(".", 1)[0])


# ---------------------------------------------------------------------------
# latency buckets
# ---------------------------------------------------------------------------

_LAT_BUCKETS = ((0.001, "lt1ms"), (0.010, "lt10ms"), (0.100, "lt100ms"),
                (1.0, "lt1s"))


def latency_bucket(latency_s: Optional[float]) -> str:
    if latency_s is None:
        return "na"
    for bound, label in _LAT_BUCKETS:
        if latency_s < bound:
            return label
    return "ge1s"


# Fixed-order label table for the native plane (index form of
# latency_bucket; pinned against it by test).
LAT_BUCKET_INDEX = ("lt1ms", "lt10ms", "lt100ms", "lt1s", "ge1s", "na")


def latency_bucket_index(latency_s: Optional[float]) -> int:
    if latency_s is None:
        return 5
    for i, (bound, _label) in enumerate(_LAT_BUCKETS):
        if latency_s < bound:
            return i
    return 4


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

SURFACES = ("oracle", "tpu", "serve", "router", "frontdoor")

# Ring sampling: always the first record of a (surface, reason) pair,
# then every RING_SAMPLE_EVERY-th decision on that key (deterministic —
# derived from the counter value itself, no clock/randomness).
RING_SAMPLE_EVERY = 16

_MAX_FIELD_LEN = 64


def _checked_entry(entry: Dict[str, Any]) -> Dict[str, Any]:
    """Write-boundary redaction: every string field must be a short
    registered identifier — token-shaped or oversized values raise,
    the same stance as :func:`telemetry.check_name`."""
    for k, v in entry.items():
        if isinstance(v, str) and ("eyJ" in v or len(v) > _MAX_FIELD_LEN
                                   or any(ch.isspace() for ch in v)):
            raise ValueError(
                f"decision field {k!r} rejected by redaction rules")
    return entry


def record_batch(surface: str, results: Sequence[Any],
                 tokens: Optional[Sequence[Any]] = None,
                 families: Optional[Sequence[str]] = None,
                 latency_s: Optional[float] = None,
                 trace: Optional[str] = None) -> None:
    """Fold one batch of per-token verdicts into decision telemetry.

    results: the verify_batch contract — claims dict / raw payload
    bytes per accept, Exception per reject. tokens OR families supply
    the per-token family ("unknown" when neither is available, e.g.
    stub engines). No-op (one attribute check) while telemetry is off.
    """
    rec = telemetry.active()
    if rec is None or not results:
        return
    lat = latency_bucket(latency_s)
    if trace is None:
        trace = telemetry.current_trace()

    # AGGREGATED exact path (the serve hot loop calls this once per
    # drained chunk): one pass groups indices by decision key, family
    # counts come from a C-speed Counter over header segments, every
    # counter increments ONCE per group — the counters and the ring
    # SAMPLE POSITIONS are identical to k single-token walks (sampled
    # counts are c == 1 or c % RING_SAMPLE_EVERY == 0 over the same
    # post-increment sequence, attributed to the same token).
    reject_groups: Dict[str, List[int]] = {}
    if any(isinstance(r, BaseException) for r in results):
        accept_idx: Any = []
        for i, res in enumerate(results):
            if isinstance(res, BaseException):
                reject_groups.setdefault(classify(res), []).append(i)
            else:
                accept_idx.append(i)
    else:
        # all-accept fast path (the raw-claims serve hot loop): no
        # index list materialized — sampling indexes a range
        accept_idx = range(len(results))

    n_results = len(results)
    ten_counts: Counter = Counter()
    ten_of = None
    if families is not None:
        fam_counts = Counter(families)
        ten_counts[TENANT_NONE] = n_results

        def fam_kid(i: int) -> tuple:
            return (families[i], None)
    elif tokens is not None:
        try:
            segs: List[Any] = [t.split(".", 1)[0] for t in tokens]
        except AttributeError:      # non-str tokens: guarded walk
            segs = [t.split(".", 1)[0] if isinstance(t, str) else None
                    for t in tokens]
        seg_counts = Counter(segs)
        # tenant resolution rides the SAME per-distinct-segment pass:
        # the first occurrence of a segment in the chunk supplies the
        # payload the issuer comes from (exactly what the native
        # plane's fix_misses does — parity by construction)
        seg_first: Dict[Any, int] = {}
        for i, seg in enumerate(segs):
            if seg not in seg_first:
                seg_first[seg] = i
        seg_fk = {seg: _seg_fkt(seg, tokens[seg_first[seg]])
                  for seg in seg_counts}
        fam_counts = Counter()
        for seg, k in seg_counts.items():
            fam_counts[seg_fk[seg][0]] += k
            ten_counts[seg_fk[seg][2]] += k

        def fam_kid(i: int) -> tuple:
            return seg_fk[segs[i]][:2]

        def ten_of(i: int) -> str:
            return seg_fk[segs[i]][2]
    else:
        fam_counts = Counter({"unknown": len(results)})
        ten_counts[TENANT_NONE] = n_results

        def fam_kid(i: int) -> tuple:
            return ("unknown", None)

    increments = {f"decision.{surface}.family.{fam}": k
                  for fam, k in fam_counts.items()}
    # per-tenant accounting: tokens / accept / reject(+reason) per
    # resolved tenant label, plus the exact global equation
    # tenant.lookups == tenant.attributed + tenant.overflow
    if n_results:
        overflow = ten_counts.get(TENANT_OTHER, 0)
        increments["tenant.lookups"] = n_results
        if n_results - overflow:
            increments["tenant.attributed"] = n_results - overflow
        if overflow:
            increments["tenant.overflow"] = overflow
    for t, k in ten_counts.items():
        increments[f"decision.{surface}.tenant.{t}.tokens"] = k
    if reject_groups and ten_of is not None:
        rej_ten: Dict[str, Counter] = {}
        ten_rejects: Counter = Counter()
        for reason, idxs in reject_groups.items():
            c = Counter(ten_of(i) for i in idxs)
            rej_ten[reason] = c
            ten_rejects.update(c)
        for t, k in ten_rejects.items():
            increments[f"decision.{surface}.tenant.{t}.reject"] = k
        for reason, c in rej_ten.items():
            for t, k in c.items():
                increments[
                    f"decision.{surface}.tenant.{t}.reject.{reason}"] = k
        for t, k in ten_counts.items():
            acc = k - ten_rejects.get(t, 0)
            if acc:
                increments[f"decision.{surface}.tenant.{t}.accept"] = acc
    elif reject_groups:
        # families-only / token-less chunks attribute to "none"
        n_rej = sum(len(v) for v in reject_groups.values())
        increments[f"decision.{surface}.tenant.{TENANT_NONE}.reject"] \
            = n_rej
        for reason, idxs in reject_groups.items():
            increments[f"decision.{surface}.tenant.{TENANT_NONE}"
                       f".reject.{reason}"] = len(idxs)
        if n_results - n_rej:
            increments[f"decision.{surface}.tenant.{TENANT_NONE}"
                       ".accept"] = n_results - n_rej
    else:
        for t, k in ten_counts.items():
            increments[f"decision.{surface}.tenant.{t}.accept"] = k
    accept_key = f"decision.{surface}.accept"
    if accept_idx:
        increments[accept_key] = len(accept_idx)
    for reason, idxs in reject_groups.items():
        increments[f"decision.{surface}.reject.{reason}"] = len(idxs)
    # one lock round for the whole chunk's counters
    post = rec.count_many(increments)
    # per-tenant latency histograms (serve surface only — the worker
    # side is where verification latency is real; router/front-door
    # views come from merged worker snapshots): every token of the
    # chunk observes the chunk latency into its tenant's series, as
    # ONE bucket add of k per tenant (sum += value * k, the exact
    # arithmetic the native plane replicates)
    if surface == "serve" and latency_s is not None:
        for t, k in ten_counts.items():
            rec.observe_many(f"tenant.{t}.request_s", latency_s, k)

    def bulk(key: str, idxs, verdict: str,
             reason: Optional[str]) -> None:
        k = len(idxs)
        after = post[key]
        start = after - k
        sampled = [1] if start == 0 else []
        m = (start // RING_SAMPLE_EVERY + 1) * RING_SAMPLE_EVERY
        while m <= after:
            sampled.append(m)
            m += RING_SAMPLE_EVERY
        for c in sampled:
            fam, kid = fam_kid(idxs[c - start - 1])
            entry: Dict[str, Any] = {
                "surface": surface, "family": fam, "verdict": verdict,
                "lat": lat,
            }
            if reason is not None:
                entry["reason"] = reason
            if kid is not None:
                entry["kid"] = kid
            if trace is not None:
                entry["trace"] = trace
            rec.decision(_checked_entry(entry))

    if accept_idx:
        bulk(f"decision.{surface}.accept", accept_idx, "accept", None)
    for reason, idxs in reject_groups.items():
        bulk(f"decision.{surface}.reject.{reason}", idxs, "reject",
             reason)


def entry_from_exemplar(key: int, fam_idx: int, lat_idx: int,
                        kid: Optional[str],
                        trace: Optional[str]) -> Dict[str, Any]:
    """One ring entry from a native-plane exemplar record.

    ``key`` is 0 for accept, ``1 + reason_index`` for a reject — the
    fields come out exactly as :func:`record_batch`'s ``bulk`` builds
    them (the fuzz parity sweep pins the two paths entry-for-entry).
    """
    entry: Dict[str, Any] = {
        "surface": "serve",
        "family": FAMILIES[fam_idx],
        "verdict": "accept" if key == 0 else "reject",
        "lat": LAT_BUCKET_INDEX[lat_idx],
    }
    if key:
        entry["reason"] = REASON_INDEX[key - 1]
    if kid:
        entry["kid"] = kid
    if trace:
        entry["trace"] = trace
    return _checked_entry(entry)


def record_one(surface: str, result: Any, token: Optional[str] = None,
               latency_s: Optional[float] = None,
               trace: Optional[str] = None) -> None:
    record_batch(surface, [result],
                 tokens=None if token is None else [token],
                 latency_s=latency_s, trace=trace)


def record_wrong_verdict(token: Any = None, n: int = 1) -> None:
    """Count a verdict conflict caught by a cross-check — globally
    (``decision.wrong_verdicts``, the zero-tolerance SLO) AND per
    tenant (``decision.tenant.<t>.wrong_verdicts``, the per-tenant
    zero-tolerance default rule) when the offending token is known."""
    rec = telemetry.active()
    if rec is None or n <= 0:
        return
    inc = {"decision.wrong_verdicts": n}
    if token is not None:
        seg = token.split(".", 1)[0] if isinstance(token, str) else None
        label = _seg_fkt(seg, token)[2]
        inc[f"decision.tenant.{label}.wrong_verdicts"] = n
    rec.count_many(inc)


def count_tenant_cache(labels: Sequence[str],
                       miss_idx: Sequence[int]) -> None:
    """Fold one vcache consult into per-tenant hit accounting
    (``vcache.tenant.<t>.lookups`` / ``.hits``) — what capstat's
    tenant ledger renders as per-tenant hit%. One count_many round
    per batch; a no-op while telemetry is off."""
    rec = telemetry.active()
    if rec is None or not labels:
        return
    lookups = Counter(labels)
    hits = lookups - Counter(labels[i] for i in miss_idx)
    inc = {}
    for t, k in lookups.items():
        inc[f"vcache.tenant.{t}.lookups"] = k
    for t, k in hits.items():
        if k:
            inc[f"vcache.tenant.{t}.hits"] = k
    rec.count_many(inc)


# ---------------------------------------------------------------------------
# read side helpers (capstat / obs_smoke)
# ---------------------------------------------------------------------------


def decision_counters(counters: Dict[str, int]) -> Dict[str, int]:
    """The ``decision.*`` subset of a counter map (snapshot or merged)."""
    return {k: v for k, v in sorted(counters.items())
            if k.startswith("decision.")}


def surface_totals(counters: Dict[str, int]) -> Dict[str, Dict[str, int]]:
    """Per-surface {accept, reject, reject.<reason>...} rollup from a
    (merged) counter map — what capstat renders as the verdict table."""
    out: Dict[str, Dict[str, int]] = {}
    for k, v in counters.items():
        if not k.startswith("decision."):
            continue
        parts = k.split(".")
        # tenant-keyed counters (decision.<surface>.tenant.<t>.* and
        # decision.tenant.<t>.wrong_verdicts) have their own rollup
        # (tenant_totals) — they must not double into the surface view
        if len(parts) < 3 or parts[1] == "tenant" \
                or parts[2] in ("family", "tenant"):
            continue
        surf = parts[1]
        row = out.setdefault(surf, {"accept": 0, "reject": 0})
        if parts[2] == "accept":
            row["accept"] += int(v)
        elif parts[2] == "reject" and len(parts) >= 4:
            row["reject"] += int(v)
            row[f"reject.{parts[3]}"] = row.get(f"reject.{parts[3]}", 0) \
                + int(v)
    return out


def tenant_totals(counters: Dict[str, int],
                  surface: Optional[str] = None
                  ) -> Dict[str, Dict[str, int]]:
    """Per-tenant rollup from a (merged) counter map: tenant label →
    {tokens, accept, reject, reject.<reason>…, wrong_verdicts,
    vcache.lookups, vcache.hits}. ``surface`` narrows the decision
    counters to one surface (capstat's ledger uses "serve" — worker-
    side truth); None sums every surface."""
    out: Dict[str, Dict[str, int]] = {}

    def row(t: str) -> Dict[str, int]:
        return out.setdefault(t, {"tokens": 0, "accept": 0,
                                  "reject": 0})

    for k, v in counters.items():
        parts = k.split(".")
        if k.startswith("decision.tenant.") and len(parts) == 4 \
                and parts[3] == "wrong_verdicts":
            r = row(parts[2])
            r["wrong_verdicts"] = r.get("wrong_verdicts", 0) + int(v)
            continue
        if k.startswith("vcache.tenant.") and len(parts) == 4:
            r = row(parts[2])
            key = f"vcache.{parts[3]}"
            r[key] = r.get(key, 0) + int(v)
            continue
        if not k.startswith("decision.") or len(parts) < 5 \
                or parts[2] != "tenant":
            continue
        if surface is not None and parts[1] != surface:
            continue
        t = parts[3]
        r = row(t)
        what = parts[4]
        if what == "tokens":
            r["tokens"] += int(v)
        elif what == "accept":
            r["accept"] += int(v)
        elif what == "reject":
            if len(parts) >= 6:
                r[f"reject.{parts[5]}"] = r.get(f"reject.{parts[5]}", 0) \
                    + int(v)
            else:
                r["reject"] += int(v)
    return out


def nonzero_check(counters: Dict[str, int],
                  surfaces: Sequence[str]) -> List[str]:
    """obs-smoke's gate: every listed surface must have counted BOTH an
    accept and a reject for the driven mixed batch. Returns problem
    strings (empty = healthy)."""
    problems = []
    rollup = surface_totals(counters)
    for surf in surfaces:
        row = rollup.get(surf)
        if row is None:
            problems.append(f"surface {surf}: no decision counters at all")
            continue
        if row["accept"] <= 0:
            problems.append(f"surface {surf}: zero accept decisions")
        if row["reject"] <= 0:
            problems.append(f"surface {surf}: zero reject decisions")
    return problems
