"""Decision records: WHY every token was accepted or rejected.

The error taxonomy in :mod:`cap_tpu.errors` (34 sentinel classes) is
precise at the raise site and invisible in telemetry — an operator
watching a rejection spike cannot tell expired from bad-signature from
malformed. This module maps every exception a verify surface can
produce onto a small, REGISTERED set of rejection-reason classes and
folds each verdict into:

- **reason-keyed mergeable counters** on the active telemetry
  recorder (``decision.<surface>.accept``,
  ``decision.<surface>.reject.<reason>``,
  ``decision.<surface>.family.<family>``) — these ride the existing
  STATS/snapshot wire and add exactly under
  ``pool.stats_merged()`` / ``capstat``;
- a **sampled decision ring** (bounded, 256 entries per recorder):
  full records ``{surface, family, verdict, reason, lat, trace,
  kid}`` for the first occurrence of every (surface, reason) pair and
  a deterministic 1-in-16 sample after that. The worker obs server
  exposes it at ``/decisions``.

Four surfaces record: the CPU oracle (``KeySet.verify_batch``), the
TPU batch engine (``TPUBatchKeySet``), the serve worker (per response
batch), and the fleet router (``FleetClient.verify_batch``). A
rejection increments the SAME reason class on every surface — the
router sees worker rejections as ``RemoteVerifyError`` whose payload
is ``"<ErrorClass>: <message>"`` (serve/protocol.py), and the
classifier parses that head back to the class's reason, so
cross-process parity is structural, not incidental.

Redaction: reasons, families, and verdicts are registered enum
strings; kids are HASHED (sha256, 12 hex chars) before they touch the
recorder; trace ids are lowercase hex; latency is a bucket label.
``_checked_entry`` enforces this at the write boundary (anything
token-shaped raises), same stance as ``telemetry.check_name``.
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
import threading
from collections import Counter
from typing import Any, Dict, List, Optional, Sequence

from .. import telemetry

# ---------------------------------------------------------------------------
# rejection-reason classes (registered; docs/OBSERVABILITY.md pins this
# table and tests pin the mapping's coverage of cap_tpu/errors.py)
# ---------------------------------------------------------------------------

REASON_MALFORMED = "malformed"            # unparseable / invalid structure
REASON_NOT_SIGNED = "not_signed"          # empty/absent signature
REASON_BAD_SIGNATURE = "bad_signature"    # signature check failed
REASON_UNKNOWN_KID = "unknown_kid"        # kid matches no known key
REASON_UNSUPPORTED_ALG = "unsupported_alg"
REASON_EXPIRED = "expired"                # exp / auth_time / request age
REASON_INVALID_CLAIMS = "invalid_claims"  # iss/aud/sub/nonce/azp/hashes
REASON_JWKS_ERROR = "jwks_error"          # key material unavailable/bad
REASON_OIDC_FLOW = "oidc_flow"            # RP flow violations
REASON_TRANSPORT = "transport"            # wire/socket/protocol failure
REASON_INTERNAL = "internal"              # anything else (bug bucket)

REASON_CLASSES = frozenset({
    REASON_MALFORMED, REASON_NOT_SIGNED, REASON_BAD_SIGNATURE,
    REASON_UNKNOWN_KID, REASON_UNSUPPORTED_ALG, REASON_EXPIRED,
    REASON_INVALID_CLAIMS, REASON_JWKS_ERROR, REASON_OIDC_FLOW,
    REASON_TRANSPORT, REASON_INTERNAL,
})

# FIXED-ORDER index form of the registry: the native telemetry plane
# (runtime/native/telemetry_native.cpp) counts by INDEX in a plain C
# struct region and the binding maps indices back to these names at
# scrape time. Order is part of the native ABI — append-only; the
# layout handshake in native_serve disables the plane on length drift.
REASON_INDEX = (
    REASON_MALFORMED, REASON_NOT_SIGNED, REASON_BAD_SIGNATURE,
    REASON_UNKNOWN_KID, REASON_UNSUPPORTED_ALG, REASON_EXPIRED,
    REASON_INVALID_CLAIMS, REASON_JWKS_ERROR, REASON_OIDC_FLOW,
    REASON_TRANSPORT, REASON_INTERNAL,
)
_REASON_TO_INDEX = {r: i for i, r in enumerate(REASON_INDEX)}

# classify() resolved per exception TYPE (one dict hit on the reject
# path instead of an MRO walk per token). RemoteVerifyError is never
# cached: its reason depends on the MESSAGE head, not the type.
_REASON_IDX_BY_TYPE: Dict[type, int] = {}


def reason_index(err: BaseException) -> int:
    """Index of ``classify(err)`` in :data:`REASON_INDEX` (cached by
    exception type; the native fold consumes the index directly)."""
    t = type(err)
    if t.__name__ == "RemoteVerifyError":
        return _REASON_TO_INDEX[classify(err)]
    idx = _REASON_IDX_BY_TYPE.get(t)
    if idx is None:
        idx = _REASON_TO_INDEX[classify(err)]
        if len(_REASON_IDX_BY_TYPE) < 1024:
            _REASON_IDX_BY_TYPE[t] = idx
    return idx

# Exception CLASS NAME -> reason. Keyed by name (not type) so the
# classifier needs no imports from the crypto-dependent modules and so
# a wire-roundtripped error ("InvalidSignatureError: ...") classifies
# identically to the in-process instance — the four-surface parity
# contract. tests/test_obs_decision.py pins completeness over every
# CapError subclass in cap_tpu/errors.py.
REASON_FOR_ERROR: Dict[str, str] = {
    # base (fallback for unmapped future subclasses via MRO walk)
    "CapError": REASON_INTERNAL,
    # structure / parameters
    "InvalidParameterError": REASON_MALFORMED,
    "NilParameterError": REASON_MALFORMED,
    "MalformedTokenError": REASON_MALFORMED,
    "TokenNotSignedError": REASON_NOT_SIGNED,
    "UnsupportedAlgError": REASON_UNSUPPORTED_ALG,
    # signature layer
    "InvalidSignatureError": REASON_BAD_SIGNATURE,
    "UnknownKeyIDError": REASON_UNKNOWN_KID,
    "IDTokenVerificationFailedError": REASON_BAD_SIGNATURE,
    # freshness
    "ExpiredTokenError": REASON_EXPIRED,
    "ExpiredRequestError": REASON_EXPIRED,
    "ExpiredAuthTimeError": REASON_EXPIRED,
    # claims validation
    "InvalidIssuerError": REASON_INVALID_CLAIMS,
    "InvalidSubjectError": REASON_INVALID_CLAIMS,
    "InvalidAudienceError": REASON_INVALID_CLAIMS,
    "InvalidNonceError": REASON_INVALID_CLAIMS,
    "InvalidNotBeforeError": REASON_INVALID_CLAIMS,
    "InvalidIssuedAtError": REASON_INVALID_CLAIMS,
    "InvalidAuthorizedPartyError": REASON_INVALID_CLAIMS,
    "InvalidAtHashError": REASON_INVALID_CLAIMS,
    "InvalidCodeHashError": REASON_INVALID_CLAIMS,
    "MissingClaimError": REASON_INVALID_CLAIMS,
    # key material
    "InvalidJWKSError": REASON_JWKS_ERROR,
    "InvalidCACertError": REASON_JWKS_ERROR,
    # OIDC relying-party flow
    "InvalidResponseStateError": REASON_OIDC_FLOW,
    "InvalidFlowError": REASON_OIDC_FLOW,
    "UnsupportedChallengeMethodError": REASON_OIDC_FLOW,
    "UnauthorizedRedirectURIError": REASON_OIDC_FLOW,
    "LoginFailedError": REASON_OIDC_FLOW,
    "UserInfoFailedError": REASON_OIDC_FLOW,
    "MissingIDTokenError": REASON_OIDC_FLOW,
    "MissingAccessTokenError": REASON_OIDC_FLOW,
    "IDGeneratorFailedError": REASON_INTERNAL,
    "NotFoundError": REASON_INTERNAL,
    # serve/fleet transport layer
    "ProtocolError": REASON_TRANSPORT,
    "MalformedFrameError": REASON_TRANSPORT,
    "FrameTooLargeError": REASON_TRANSPORT,
    "FrameCorruptError": REASON_TRANSPORT,
    "FleetExhaustedError": REASON_TRANSPORT,
    "ConnectionError": REASON_TRANSPORT,
    "TimeoutError": REASON_TRANSPORT,
    "OSError": REASON_TRANSPORT,
}


def classify(err: BaseException) -> str:
    """Map one rejection to its registered reason class.

    ``RemoteVerifyError`` (a worker rejection crossing the CVB1 wire)
    carries ``"<ErrorClass>: <message>"`` — the head is parsed back so
    the router increments the SAME reason the worker's engine did.
    Everything else walks the MRO by class name; unknown classes land
    in ``internal`` (never raises — classification must not be able to
    break a verify path).
    """
    if type(err).__name__ == "RemoteVerifyError":
        head = str(err).split(":", 1)[0].strip()
        return REASON_FOR_ERROR.get(head, REASON_INTERNAL)
    for klass in type(err).__mro__:
        reason = REASON_FOR_ERROR.get(klass.__name__)
        if reason is not None:
            return reason
    return REASON_INTERNAL


# ---------------------------------------------------------------------------
# family + kid extraction (bounded, cached — hot-path safe)
# ---------------------------------------------------------------------------

# Fixed-order family registry — like REASON_INDEX, the ORDER is part
# of the native telemetry plane's ABI (telemetry_native.h N_FAM /
# FAM_UNKNOWN): new families insert BEFORE "other"/"unknown" with a
# matching header bump + rebuild, and the cap_tel_layout handshake
# disables the plane on any drift.
FAMILIES = ("rs", "ps", "es", "ed", "mldsa44", "mldsa65", "mldsa87",
            "slhdsa128s", "slhdsa128f", "other", "unknown")

_FAMILY_FOR_ALG_PREFIX = {"RS": "rs", "PS": "ps", "ES": "es"}

# Post-quantum families: one registered family per parameter set so a
# hybrid-migration rollout can watch ES256 traffic drain and ML-DSA /
# SLH-DSA traffic ramp as separate counter series (docs/KEYPLANE.md).
_MLDSA_FAMILY = {"ML-DSA-44": "mldsa44", "ML-DSA-65": "mldsa65",
                 "ML-DSA-87": "mldsa87",
                 "SLH-DSA-SHAKE-128s": "slhdsa128s",
                 "SLH-DSA-SHAKE-128f": "slhdsa128f"}

# JOSE headers repeat massively across a token stream (one IdP = a
# handful of distinct headers), so (family, kid-hash) is cached by the
# raw header segment. The cache holds header TEXT as keys in memory
# only — nothing from it is ever recorded. Bounded: cleared at cap.
_HDR_CACHE: Dict[str, tuple] = {}
_HDR_CACHE_CAP = 4096
_HDR_LOCK = threading.Lock()


def family_for_alg(alg: Optional[str]) -> str:
    # non-string alg values (e.g. a crafted header {"alg": 5}) must
    # classify, not raise — a TypeError here used to escape through
    # record_batch into the serve responder (found by the native-plane
    # parity sweep's adversarial corpus)
    if not alg or not isinstance(alg, str):
        return "unknown"
    if alg == "EdDSA":
        return "ed"
    fam = _MLDSA_FAMILY.get(alg)
    if fam is not None:
        return fam
    return _FAMILY_FOR_ALG_PREFIX.get(alg[:2], "other")


def hash_kid(kid: Optional[str]) -> Optional[str]:
    """12-hex one-way digest: correlates records without carrying the
    kid itself (kids can embed tenant/issuer hints)."""
    if not kid:
        return None
    return hashlib.sha256(str(kid).encode()).hexdigest()[:12]


def _parse_header_segment(seg: str) -> tuple:
    try:
        pad = "=" * (-len(seg) % 4)
        hdr = json.loads(base64.urlsafe_b64decode(seg + pad))
        if not isinstance(hdr, dict):
            return ("unknown", None)
        return (family_for_alg(hdr.get("alg")), hash_kid(hdr.get("kid")))
    except (ValueError, binascii.Error, UnicodeDecodeError):
        return ("unknown", None)


def _seg_family_kid(seg: Any) -> tuple:
    """(family, kid-hash-or-None) for one header SEGMENT (cached)."""
    if not isinstance(seg, str) or not seg or len(seg) > 1024:
        return ("unknown", None)
    hit = _HDR_CACHE.get(seg)
    if hit is not None:
        return hit
    out = _parse_header_segment(seg)
    with _HDR_LOCK:
        if len(_HDR_CACHE) >= _HDR_CACHE_CAP:
            _HDR_CACHE.clear()
        _HDR_CACHE[seg] = out
    return out


def token_family_kid(token: Any) -> tuple:
    """(family, kid-hash-or-None) from a token's header segment.

    O(1) per repeated header (cache hit); the parse itself is bounded
    (header segment > 1024 chars -> "unknown" without decoding).
    """
    if not isinstance(token, str):
        return ("unknown", None)
    return _seg_family_kid(token.split(".", 1)[0])


# ---------------------------------------------------------------------------
# latency buckets
# ---------------------------------------------------------------------------

_LAT_BUCKETS = ((0.001, "lt1ms"), (0.010, "lt10ms"), (0.100, "lt100ms"),
                (1.0, "lt1s"))


def latency_bucket(latency_s: Optional[float]) -> str:
    if latency_s is None:
        return "na"
    for bound, label in _LAT_BUCKETS:
        if latency_s < bound:
            return label
    return "ge1s"


# Fixed-order label table for the native plane (index form of
# latency_bucket; pinned against it by test).
LAT_BUCKET_INDEX = ("lt1ms", "lt10ms", "lt100ms", "lt1s", "ge1s", "na")


def latency_bucket_index(latency_s: Optional[float]) -> int:
    if latency_s is None:
        return 5
    for i, (bound, _label) in enumerate(_LAT_BUCKETS):
        if latency_s < bound:
            return i
    return 4


# ---------------------------------------------------------------------------
# recording
# ---------------------------------------------------------------------------

SURFACES = ("oracle", "tpu", "serve", "router", "frontdoor")

# Ring sampling: always the first record of a (surface, reason) pair,
# then every RING_SAMPLE_EVERY-th decision on that key (deterministic —
# derived from the counter value itself, no clock/randomness).
RING_SAMPLE_EVERY = 16

_MAX_FIELD_LEN = 64


def _checked_entry(entry: Dict[str, Any]) -> Dict[str, Any]:
    """Write-boundary redaction: every string field must be a short
    registered identifier — token-shaped or oversized values raise,
    the same stance as :func:`telemetry.check_name`."""
    for k, v in entry.items():
        if isinstance(v, str) and ("eyJ" in v or len(v) > _MAX_FIELD_LEN
                                   or any(ch.isspace() for ch in v)):
            raise ValueError(
                f"decision field {k!r} rejected by redaction rules")
    return entry


def record_batch(surface: str, results: Sequence[Any],
                 tokens: Optional[Sequence[Any]] = None,
                 families: Optional[Sequence[str]] = None,
                 latency_s: Optional[float] = None,
                 trace: Optional[str] = None) -> None:
    """Fold one batch of per-token verdicts into decision telemetry.

    results: the verify_batch contract — claims dict / raw payload
    bytes per accept, Exception per reject. tokens OR families supply
    the per-token family ("unknown" when neither is available, e.g.
    stub engines). No-op (one attribute check) while telemetry is off.
    """
    rec = telemetry.active()
    if rec is None or not results:
        return
    lat = latency_bucket(latency_s)
    if trace is None:
        trace = telemetry.current_trace()

    # AGGREGATED exact path (the serve hot loop calls this once per
    # drained chunk): one pass groups indices by decision key, family
    # counts come from a C-speed Counter over header segments, every
    # counter increments ONCE per group — the counters and the ring
    # SAMPLE POSITIONS are identical to k single-token walks (sampled
    # counts are c == 1 or c % RING_SAMPLE_EVERY == 0 over the same
    # post-increment sequence, attributed to the same token).
    reject_groups: Dict[str, List[int]] = {}
    if any(isinstance(r, BaseException) for r in results):
        accept_idx: Any = []
        for i, res in enumerate(results):
            if isinstance(res, BaseException):
                reject_groups.setdefault(classify(res), []).append(i)
            else:
                accept_idx.append(i)
    else:
        # all-accept fast path (the raw-claims serve hot loop): no
        # index list materialized — sampling indexes a range
        accept_idx = range(len(results))

    if families is not None:
        fam_counts = Counter(families)

        def fam_kid(i: int) -> tuple:
            return (families[i], None)
    elif tokens is not None:
        try:
            segs: List[Any] = [t.split(".", 1)[0] for t in tokens]
        except AttributeError:      # non-str tokens: guarded walk
            segs = [t.split(".", 1)[0] if isinstance(t, str) else None
                    for t in tokens]
        seg_counts = Counter(segs)
        seg_fk = {seg: _seg_family_kid(seg) for seg in seg_counts}
        fam_counts = Counter()
        for seg, k in seg_counts.items():
            fam_counts[seg_fk[seg][0]] += k

        def fam_kid(i: int) -> tuple:
            return seg_fk[segs[i]]
    else:
        fam_counts = Counter({"unknown": len(results)})

        def fam_kid(i: int) -> tuple:
            return ("unknown", None)

    increments = {f"decision.{surface}.family.{fam}": k
                  for fam, k in fam_counts.items()}
    accept_key = f"decision.{surface}.accept"
    if accept_idx:
        increments[accept_key] = len(accept_idx)
    for reason, idxs in reject_groups.items():
        increments[f"decision.{surface}.reject.{reason}"] = len(idxs)
    # one lock round for the whole chunk's counters
    post = rec.count_many(increments)

    def bulk(key: str, idxs, verdict: str,
             reason: Optional[str]) -> None:
        k = len(idxs)
        after = post[key]
        start = after - k
        sampled = [1] if start == 0 else []
        m = (start // RING_SAMPLE_EVERY + 1) * RING_SAMPLE_EVERY
        while m <= after:
            sampled.append(m)
            m += RING_SAMPLE_EVERY
        for c in sampled:
            fam, kid = fam_kid(idxs[c - start - 1])
            entry: Dict[str, Any] = {
                "surface": surface, "family": fam, "verdict": verdict,
                "lat": lat,
            }
            if reason is not None:
                entry["reason"] = reason
            if kid is not None:
                entry["kid"] = kid
            if trace is not None:
                entry["trace"] = trace
            rec.decision(_checked_entry(entry))

    if accept_idx:
        bulk(f"decision.{surface}.accept", accept_idx, "accept", None)
    for reason, idxs in reject_groups.items():
        bulk(f"decision.{surface}.reject.{reason}", idxs, "reject",
             reason)


def entry_from_exemplar(key: int, fam_idx: int, lat_idx: int,
                        kid: Optional[str],
                        trace: Optional[str]) -> Dict[str, Any]:
    """One ring entry from a native-plane exemplar record.

    ``key`` is 0 for accept, ``1 + reason_index`` for a reject — the
    fields come out exactly as :func:`record_batch`'s ``bulk`` builds
    them (the fuzz parity sweep pins the two paths entry-for-entry).
    """
    entry: Dict[str, Any] = {
        "surface": "serve",
        "family": FAMILIES[fam_idx],
        "verdict": "accept" if key == 0 else "reject",
        "lat": LAT_BUCKET_INDEX[lat_idx],
    }
    if key:
        entry["reason"] = REASON_INDEX[key - 1]
    if kid:
        entry["kid"] = kid
    if trace:
        entry["trace"] = trace
    return _checked_entry(entry)


def record_one(surface: str, result: Any, token: Optional[str] = None,
               latency_s: Optional[float] = None,
               trace: Optional[str] = None) -> None:
    record_batch(surface, [result],
                 tokens=None if token is None else [token],
                 latency_s=latency_s, trace=trace)


# ---------------------------------------------------------------------------
# read side helpers (capstat / obs_smoke)
# ---------------------------------------------------------------------------


def decision_counters(counters: Dict[str, int]) -> Dict[str, int]:
    """The ``decision.*`` subset of a counter map (snapshot or merged)."""
    return {k: v for k, v in sorted(counters.items())
            if k.startswith("decision.")}


def surface_totals(counters: Dict[str, int]) -> Dict[str, Dict[str, int]]:
    """Per-surface {accept, reject, reject.<reason>...} rollup from a
    (merged) counter map — what capstat renders as the verdict table."""
    out: Dict[str, Dict[str, int]] = {}
    for k, v in counters.items():
        if not k.startswith("decision."):
            continue
        parts = k.split(".")
        if len(parts) < 3 or parts[2] == "family":
            continue
        surf = parts[1]
        row = out.setdefault(surf, {"accept": 0, "reject": 0})
        if parts[2] == "accept":
            row["accept"] += int(v)
        elif parts[2] == "reject" and len(parts) >= 4:
            row["reject"] += int(v)
            row[f"reject.{parts[3]}"] = row.get(f"reject.{parts[3]}", 0) \
                + int(v)
    return out


def nonzero_check(counters: Dict[str, int],
                  surfaces: Sequence[str]) -> List[str]:
    """obs-smoke's gate: every listed surface must have counted BOTH an
    accept and a reject for the driven mixed batch. Returns problem
    strings (empty = healthy)."""
    problems = []
    rollup = surface_totals(counters)
    for surf in surfaces:
        row = rollup.get(surf)
        if row is None:
            problems.append(f"surface {surf}: no decision counters at all")
            continue
        if row["accept"] <= 0:
            problems.append(f"surface {surf}: zero accept decisions")
        if row["reject"] <= 0:
            problems.append(f"surface {surf}: zero reject decisions")
    return problems
