"""SLO engine: declarative objectives, multi-window burn rates.

The fleet's availability contract is *qualitative* in docs/SERVE.md
("never wrong, at worst slow"); this module makes it *enforceable*:
objectives are declared as data, evaluated against (merged) telemetry
snapshots, and rendered by ``capstat --slo`` — nonzero exit on breach,
so CI and cron probes can page on contract burn instead of reading
dashboards.

Rule kinds
----------

``counter <name> max <v>``
    A counter must never exceed ``v`` (the wrong-verdict objective is
    ``counter decision.wrong_verdicts max 0``). Evaluated on totals
    and, when history exists, on per-window deltas.

``ratio <num> / <den> max <r> [burn <b>]``
    The rate ``num/den`` must stay at or below objective ``r``
    (oracle-fallback rate, hedge rate, protocol-error rate). The
    **burn rate** is ``(num/den) / r`` — 1.0 means the budget is being
    consumed exactly as fast as allowed. A rule breaches when burn
    exceeds ``b`` (default 1.0) in EVERY evaluated window
    (multi-window discipline: a short spike that the long window has
    already absorbed does not page; a sustained burn trips both).

``quantile <series> <p50|p95|p99> max <seconds>``
    A histogram series quantile ceiling (stage latency targets).
    Histogram buckets are cumulative, so quantile rules evaluate on
    lifetime totals (documented limitation — windowed quantiles would
    need bucket-delta history).

``occupancy_floor min <fraction>``
    Device occupancy (``device.busy_us / device.wall_us`` from the r22
    occupancy plane, reset-clamped window deltas) must stay at or
    above the floor **while under load** — a window with zero
    ``device.dispatches`` is idle and never burns (an idle fleet is
    cheap, not broken). Breaches only when EVERY loaded window is
    below the floor, same multi-window discipline as ratio rules.
    ROADMAP #5's acceptance gate is this rule at ``min 0.9``.

Any rule whose names contain the literal ``tenant.*`` is a
**per-tenant template**: at evaluation time it expands into one
concrete rule per observed tenant id (``tenant.<t>`` substituted
everywhere, result named ``rule[<t>]``, multi-window burn semantics
unchanged) — e.g. ``tr ratio decision.serve.tenant.*.reject /
decision.serve.tenant.*.tokens max 0.5 burn 1.5`` pages on the ONE
issuer burning its rejection budget while every other tenant's rule
stays green. ``tq quantile tenant.*.request_s p99 max 0.05`` works
the same way over the per-tenant latency series.

Windows: an :class:`SLOEngine` fed periodic snapshots via
:meth:`SLOEngine.observe` evaluates counter/ratio rules over each
configured window's delta. A one-shot evaluation (``capstat --slo``
scraping a live fleet once) has a single sample: every rule evaluates
over process-lifetime totals, labeled window ``"lifetime"``.

Rules files are plain text (one rule per line, ``#`` comments):

    wrong_verdicts   counter decision.wrong_verdicts max 0
    oracle_fallback  ratio fleet.fallback_tokens / worker.tokens max 0.05
    hedge_rate       ratio fleet.hedges / worker.requests max 0.25 burn 2
    flush_p99        quantile batcher.flush p99 max 0.5
"""

from __future__ import annotations

import re
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import telemetry

# Per-tenant rule templates: any rule whose counter/series names
# contain the literal ``tenant.*`` is EXPANDED at evaluation time —
# one concrete rule per tenant id observed in the evaluated counters
# and series (``tenant.<t>`` substituted, result name ``rule[<t>]``).
# Tenant ids are issuer hashes plus the fixed "none"/"other" labels
# (docs/OBSERVABILITY.md §Tenant attribution); a template with no
# observed tenants evaluates to a single vacuous-ok result so a quiet
# fleet never pages.
TENANT_WILDCARD = "tenant.*"
_TENANT_ID_RE = re.compile(r"\btenant\.([0-9a-f]{12}|none|other)\.")

DEFAULT_RULES_TEXT = """
# The availability contract, as data. `capstat --slo` evaluates these
# (or a rules file) against the scraped fleet; nonzero exit on breach.
wrong_verdicts   counter decision.wrong_verdicts max 0
protocol_errors  ratio worker.protocol_errors / worker.requests max 0.01
oracle_fallback  ratio fleet.fallback_tokens / worker.tokens max 0.05
hedge_rate       ratio fleet.hedges / worker.requests max 0.25
# Keyplane: a rotation must reach every worker fast (push start →
# last ack; docs/KEYPLANE.md) and pushes must not be flaking.
rotation_lag     quantile keyplane.propagate_s p99 max 5
push_failures    ratio keyplane.push_failures / keyplane.push_attempts max 0.5
# Verdict cache: the serve-time tripwire must NEVER fire — a cached
# accept served past its exp/epoch clamp would be a wrong verdict in
# the making (docs/SERVE.md cache-tier invalidation matrix).
stale_accepts    counter vcache.stale_accepts max 0
# Per-tenant budgets (templates — expanded per observed tenant id):
# wrong verdicts are zero-tolerance per tenant exactly as globally,
# and a tenant whose traffic is mostly rejections is burning its own
# rejection budget (a flooding/abusive issuer shows up HERE without
# drowning in fleet-wide averages). Thresholds: ratio > 0.5 sustained
# at burn > 1.5 → a tenant sending ≥75% garbage pages; the obs-smoke
# two-tenant gate pins flood-breaches-while-quiet-stays-green.
tenant_wrong_verdicts counter decision.tenant.*.wrong_verdicts max 0
tenant_reject_ratio   ratio decision.serve.tenant.*.reject / decision.serve.tenant.*.tokens max 0.5 burn 1.5
# Admission (r20): a tenant whose traffic is mostly THROTTLED is
# burning the fleet's admission budget — its own rule pages (the
# flooder breaches, quiet tenants have zero throttles and stay
# green), and the pool autoscaler reads this burn as its shed signal.
tenant_throttle_ratio ratio decision.serve.tenant.*.reject.throttled / decision.serve.tenant.*.tokens max 0.5 burn 1.5
# Occupancy (r22): sustained device idling UNDER LOAD is throughput
# left on the table. Off by default — the discrete-dispatch baseline
# (docs/PERF.md §Round 22) sits far below ROADMAP #5's ≥90% gate until
# continuous batching lands; uncomment (and tighten toward 0.9) then.
#occupancy       occupancy_floor min 0.05
"""


class SLOError(ValueError):
    """A rules file / rule line could not be parsed."""


class SLORule:
    """One declarative objective (see module docstring for kinds)."""

    __slots__ = ("name", "kind", "counter", "num", "den", "series",
                 "quantile", "max_value", "burn_threshold")

    def __init__(self, name: str, kind: str, *, counter: str = "",
                 num: str = "", den: str = "", series: str = "",
                 quantile: str = "p99", max_value: float = 0.0,
                 burn_threshold: float = 1.0):
        self.name = name
        self.kind = kind
        self.counter = counter
        self.num = num
        self.den = den
        self.series = series
        self.quantile = quantile
        self.max_value = max_value
        self.burn_threshold = burn_threshold


def parse_rules(text: str) -> List[SLORule]:
    """Parse the text syntax; raises :class:`SLOError` with the line on
    any violation (an unparseable SLO config must fail loudly, not
    silently guard nothing)."""
    rules: List[SLORule] = []
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        toks = line.split()
        try:
            name, kind = toks[0], toks[1]
            if kind == "counter":
                # <name> counter <counter> max <v>
                if toks[3] != "max":
                    raise IndexError
                rules.append(SLORule(name, "counter", counter=toks[2],
                                     max_value=float(toks[4])))
            elif kind == "ratio":
                # <name> ratio <num> / <den> max <r> [burn <b>]
                if toks[3] != "/" or toks[5] != "max":
                    raise IndexError
                burn = 1.0
                if len(toks) > 7:
                    if toks[7] != "burn":
                        raise IndexError
                    burn = float(toks[8])
                rules.append(SLORule(name, "ratio", num=toks[2],
                                     den=toks[4],
                                     max_value=float(toks[6]),
                                     burn_threshold=burn))
            elif kind == "quantile":
                # <name> quantile <series> <pXX> max <seconds>
                if toks[3] not in ("p50", "p95", "p99") \
                        or toks[4] != "max":
                    raise IndexError
                rules.append(SLORule(name, "quantile", series=toks[2],
                                     quantile=toks[3],
                                     max_value=float(toks[5])))
            elif kind == "occupancy_floor":
                # <name> occupancy_floor min <fraction>
                if toks[2] != "min":
                    raise IndexError
                rules.append(SLORule(name, "occupancy_floor",
                                     max_value=float(toks[3])))
            else:
                raise SLOError(
                    f"line {lineno}: unknown rule kind {kind!r}")
        except (IndexError, ValueError) as e:
            if isinstance(e, SLOError):
                raise
            raise SLOError(
                f"line {lineno}: cannot parse rule {line!r}") from e
    return rules


def default_rules() -> List[SLORule]:
    return parse_rules(DEFAULT_RULES_TEXT)


def is_tenant_template(rule: SLORule) -> bool:
    return any(TENANT_WILDCARD in f for f in
               (rule.counter, rule.num, rule.den, rule.series))


def observed_tenants(counters: Dict[str, Any],
                     series_names: Sequence[str] = ()) -> List[str]:
    """Tenant ids present in a counter map / series-name set — what a
    ``tenant.*`` rule template expands over."""
    ids = set()
    for k in counters:
        m = _TENANT_ID_RE.search(k)
        if m:
            ids.add(m.group(1))
    for k in series_names:
        m = _TENANT_ID_RE.search(k)
        if m:
            ids.add(m.group(1))
    return sorted(ids)


def expand_tenant_rule(rule: SLORule, tenant_id: str) -> SLORule:
    """One concrete rule for one tenant id (``tenant.*`` substituted,
    name suffixed ``[<id>]``)."""
    sub = f"tenant.{tenant_id}"
    return SLORule(
        f"{rule.name}[{tenant_id}]", rule.kind,
        counter=rule.counter.replace(TENANT_WILDCARD, sub),
        num=rule.num.replace(TENANT_WILDCARD, sub),
        den=rule.den.replace(TENANT_WILDCARD, sub),
        series=rule.series.replace(TENANT_WILDCARD, sub),
        quantile=rule.quantile, max_value=rule.max_value,
        burn_threshold=rule.burn_threshold)


class SLOEngine:
    """Evaluate rules against snapshots, with optional burn windows.

    windows: seconds of history per burn window (short, long). History
    is bounded: one retained sample per ``min(windows)/4`` interval,
    capped at 512 samples.
    """

    MAX_SAMPLES = 512

    def __init__(self, rules: Sequence[SLORule],
                 windows: Tuple[float, ...] = (60.0, 300.0)):
        self.rules = list(rules)
        self.windows = tuple(sorted(windows))
        self._samples: List[Tuple[float, Dict[str, int]]] = []

    # -- history ----------------------------------------------------------

    def observe(self, snapshot: Dict[str, Any],
                now: Optional[float] = None) -> None:
        """Feed one (merged) snapshot into the burn-window history."""
        now = time.monotonic() if now is None else now
        counters = dict(snapshot.get("counters") or {})
        min_gap = (self.windows[0] / 4.0) if self.windows else 1.0
        if self._samples and now - self._samples[-1][0] < min_gap:
            self._samples[-1] = (self._samples[-1][0], counters)
        else:
            self._samples.append((now, counters))
        if len(self._samples) > self.MAX_SAMPLES:
            del self._samples[0:len(self._samples) - self.MAX_SAMPLES]

    def _window_deltas(self, now: float
                       ) -> List[Tuple[str, Dict[str, int]]]:
        """(label, counter-delta) per window with data; falls back to a
        single lifetime pseudo-window when history is too thin."""
        out: List[Tuple[str, Dict[str, int]]] = []
        if len(self._samples) >= 2:
            latest = self._samples[-1][1]
            for w in self.windows:
                base = None
                for t, counters in self._samples:
                    if t >= now - w:
                        base = counters
                        break
                if base is None or base is latest:
                    continue
                delta = {k: latest.get(k, 0) - base.get(k, 0)
                         for k in latest}
                out.append((f"{int(w)}s", delta))
        if not out and self._samples:
            out.append(("lifetime", self._samples[-1][1]))
        return out

    # -- evaluation -------------------------------------------------------

    def evaluate(self, snapshot: Optional[Dict[str, Any]] = None,
                 now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One result dict per rule: {name, kind, ok, detail,
        windows: {label: burn-or-value}}. ``snapshot`` (when given) is
        observed first, so a one-shot caller needs a single call."""
        now = time.monotonic() if now is None else now
        if snapshot is not None:
            self.observe(snapshot, now=now)
        deltas = self._window_deltas(now)
        summary = (telemetry.summarize_snapshot(snapshot)
                   if snapshot is not None else {})
        # tenant templates expand over the tenants observed in the
        # LATEST counters + the snapshot's series names — per-tenant
        # objectives are evaluated per tenant, never averaged across
        # tenants (a flooding issuer must not hide behind quiet ones)
        tenants: Optional[List[str]] = None
        results = []
        for rule in self.rules:
            if not is_tenant_template(rule):
                results.append(self._eval_rule(rule, deltas, summary))
                continue
            if tenants is None:
                latest = self._samples[-1][1] if self._samples else {}
                tenants = observed_tenants(latest, summary.keys())
            if not tenants:
                results.append({
                    "name": rule.name, "kind": rule.kind, "ok": True,
                    "windows": {},
                    "detail": "no tenants observed (template idle)"})
                continue
            for tid in tenants:
                res = self._eval_rule(expand_tenant_rule(rule, tid),
                                      deltas, summary)
                res["tenant"] = tid
                results.append(res)
        return results

    def _eval_rule(self, rule: SLORule,
                   deltas: List[Tuple[str, Dict[str, int]]],
                   summary: Dict[str, Dict[str, float]]
                   ) -> Dict[str, Any]:
        res: Dict[str, Any] = {"name": rule.name, "kind": rule.kind,
                               "ok": True, "windows": {}}
        if rule.kind == "counter":
            breached = []
            for label, counters in deltas:
                v = counters.get(rule.counter, 0)
                res["windows"][label] = v
                breached.append(v > rule.max_value)
            res["ok"] = not (breached and all(breached))
            res["detail"] = (f"{rule.counter} max {rule.max_value:g}")
        elif rule.kind == "ratio":
            burns = []
            for label, counters in deltas:
                num = counters.get(rule.num, 0)
                den = counters.get(rule.den, 0)
                rate = (num / den) if den > 0 else 0.0
                burn = (rate / rule.max_value if rule.max_value > 0
                        else (float("inf") if rate > 0 else 0.0))
                res["windows"][label] = round(burn, 4)
                burns.append(burn > rule.burn_threshold)
            res["ok"] = not (burns and all(burns))
            res["detail"] = (f"{rule.num}/{rule.den} max "
                             f"{rule.max_value:g} "
                             f"burn>{rule.burn_threshold:g}")
        elif rule.kind == "occupancy_floor":
            # under-load discipline: an idle window (no dispatches)
            # never burns; deltas are reset-clamped per the r13 stance
            loaded = []
            for label, counters in deltas:
                wall = max(0, counters.get("device.wall_us", 0))
                busy = max(0, counters.get("device.busy_us", 0))
                disp = max(0, counters.get("device.dispatches", 0))
                if wall <= 0 or disp <= 0:
                    res["windows"][label] = "idle"
                    continue
                occ = min(1.0, busy / wall)
                res["windows"][label] = round(occ, 4)
                loaded.append(occ < rule.max_value)
            res["ok"] = not (loaded and all(loaded))
            res["detail"] = (f"device.occupancy min "
                             f"{rule.max_value:g} (under load)")
        elif rule.kind == "quantile":
            s = summary.get(rule.series)
            v = s[rule.quantile] if s else 0.0
            res["windows"]["lifetime"] = round(v, 6)
            res["ok"] = v <= rule.max_value
            res["detail"] = (f"{rule.series} {rule.quantile} max "
                             f"{rule.max_value:g}s")
        else:  # unreachable via parse_rules; defensive for dict-built rules
            res["ok"] = False
            res["detail"] = f"unknown rule kind {rule.kind!r}"
        return res


def any_breach(results: Sequence[Dict[str, Any]]) -> bool:
    return any(not r.get("ok", False) for r in results)


def format_results(results: Sequence[Dict[str, Any]]) -> str:
    """The ``capstat --slo`` table."""
    lines = ["SLO                        state   windows (burn/value)"]
    for r in results:
        state = "ok" if r["ok"] else "BREACH"
        wins = "  ".join(f"{k}={v}" for k, v in r["windows"].items()) \
            or "no-data"
        lines.append(f"  {r['name']:<24} {state:<7} {wins}   "
                     f"[{r.get('detail', '')}]")
    return "\n".join(lines)


def evaluate_once(snapshot: Dict[str, Any],
                  rules: Optional[Sequence[SLORule]] = None
                  ) -> List[Dict[str, Any]]:
    """One-shot evaluation over a single (merged) snapshot — the
    ``capstat --slo`` / bench-embedding entry point."""
    eng = SLOEngine(rules if rules is not None else default_rules())
    return eng.evaluate(snapshot)
