"""The fleet pool manager: spawn, place, supervise, respawn.

``WorkerPool`` owns N ``worker_main`` subprocesses under an explicit
single-owner-per-device placement (``parallel.place``): every worker's
device group is carried as subprocess environment, so two workers can
never share a chip — the placement bug VERDICT r5 flagged in the serve
projection is structurally impossible here.

Supervision model (the host-side dispatcher shape of the FPGA/GPU
batch-verification engines in PAPERS.md — arXiv:2112.02229,
arXiv:2211.12265):

- a supervisor thread polls each child (``Popen.poll``) and pings its
  serve socket on a fresh connection every ``ping_interval``;
- a dead child (crash, kill -9) or one that misses ``hung_after``
  consecutive pings is respawned onto the SAME device group — the old
  process is made fully dead first (SIGTERM → grace → SIGKILL), so
  device ownership transfers without ever being shared;
- respawns are capped (``max_restarts``) to bound a crash storm; a
  worker past the cap is marked ``failed`` and its devices idle.

The pool never touches tokens — it moves processes and reads health.
Routing lives in :mod:`cap_tpu.fleet.router`, which consumes
``endpoints()`` (live addresses, re-polled per attempt round).
"""

from __future__ import annotations

import os
import shutil
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .. import telemetry
from ..errors import CapError
from ..obs import postmortem as _postmortem
from ..parallel.place import (
    WorkerPlacement,
    assert_single_owner,
    single_owner_placement,
)
from ..serve import protocol


class FleetError(CapError):
    default_message = "fleet error"


# Worker lifecycle states.
STARTING = "starting"
READY = "ready"
DRAINING = "draining"
DEAD = "dead"          # crash observed, respawn pending/possible
FAILED = "failed"      # out of respawn budget; devices idle
RETIRED = "retired"    # drained by resize(); slot reusable on growth


class WorkerHandle:
    """One supervised worker slot (a device group and its process)."""

    def __init__(self, placement: WorkerPlacement):
        self.placement = placement
        self.proc: Optional[subprocess.Popen] = None
        self.address: Optional[Tuple[str, int]] = None
        self.obs_address: Optional[Tuple[str, int]] = None
        self.state = STARTING
        self.restarts = 0
        self.ping_failures = 0
        # Last key epoch this worker ACKED (or announced on its ready
        # line); the supervisor re-pushes until it matches the pool's
        # current distribution — convergence after crash/kill -9.
        self.key_epoch: Optional[int] = None
        # Which serve chain the worker announced on its ready line
        # ("native" / "python"; None before the first ready line).
        self.serve_chain: Optional[str] = None
        # Transport capability from the ready line ("shm" / "socket";
        # None while starting) — what actually runs, stale-.so
        # fallback included.
        self.transport: Optional[str] = None
        # Latest collected crash/drain postmortem (obs.postmortem doc)
        # and the checkpoint file the worker writes into.
        self.postmortem: Optional[dict] = None
        self.postmortem_path: Optional[str] = None
        # Peer-fill state: a freshly (re)spawned worker boots with an
        # EMPTY verdict cache; the supervisor keeps offering it a
        # sibling's cache dump (CVB1 types 13/14) until one lands or
        # the attempt budget runs out — warming comes from a peer, not
        # from re-verifying against the IdP.
        self.peer_fill_pending = False
        self.peer_fill_attempts = 0

    @property
    def worker_id(self) -> int:
        return self.placement.worker_id

    @property
    def pid(self) -> Optional[int]:
        return self.proc.pid if self.proc is not None else None


class WorkerPool:
    """Spawn and supervise a fleet of verify workers.

    keyset_spec: passed to every worker (``worker_main.make_keyset``).
    placements: explicit list, or None → ``single_owner_placement(
    n_workers, n_devices or n_workers, platform)``.
    """

    def __init__(self, n_workers: int, keyset_spec: str = "stub",
                 placements: Optional[List[WorkerPlacement]] = None,
                 n_devices: Optional[int] = None, platform: str = "cpu",
                 host: str = "127.0.0.1",
                 target_batch: int = 4096, max_wait_ms: float = 2.0,
                 max_batch: int = 32768,
                 ping_interval: float = 0.5, ping_timeout: float = 2.0,
                 hung_after: int = 3, max_restarts: int = 5,
                 spawn_timeout: float = 60.0, drain_grace: float = 5.0,
                 env_extra: Optional[Dict[str, str]] = None,
                 postmortem_dir: Optional[str] = None,
                 postmortem_interval: float = 1.0,
                 keys_push_timeout: float = 30.0,
                 serve_chain: Optional[str] = None,
                 transport: Optional[str] = None,
                 peer_fill: bool = True, peer_fill_max: int = 2048,
                 peer_fill_attempts: int = 50,
                 autoscale: Optional[dict] = None):
        if placements is None:
            placements = single_owner_placement(
                n_workers, n_devices if n_devices is not None else n_workers,
                platform=platform)
        if len(placements) != n_workers:
            raise FleetError(f"{n_workers} workers but "
                             f"{len(placements)} placements")
        assert_single_owner(placements)
        self._spec = keyset_spec
        self._host = host
        self._worker_args = ["--target-batch", str(target_batch),
                             "--max-wait-ms", str(max_wait_ms),
                             "--max-batch", str(max_batch),
                             "--drain-deadline-s", str(drain_grace)]
        if serve_chain is not None:
            # explicit chain selection ("native"/"python"/"auto") —
            # the ready line still reports what actually came up
            self._worker_args += ["--serve-chain", serve_chain]
        if transport is not None:
            # transport capability ("shm"/"socket"/"auto") — same
            # report-what-runs stance as the serve chain
            self._worker_args += ["--transport", transport]
        self._ping_interval = ping_interval
        self._ping_timeout = ping_timeout
        self._hung_after = hung_after
        self._max_restarts = max_restarts
        self._spawn_timeout = spawn_timeout
        self._drain_grace = drain_grace
        self._env_extra = dict(env_extra or {})
        # Crash postmortems are ON by default: workers checkpoint into
        # per-slot files here; the pool collects a file once the death
        # is CONFIRMED (so even kill -9 leaves a ≤interval-stale
        # document). postmortem_dir=None → a pool-owned temp dir,
        # removed in close(); an explicit dir is the caller's to keep.
        self._pm_interval = postmortem_interval
        self._pm_dir_owned = postmortem_dir is None
        self._pm_dir = (tempfile.mkdtemp(prefix="cap-fleet-pm-")
                        if postmortem_dir is None else postmortem_dir)
        os.makedirs(self._pm_dir, exist_ok=True)
        # Keyplane distribution state: the epoch+JWKS the fleet should
        # converge on. Set BEFORE the first worker is contacted in
        # push_keys, so a crash mid-push leaves the supervisor enough
        # to finish the rotation on the respawned worker.
        self._keys_push_timeout = keys_push_timeout
        self._keys_current: Optional[Tuple[int, dict]] = None
        # Verdict-cache peer fill (docs/SERVE.md §Front door): ON by
        # default — correctness is clamp-guaranteed worker-side, so
        # the only cost of offering is two small control exchanges.
        self._peer_fill = bool(peer_fill)
        self._peer_fill_max = int(peer_fill_max)
        self._peer_fill_budget = int(peer_fill_attempts)
        self._lock = threading.Lock()
        self._closed = threading.Event()
        # Resize machinery (r20): the placement split every later
        # growth extends, plus the bounded transition log capstat and
        # the chaos postmortems render.
        self._platform = placements[0].platform if placements else "cpu"
        self._devices_per_worker = (len(placements[0].device_ids)
                                    if placements else 1)
        self._resize_events: List[dict] = []
        self._handles = [WorkerHandle(p) for p in placements]
        for h in self._handles:
            self._spawn(h)
        telemetry.gauge("fleet.pool_size", n_workers)
        # SLO-burn autoscaler (r20): opt-in via a knob dict (see
        # fleet/autoscale.PoolAutoscaler); ticked from the supervisor
        # sweep so scaling rides the existing supervision cadence.
        self._autoscaler = None
        if autoscale is not None:
            from .autoscale import PoolAutoscaler

            self._autoscaler = PoolAutoscaler(self, **autoscale)
        self._supervisor = threading.Thread(
            target=self._supervise_loop, daemon=True,
            name="cap-tpu-fleet-supervisor")
        self._supervisor.start()

    # -- public surface ---------------------------------------------------

    def endpoints(self) -> Dict[int, Tuple[str, int]]:
        """worker_id → (host, port) of every READY worker."""
        with self._lock:
            return {h.worker_id: h.address for h in self._handles
                    if h.state == READY and h.address is not None}

    def obs_endpoints(self) -> Dict[int, Tuple[str, int]]:
        """worker_id → (host, port) of every READY worker's HTTP
        observability server (/metrics, /snapshot, /flight) — what
        ``tools/capstat.py`` scrapes."""
        with self._lock:
            return {h.worker_id: h.obs_address for h in self._handles
                    if h.state == READY and h.obs_address is not None}

    def address(self, worker_id: int) -> Optional[Tuple[str, int]]:
        with self._lock:
            return self._handles[worker_id].address

    def pid(self, worker_id: int) -> Optional[int]:
        with self._lock:
            return self._handles[worker_id].pid

    def state(self, worker_id: int) -> str:
        with self._lock:
            return self._handles[worker_id].state

    def restarts(self, worker_id: int) -> int:
        with self._lock:
            return self._handles[worker_id].restarts

    def placement_map(self) -> Dict[int, Tuple[int, ...]]:
        """worker_id → owned device ids (the single-owner map)."""
        return {h.worker_id: h.placement.device_ids for h in self._handles}

    def wait_all_ready(self, timeout: float = 60.0) -> bool:
        """Block until every non-failed worker is READY (or timeout)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                states = [h.state for h in self._handles]
            if all(s in (READY, FAILED) for s in states):
                return all(s == READY for s in states)
            time.sleep(0.05)
        return False

    # -- resize / autoscale (r20) -----------------------------------------

    def size(self) -> int:
        """ACTIVE worker slots (everything not retired/failed)."""
        with self._lock:
            return sum(1 for h in self._handles
                       if h.state not in (RETIRED, FAILED))

    def resize_events(self, last: int = 64) -> List[dict]:
        """The bounded transition log: every resize / shed / unshed,
        newest last — capstat renders it and the chaos postmortems
        embed it (the pool annotates collected docs)."""
        with self._lock:
            return list(self._resize_events[-last:])

    def _record_resize(self, kind: str, frm: int, to: int, reason: str,
                       tenant: Optional[str] = None) -> None:
        ev: Dict[str, Any] = {"t": time.time(), "kind": kind,
                              "from": frm, "to": to, "reason": reason}
        if tenant is not None:
            ev["tenant"] = tenant
        with self._lock:
            self._resize_events.append(ev)
            del self._resize_events[:-64]
        telemetry.count(f"fleet.resize.{kind}")
        telemetry.gauge("fleet.pool_size", to)

    def resize(self, n: int, reason: str = "manual") -> int:
        """Grow or shrink the pool to ``n`` active workers under the
        existing placement + supervision machinery.

        Growth reuses RETIRED slots first (fresh respawn budget), then
        appends new single-owner placements extending the original
        devices-per-worker split — virtual on ``cpu`` (each child gets
        its own device world), so growth is unbounded there; a ``tpu``
        pool cannot grow past the chips it was given. Shrink drains
        the HIGHEST-id active workers (SIGTERM → grace → SIGKILL,
        postmortem collected) and retires their slots. Every
        transition is a counter (``fleet.resize.up`` / ``.down``) and
        a :meth:`resize_events` entry. Returns the new active size."""
        n = int(n)
        if n < 1:
            raise FleetError(f"cannot resize below 1 worker (asked {n})")
        cur = self.size()
        if n == cur or self._closed.is_set():
            return cur
        if n > cur:
            grow = n - cur
            with self._lock:
                retired = [h for h in self._handles
                           if h.state == RETIRED][:grow]
            for h in retired:
                with self._lock:
                    h.restarts = 0
                self._spawn(h)
                grow -= 1
            while grow > 0:
                if self._platform == "tpu":
                    raise FleetError(
                        "cannot grow a TPU pool past its initial "
                        "device budget (single-owner placement)")
                with self._lock:
                    wid = len(self._handles)
                    placement = WorkerPlacement(
                        worker_id=wid,
                        device_ids=tuple(range(
                            wid * self._devices_per_worker,
                            (wid + 1) * self._devices_per_worker)),
                        platform=self._platform)
                    active_pl = [x.placement for x in self._handles
                                 if x.state != RETIRED]
                    h = WorkerHandle(placement)
                    self._handles.append(h)
                # disjointness stays structural even under growth
                assert_single_owner(active_pl + [placement])
                self._spawn(h)
                grow -= 1
            self._record_resize("up", cur, n, reason)
            return n
        # shrink: drain the highest-id active workers
        with self._lock:
            victims = sorted(
                (h for h in self._handles
                 if h.state not in (RETIRED, FAILED)),
                key=lambda h: -h.worker_id)[: cur - n]
            for h in victims:
                h.state = DRAINING
        for h in victims:
            self._reap(h, graceful=True)
            self._collect_postmortem(h)
            with self._lock:
                h.state = RETIRED
        self._record_resize("down", cur, n, reason)
        return n

    # -- admission distribution (r20) -------------------------------------

    def _control_exchange(self, h: WorkerHandle,
                          doc: dict) -> Optional[dict]:
        """One type-13/14 control exchange on a fresh connection
        (KEYS-push shape; returns the ack doc or None)."""
        import json as _json

        with self._lock:
            addr = h.address if h.state == READY else None
        if addr is None:
            return None
        try:
            with socket.create_connection(
                    addr, timeout=self._ping_timeout) as s:
                s.settimeout(self._keys_push_timeout)
                protocol.send_peer_fill(s, doc)
                ftype, entries = protocol.FrameReader(s).recv_frame()
            if (ftype != protocol.T_PEER_ACK or not entries
                    or entries[0][0] != 0):
                return None
            return _json.loads(entries[0][1])
        except (OSError, protocol.ProtocolError, ValueError):
            return None

    def push_admission(self, doc: dict) -> Dict[int, bool]:
        """Push one admission op (rate/burst retune and/or per-tenant
        shed scales) to every READY worker — the autoscaler's tighten
        lever, riding the existing peer-fill control pair (no new
        frame type). Returns worker_id → applied."""
        doc = {**doc, "op": "admission"}
        with self._lock:
            targets = [h for h in self._handles
                       if h.state == READY and h.address is not None]
        telemetry.count("fleet.admission_pushes")
        out: Dict[int, bool] = {}
        for h in targets:
            out[h.worker_id] = self._control_exchange(h, doc) \
                is not None
        return out

    def shed_tenant(self, tenant: str, scale: float,
                    reason: str = "slo-burn") -> Dict[int, bool]:
        """Tighten one tenant's admission fleet-wide (scale < 1.0
        sheds; 1.0 restores) — counted, evented, capstat-visible."""
        out = self.push_admission({"scale": {str(tenant):
                                             float(scale)}})
        sz = self.size()
        self._record_resize("shed" if scale < 1.0 else "unshed",
                            sz, sz, reason, tenant=str(tenant))
        return out

    def stats(self) -> Dict[int, Optional[dict]]:
        """Aggregate per-worker STATS snapshots (None for the dead)."""
        out: Dict[int, Optional[dict]] = {}
        for wid, addr in sorted(self.endpoints().items()):
            try:
                with socket.create_connection(
                        addr, timeout=self._ping_timeout) as s:
                    protocol.send_stats_request(s)
                    reader = protocol.FrameReader(s)
                    ftype, entries = reader.recv_frame()
                if ftype == protocol.T_STATS_RESP and entries:
                    import json as _json

                    out[wid] = _json.loads(entries[0][1].decode())
                else:
                    out[wid] = None
            except (OSError, protocol.ProtocolError):
                out[wid] = None
        with self._lock:
            for h in self._handles:
                out.setdefault(h.worker_id, None)
        return out

    def tenant_totals(self) -> dict:
        """Fleet-wide per-tenant rollup (issuer hash → tokens /
        accept / reject mix / vcache splits) over the EXACT merged
        worker counters — the pool-side form of ``capstat --tenants``
        (docs/OBSERVABILITY.md §Tenant attribution)."""
        from ..obs import decision as _decision

        merged = self.stats_merged()["aggregate"]["counters"]
        return _decision.tenant_totals(merged)

    def stats_merged(self) -> dict:
        """Per-worker STATS plus an EXACT fleet aggregate.

        The per-worker payloads carry mergeable telemetry snapshots
        (bucket counts), so the aggregate's p50/p95/p99 are those of
        one recorder that had observed every worker's samples — not a
        lossy average of per-worker quantiles.
        """
        from ..obs import occupancy as _occupancy

        workers = self.stats()
        merged = telemetry.merge_snapshots(
            [(s or {}).get("snapshot") for s in workers.values()])
        return {
            "workers": workers,
            "aggregate": {
                "snapshot": merged,
                "series": telemetry.summarize_snapshot(merged),
                "counters": merged["counters"],
                "gauges": merged["gauges"],
                # fleet occupancy from the EXACT merged counters:
                # sum-busy / sum-wall = worker-weighted mean (None
                # until some worker's engine dispatched)
                "occupancy": _occupancy.occupancy_from_counters(
                    merged["counters"]),
                "queued_tokens": sum(
                    (s or {}).get("queued_tokens", 0)
                    for s in workers.values()),
                "inflight_batches": sum(
                    (s or {}).get("inflight_batches", 0)
                    for s in workers.values()),
                "restarts": {h.worker_id: h.restarts
                             for h in self._handles},
                "key_epochs": self.key_epochs(),
                "epoch_skew": self.epoch_skew(),
                "serve_chains": self.serve_chains(),
                "transports": self.transports(),
                "pool_size": self.size(),
                "resize_events": self.resize_events(),
            },
        }

    # -- keyplane distribution --------------------------------------------

    def push_keys(self, jwks_doc: dict,
                  epoch: Optional[int] = None) -> Dict[int, Optional[int]]:
        """Push one key epoch to every READY worker; returns
        worker_id → acked epoch (None: push failed — the supervisor
        keeps re-pushing until the worker converges or dies).

        The distribution target is recorded BEFORE any worker is
        contacted: a worker killed mid-push converges after respawn
        (the ready-path re-push), and a worker that missed its frame
        converges on the next supervisor sweep. ``epoch`` defaults to
        the previous push's epoch + 1.
        """
        with self._lock:
            if epoch is None:
                epoch = (self._keys_current[0] + 1
                         if self._keys_current else 1)
            epoch = int(epoch)
            self._keys_current = (epoch, jwks_doc)
            targets = [h for h in self._handles
                       if h.state == READY and h.address is not None]
        telemetry.count("keyplane.pushes")
        telemetry.gauge("keyplane.epoch", epoch)
        t0 = time.perf_counter()
        out: Dict[int, Optional[int]] = {}
        for h in targets:
            out[h.worker_id] = self._push_keys_to(h, jwks_doc, epoch)
        if out and all(v == epoch for v in out.values()):
            # Rotation propagation lag: push start → last ack. The
            # default SLO rules bound its p99 (docs/KEYPLANE.md).
            telemetry.observe("keyplane.propagate_s",
                              time.perf_counter() - t0)
        with self._lock:
            for h in self._handles:
                out.setdefault(h.worker_id, h.key_epoch
                               if h.key_epoch == epoch else None)
        return out

    def _push_keys_to(self, h: WorkerHandle, jwks_doc: dict,
                      epoch: int) -> Optional[int]:
        """One KEYS push/ack exchange on a fresh connection."""
        with self._lock:
            addr = h.address if h.state == READY else None
        if addr is None:
            return None
        telemetry.count("keyplane.push_attempts")
        try:
            with socket.create_connection(
                    addr, timeout=self._ping_timeout) as s:
                # Table builds on real keysets take longer than a
                # ping: the exchange gets its own (generous) deadline.
                s.settimeout(self._keys_push_timeout)
                protocol.send_keys_push(s, jwks_doc, epoch)
                ftype, entries = protocol.FrameReader(s).recv_frame()
        except (OSError, protocol.ProtocolError):
            telemetry.count("keyplane.push_failures")
            return None
        if (ftype != protocol.T_KEYS_ACK or not entries
                or entries[0][0] != 0):
            telemetry.count("keyplane.push_failures")
            return None
        import json as _json

        try:
            got = int(_json.loads(entries[0][1]).get("epoch"))
        except (ValueError, TypeError):
            telemetry.count("keyplane.push_failures")
            return None
        with self._lock:
            h.key_epoch = got
        return got

    def key_epochs(self) -> Dict[int, Optional[int]]:
        """worker_id → last known key epoch (ready line or KEYS ack)."""
        with self._lock:
            return {h.worker_id: h.key_epoch for h in self._handles}

    def serve_chains(self) -> Dict[int, Optional[str]]:
        """worker_id → serve chain from the ready line ("native" /
        "python"; None while a worker is still starting) — how
        bench_serve/capstat see which chain each worker runs."""
        with self._lock:
            return {h.worker_id: h.serve_chain for h in self._handles}

    def transports(self) -> Dict[int, Optional[str]]:
        """worker_id → transport capability from the ready line
        ("shm" / "socket"; None while starting) — fleet transport
        state in one place, like :meth:`serve_chains`."""
        with self._lock:
            return {h.worker_id: h.transport for h in self._handles}

    def keys_epoch(self) -> Optional[int]:
        """The epoch the fleet is converging on (None: never pushed)."""
        with self._lock:
            return self._keys_current[0] if self._keys_current else None

    def epoch_skew(self) -> int:
        """Spread between the newest and oldest known worker epoch —
        0 when the fleet is converged (what the router surfaces)."""
        epochs = [e for e in self.key_epochs().values() if e is not None]
        if not epochs:
            return 0
        return max(epochs) - min(epochs)

    # -- verdict-cache peer fill ------------------------------------------

    def _peer_fill_once(self, h: WorkerHandle) -> bool:
        """Offer ``h`` one sibling's cache dump: pull an export from a
        READY peer, push it into ``h`` as an import. Returns True when
        at least one entry landed (the worker's own clamps decide —
        the pool moves opaque entries, it never parses verdicts).

        Every fault is survivable: a dead sibling, an empty cache, an
        epoch mismatch at the importer all just mean "try again on the
        next supervisor sweep" while the attempt budget lasts."""
        import json as _json

        with self._lock:
            addr = h.address if h.state == READY else None
            donors = [d.address for d in self._handles
                      if d is not h and d.state == READY
                      and d.address is not None]
        if addr is None or not donors:
            return False
        telemetry.count("fleet.peer_fill_attempts")
        for donor in donors:
            try:
                with socket.create_connection(
                        donor, timeout=self._ping_timeout) as s:
                    s.settimeout(self._keys_push_timeout)
                    protocol.send_peer_fill(
                        s, {"op": "export",
                            "max": self._peer_fill_max})
                    ftype, entries = \
                        protocol.FrameReader(s).recv_frame()
                if (ftype != protocol.T_PEER_ACK or not entries
                        or entries[0][0] != 0):
                    continue
                doc = _json.loads(entries[0][1])
                dump = doc.get("entries") or []
                if not dump:
                    continue
                with socket.create_connection(
                        addr, timeout=self._ping_timeout) as s:
                    s.settimeout(self._keys_push_timeout)
                    protocol.send_peer_fill(
                        s, {"op": "import", "epoch": doc.get("epoch"),
                            "entries": dump})
                    ftype, entries = \
                        protocol.FrameReader(s).recv_frame()
                if (ftype != protocol.T_PEER_ACK or not entries
                        or entries[0][0] != 0):
                    continue
                imported = int(
                    _json.loads(entries[0][1]).get("imported") or 0)
                if imported > 0:
                    telemetry.count("fleet.peer_fill_transfers")
                    telemetry.count("fleet.peer_fill_entries",
                                    imported)
                    return True
            except (OSError, protocol.ProtocolError, ValueError,
                    TypeError):
                telemetry.count("fleet.peer_fill_errors")
                continue
        return False

    def _peer_fill_sweep(self, h: WorkerHandle) -> None:
        """One supervisor-cadence peer-fill attempt for a pending
        worker; clears the pending flag on success or budget
        exhaustion."""
        with self._lock:
            if (not self._peer_fill or not h.peer_fill_pending
                    or h.state != READY):
                return
            h.peer_fill_attempts += 1
            give_up = h.peer_fill_attempts > self._peer_fill_budget
        if give_up:
            with self._lock:
                h.peer_fill_pending = False
            return
        if self._peer_fill_once(h):
            with self._lock:
                h.peer_fill_pending = False

    def postmortem(self, worker_id: int) -> Optional[dict]:
        """The latest postmortem collected for this slot (crash or
        drain), or — when no death was confirmed yet — whatever the
        LIVE worker last checkpointed (best-effort read)."""
        with self._lock:
            h = self._handles[worker_id]
            doc, path = h.postmortem, h.postmortem_path
        if doc is not None:
            return doc
        return _postmortem.read_postmortem(path) if path else None

    def postmortem_path(self, worker_id: int) -> Optional[str]:
        with self._lock:
            return self._handles[worker_id].postmortem_path

    def postmortems(self) -> Dict[int, Optional[dict]]:
        return {h.worker_id: self.postmortem(h.worker_id)
                for h in self._handles}

    def _collect_postmortem(self, h: WorkerHandle) -> None:
        """Read the dead worker's last checkpoint into the handle
        (called only after the death is CONFIRMED, so the file cannot
        be mid-replace — writes are atomic anyway)."""
        if not h.postmortem_path:
            return
        doc = _postmortem.read_postmortem(h.postmortem_path)
        if doc is not None:
            # Pool-side enrichment: the dying worker cannot see pool
            # transitions, so the collector stamps the resize/shed log
            # onto the doc — the chaos bar "resize events visible in
            # the victim's postmortem".
            events = self.resize_events()
            if events:
                doc["pool_resize_events"] = events
            with self._lock:
                h.postmortem = doc
            telemetry.count("fleet.postmortems_collected")

    def restart(self, worker_id: int, graceful: bool = True) -> None:
        """Respawn one worker onto its device group.

        graceful: SIGTERM first (the worker drains: stops accepting,
        flushes queued batches) with ``drain_grace`` to comply, then
        SIGKILL. The replacement is only spawned once the old process
        is confirmed dead — single-owner transfer, never sharing.
        """
        with self._lock:
            h = self._handles[worker_id]
            h.state = DRAINING
        self._reap(h, graceful=graceful)
        self._collect_postmortem(h)
        with self._lock:
            if self._closed.is_set():
                return
            h.restarts += 1
            if h.restarts > self._max_restarts:
                h.state = FAILED
                telemetry.count("fleet.workers_failed")
                return
        telemetry.count("fleet.respawns")
        self._spawn(h)

    def close(self) -> None:
        self._closed.set()
        for h in self._handles:
            self._reap(h, graceful=True)
            self._collect_postmortem(h)
            with self._lock:
                h.state = DEAD
        if self._pm_dir_owned:
            # The docs were collected onto the handles; the pool-owned
            # checkpoint dir has served its purpose.
            shutil.rmtree(self._pm_dir, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- internals --------------------------------------------------------

    def _spawn(self, h: WorkerHandle) -> None:
        h.postmortem_path = os.path.join(
            self._pm_dir, f"worker-{h.worker_id}.json")
        env = {**os.environ, **h.placement.env(), **self._env_extra,
               "CAP_FLEET_PM_PATH": h.postmortem_path,
               "CAP_FLEET_PM_INTERVAL": str(self._pm_interval)}
        cmd = [sys.executable, "-m", "cap_tpu.fleet.worker_main",
               "--host", self._host, "--port", "0",
               "--keyset", self._spec, *self._worker_args]
        with self._lock:
            h.state = STARTING
            h.address = None
            h.ping_failures = 0
            h.proc = subprocess.Popen(
                cmd, stdout=subprocess.PIPE, stderr=None, env=env,
                text=True, bufsize=1,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))))
        threading.Thread(target=self._await_ready, args=(h, h.proc),
                         daemon=True, name="cap-tpu-fleet-ready").start()

    def _await_ready(self, h: WorkerHandle, proc: subprocess.Popen) -> None:
        """Parse the child's ready line (bounded), then keep draining
        its stdout so a chatty child can never block on a full pipe."""
        deadline = time.monotonic() + self._spawn_timeout
        port = None
        obs_port = None
        epoch = None
        serve_chain = None
        transport = None
        try:
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:            # EOF: child died before ready
                    break
                if line.startswith("CAP_FLEET_READY"):
                    for field in line.split():
                        k, _, v = field.partition("=")
                        if k == "port":
                            port = int(v)
                        elif k == "obs":
                            obs_port = int(v)
                        elif k == "epoch":
                            epoch = int(v)
                        elif k == "serve_chain":
                            serve_chain = v
                        elif k == "transport":
                            transport = v
                    break
        except (OSError, ValueError):
            port = None
        with self._lock:
            if h.proc is not proc or self._closed.is_set():
                return                  # superseded by a later respawn
            if port is None:
                h.state = DEAD
                telemetry.count("fleet.spawn_failures")
            else:
                h.address = (self._host, port)
                h.obs_address = ((self._host, obs_port)
                                 if obs_port else None)
                h.key_epoch = epoch
                h.serve_chain = serve_chain
                h.transport = transport
                h.state = READY
                h.peer_fill_pending = self._peer_fill
                h.peer_fill_attempts = 0
                telemetry.count("fleet.workers_started")
            keys_current = self._keys_current
        if port is not None and keys_current is not None \
                and epoch != keys_current[0]:
            # A (re)spawned worker boots on its own key material:
            # converge it onto the fleet's current epoch immediately —
            # the kill -9-mid-push recovery path.
            self._push_keys_to(h, keys_current[1], keys_current[0])
        if port is not None:
            # First peer-fill offer right at ready (epochs converged
            # above); siblings that are still cold fail soft and the
            # supervisor keeps retrying on its sweep cadence.
            self._peer_fill_sweep(h)
        # Drain any further output (worker stays quiet normally).
        try:
            for _ in proc.stdout:
                pass
        except (OSError, ValueError):
            pass

    def _ping(self, addr: Tuple[str, int]) -> bool:
        t0 = time.perf_counter()
        try:
            with socket.create_connection(
                    addr, timeout=self._ping_timeout) as s:
                s.settimeout(self._ping_timeout)
                protocol.send_ping(s)
                ftype, _ = protocol.recv_frame(s)
                if ftype == protocol.T_PONG:
                    # Health-ping round trip: the supervisor's view of
                    # worker responsiveness (a climbing p99 here is the
                    # early signal before hung_after trips).
                    telemetry.observe("fleet.ping_s",
                                      time.perf_counter() - t0)
                    return True
                return False
        except (OSError, protocol.ProtocolError):
            return False

    def _supervise_loop(self) -> None:
        while not self._closed.wait(self._ping_interval):
            with self._lock:
                telemetry.gauge(
                    "fleet.workers_ready",
                    sum(1 for h in self._handles if h.state == READY))
            if self._autoscaler is not None:
                try:
                    self._autoscaler.tick()
                except Exception:  # noqa: BLE001 - never kill the loop
                    telemetry.count("fleet.autoscale_errors")
            for h in list(self._handles):
                if self._closed.is_set():
                    return
                with self._lock:
                    state, proc, addr = h.state, h.proc, h.address
                if state in (FAILED, RETIRED) or proc is None:
                    continue
                if proc.poll() is not None and state != DRAINING:
                    # Crash (or kill -9): the process is gone.
                    telemetry.count("fleet.worker_crashes")
                    with self._lock:
                        h.state = DEAD
                    self.restart(h.worker_id, graceful=False)
                    continue
                if state == READY and addr is not None:
                    if self._ping(addr):
                        with self._lock:
                            h.ping_failures = 0
                            keys_current = self._keys_current
                            stale = (keys_current is not None
                                     and h.key_epoch != keys_current[0])
                        if stale:
                            # Missed or failed push (worker restarted
                            # mid-rotation, transient socket error):
                            # keep re-pushing until the ack matches.
                            self._push_keys_to(h, keys_current[1],
                                               keys_current[0])
                        self._peer_fill_sweep(h)
                    else:
                        with self._lock:
                            h.ping_failures += 1
                            hung = h.ping_failures >= self._hung_after
                        if hung:
                            # Alive but unresponsive: treat as hung.
                            telemetry.count("fleet.workers_hung")
                            self.restart(h.worker_id, graceful=True)
                elif state == DEAD:
                    self.restart(h.worker_id, graceful=False)

    def _reap(self, h: WorkerHandle, graceful: bool) -> None:
        """Make the worker's process fully dead (drain → kill)."""
        with self._lock:
            proc = h.proc
        if proc is None or proc.poll() is not None:
            return
        try:
            proc.send_signal(signal.SIGTERM if graceful
                             else signal.SIGKILL)
        except (ProcessLookupError, OSError):
            return
        try:
            proc.wait(timeout=self._drain_grace if graceful else 5.0)
        except subprocess.TimeoutExpired:
            try:
                proc.kill()
                proc.wait(timeout=5.0)
            except (ProcessLookupError, OSError,
                    subprocess.TimeoutExpired):
                pass
