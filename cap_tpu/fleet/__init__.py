"""Serve fleet: multi-worker pool, failover routing, fault injection.

The production-scale layer above :mod:`cap_tpu.serve` (ROADMAP: "heavy
traffic from millions of users"): one ``VerifyWorker`` process per
exclusive device group (``parallel.place.single_owner_placement``),
supervised by :class:`WorkerPool` (health pings, crash detection,
respawn with graceful drain), fronted by :class:`FleetClient`
(balancing, per-worker deadlines, circuit breakers, hedged retry,
checksummed frames, terminal CPU-oracle fallback). :class:`FrontDoor`
is the tier above THAT: one router speaking CVB1 to N pools ("hosts"),
routing every token by consistent hash over its digest so repeats land
on the host that cached their verdict — the fleet-wide verdict tier —
with bounded-load spill, breaker-driven re-route, keyplane fan-out and
peer-fill cache warming. ``chaos`` is the fault-injection harness the
availability contract is tested against: zero wrong verdicts, zero
lost submissions, under kill -9, stalls, black holes, and corrupt
frames. See docs/SERVE.md.
"""

from .autoscale import PoolAutoscaler
from .frontdoor import ConsistentHashRing, FrontDoor
from .pool import FleetError, WorkerPool
from .router import FleetClient, FleetExhaustedError

__all__ = ["ConsistentHashRing", "FleetClient", "FleetError",
           "FleetExhaustedError", "FrontDoor", "PoolAutoscaler",
           "WorkerPool"]
