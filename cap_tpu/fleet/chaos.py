"""Fault-injection harness for the serve fleet.

Two levers, composable from tests:

- **process faults**: :func:`kill9` sends SIGKILL to a live worker —
  the hardest crash there is, mid-batch by construction when the
  worker is sleeping in its (stub) device or draining a real one;
- **network faults**: :class:`ChaosProxy`, a TCP forwarder that sits
  between the router and one worker and can, at any moment:

  - ``delay_accept(s)`` — hold every new connection for ``s`` before
    the upstream connect (slow-accept worker);
  - ``stall()`` — stop moving bytes (both directions) while keeping
    the connections open: the client sees a socket that accepts writes
    and never answers;
  - ``blackhole()`` — keep reading and DROP everything (the worker
    never sees requests; the client never sees responses);
  - ``corrupt(direction, offset, xor)`` — flip byte(s) of the next
    forwarded chunk: the corrupt-response-frame mode that the
    checksummed CVB1 frames (types 7/8) must catch;
  - ``clear()`` — lift every fault (in-flight connections resume).

The proxy's target is a CALLABLE so a respawned worker (new port) is
picked up by the next connection — tests route the router through
proxies and the pool around them.

The harness moves bytes and signals only: it never parses, logs, or
stores token material (redaction discipline).
"""

from __future__ import annotations

import os
import signal
import socket
import threading
import time
from typing import Callable, Optional, Tuple, Union

Target = Union[Tuple[str, int], Callable[[], Optional[Tuple[str, int]]]]


def kill9(pid: int) -> None:
    """SIGKILL a worker process (the crash the pool must recover)."""
    os.kill(pid, signal.SIGKILL)


class _Faults:
    """Shared, lock-guarded fault state for one proxy."""

    def __init__(self):
        self.lock = threading.Lock()
        self.accept_delay = 0.0
        self.stalled = False
        self.blackholed = False
        # direction -> remaining corruptions [(offset, xor)]
        self.corrupt_c2s: list = []
        self.corrupt_s2c: list = []


class ChaosProxy:
    """A byte-level TCP forwarder with switchable faults.

    target: (host, port) or a callable returning the CURRENT address
    (None → connection refused), e.g. ``lambda: pool.address(0)``.
    """

    def __init__(self, target: Target, host: str = "127.0.0.1",
                 port: int = 0):
        self._target = target
        self._faults = _Faults()
        self._closed = threading.Event()
        self._conns: list = []
        self._conns_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self._addr = self._sock.getsockname()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="cap-tpu-chaos-accept").start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._addr

    # -- fault switchboard ------------------------------------------------

    def delay_accept(self, seconds: float) -> None:
        with self._faults.lock:
            self._faults.accept_delay = seconds

    def stall(self) -> None:
        with self._faults.lock:
            self._faults.stalled = True

    def blackhole(self) -> None:
        with self._faults.lock:
            self._faults.blackholed = True

    def corrupt(self, direction: str = "s2c", offset: int = 9,
                xor: int = 0x01, times: int = 1) -> None:
        """Flip ``xor`` into byte ``offset`` of the next ``times``
        forwarded chunks in ``direction`` ("s2c" = response path).
        The default (offset 9, xor 0x01) hits the first response
        entry's STATUS byte — the exact bit whose silent flip would
        turn a verified token into a rejection."""
        with self._faults.lock:
            lst = (self._faults.corrupt_s2c if direction == "s2c"
                   else self._faults.corrupt_c2s)
            lst.extend([(offset, xor)] * times)

    def clear(self) -> None:
        with self._faults.lock:
            self._faults.accept_delay = 0.0
            self._faults.stalled = False
            self._faults.blackholed = False
            self._faults.corrupt_c2s.clear()
            self._faults.corrupt_s2c.clear()

    def drop_connections(self) -> None:
        """Hard-close every proxied connection (both sides see RST)."""
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self.drop_connections()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- internals --------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                client, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._bridge, args=(client,),
                             daemon=True, name="cap-tpu-chaos-conn").start()

    def _bridge(self, client: socket.socket) -> None:
        with self._faults.lock:
            delay = self._faults.accept_delay
        if delay:
            time.sleep(delay)
        if self._closed.is_set():
            client.close()
            return
        target = self._target() if callable(self._target) else self._target
        try:
            if target is None:
                raise OSError("no live target")
            upstream = socket.create_connection(target, timeout=10.0)
        except OSError:
            client.close()
            return
        with self._conns_lock:
            self._conns.extend([client, upstream])
        threading.Thread(
            target=self._pump, args=(client, upstream, "c2s"),
            daemon=True, name="cap-tpu-chaos-c2s").start()
        self._pump(upstream, client, "s2c")

    def _pump(self, src: socket.socket, dst: socket.socket,
              direction: str) -> None:
        try:
            while not self._closed.is_set():
                # A stalled proxy stops READING too: backpressure
                # propagates to the sender, like a wedged worker.
                while True:
                    with self._faults.lock:
                        stalled = self._faults.stalled
                    if not stalled or self._closed.is_set():
                        break
                    time.sleep(0.02)
                chunk = src.recv(1 << 16)
                if not chunk:
                    break
                with self._faults.lock:
                    if self._faults.blackholed:
                        continue        # read and drop
                    lst = (self._faults.corrupt_s2c if direction == "s2c"
                           else self._faults.corrupt_c2s)
                    if lst:
                        offset, xor = lst.pop(0)
                        b = bytearray(chunk)
                        b[min(offset, len(b) - 1)] ^= xor
                        chunk = bytes(b)
                dst.sendall(chunk)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.close()
                except OSError:
                    pass
