"""SLO-burn-driven pool autoscaling + tenant shed (ROADMAP #1).

The control loop the pool supervisor ticks (``WorkerPool(...,
autoscale={...})``): it watches the fleet's queue-depth gauges and the
r19 per-tenant burn rates over the EXACT merged worker counters and
moves the three levers the enforcement plane exposes:

- **scale up** (``pool.resize(+1)``) on SUSTAINED global queue
  pressure — ``high_queue_per_worker`` tokens of backlog per active
  worker for ``sustain_ticks`` consecutive looks;
- **shed** when already at ``max_workers``: tighten admission for the
  burn-rate-breaching tenant with the LOWEST configured weight (ties:
  most tokens — the flooder), via ``pool.shed_tenant`` (the op rides
  the CVB1 type-13/14 control pair; workers scale that tenant's
  bucket rate). Only a tenant actually breaching a ``tenant.*`` SLO
  template is ever shed — quiet tenants are untouchable by design;
- **scale down / unshed** after ``quiet_ticks`` consecutive calm
  looks: sheds lift first (restore scale 1.0), then the pool shrinks
  toward ``min_workers``.

Every transition is a counter (``fleet.resize.*``) and a
``pool.resize_events()`` entry; capstat's tenant ledger renders the
pool line from them and the chaos postmortems embed the log.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from .. import telemetry
from ..obs import decision as _decision
from ..obs import slo as _slo


class PoolAutoscaler:
    """One pool's scaling/shed control loop (ticked by the pool's
    supervisor thread; every fault is swallowed into a counter — the
    supervisor must survive anything this class does)."""

    def __init__(self, pool, min_workers: int = 1,
                 max_workers: int = 4, *,
                 high_queue_per_worker: float = 1024.0,
                 sustain_ticks: int = 3, quiet_ticks: int = 10,
                 interval_s: float = 1.0, shed_scale: float = 0.25,
                 shed: bool = True,
                 tenant_weights: Optional[Dict[str, int]] = None):
        self._pool = pool
        self.min_workers = max(1, int(min_workers))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.high_queue_per_worker = float(high_queue_per_worker)
        self.sustain_ticks = max(1, int(sustain_ticks))
        self.quiet_ticks = max(1, int(quiet_ticks))
        self.interval_s = float(interval_s)
        self.shed_scale = float(shed_scale)
        self.shed_enabled = bool(shed)
        self.tenant_weights = dict(tenant_weights or {})
        self._hot = 0
        self._quiet = 0
        self._last_tick = 0.0
        self.shed_state: Dict[str, float] = {}
        # tenant SLO templates only: the burn signal the shed lever
        # keys off (expanded per observed tenant at eval time)
        self._rules = [r for r in _slo.default_rules()
                       if _slo.is_tenant_template(r)]
        self._engine = _slo.SLOEngine(self._rules)

    # -- signal extraction -------------------------------------------------

    @staticmethod
    def _pressure(merged: Dict[str, Any]) -> float:
        """Global backlog in tokens: batcher queues + native rings."""
        agg = merged.get("aggregate") or {}
        queued = float(agg.get("queued_tokens") or 0)
        for st in (merged.get("workers") or {}).values():
            queued += float((st or {}).get("ring_depth") or 0)
        return queued

    def _breaching_tenants(self, snapshot: Dict[str, Any]
                           ) -> List[str]:
        """Tenant ids currently burning a tenant.* SLO rule (the r19
        burn-rate signal), multi-window semantics unchanged."""
        out = set()
        for r in self._engine.evaluate(snapshot):
            tid = r.get("tenant")
            if tid is not None and not r.get("ok", True):
                out.add(tid)
        return sorted(out)

    def _pick_shed(self, breaching: List[str],
                   counters: Dict[str, int]) -> Optional[str]:
        """Lowest-weight breaching tenant first; ties → most tokens
        (the flooder). Already fully-shed tenants are skipped."""
        totals = _decision.tenant_totals(counters, surface="serve")
        best = None
        best_key = None
        for t in breaching:
            if t in (_decision.TENANT_NONE,):
                continue
            if self.shed_state.get(t, 1.0) <= self.shed_scale:
                continue            # already tightened
            key = (self.tenant_weights.get(t, 1),
                   -(totals.get(t, {}).get("tokens", 0)))
            if best_key is None or key < best_key:
                best, best_key = t, key
        return best

    # -- the loop ----------------------------------------------------------

    def tick(self, now: Optional[float] = None,
             merged: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """One control-loop step (rate-limited to ``interval_s``).
        Returns the action taken ("up"/"down"/"shed"/"unshed"/None) —
        handy for tests; the pool ignores it."""
        now = time.monotonic() if now is None else now
        if now - self._last_tick < self.interval_s:
            return None
        self._last_tick = now
        pool = self._pool
        if merged is None:
            merged = pool.stats_merged()
        agg = merged.get("aggregate") or {}
        snapshot = agg.get("snapshot") or {}
        counters = {k: int(v) for k, v in
                    (agg.get("counters") or {}).items()}
        active = pool.size()
        pressure = self._pressure(merged)
        per_worker = pressure / max(1, active)
        telemetry.gauge("fleet.autoscale_pressure", per_worker)
        if per_worker > self.high_queue_per_worker:
            self._hot += 1
            self._quiet = 0
        else:
            self._hot = 0
            self._quiet += 1
        if self._hot >= self.sustain_ticks:
            self._hot = 0
            if active < self.max_workers:
                pool.resize(active + 1, reason="queue-pressure")
                return "up"
            if self.shed_enabled:
                tenant = self._pick_shed(
                    self._breaching_tenants(snapshot), counters)
                if tenant is not None:
                    pool.shed_tenant(tenant, self.shed_scale,
                                     reason="slo-burn@max-size")
                    self.shed_state[tenant] = self.shed_scale
                    return "shed"
            return None
        if self._quiet >= self.quiet_ticks:
            self._quiet = 0
            if self.shed_state:
                tenant = sorted(self.shed_state)[0]
                pool.shed_tenant(tenant, 1.0, reason="quiet-restore")
                self.shed_state.pop(tenant, None)
                return "unshed"
            if active > self.min_workers:
                pool.resize(active - 1, reason="quiet")
                return "down"
        return None
